"""Pipeline specs: stage nodes (existing ``bst`` tools) + dataset edges.

A spec is plain JSON (or the equivalent Python dicts / dataclasses):

.. code-block:: json

    {
      "name": "resave-fuse-downsample-detect",
      "datasets": {
        "resaved": {"path": "resaved.n5", "ephemeral": true},
        "fused":   {"path": "fused.n5"}
      },
      "stages": [
        {"id": "resave", "tool": "resave",
         "args": ["-x", "/data/dataset.xml", "-o", "@resaved",
                  "-xo", "@workdir/resaved.xml", "--N5"],
         "writes": ["resaved"]},
        {"id": "fuse", "tool": "affine-fusion", "args": ["-o", "@fused"],
         "after": ["create"], "reads": ["resaved"], "writes": ["fused"]}
      ]
    }

- ``datasets`` are the edges: a stage listing a name in ``writes`` is its
  producer, in ``reads`` a consumer. Streamed edges (the default) gate
  consumer reads at output-block granularity and hand blocks over in
  memory; ``"stream": false`` turns the edge into a plain barrier
  (consumer waits for the producer to finish).
- ``"ephemeral": true`` marks an intermediate container: unless the run
  keeps intermediates, it is elided to an in-process ``memory://`` root
  (``"backing": "disk"`` spills to a run-scoped temp dir instead, e.g.
  for intermediates larger than RAM) and is cleaned up on success AND on
  failure/cancel — no orphaned half-written trees.
- ``@name`` tokens in ``args`` substitute the dataset's resolved path;
  ``@workdir`` the run's working directory. Everything else passes to
  the tool verbatim.
- ``after`` adds explicit barrier edges with no dataset (e.g. a stage
  that needs a file a predecessor writes outside any container, like a
  rewired XML).
- ``ranks`` pins a stage to specific process ranks in a multi-host run
  (e.g. ``"ranks": [0]`` for metadata-only container creation, which
  must not race across ranks). Non-owner ranks skip the tool and adopt
  the owners' outcome from their ``done`` broadcasts over the block
  exchange. Single-process runs ignore the field.
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field

from ..io.uris import has_scheme

# the serve surface must not nest, and a pipeline inside a pipeline is a
# recursion bomb, not a workflow
BLOCKED_TOOLS = {"serve", "submit", "jobs", "cancel", "pipeline"}

_NAME_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_.\-]*$")
_TOKEN_RE = re.compile(r"@([A-Za-z_][A-Za-z0-9_.\-]*)")
_BACKINGS = ("memory", "disk")


class SpecError(ValueError):
    """The pipeline spec is malformed (unknown refs, cycles, bad tools)."""


@dataclass
class DatasetSpec:
    """One named container edge of the pipeline."""

    name: str
    path: str | None = None
    ephemeral: bool = False
    stream: bool = True
    backing: str = "memory"      # ephemeral only: "memory" | "disk"
    resolved: str | None = None  # filled by PipelineSpec.resolve()
    elided: bool = False         # resolved to a memory:// root


@dataclass
class StageSpec:
    """One stage node: an existing ``bst`` tool invocation."""

    id: str
    tool: str
    args: list[str] = field(default_factory=list)
    after: list[str] = field(default_factory=list)
    reads: list[str] = field(default_factory=list)
    writes: list[str] = field(default_factory=list)
    ranks: list[int] = field(default_factory=list)  # empty = every rank


@dataclass
class PipelineSpec:
    name: str
    stages: list[StageSpec]
    datasets: dict[str, DatasetSpec]

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_dict(d: dict) -> "PipelineSpec":
        if not isinstance(d, dict):
            raise SpecError("pipeline spec must be a JSON object")
        datasets: dict[str, DatasetSpec] = {}
        for name, ds in (d.get("datasets") or {}).items():
            ds = ds or {}
            if not isinstance(ds, dict):
                raise SpecError(f"dataset {name!r} must be an object")
            datasets[str(name)] = DatasetSpec(
                name=str(name),
                path=ds.get("path"),
                ephemeral=bool(ds.get("ephemeral", False)),
                stream=bool(ds.get("stream", True)),
                backing=str(ds.get("backing", "memory")),
            )
        stages = []
        for s in (d.get("stages") or []):
            if not isinstance(s, dict):
                raise SpecError("each stage must be an object")
            stages.append(StageSpec(
                id=str(s.get("id", "")),
                tool=str(s.get("tool", "")),
                args=[str(a) for a in (s.get("args") or [])],
                after=[str(a) for a in (s.get("after") or [])],
                reads=[str(a) for a in (s.get("reads") or [])],
                writes=[str(a) for a in (s.get("writes") or [])],
                ranks=[int(r) for r in (s.get("ranks") or [])],
            ))
        spec = PipelineSpec(name=str(d.get("name") or "pipeline"),
                            stages=stages, datasets=datasets)
        spec.validate()
        return spec

    @staticmethod
    def load(path: str) -> "PipelineSpec":
        with open(path, encoding="utf-8") as f:
            try:
                d = json.load(f)
            except ValueError as e:
                raise SpecError(f"{path}: not valid JSON ({e})") from e
        return PipelineSpec.from_dict(d)

    # -- validation ---------------------------------------------------------

    def validate(self) -> None:
        if not self.stages:
            raise SpecError("pipeline has no stages")
        ids = [s.id for s in self.stages]
        if len(set(ids)) != len(ids):
            dup = sorted({i for i in ids if ids.count(i) > 1})
            raise SpecError(f"duplicate stage id(s): {dup}")
        for name, ds in self.datasets.items():
            if not _NAME_RE.match(name) or name == "workdir":
                raise SpecError(f"bad dataset name {name!r} (identifier, "
                                f"not 'workdir')")
            if ds.backing not in _BACKINGS:
                raise SpecError(f"dataset {name!r}: backing must be one of "
                                f"{_BACKINGS}, got {ds.backing!r}")
        from ..cli.main import cli as _cli

        for s in self.stages:
            if not s.id or not _NAME_RE.match(s.id):
                raise SpecError(f"bad stage id {s.id!r}")
            if s.tool in BLOCKED_TOOLS or s.tool not in _cli.commands:
                raise SpecError(f"stage {s.id!r}: unknown or unservable "
                                f"tool {s.tool!r}")
            for ref in s.after:
                if ref not in ids:
                    raise SpecError(f"stage {s.id!r}: unknown stage "
                                    f"{ref!r} in after")
                if ref == s.id:
                    raise SpecError(f"stage {s.id!r} lists itself in after")
            if any(r < 0 for r in s.ranks):
                raise SpecError(f"stage {s.id!r}: ranks must be "
                                f"non-negative, got {s.ranks}")
            for name in [*s.reads, *s.writes]:
                if name not in self.datasets:
                    raise SpecError(f"stage {s.id!r}: undeclared dataset "
                                    f"{name!r}")
            for arg in s.args:
                for m in _TOKEN_RE.finditer(arg):
                    tokname = m.group(1)
                    if tokname != "workdir" and tokname not in self.datasets:
                        raise SpecError(
                            f"stage {s.id!r}: arg {arg!r} references "
                            f"undeclared dataset @{tokname}")
        for name in self.datasets:
            if not self.producers_of(name):
                raise SpecError(
                    f"dataset {name!r} has no producer stage (external "
                    f"inputs are plain args, not datasets)")
        self._check_cycles()

    def producers_of(self, name: str) -> list[str]:
        return [s.id for s in self.stages if name in s.writes]

    def consumers_of(self, name: str) -> list[str]:
        return [s.id for s in self.stages if name in s.reads]

    def barrier_parents(self, stage: StageSpec) -> set[str]:
        """Stages that must FINISH before ``stage`` starts: explicit
        ``after`` edges plus producers of its non-streamed inputs."""
        parents = set(stage.after)
        for name in stage.reads:
            if not self.datasets[name].stream:
                parents.update(self.producers_of(name))
        parents.discard(stage.id)
        return parents

    def stream_parents(self, stage: StageSpec) -> set[str]:
        """Producers of ``stage``'s streamed inputs: they only need to
        have STARTED (block gating covers the rest)."""
        parents: set[str] = set()
        for name in stage.reads:
            if self.datasets[name].stream:
                parents.update(self.producers_of(name))
        parents.discard(stage.id)
        return parents

    def parents(self, stage: StageSpec) -> set[str]:
        return self.barrier_parents(stage) | self.stream_parents(stage)

    def _check_cycles(self) -> None:
        by_id = {s.id: s for s in self.stages}
        state: dict[str, int] = {}   # 0 visiting, 1 done

        def visit(sid, trail):
            if state.get(sid) == 1:
                return
            if state.get(sid) == 0:
                cyc = trail[trail.index(sid):] + [sid]
                raise SpecError(f"dependency cycle: {' -> '.join(cyc)}")
            state[sid] = 0
            for p in sorted(self.parents(by_id[sid])):
                visit(p, trail + [sid])
            state[sid] = 1

        for s in self.stages:
            visit(s.id, [])

    # -- resolution ---------------------------------------------------------

    def resolve(self, workdir: str, keep_intermediates: bool,
                run_id: str) -> None:
        """Fill every dataset's ``resolved`` path and substitute ``@name``
        / ``@workdir`` tokens in the stage args. Ephemeral datasets elide
        to ``memory://bst-dag-<run>/<name>`` (or a run-scoped temp dir
        with disk backing) unless intermediates are kept."""
        workdir = os.path.abspath(workdir)
        for ds in self.datasets.values():
            if ds.ephemeral and not keep_intermediates:
                if ds.backing == "memory":
                    ds.resolved = f"memory://bst-dag-{run_id}/{ds.name}"
                    ds.elided = True
                else:
                    ds.resolved = os.path.join(
                        workdir, f".bst-dag-tmp-{run_id}", ds.name)
                    ds.elided = False
            else:
                p = ds.path or ds.name
                ds.resolved = p if has_scheme(p) else \
                    os.path.abspath(os.path.join(workdir, p))
                ds.elided = False

        def sub(arg: str) -> str:
            def repl(m):
                tokname = m.group(1)
                if tokname == "workdir":
                    return workdir
                return self.datasets[tokname].resolved

            return _TOKEN_RE.sub(repl, arg)

        for s in self.stages:
            s.args = [sub(a) for a in s.args]


def registration_spec(xml: str, prefix: str = "registration",
                      label: str = "beads") -> dict:
    """The canonical REGISTRATION round as one streamed pipeline: detect →
    match → solve (ROADMAP item 2 follow-on, past detect into the
    interest-point match and the global solve).

    These stages exchange state through the project XML + interest-point
    store rather than container datasets, so the edges are explicit
    ``after`` barriers: the matcher starts when detection has committed
    its points, the solver is barrier-gated on the matcher's
    correspondences. Run as one ``bst pipeline`` job the three stages
    share the warm mesh, compiled-fn buckets and decoded-chunk cache —
    and through ``bst submit --pipeline`` they ride a resident daemon."""
    xml = os.path.abspath(xml)
    return {
        "name": f"{prefix}-detect-match-solve",
        "datasets": {},
        "stages": [
            {"id": "detect", "tool": "detect-interestpoints",
             "args": ["-x", xml, "-l", label,
                      "-dsxy", "1", "-dsz", "1"]},
            {"id": "match", "tool": "match-interestpoints",
             "args": ["-x", xml, "-l", label, "--clearCorrespondences"],
             "after": ["detect"]},
            # the global solve is barrier-gated on the matcher's stored
            # correspondences; it writes the optimized registrations back
            # into the project XML
            {"id": "solve", "tool": "solver",
             "args": ["-x", xml, "-s", "IP", "-l", label,
                      "--method", "ONE_ROUND_ITERATIVE",
                      "-tm", "TRANSLATION"],
             "after": ["match"]},
        ],
    }


def example_spec(xml: str, prefix: str = "pipeline") -> dict:
    """The canonical streamed resave -> fuse -> downsample -> detect
    pipeline for a project XML, as a plain spec dict (what ``bst pipeline
    init`` writes). All paths are absolute so the spec runs identically
    from a shell, through ``bst pipeline run``, or submitted to a `bst
    serve` daemon with a different working directory."""
    xml = os.path.abspath(xml)
    root = os.path.dirname(xml)
    rexml = os.path.join(root, f"{prefix}-resaved.xml")
    return {
        "name": f"{prefix}-resave-fuse-downsample-detect",
        "datasets": {
            # the classic intermediate: consumed by fusion AND detection,
            # then dead — elided to memory unless --keep-intermediates
            "resaved": {"path": os.path.join(root, f"{prefix}-resaved.n5"),
                        "ephemeral": True},
            "fused": {"path": os.path.join(root, f"{prefix}-fused.n5")},
        },
        "stages": [
            {"id": "resave", "tool": "resave",
             "args": ["-x", xml, "-xo", rexml, "-o", "@resaved", "--N5"],
             "writes": ["resaved"]},
            # barrier on resave: the rewired XML is written when the
            # resave commits (it is a file, not a container edge)
            # metadata-only container creation must not race across
            # ranks in a multi-host run — pin it to rank 0 (no-op when
            # single-process)
            {"id": "create", "tool": "create-fusion-container",
             "args": ["-x", rexml, "-o", "@fused", "-s", "N5",
                      "-d", "UINT16", "--minIntensity", "0",
                      "--maxIntensity", "65535"],
             "after": ["resave"], "ranks": [0]},
            {"id": "fuse", "tool": "affine-fusion",
             "args": ["-o", "@fused"],
             "after": ["create"], "reads": ["resaved"],
             "writes": ["fused"]},
            # streamed: starts with fusion and consumes fused s0 blocks
            # the moment they are published
            {"id": "downsample", "tool": "downsample",
             "args": ["-i", "@fused", "-di", "ch0tp0/s0", "-ds", "2,2,1"],
             "reads": ["fused"], "writes": ["fused"]},
            # independent consumer branch of the elided intermediate
            {"id": "detect", "tool": "detect-interestpoints",
             "args": ["-x", rexml, "-l", "beads", "-s", "1.8",
                      "-t", "0.008", "-dsxy", "1", "-dsz", "1"],
             "after": ["resave"], "reads": ["resaved"]},
        ],
    }

"""Block-granular streaming exchange between pipeline stages.

The one-shot pipeline bounces every byte between stages off a container:
fusion writes full N5/zarr trees that downsample and detection re-read
moments later (the dominant cost after the kernels, PERF §3g-k). This
module replaces that round-trip for stages running in ONE process under
the DAG executor (dag/executor.py): it hooks the two choke points every
driver already funnels through — ``Dataset.read`` / ``Dataset.write``
(io/chunkstore.py) — so no per-driver callback plumbing is needed.

Per streamed edge (a named dataset with producer and consumer stages):

- **readiness** — a producer's write marks the storage-chunk positions it
  fully covered as complete; a consumer's read of a not-yet-covered box
  blocks until the covering blocks land (or every producer finished —
  blocks a producer legitimately never writes, e.g. fusion's empty
  blocks, resolve then). This is scheduling at *output-block*
  granularity: the consumer is already running while the producer still
  is.
- **in-memory handoff** — the write is also split into its decoded
  chunks and pushed into the process-wide decoded-chunk LRU
  (io/chunkcache.py), so the consumer's gated read is served from memory
  with zero container decode. With the container itself elided to a
  ``memory://`` root the edge never touches disk at all.
- **device-resident handoff** — one tier above the host LRU: a producer
  that still holds a finished block in HBM publishes it through
  ``Dataset.write_device`` into a byte-budgeted device cache
  (``BST_DAG_HANDOFF_BYTES``), and a same-mesh consumer's gated read
  resolves a THIRD way — served from device, as jax arrays, with zero
  D2H and zero decode (``Dataset.read_device``). Over budget (or when a
  host-side read needs the bytes) chunks spill to the host LRU + the
  container, so backpressure and fallback semantics are exactly the
  host tier's; with the budget at 0 the device tier is off and every
  path is bit-identical to the host handoff.
- **backpressure** — published-but-unconsumed bytes are charged against
  ``BST_DAG_EXCHANGE_BYTES``; an over-budget producer stalls until
  consumers drain. One escape hatch prevents the classic reorder
  deadlock: while any consumer is *waiting* for unpublished blocks the
  producer never stalls (a starved consumer cannot drain the ledger).
- **accounting** — every consumer read of a streamed edge is attributed
  as elided (served by the handoff) or re-read (container decode), per
  edge and in the ``bst_dag_*`` process metrics, so `bst trace-report`
  and the bench ``pipeline`` extra can show exactly how many
  intermediate bytes never made the round trip.
- **cross-host edges** — with the rank-addressed block exchange
  attached (dag/exchange.py, ``BST_DAG_EXCHANGE_ADDR``), coverage and
  producer-done state replicate across every rank of a multi-process
  run: a remote rank's publish releases local gates, a remote-owned
  chunk is fetched once over TCP into the local decoded-chunk LRU
  (accounted ``bst_dag_xhost_bytes_total``) so the gated read still
  elides the container, and a peer that dies without saying goodbye
  fails exactly the gates waiting on its blocks — only the downstream
  cone of the streamed edge poisons, independent branches finish.

Everything here is inert until the executor registers edges: outside a
pipeline run the chunkstore hot paths pay one list-load.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import threading
import time
from collections import OrderedDict

import numpy as np

from .. import config, profiling
from ..io import chunkcache, chunkstore
from ..io.uris import has_scheme
from ..observe import metrics as _metrics
from ..observe import trace as _trace
from ..utils import cancel as _cancel
from .exchange import ExchangeError

_BLOCKS = _metrics.counter("bst_dag_blocks_streamed_total")
_ELIDED = _metrics.counter("bst_dag_bytes_elided_total")
_REREAD = _metrics.counter("bst_dag_bytes_reread_total")
_EPH_WRITE = _metrics.counter("bst_dag_ephemeral_write_bytes_total")
_EXCHANGE = _metrics.gauge("bst_dag_exchange_bytes")
_QUEUE = _metrics.gauge("bst_dag_exchange_blocks")
_STALL = _metrics.counter("bst_dag_producer_stall_seconds_total")
_WAIT = _metrics.counter("bst_dag_consumer_wait_seconds_total")
_HANDOFF_BLOCKS = _metrics.counter("bst_dag_handoff_blocks_total")
_HANDOFF_SERVED = _metrics.counter("bst_dag_handoff_bytes_served_total")
_HANDOFF_SPILL = _metrics.counter("bst_dag_handoff_spill_bytes_total")
_HANDOFF_BYTES = _metrics.gauge("bst_dag_handoff_bytes")

# wake-up tick for gate/stall waits: long enough to be free, short enough
# that cancellation (polled on every tick) stays responsive
_TICK_S = 0.2


class StageToken:
    """Identity of one running stage. Carried in a contextvar (and into
    every worker pool the stage spawns, via utils.threads), so the
    chunkstore hooks know WHICH stage is reading or writing. Identity is
    the object itself — ids may repeat across concurrent runs."""

    __slots__ = ("stage_id", "run_id")

    def __init__(self, stage_id: str, run_id: str):
        self.stage_id = stage_id
        self.run_id = run_id

    def __repr__(self):
        return f"StageToken({self.stage_id!r}@{self.run_id})"


_current_stage: contextvars.ContextVar[StageToken | None] = \
    contextvars.ContextVar("bst-dag-stage", default=None)


def current_stage() -> StageToken | None:
    return _current_stage.get()


@contextlib.contextmanager
def stage_scope(token: StageToken):
    """Make ``token`` the ambient stage for this context (and, via
    utils.threads, every worker thread spawned under it)."""
    tok = _current_stage.set(token)
    try:
        yield token
    finally:
        _current_stage.reset(tok)


def norm_root(root) -> str:
    """Canonical edge key of a container root: URIs verbatim, local paths
    absolute — both the executor (registering the resolved spec path) and
    the hooks (seeing whatever string the driver opened the store with)
    normalize through here so they cannot disagree."""
    r = str(root)
    return r if has_scheme(r) else os.path.abspath(r)


class EdgeState:
    """One pipeline dataset edge: which stages produce and consume it,
    whether it streams (block gating + handoff) and whether its container
    is elided to memory, plus this run's authoritative totals. All
    mutable counters are guarded by the owning registry's lock."""

    def __init__(self, name: str, root: str, producers, consumers,
                 elided: bool = False, stream: bool = True):
        self.name = name
        self.root = norm_root(root)
        self.producers: frozenset[StageToken] = frozenset(producers)
        self.consumers: frozenset[StageToken] = frozenset(consumers)
        self.elided = bool(elided)
        self.stream = bool(stream)
        # per-run totals (filled under the registry lock)
        self.blocks_published = 0
        self.bytes_published = 0
        self.bytes_elided = 0
        self.bytes_reread = 0
        self.bytes_xhost = 0
        self.blocks_handoff = 0
        self.bytes_handoff = 0
        self.bytes_spilled = 0
        self.stall_s = 0.0
        self.wait_s = 0.0

    def summary(self) -> dict:
        return {
            "edge": self.name,
            "root": self.root,
            "elided": self.elided,
            "stream": self.stream,
            "blocks_streamed": self.blocks_published,
            "bytes_published": self.bytes_published,
            "bytes_elided": self.bytes_elided,
            "bytes_reread": self.bytes_reread,
            "bytes_xhost": self.bytes_xhost,
            "blocks_handoff": self.blocks_handoff,
            "bytes_handoff": self.bytes_handoff,
            "bytes_spilled": self.bytes_spilled,
            "producer_stall_s": round(self.stall_s, 3),
            "consumer_wait_s": round(self.wait_s, 3),
        }


def _geometry(ds):
    """(block_size, dims) of a dataset, or None when it has no usable
    chunk grid (the hooks then pass the IO through ungated)."""
    try:
        block = tuple(int(b) for b in ds.block_size)
        dims = tuple(int(d) for d in ds.shape)
    except Exception:
        return None
    if not block or len(block) != len(dims) or any(b <= 0 for b in block):
        return None
    return block, dims


def _ds_key(ds):
    """(normalized root, dataset path) of a Dataset, or None when it has
    no stable identity."""
    try:
        root, path = ds._cache_key()
    except Exception:
        return None
    if root is None:
        return None
    return norm_root(root), str(path).strip("/")


def _touched_positions(offset, shape, block):
    grids = [range(int(offset[d]) // block[d],
                   (int(offset[d]) + int(shape[d]) - 1) // block[d] + 1)
             for d in range(len(block))]
    return list(itertools.product(*grids))


def _covered_positions(offset, shape, block, dims):
    """Chunk positions whose full (array-clipped) extent lies inside the
    written box — only those may be marked complete / handed off; a
    partially covered interior chunk stays pending until the producer
    finishes (the drivers' grids are chunk-aligned, so in practice this
    is every touched chunk)."""
    nd = len(block)
    out = []
    for pos in _touched_positions(offset, shape, block):
        lo = [pos[d] * block[d] for d in range(nd)]
        hi = [min(lo[d] + block[d], dims[d]) for d in range(nd)]
        if all(lo[d] >= int(offset[d])
               and hi[d] <= int(offset[d]) + int(shape[d])
               for d in range(nd)):
            out.append(pos)
    return out


def _chunk_slices(pos, offset, block, dims):
    nd = len(block)
    return tuple(
        slice(pos[d] * block[d] - int(offset[d]),
              min((pos[d] + 1) * block[d], dims[d]) - int(offset[d]))
        for d in range(nd))


class _HandoffCache:
    """Byte-budgeted LRU of DEVICE-resident produced chunks awaiting
    their streamed consumers — the HBM tier above the host decoded-chunk
    LRU. Keys are ``(edge root, dataset path, chunk position)``; entries
    carry the device array, its byte size and the producing ``Dataset``
    (the spill target's write handle). The lock is never held across
    device ops or container IO: ``put_many`` returns what it evicted and
    the CALLER spills those entries to the host tier."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, tuple] = OrderedDict()
        self._bytes = 0

    @staticmethod
    def budget() -> int:
        return config.get_bytes("BST_DAG_HANDOFF_BYTES")

    def enabled(self) -> bool:
        return self.budget() > 0

    def put_many(self, items) -> list:
        """Insert ``[(key, dev, nbytes, ds), ...]``; returns the evicted
        entries (same shape) the caller must materialize to the host."""
        evicted = []
        budget = self.budget()
        with self._lock:
            for key, dev, nbytes, ds in items:
                old = self._entries.pop(key, None)
                if old is not None:
                    self._bytes -= old[1]
                self._entries[key] = (dev, nbytes, ds)
                self._bytes += nbytes
            while self._bytes > budget and self._entries:
                k, (dev, nbytes, ds) = self._entries.popitem(last=False)
                self._bytes -= nbytes
                evicted.append((k, dev, nbytes, ds))
            _HANDOFF_BYTES.set(self._bytes)
        return evicted

    def get_many(self, keys) -> list | None:
        """The entries for ``keys`` (refreshing recency), or None when
        ANY is absent — consumers assemble all-device or not at all."""
        with self._lock:
            if any(k not in self._entries for k in keys):
                return None
            out = []
            for k in keys:
                self._entries.move_to_end(k)
                out.append(self._entries[k])
            return out

    def pop_many(self, keys) -> list:
        """Remove and return the present entries among ``keys``."""
        out = []
        with self._lock:
            for k in keys:
                ent = self._entries.pop(k, None)
                if ent is not None:
                    self._bytes -= ent[1]
                    out.append((k, *ent))
            if out:
                _HANDOFF_BYTES.set(self._bytes)
        return out

    def pop_root(self, root) -> list:
        """Remove and return every entry under an edge root (flush)."""
        with self._lock:
            doomed = [k for k in self._entries if k[0] == root]
            out = []
            for k in doomed:
                ent = self._entries.pop(k)
                self._bytes -= ent[1]
                out.append((k, *ent))
            if out:
                _HANDOFF_BYTES.set(self._bytes)
        return out


class StreamRegistry:
    """Process-wide edge registry + block exchange. One instance serves
    every concurrent pipeline run (runs register/unregister their own
    edges; stage tokens are object-identity so ids never collide)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._edges: dict[str, EdgeState] = {}          # root -> edge
        self._coverage: dict[tuple, set] = {}           # (root, path) -> pos
        self._pending: dict[tuple, list] = {}           # (root,path,pos) ->
        #                                       [nbytes, {consumer tokens}]
        self._finished: set[StageToken] = set()
        self._exchange_bytes = 0
        self._gate_waiters = 0
        self._handoff = _HandoffCache()
        # cross-host state (only populated while an Exchange is attached)
        self._exchange = None                       # dag.exchange.Exchange
        self._remote_cov: dict[tuple, dict] = {}    # (root,path)->{pos:rank}
        self._remote_done: dict[str, set] = {}      # stage id -> peer ranks
        self._remote_failed: set[str] = set()       # failed on some peer
        self._dead_ranks: set[int] = set()
        self._datasets: dict[tuple, object] = {}    # (root,path) -> Dataset

    # -- lifecycle (executor side) -----------------------------------------

    def register(self, edges) -> None:
        with self._cond:
            for e in edges:
                self._edges[e.root] = e
            if self._edges:
                # installed under the lock: a concurrent unregister of the
                # LAST other run must not race this install away
                chunkstore.set_dag_hooks(self)

    def unregister(self, edges) -> None:
        # flush first, OUTSIDE the lock: device-published chunks of a
        # non-elided edge may exist only in HBM, and the container must
        # hold them before the edge identity disappears. Elided edges'
        # entries are simply dropped — their memory:// container is
        # removed right after this returns.
        for e in edges:
            ents = self._handoff.pop_root(e.root)
            if ents and not e.elided:
                self._spill(ents)
        with self._cond:
            for e in edges:
                if self._edges.get(e.root) is e:
                    del self._edges[e.root]
                for key in [k for k in self._coverage if k[0] == e.root]:
                    del self._coverage[key]
                for key in [k for k in self._pending if k[0] == e.root]:
                    nbytes, _ = self._pending.pop(key)
                    self._exchange_bytes -= nbytes
                for key in [k for k in self._remote_cov if k[0] == e.root]:
                    del self._remote_cov[key]
                for key in [k for k in self._datasets if k[0] == e.root]:
                    del self._datasets[key]
                for t in e.producers | e.consumers:
                    self._remote_done.pop(t.stage_id, None)
                    self._remote_failed.discard(t.stage_id)
                self._finished -= e.producers | e.consumers
            self._update_gauges_locked()
            if not self._edges:
                chunkstore.set_dag_hooks(None)
            self._cond.notify_all()

    def stage_finished(self, token: StageToken, ok: bool = True) -> None:
        """A stage reached a terminal state: release every exchange claim
        it still held and wake gate/stall waiters (producers-done and
        consumers-alive conditions may both have flipped). ``ok=False``
        (failed/cancelled) matters cross-host: peers gating on this
        stage's blocks must poison their downstream cone, not consume a
        half-written edge."""
        with self._cond:
            self._finished.add(token)
            for key in list(self._pending):
                nbytes, owed = self._pending[key]
                if token in owed:
                    owed.discard(token)
                    if not owed:
                        del self._pending[key]
                        self._exchange_bytes -= nbytes
            self._update_gauges_locked()
            self._cond.notify_all()
            x = self._exchange
        if x is not None:
            x.broadcast_done(token.stage_id, ok)

    # -- cross-host exchange (dag/exchange.py) ------------------------------

    def set_exchange(self, x) -> None:
        """Attach (or detach, with None) the cross-host exchange. The
        remote-state maps live and die with the attachment — a later
        single-process run must not see a stale peer's coverage."""
        with self._cond:
            self._exchange = x
            if x is None:
                self._remote_cov.clear()
                self._remote_done.clear()
                self._remote_failed.clear()
                self._dead_ranks.clear()
            self._cond.notify_all()

    def remote_cover(self, root, path, positions, rank, per=1) -> None:
        """A peer rank published these chunk positions (exchange server
        thread). First writer wins the ownership slot: re-publishes of
        an already-owned position keep the original fetch target."""
        with self._cond:
            owner = self._remote_cov.setdefault((root, path), {})
            for p in positions:
                owner.setdefault(tuple(p), int(rank))
            self._cond.notify_all()

    def remote_done(self, stage_id, rank, ok=True) -> None:
        """A peer rank's instance of a stage reached a terminal state;
        a failed/cancelled one additionally marks the stage remote-failed
        so gates on its unpublished blocks raise instead of consuming a
        half-written edge."""
        with self._cond:
            self._remote_done.setdefault(str(stage_id), set()).add(
                int(rank))
            if not ok:
                self._remote_failed.add(str(stage_id))
            self._cond.notify_all()

    def remote_rank_dead(self, rank) -> None:
        """A peer's connection dropped without a goodbye: its unpublished
        blocks will never arrive. Gates waiting on them raise (failing
        exactly the downstream cone) instead of hanging forever."""
        with self._cond:
            self._dead_ranks.add(int(rank))
            self._cond.notify_all()

    def wait_remote_done(self, stage_id, ranks) -> bool:
        """Adopt the outcome of a rank-pinned stage this rank does not
        own: block until every owner rank has broadcast ``done`` for
        ``stage_id`` over the exchange. True when all owners finished
        OK; False when the stage failed on a peer or an owner died
        before reporting — the caller fails its local instance so the
        downstream cone poisons identically on every rank."""
        stage_id, want = str(stage_id), {int(r) for r in ranks}
        with self._cond:
            while True:
                if stage_id in self._remote_failed:
                    return False
                have = set(self._remote_done.get(stage_id, ()))
                if want <= have:
                    return True
                if (want - have) & self._dead_ranks:
                    return False
                self._cond.wait(_TICK_S)
                _cancel.check("dag remote stage")

    def serve_chunk(self, root, path, pos):
        """Produce one locally-owned decoded chunk for a remote fetch:
        the decoded-chunk LRU first, the container as fallback (the
        producing write always lands there — for an elided edge, in THIS
        rank's memory:// kvstore). None when this rank cannot serve it.
        Runs on exchange server threads with no ambient stage, so the
        re-entrant ``ds.read`` is neither gated nor accounted."""
        with self._lock:
            ds = self._datasets.get((root, path))
        if ds is None:
            return None
        pos = tuple(int(x) for x in pos)
        if chunkcache.enabled() and ds._cacheable():
            arr = chunkcache.get_cache().get(
                (ds._cache_key(), ds._cache_sig(), pos))
            if arr is not None:
                return np.asarray(arr)
        geo = _geometry(ds)
        if geo is None:
            return None
        block, dims = geo
        nd = len(block)
        if len(pos) != nd:
            return None
        lo = [pos[d] * block[d] for d in range(nd)]
        if any(lo[d] < 0 or lo[d] >= dims[d] for d in range(nd)):
            return None
        shape = [min(block[d], dims[d] - lo[d]) for d in range(nd)]
        return np.asarray(ds.read(lo, shape))

    def _fetch_remote(self, edge, ds, root, path, need) -> None:
        """Pull the remote-owned chunks a gated read needs into the local
        decoded-chunk LRU, once, so the read below resolves via the cache
        (zero container decode — crucial for elided roots, whose LOCAL
        memory container never held a remote rank's bytes)."""
        x = self._exchange
        if x is None:
            return
        with self._lock:
            rcov = self._remote_cov.get((root, path))
            if not rcov:
                return
            cov = self._coverage.get((root, path)) or ()
            todo = [(p, rcov[p]) for p in need
                    if p in rcov and p not in cov]
        if not todo:
            return
        if not (chunkcache.enabled() and ds._cacheable()):
            # no local tier to land the bytes in: the read falls through
            # to the container — correct on a shared filesystem, and the
            # covers above still gated it for readiness
            return
        cc = chunkcache.get_cache()
        dkey, sig = ds._cache_key(), ds._cache_sig()
        fetched = 0
        for pos, rank in todo:
            if cc.get((dkey, sig, pos)) is not None:
                continue   # already fetched (or handed off) — once only
            arr = x.fetch(rank, root, path, pos)
            cc.put((dkey, sig, pos), arr, record_miss=False)
            fetched += int(arr.nbytes)
        if fetched:
            with self._lock:
                edge.bytes_xhost += fetched

    def _update_gauges_locked(self) -> None:
        _EXCHANGE.set(self._exchange_bytes)
        _QUEUE.set(len(self._pending))

    # -- chunkstore hooks ---------------------------------------------------

    def _consumer_edge(self, ds, offset):
        """``(edge, tok, root, path, block, dims)`` when the ambient
        stage is a streamed-edge consumer of ``ds`` with gateable
        geometry; None otherwise (the read passes straight through)."""
        if not self._edges:
            return None
        tok = _current_stage.get()
        if tok is None:
            return None
        key = _ds_key(ds)
        if key is None:
            return None
        root, path = key
        edge = self._edges.get(root)
        if edge is None or not edge.stream or tok not in edge.consumers:
            return None
        geo = _geometry(ds)
        if geo is None:
            return None
        block, dims = geo
        if len(block) != len(tuple(offset)):
            return None
        return edge, tok, root, path, block, dims

    def gate(self, ds, offset, shape) -> None:
        """Block a consumer stage's read until the producer has written
        every storage chunk the box touches (or all producers finished).
        No-op for non-edge datasets, non-consumer stages, and reads the
        hook cannot reason about. A HOST read arriving here also
        materializes any chunks that exist only device-resident — the
        host tiers below would otherwise decode container zeros."""
        res = self._consumer_edge(ds, offset)
        if res is None:
            return
        edge, tok, root, path, block, _dims = res
        need = _touched_positions(offset, shape, block)
        self._wait_and_consume(edge, tok, root, path, need)
        self._fetch_remote(edge, ds, root, path, need)
        ents = self._handoff.pop_many([(root, path, p) for p in need])
        if ents:
            self._spill(ents)
        self._feed_prefetch(ds, tok, root, path, need)

    def box_ready(self, ds, offset, shape) -> bool:
        """Non-blocking gate probe for the async prefetcher
        (io/prefetch.py): True when prefetching this box now is safe.
        Datasets that are not streamed edges always are; a streamed
        edge's box is ready only when every touched chunk has LOCAL
        coverage — an unpublished chunk would cache container zeros, a
        remote-owned one would cache a peer's bytes this rank's
        container never held."""
        if not self._edges:
            return True
        key = _ds_key(ds)
        if key is None:
            return True
        root, path = key
        edge = self._edges.get(root)
        if edge is None or not edge.stream:
            return True
        geo = _geometry(ds)
        if geo is None:
            return False
        block, _dims = geo
        if len(block) != len(tuple(offset)):
            return False
        need = _touched_positions(offset, shape, block)
        with self._lock:
            cov = self._coverage.get((root, path)) or ()
            return all(p in cov for p in need)

    def _feed_prefetch(self, ds, tok, root, path, just_read) -> None:
        """Feed the async prefetcher the published-but-unconsumed blocks
        this consumer is still OWED on the edge: those are its known
        future gated reads, already written by the producer, so decoding
        them now overlaps the consumer's current block's compute. No-op
        (one enabled() check) while the prefetcher is off."""
        from ..io import prefetch as _prefetch

        if not _prefetch.enabled():
            return
        done = set(just_read)
        with self._lock:
            owed = [k[2] for k, ent in self._pending.items()
                    if k[0] == root and k[1] == path
                    and tok in ent[1] and k[2] not in done]
        if not owed:
            return
        geo = _geometry(ds)
        if geo is None:
            return
        block, dims = geo
        nd = len(block)
        boxes = []
        for pos in owed[:16]:   # enough to stay ahead of one consumer
            lo = [pos[d] * block[d] for d in range(nd)]
            shp = [min(block[d], dims[d] - lo[d]) for d in range(nd)]
            if all(s > 0 for s in shp):
                boxes.append((ds, lo, shp))
        if boxes:
            _prefetch.submit_boxes(boxes)

    def _wait_and_consume(self, edge, tok, root, path, need) -> None:
        with self._cond:
            if not self._missing_locked(root, path, need, edge, tok):
                self._consume_locked(edge, tok, root, path, need)
                return
            with profiling.span("dag.wait", stage=edge.name):
                t0 = time.perf_counter()
                self._gate_waiters += 1
                try:
                    while self._missing_locked(root, path, need, edge, tok):
                        self._cond.wait(_TICK_S)
                        _cancel.check("dag gate")
                finally:
                    self._gate_waiters -= 1
                    dt = time.perf_counter() - t0
                    edge.wait_s += dt
                    _WAIT.inc(dt)
                    self._cond.notify_all()
            self._consume_locked(edge, tok, root, path, need)

    def device_read(self, ds, offset, shape):
        """Consumer side, device tier — the gate's THIRD resolution:
        after the ordinary wait (coverage-complete or producers-done),
        assemble the whole box from HBM-resident handoff chunks and hand
        it to the consumer as a device array: zero D2H, zero decode.
        Returns None when any covering chunk is not device-resident; the
        caller then falls back to ``Dataset.read``, whose gate spills
        whatever IS device-resident so the host tiers hold real bytes."""
        if not self._handoff.enabled():
            return None
        res = self._consumer_edge(ds, offset)
        if res is None:
            return None
        edge, tok, root, path, block, dims = res
        need = _touched_positions(offset, shape, block)
        self._wait_and_consume(edge, tok, root, path, need)
        ents = self._handoff.get_many([(root, path, p) for p in need])
        if ents is None:
            return None
        import jax.numpy as jnp

        off = [int(o) for o in offset]
        shp = [int(s) for s in shape]
        nd = len(block)
        with profiling.span("dag.handoff_read", stage=edge.name):
            if len(need) == 1:
                dev = ents[0][0]
                lo = [need[0][d] * block[d] for d in range(nd)]
                src = tuple(slice(off[d] - lo[d], off[d] + shp[d] - lo[d])
                            for d in range(nd))
                out = dev if all(
                    s.start == 0 and s.stop == dev.shape[d]
                    for d, s in enumerate(src)) else dev[src]
            else:
                import jax

                # chunks are committed to their producer devices; the
                # assembly must live on ONE device (mixed placements are
                # an error) — slice on the owner, copy only the slice
                target = next(iter(ents[0][0].devices()))
                out = jax.device_put(
                    jnp.zeros(tuple(shp), ents[0][0].dtype), target)
                for pos, (dev, _nb, _ds) in zip(need, ents):
                    lo = [pos[d] * block[d] for d in range(nd)]
                    src = tuple(
                        slice(max(off[d] - lo[d], 0),
                              min(off[d] + shp[d] - lo[d], dev.shape[d]))
                        for d in range(nd))
                    dst = tuple(
                        slice(max(lo[d] - off[d], 0),
                              max(lo[d] - off[d], 0)
                              + (src[d].stop - src[d].start))
                        for d in range(nd))
                    out = out.at[dst].set(jax.device_put(dev[src], target))
        nbytes = int(np.dtype(out.dtype).itemsize) * int(np.prod(shp))
        with self._cond:
            edge.bytes_handoff += nbytes
        _HANDOFF_SERVED.inc(nbytes)
        return out

    def _missing_locked(self, root, path, need, edge, tok) -> bool:
        cov = self._coverage.get((root, path))
        rcov = self._remote_cov.get((root, path))
        if all((cov is not None and p in cov)
               or (rcov is not None and p in rcov) for p in need):
            return False
        if self._dead_ranks:
            # chunks are still missing and a peer died holding them:
            # hanging here would wedge the stage forever — raise, so only
            # this consumer's downstream cone fails
            raise ExchangeError(
                f"exchange peer rank(s) {sorted(self._dead_ranks)} died "
                f"with blocks outstanding on edge {edge.name}")
        bad = {p.stage_id for p in edge.producers
               if p.stage_id in self._remote_failed}
        if bad:
            # a peer's instance of a producer failed: its slice of the
            # edge will never publish — consuming now would read a
            # half-written edge
            raise ExchangeError(
                f"producer stage(s) {sorted(bad)} failed on a peer rank "
                f"with blocks outstanding on edge {edge.name}")
        # blocks a producer never writes (fusion's empty blocks) resolve
        # when every OTHER producer is terminal — the data then simply is
        # what the container holds
        return not self._producers_done_locked(edge, tok)

    def _producers_done_locked(self, edge, tok) -> bool:
        for p in edge.producers:
            if p is not tok and p not in self._finished:
                return False
        # cross-host: every peer rank's instance of each producer stage
        # must be terminal too (its last covers have then been sent)
        w = self._exchange.world if self._exchange is not None else 1
        if w > 1:
            for p in edge.producers:
                peers = self._remote_done.get(p.stage_id, ())
                if len(set(peers) | self._dead_ranks) < w - 1:
                    return False
        return True

    def _consume_locked(self, edge, tok, root, path, need) -> None:
        drained = False
        for pos in need:
            ent = self._pending.get((root, path, pos))
            if ent is not None and tok in ent[1]:
                ent[1].discard(tok)
                if not ent[1]:
                    del self._pending[(root, path, pos)]
                    self._exchange_bytes -= ent[0]
                drained = True
        if drained:
            self._update_gauges_locked()
            self._cond.notify_all()

    def on_write(self, ds, data, offset) -> None:
        """Producer side: mark covered chunks complete, hand their decoded
        bytes to the chunk cache, charge the exchange, stall over budget."""
        if not self._edges:
            return
        key = _ds_key(ds)
        if key is None:
            return
        root, path = key
        edge = self._edges.get(root)
        if edge is None:
            return
        if edge.elided:
            _EPH_WRITE.inc(int(data.nbytes))
        if not edge.stream:
            return
        tok = _current_stage.get()
        if tok is None or tok not in edge.producers:
            # only DECLARED producers publish completion: a foreign write
            # into the same root (another daemon job, an init-style stage
            # not in `writes`) must never unblock a gated consumer with
            # bytes the real producer has not written yet
            return
        geo = _geometry(ds)
        if geo is None:
            return
        block, dims = geo
        if len(block) != data.ndim:
            return
        covered = _covered_positions(offset, data.shape, block, dims)
        if not covered:
            return
        # a host write supersedes any device-resident copies of the same
        # chunks: drop them, the fresh bytes live on the host path now
        self._handoff.pop_many([(root, path, p) for p in covered])
        # write-through handoff: the consumer's gated read finds these in
        # the decoded-chunk cache and never decodes the container (copies,
        # so a driver reusing its write buffer cannot corrupt the cache)
        if chunkcache.enabled() and ds._cacheable():
            dkey = ds._cache_key()
            sig = ds._cache_sig()
            cc = chunkcache.get_cache()
            for pos in covered:
                piece = np.array(
                    data[_chunk_slices(pos, offset, block, dims)], copy=True)
                cc.put((dkey, sig, pos), piece, record_miss=False)
        nbytes = int(data.nbytes)
        per = max(1, nbytes // len(covered))
        if _trace.enabled():
            _trace.instant("dag.publish", stage=edge.name, nbytes=nbytes,
                           item=tuple(int(o) for o in offset))
        with self._cond:
            self._datasets[(root, path)] = ds
            fresh = self._publish_locked(edge, tok, root, path, covered,
                                         per)
        # broadcast OUTSIDE the lock: a full peer queue blocks (bounded
        # backpressure), and gate waiters must keep draining meanwhile
        x = self._exchange
        if x is not None and fresh:
            x.broadcast_cover(root, path, fresh, per)
        with self._cond:
            self._stall_locked(edge, tok)

    def _publish_locked(self, edge, tok, root, path, covered, per) -> list:
        """Shared completion accounting of the host and device publish
        paths: coverage, per-run totals, the exchange ledger. Returns the
        first-time-covered positions (the cross-host cover broadcast)."""
        cov = self._coverage.setdefault((root, path), set())
        fresh = [p for p in covered if p not in cov]
        cov.update(covered)
        if fresh:
            edge.blocks_published += len(fresh)
            edge.bytes_published += per * len(fresh)
            _BLOCKS.inc(len(fresh))
            owed = {c for c in edge.consumers
                    if c not in self._finished and c is not tok}
            if owed:
                for p in fresh:
                    self._pending[(root, path, p)] = [per, set(owed)]
                self._exchange_bytes += per * len(fresh)
            self._update_gauges_locked()
        self._cond.notify_all()
        return fresh

    def on_write_device(self, ds, dev, offset) -> bool:
        """Producer side, device tier: keep a finished block's covered
        chunks HBM-resident for same-mesh streamed consumers instead of
        draining them to host. Returns True when the block was published
        device-resident — the caller skips its host write entirely;
        False sends it down the ordinary host write path.

        All-or-nothing: a block whose box does not fully cover every
        storage chunk it touches is rejected (partial chunks must merge
        through the container like any host write), as are datasets the
        spill tier could not hold coherently (non-cacheable stores)."""
        if not self._edges or not self._handoff.enabled():
            return False
        if self._exchange is not None:
            # chunks held only in HBM are invisible to remote fetches
            # (serve_chunk reads the host tiers): multi-process runs keep
            # every publish on the host path
            return False
        tok = _current_stage.get()
        if tok is None:
            return False
        key = _ds_key(ds)
        if key is None:
            return False
        root, path = key
        edge = self._edges.get(root)
        if edge is None or not edge.stream or tok not in edge.producers:
            return False
        if not ds._cacheable():
            return False
        geo = _geometry(ds)
        if geo is None:
            return False
        block, dims = geo
        if len(block) != dev.ndim:
            return False
        touched = _touched_positions(offset, dev.shape, block)
        covered = _covered_positions(offset, dev.shape, block, dims)
        if not covered or len(covered) != len(touched):
            return False
        itemsize = int(np.dtype(dev.dtype).itemsize)
        items, nbytes = [], 0
        for pos in covered:
            piece = dev[_chunk_slices(pos, offset, block, dims)]
            nb = itemsize * int(np.prod(piece.shape))
            items.append(((root, path, pos), piece, nb, ds))
            nbytes += nb
        evicted = self._handoff.put_many(items)
        per = max(1, nbytes // len(covered))
        if _trace.enabled():
            _trace.instant("dag.handoff_publish", stage=edge.name,
                           nbytes=nbytes,
                           item=tuple(int(o) for o in offset))
        _HANDOFF_BLOCKS.inc(len(covered))
        with self._cond:
            edge.blocks_handoff += len(covered)
            self._publish_locked(edge, tok, root, path, covered, per)
        if evicted:
            self._spill(evicted)
        with self._cond:
            self._stall_locked(edge, tok)
        return True

    def _spill(self, entries) -> None:
        """Materialize device-resident handoff chunks to the host tier:
        fetch, write through the container (a non-elided output must hold
        the real bytes) and re-seed the decoded-chunk LRU so a streamed
        consumer's host read still elides the decode. Never called with a
        registry lock held — the write re-enters ``on_write``."""
        import jax

        for (root, path, pos), dev, nbytes, ds in entries:
            edge = self._edges.get(root)
            with profiling.span("dag.handoff_spill",
                                stage=edge.name if edge else path):
                arr = np.asarray(jax.device_get(dev))
                geo = _geometry(ds)
                if geo is None:
                    continue
                block, _dims = geo
                lo = [pos[d] * block[d] for d in range(len(block))]
                ds.write(arr, lo)
                if chunkcache.enabled() and ds._cacheable():
                    chunkcache.get_cache().put(
                        (ds._cache_key(), ds._cache_sig(), pos), arr,
                        record_miss=False)
            _HANDOFF_SPILL.inc(nbytes)
            if edge is not None:
                with self._cond:
                    edge.bytes_spilled += nbytes

    def _stall_locked(self, edge, tok) -> None:
        """Backpressure: hold the producer while the exchange is over
        budget AND some consumer is alive to drain it AND no consumer is
        starved waiting for unpublished blocks (stalling then would be
        the textbook reorder deadlock — the producer must run)."""
        budget = config.get_bytes("BST_DAG_EXCHANGE_BYTES")

        def should_stall():
            if not budget or self._exchange_bytes <= budget:
                return False
            if self._gate_waiters:
                return False
            return any(c not in self._finished and c is not tok
                       for c in edge.consumers)

        if not should_stall():
            return
        with profiling.span("dag.stall", stage=edge.name):
            t0 = time.perf_counter()
            try:
                while should_stall():
                    self._cond.wait(_TICK_S)
                    _cancel.check("dag backpressure")
            finally:
                dt = time.perf_counter() - t0
                edge.stall_s += dt
                _STALL.inc(dt)

    def account_read(self, ds, via: str, nbytes: int) -> None:
        """Attribute a consumer's streamed-edge read bytes: ``cache`` =
        served by the handoff (container re-read elided), anything else =
        a container decode the streaming failed to elide."""
        if not self._edges or not nbytes:
            return
        tok = _current_stage.get()
        if tok is None:
            return
        key = _ds_key(ds)
        if key is None:
            return
        edge = self._edges.get(key[0])
        if edge is None or not edge.stream or tok not in edge.consumers:
            return
        with self._cond:
            if via == "cache":
                edge.bytes_elided += int(nbytes)
            else:
                edge.bytes_reread += int(nbytes)
        if via == "cache":
            _ELIDED.inc(int(nbytes))
        else:
            _REREAD.inc(int(nbytes))


_REGISTRY = StreamRegistry()


def registry() -> StreamRegistry:
    return _REGISTRY


def handoff_active() -> bool:
    """True when a StreamRegistry is hooked AND the device handoff tier
    has a budget: producer stages should offer their device-resident
    outputs via ``Dataset.write_device`` before any D2H fetch."""
    from ..io import chunkstore

    hooks = chunkstore._DAG_HOOKS[0]
    return (hooks is not None
            and getattr(hooks, "_handoff", None) is not None
            and hooks._handoff.enabled())

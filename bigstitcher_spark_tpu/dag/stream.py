"""Block-granular streaming exchange between pipeline stages.

The one-shot pipeline bounces every byte between stages off a container:
fusion writes full N5/zarr trees that downsample and detection re-read
moments later (the dominant cost after the kernels, PERF §3g-k). This
module replaces that round-trip for stages running in ONE process under
the DAG executor (dag/executor.py): it hooks the two choke points every
driver already funnels through — ``Dataset.read`` / ``Dataset.write``
(io/chunkstore.py) — so no per-driver callback plumbing is needed.

Per streamed edge (a named dataset with producer and consumer stages):

- **readiness** — a producer's write marks the storage-chunk positions it
  fully covered as complete; a consumer's read of a not-yet-covered box
  blocks until the covering blocks land (or every producer finished —
  blocks a producer legitimately never writes, e.g. fusion's empty
  blocks, resolve then). This is scheduling at *output-block*
  granularity: the consumer is already running while the producer still
  is.
- **in-memory handoff** — the write is also split into its decoded
  chunks and pushed into the process-wide decoded-chunk LRU
  (io/chunkcache.py), so the consumer's gated read is served from memory
  with zero container decode. With the container itself elided to a
  ``memory://`` root the edge never touches disk at all.
- **backpressure** — published-but-unconsumed bytes are charged against
  ``BST_DAG_EXCHANGE_BYTES``; an over-budget producer stalls until
  consumers drain. One escape hatch prevents the classic reorder
  deadlock: while any consumer is *waiting* for unpublished blocks the
  producer never stalls (a starved consumer cannot drain the ledger).
- **accounting** — every consumer read of a streamed edge is attributed
  as elided (served by the handoff) or re-read (container decode), per
  edge and in the ``bst_dag_*`` process metrics, so `bst trace-report`
  and the bench ``pipeline`` extra can show exactly how many
  intermediate bytes never made the round trip.

Everything here is inert until the executor registers edges: outside a
pipeline run the chunkstore hot paths pay one list-load.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import threading
import time

import numpy as np

from .. import config, profiling
from ..io import chunkcache, chunkstore
from ..io.uris import has_scheme
from ..observe import metrics as _metrics
from ..observe import trace as _trace
from ..utils import cancel as _cancel

_BLOCKS = _metrics.counter("bst_dag_blocks_streamed_total")
_ELIDED = _metrics.counter("bst_dag_bytes_elided_total")
_REREAD = _metrics.counter("bst_dag_bytes_reread_total")
_EPH_WRITE = _metrics.counter("bst_dag_ephemeral_write_bytes_total")
_EXCHANGE = _metrics.gauge("bst_dag_exchange_bytes")
_QUEUE = _metrics.gauge("bst_dag_exchange_blocks")
_STALL = _metrics.counter("bst_dag_producer_stall_seconds_total")
_WAIT = _metrics.counter("bst_dag_consumer_wait_seconds_total")

# wake-up tick for gate/stall waits: long enough to be free, short enough
# that cancellation (polled on every tick) stays responsive
_TICK_S = 0.2


class StageToken:
    """Identity of one running stage. Carried in a contextvar (and into
    every worker pool the stage spawns, via utils.threads), so the
    chunkstore hooks know WHICH stage is reading or writing. Identity is
    the object itself — ids may repeat across concurrent runs."""

    __slots__ = ("stage_id", "run_id")

    def __init__(self, stage_id: str, run_id: str):
        self.stage_id = stage_id
        self.run_id = run_id

    def __repr__(self):
        return f"StageToken({self.stage_id!r}@{self.run_id})"


_current_stage: contextvars.ContextVar[StageToken | None] = \
    contextvars.ContextVar("bst-dag-stage", default=None)


def current_stage() -> StageToken | None:
    return _current_stage.get()


@contextlib.contextmanager
def stage_scope(token: StageToken):
    """Make ``token`` the ambient stage for this context (and, via
    utils.threads, every worker thread spawned under it)."""
    tok = _current_stage.set(token)
    try:
        yield token
    finally:
        _current_stage.reset(tok)


def norm_root(root) -> str:
    """Canonical edge key of a container root: URIs verbatim, local paths
    absolute — both the executor (registering the resolved spec path) and
    the hooks (seeing whatever string the driver opened the store with)
    normalize through here so they cannot disagree."""
    r = str(root)
    return r if has_scheme(r) else os.path.abspath(r)


class EdgeState:
    """One pipeline dataset edge: which stages produce and consume it,
    whether it streams (block gating + handoff) and whether its container
    is elided to memory, plus this run's authoritative totals. All
    mutable counters are guarded by the owning registry's lock."""

    def __init__(self, name: str, root: str, producers, consumers,
                 elided: bool = False, stream: bool = True):
        self.name = name
        self.root = norm_root(root)
        self.producers: frozenset[StageToken] = frozenset(producers)
        self.consumers: frozenset[StageToken] = frozenset(consumers)
        self.elided = bool(elided)
        self.stream = bool(stream)
        # per-run totals (filled under the registry lock)
        self.blocks_published = 0
        self.bytes_published = 0
        self.bytes_elided = 0
        self.bytes_reread = 0
        self.stall_s = 0.0
        self.wait_s = 0.0

    def summary(self) -> dict:
        return {
            "edge": self.name,
            "root": self.root,
            "elided": self.elided,
            "stream": self.stream,
            "blocks_streamed": self.blocks_published,
            "bytes_published": self.bytes_published,
            "bytes_elided": self.bytes_elided,
            "bytes_reread": self.bytes_reread,
            "producer_stall_s": round(self.stall_s, 3),
            "consumer_wait_s": round(self.wait_s, 3),
        }


def _geometry(ds):
    """(block_size, dims) of a dataset, or None when it has no usable
    chunk grid (the hooks then pass the IO through ungated)."""
    try:
        block = tuple(int(b) for b in ds.block_size)
        dims = tuple(int(d) for d in ds.shape)
    except Exception:
        return None
    if not block or len(block) != len(dims) or any(b <= 0 for b in block):
        return None
    return block, dims


def _ds_key(ds):
    """(normalized root, dataset path) of a Dataset, or None when it has
    no stable identity."""
    try:
        root, path = ds._cache_key()
    except Exception:
        return None
    if root is None:
        return None
    return norm_root(root), str(path).strip("/")


def _touched_positions(offset, shape, block):
    grids = [range(int(offset[d]) // block[d],
                   (int(offset[d]) + int(shape[d]) - 1) // block[d] + 1)
             for d in range(len(block))]
    return list(itertools.product(*grids))


def _covered_positions(offset, shape, block, dims):
    """Chunk positions whose full (array-clipped) extent lies inside the
    written box — only those may be marked complete / handed off; a
    partially covered interior chunk stays pending until the producer
    finishes (the drivers' grids are chunk-aligned, so in practice this
    is every touched chunk)."""
    nd = len(block)
    out = []
    for pos in _touched_positions(offset, shape, block):
        lo = [pos[d] * block[d] for d in range(nd)]
        hi = [min(lo[d] + block[d], dims[d]) for d in range(nd)]
        if all(lo[d] >= int(offset[d])
               and hi[d] <= int(offset[d]) + int(shape[d])
               for d in range(nd)):
            out.append(pos)
    return out


def _chunk_slices(pos, offset, block, dims):
    nd = len(block)
    return tuple(
        slice(pos[d] * block[d] - int(offset[d]),
              min((pos[d] + 1) * block[d], dims[d]) - int(offset[d]))
        for d in range(nd))


class StreamRegistry:
    """Process-wide edge registry + block exchange. One instance serves
    every concurrent pipeline run (runs register/unregister their own
    edges; stage tokens are object-identity so ids never collide)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._edges: dict[str, EdgeState] = {}          # root -> edge
        self._coverage: dict[tuple, set] = {}           # (root, path) -> pos
        self._pending: dict[tuple, list] = {}           # (root,path,pos) ->
        #                                       [nbytes, {consumer tokens}]
        self._finished: set[StageToken] = set()
        self._exchange_bytes = 0
        self._gate_waiters = 0

    # -- lifecycle (executor side) -----------------------------------------

    def register(self, edges) -> None:
        with self._cond:
            for e in edges:
                self._edges[e.root] = e
            if self._edges:
                # installed under the lock: a concurrent unregister of the
                # LAST other run must not race this install away
                chunkstore.set_dag_hooks(self)

    def unregister(self, edges) -> None:
        with self._cond:
            for e in edges:
                if self._edges.get(e.root) is e:
                    del self._edges[e.root]
                for key in [k for k in self._coverage if k[0] == e.root]:
                    del self._coverage[key]
                for key in [k for k in self._pending if k[0] == e.root]:
                    nbytes, _ = self._pending.pop(key)
                    self._exchange_bytes -= nbytes
                self._finished -= e.producers | e.consumers
            self._update_gauges_locked()
            if not self._edges:
                chunkstore.set_dag_hooks(None)
            self._cond.notify_all()

    def stage_finished(self, token: StageToken) -> None:
        """A stage reached a terminal state: release every exchange claim
        it still held and wake gate/stall waiters (producers-done and
        consumers-alive conditions may both have flipped)."""
        with self._cond:
            self._finished.add(token)
            for key in list(self._pending):
                nbytes, owed = self._pending[key]
                if token in owed:
                    owed.discard(token)
                    if not owed:
                        del self._pending[key]
                        self._exchange_bytes -= nbytes
            self._update_gauges_locked()
            self._cond.notify_all()

    def _update_gauges_locked(self) -> None:
        _EXCHANGE.set(self._exchange_bytes)
        _QUEUE.set(len(self._pending))

    # -- chunkstore hooks ---------------------------------------------------

    def gate(self, ds, offset, shape) -> None:
        """Block a consumer stage's read until the producer has written
        every storage chunk the box touches (or all producers finished).
        No-op for non-edge datasets, non-consumer stages, and reads the
        hook cannot reason about."""
        if not self._edges:
            return
        tok = _current_stage.get()
        if tok is None:
            return
        key = _ds_key(ds)
        if key is None:
            return
        root, path = key
        edge = self._edges.get(root)
        if edge is None or not edge.stream or tok not in edge.consumers:
            return
        geo = _geometry(ds)
        if geo is None:
            return
        block, _dims = geo
        if len(block) != len(tuple(offset)):
            return
        need = _touched_positions(offset, shape, block)
        with self._cond:
            if not self._missing_locked(root, path, need, edge, tok):
                self._consume_locked(edge, tok, root, path, need)
                return
            with profiling.span("dag.wait", stage=edge.name):
                t0 = time.perf_counter()
                self._gate_waiters += 1
                try:
                    while self._missing_locked(root, path, need, edge, tok):
                        self._cond.wait(_TICK_S)
                        _cancel.check("dag gate")
                finally:
                    self._gate_waiters -= 1
                    dt = time.perf_counter() - t0
                    edge.wait_s += dt
                    _WAIT.inc(dt)
                    self._cond.notify_all()
            self._consume_locked(edge, tok, root, path, need)

    def _missing_locked(self, root, path, need, edge, tok) -> bool:
        cov = self._coverage.get((root, path))
        if cov is not None and all(p in cov for p in need):
            return False
        # blocks a producer never writes (fusion's empty blocks) resolve
        # when every OTHER producer is terminal — the data then simply is
        # what the container holds
        return not all(p in self._finished
                       for p in edge.producers if p is not tok)

    def _consume_locked(self, edge, tok, root, path, need) -> None:
        drained = False
        for pos in need:
            ent = self._pending.get((root, path, pos))
            if ent is not None and tok in ent[1]:
                ent[1].discard(tok)
                if not ent[1]:
                    del self._pending[(root, path, pos)]
                    self._exchange_bytes -= ent[0]
                drained = True
        if drained:
            self._update_gauges_locked()
            self._cond.notify_all()

    def on_write(self, ds, data, offset) -> None:
        """Producer side: mark covered chunks complete, hand their decoded
        bytes to the chunk cache, charge the exchange, stall over budget."""
        if not self._edges:
            return
        key = _ds_key(ds)
        if key is None:
            return
        root, path = key
        edge = self._edges.get(root)
        if edge is None:
            return
        if edge.elided:
            _EPH_WRITE.inc(int(data.nbytes))
        if not edge.stream:
            return
        tok = _current_stage.get()
        if tok is None or tok not in edge.producers:
            # only DECLARED producers publish completion: a foreign write
            # into the same root (another daemon job, an init-style stage
            # not in `writes`) must never unblock a gated consumer with
            # bytes the real producer has not written yet
            return
        geo = _geometry(ds)
        if geo is None:
            return
        block, dims = geo
        if len(block) != data.ndim:
            return
        covered = _covered_positions(offset, data.shape, block, dims)
        if not covered:
            return
        # write-through handoff: the consumer's gated read finds these in
        # the decoded-chunk cache and never decodes the container (copies,
        # so a driver reusing its write buffer cannot corrupt the cache)
        if chunkcache.enabled() and ds._cacheable():
            dkey = ds._cache_key()
            sig = ds._cache_sig()
            cc = chunkcache.get_cache()
            for pos in covered:
                piece = np.array(
                    data[_chunk_slices(pos, offset, block, dims)], copy=True)
                cc.put((dkey, sig, pos), piece, record_miss=False)
        nbytes = int(data.nbytes)
        per = max(1, nbytes // len(covered))
        if _trace.enabled():
            _trace.instant("dag.publish", stage=edge.name, nbytes=nbytes,
                           item=tuple(int(o) for o in offset))
        with self._cond:
            cov = self._coverage.setdefault((root, path), set())
            fresh = [p for p in covered if p not in cov]
            cov.update(covered)
            if fresh:
                edge.blocks_published += len(fresh)
                edge.bytes_published += per * len(fresh)
                _BLOCKS.inc(len(fresh))
                owed = {c for c in edge.consumers
                        if c not in self._finished and c is not tok}
                if owed:
                    for p in fresh:
                        self._pending[(root, path, p)] = [per, set(owed)]
                    self._exchange_bytes += per * len(fresh)
                self._update_gauges_locked()
            self._cond.notify_all()
            self._stall_locked(edge, tok)

    def _stall_locked(self, edge, tok) -> None:
        """Backpressure: hold the producer while the exchange is over
        budget AND some consumer is alive to drain it AND no consumer is
        starved waiting for unpublished blocks (stalling then would be
        the textbook reorder deadlock — the producer must run)."""
        budget = config.get_bytes("BST_DAG_EXCHANGE_BYTES")

        def should_stall():
            if not budget or self._exchange_bytes <= budget:
                return False
            if self._gate_waiters:
                return False
            return any(c not in self._finished and c is not tok
                       for c in edge.consumers)

        if not should_stall():
            return
        with profiling.span("dag.stall", stage=edge.name):
            t0 = time.perf_counter()
            try:
                while should_stall():
                    self._cond.wait(_TICK_S)
                    _cancel.check("dag backpressure")
            finally:
                dt = time.perf_counter() - t0
                edge.stall_s += dt
                _STALL.inc(dt)

    def account_read(self, ds, via: str, nbytes: int) -> None:
        """Attribute a consumer's streamed-edge read bytes: ``cache`` =
        served by the handoff (container re-read elided), anything else =
        a container decode the streaming failed to elide."""
        if not self._edges or not nbytes:
            return
        tok = _current_stage.get()
        if tok is None:
            return
        key = _ds_key(ds)
        if key is None:
            return
        edge = self._edges.get(key[0])
        if edge is None or not edge.stream or tok not in edge.consumers:
            return
        with self._cond:
            if via == "cache":
                edge.bytes_elided += int(nbytes)
            else:
                edge.bytes_reread += int(nbytes)
        if via == "cache":
            _ELIDED.inc(int(nbytes))
        else:
            _REREAD.inc(int(nbytes))


_REGISTRY = StreamRegistry()


def registry() -> StreamRegistry:
    return _REGISTRY

"""The streaming stage-DAG executor: run a pipeline spec in one process.

Where the one-shot flow runs resave, detection, fusion and downsampling
as separate processes with full containers between them, this executor
runs the SAME click commands as stage nodes of a DAG, in one process, on
one warm mesh and one set of process-wide caches:

- a stage STARTS when its barrier parents (explicit ``after`` edges and
  producers of its non-streamed inputs) are done and its streamed
  producers have merely *started* — readiness below stage granularity is
  the stream registry's job (dag/stream.py), which gates each consumer
  read on the producer's block completions;
- a stage that fails or is cancelled poisons its downstream cone
  (transitively, via each stage's cancel token); independent branches
  run to completion;
- ephemeral intermediates are elided to ``memory://`` roots (or a
  run-scoped temp dir with disk backing) and cleaned up on success AND
  on failure/cancel, through ``ChunkStore.remove`` so the decoded-chunk
  cache sees the write-generation bump;
- inside a ``bst serve`` job the ambient job cancel token is polled by
  the coordination loop, so cancelling the daemon job poisons every
  stage.

With ``BST_DAG_EXCHANGE_ADDR`` set (dag/exchange.py) the executor also
runs MULTI-process: every rank executes the same spec SPMD — each stage
takes its deterministic slice of the work through the existing
multi-host paths (parallel/distributed.py) — while block coverage,
producer-done state and remote-owned chunks replicate between ranks
over the rank-addressed exchange, so a producer on one rank feeds a
consumer on another at block granularity. Ranks share one run id (rank
0's, allgathered) so elided roots resolve identically, and enter/leave
the run through barriers so no rank tears down containers a peer still
fetches from. Stages that issue collectives (resave/fusion barriers,
the pair-split allgather, the global solve) must not run concurrently
with each other — sequence them with ``after`` edges; the canonical
specs already do. Without the knob, a multi-process world is rejected
exactly as before.
"""

from __future__ import annotations

import os
import threading
import time

from dataclasses import dataclass, field

from .. import observe, profiling
from ..observe import metrics as _metrics
from ..utils import cancel as _cancel
from ..utils.threads import ctx_thread
from . import stream
from .spec import PipelineSpec, SpecError, StageSpec

PENDING = "pending"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
CANCELLED = "cancelled"

_TERMINAL = (DONE, FAILED, CANCELLED)

_STAGES_DONE = {s: _metrics.counter("bst_dag_stages_completed_total",
                                    status=s) for s in _TERMINAL}
_CONTAINERS_ELIDED = _metrics.counter("bst_dag_containers_elided_total")


@dataclass
class StageRun:
    """One stage's execution state."""

    spec: StageSpec
    token: stream.StageToken
    cancel: _cancel.CancelToken = field(default_factory=_cancel.CancelToken)
    state: str = PENDING
    error: str | None = None
    started_at: float | None = None
    finished_at: float | None = None

    def summary(self) -> dict:
        d = {"id": self.spec.id, "tool": self.spec.tool,
             "state": self.state}
        if self.started_at is not None:
            d["seconds"] = round((self.finished_at or time.time())
                                 - self.started_at, 3)
        if self.error:
            d["error"] = self.error
        return d


@dataclass
class PipelineResult:
    name: str
    ok: bool
    seconds: float
    stages: list[dict]
    edges: list[dict]
    containers_elided: int
    kept_intermediates: list[str]

    def to_dict(self) -> dict:
        return {
            "name": self.name, "ok": self.ok,
            "seconds": round(self.seconds, 3),
            "stages": self.stages, "edges": self.edges,
            "containers_elided": self.containers_elided,
            "kept_intermediates": self.kept_intermediates,
            "bytes_elided": sum(e["bytes_elided"] for e in self.edges),
            "bytes_reread": sum(e["bytes_reread"] for e in self.edges),
            "bytes_xhost": sum(e.get("bytes_xhost", 0)
                               for e in self.edges),
            "blocks_streamed": sum(e["blocks_streamed"]
                                   for e in self.edges),
            "blocks_handoff": sum(e["blocks_handoff"] for e in self.edges),
            "bytes_handoff": sum(e["bytes_handoff"] for e in self.edges),
            "bytes_spilled": sum(e["bytes_spilled"] for e in self.edges),
        }


def _new_run_id() -> str:
    # pid + monotonic tick: unique within this host's concurrent runs
    # without touching wall-clock randomness
    return f"{os.getpid():x}-{time.monotonic_ns() & 0xFFFFFFFF:08x}"


def _invoke_tool(tool: str, args: list[str]) -> int:
    """Run one registered click command in-process (the same invocation
    surface the serve daemon uses). Returns the exit code; raises on
    hard errors so the stage records the message."""
    import click

    from ..cli.main import cli as _cli

    try:
        _cli(args=[tool, *args], prog_name="bst", standalone_mode=False)
    except click.exceptions.Exit as e:
        return int(e.exit_code or 0)
    except SystemExit as e:
        return int(e.code) if isinstance(e.code, int) else 1
    return 0


def _remove_container(root: str) -> None:
    """Best-effort removal of an (ephemeral) container root — local trees
    rmtree'd, memory:// roots deleted from the shared kvstore — through
    ChunkStore.remove so the chunk cache invalidates and write
    generations bump."""
    from ..io.chunkstore import ChunkStore, StorageFormat

    try:
        ChunkStore(root, StorageFormat.N5).remove("")
    except Exception as e:  # cleanup must never mask the run's outcome
        observe.log(f"pipeline: cleanup of {root} failed: {e!r}",
                    stage="pipeline")


class _Executor:
    def __init__(self, spec: PipelineSpec, run_id: str, rank: int = 0,
                 world: int = 1):
        self.spec = spec
        self.run_id = run_id
        self.rank = rank
        self.world = world
        self._lock = threading.Lock()
        self._changed = threading.Condition(self._lock)
        self.runs = {
            s.id: StageRun(spec=s, token=stream.StageToken(s.id, run_id))
            for s in spec.stages
        }

    # -- dependency queries (pure) -----------------------------------------

    def _children(self, sid: str) -> set[str]:
        return {s.id for s in self.spec.stages
                if sid in self.spec.parents(s)}

    def _cone(self, sid: str) -> set[str]:
        out, work = set(), [sid]
        while work:
            cur = work.pop()
            for c in self._children(cur):
                if c not in out:
                    out.add(c)
                    work.append(c)
        return out

    def _eligible_locked(self, run: StageRun) -> bool:
        for p in self.spec.barrier_parents(run.spec):
            if self.runs[p].state != DONE:
                return False
        for p in self.spec.stream_parents(run.spec):
            if self.runs[p].state not in (RUNNING, DONE):
                return False
        return True

    def _doomed_locked(self, run: StageRun) -> bool:
        return any(self.runs[p].state in (FAILED, CANCELLED)
                   for p in self.spec.parents(run.spec))

    # -- stage thread -------------------------------------------------------

    def _owners(self, run: StageRun) -> set[int] | None:
        """Peer ranks a pinned stage runs on, or None when this rank runs
        it itself (unpinned stage, owner rank, or single-process world —
        ``ranks`` is a multi-host concern only)."""
        if not run.spec.ranks or self.world <= 1:
            return None
        owners = {r for r in run.spec.ranks if r < self.world}
        if not owners or self.rank in owners:
            return None
        return owners

    def _run_stage(self, run: StageRun) -> None:
        import click

        state, err = DONE, None
        try:
            with _cancel.scope(run.cancel), \
                    stream.stage_scope(run.token), \
                    profiling.span("dag.stage", stage=run.spec.id):
                owners = self._owners(run)
                if owners is not None:
                    # rank-pinned stage owned elsewhere: adopt the
                    # owners' outcome from their exchange broadcasts
                    if not stream.registry().wait_remote_done(
                            run.spec.id, owners):
                        state, err = FAILED, (
                            f"rank-pinned stage failed on peer rank(s) "
                            f"{sorted(owners)}")
                else:
                    rc = _invoke_tool(run.spec.tool, run.spec.args)
                    if rc != 0:
                        state, err = FAILED, f"exit code {rc}"
        except _cancel.Cancelled:
            state, err = CANCELLED, "cancelled"
        except click.ClickException as e:
            state, err = FAILED, e.format_message()
        except BaseException as e:  # noqa: BLE001 — stage crash isolation
            state, err = FAILED, repr(e)[:500]
        stream.registry().stage_finished(run.token, ok=(state == DONE))
        with self._changed:
            run.state = state
            run.error = err
            run.finished_at = time.time()
            _STAGES_DONE[state].inc()
            if state != DONE:
                self._poison_cone_locked(run.spec.id)
            self._changed.notify_all()
        observe.log(f"pipeline: stage {run.spec.id} {state}"
                    f"{' (' + err + ')' if err else ''}", stage="pipeline")

    def _poison_cone_locked(self, sid: str) -> None:
        """A terminal non-DONE stage cancels its downstream cone: running
        descendants get their token set (the work loops unwind at their
        safe points), pending ones flip straight to CANCELLED."""
        for did in self._cone(sid):
            d = self.runs[did]
            d.cancel.cancel()
            if d.state == PENDING:
                d.state = CANCELLED
                d.error = f"upstream {sid} failed/cancelled"
                d.finished_at = time.time()
                _STAGES_DONE[CANCELLED].inc()
                stream.registry().stage_finished(d.token, ok=False)

    # -- coordination loop --------------------------------------------------

    def run(self) -> None:
        threads: list[threading.Thread] = []
        with self._changed:
            while True:
                for run in self.runs.values():
                    if run.state != PENDING:
                        continue
                    if self._doomed_locked(run):
                        run.state = CANCELLED
                        run.error = "upstream failed/cancelled"
                        run.finished_at = time.time()
                        _STAGES_DONE[CANCELLED].inc()
                        stream.registry().stage_finished(run.token,
                                                         ok=False)
                        continue
                    if self._eligible_locked(run):
                        run.state = RUNNING
                        run.started_at = time.time()
                        observe.log(f"pipeline: stage {run.spec.id} "
                                    f"({run.spec.tool}) started",
                                    stage="pipeline")
                        th = ctx_thread(self._run_stage, (run,),
                                        name=f"bst-dag-{run.spec.id}")
                        th.start()
                        threads.append(th)
                if all(r.state in _TERMINAL for r in self.runs.values()):
                    break
                self._changed.wait(0.2)
                if _cancel.cancelled():
                    # the surrounding job (a `bst serve` cancel, a daemon
                    # drain) was poisoned: poison every stage and keep
                    # looping until they unwind
                    for run in self.runs.values():
                        run.cancel.cancel()
                        if run.state == PENDING:
                            run.state = CANCELLED
                            run.error = "pipeline cancelled"
                            run.finished_at = time.time()
                            _STAGES_DONE[CANCELLED].inc()
                            stream.registry().stage_finished(run.token,
                                                             ok=False)
        for th in threads:
            th.join()


def run_pipeline(spec: PipelineSpec | dict | str, *,
                 workdir: str | None = None,
                 keep_intermediates: bool = False) -> PipelineResult:
    """Execute a pipeline spec (a :class:`PipelineSpec`, a spec dict, or
    a path to a spec JSON file). Returns the :class:`PipelineResult`;
    raises :class:`dag.spec.SpecError` on a malformed spec. Stage
    failures do NOT raise — they are reported per stage with
    ``result.ok`` False."""
    if isinstance(spec, str):
        if workdir is None:
            workdir = os.path.dirname(os.path.abspath(spec)) or "."
        spec = PipelineSpec.load(spec)
    elif isinstance(spec, dict):
        spec = PipelineSpec.from_dict(spec)
    else:
        spec.validate()
    workdir = os.path.abspath(workdir or os.getcwd())

    from ..parallel import distributed as _dist

    xch = None
    if _dist.world()[1] > 1:
        from . import exchange as _exchange

        xch = _exchange.ensure_started()
        if xch is None:
            raise SpecError(
                "bst pipeline needs the cross-host block exchange to run "
                "multi-process: set BST_DAG_EXCHANGE_ADDR (one host:port "
                "per rank) to execute the spec SPMD across ranks, or run "
                "the one-shot tools")

    run_id = _new_run_id()
    if xch is not None:
        # every rank must resolve IDENTICAL elided roots and temp dirs —
        # the exchange keys coverage on them; rank 0's id wins
        run_id = _dist.allgather_object(run_id)[0]
    spec.resolve(workdir, keep_intermediates, run_id)
    rank, world = _dist.world() if xch is not None else (0, 1)
    ex = _Executor(spec, run_id, rank=rank, world=world)

    edges = []
    for name, ds in spec.datasets.items():
        consumers = {ex.runs[c].token for c in spec.consumers_of(name)}
        producers = {ex.runs[p].token for p in spec.producers_of(name)}
        if not consumers and not ds.elided:
            continue  # nothing to gate, nothing to account
        edges.append(stream.EdgeState(
            name, ds.resolved, producers, consumers,
            elided=ds.elided, stream=ds.stream))
    elided_roots = [ds.resolved for ds in spec.datasets.values()
                    if ds.elided]
    temp_roots = [ds.resolved for ds in spec.datasets.values()
                  if ds.ephemeral and not keep_intermediates
                  and not ds.elided]
    kept = [ds.resolved for ds in spec.datasets.values()
            if ds.ephemeral and keep_intermediates]
    _CONTAINERS_ELIDED.inc(len(elided_roots))

    reg = stream.registry()
    reg.register(edges)
    if xch is not None:
        reg.set_exchange(xch)
        # no rank may start producing (and broadcasting covers) into a
        # world where a peer has not yet registered its edges
        _dist.barrier("dag-start")
    t0 = time.time()
    observe.log(f"pipeline {spec.name}: {len(spec.stages)} stages, "
                f"{len(edges)} edges "
                f"({len(elided_roots)} container(s) elided to memory)",
                stage="pipeline")
    try:
        ex.run()
    finally:
        if xch is not None:
            # peers may still be fetching this rank's chunks: hold the
            # containers and the serve index until every rank's stages
            # are terminal, then detach (clearing remote state)
            try:
                _dist.barrier("dag-end")
            finally:
                reg.set_exchange(None)
        reg.unregister(edges)
        # ephemeral lifecycle: cleaned on success AND on failure/cancel —
        # a half-written elided tree must never outlive its run
        with profiling.span("dag.cleanup"):
            for root in [*elided_roots, *temp_roots]:
                _remove_container(root)
            for root in temp_roots:
                parent = os.path.dirname(root)
                if os.path.basename(parent).startswith(".bst-dag-tmp-"):
                    try:
                        os.rmdir(parent)
                    except OSError:
                        pass

    seconds = time.time() - t0
    stage_rows = [ex.runs[s.id].summary() for s in spec.stages]
    edge_rows = [e.summary() for e in edges]
    ok = all(r["state"] == DONE for r in stage_rows)
    observe.progress.record_stage(
        "pipeline",
        done=sum(1 for r in stage_rows if r["state"] == DONE),
        total=len(stage_rows),
        name=spec.name,
        seconds=round(seconds, 3),
        blocks_streamed=sum(e["blocks_streamed"] for e in edge_rows),
        bytes_elided=sum(e["bytes_elided"] for e in edge_rows),
        bytes_reread=sum(e["bytes_reread"] for e in edge_rows),
        blocks_handoff=sum(e["blocks_handoff"] for e in edge_rows),
        bytes_handoff=sum(e["bytes_handoff"] for e in edge_rows),
        containers_elided=len(elided_roots),
    )
    return PipelineResult(
        name=spec.name, ok=ok, seconds=seconds, stages=stage_rows,
        edges=edge_rows, containers_elided=len(elided_roots),
        kept_intermediates=kept)

"""Cross-host block exchange: rank-addressed chunk transport for the DAG.

The stream registry (dag/stream.py) gates consumer reads on producer
block completions — but its coverage map and decoded-chunk handoff live
in ONE process. A multi-process pipeline run (every rank executing the
same spec SPMD, each stage taking its deterministic slice of the block
grid) therefore needs three things this module provides, riding the
relay's line-JSON TCP framing (observe/relay.py, PR 15):

- **coverage broadcast** — a producer's published chunk positions are
  pushed to every peer rank (``cover`` messages), so a remote consumer's
  gate releases the moment the block lands anywhere in the world.
  Stage-terminal ``done`` messages extend the producers-finished release
  the same way: a gate only falls through to "the data is what the
  container holds" once every rank's instance of the producer is
  terminal.
- **chunk fetch** — a consumer whose needed chunk is owned by a remote
  rank pulls it ONCE over TCP (``fetch`` request, header line + raw
  bytes reply) into the local decoded-chunk LRU; the read then resolves
  via the cache exactly like a local handoff (zero container decode,
  accounted ``bst_dag_xhost_bytes_total``).
- **failure containment** — a peer whose connection dies without a
  ``bye`` is declared dead; gates waiting on its blocks raise instead of
  hanging, so only the downstream cone of the streamed edge fails while
  independent branches run to completion. Push queues are BOUNDED: a
  slow peer backpressures the producing rank (counted in
  ``bst_dag_xhost_stall_seconds_total``), it never drops a cover
  message (dropping one would wedge a remote gate forever).

Addressing is static and rank-ordered: ``BST_DAG_EXCHANGE_ADDR`` holds a
comma-separated ``host:port`` list where entry *i* is the endpoint rank
*i* serves. Same trust model as the telemetry relay: plain TCP, no auth,
pod-internal networks only.
"""

from __future__ import annotations

import contextlib
import json
import queue as _queuemod
import socket
import threading
import time

import numpy as np

from .. import config, profiling
from ..observe import metrics as _metrics
from ..observe.relay import _set_keepalive, _shutdown_close
from ..utils import cancel as _cancel

SCHEMA = "bst-xhost/1"

_FETCHES = _metrics.counter("bst_dag_xhost_fetches_total")
_FETCH_BYTES = _metrics.counter("bst_dag_xhost_bytes_total")
_SERVED_BYTES = _metrics.counter("bst_dag_xhost_served_bytes_total")
_STALL = _metrics.counter("bst_dag_xhost_stall_seconds_total")
_PEERS = _metrics.gauge("bst_dag_xhost_peers_connected")

# push-queue tick while blocked on a full peer queue: long enough to be
# free, short enough that cancellation stays responsive
_TICK_S = 0.2
# one fetch round trip (request + decode + reply) must finish within
# this, or the peer is treated as gone for THIS fetch and retried once
_FETCH_TIMEOUT_S = 30.0


class ExchangeError(RuntimeError):
    """A peer rank died or the exchange cannot serve a required chunk."""


def parse_addresses(spec: str) -> list[tuple[str, int]]:
    """``host:port,host:port,...`` -> rank-ordered endpoint list."""
    out = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        host, sep, port = part.rpartition(":")
        if not sep:
            raise ValueError(
                f"BST_DAG_EXCHANGE_ADDR wants host:port entries, got "
                f"{part!r}")
        out.append((host or "127.0.0.1", int(port)))
    return out


def _send_line(sock: socket.socket, msg: dict) -> None:
    sock.sendall((json.dumps(msg) + "\n").encode())


def _recv_exact(f, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        piece = f.read(n - len(buf))
        if not piece:
            raise ExchangeError("peer closed mid-payload")
        buf += piece
    return bytes(buf)


class _Peer:
    """The outbound side toward ONE remote rank: a bounded push queue
    drained by a sender thread (cover/done broadcasts, backoff
    reconnect) plus a lock-guarded request/reply connection for chunk
    fetches. Neither connection is opened until first use."""

    def __init__(self, rank: int, address: tuple[str, int],
                 my_rank: int, queue_max: int):
        self.rank = rank
        self.address = address
        self.my_rank = my_rank
        self._q: _queuemod.Queue = _queuemod.Queue(maxsize=queue_max)
        self._stop = threading.Event()
        self._sock: socket.socket | None = None
        self._backoff = 1.0
        # _fetch_lock serializes whole fetch round trips; _fetch_ref_lock
        # guards ONLY the connection refs, so teardown can interrupt an
        # in-flight round trip without waiting up to _FETCH_TIMEOUT_S for
        # _fetch_lock to come free
        self._fetch_lock = threading.Lock()
        self._fetch_ref_lock = threading.Lock()
        self._fetch_sock: socket.socket | None = None
        self._fetch_file = None
        # raw daemon thread on purpose: the sender is peer-lived, shared
        # by every job in the process, and must not pin the first job's
        # cancel scope or config overrides (what ctx_thread would capture)
        self._thread = threading.Thread(  # bst-lint: off=thread-spawn
            target=self._run, name=f"bst-xhost-peer-{rank}", daemon=True)
        self._thread.start()

    # -- push side (cover / done broadcasts) --------------------------------

    def push(self, msg: dict) -> None:
        """Enqueue one broadcast. A full queue BLOCKS (counted stall):
        cover messages are correctness, not telemetry — dropping one
        would leave a remote gate waiting forever."""
        while not self._stop.is_set():
            try:
                self._q.put(msg, timeout=_TICK_S)
                return
            except _queuemod.Full:
                _STALL.inc(_TICK_S)
                _cancel.check("xhost push")

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                msg = self._q.get(timeout=0.5)
            except _queuemod.Empty:
                continue
            while not self._stop.is_set():
                sock = self._connect()
                if sock is None:
                    time.sleep(min(self._backoff, 1.0))
                    continue
                try:
                    _send_line(sock, msg)
                    break
                except OSError:
                    self._close()
        # drain best-effort on shutdown so the goodbye (and any final
        # covers) reach a still-listening peer instead of being dropped
        while True:
            try:
                msg = self._q.get_nowait()
            except _queuemod.Empty:
                break
            sock = self._connect()
            if sock is None:
                break
            try:
                _send_line(sock, msg)
            except OSError:
                break
        self._close()
        self._close_fetch()

    def _connect(self) -> socket.socket | None:
        if self._sock is not None:
            return self._sock
        sock = self._open()
        if sock is None:
            return None
        self._sock = sock
        return sock

    def _open(self) -> socket.socket | None:
        try:
            sock = socket.create_connection(self.address, timeout=5.0)
        except OSError:
            self._backoff = min(self._backoff * 2, 5.0)
            return None
        self._backoff = 1.0
        sock.settimeout(10.0)
        _set_keepalive(sock)
        try:
            _send_line(sock, {"t": "hello", "schema": SCHEMA,
                              "rank": self.my_rank})
        except OSError:
            _shutdown_close(sock)
            return None
        return sock

    def _close(self) -> None:
        if self._sock is not None:
            _shutdown_close(self._sock)
            self._sock = None

    # -- fetch side (request/reply) -----------------------------------------

    def fetch(self, root: str, path: str, pos: tuple) -> np.ndarray:
        """Pull one decoded chunk from this peer. Retries a broken
        connection once (the peer may have restarted between covers);
        a second failure raises :class:`ExchangeError`."""
        last: Exception | None = None
        for _ in range(2):
            try:
                return self._fetch_once(root, path, pos)
            except (OSError, ExchangeError) as e:
                last = e
                self._close_fetch()
                if isinstance(e, ExchangeError) and "peer error" in str(e):
                    break   # the peer answered; retrying will not help
        raise ExchangeError(
            f"fetch of {path}:{pos} from rank {self.rank} "
            f"({self.address[0]}:{self.address[1]}) failed: {last}")

    def _fetch_once(self, root, path, pos) -> np.ndarray:
        if self._stop.is_set():
            raise ExchangeError("peer is stopped")
        # one outstanding round trip per peer keeps the reply stream
        # unambiguous, so blocking while _fetch_lock is held is the
        # POINT of the lock: nothing else ever waits on it — teardown
        # interrupts an in-flight round trip via _close_fetch's socket
        # shutdown (under _fetch_ref_lock), never by taking this lock
        with self._fetch_lock:
            head, raw = self._fetch_roundtrip(root, path, pos)  # bst-lint: off=blocking-under-lock — round-trip serialization lock, interrupted via _close_fetch, see above
        arr = np.frombuffer(raw, dtype=np.dtype(head["dtype"]))
        return arr.reshape(tuple(head["shape"])).copy()

    def _fetch_roundtrip(self, root, path, pos) -> tuple[dict, bytes]:
        """One fetch request/reply on the cached connection, opening it
        on first use. Caller holds ``_fetch_lock``; the refs publish
        under ``_fetch_ref_lock`` so ``_close_fetch`` can shut the
        socket down mid-round-trip (the reader unblocks with EOF)."""
        with self._fetch_ref_lock:
            sock, f = self._fetch_sock, self._fetch_file
        if sock is None:
            sock = socket.create_connection(self.address, timeout=5.0)
            sock.settimeout(_FETCH_TIMEOUT_S)
            _set_keepalive(sock)
            _send_line(sock, {"t": "hello", "schema": SCHEMA,
                              "rank": self.my_rank})
            f = sock.makefile("rb")
            with self._fetch_ref_lock:
                publish = not self._stop.is_set()
                if publish:
                    self._fetch_sock, self._fetch_file = sock, f
            if not publish:
                # stopped while connecting: tear the fresh connection
                # down ourselves, _close_fetch already ran
                with contextlib.suppress(OSError):
                    f.close()
                _shutdown_close(sock)
                raise ExchangeError("peer is stopped")
        _send_line(sock, {"t": "fetch", "root": root, "path": path,
                          "pos": list(pos)})
        line = f.readline()
        if not line:
            raise ExchangeError("peer closed during fetch")
        head = json.loads(line)
        if not head.get("ok"):
            raise ExchangeError(f"peer error: {head.get('error')}")
        return head, _recv_exact(f, int(head["nbytes"]))

    def _close_fetch(self) -> None:
        """Interrupt-style teardown: swap the refs out under the tiny
        ref lock (NEVER ``_fetch_lock`` — an in-flight round trip can
        hold that for up to ``_FETCH_TIMEOUT_S``), then shut the socket
        down FIRST so a reader blocked in ``readline`` unblocks with
        EOF, and only then close the file wrapper."""
        with self._fetch_ref_lock:
            sock, f = self._fetch_sock, self._fetch_file
            self._fetch_sock = self._fetch_file = None
        if sock is not None:
            _shutdown_close(sock)
        if f is not None:
            with contextlib.suppress(OSError):
                f.close()

    def stop(self) -> None:
        self._stop.set()
        # interrupt any in-flight fetch BEFORE joining the sender: a
        # round trip wedged on a dead peer would otherwise hold stop()
        # hostage for up to _FETCH_TIMEOUT_S per peer
        self._close_fetch()
        self._thread.join(timeout=5.0)


class Exchange:
    """One rank's exchange endpoint: the server every peer pushes to and
    fetches from, plus one :class:`_Peer` per remote rank. ``registry``
    is the stream registry the server applies remote state to (the
    process singleton in production; tests wire private registries to
    simulate a world inside one process)."""

    def __init__(self, rank: int, addresses, registry=None,
                 queue_max: int | None = None):
        from . import stream as _stream

        self.rank = int(rank)
        self.addresses = list(addresses)
        if not (0 <= self.rank < len(self.addresses)):
            raise ValueError(
                f"exchange rank {rank} outside the {len(self.addresses)}"
                f"-entry BST_DAG_EXCHANGE_ADDR list")
        self.registry = registry if registry is not None \
            else _stream.registry()
        qmax = max(8, queue_max if queue_max is not None
                   else config.get_int("BST_RELAY_QUEUE") or 256)
        self._peers = {r: _Peer(r, a, self.rank, qmax)
                       for r, a in enumerate(self.addresses)
                       if r != self.rank}
        self._stop = threading.Event()
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        host, port = self.addresses[self.rank]
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("" if host in ("", "0.0.0.0") else host, port))
        srv.listen(16)
        srv.settimeout(0.5)
        self._server = srv
        # raw daemon thread on purpose: the acceptor is exchange-lived
        # and serves every job — it must not capture one job's context
        self._accept_thread = threading.Thread(  # bst-lint: off=thread-spawn
            target=self._accept_loop, name="bst-xhost-server", daemon=True)
        self._accept_thread.start()

    @property
    def world(self) -> int:
        return len(self.addresses)

    # -- broadcasts (producer side) -----------------------------------------

    def broadcast_cover(self, root: str, path: str, positions,
                        per: int) -> None:
        msg = {"t": "cover", "rank": self.rank, "root": root,
               "path": path, "pos": [list(p) for p in positions],
               "per": int(per)}
        for p in self._peers.values():
            p.push(msg)

    def broadcast_done(self, stage_id: str, ok: bool = True) -> None:
        msg = {"t": "done", "rank": self.rank, "stage": stage_id,
               "ok": bool(ok)}
        for p in self._peers.values():
            p.push(msg)

    # -- fetch (consumer side) ----------------------------------------------

    def fetch(self, rank: int, root: str, path: str,
              pos: tuple) -> np.ndarray:
        peer = self._peers.get(int(rank))
        if peer is None:
            raise ExchangeError(f"no exchange peer for rank {rank}")
        with profiling.span("dag.xhost_fetch", item=f"rank{rank}",
                            stage=path):
            arr = peer.fetch(root, path, tuple(int(x) for x in pos))
        _FETCHES.inc()
        _FETCH_BYTES.inc(int(arr.nbytes))
        return arr

    # -- server side ---------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.settimeout(None)
            _set_keepalive(conn)
            with self._conns_lock:
                self._conns.add(conn)
                _PEERS.set(len(self._conns))
            # raw daemon thread on purpose: serves a PEER RANK's pushes
            # for the life of its connection, on behalf of every job
            threading.Thread(target=self._serve_conn, args=(conn,),  # bst-lint: off=thread-spawn
                             name="bst-xhost-conn", daemon=True).start()
        with contextlib.suppress(OSError):
            self._server.close()

    def _serve_conn(self, conn: socket.socket) -> None:
        """One peer connection: line-JSON requests, dispatched to the
        registry. A connection that drops WITHOUT a ``bye`` from a rank
        that said hello marks that rank dead — its blocks will never
        arrive, and every gate waiting on them must fail rather than
        hang."""
        rank: int | None = None
        clean = False
        f = conn.makefile("rb")
        try:
            for line in f:
                if self._stop.is_set():
                    clean = True
                    break
                try:
                    msg = json.loads(line)
                except ValueError:
                    continue
                t = msg.get("t")
                if t == "hello":
                    rank = int(msg.get("rank", -1))
                elif t == "cover":
                    self.registry.remote_cover(
                        msg["root"], msg["path"],
                        [tuple(int(x) for x in p) for p in msg["pos"]],
                        int(msg["rank"]), int(msg.get("per", 1)))
                elif t == "done":
                    self.registry.remote_done(msg["stage"],
                                              int(msg["rank"]),
                                              bool(msg.get("ok", True)))
                elif t == "fetch":
                    self._serve_fetch(conn, msg)
                elif t == "bye":
                    clean = True
                    break
        except (OSError, ValueError):
            pass
        finally:
            with contextlib.suppress(OSError):
                f.close()
            with self._conns_lock:
                self._conns.discard(conn)
                _PEERS.set(len(self._conns))
            _shutdown_close(conn)
            if rank is not None and not clean and not self._stop.is_set():
                self.registry.remote_rank_dead(rank)

    def _serve_fetch(self, conn: socket.socket, msg: dict) -> None:
        pos = tuple(int(x) for x in msg["pos"])
        with profiling.span("dag.xhost_serve", stage=str(msg["path"])):
            try:
                arr = self.registry.serve_chunk(
                    str(msg["root"]), str(msg["path"]), pos)
            except Exception as e:   # noqa: BLE001 — reply, don't die
                arr, err = None, repr(e)
            else:
                err = f"no chunk {msg['path']}:{pos} on rank {self.rank}"
        if arr is None:
            _send_line(conn, {"t": "chunk", "ok": False, "error": err})
            return
        arr = np.ascontiguousarray(arr)
        _send_line(conn, {"t": "chunk", "ok": True,
                          "dtype": arr.dtype.str, "shape": list(arr.shape),
                          "nbytes": int(arr.nbytes)})
        conn.sendall(arr.tobytes())
        _SERVED_BYTES.inc(int(arr.nbytes))

    # -- lifecycle -----------------------------------------------------------

    def stop(self) -> None:
        if self._stop.is_set():
            return
        for p in self._peers.values():
            p.push({"t": "bye", "rank": self.rank})
        self._stop.set()
        for p in self._peers.values():
            p.stop()
        with contextlib.suppress(OSError):
            self._server.close()
        self._accept_thread.join(timeout=5.0)
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            _shutdown_close(c)


# -- process singleton --------------------------------------------------------

_STARTED: list[Exchange | None] = [None]
_START_LOCK = threading.Lock()


def configured() -> bool:
    return bool(config.get_str("BST_DAG_EXCHANGE_ADDR"))


def ensure_started() -> Exchange | None:
    """The process-wide exchange for this rank, started on first call.
    None when ``BST_DAG_EXCHANGE_ADDR`` is unset or the jax world is a
    single process (nothing to exchange with). Raises when the address
    list is shorter than the world — a rank without an endpoint cannot
    participate."""
    spec = config.get_str("BST_DAG_EXCHANGE_ADDR")
    if not spec:
        return None
    from ..parallel.distributed import world

    pi, pc = world()
    if pc <= 1:
        return None
    with _START_LOCK:
        if _STARTED[0] is not None:
            return _STARTED[0]
        addrs = parse_addresses(spec)
        if len(addrs) < pc:
            raise ExchangeError(
                f"BST_DAG_EXCHANGE_ADDR lists {len(addrs)} endpoint(s) "
                f"for a {pc}-process world")
        _STARTED[0] = Exchange(pi, addrs[:pc])
        return _STARTED[0]


def shutdown() -> None:
    with _START_LOCK:
        x, _STARTED[0] = _STARTED[0], None
    if x is not None:
        x.stop()

"""Streaming block-granular stage-DAG executor (`bst pipeline`).

Declares pipelines of existing ``bst`` tools as stage nodes with dataset
edges, runs them in one process on the warm mesh and caches, tracks
readiness at output-block granularity (a consumer starts while its
producer is still writing), hands blocks over in memory through the
decoded-chunk cache, and optionally elides intermediate containers to
``memory://`` roots entirely — killing the write-then-reread round trip
between resave, fusion, downsampling and detection.

- :mod:`dag.spec` — the pipeline spec model (JSON + Python API).
- :mod:`dag.stream` — the block-exchange registry hooked into
  ``Dataset.read``/``write``.
- :mod:`dag.executor` — stage scheduling, failure-cone cancellation,
  ephemeral-container lifecycle.
"""

from .executor import PipelineResult, run_pipeline
from .spec import PipelineSpec, SpecError, example_spec, registration_spec

__all__ = ["PipelineResult", "PipelineSpec", "SpecError", "example_spec",
           "registration_spec", "run_pipeline"]

"""Thread-safe in-process metrics registry with Prometheus textfile export.

The Spark reference gets per-stage task counts, byte totals and retry
accounting from the Spark metrics system for free; here every layer
(chunk IO, transfers, retry, stage drivers) feeds one process-wide
registry. The registry is ALWAYS on — a counter update is one lock
acquisition per chunk-level operation, invisible next to the IO it
accounts — while the event log and manifests only activate with
``--telemetry-dir``. ``bench.py`` snapshots/deltas the same registry, so
BENCH artifacts gain IO/transfer columns without bespoke glue.

Series are keyed by ``(name, sorted(labels))``; handles stay valid across
``reset()`` (values are zeroed in place, series are never dropped), so hot
paths may cache the returned Counter/Gauge/Histogram objects.
"""

from __future__ import annotations

import bisect
import threading


class Counter:
    """Monotonic counter (resettable only via the registry)."""

    __slots__ = ("_lock", "_v")

    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0

    def inc(self, v: int | float = 1) -> None:
        with self._lock:
            self._v += v

    @property
    def value(self) -> int | float:
        return self._v

    def _reset(self) -> None:
        with self._lock:
            self._v = 0


class Gauge:
    """Last-value gauge."""

    __slots__ = ("_lock", "_v")

    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0

    def set(self, v: int | float) -> None:
        with self._lock:
            self._v = v

    def inc(self, v: int | float = 1) -> None:
        with self._lock:
            self._v += v

    @property
    def value(self) -> int | float:
        return self._v

    def _reset(self) -> None:
        with self._lock:
            self._v = 0


DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   30.0, 60.0, 300.0)


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics)."""

    __slots__ = ("_lock", "buckets", "_counts", "_sum", "_count")

    def __init__(self, buckets=DEFAULT_BUCKETS):
        self._lock = threading.Lock()
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +1 = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def value(self) -> dict:
        with self._lock:
            return {"count": self._count, "sum": self._sum}

    def cumulative_counts(self) -> list[int]:
        with self._lock:
            out, acc = [], 0
            for c in self._counts:
                acc += c
                out.append(acc)
            return out

    def _reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0


def _series_key(name: str, labels: dict) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{_escape(str(v))}"'
                     for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._series: dict[str, Counter | Gauge | Histogram] = {}
        self._labels: dict[str, dict] = {}
        self._types: dict[str, str] = {}

    def _get(self, cls, typ: str, name: str, labels: dict, **kw):
        key = _series_key(name, labels)
        with self._lock:
            if self._types.setdefault(name, typ) != typ:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{self._types[name]}, not {typ}")
            m = self._series.get(key)
            if m is None:
                m = cls(**kw)
                self._series[key] = m
                self._labels[key] = dict(labels)
            return m

    def counter(self, name: str, /, **labels) -> Counter:
        return self._get(Counter, "counter", name, labels)

    def gauge(self, name: str, /, **labels) -> Gauge:
        return self._get(Gauge, "gauge", name, labels)

    def histogram(self, name: str, /, buckets=DEFAULT_BUCKETS,
                  **labels) -> Histogram:
        return self._get(Histogram, "histogram", name, labels,
                         buckets=buckets)

    def reset(self) -> None:
        """Zero every series in place (cached handles stay valid)."""
        with self._lock:
            for m in self._series.values():
                m._reset()

    def snapshot(self) -> dict:
        """``{series_key: value}`` — numbers for counters/gauges,
        ``{"count", "sum"}`` dicts for histograms."""
        with self._lock:
            items = list(self._series.items())
        return {k: m.value for k, m in items}

    def snapshot_delta(self, baseline: dict | None) -> dict:
        """Current snapshot minus ``baseline`` (series absent from the
        baseline count from zero). Gauges report their current value."""
        cur = self.snapshot()
        if not baseline:
            return cur
        out = {}
        with self._lock:
            types = {k: type(m) for k, m in self._series.items()}
        for k, v in cur.items():
            b = baseline.get(k)
            if types.get(k) is Gauge or b is None:
                out[k] = v
            elif isinstance(v, dict):
                out[k] = {"count": v["count"] - b.get("count", 0),
                          "sum": v["sum"] - b.get("sum", 0.0)}
            else:
                out[k] = v - b
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition (textfile-collector compatible)."""
        with self._lock:
            items = sorted(self._series.items())
            labels = dict(self._labels)
            types = dict(self._types)
        lines: list[str] = []
        seen_type: set[str] = set()
        for key, m in items:
            name = key.split("{", 1)[0]
            if name not in seen_type:
                seen_type.add(name)
                lines.append(f"# TYPE {name} {types[name]}")
            if isinstance(m, Histogram):
                lab = labels[key]
                cum = m.cumulative_counts()
                for edge, c in zip((*m.buckets, "+Inf"), cum):
                    le = {**lab, "le": edge}
                    lines.append(f"{_series_key(name + '_bucket', le)} {c}")
                v = m.value
                suffix = key[len(name):]
                lines.append(f"{name}_sum{suffix} {_fmt(v['sum'])}")
                lines.append(f"{name}_count{suffix} {v['count']}")
            else:
                lines.append(f"{key} {_fmt(m.value)}")
        return "\n".join(lines) + "\n"


def _fmt(v) -> str:
    if isinstance(v, float) and not v.is_integer():
        return repr(v)
    return str(int(v))


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


def counter(name: str, /, **labels) -> Counter:
    return _REGISTRY.counter(name, **labels)


def gauge(name: str, /, **labels) -> Gauge:
    return _REGISTRY.gauge(name, **labels)


def histogram(name: str, /, buckets=DEFAULT_BUCKETS, **labels) -> Histogram:
    return _REGISTRY.histogram(name, buckets=buckets, **labels)

"""Registry of every ``bst_*`` metric series name.

A typo'd metric string fails silently: the registry happily creates a
fresh zero-valued series, dashboards and BENCH columns read the intended
name, and the counter "works" while reporting nothing. Declaring every
name exactly once here — and lint-enforcing (analysis/checks.py,
``metric-name``) that any ``bst_*`` string literal elsewhere in the
package appears in this table — turns that silent drift into a tier-1
test failure.

Keys are the exposition names; values are one-line help strings (also
usable as Prometheus # HELP text). Names follow prometheus conventions:
``_total`` for counters, unit suffixes (``_bytes``, ``_ms``, ``_seconds``,
``_pct``) for everything else.
"""

from __future__ import annotations

METRICS: dict[str, str] = {
    # chunk IO (io/chunkstore.py), labeled by path taken
    "bst_io_read_bytes_total": "bytes read per (op, implementation path)",
    "bst_io_read_ops_total": "chunk-level read operations per path",
    "bst_io_write_bytes_total": "bytes written per (op, implementation path)",
    "bst_io_write_ops_total": "chunk-level write operations per path",
    # remote object-store traffic (io/chunkstore.py): the subset of the
    # io totals above that crossed the network to an s3/gs root — the
    # remote_read_stall advisor evidence and the warm-leg "zero remote
    # rereads" assertion of scripts/cloud_smoke.sh
    "bst_io_remote_read_bytes_total":
        "bytes decoded from remote (s3/gs) object stores",
    "bst_io_remote_write_bytes_total":
        "bytes uploaded to remote (s3/gs) object stores",
    # async chunk prefetcher (io/prefetch.py)
    "bst_io_prefetch_bytes_total":
        "decoded bytes fetched ahead of the consumer by the prefetch pool",
    "bst_io_prefetch_hit_total":
        "prefetched chunks later consumed from the decoded LRU",
    "bst_io_prefetch_miss_total":
        "prefetched chunks dropped unconsumed (evicted from the tracking "
        "window before any reader wanted them — wasted read-ahead)",
    "bst_io_prefetch_hit_bytes_total":
        "bytes of prefetched chunks later consumed from the decoded LRU",
    # NVMe/local-disk spill tier under the decoded LRU (io/disktier.py)
    "bst_io_disktier_hit_bytes_total":
        "bytes promoted back to the memory LRU from the disk spill tier",
    "bst_io_disktier_spill_bytes_total":
        "bytes the memory LRU spilled to the disk tier on eviction",
    "bst_io_disktier_evict_bytes_total":
        "bytes evicted from the disk tier (budget pressure/invalidation)",
    "bst_io_disktier_bytes": "current disk-tier resident bytes",
    "bst_io_disktier_entries": "current disk-tier entry count",
    # multipart-parallel remote uploads (io/chunkstore.py)
    "bst_io_upload_inflight":
        "remote chunk uploads currently in flight in the upload pool",
    # decoded-chunk LRU cache (io/chunkcache.py)
    "bst_chunk_cache_hits_total": "decoded-chunk cache hits",
    "bst_chunk_cache_misses_total": "decoded-chunk cache misses",
    "bst_chunk_cache_hit_bytes_total": "bytes served from the chunk cache",
    "bst_chunk_cache_miss_bytes_total": "bytes decoded on cache miss",
    "bst_chunk_cache_evictions_total": "chunk-cache LRU evictions",
    "bst_chunk_cache_evict_bytes_total": "bytes evicted from the chunk cache",
    "bst_chunk_cache_invalidations_total":
        "chunk-cache entries dropped by write/remove invalidation",
    "bst_chunk_cache_bytes": "current chunk-cache resident bytes",
    "bst_chunk_cache_entries": "current chunk-cache entry count",
    # host<->device transfers (parallel/mesh.py, models/, ops drivers)
    "bst_xfer_h2d_bytes_total": "host-to-device bytes shipped",
    "bst_xfer_d2h_bytes_total": "device-to-host bytes fetched",
    "bst_xfer_h2d_bytes_saved_total":
        "H2D bytes avoided by native-dtype transport (vs f32 upload)",
    "bst_xfer_d2h_bytes_saved_total":
        "D2H bytes avoided by on-device output conversion",
    # fused multiscale epilogue (models/affine_fusion.py): pyramid-level
    # bytes that rode the fusion drain instead of a container re-read pass
    "bst_epilogue_d2h_bytes_total":
        "pyramid-level bytes fetched device-to-host by the fusion epilogue",
    "bst_epilogue_write_bytes_total":
        "pyramid-level bytes written by the fusion epilogue drain",
    # HBM-resident composite tile cache (models/affine_fusion.py)
    "bst_tile_cache_hits_total": "composite tile cache hits",
    "bst_tile_cache_misses_total": "composite tile cache misses",
    "bst_tile_cache_hit_bytes_total": "tile bytes served device-resident",
    "bst_tile_cache_evict_bytes_total": "tile bytes evicted from HBM",
    # in-flight dispatch window (utils/devicemem.py)
    "bst_inflight_bytes": "bytes currently dispatched but not drained",
    "bst_inflight_bytes_highwater": "high-water mark of in-flight bytes",
    # retry layer (parallel/retry.py)
    "bst_retry_rounds_total": "block retry rounds executed",
    "bst_blocks_failed_total": "blocks that failed (per exception class)",
    # multi-host barriers (parallel/distributed.py)
    "bst_barrier_seconds": "per-name barrier wait time histogram",
    # stage progress (observe/progress.py)
    "bst_stage_items_done_total": "work items completed per stage",
    # pair-parallel scheduler (parallel/pairsched.py)
    "bst_pair_dispatch_total": "pair tasks dispatched per (stage, device)",
    "bst_pair_busy_ms_total": "device busy milliseconds per (stage, device)",
    "bst_pair_redispatch_total":
        "pair tasks re-dispatched after a device failure",
    "bst_pair_device_util_pct": "stage device-utilization percentage",
    "bst_pair_proc_busy_ms_total":
        "per-process pair-scheduler busy milliseconds (stage, process) — "
        "the multihost split-imbalance evidence",
    "bst_pair_proc_util_pct":
        "per-process pair-scheduler device-utilization percentage",
    # timeline flight recorder (observe/trace.py)
    "bst_trace_events_total": "trace events recorded into the ring buffer",
    "bst_trace_events_dropped_total":
        "trace events dropped by ring-buffer overflow (newest events win)",
    # compiled-fn bucket table (parallel/mesh.py + the composite factory
    # call site in models/affine_fusion.py): whether a kernel request hit
    # an already-built bucket (warm, no recompile) or built a new one
    "bst_compiled_fn_warm_hits_total":
        "kernel-bucket requests served by an already-built compiled fn",
    "bst_compiled_fn_cold_builds_total":
        "kernel-bucket requests that built (compiled) a new fn",
    # live HTTP exporter + process self-gauges (observe/httpexport.py):
    # refreshed at every scrape so a dashboard sees the resident process
    # itself, not only its workload
    "bst_process_uptime_seconds": "seconds since this process started",
    "bst_process_rss_bytes": "resident-set size of this process",
    "bst_process_threads": "live thread count of this process",
    "bst_process_open_fds": "open file descriptors of this process",
    "bst_http_requests_total":
        "live-exporter HTTP requests served, labeled by endpoint",
    # manifest history store (observe/history.py)
    "bst_history_records_total":
        "run/job manifests appended to the BST_HISTORY_DIR history store",
    # cross-host telemetry relay (observe/relay.py): non-zero ranks push
    # metric snapshots / heartbeats / warn events to the rank-0 collector
    # through a bounded queue that drops (and counts) under backpressure
    "bst_relay_sent_total":
        "relay messages shipped to the collector by this push client",
    "bst_relay_send_bytes_total":
        "serialized relay bytes shipped to the collector",
    "bst_relay_dropped_total":
        "relay messages dropped instead of blocking the producing rank, "
        "labeled by reason (queue = bounded queue full, conn = collector "
        "unreachable)",
    "bst_relay_reconnects_total":
        "successful relay client reconnects after a lost collector",
    "bst_relay_recv_total":
        "relay messages received by this collector, labeled by type",
    "bst_relay_ranks_connected":
        "push clients currently connected to this relay collector",
    # serve daemon (serve/): queue + lifecycle + per-job cache warmth
    "bst_serve_jobs_submitted_total": "jobs accepted by the serve daemon",
    "bst_serve_jobs_completed_total":
        "jobs finished, labeled by terminal status (ok/error/cancelled)",
    "bst_serve_queue_depth": "jobs currently queued (not yet running)",
    "bst_serve_active_jobs": "jobs currently executing",
    "bst_serve_wait_seconds":
        "queue wait (submit to start) histogram per job",
    "bst_serve_compile_warm_hits_total":
        "per-job warm compiled-fn bucket hits observed by the daemon "
        "(the amortized-compile win of a resident process)",
    "bst_serve_jobs_stalled":
        "RUNNING jobs whose stage.progress has not advanced for "
        "BST_STALL_TIMEOUT_S (the stall watchdog's live gauge)",
    # device-side global solvers (ops/solve.py, models/solver.py,
    # ops/intensity.py): the compiled-relaxation / CG hot path
    "bst_solve_iterations_total":
        "relaxation sweeps (or CG steps) executed inside compiled device "
        "solve loops, labeled by stage where applicable",
    "bst_solve_links_dropped_total":
        "links removed by the iterative drop-worst-link solve",
    "bst_solve_device_ms_total":
        "wall milliseconds spent inside compiled device solve kernels, "
        "labeled by stage (relax / intensity)",
    # streaming stage-DAG executor (dag/): producer->consumer block
    # exchange that replaces intermediate-container round-trips
    "bst_dag_blocks_streamed_total":
        "output blocks published on streamed pipeline edges",
    "bst_dag_bytes_elided_total":
        "streamed-edge bytes consumers read from the in-memory handoff "
        "(decoded-chunk cache) instead of re-reading the container",
    "bst_dag_bytes_reread_total":
        "streamed-edge bytes consumers had to decode from the container "
        "(handoff miss — evicted or never published)",
    "bst_dag_ephemeral_write_bytes_total":
        "bytes written to elided (memory-backed) intermediate containers "
        "that never touch disk",
    "bst_dag_exchange_bytes":
        "published-but-unconsumed bytes in the block-exchange ledger",
    "bst_dag_exchange_blocks":
        "published-but-unconsumed blocks in the block-exchange ledger",
    "bst_dag_producer_stall_seconds_total":
        "seconds producers stalled on block-exchange backpressure",
    "bst_dag_consumer_wait_seconds_total":
        "seconds consumers waited for input blocks not yet produced",
    "bst_dag_handoff_blocks_total":
        "producer chunks published DEVICE-resident into the HBM handoff "
        "cache (skipping even the host decoded-chunk LRU)",
    "bst_dag_handoff_bytes_served_total":
        "streamed-edge bytes consumers read as device arrays straight "
        "from the HBM handoff cache (zero D2H, zero container decode)",
    "bst_dag_handoff_spill_bytes_total":
        "handoff-cache bytes spilled to the host decoded-chunk LRU "
        "(budget pressure, a host-side read, or the end-of-run flush)",
    "bst_dag_handoff_bytes":
        "device bytes currently resident in the HBM handoff cache",
    "bst_dag_stages_completed_total":
        "pipeline stages finished, labeled by terminal status",
    "bst_dag_containers_elided_total":
        "ephemeral intermediate containers elided to memory (never "
        "materialized on disk)",
    # cross-host streamed edges (dag/exchange.py): rank-addressed block
    # exchange that extends streamed-edge gating across process boundaries
    "bst_dag_xhost_fetches_total":
        "remote-owned chunks fetched over the cross-host block exchange",
    "bst_dag_xhost_bytes_total":
        "streamed-edge bytes fetched from peer ranks over TCP (each "
        "remote-owned chunk fetched once into the local decoded LRU)",
    "bst_dag_xhost_served_bytes_total":
        "streamed-edge bytes this rank served to fetching peers",
    "bst_dag_xhost_stall_seconds_total":
        "seconds producers blocked on a peer's bounded exchange queue "
        "(cross-host backpressure)",
    "bst_dag_xhost_peers_connected":
        "exchange peer connections currently established by this rank",
    # telemetry-loop closer (tune/): advisor rules + autotuner trials +
    # daemon-side profile application
    "bst_tune_trials_total":
        "autotuner trial executions, labeled by workload",
    "bst_tune_rules_fired_total":
        "advisor diagnoses emitted, labeled by rule",
    "bst_tune_profiles_applied_total":
        "tuned profiles applied to submitted jobs by the serve daemon",
}

# Every trace/profiling SPAN name, declared exactly once — the same
# silent-drift argument as METRICS above: a typo'd span name would mint a
# fresh timeline series the trace-report and the span aggregates both
# miss. The ``span-name`` lint check (analysis/checks.py) enforces that
# every literal passed to ``profiling.span`` / ``trace.span`` /
# ``trace.instant`` appears here and bans dynamically constructed names;
# dynamic identity (device ordinal, block offset, pair index, bytes)
# belongs in the span's attribution kwargs, never in the name.
SPANS: dict[str, str] = {
    # affine fusion driver (models/affine_fusion.py)
    "fusion.kernel": "fused XLA computation (dispatch + on-device compute)",
    "fusion.prefetch": "host-side source-box prefetch for one view patch",
    "fusion.h2d_tiles": "composite-path tile upload into HBM",
    "fusion.d2h": "device-to-host fetch of fused output (slab or block)",
    "fusion.write": "container write of fused output (slab or block)",
    # fused multiscale epilogue: pyramid levels computed in HBM and shipped
    # in the same drain as the full-res volume (never a second full-res
    # pass — trace-counted by the tier-1 single-drain test)
    "fusion.epilogue.kernel":
        "on-device downsample-pyramid computation (epilogue dispatch)",
    "fusion.epilogue.d2h": "device-to-host fetch of an epilogue pyramid slab",
    "fusion.epilogue.write":
        "container write of an epilogue pyramid slab or block",
    # detection / stitching / matching / nonrigid drivers
    "detection.kernel": "DoG + localization device computation",
    "detection.extract":
        "descriptor-extraction device dispatch of the STAGED two-pass "
        "detect+extract path (absent when the fused program runs)",
    "stitching.extract": "overlap crop extraction for one pair batch",
    "stitching.kernel": "phase-correlation device program",
    "stitching.kernel_sync": "PCM device completion sync",
    "stitching.refine": "host-side Pearson refinement of PCM peaks",
    "nonrigid.kernel": "nonrigid fusion device computation",
    "nonrigid.write": "nonrigid fused block write",
    "nonrigid.prefetch": "nonrigid source patch prefetch",
    "matching.group_pair": "descriptor matching for one view-group pair",
    "matching.pair": "descriptor matching for one view pair",
    # shared mesh work loop (parallel/mesh.py)
    "mesh.d2h": "batched device_get of one sharded batch's outputs",
    # pair-work scheduler (parallel/pairsched.py)
    "pair.dispatch": "one pair task's device dispatch on its worker",
    "pair.drain": "one segment's batched fetch + host post-processing",
    "pair.redispatch": "pair task re-dispatched after a device failure",
    # retry / IO / multihost (parallel/retry.py, io/chunkstore.py,
    # parallel/distributed.py)
    "retry.attempt": "one work item's processing attempt",
    "block.fail": "a work item's attempt raised (instant)",
    "io.read": "chunk-level container read (instant, bytes attributed)",
    "io.write": "chunk-level container write (instant, bytes attributed)",
    "io.prefetch":
        "async read-ahead of one future work item's chunks into the "
        "decoded LRU (prefetch pool worker, bytes attributed)",
    "io.disktier":
        "disk spill-tier file IO (stage=spill/load, bytes attributed)",
    "io.upload":
        "one chunk's remote object-store put in the bounded upload pool",
    "barrier": "cross-host barrier wait (alignment anchor for merge)",
    # serve daemon (serve/daemon.py)
    "serve.job": "one submitted job's full execution on its slot",
    "serve.submit": "a job was accepted into the queue (instant)",
    "serve.cancel": "a cancel request was applied to a job (instant)",
    "serve.shutdown": "the daemon began draining/shutting down (instant)",
    "serve.stall":
        "the watchdog flagged a running job as stalled (instant)",
    "serve.trace_dump":
        "the live flight-recorder ring was snapshotted on demand (instant)",
    # device-side global solvers (models/solver.py, ops/intensity.py)
    "solve.relax":
        "one compiled global-solve kernel invocation (the whole "
        "lax.while_loop relaxation or CG iteration, dispatch to done)",
    "solve.reduce":
        "host fetch of a device solve's final models/errors (the single "
        "drain point of a solve call)",
    "solve.global":
        "a global-mesh solve kernel spanning every process's devices on "
        "the links axis (psum-sharded relax or intensity CG)",
    # multihost pair split (parallel/pairsched.py)
    "pair.allgather":
        "cross-process allgather merging each rank's pair-task results "
        "after a processes-first split",
    # cross-host telemetry relay (observe/relay.py)
    "relay.send":
        "one relay message's serialization + socket send on the client's "
        "relay thread (never the producing hot path)",
    "relay.connect":
        "the relay client (re)connected to its collector (instant)",
    "relay.dump":
        "a cluster-wide flight-recorder pull: request every connected "
        "rank's live ring, fold with the local one into one Perfetto file",
    # streaming stage-DAG executor (dag/executor.py, dag/stream.py)
    "dag.stage": "one pipeline stage's full execution on its thread",
    "dag.wait":
        "a consumer stage blocked for input blocks not yet produced",
    "dag.stall": "a producer stage blocked on block-exchange backpressure",
    "dag.publish": "a producer published an output block (instant)",
    "dag.handoff_publish":
        "a producer published a block device-resident into the HBM "
        "handoff cache (instant)",
    "dag.handoff_read":
        "a consumer's gated read assembled device-resident from the HBM "
        "handoff cache (zero D2H)",
    "dag.handoff_spill":
        "handoff-cache chunks materialized to the host tier (eviction, "
        "host read, or flush)",
    "dag.cleanup": "ephemeral intermediate-container cleanup",
    "dag.xhost_fetch":
        "one remote-owned chunk fetched from a peer rank over TCP",
    "dag.xhost_serve":
        "this rank served one chunk to a fetching peer",
    # telemetry-loop closer (tune/)
    "tune.advise": "one advisor pass over a recorded run's evidence",
    "tune.trial": "one autotuner trial execution under candidate overrides",
}


def declared() -> frozenset[str]:
    return frozenset(METRICS)


def declared_spans() -> frozenset[str]:
    return frozenset(SPANS)

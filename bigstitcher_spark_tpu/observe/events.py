"""Append-only JSONL event log, one file per process.

The Spark reference's event log (spark.eventLog / the history server)
re-expressed for the multi-host SPMD runtime: every process appends to its
own ``events-{process_index:05d}-of-{process_count:05d}.jsonl`` inside the
run's telemetry directory, so pod runs never collide on a shared
filesystem and ``bst telemetry-merge`` can fold the N files afterwards.

Disabled (the default) the hot-path cost is one ``is None`` check per
``emit`` call; enabled, each event is one buffered+flushed JSON line.
"""

from __future__ import annotations

import json
import os
import threading
import time

_lock = threading.RLock()
_dir: str | None = None
_file = None
_path: str | None = None


def world() -> tuple[int, int]:
    """(process_index, process_count), preferring the live jax runtime and
    falling back to the BST_* launch env (so filenames are stable even
    before/without backend init)."""
    try:
        from ..parallel.distributed import world as _w

        return _w()
    except Exception:
        from .. import config

        return (config.get_int("BST_PROCESS_ID") or 0,
                config.get_int("BST_NUM_PROCESSES") or 1)


def event_log_name(process_index: int, process_count: int) -> str:
    return f"events-{process_index:05d}-of-{process_count:05d}.jsonl"


def configure(directory: str) -> None:
    """Route subsequent ``emit`` calls to ``directory`` (file opened lazily
    on first event, in append mode — reruns extend, never truncate)."""
    global _dir, _file, _path
    with _lock:
        if _file is not None:
            _file.close()
        _dir, _file, _path = os.path.abspath(directory), None, None
        os.makedirs(_dir, exist_ok=True)


def enabled() -> bool:
    return _dir is not None


def path() -> str | None:
    return _path


def _json_safe(o):
    if hasattr(o, "dtype") and getattr(o, "ndim", 1) == 0:
        if o.dtype.kind in "ui":
            return int(o)
        if o.dtype.kind == "f":
            return float(o)
        if o.dtype.kind == "b":
            return bool(o)
    if hasattr(o, "tolist"):
        return o.tolist()
    return str(o)


def emit(etype: str, **fields) -> None:
    """Append one event; no-op unless configured. ``None`` fields drop."""
    if _dir is None:
        return
    with _lock:
        if _dir is None:
            return
        global _file, _path
        if _file is None:
            pi, pc = world()
            _path = os.path.join(_dir, event_log_name(pi, pc))
            _file = open(_path, "a", encoding="utf-8")
        rec = {"ts": round(time.time(), 6), "type": etype}
        rec.update({k: v for k, v in fields.items() if v is not None})
        _file.write(json.dumps(rec, default=_json_safe) + "\n")
        _file.flush()


def close() -> str | None:
    """Close the log and de-configure; returns the written path (if any)."""
    global _dir, _file, _path
    with _lock:
        p = _path
        if _file is not None:
            _file.close()
        _dir, _file, _path = None, None, None
        return p


def iter_events(path: str):
    """Parse a JSONL event file back into dicts (round-trip reader used by
    tests and the merge tool). Unparseable lines are skipped, not fatal:
    a crash can tear a line mid-write, and append-mode reruns then bury
    the torn line mid-file — later events must still be readable."""
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except ValueError:
                continue

"""Append-only JSONL event log: one file per process, plus per-JOB files.

The Spark reference's event log (spark.eventLog / the history server)
re-expressed for the multi-host SPMD runtime: every process appends to its
own ``events-{process_index:05d}-of-{process_count:05d}.jsonl`` inside the
run's telemetry directory, so pod runs never collide on a shared
filesystem and ``bst telemetry-merge`` can fold the N files afterwards.

A long-lived ``bst serve`` daemon breaks the one-process-one-run
assumption: two jobs in one process would interleave into one file named
by process_index, and their manifests could no longer be separated. So
emission is now SCOPED: a job opens its own sink (its own directory +
``events-job-{label}-...jsonl`` file) and activates it on a context
variable; ``emit`` routes to the active job's sink, falling back to the
process-wide default sink (the classic ``--telemetry-dir`` behavior)
outside any job scope. Worker threads inherit the scope through
:mod:`utils.threads`. Sinks also carry subscriber callbacks — the serve
daemon's live heartbeat stream to ``bst submit`` clients.

Disabled (the default) the hot-path cost is one sink-resolution check per
``emit`` call; enabled, each event is one buffered+flushed JSON line.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time

_lock = threading.RLock()


def world() -> tuple[int, int]:
    """(process_index, process_count), preferring the live jax runtime and
    falling back to the BST_* launch env (so filenames are stable even
    before/without backend init)."""
    try:
        from ..parallel.distributed import world as _w

        return _w()
    except Exception:
        from .. import config

        return (config.get_int("BST_PROCESS_ID") or 0,
                config.get_int("BST_NUM_PROCESSES") or 1)


def event_log_name(process_index: int, process_count: int) -> str:
    return f"events-{process_index:05d}-of-{process_count:05d}.jsonl"


def job_event_log_name(job: str, process_index: int,
                       process_count: int) -> str:
    """Per-job log name: the job label keeps two daemon jobs out of each
    other's files, the process pair keeps pod runs collision-free."""
    return (f"events-job-{job}-"
            f"{process_index:05d}-of-{process_count:05d}.jsonl")


class _Sink:
    """One JSONL output (lazily opened, append mode) + its subscribers."""

    def __init__(self, directory: str, job: str | None = None):
        self.dir = os.path.abspath(directory)
        self.job = job
        self.path: str | None = None
        self._file = None
        self.subscribers: list = []
        os.makedirs(self.dir, exist_ok=True)

    def write_locked(self, rec: dict) -> None:
        if self._file is None:
            pi, pc = world()
            name = (event_log_name(pi, pc) if self.job is None
                    else job_event_log_name(self.job, pi, pc))
            self.path = os.path.join(self.dir, name)
            self._file = open(self.path, "a", encoding="utf-8")
        self._file.write(json.dumps(rec, default=_json_safe) + "\n")
        self._file.flush()

    def close_locked(self) -> str | None:
        if self._file is not None:
            self._file.close()
            self._file = None
        return self.path


_default: _Sink | None = None
_jobs: dict[str, _Sink] = {}

# global taps see EVERY emitted event regardless of sink state (the
# telemetry relay forwards a warn/error subset to the pod collector even
# when no --telemetry-dir is configured). Empty by default: the
# no-telemetry hot path pays one extra truthiness check.
_taps: list = []


def add_tap(cb) -> None:
    if cb not in _taps:
        _taps.append(cb)


def remove_tap(cb) -> None:
    if cb in _taps:
        _taps.remove(cb)


_current: contextvars.ContextVar[str | None] = \
    contextvars.ContextVar("bst-event-job", default=None)


def _sink() -> _Sink | None:
    """The sink the current context emits to: the active job's (when one
    is open), else the process default."""
    label = _current.get()
    if label is not None:
        s = _jobs.get(label)
        if s is not None:
            return s
    return _default


def configure(directory: str) -> None:
    """Route subsequent default-scope ``emit`` calls to ``directory``
    (file opened lazily on first event, in append mode — reruns extend,
    never truncate)."""
    global _default
    with _lock:
        if _default is not None:
            _default.close_locked()
        _default = _Sink(directory)


def enabled() -> bool:
    return _sink() is not None


def path() -> str | None:
    s = _sink()
    return s.path if s is not None else None


def _json_safe(o):
    if hasattr(o, "dtype") and getattr(o, "ndim", 1) == 0:
        if o.dtype.kind in "ui":
            return int(o)
        if o.dtype.kind == "f":
            return float(o)
        if o.dtype.kind == "b":
            return bool(o)
    if hasattr(o, "tolist"):
        return o.tolist()
    return str(o)


def emit(etype: str, **fields) -> None:
    """Append one event to the current scope's sink; no-op unless one is
    configured or a global tap (the telemetry relay) is listening.
    ``None`` fields drop. Subscribers run OUTSIDE the module lock (a slow
    consumer — e.g. a serve client socket — must not stall every other
    emitter)."""
    s = _sink()
    if s is None and not _taps:
        return
    rec = {"ts": round(time.time(), 6), "type": etype}
    rec.update({k: v for k, v in fields.items() if v is not None})
    if s is not None:
        with _lock:
            if s is not _sink():   # scope closed while we raced here
                s = None
            else:
                s.write_locked(rec)
                subs = list(s.subscribers)
        if s is not None:
            for cb in subs:
                try:
                    cb(rec)
                except Exception:
                    with _lock:
                        if cb in s.subscribers:
                            s.subscribers.remove(cb)
    for tap in list(_taps):
        try:
            tap(rec)
        except Exception:
            pass   # a broken tap must never cost the emitting run


def close() -> str | None:
    """Close the DEFAULT log and de-configure it; returns the written path
    (if any). Job sinks close via :func:`close_job`."""
    global _default
    with _lock:
        if _default is None:
            return None
        p = _default.close_locked()
        _default = None
        return p


# -- job scopes (the serve daemon's per-job telemetry) ----------------------

def open_job(label: str, directory: str) -> None:
    """Register a per-job sink writing into ``directory``. The scope only
    routes events once :func:`activate_job` sets it on the context."""
    with _lock:
        old = _jobs.get(label)
        if old is not None:
            old.close_locked()
        _jobs[label] = _Sink(directory, job=label)


def close_job(label: str) -> str | None:
    """Close and drop a job sink; returns its log path (if it wrote)."""
    with _lock:
        s = _jobs.pop(label, None)
        return s.close_locked() if s is not None else None


def activate_job(label: str):
    """Make ``label`` the emitting scope for this context; returns a token
    for :func:`deactivate_job`."""
    return _current.set(label)


def deactivate_job(token) -> None:
    _current.reset(token)


def current_job() -> str | None:
    """The job label this context emits under, or None (default scope)."""
    return _current.get()


def subscribe(label: str, cb) -> bool:
    """Attach ``cb(record)`` to a job sink's event stream (called after
    each write, outside the log lock). False when no such sink is open."""
    with _lock:
        s = _jobs.get(label)
        if s is None:
            return False
        s.subscribers.append(cb)
        return True


def unsubscribe(label: str, cb) -> None:
    with _lock:
        s = _jobs.get(label)
        if s is not None and cb in s.subscribers:
            s.subscribers.remove(cb)


def iter_events(path: str):
    """Parse a JSONL event file back into dicts (round-trip reader used by
    tests and the merge tool). Unparseable lines are skipped, not fatal:
    a crash can tear a line mid-write, and append-mode reruns then bury
    the torn line mid-file — later events must still be readable."""
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except ValueError:
                continue

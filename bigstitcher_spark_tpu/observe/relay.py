"""Cross-host telemetry relay: rank-N push clients, a rank-0 collector.

Every live-observability surface so far — ``/metrics``, ``/healthz``,
``bst top``, ``bst trace-dump`` — is strictly host-local: the exporter
binds one host, the event/trace files are per-process and only fold
post-hoc through ``bst telemetry-merge``. A pod run (or a future
multi-host daemon) is therefore blind *while it runs*: no live view of a
remote rank, no pod health verdict, no way to tell which host stalls.
The Spark reference leans on the driver UI for exactly this cluster-wide
live view; in a driverless SPMD world this module builds the fan-in:

- **push client** (:class:`RelayClient`): every non-collector process
  with ``BST_TELEMETRY_RELAY`` set ships periodic metric-registry
  snapshots (the rendered Prometheus text), health heartbeats (process
  stats, stage progress, cache/in-flight gauges, trace state) and a
  warn/error event subset to the collector over one TCP connection. All
  traffic flows through a BOUNDED queue drained by a dedicated relay
  thread: a slow or absent collector fills the queue and further
  messages drop (counted in ``bst_relay_dropped_total``) — the
  producing rank's hot path never blocks on telemetry. The client
  reconnects with backoff after a collector restart.
- **collector** (:class:`RelayCollector`): rank 0 (or any ``bst serve``
  daemon) binds the ``BST_TELEMETRY_RELAY`` address and merges the
  per-rank state into the existing live plane via
  :mod:`observe.httpexport`'s cluster providers: ``/metrics`` gains a
  ``host``/``process_index``-labeled copy of every rank's series (its
  own included), ``/healthz`` becomes a pod verdict (a rank whose
  heartbeat goes silent past ``BST_STALL_TIMEOUT_S`` → 503 naming the
  host, recovering when heartbeats resume), and ``/cluster`` serves the
  per-rank JSON rows behind ``bst top --cluster``. The collector can
  also pull a live flight-recorder snapshot from every connected rank
  (:meth:`RelayCollector.cluster_trace_dump`) and fold them — plus its
  own ring — through the barrier-anchored ``merge_traces`` into ONE
  Perfetto file mid-run (``bst trace-dump --cluster``).

Role resolution (:func:`ensure_started`): with the knob unset the relay
is fully off — zero overhead, byte-identical telemetry. With it set,
process 0 of a multi-process world tries to HOST the address and falls
back to pushing when the bind fails (someone on this host — typically a
``bst serve`` daemon, which always hosts — already owns it); every
other process pushes. The wire is line-delimited JSON over a plain TCP
socket with NO auth — same trust assumption as ``BST_METRICS_HOST``:
pod-internal networks only (README "Live monitoring").
"""

from __future__ import annotations

import contextlib
import json
import os
import queue as _queuemod
import shutil
import socket
import tempfile
import threading
import time

from . import metrics as _metrics
from . import trace as _trace
from .. import config

SCHEMA = "bst-relay/1"

# event types a push client forwards to the collector (the warn/error
# surface an operator watches a pod for; stage.progress deliberately
# rides the periodic snapshot instead — per-block spam would drown the
# bounded queue)
FORWARDED_EVENTS = frozenset({
    "block.fail", "retry.round", "job.stall", "job.resume",
    "run.start", "run.end", "stage.end", "barrier",
})

# events kept per rank on the collector for /cluster display
_RANK_EVENT_KEEP = 25

_SENT = _metrics.counter("bst_relay_sent_total")
_SENT_BYTES = _metrics.counter("bst_relay_send_bytes_total")
_DROP_QUEUE = _metrics.counter("bst_relay_dropped_total", reason="queue")
_DROP_CONN = _metrics.counter("bst_relay_dropped_total", reason="conn")
_RECONNECTS = _metrics.counter("bst_relay_reconnects_total")
_RANKS_CONNECTED = _metrics.gauge("bst_relay_ranks_connected")


def parse_address(addr: str) -> tuple[str, int]:
    """``host:port`` -> (host, port); the host part may be empty
    (collector: bind all interfaces)."""
    host, sep, port = addr.rpartition(":")
    if not sep:
        raise ValueError(f"BST_TELEMETRY_RELAY wants host:port, got "
                         f"{addr!r}")
    return host, int(port)


def _set_keepalive(sock: socket.socket) -> None:
    """Both relay roles hold long-lived mostly-idle connections whose
    readers treat silence as normal, so a HALF-OPEN peer (host
    power-cut, no FIN/RST) would otherwise look alive indefinitely —
    the client until its send buffer fills, the collector until TCP
    retransmission gives up (~15 min), leaving a phantom connected rank
    that stalls every cluster dump for its full timeout. Keepalive
    probes surface dead peers to the blocked recv in ~25s. Each option
    is guarded on its own — TCP_KEEPALIVE is the Darwin spelling of the
    idle time, and a sandbox denying one setsockopt must neither kill
    the relay thread nor abandon the remaining tuning."""
    with contextlib.suppress(OSError):
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
    for opt, val in (("TCP_KEEPIDLE", 10), ("TCP_KEEPALIVE", 10),
                     ("TCP_KEEPINTVL", 5), ("TCP_KEEPCNT", 3)):
        o = getattr(socket, opt, None)
        if o is not None:
            with contextlib.suppress(OSError):
                sock.setsockopt(socket.IPPROTO_TCP, o, val)


def _shutdown_close(sock: socket.socket) -> None:
    """shutdown(SHUT_RDWR) then close: a handler thread's makefile
    holds an io-ref, so close() alone defers the real close and leaves
    the connection (and the remote client) fully alive."""
    with contextlib.suppress(OSError):
        sock.shutdown(socket.SHUT_RDWR)
    with contextlib.suppress(OSError):
        sock.close()


def _identity() -> tuple[str, int, int]:
    """(host, process_index, process_count) of THIS process. The
    explicit BST_PROCESS_ID / BST_NUM_PROCESSES launch env wins over the
    live jax world: two independently-launched local workers (no shared
    jax.distributed runtime) would otherwise both claim rank (0, 1) and
    collapse into one collector row."""
    pi = config.get_int("BST_PROCESS_ID")
    pc = config.get_int("BST_NUM_PROCESSES")
    if pi is None or pc is None:
        from . import events as _events

        jpi, jpc = _events.world()
        pi = jpi if pi is None else pi
        pc = jpc if pc is None else pc
    return socket.gethostname(), int(pi), int(pc)


# -- push client -------------------------------------------------------------


class RelayClient:
    """One process's push side: a bounded queue drained by a relay
    thread that owns the TCP connection. ``offer`` (and the event tap
    feeding it) never block — backpressure drops and counts."""

    def __init__(self, address: str, *, host: str | None = None,
                 process_index: int | None = None,
                 process_count: int | None = None,
                 interval_s: float | None = None,
                 queue_max: int | None = None):
        self.address = parse_address(address)
        h, pi, pc = _identity()
        self.host = host if host is not None else h
        self.process_index = (process_index if process_index is not None
                              else pi)
        self.process_count = (process_count if process_count is not None
                              else pc)
        self._interval_arg = interval_s
        self._q: _queuemod.Queue = _queuemod.Queue(
            maxsize=max(8, queue_max
                        if queue_max is not None
                        else config.get_int("BST_RELAY_QUEUE") or 256))
        self._stop = threading.Event()
        self._sock: socket.socket | None = None
        self._sock_lock = threading.Lock()
        self._next_connect = 0.0
        self._backoff = 1.0
        self._connects = 0
        self._thread: threading.Thread | None = None
        self._own_trace = False
        self.connected = threading.Event()   # test/diagnostic surface

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "RelayClient":
        from . import events as _events

        # a relayed rank records its flight recorder always (bounded
        # ring, newest wins) so a cluster trace-dump has something to
        # pull without anyone having passed --trace before the incident
        if not _trace.enabled():
            _trace.configure()
            self._own_trace = True
        from . import progress as _progress

        _progress.set_live_tracking(True)
        _events.add_tap(self._tap)
        # raw daemon thread on purpose: the relay sender is process-lived
        # telemetry infrastructure serving every job — it must not pin
        # the starting job's cancel scope or config overrides
        self._thread = threading.Thread(target=self._run,  # bst-lint: off=thread-spawn
                                        name="bst-relay-client",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        if self._stop.is_set():
            return   # idempotent (atexit + explicit stop)
        from . import events as _events
        from . import progress as _progress

        _events.remove_tap(self._tap)
        _progress.set_live_tracking(False)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        self._close_sock()
        if self._own_trace and _trace.enabled():
            _trace.reset()

    def _interval(self) -> float:
        if self._interval_arg is not None:
            return float(self._interval_arg)
        return float(config.get_float("BST_RELAY_INTERVAL_S") or 2.0)

    # -- producer side (never blocks) ---------------------------------------

    def offer(self, msg: dict) -> bool:
        """Enqueue one message for the relay thread; full queue drops
        and counts instead of blocking the caller."""
        try:
            self._q.put_nowait(msg)
            return True
        except _queuemod.Full:
            _DROP_QUEUE.inc()
            return False

    def _tap(self, rec: dict) -> None:
        if rec.get("type") in FORWARDED_EVENTS:
            self.offer({"t": "event", "rec": rec})

    # -- relay thread --------------------------------------------------------

    def _run(self) -> None:
        next_snap = 0.0
        while not self._stop.is_set():
            now = time.monotonic()
            # clamp so lowering BST_RELAY_INTERVAL_S live takes effect
            # immediately instead of after one old-length sleep
            next_snap = min(next_snap, now + max(0.2, self._interval()))
            if now >= next_snap:
                self.offer({"t": "snap", "payload": self._snapshot()})
                next_snap = now + max(0.2, self._interval())
            try:
                msg = self._q.get(timeout=max(
                    0.05, min(0.5, next_snap - time.monotonic())))
            except _queuemod.Empty:
                continue
            self._deliver(msg)
        # drain what is already queued, then say goodbye so the
        # collector can tell a finished rank from a dead one
        while True:
            try:
                self._deliver(self._q.get_nowait())
            except _queuemod.Empty:
                break
        self._deliver({"t": "bye"})

    def _snapshot(self) -> dict:
        from . import httpexport as _httpexport
        from . import progress as _progress

        payload: dict = {
            "ts": round(time.time(), 3),
            "process": _httpexport.process_stats(),
            "progress": _progress.latest(),
            "trace": _trace.stats(),
            "dropped": {"queue": int(_DROP_QUEUE.value),
                        "conn": int(_DROP_CONN.value)},
            "inflight": {
                "bytes": _metrics.gauge("bst_inflight_bytes").value,
                "highwater_bytes": _metrics.gauge(
                    "bst_inflight_bytes_highwater").value,
            },
            "prom": _metrics.get_registry().render_prometheus(),
        }
        try:
            from ..io.chunkcache import get_cache

            payload["chunk_cache"] = get_cache().stats()
        except Exception:   # cache layer optional for bare clients
            pass
        try:
            from ..parallel.pairsched import process_util_snapshot

            util = process_util_snapshot()
            if util:
                payload["pair_util"] = util
        except Exception:   # scheduler layer optional for bare clients
            pass
        return payload

    def _deliver(self, msg: dict) -> None:
        if not self._ensure_conn():
            _DROP_CONN.inc()
            return
        data = (json.dumps(msg, default=str) + "\n").encode()
        with _trace.span("relay.send", nbytes=len(data)):
            # read the ref under the lock, send OUTSIDE it: a send that
            # rides its 10s timeout must not stall _close_sock and the
            # reconnect path behind it. A connection swapped mid-send
            # errors out and _close_sock(expected) ignores the stale ref.
            with self._sock_lock:
                sock = self._sock
            if sock is None:
                _DROP_CONN.inc()
                return
            try:
                sock.sendall(data)
            except OSError:
                self._close_sock(sock)
                _DROP_CONN.inc()
                return
        _SENT.inc()
        _SENT_BYTES.inc(len(data))

    def _ensure_conn(self) -> bool:
        if self._sock is not None:
            return True
        now = time.monotonic()
        if now < self._next_connect:
            return False
        try:
            sock = socket.create_connection(self.address, timeout=5.0)
        except OSError:
            self._next_connect = now + self._backoff
            self._backoff = min(self._backoff * 2, 5.0)
            return False
        # sends must eventually error on a dead-but-open collector so
        # the client falls back to dropping instead of wedging forever
        sock.settimeout(10.0)
        _set_keepalive(sock)
        hello = (json.dumps({
            "t": "hello", "schema": SCHEMA, "host": self.host,
            "process_index": self.process_index,
            "process_count": self.process_count, "pid": os.getpid(),
        }) + "\n").encode()
        try:
            sock.sendall(hello)
        except OSError:
            _shutdown_close(sock)
            self._next_connect = now + self._backoff
            return False
        with self._sock_lock:
            self._sock = sock
        self._backoff = 1.0
        self._connects += 1
        if self._connects > 1:
            _RECONNECTS.inc()
        _trace.instant("relay.connect", item=f"{self.address[0]}:"
                                             f"{self.address[1]}")
        self.connected.set()
        # raw daemon thread on purpose: connection-lived reader, same
        # no-job-context rationale as the sender thread
        threading.Thread(target=self._reader, args=(sock,),  # bst-lint: off=thread-spawn
                         name="bst-relay-reader", daemon=True).start()
        return True

    def _close_sock(self, expected: socket.socket | None = None) -> None:
        """Drop the current connection; with ``expected`` given, only if
        it is still the current one — a check-then-close outside the
        lock could otherwise tear down a connection a concurrent
        reconnect just established (one spurious reconnect cycle: the
        very flap the idle-tolerant reader exists to prevent)."""
        with self._sock_lock:
            sock = self._sock
            if expected is not None and sock is not expected:
                return
            self._sock = None
        self.connected.clear()
        if sock is not None:
            _shutdown_close(sock)   # also wakes a reader blocked in recv

    def _reader(self, sock: socket.socket) -> None:
        """Collector->client requests (cluster trace pulls) arrive on
        the same connection; responses go back through the bounded
        queue so the relay thread stays the only socket writer. The
        socket timeout exists for the WRITER (a wedged sendall must
        eventually error) — the collector is silent except for trace
        pulls, so a read timing out just means idle: keep listening.
        Only EOF or a real socket error tears the connection down."""
        buf = b""
        try:
            # deliberately NOT gated on _stop: stop() drains the queue
            # and sends the goodbye AFTER setting it — a reader that
            # exited on the flag mid-drain would close the socket under
            # that final sendall. stop()'s own _close_sock (after the
            # relay thread joins) wakes the blocked recv to exit.
            while sock is self._sock:
                try:
                    chunk = sock.recv(65536)
                except TimeoutError:
                    continue   # idle connection — normal, not a failure
                if not chunk:
                    break   # EOF: the collector closed on us
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    try:
                        msg = json.loads(line)
                    except ValueError:
                        continue
                    if not isinstance(msg, dict):
                        continue
                    if msg.get("t") == "trace-dump":
                        self.offer({"t": "trace", "req": msg.get("req"),
                                    "doc": self._trace_doc()})
        except OSError:
            pass
        finally:
            self._close_sock(sock)

    def _trace_doc(self) -> dict | None:
        if not _trace.enabled():
            return None
        return _trace.export(self.process_index, self.process_count)


# -- collector ---------------------------------------------------------------


def _merge_expositions(texts: list) -> str:
    """Merge ``(host, process_index, prometheus_text)`` expositions into
    ONE valid exposition: every metric family appears exactly once, as a
    contiguous group under a single ``# TYPE`` comment holding the
    series of every source — duplicate or split families are invalid
    per the Prometheus text-format spec (promtool/OpenMetrics reject
    them even though the scraper tolerates them). ``host=None`` marks
    the local render (series pass through unlabeled); every other
    source gets ``host``/``process_index`` injected into each series."""
    fams: dict[str, dict] = {}   # insertion-ordered: first sight wins

    def fam(name: str) -> dict:
        f = fams.get(name)
        if f is None:
            f = fams[name] = {"type": None, "lines": []}
        return f

    for host, pi, text in texts:
        inject = (None if host is None
                  else f'host="{host}",process_index="{pi}"')
        for line in text.splitlines():
            if not line:
                continue
            if line.startswith("# TYPE "):
                parts = line.split()
                if len(parts) >= 4 and fam(parts[2])["type"] is None:
                    fams[parts[2]]["type"] = parts[3]
                continue
            if line.startswith("#"):
                continue
            name_part, _, value = line.rpartition(" ")
            if not name_part:
                continue
            if "{" in name_part:
                name, rest = name_part.split("{", 1)
                series = (line if inject is None
                          else f"{name}{{{inject},{rest} {value}")
            else:
                name = name_part
                series = (line if inject is None
                          else f"{name}{{{inject}}} {value}")
            # histogram sample suffixes group under the parent family
            # (whose TYPE line precedes its series in every render)
            base = name
            for suf in ("_bucket", "_sum", "_count"):
                if name.endswith(suf):
                    parent = fams.get(name[:-len(suf)])
                    if parent is not None and parent["type"] in (
                            "histogram", "summary"):
                        base = name[:-len(suf)]
                    break
            fam(base)["lines"].append(series)
    out: list[str] = []
    for name, f in fams.items():
        if not f["lines"]:
            continue
        if f["type"] is not None:
            out.append(f"# TYPE {name} {f['type']}")
        out.extend(f["lines"])
    return "\n".join(out) + "\n"


class RelayCollector:
    """The fan-in side: accepts push clients, keeps per-rank state, and
    plugs the aggregate into the live HTTP plane (cluster providers)."""

    def __init__(self, host: str, port: int):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, int(port)))
        srv.listen(32)
        srv.settimeout(1.0)
        self._srv = srv
        self.host = host or "0.0.0.0"
        self.port = srv.getsockname()[1]
        self.started_at = time.time()
        self._lock = threading.Lock()
        self._ranks: dict[tuple, dict] = {}
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._recv = {t: _metrics.counter("bst_relay_recv_total", type=t)
                      for t in ("hello", "snap", "event", "trace", "bye")}
        self._dump_lock = threading.Lock()
        self._dump_seq = 0
        self._dumps: dict[int, dict] = {}

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "RelayCollector":
        from . import httpexport as _httpexport

        # raw daemon thread on purpose: the collector is a standalone
        # process-lived service, no job context exists to carry
        th = threading.Thread(target=self._accept_loop,  # bst-lint: off=thread-spawn
                              name="bst-relay-accept", daemon=True)
        th.start()
        self._threads.append(th)
        _httpexport.set_cluster_providers(health=self.pod_health,
                                          cluster=self.cluster_status,
                                          metrics_render=self.metrics_render)
        return self

    def stop(self) -> None:
        from . import httpexport as _httpexport

        _httpexport.clear_cluster_providers()
        self._stop.set()
        with contextlib.suppress(OSError):
            self._srv.close()
        with self._lock:
            conns = [r.get("conn") for r in self._ranks.values()]
        for c in conns:
            if c is not None:
                _shutdown_close(c)
        for th in self._threads:
            if th is not threading.current_thread():
                th.join(timeout=5)
        _RANKS_CONNECTED.set(0)

    # -- accept / per-connection readers ------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            # accepted sockets don't inherit the listener's options and
            # the handler blocks in a plain read — without keepalive a
            # no-FIN dead worker stays a phantom connected rank
            _set_keepalive(conn)
            # raw daemon thread on purpose: per-rank collector handler,
            # no job context exists in the collector process
            th = threading.Thread(target=self._handle, args=(conn,),  # bst-lint: off=thread-spawn
                                  name="bst-relay-conn", daemon=True)
            th.start()
            # prune finished handlers so a long-lived daemon with flaky
            # reconnecting clients never accumulates dead Thread objects
            self._threads = [t for t in self._threads
                             if t.is_alive()] + [th]

    def _update_connected_gauge(self) -> None:
        with self._lock:
            n = sum(1 for r in self._ranks.values() if r["connected"])
        _RANKS_CONNECTED.set(n)

    def _handle(self, conn: socket.socket) -> None:
        rank: dict | None = None
        wlock = threading.Lock()
        try:
            f = conn.makefile("rb")
            for line in f:
                try:
                    msg = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(msg, dict):
                    continue   # valid JSON, wrong shape: a stray peer
                t = msg.get("t")
                c = self._recv.get(t)
                if c is not None:
                    c.inc()
                if t == "hello":
                    rank = self._register(msg, conn, wlock)
                elif rank is None:
                    continue
                elif t == "snap":
                    with self._lock:
                        rank["last_seen"] = time.time()
                        rank["snap_at"] = rank["last_seen"]
                        rank["snap"] = msg.get("payload") or {}
                        rank["done"] = False
                elif t == "event":
                    with self._lock:
                        rank["last_seen"] = time.time()
                        rank["events"].append(msg.get("rec") or {})
                        del rank["events"][:-_RANK_EVENT_KEEP]
                elif t == "trace":
                    self._dump_response(msg)
                elif t == "bye":
                    with self._lock:
                        rank["done"] = True
                    break
        except OSError:
            pass
        finally:
            if rank is not None:
                with self._lock:
                    if rank.get("conn") is conn:
                        rank["connected"] = False
                        rank["conn"] = None
                self._update_connected_gauge()
            _shutdown_close(conn)

    def _register(self, msg: dict, conn, wlock) -> dict:
        key = (str(msg.get("host")), int(msg.get("process_index") or 0),
               int(msg.get("process_count") or 1))
        with self._lock:
            rank = self._ranks.get(key)
            if rank is None:
                rank = {"host": key[0], "process_index": key[1],
                        "process_count": key[2], "events": []}
                self._ranks[key] = rank
            old = rank.get("conn")
            rank.update(conn=conn, wlock=wlock, pid=msg.get("pid"),
                        connected=True, done=False,
                        last_seen=time.time())
        if old is not None and old is not conn:
            _shutdown_close(old)   # wake its handler too
        self._update_connected_gauge()
        return rank

    # -- aggregate views ------------------------------------------------------

    def _rows(self) -> list[dict]:
        now = time.time()
        timeout_s = config.get_int("BST_STALL_TIMEOUT_S") or 0
        with self._lock:
            ranks = [dict(r) for r in self._ranks.values()]
        rows = []
        for r in sorted(ranks, key=lambda r: (r["host"],
                                              r["process_index"])):
            age = round(now - r["last_seen"], 1)
            snap = r.get("snap") or {}
            rows.append({
                "host": r["host"],
                "process_index": r["process_index"],
                "process_count": r["process_count"],
                "pid": r.get("pid"),
                "connected": r["connected"],
                "done": r.get("done", False),
                "age_s": age,
                # the pod verdict: silent past the stall timeout, and
                # neither finished nor merely between reconnects with a
                # fresh heartbeat
                "stalled": (timeout_s > 0 and not r.get("done")
                            and age > timeout_s),
                "progress": snap.get("progress"),
                "process": snap.get("process"),
                "chunk_cache": snap.get("chunk_cache"),
                "pair_util": snap.get("pair_util"),
                "inflight": snap.get("inflight"),
                "trace": snap.get("trace"),
                "dropped": snap.get("dropped"),
                "events": [e.get("type") for e in r.get("events", [])][-5:],
            })
        return rows

    def cluster_status(self) -> dict:
        rows = self._rows()
        return {
            "collector": {
                "address": f"{self.host}:{self.port}",
                "uptime_s": round(time.time() - self.started_at, 1),
                "stall_timeout_s": config.get_int("BST_STALL_TIMEOUT_S")
                or 0,
                "ranks": len(rows),
                "connected": sum(1 for r in rows if r["connected"]),
            },
            "ranks": rows,
        }

    def pod_health(self, ok: bool, payload: dict) -> tuple[bool, dict]:
        """Merge the pod verdict into a local /healthz result: any rank
        silent past BST_STALL_TIMEOUT_S makes the pod unhealthy, naming
        the host; a finished (bye) rank never does."""
        rows = self._rows()
        silent = [{"host": r["host"],
                   "process_index": r["process_index"],
                   "age_s": r["age_s"]}
                  for r in rows if r["stalled"]]
        payload = dict(payload)
        payload["cluster"] = {
            "ranks": len(rows),
            "connected": sum(1 for r in rows if r["connected"]),
            "silent_ranks": silent,
        }
        if silent:
            ok = False
        payload["ok"] = ok
        return ok, payload

    def metrics_render(self, local_text: str) -> str:
        """The collector's /metrics body: the local registry render
        merged with a host/process_index-labeled copy of every rank's
        series — the collector's own included (unless a connected rank
        already claims its identity). Families merge contiguously under
        one TYPE comment each, keeping the exposition valid (see
        :func:`_merge_expositions`); ranks colliding on (host,
        process_index) — independently-launched workers with mismatched
        process_count claims occupy distinct _ranks keys — dedupe to
        the freshest SNAPSHOT (snap_at, not last_seen: heartbeats and
        events also touch last_seen and must not let a stale snapshot
        win), since duplicate identical-label samples are as invalid as
        split families."""
        with self._lock:
            newest: dict = {}
            for r in self._ranks.values():
                prom = (r.get("snap") or {}).get("prom")
                if not prom:
                    continue
                k = (r["host"], r["process_index"])
                snap_at = r.get("snap_at", 0)
                if k not in newest or snap_at > newest[k][0]:
                    newest[k] = (snap_at, prom)
        host, pi, _pc = _identity()
        texts: list = [(None, 0, local_text)]
        if (host, pi) not in newest:
            texts.append((host, pi, local_text))
        texts += [(h, p, prom)
                  for (h, p), (_seen, prom) in sorted(newest.items())]
        return ("# relay-aggregated cluster render (one labeled copy "
                "per rank, families merged)\n"
                + _merge_expositions(texts))

    # -- cluster flight-recorder pull ----------------------------------------

    def _dump_response(self, msg: dict) -> None:
        req = msg.get("req")
        with self._dump_lock:
            pend = self._dumps.get(req)
            if pend is None:
                return
            pend["results"].append(msg.get("doc"))
            if len(pend["results"]) >= pend["want"]:
                pend["event"].set()

    def cluster_trace_dump(self, out: str,
                           timeout_s: float = 15.0) -> dict:
        """Pull the live flight-recorder ring of every connected rank,
        fold them (plus the local ring) through the barrier-anchored
        ``merge_traces`` into ONE Perfetto file at ``out`` — mid-run,
        nothing pauses. Ranks that fail to answer within ``timeout_s``
        are reported missing, never fatal."""
        with _trace.span("relay.dump"):
            have_local = _trace.enabled()
            lhost, lpi, lpc = _identity()
            with self._dump_lock:
                self._dump_seq += 1
                req = self._dump_seq
            with self._lock:
                # the hosting rank's self-client would hand back the
                # very ring the local export below already contributes —
                # pulling both would duplicate every local event in the
                # merged file. Identify the self-CONNECTION by pid (an
                # unrelated same-host worker may legitimately claim the
                # same process_index — see _identity's collision note)
                targets = [(k, r["conn"], r["wlock"])
                           for k, r in self._ranks.items()
                           if r["connected"] and r.get("conn") is not None
                           and not (have_local and k[0] == lhost
                                    and r.get("pid") == os.getpid())]
            asked = []
            line = (json.dumps({"t": "trace-dump", "req": req})
                    + "\n").encode()
            # want starts unreachable so a fast rank answering before
            # every request went out cannot complete the wait early
            pend = {"results": [], "want": float("inf"),
                    "event": threading.Event()}
            with self._dump_lock:
                self._dumps[req] = pend
            for key, conn, wlock in targets:
                try:
                    # per-connection writer lock held across the send on
                    # purpose: it serializes dump requests with the
                    # handler's replies on the SAME socket, nothing else
                    # contends for it, and the socket's own timeout
                    # bounds the stall
                    with wlock:
                        conn.sendall(line)  # bst-lint: off=blocking-under-lock — single-writer serialization, see above
                    asked.append(key)
                except OSError:
                    continue
            with self._dump_lock:
                pend["want"] = len(asked)
                if len(pend["results"]) >= pend["want"]:
                    pend["event"].set()
            if asked:
                pend["event"].wait(timeout_s)
            with self._dump_lock:
                self._dumps.pop(req, None)
            docs = [d for d in pend["results"] if d]
            tmpdir = tempfile.mkdtemp(prefix="bst-relay-dump-")
            try:
                if have_local:
                    docs = [_trace.export(lpi, lpc), *docs]
                written = 0
                for doc in docs:
                    meta = doc.get("bst") or {}
                    pi = int(meta.get("process_index") or 0)
                    pc = int(meta.get("process_count") or 1)
                    path = os.path.join(tmpdir, _trace.trace_name(pi, pc))
                    n = 0
                    while os.path.exists(path):   # identity collisions
                        n += 1
                        path = os.path.join(
                            tmpdir, f"trace-{pi:05d}-of-{pc:05d}-{n}.json")
                    with open(path, "w", encoding="utf-8") as f:
                        json.dump(doc, f, default=str)
                    written += 1
                merged = _trace.merge_traces(tmpdir,
                                             output=os.path.abspath(out))
            finally:
                shutil.rmtree(tmpdir, ignore_errors=True)
            if merged is None:
                raise RuntimeError(
                    "no flight-recorder rings to dump: neither this "
                    "process nor any connected rank is recording")
            return {"path": str(merged), "ranks": len(pend["results"]),
                    "asked": len(asked),
                    "missing": max(0, len(asked)
                                   - len(pend["results"])),
                    "local_ring": have_local,
                    "traces": written, **merged.bst}


# -- module singletons / role resolution -------------------------------------

_rlock = threading.Lock()
_CLIENT: RelayClient | None = None
_COLLECTOR: RelayCollector | None = None


def client() -> RelayClient | None:
    return _CLIENT


def collector() -> RelayCollector | None:
    return _COLLECTOR


def serve(address: str) -> RelayCollector:
    """Host the collector at ``address`` (singleton; raises OSError when
    the bind fails — callers fall back to pushing or log and continue)."""
    global _COLLECTOR
    host, port = parse_address(address)
    with _rlock:
        if _COLLECTOR is not None:
            return _COLLECTOR
        _COLLECTOR = RelayCollector(host, port).start()
        return _COLLECTOR


def connect(address: str) -> RelayClient:
    """Start the push client toward ``address`` (singleton). Returns
    immediately; the relay thread connects (and reconnects) on its own.
    A process-exit hook sends the ``bye`` goodbye so a finished rank
    never reads as a silent (stalled) one on the collector."""
    global _CLIENT
    import atexit

    with _rlock:
        if _CLIENT is not None:
            return _CLIENT
        _CLIENT = RelayClient(address).start()
        atexit.register(stop)
        return _CLIENT


def ensure_started():
    """Knob-driven idempotent bring-up (called beside the multi-host
    ``initialize`` and by workload tools): no-op unless
    ``BST_TELEMETRY_RELAY`` is set. Process 0 of a multi-process world
    hosts, falling back to pushing when the address is already owned
    (a daemon on this host); everyone else pushes."""
    addr = config.get_str("BST_TELEMETRY_RELAY")
    if not addr:
        return None
    if _COLLECTOR is not None:
        return _COLLECTOR
    if _CLIENT is not None:
        return _CLIENT
    _h, pi, pc = _identity()
    if pi == 0 and pc > 1:
        try:
            col = serve(addr)
        except OSError:
            pass   # someone on this host already collects — push instead
        else:
            # the hosting rank is a pod member too: push into our own
            # collector so /cluster and the pod health verdict cover
            # rank 0, not only ranks 1..N-1 — via the BOUND interface
            # (a collector on a routable address has nothing listening
            # on loopback; wildcard binds map back to 127.0.0.1)
            from . import httpexport as _httpexport

            connect(f"{_httpexport.display_host(col.host)}:{col.port}")
            return col
    return connect(addr)


def stop() -> None:
    """Stop whichever role this process runs and drop the singletons."""
    global _CLIENT, _COLLECTOR
    with _rlock:
        cl, _CLIENT = _CLIENT, None
        co, _COLLECTOR = _COLLECTOR, None
    if cl is not None:
        cl.stop()
    if co is not None:
        co.stop()


def stop_collector() -> None:
    """Stop only the collector (the serve daemon's drain path — a push
    client owned by the surrounding process lives on)."""
    global _COLLECTOR
    with _rlock:
        co, _COLLECTOR = _COLLECTOR, None
    if co is not None:
        co.stop()

"""Cross-run manifest history store + performance regression diff.

Run manifests die with their telemetry directory: two runs of the same
workload land in two unrelated file trees and nothing compares them.
ROADMAP items 1 and 5b (queue-aware autotuning, ``bst tune`` replaying
manifests) need a durable cross-run record, and so does any human asking
"did yesterday's change make fusion slower?" — the performance-
portability question SparkCL answers by *measuring* each backend
(PAPERS.md, arXiv 1505.01120).

The store is a directory (``BST_HISTORY_DIR``): one compact JSON record
per finalized run/job manifest (span table, metric deltas, stage
summaries, device info — the numbers; argv/params ride along, the event
logs do not) plus an append-only ``index.jsonl`` of one-line summaries.
Appends are O_APPEND single-line writes, so concurrent processes (a
daemon's jobs, a pod's ranks) interleave without locks and never tear
the index. Recording is a no-op unless the knob is set, and history IO
failures never fail the run being recorded.

``bst history [list|show|add]`` browses and imports records; ``bst
perf-diff`` compares two of them — span wall-clock, byte counters and
cache hit ratios — against a configurable regression threshold. This is
the substrate ``bst tune`` will replay.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time

from . import metrics as _metrics
from .. import config

SCHEMA = "bst-history-record/1"

_RECORDS = _metrics.counter("bst_history_records_total")

_seq_lock = threading.Lock()
_seq = 0

# manifest keys copied into a history record verbatim — the numeric
# surface perf-diff / bst tune consume, minus the heavyweight event logs
# that stay in the telemetry dir. trace_file is kept as a POINTER
# (resolved relative to source_manifest) so `bst tune advise` can reach
# the flight-recorder decomposition of a recorded run.
_KEEP = ("tool", "argv", "params", "world", "device", "started_at",
         "seconds", "status", "error", "spans", "metrics", "stages",
         "trace_file")


def history_dir(override: str | None = None) -> str | None:
    d = override or config.get_str("BST_HISTORY_DIR")
    return os.path.abspath(d) if d else None


def _next_record_id(tool: str | None) -> str:
    """Collision-free across processes without coordination: wall-clock
    second + pid + a process-local sequence, prefixed by the tool name so
    ``bst history list`` reads meaningfully."""
    global _seq
    with _seq_lock:
        _seq += 1
        n = _seq
    stamp = time.strftime("%Y%m%d-%H%M%S")
    return f"{(tool or 'run')}-{stamp}-p{os.getpid()}-{n:03d}"


def _write_record(d: str, rid: str, rec: dict,
                  job: str | None = None) -> str:
    """The shared store-append tail: atomic record file + one-line
    index.jsonl append (O_APPEND: concurrent processes never tear it)."""
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, rid + ".json")
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(rec, f, indent=1, default=str)
        f.write("\n")
    os.replace(tmp, path)
    line = json.dumps({"id": rid, "ts": rec["recorded_at"],
                       "tool": rec.get("tool"), "job": job,
                       "status": rec.get("status"),
                       "seconds": rec.get("seconds"),
                       "file": os.path.basename(path)})
    with open(os.path.join(d, "index.jsonl"), "a", encoding="utf-8") as f:
        f.write(line + "\n")
    _RECORDS.inc()
    return rid


def record_manifest(manifest_path: str, *, job: str | None = None,
                    directory: str | None = None) -> str | None:
    """Append one finalized manifest to the history store; returns the
    record id, or None when no history dir is configured. Never raises
    past IO problems to the caller's caller — the finalize paths wrap
    this in a broad except, and so should any other producer."""
    d = history_dir(directory)
    if d is None:
        return None
    with open(manifest_path, encoding="utf-8") as f:
        doc = json.load(f)
    rid = _next_record_id(doc.get("tool") or (job and "job"))
    rec = {"schema": SCHEMA, "id": rid,
           "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
           "source_manifest": os.path.abspath(manifest_path)}
    if job is not None:
        rec["job"] = job
    rec.update({k: doc[k] for k in _KEEP if k in doc})
    return _write_record(d, rid, rec, job=job)


def record_merged_report(report: dict, *, source: str | None = None,
                         directory: str | None = None) -> str | None:
    """Append a ``bst telemetry-merge`` pod report to the history store
    so `bst history` / `bst perf-diff` cover multi-process runs, not only
    the single-process finalize paths. The merged report's summed span
    table / metric totals / stage rows diff exactly like a manifest's;
    ``seconds`` is the pod wall clock (max over ranks) and ``status`` is
    ok only when every rank's was. No-op unless a history dir is
    configured."""
    d = history_dir(directory)
    if d is None:
        return None
    procs = report.get("processes") or []
    tools = sorted({p.get("tool") for p in procs if p.get("tool")})
    tool = tools[0] if len(tools) == 1 else "pod"
    statuses = {p.get("status") for p in procs}
    rid = _next_record_id(f"pod-{tool}")
    rec = {
        "schema": SCHEMA, "id": rid,
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "source_manifest": os.path.abspath(source)
        if source else report.get("directory"),
        "tool": tool,
        "world": {"process_index": 0,
                  "process_count": report.get("process_count")},
        "seconds": report.get("wall_clock_s"),
        # zero manifests (every rank died before finalize) must not
        # masquerade as a healthy baseline in perf-diff
        "status": ("ok" if statuses <= {"ok"} else "error")
        if procs else "unknown",
        "spans": report.get("spans") or {},
        "metrics": report.get("metrics") or {},
        "stages": report.get("stages") or [],
        "params": {"merged_processes": len(procs),
                   "tools": tools,
                   "directory": report.get("directory")},
    }
    return _write_record(d, rid, rec)


def list_records(directory: str | None = None, *, tool: str | None = None,
                 since: str | None = None,
                 limit: int | None = None) -> list[dict]:
    """Index entries, oldest first; [] when the store exists but is
    empty. Raises FileNotFoundError when no history dir is configured.

    ``tool`` keeps only records of that tool, ``since`` only records
    whose timestamp is >= the given stamp (ISO timestamps compare
    lexicographically, so any prefix like "2026-08" works), ``limit``
    keeps the NEWEST N entries after the other filters (still returned
    oldest first)."""
    d = history_dir(directory)
    if d is None:
        raise FileNotFoundError(
            "no history dir: set BST_HISTORY_DIR or pass --history-dir")
    idx = os.path.join(d, "index.jsonl")
    out: list[dict] = []
    if os.path.exists(idx):
        with open(idx, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue   # torn line from a crashed writer
    if tool is not None:
        out = [e for e in out if e.get("tool") == tool]
    if since is not None:
        out = [e for e in out if (e.get("ts") or "") >= since]
    if limit is not None and limit >= 0:
        out = out[len(out) - limit:] if limit else []
    return out


def load_record(ref: str, directory: str | None = None) -> dict:
    """One record by exact id, unique id prefix, negative index ("-1" =
    most recent), or a direct path to a record/manifest JSON file."""
    if os.path.sep in ref or os.path.exists(ref):
        with open(ref, encoding="utf-8") as f:
            return json.load(f)
    entries = list_records(directory)
    try:
        i = int(ref)
        if i < 0:
            ref = entries[i]["id"]   # IndexError -> KeyError below
    except (ValueError, IndexError):
        pass
    matches = [e for e in entries if e["id"] == ref]
    if not matches:
        matches = [e for e in entries if e["id"].startswith(ref)]
    if not matches:
        raise KeyError(f"no history record matching {ref!r}")
    if len(matches) > 1:
        raise KeyError(f"{ref!r} is ambiguous: "
                       f"{[e['id'] for e in matches[:5]]}")
    d = history_dir(directory)
    with open(os.path.join(d, matches[0]["file"]), encoding="utf-8") as f:
        return json.load(f)


def _flat_metrics(rec: dict) -> dict[str, float]:
    """Numeric metric series of a record (histogram dicts flatten to
    their _sum/_count pair so they diff like any counter)."""
    out: dict[str, float] = {}
    for k, v in (rec.get("metrics") or {}).items():
        if isinstance(v, dict):
            if "sum" in v:
                out[k + "_sum"] = float(v["sum"])
            if "count" in v:
                out[k + "_count"] = float(v["count"])
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            out[k] = float(v)
    return out


def _ratio(flat: dict[str, float], hits: str, misses: str) -> float | None:
    h = sum(v for k, v in flat.items() if k.split("{")[0] == hits)
    m = sum(v for k, v in flat.items() if k.split("{")[0] == misses)
    return h / (h + m) if (h + m) > 0 else None


def diff(a: dict, b: dict, *, threshold_pct: float = 20.0,
         min_seconds: float = 0.05, min_bytes: int = 1 << 20) -> dict:
    """Compare run ``b`` against baseline ``a``: span wall-clock totals,
    byte/op counters and cache hit ratios. A *regression* is ``b`` worse
    than ``a`` by more than ``threshold_pct`` percent AND by more than
    the absolute noise floor (``min_seconds`` for spans, ``min_bytes``
    for byte counters; hit ratios regress when they drop by more than
    ``threshold_pct`` percentage points)."""
    thr = threshold_pct / 100.0
    regressions: list[dict] = []

    spans = []
    sa = a.get("spans") or {}
    sb = b.get("spans") or {}
    for name in sorted(set(sa) | set(sb)):
        ta = float((sa.get(name) or {}).get("total_s") or 0.0)
        tb = float((sb.get(name) or {}).get("total_s") or 0.0)
        row = {"span": name, "a_s": round(ta, 3), "b_s": round(tb, 3),
               "delta_s": round(tb - ta, 3),
               "delta_pct": (round((tb - ta) / ta * 100, 1) if ta > 0
                             else None)}
        if tb - ta > min_seconds and (ta <= 0 or tb > ta * (1 + thr)):
            row["regression"] = True
            regressions.append({"kind": "span", **row})
        spans.append(row)

    fa, fb = _flat_metrics(a), _flat_metrics(b)
    counters = []
    for key in sorted(set(fa) | set(fb)):
        base = key.split("{")[0]
        if not (base.endswith("_bytes_total") or base.endswith("_bytes")):
            continue
        va, vb = fa.get(key, 0.0), fb.get(key, 0.0)
        row = {"metric": key, "a": int(va), "b": int(vb),
               "delta": int(vb - va),
               "delta_pct": (round((vb - va) / va * 100, 1) if va > 0
                             else None)}
        if vb - va > min_bytes and (va <= 0 or vb > va * (1 + thr)):
            row["regression"] = True
            regressions.append({"kind": "bytes", **row})
        counters.append(row)

    caches = []
    for label, hits, misses in (
            ("chunk_cache", "bst_chunk_cache_hits_total",
             "bst_chunk_cache_misses_total"),
            ("tile_cache", "bst_tile_cache_hits_total",
             "bst_tile_cache_misses_total")):
        ra, rb = _ratio(fa, hits, misses), _ratio(fb, hits, misses)
        if ra is None and rb is None:
            continue
        row = {"cache": label,
               "a_hit_ratio": round(ra, 4) if ra is not None else None,
               "b_hit_ratio": round(rb, 4) if rb is not None else None}
        if ra is not None and rb is not None \
                and (ra - rb) * 100 > threshold_pct:
            row["regression"] = True
            regressions.append({"kind": "cache", **row})
        caches.append(row)

    wa = float(a.get("seconds") or 0.0)
    wb = float(b.get("seconds") or 0.0)
    wall = {"a_s": round(wa, 3), "b_s": round(wb, 3),
            "delta_s": round(wb - wa, 3),
            "delta_pct": round((wb - wa) / wa * 100, 1) if wa > 0 else None}
    if wb - wa > min_seconds and wa > 0 and wb > wa * (1 + thr):
        wall["regression"] = True
        regressions.append({"kind": "wall_clock", **wall})

    return {"a": a.get("id") or a.get("tool"),
            "b": b.get("id") or b.get("tool"),
            "threshold_pct": threshold_pct,
            "wall_clock": wall,
            "spans": spans,
            "byte_counters": counters,
            "caches": caches,
            "regressions": regressions}


def import_path(path: str, directory: str | None = None) -> list[str]:
    """``bst history add``: import manifest file(s) — a single JSON file
    or a telemetry directory's ``manifest-*.json`` set — into the store;
    returns the new record ids."""
    paths = (sorted(glob.glob(os.path.join(path, "manifest-*.json")))
             if os.path.isdir(path) else [path])
    if not paths:
        raise FileNotFoundError(f"no manifest-*.json under {path}")
    return [rid for p in paths
            if (rid := record_manifest(p, directory=directory)) is not None]

"""Per-stage progress heartbeats and stage summary records.

``Heartbeat`` is the per-block progress channel of the block-writing
drivers (affine fusion, resave, downsample, nonrigid): rate-limited
``stage.progress`` events with done/total, blocks/s and ETA, plus a final
``stage.end`` record that captures ETA-vs-actual for the run manifest.
``record_stage`` lets a driver file its own end-of-stage summary (block /
voxel totals from its stats object).

Stage records accumulate only while telemetry is configured, so library
use (bench loops, tests) never grows unbounded state.
"""

from __future__ import annotations

import threading
import time

from . import events, metrics

_rec_lock = threading.Lock()
_records: list[dict] = []

# live last-progress row for the telemetry relay (observe/relay.py): the
# push client ships it with every heartbeat so `bst top --cluster` shows
# a remote rank's stage/done/total without any event-log plumbing.
# Tracking is OFF by default — a run without an active relay client pays
# nothing beyond the existing events.enabled() check.
_live_lock = threading.Lock()
_live: dict | None = None
_track_live = False
_track_count = 0


def set_live_tracking(on: bool) -> None:
    """Refcounted on/off (tests run several relay clients in one
    process; production runs exactly one)."""
    global _track_live, _live, _track_count
    with _live_lock:
        _track_count = max(0, _track_count + (1 if on else -1))
        _track_live = _track_count > 0
        if not _track_live:
            _live = None


def latest() -> dict | None:
    """The most recent stage-progress row (relay tracking only)."""
    with _live_lock:
        return dict(_live) if _live is not None else None


def _set_live(**row) -> None:
    global _live
    with _live_lock:
        if _track_live:
            _live = {k: v for k, v in row.items() if v is not None}


def reset_records() -> None:
    with _rec_lock:
        _records.clear()


def records() -> list[dict]:
    with _rec_lock:
        return [dict(r) for r in _records]


def take_records(job: str) -> list[dict]:
    """Remove and return the stage records filed under ``job``'s event
    scope (the serve daemon's per-job manifests): popping them keeps a
    long-lived daemon's record list from growing per job, and keeps job
    stages out of the daemon's own run manifest."""
    with _rec_lock:
        mine = [dict(r) for r in _records if r.get("job") == job]
        _records[:] = [r for r in _records if r.get("job") != job]
    for r in mine:
        r.pop("job", None)
    return mine


def _append_record(rec: dict) -> None:
    if not events.enabled():
        return
    # records filed inside a job's event scope carry the job label so a
    # daemon can split concurrent jobs' stage tables into their manifests
    job = events.current_job()
    if job is not None:
        rec = {**rec, "job": job}
    with _rec_lock:
        _records.append(rec)


def record_stage(stage: str, **fields) -> None:
    """File a driver's end-of-stage summary (manifest ``stages`` table)."""
    rec = {"stage": stage, **{k: v for k, v in fields.items()
                              if v is not None}}
    events.emit("stage.summary", **rec)
    _append_record(rec)


class Heartbeat:
    """Thread-safe done/total progress for one work list.

    ``tick`` per completed item; emits ``stage.progress`` at most every
    ``every_s`` seconds (always on completion). The first emitted ETA is
    kept so the manifest can show estimate-vs-actual.
    """

    def __init__(self, stage: str, total: int, every_s: float = 2.0):
        self.stage = stage
        self.total = int(total)
        self.every_s = every_s
        self._lock = threading.Lock()
        self._done = 0
        self._retry_rounds = 0
        self._t0 = time.perf_counter()
        self._last_emit = self._t0
        self._eta_first_s: float | None = None
        self._counter = metrics.counter("bst_stage_items_done_total",
                                        stage=stage)
        self._finished = False
        _set_live(stage=stage, done=0, total=self.total,
                  ts=round(time.time(), 3))
        events.emit("stage.start", stage=stage, total=self.total)

    def tick(self, n: int = 1) -> None:
        self._counter.inc(n)
        with self._lock:
            self._done += n
            if not events.enabled() and not _track_live:
                return
            now = time.perf_counter()
            done, total = self._done, self.total
            if now - self._last_emit < self.every_s and done < total:
                return
            self._last_emit = now
            elapsed = now - self._t0
            rate = done / max(elapsed, 1e-9)
            eta_s = (total - done) / max(rate, 1e-9)
            if self._eta_first_s is None:
                # projected total duration at the first estimate
                self._eta_first_s = elapsed + eta_s
        _set_live(stage=self.stage, done=done, total=total,
                  rate_per_s=round(rate, 3), eta_s=round(eta_s, 1),
                  ts=round(time.time(), 3))
        if events.enabled():
            events.emit("stage.progress", stage=self.stage, done=done,
                        total=total, rate_per_s=round(rate, 3),
                        eta_s=round(eta_s, 1))

    def retry_round(self) -> None:
        with self._lock:
            self._retry_rounds += 1

    def finish(self, **extra) -> dict:
        with self._lock:
            if self._finished:
                return {}
            self._finished = True
            elapsed = time.perf_counter() - self._t0
            rec = {
                "stage": self.stage,
                "done": self._done,
                "total": self.total,
                "seconds": round(elapsed, 3),
                "rate_per_s": round(self._done / max(elapsed, 1e-9), 3),
                "retry_rounds": self._retry_rounds,
            }
            if self._eta_first_s is not None:
                rec["eta_first_s"] = round(self._eta_first_s, 3)
                rec["eta_error_s"] = round(elapsed - self._eta_first_s, 3)
        rec.update({k: v for k, v in extra.items() if v is not None})
        _set_live(stage=self.stage, done=rec["done"], total=rec["total"],
                  rate_per_s=rec["rate_per_s"], finished=True,
                  ts=round(time.time(), 3))
        events.emit("stage.end", **rec)
        _append_record(rec)
        return rec

"""Embedded live HTTP exporter: /metrics, /healthz, /status, /jobs.

Until now every telemetry artifact was end-of-run: the Prometheus
textfile, the run manifest and the Perfetto trace all materialize at
``observe.finalize()``. That was fine for one-shot tools and is blind
for the resident ``bst serve`` daemon and long streamed pipelines — a
stalled job, a starved dag consumer or a leaking cache in a process that
never exits is invisible. This module is the live view: a stdlib
``http.server`` bound to 127.0.0.1 (``BST_METRICS_PORT``; 0 = off)
serving

- ``/metrics`` — the SAME ``MetricsRegistry.render_prometheus()`` text
  the end-of-run textfile contains, scraped live, plus process
  self-gauges (uptime, RSS, thread count, open FDs) refreshed per
  scrape;
- ``/healthz`` — liveness JSON, HTTP 200 when healthy and 503 when not
  (the daemon wires mesh liveness, slot-loop heartbeat age and the
  stall watchdog's stalled-job count in here; a bare one-shot process
  is healthy as long as it answers);
- ``/status`` — one JSON status object (daemon queue/cache/dag state,
  or generic process + trace state outside a daemon);
- ``/jobs`` — the job table (empty outside a daemon).

The server is one module-level singleton so the daemon and the CLI
bootstrapping path never race two exporters onto one port; *providers*
(status/health/jobs callables) are swappable at runtime — the daemon
attaches its own on start and detaches them on drain, leaving the
generic process view for whatever outlives it. Handlers run on the
ThreadingHTTPServer's daemon threads, so a scrape can never block (or be
blocked by) job execution — the registry render takes the registry lock
exactly like the end-of-run textfile writer does.
"""

from __future__ import annotations

import http.server
import json
import os
import threading
import time

from . import metrics as _metrics
from .. import config

_PROC_START = time.time()

_UPTIME = _metrics.gauge("bst_process_uptime_seconds")
_RSS = _metrics.gauge("bst_process_rss_bytes")
_THREADS = _metrics.gauge("bst_process_threads")
_FDS = _metrics.gauge("bst_process_open_fds")


def _rss_bytes() -> int | None:
    """Resident-set size via /proc (linux); None where unavailable."""
    try:
        with open("/proc/self/statm", encoding="ascii") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGESIZE")
    except (OSError, ValueError, IndexError):
        return None


def _open_fds() -> int | None:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return None


def process_stats() -> dict:
    """Uptime / RSS / threads / open-FD snapshot of THIS process,
    refreshed into the registry gauges so the same numbers appear in
    ``/metrics`` scrapes and end-of-run textfiles."""
    up = time.time() - _PROC_START
    rss = _rss_bytes()
    nthreads = threading.active_count()
    fds = _open_fds()
    _UPTIME.set(round(up, 3))
    _THREADS.set(nthreads)
    if rss is not None:
        _RSS.set(rss)
    if fds is not None:
        _FDS.set(fds)
    out = {"pid": os.getpid(), "uptime_s": round(up, 1),
           "threads": nthreads}
    if rss is not None:
        out["rss_bytes"] = rss
    if fds is not None:
        out["open_fds"] = fds
    return out


# -- providers ---------------------------------------------------------------
# status() -> dict; health() -> (ok: bool, payload: dict); jobs() -> list.
# The daemon swaps its own in; the defaults describe a bare process.
# Above them sits a second, independently-owned layer the telemetry
# relay collector (observe/relay.py) attaches: cluster_health(ok,
# payload) -> (ok, payload) merges the pod verdict into /healthz,
# cluster() -> dict feeds the /cluster endpoint, and
# metrics_render(local_text) -> str replaces the /metrics body with the
# family-merged cluster render (local series plus one
# host/process_index-labeled copy per rank, each metric family kept
# contiguous so the exposition stays spec-valid).

_plock = threading.Lock()
_PROVIDERS: dict = {"status": None, "health": None, "jobs": None,
                    "cluster_health": None, "cluster": None,
                    "metrics_render": None}


def set_providers(status=None, health=None, jobs=None) -> None:
    with _plock:
        if status is not None:
            _PROVIDERS["status"] = status
        if health is not None:
            _PROVIDERS["health"] = health
        if jobs is not None:
            _PROVIDERS["jobs"] = jobs


def clear_providers() -> None:
    with _plock:
        _PROVIDERS.update(status=None, health=None, jobs=None)


def set_cluster_providers(health=None, cluster=None,
                          metrics_render=None) -> None:
    """The relay collector's layer — separate setters so a daemon drain
    (clear_providers) never tears down the cluster plane, and vice
    versa."""
    with _plock:
        if health is not None:
            _PROVIDERS["cluster_health"] = health
        if cluster is not None:
            _PROVIDERS["cluster"] = cluster
        if metrics_render is not None:
            _PROVIDERS["metrics_render"] = metrics_render


def clear_cluster_providers() -> None:
    with _plock:
        _PROVIDERS.update(cluster_health=None, cluster=None,
                          metrics_render=None)


def _provider(name: str):
    with _plock:
        return _PROVIDERS[name]


def _default_status() -> dict:
    from . import trace as _trace
    from . import telemetry_dir as _tdir  # type: ignore[attr-defined]

    return {"process": process_stats(), "trace": _trace.stats(),
            "telemetry_dir": _tdir()}


def _default_health() -> tuple[bool, dict]:
    return True, {"ok": True, "uptime_s": round(time.time() - _PROC_START, 1)}


class _Handler(http.server.BaseHTTPRequestHandler):
    server_version = "bst-exporter/1"

    def log_message(self, *args) -> None:   # no stderr chatter per scrape
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, doc) -> None:
        body = (json.dumps(doc, indent=1, default=str) + "\n").encode()
        self._send(code, body, "application/json")

    def do_GET(self) -> None:   # noqa: N802 — BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/metrics":
                _metrics.counter("bst_http_requests_total",
                                 endpoint="metrics").inc()
                process_stats()   # refresh the self-gauges pre-render
                text = _metrics.get_registry().render_prometheus()
                render = _provider("metrics_render")
                if render is not None:
                    try:
                        merged = render(text)
                        if isinstance(merged, str):
                            text = merged
                    except Exception:
                        pass   # a broken relay must not cost /metrics
                self._send(200, text.encode(), "text/plain; version=0.0.4")
            elif path == "/healthz":
                _metrics.counter("bst_http_requests_total",
                                 endpoint="healthz").inc()
                health = _provider("health") or _default_health
                ok, payload = health()
                cluster_health = _provider("cluster_health")
                if cluster_health is not None:
                    ok, payload = cluster_health(ok, payload)
                self._send_json(200 if ok else 503, payload)
            elif path in ("/status", "/"):
                _metrics.counter("bst_http_requests_total",
                                 endpoint="status").inc()
                status = _provider("status")
                doc = status() if status is not None else _default_status()
                self._send_json(200, doc)
            elif path == "/jobs":
                _metrics.counter("bst_http_requests_total",
                                 endpoint="jobs").inc()
                jobs = _provider("jobs")
                self._send_json(200, {"jobs": jobs() if jobs is not None
                                      else []})
            elif path == "/cluster":
                _metrics.counter("bst_http_requests_total",
                                 endpoint="cluster").inc()
                cluster = _provider("cluster")
                if cluster is None:
                    self._send_json(404, {
                        "error": "no relay collector in this process — "
                                 "set BST_TELEMETRY_RELAY (or `bst serve "
                                 "--relay`) to aggregate a pod here"})
                else:
                    self._send_json(200, cluster())
            else:
                self._send_json(404, {"error": f"no such endpoint {path!r}",
                                      "endpoints": ["/metrics", "/healthz",
                                                    "/status", "/jobs",
                                                    "/cluster"]})
        except (BrokenPipeError, ConnectionResetError):
            pass   # scraper went away mid-response
        except Exception as e:   # a broken provider must not kill the server
            try:
                self._send_json(500, {"error": repr(e)[:500]})
            except OSError:
                pass


def display_host(host: str | None) -> str:
    """A connectable spelling of a bind host for echoes and URLs:
    wildcard binds answer on loopback."""
    if not host or host in ("0.0.0.0", "::"):
        return "127.0.0.1"
    return host


class Exporter:
    """One running HTTP exporter; ``stop()`` shuts the server down and
    joins its accept thread."""

    def __init__(self, server: http.server.ThreadingHTTPServer,
                 thread: threading.Thread):
        self._server = server
        self._thread = thread

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        return (f"http://{display_host(self._server.server_address[0])}:"
                f"{self.port}")

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=10)


_elock = threading.Lock()
_EXPORTER: Exporter | None = None


def active() -> Exporter | None:
    return _EXPORTER


def start(port: int, host: str | None = None) -> Exporter:
    """Bind and serve on ``host:port`` (``port=0`` asks the OS for a free
    one — note the knob path treats 0 as OFF; programmatic/explicit-flag
    callers use 0 for ephemeral test/doc daemons). ``host`` defaults to
    the ``BST_METRICS_HOST`` knob (127.0.0.1 — a pod's rank-0 exporter
    sets 0.0.0.0 so the aggregated plane is scrapeable from outside the
    host; the server has NO auth, so only widen the bind on a trusted
    network). Returns the existing exporter when one is already running
    (singleton)."""
    global _EXPORTER
    if host is None:
        host = config.get_str("BST_METRICS_HOST") or "127.0.0.1"
    with _elock:
        if _EXPORTER is not None:
            return _EXPORTER
        srv = http.server.ThreadingHTTPServer((host, int(port)), _Handler)
        srv.daemon_threads = True
        # raw daemon thread on purpose: process-lived exporter serving
        # scrapes for every job, no job context to carry
        th = threading.Thread(target=srv.serve_forever,  # bst-lint: off=thread-spawn
                              name="bst-http-exporter", daemon=True)
        th.start()
        _EXPORTER = Exporter(srv, th)
        return _EXPORTER


def ensure_started() -> Exporter | None:
    """Knob-driven idempotent start: BST_METRICS_PORT > 0 starts (or
    returns) the exporter, anything else is off. Bind failures are
    reported, never fatal — losing the live view must not kill a run."""
    if _EXPORTER is not None:
        return _EXPORTER
    port = config.get_int("BST_METRICS_PORT") or 0
    if port <= 0:
        return None
    try:
        return start(port)
    except OSError as e:
        from . import log as _log  # type: ignore[attr-defined]

        _log(f"live exporter disabled: cannot bind port {port}: {e}",
             stage="observe")
        return None


def stop() -> None:
    """Stop the exporter (if running) and drop the singleton."""
    global _EXPORTER
    with _elock:
        exp = _EXPORTER
        _EXPORTER = None
    if exp is not None:
        exp.stop()

"""Structured run telemetry: event log, metrics registry, run manifests.

The Spark reference gets observability for free from its runtime — an
event log, a history server, per-stage task counts and retry accounting.
This package is the TPU port's equivalent, threaded through every layer:

- :mod:`.events` — append-only JSONL event log, one file per process
  named by ``(process_index, process_count)`` so multi-host runs never
  collide;
- :mod:`.metrics` — always-on thread-safe counter/gauge/histogram
  registry with a Prometheus-style textfile export;
- :mod:`.progress` — per-stage heartbeats (done/total, rate, ETA) and
  stage summary records;
- :mod:`.manifest` — the per-run manifest written at command end plus
  the ``bst telemetry-merge`` fold of N per-process files.

Activation is one call — ``observe.configure(telemetry_dir)`` — wired to
the shared ``--telemetry-dir`` / ``--profile`` CLI options; disabled (the
default) every ``events.emit`` is a single ``is None`` check and nothing
touches the filesystem.
"""

from __future__ import annotations

import os
import sys
import time

from . import events, manifest, metrics, progress, trace  # noqa: F401

_STATE: dict = {
    "dir": None,
    "started_at": None,
    "metrics_baseline": None,
    "enabled_profiling": False,
}


def configure(telemetry_dir: str, profile: bool = True) -> None:
    """Activate telemetry into ``telemetry_dir`` for the rest of this run.

    Opens the per-process event log (lazily), snapshots the metrics
    registry so the manifest reports this run's deltas, resets the stage
    records, and (by default) enables the span profiler so the manifest
    carries the span-stat table."""
    from .. import profiling

    d = os.path.abspath(telemetry_dir)
    os.makedirs(d, exist_ok=True)
    events.configure(d)
    progress.reset_records()
    _STATE["dir"] = d
    _STATE["started_at"] = time.time()
    _STATE["metrics_baseline"] = metrics.get_registry().snapshot()
    if profile and not profiling.get().enabled:
        profiling.enable(True)
        _STATE["enabled_profiling"] = True
    events.emit("run.start", argv=list(sys.argv), pid=os.getpid())


def active() -> bool:
    return _STATE["dir"] is not None


def telemetry_dir() -> str | None:
    return _STATE["dir"]


def log(message: str, stage: str | None = None, echo: bool = True,
        **fields) -> None:
    """Structured replacement for the drivers' bare ``print``: always an
    event (when telemetry is on), a stdout line only when ``echo`` —
    callers pass their existing ``progress``/``verbose`` flag, so console
    behavior is unchanged while the event log sees everything."""
    if events.enabled():
        events.emit("log", stage=stage, message=message, **fields)
    if echo:
        print(message)


def finalize(tool: str | None = None, params: dict | None = None,
             status: str = "ok", error: str | None = None) -> str | None:
    """End the telemetry run: write the Prometheus textfile and the run
    manifest, close the event log, restore profiler state. Idempotent —
    returns the manifest path, or None when telemetry was never
    configured."""
    from .. import profiling

    if not active():
        return None
    d = _STATE["dir"]
    pi, pc = events.world()
    reg = metrics.get_registry()
    prom_path = os.path.join(d, f"metrics-{pi:05d}-of-{pc:05d}.prom")
    with open(prom_path, "w", encoding="utf-8") as f:
        f.write(reg.render_prometheus())
    spans = {k: {"count": s.count, "total_s": round(s.total_s, 3),
                 "max_s": round(s.max_s, 3), "min_s": round(s.min_s, 3)}
             for k, s in profiling.get().stats().items()}
    seconds = time.time() - _STATE["started_at"]
    events.emit("run.end", status=status, seconds=round(seconds, 3),
                error=error)
    # archive the flight-recorder ring (if one is recording) next to the
    # manifest, so a traced run's timeline travels with its telemetry —
    # unless BST_TRACE_PATH/configure(path=) sent it elsewhere, in which
    # case the manifest must point at the real location, not a dangling
    # dir-local basename
    trace_path = trace.finalize(dir_hint=d)
    if trace_path is not None and \
            os.path.dirname(os.path.abspath(trace_path)) == \
            os.path.abspath(d):
        trace_path = os.path.basename(trace_path)
    ev_path = events.close()
    path = manifest.write_manifest(
        d,
        tool=tool,
        argv=list(sys.argv),
        params=params,
        world=(pi, pc),
        started_at=_STATE["started_at"],
        seconds=seconds,
        status=status,
        error=error,
        spans=spans,
        metrics_delta=reg.snapshot_delta(_STATE["metrics_baseline"]),
        # job-scoped stage records belong to their JobRun manifests, not
        # the process-wide one (a serve daemon's own manifest would
        # otherwise re-report every job's stages)
        stages=[r for r in progress.records() if "job" not in r],
        events_file=os.path.basename(ev_path) if ev_path else None,
        trace_file=trace_path,
    )
    progress.reset_records()
    if _STATE["enabled_profiling"]:
        profiling.enable(False)
    _STATE.update(dir=None, started_at=None, metrics_baseline=None,
                  enabled_profiling=False)
    _record_history(path)
    return path


def _record_history(manifest_path: str | None,
                    job: str | None = None) -> None:
    """Append a finalized manifest to the BST_HISTORY_DIR store (no-op
    when the knob is unset); history IO must never fail the run it
    records."""
    if manifest_path is None:
        return
    try:
        from . import history

        history.record_manifest(manifest_path, job=job)
    except Exception:
        pass


class JobRun:
    """Scoped telemetry for ONE job inside a long-lived process (the
    ``bst serve`` daemon's per-job manifests).

    Where :func:`configure`/:func:`finalize` own the whole process run,
    a JobRun owns one job's slice of it: its own event-log sink
    (``events-job-<label>-*.jsonl`` in its own directory, routed by the
    job's context scope so concurrent jobs never interleave), its own
    metric DELTAS (registry snapshot at open, delta at finalize — the
    process registry stays shared, which is the point: warm caches are
    visible as per-job hit deltas), its own span-count deltas, and its
    own stage records (tagged by the event scope, popped at finalize).

    Use as a context manager around the job's execution on the job's
    thread — worker threads inherit the scope via utils.threads — then
    call :meth:`finalize` for the manifest.
    """

    def __init__(self, label: str, directory: str, tool: str | None = None):
        from .. import profiling

        self.label = str(label)
        self.dir = os.path.abspath(directory)
        self.tool = tool
        self.started_at = time.time()
        events.open_job(self.label, self.dir)
        self._metrics_baseline = metrics.get_registry().snapshot()
        self._span_baseline = {
            k: (s.count, s.total_s)
            for k, s in profiling.get().stats().items()}
        self._token = None
        self._finalized = False

    def __enter__(self):
        self._token = events.activate_job(self.label)
        events.emit("job.start", job=self.label, tool=self.tool,
                    pid=os.getpid())
        return self

    def __exit__(self, *exc):
        if self._token is not None:
            events.deactivate_job(self._token)
            self._token = None
        return False

    def finalize(self, status: str = "ok", error: str | None = None,
                 params: dict | None = None,
                 argv: list[str] | None = None) -> str | None:
        """Write the job's manifest into its directory and close its event
        sink. Idempotent; returns the manifest path."""
        from .. import profiling

        if self._finalized:
            return None
        self._finalized = True
        seconds = time.time() - self.started_at
        # the job.end record must land in the JOB's log regardless of
        # which thread finalizes
        token = events.activate_job(self.label)
        try:
            events.emit("job.end", job=self.label, status=status,
                        seconds=round(seconds, 3), error=error)
        finally:
            events.deactivate_job(token)
        ev_path = events.close_job(self.label)
        spans = {}
        for k, s in profiling.get().stats().items():
            c0, t0 = self._span_baseline.get(k, (0, 0.0))
            if s.count <= c0:
                continue
            # count/total are true deltas; min/max are process-lifetime
            # aggregates (the profiler keeps no per-interval extrema)
            spans[k] = {"count": s.count - c0,
                        "total_s": round(s.total_s - t0, 3),
                        "max_s": round(s.max_s, 3),
                        "min_s": round(s.min_s, 3)}
        reg = metrics.get_registry()
        path = manifest.write_manifest(
            self.dir,
            tool=self.tool,
            argv=argv if argv is not None else [],
            params=params,
            world=events.world(),
            started_at=self.started_at,
            seconds=seconds,
            status=status,
            error=error,
            spans=spans,
            metrics_delta=reg.snapshot_delta(self._metrics_baseline),
            stages=progress.take_records(self.label),
            events_file=os.path.basename(ev_path) if ev_path else None,
        )
        _record_history(path, job=self.label)
        return path

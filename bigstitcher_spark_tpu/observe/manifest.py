"""Per-run manifests and the multi-process merge report.

One manifest per process per run
(``manifest-{process_index:05d}-of-{process_count:05d}.json``), written
next to the event log when the run finishes: CLI argv + resolved config,
world size, device kind/count, the span-stat table from ``profiling``,
the run's metric deltas (IO bytes, transfer bytes, retry rounds, block
counters) and per-stage summaries (done/total, blocks/s, ETA-vs-actual).
``merge_run`` folds N per-process files (a pod run) into one report — the
role of the Spark history server's application summary.
"""

from __future__ import annotations

import glob
import json
import os
import time

SCHEMA = "bst-run-manifest/1"
MERGED_SCHEMA = "bst-merged-report/1"


def manifest_name(process_index: int, process_count: int) -> str:
    return f"manifest-{process_index:05d}-of-{process_count:05d}.json"


def device_info() -> dict:
    """Best-effort device inventory; empty when no backend ever came up."""
    try:
        import jax

        devs = jax.devices()
        return {
            "platform": devs[0].platform,
            "device_kind": getattr(devs[0], "device_kind", None),
            "local_device_count": jax.local_device_count(),
            "device_count": len(devs),
        }
    except Exception:
        return {}


def _json_default(o):
    if hasattr(o, "tolist"):
        return o.tolist()
    return str(o)


def write_manifest(
    directory: str,
    *,
    tool: str | None,
    argv: list[str],
    params: dict | None,
    world: tuple[int, int],
    started_at: float,
    seconds: float,
    status: str,
    error: str | None,
    spans: dict,
    metrics_delta: dict,
    stages: list[dict],
    events_file: str | None,
    trace_file: str | None = None,
) -> str:
    pi, pc = world
    doc = {
        "schema": SCHEMA,
        "tool": tool,
        "argv": list(argv),
        "params": params or {},
        "world": {"process_index": pi, "process_count": pc},
        "device": device_info(),
        "started_at": time.strftime("%Y-%m-%dT%H:%M:%S",
                                    time.localtime(started_at)),
        "seconds": round(seconds, 3),
        "status": status,
        "spans": spans,
        "metrics": metrics_delta,
        "stages": stages,
        "events_file": events_file,
    }
    if trace_file:
        doc["trace_file"] = trace_file
    if error:
        doc["error"] = error
    path = os.path.join(directory, manifest_name(pi, pc))
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, default=_json_default)
        f.write("\n")
    os.replace(tmp, path)
    return path


def _merge_numeric(dst: dict, src: dict) -> None:
    for k, v in src.items():
        if isinstance(v, dict):
            node = dst.setdefault(k, {})
            if isinstance(node, dict):
                _merge_numeric(node, v)
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            dst[k] = dst.get(k, 0) + v


def _merge_spans(dst: dict, src: dict) -> None:
    for name, s in src.items():
        d = dst.setdefault(name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
        d["count"] += s.get("count", 0)
        d["total_s"] = round(d["total_s"] + s.get("total_s", 0.0), 3)
        d["max_s"] = max(d["max_s"], s.get("max_s", 0.0))
        if "min_s" in s:   # pre-min_s manifests merge without it
            d["min_s"] = min(d.get("min_s", s["min_s"]), s["min_s"])


def merge_run(directory: str) -> dict:
    """Fold every per-process manifest + event log in ``directory`` into
    one report: summed counters, merged span table, per-stage totals and
    a failure breakdown by exception class."""
    from . import events as ev

    man_paths = sorted(glob.glob(os.path.join(directory, "manifest-*.json")))
    ev_paths = sorted(glob.glob(os.path.join(directory, "events-*.jsonl")))
    if not man_paths and not ev_paths:
        raise FileNotFoundError(
            f"no manifest-*.json or events-*.jsonl under {directory}")

    processes: list[dict] = []
    metrics_sum: dict = {}
    spans: dict = {}
    stages: dict[str, dict] = {}
    wall_s = 0.0
    for p in man_paths:
        with open(p, encoding="utf-8") as f:
            m = json.load(f)
        w = m.get("world", {})
        processes.append({
            "process_index": w.get("process_index"),
            "process_count": w.get("process_count"),
            "tool": m.get("tool"),
            "status": m.get("status"),
            "seconds": m.get("seconds"),
            "device": m.get("device", {}),
            "manifest": os.path.basename(p),
        })
        wall_s = max(wall_s, float(m.get("seconds") or 0.0))
        _merge_numeric(metrics_sum, m.get("metrics", {}))
        _merge_spans(spans, m.get("spans", {}))
        for rec in m.get("stages", []):
            name = rec.get("stage", "?")
            d = stages.setdefault(name, {"stage": name})
            _merge_numeric(d, {k: v for k, v in rec.items() if k != "stage"})

    event_count = 0
    failures_by_exception: dict[str, int] = {}
    for p in ev_paths:
        for rec in ev.iter_events(p):
            event_count += 1
            if rec.get("type") == "block.fail" and rec.get("exception"):
                exc = rec["exception"]
                failures_by_exception[exc] = (
                    failures_by_exception.get(exc, 0) + 1)

    total_done = sum(int(s.get("done") or s.get("blocks") or 0)
                     for s in stages.values())
    report = {
        "schema": MERGED_SCHEMA,
        "directory": os.path.abspath(directory),
        "processes": processes,
        "process_count": (max((p["process_count"] or 1 for p in processes),
                              default=len(ev_paths) or 1)),
        "wall_clock_s": round(wall_s, 3),
        "items_done": total_done,
        "items_per_s": round(total_done / wall_s, 3) if wall_s else None,
        "stages": sorted(stages.values(), key=lambda s: s["stage"]),
        "spans": spans,
        "metrics": metrics_sum,
        "events": event_count,
        "failures_by_exception": failures_by_exception,
    }
    return report

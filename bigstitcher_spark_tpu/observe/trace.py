"""Timeline flight recorder: bounded ring buffer of begin/end trace events.

The span aggregates (:mod:`profiling`) can say `fusion.d2h` took 13.8 s
total — they cannot say whether it OVERLAPPED `fusion.write`, how long a
device sat idle between dispatches, or which per-block chain was the
critical path. Those are exactly the questions the measured frontier
raises (PERF §3g–k: D2H + writes dwarf compute while the kernel runs at
376 Mvox/s), and what the streaming stage-DAG executor and the autotuner
(ROADMAP items 2 and 5) need answered before they can schedule overlap.

This module is the recorder only: a process-wide, thread-safe, bounded
ring of timestamped begin/end/instant events carrying thread id, device
ordinal, stage, work-item identity (block offset / pair index) and byte
payload. Analysis lives in :mod:`..analysis.tracereport` (the
``bst trace-report`` CLI); export is Chrome/Perfetto ``trace_event``
JSON, loadable directly in ``ui.perfetto.dev``, one track per device and
per host thread.

Cost model:

- **off (default)**: ``enabled()`` is one dict read; ``span`` yields
  immediately; nothing allocates. ``profiling.span`` call sites pay one
  extra truthiness check.
- **on**: one lock + tuple append per event. The ring is sized in bytes
  (``BST_TRACE_BUFFER_BYTES`` / ``_EVENT_COST_BYTES``) and OVERFLOW
  KEEPS THE NEWEST events (the tail of a run is where the frontier is);
  drops are counted (``bst_trace_events_dropped_total``), never silent.

Enable with ``--trace`` (every tool, ``cli/common.py``) or
``trace.configure()``; the file lands at ``BST_TRACE_PATH``, else next
to the telemetry file set as ``trace-{pi:05d}-of-{pc:05d}.json`` (so
``bst telemetry-merge`` can fold + barrier-align a pod run's traces),
else ``./bst-trace.json``.

Span NAMES are literals declared in ``observe/metric_names.py``'s
``SPANS`` table — the ``span-name`` lint check bans dynamic names, and
reusing :mod:`profiling`'s names means the trace and the span aggregates
can never disagree about what was measured. Dynamic identity (device,
block offset, pair index, bytes) rides in the event's args instead.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque

from . import metrics as _metrics
from .. import config

SCHEMA = "bst-trace/1"
MERGED_SCHEMA = "bst-merged-trace/1"

# amortized python-side cost of one buffered event tuple (8-slot tuple +
# interned strings + smallint refs); sizes the ring from the byte knob
_EVENT_COST_BYTES = 160
_MIN_CAPACITY = 64

# device-track ids in the exported trace: Perfetto tids are plain ints,
# so device ordinals map to a reserved high range and host threads to
# small first-appearance indices — one track per device, one per thread
_DEVICE_TID_BASE = 10_000

_EVENTS_TOTAL = _metrics.counter("bst_trace_events_total")
_EVENTS_DROPPED = _metrics.counter("bst_trace_events_dropped_total")

_lock = threading.Lock()
_STATE: dict = {
    "enabled": False,
    "buf": None,           # deque of (ts, ph, name, tid, device, stage,
    "capacity": 0,         #           item, nbytes)
    "recorded": 0,
    "dropped": 0,
    "path": None,          # explicit output override (beats the knob)
    "last_path": None,     # where finalize() wrote, for CLI echo
}
_thread_names: dict[int, str] = {}


def trace_name(process_index: int, process_count: int) -> str:
    return f"trace-{process_index:05d}-of-{process_count:05d}.json"


def configure(buffer_bytes: int | None = None, path: str | None = None) -> None:
    """Start recording into a fresh ring. ``buffer_bytes`` defaults to the
    ``BST_TRACE_BUFFER_BYTES`` knob; ``path`` overrides the output
    resolution of :func:`finalize`."""
    if buffer_bytes is None:
        buffer_bytes = config.get_bytes("BST_TRACE_BUFFER_BYTES")
    cap = max(_MIN_CAPACITY, int(buffer_bytes) // _EVENT_COST_BYTES)
    with _lock:
        _thread_names.clear()  # OS thread idents get recycled across runs
        _STATE["buf"] = deque(maxlen=cap)
        _STATE["capacity"] = cap
        _STATE["recorded"] = 0
        _STATE["dropped"] = 0
        _STATE["path"] = path
        _STATE["last_path"] = None
        _STATE["enabled"] = True


def enabled() -> bool:
    return _STATE["enabled"]


def last_path() -> str | None:
    return _STATE["last_path"]


def record(ph: str, name: str, *, device: int | None = None,
           stage: str | None = None, item=None, nbytes: int | None = None,
           ts: float | None = None) -> None:
    """Append one event (``ph``: ``"B"`` begin / ``"E"`` end / ``"i"``
    instant); no-op unless configured. ``ts`` is wall-clock seconds
    (defaulted) — wall clock, not a monotonic counter, because multihost
    merge aligns traces across processes via shared barrier exits."""
    if not _STATE["enabled"]:
        return
    t = time.time() if ts is None else ts
    tid = threading.get_ident()
    with _lock:
        buf = _STATE["buf"]
        if buf is None:
            return
        if tid not in _thread_names:
            _thread_names[tid] = threading.current_thread().name
        if len(buf) == _STATE["capacity"]:
            _STATE["dropped"] += 1     # deque drops the OLDEST: newest win
            _EVENTS_DROPPED.inc()
        buf.append((t, ph, name, tid, device, stage, item, nbytes))
        _STATE["recorded"] += 1
        _EVENTS_TOTAL.inc()


@contextlib.contextmanager
def span(name: str, *, device: int | None = None, stage: str | None = None,
         item=None, nbytes: int | None = None):
    """Record a begin/end pair around the body (trace-only — use
    :func:`profiling.span` where the wall-clock aggregate should exist
    too; that one forwards here when tracing is on)."""
    if not _STATE["enabled"]:
        yield
        return
    record("B", name, device=device, stage=stage, item=item, nbytes=nbytes)
    try:
        yield
    finally:
        record("E", name, device=device, stage=stage, item=item,
               nbytes=nbytes)


def instant(name: str, *, device: int | None = None, stage: str | None = None,
            item=None, nbytes: int | None = None) -> None:
    record("i", name, device=device, stage=stage, item=item, nbytes=nbytes)


def stats() -> dict:
    with _lock:
        return {
            "enabled": _STATE["enabled"],
            "recorded": _STATE["recorded"],
            "dropped": _STATE["dropped"],
            "buffered": len(_STATE["buf"]) if _STATE["buf"] is not None else 0,
            "capacity_events": _STATE["capacity"],
        }


def snapshot() -> list[dict]:
    """The buffered events as dicts (oldest first) — the test/report
    surface that needs no file round-trip."""
    with _lock:
        items = list(_STATE["buf"]) if _STATE["buf"] is not None else []
    out = []
    for t, ph, name, tid, device, stage, item, nbytes in items:
        rec = {"ts": t, "ph": ph, "name": name, "tid": tid}
        if device is not None:
            rec["device"] = device
        if stage is not None:
            rec["stage"] = stage
        if item is not None:
            rec["item"] = item
        if nbytes is not None:
            rec["nbytes"] = nbytes
        out.append(rec)
    return out


def reset() -> None:
    """Stop recording and drop the buffer (test isolation)."""
    with _lock:
        _thread_names.clear()
        _STATE["enabled"] = False
        _STATE["buf"] = None
        _STATE["capacity"] = 0
        _STATE["recorded"] = 0
        _STATE["dropped"] = 0
        _STATE["path"] = None


def export(process_index: int = 0, process_count: int = 1) -> dict:
    """The Chrome/Perfetto ``trace_event`` JSON document: ``B``/``E``/``i``
    events in microseconds, device-attributed events routed to one track
    per device ordinal, host events to one track per thread, plus the
    ``M`` metadata naming every track."""
    with _lock:
        items = list(_STATE["buf"]) if _STATE["buf"] is not None else []
        tnames = dict(_thread_names)
        recorded, dropped = _STATE["recorded"], _STATE["dropped"]

    tid_index: dict[int, int] = {}
    for _t, _ph, _n, tid, device, *_rest in items:
        if device is None and tid not in tid_index:
            tid_index[tid] = len(tid_index) + 1

    meta = [{
        "ph": "M", "name": "process_name", "pid": process_index,
        "args": {"name": f"bst process {process_index}/{process_count}"},
    }]
    used_device_tids: set[int] = set()
    events = []
    for t, ph, name, tid, device, stage, item, nbytes in items:
        if device is not None:
            out_tid = _DEVICE_TID_BASE + int(device)
            used_device_tids.add(out_tid)
        else:
            out_tid = tid_index[tid]
        args = {}
        if stage is not None:
            args["stage"] = stage
        if item is not None:
            args["item"] = item
        if nbytes is not None:
            args["bytes"] = int(nbytes)
        if device is not None:
            args["device"] = int(device)
        ev = {"name": name, "cat": name.split(".")[0], "ph": ph,
              "ts": round(t * 1e6, 1), "pid": process_index, "tid": out_tid,
              "args": args}
        if ph == "i":
            ev["s"] = "t"
        events.append(ev)
    for dt in sorted(used_device_tids):
        meta.append({"ph": "M", "name": "thread_name", "pid": process_index,
                     "tid": dt,
                     "args": {"name": f"device {dt - _DEVICE_TID_BASE}"}})
        meta.append({"ph": "M", "name": "thread_sort_index",
                     "pid": process_index, "tid": dt,
                     "args": {"sort_index": dt - _DEVICE_TID_BASE}})
    for tid, idx in tid_index.items():
        meta.append({"ph": "M", "name": "thread_name", "pid": process_index,
                     "tid": idx,
                     "args": {"name": tnames.get(tid, f"thread {tid}")}})
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "bst": {"schema": SCHEMA, "process_index": process_index,
                "process_count": process_count, "recorded": recorded,
                "dropped": dropped},
    }


def dump(path: str) -> str:
    """Write the ring's CURRENT contents as Perfetto JSON to ``path``
    WITHOUT stopping the recorder — :func:`export` copies the buffer
    under the ring lock, so the snapshot is consistent while events keep
    flowing (the on-demand ``bst trace-dump`` path; :func:`finalize` is
    the end-of-run variant that also stops recording)."""
    from . import events as _events

    pi, pc = _events.world()
    doc = export(pi, pc)
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    # pid AND thread id: two concurrent daemon-op dumps to one path must
    # not interleave into a shared temp file
    tmp = f"{path}.tmp{os.getpid()}-{threading.get_ident()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, default=str)
        f.write("\n")
    os.replace(tmp, path)   # a live dump must never expose a torn file
    return path


def dump_live(path: str) -> str:
    """:func:`dump` with an explicit not-recording error — the daemon op
    / CLI surface of the on-demand flight-recorder snapshot."""
    if not _STATE["enabled"]:
        raise RuntimeError(
            "flight recorder is not recording — enable it with --trace / "
            "BST_TRACE=1 (the serve daemon records always)")
    return dump(path)


def finalize(dir_hint: str | None = None) -> str | None:
    """Write the trace (if recording) and stop. Output resolution:
    explicit ``configure(path=)`` > the ``BST_TRACE_PATH`` knob >
    ``dir_hint`` (the telemetry dir, when a run has one) >
    ``./bst-trace.json``. Idempotent — returns the path, or None when
    nothing was recording."""
    from . import events as _events

    if not _STATE["enabled"]:
        return None
    path = _STATE["path"] or config.get_str("BST_TRACE_PATH")
    if path is None:
        pi, pc = _events.world()
        path = os.path.join(dir_hint, trace_name(pi, pc)) if dir_hint \
            else os.path.abspath("bst-trace.json")
    path = dump(path)
    with _lock:
        _STATE["enabled"] = False
        _STATE["buf"] = None
        _STATE["last_path"] = path
    return path


# -- multihost fold ---------------------------------------------------------

def _barrier_exits(doc: dict) -> dict[tuple, float]:
    """(barrier stage, occurrence index FROM THE END) -> exit timestamp
    (µs). Barrier EXITS are the alignment anchor: every process leaves
    ``sync_global_devices`` together, so equal-keyed exits mark the same
    wall-clock instant regardless of per-host clock skew. Occurrences are
    indexed from the tail (-1 = last) because ring overflow keeps the
    NEWEST events — processes that dropped different numbers of early
    barriers still pair their surviving tails correctly."""
    per_stage: dict = {}
    for ev in doc.get("traceEvents", ()):
        if ev.get("name") == "barrier" and ev.get("ph") == "E":
            stage = (ev.get("args") or {}).get("stage")
            per_stage.setdefault(stage, []).append(float(ev["ts"]))
    return {(stage, i - len(ts)): t
            for stage, ts in per_stage.items()
            for i, t in enumerate(ts)}


class MergedTracePath(str):
    """The merged-trace output path, carrying the merged ``bst`` metadata
    as ``.bst`` so callers (telemetry-merge) need not re-parse the — for
    a pod run, potentially very large — file they just wrote."""

    bst: dict


def merge_traces(directory: str,
                 output: str | None = None) -> MergedTracePath | None:
    """Fold per-process ``trace-*.json`` files into one
    ``merged-trace.json``, aligning each process's clock to process 0 via
    the shared barrier exit events; returns the output path (a str
    subclass exposing the merged metadata as ``.bst``) or None when the
    directory has no traces."""
    import glob as _glob

    paths = sorted(_glob.glob(os.path.join(directory, "trace-*-of-*.json")))
    if not paths:
        return None
    docs = []
    for p in paths:
        with open(p, encoding="utf-8") as f:
            docs.append(json.load(f))
    docs.sort(key=lambda d: d.get("bst", {}).get("process_index", 0))
    ref = _barrier_exits(docs[0])
    merged: list = []
    offsets: dict[int, float] = {}
    unaligned: list[int] = []
    for doc in docs:
        pid = doc.get("bst", {}).get("process_index", 0)
        off = 0.0
        if doc is not docs[0]:
            own = _barrier_exits(doc)
            deltas = sorted(ref[k] - own[k] for k in ref if k in own)
            if deltas:
                off = deltas[len(deltas) // 2]   # median: straggler-robust
            else:
                unaligned.append(pid)
        offsets[pid] = round(off, 1)
        for ev in doc.get("traceEvents", ()):
            if off and "ts" in ev:
                ev = {**ev, "ts": round(ev["ts"] + off, 1)}
            merged.append(ev)
    out = output or os.path.join(directory, "merged-trace.json")
    # recorded/dropped totals ride along so trace-report on the merged
    # file still surfaces ring overflow — drops are never silent
    bst = {"schema": MERGED_SCHEMA,
           "process_count": len(docs),
           "recorded": sum(int(d.get("bst", {}).get("recorded") or 0)
                           for d in docs),
           "dropped": sum(int(d.get("bst", {}).get("dropped") or 0)
                          for d in docs),
           "clock_offsets_us": offsets,
           "unaligned_processes": unaligned}
    with open(out, "w", encoding="utf-8") as f:
        json.dump({"traceEvents": merged, "displayTimeUnit": "ms",
                   "bst": bst}, f, default=str)
        f.write("\n")
    res = MergedTracePath(out)
    res.bst = bst
    return res

"""Central registry of every ``BST_*`` runtime knob.

The Spark reference centralizes tuning in spark-defaults / ``--conf``;
here the equivalent surface grew organically as ~22 scattered
``os.environ`` reads, two of them frozen at import time (io/uris.py) so
setting them after import was silently ignored. This module is now the
ONLY place in the package allowed to touch ``os.environ`` for ``BST_*``
names — ``bst lint`` (analysis/) machine-checks that — and every knob is
declared exactly once with its type, default and documentation.

Reads go through :func:`get` (or the typed wrappers) and hit the
environment at CALL time, so tests and long-lived processes can retune
without re-importing, and ``bst`` subprocesses launched with a mutated
environment behave the way the caller expects. Unparseable values fall
back to the declared default (a typo'd budget must not crash a pod run
mid-stage), matching the historical behavior of the inline reads.

Above the environment sits a PER-CONTEXT override layer
(:func:`overrides`): a ``contextvars``-scoped dict of raw knob strings
consulted before ``os.environ``. This is how the ``bst serve`` daemon
gives each resident job its own configuration — N concurrent jobs in one
process cannot share a mutable ``os.environ`` (mutating it from a job
leaks into every other job; the ``env-mutation`` lint check bans exactly
that). Override values parse with the SAME rules as environment strings,
and :mod:`utils.threads` carries the context into worker threads so a
job's pools and device workers see the job's values, not the daemon's.

``bst config`` renders :func:`resolve` — every knob, its resolved value,
and whether it came from an override, the environment or the default —
which is also what ``bst env`` embeds so diagnostics always show the
full surface.
"""

from __future__ import annotations

import contextvars
import os
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any

# explicit falsy spellings for bool knobs: anything else set-and-nonempty
# is truthy, so a stray BST_PAIR_SHARD=2 or =true cannot silently flip a
# feature OFF (the failure mode called out at parallel/pairsched.py)
_FALSY = frozenset({"0", "false", "no", "off"})


@dataclass(frozen=True)
class Tunable:
    """Autotune metadata for a knob the ``bst tune`` searcher may move.

    ``lo``/``hi`` bound numeric (int/bytes) knobs; ``scale`` says how the
    searcher steps between candidates (``pow2`` halves/doubles, ``linear``
    adds/subtracts ``step``). bool knobs need no bounds (the candidate set
    is the flip) and str knobs draw candidates from the knob's declared
    ``choices``. Declaring a Tunable is a statement of SAFETY, not value:
    every value in range must be performance-only — it may never change
    job output bytes (tests/test_tune.py asserts this for the profile
    application path)."""

    lo: int | float | None = None
    hi: int | float | None = None
    scale: str = "pow2"
    step: int | float = 1

    def as_dict(self) -> dict:
        return {"lo": self.lo, "hi": self.hi, "scale": self.scale,
                "step": self.step}


@dataclass(frozen=True)
class Knob:
    """One declared ``BST_*`` variable.

    ``kind`` drives parsing: ``str`` verbatim, ``int`` via int(), ``bytes``
    via int(float()) clamped >= 0 (accepts "2e9"), ``bool`` via the
    explicit-falsy rule above. ``consumer`` records which layer reads it:
    ``runtime`` (this package), ``wrapper`` (the ./install shell wrappers),
    ``bench`` (bench.py / scripts), ``tests`` (the pytest suite) —
    non-runtime knobs are declared so docs, ``bst config`` and the
    doc-drift test cover the whole surface, not because the package reads
    them. ``tunable`` marks knobs the ``bst tune`` autotuner may search
    (performance-only knobs with safe kind-aware bounds)."""

    name: str
    kind: str
    default: Any
    doc: str
    consumer: str = "runtime"
    choices: tuple[str, ...] | None = None
    tunable: Tunable | None = None


KNOBS: dict[str, Knob] = {}


def _knob(name: str, kind: str, default, doc: str, *,
          consumer: str = "runtime", choices=None, tunable=None) -> None:
    if name in KNOBS:
        raise ValueError(f"knob {name} declared twice")
    KNOBS[name] = Knob(name, kind, default, doc, consumer,
                       tuple(choices) if choices else None, tunable)


def tunable_knobs() -> dict[str, Knob]:
    """The declared-tunable subset of the registry, for `bst tune`."""
    return {n: k for n, k in KNOBS.items() if k.tunable is not None}


# -- IO / caching ----------------------------------------------------------
_knob("BST_NATIVE_IO", "bool", True,
      "Use the native C++ chunk codec (zstd/lz4/raw N5 + zarr v2) for "
      "GIL-free reads/writes when built; 0 forces tensorstore.")
_knob("BST_CHUNK_CACHE_BYTES", "bytes", 1 << 30,
      "Byte budget of the process-wide decoded-chunk LRU cache "
      "(io/chunkcache.py); 0 disables caching entirely.",
      tunable=Tunable(lo=64 << 20, hi=16 << 30))
_knob("BST_TILE_CACHE_BYTES", "bytes", int(2e9),
      "Byte budget of the HBM-resident composite fusion tile cache keyed "
      "by dataset signature + write generation; 0 disables.",
      tunable=Tunable(lo=64 << 20, hi=32 << 30))
_knob("BST_WRITE_THREADS", "int", 8,
      "Concurrent writer threads for the pipelined device-volume drain "
      "(fusion full-res + epilogue pyramid slabs). ~8 MB slabs over ~8 "
      "streams measured best on the wire-limited link; h5py containers "
      "always clamp to 1 (single-writer rule).",
      tunable=Tunable(lo=1, hi=64))
_knob("BST_S3_REGION", "str", None,
      "Default AWS region for s3:// roots (the reference's --s3Region); "
      "io.uris.set_s3_region() overrides at runtime.")
_knob("BST_S3_ENDPOINT", "str", None,
      "Custom S3-protocol endpoint (MinIO / on-prem stores / test fakes); "
      "io.uris.set_s3_endpoint() overrides at runtime.")
_knob("BST_REMOTE_CACHE", "str", "run",
      "Decoded-chunk LRU eligibility of REMOTE object stores (s3/gs). "
      "'run' (default) caches their chunks keyed by a per-run pin plus "
      "the dataset metadata object's content signature — coherent "
      "against this process's own writes (generation-bump invalidation) "
      "and against any store mutation that rewrites the metadata object; "
      "an external process mutating chunk objects mid-run is outside the "
      "contract (documented coherence window, README 'Configuration'). "
      "'off' restores the historical bypass bit-identically.",
      choices=("run", "off"))
_knob("BST_PREFETCH_BYTES", "bytes", 256 << 20,
      "Byte budget of the async chunk prefetcher (io/prefetch.py): the "
      "mesh/pairsched/dag drivers enqueue their known FUTURE work items' "
      "source boxes and a small thread pool fetches them into the "
      "decoded-chunk LRU ahead of the consumer, bounded by this many "
      "fetched-but-unconsumed bytes. 0 disables prefetch entirely "
      "(drivers take the exact pre-prefetch paths).",
      tunable=Tunable(lo=32 << 20, hi=8 << 30))
_knob("BST_PREFETCH_THREADS", "int", 4,
      "Worker threads of the async chunk prefetcher; 0 disables prefetch "
      "like BST_PREFETCH_BYTES=0.",
      tunable=Tunable(lo=1, hi=32))
_knob("BST_DISK_TIER_BYTES", "bytes", 0,
      "Byte budget of the NVMe/local-disk spill tier under the decoded-"
      "chunk LRU (io/disktier.py): entries the memory LRU evicts under "
      "budget pressure spill to a run-scoped local directory and promote "
      "back on hit instead of re-fetching from the (possibly remote) "
      "store. 0 (default) disables the tier bit-identically.",
      tunable=Tunable(lo=256 << 20, hi=1 << 40))
_knob("BST_DISK_TIER_DIR", "str", None,
      "Directory of the disk spill tier (put it on local NVMe). Default: "
      "a bst-disktier-<pid> directory under the system temp dir, removed "
      "at process exit.")
_knob("BST_UPLOAD_THREADS", "int", 8,
      "Concurrent upload workers for direct writes to REMOTE object "
      "stores (s3/gs): a multi-chunk box splits per storage chunk and "
      "the chunk puts run through a bounded pool with retry/backoff "
      "(parallel/retry.py) instead of one serialized tensorstore write. "
      "0 or 1 restores the single serialized write path.",
      tunable=Tunable(lo=1, hi=64))

# -- device memory / dispatch windows --------------------------------------
_knob("BST_INFLIGHT_BYTES", "bytes", None,
      "Process-wide byte budget for dispatched-but-undrained device work "
      "(utils/devicemem.py). Default: derived from the backend's "
      "memory_stats (60% of free HBM), 2e9 where the runtime reports "
      "nothing (XLA:CPU).",
      tunable=Tunable(lo=128 << 20, hi=64 << 30))
_knob("BST_PAIR_INFLIGHT_BYTES", "bytes", None,
      "PER-DEVICE byte budget for a pair stage's in-flight work "
      "(stitching PCM, descriptor/intensity matching). Default: each "
      "device's own memory_stats-derived budget.",
      tunable=Tunable(lo=64 << 20, hi=64 << 30))
_knob("BST_DEVICE_TILE_BUDGET", "bytes", int(4e9),
      "Device-residency budget for the whole-volume composite fusion "
      "path (tiles + f32 accumulators must fit or the driver falls back "
      "to the per-block path).")
_knob("BST_PER_DEV_BUDGET", "bytes", int(1e9),
      "Per-device staging budget the fusion drivers use to pack several "
      "blocks per dispatch (per_dev).")
_knob("BST_EARLY_DISPATCH", "bool", True,
      "Allow the sharded work loop to dispatch batches ahead of the one "
      "currently draining; 0 forces strict one-batch-at-a-time.",
      tunable=Tunable())
_knob("BST_PAIR_SHARD", "bool", True,
      "Spread the pair-parallel stages over every local device "
      "(parallel/pairsched.py); 0 pins them to one device.",
      tunable=Tunable())

# -- kernels ---------------------------------------------------------------
_knob("BST_DOG_BLUR", "str", "auto",
      "DoG blur strategy: fft (rfftn transfer multiply, the CPU win) or "
      "gemm (Toeplitz matmuls on the MXU); auto picks per backend.",
      choices=("auto", "fft", "gemm"), tunable=Tunable())
_knob("BST_FUSED_DETECT", "bool", True,
      "Compile DoG detection + descriptor extraction into ONE per-block "
      "jitted program when a detection run requests descriptors "
      "(models/detection.py): peaks never leave HBM between detect and "
      "extract. 0 runs the staged two-dispatch path (bitwise-equal "
      "output, one extra kernel round-trip per block).",
      tunable=Tunable())

# -- global solvers (ops/solve.py) -----------------------------------------
_knob("BST_SOLVE_DEVICE", "bool", True,
      "Run the global registration relaxation and the intensity "
      "coefficient solve as jit-compiled device iteration (one "
      "lax.while_loop per solve, float64); 0 restores the host numpy "
      "reference path. Both paths share convergence semantics and agree "
      "to ≤1e-6 (documented in tests/test_solve_device.py).")
_knob("BST_SOLVE_SHARD", "int", 500000,
      "Point-row threshold above which a device solve shards its link "
      "rows across all local devices (rows grouped by owner tile via "
      "pairsched cost-weighted placement, per-sweep segment moments "
      "reduced with psum over the 1-D solve mesh axis). Sharded and "
      "single-device solves are bit-identical. 0 disables sharding.")
_knob("BST_SOLVE_GLOBAL", "str", "auto",
      "Span the sharded solve's 1-D links axis across ALL processes' "
      "devices instead of only the local ones (the global solve mesh). "
      "auto enables it exactly when the jax world has >1 process; 1 "
      "forces the global mesh (requires an initialized multi-process "
      "runtime); 0 pins the solve mesh to local devices. Owner-tile row "
      "grouping makes the cross-host psum exact, so global and "
      "single-host solves are bit-identical.",
      choices=("auto", "1", "0"))

# -- multi-host runtime ----------------------------------------------------
_knob("BST_COORDINATOR", "str", None,
      "host:port of process 0 for jax.distributed multi-host init "
      "(scripts/pod_launch.sh sets it).")
_knob("BST_NUM_PROCESSES", "int", None,
      "World size of the multi-host runtime; also the event-log filename "
      "fallback before backend init.")
_knob("BST_PROCESS_ID", "int", None,
      "This process's rank in the multi-host runtime; event-log filename "
      "fallback before backend init.")
_knob("BST_DISTRIBUTED", "bool", False,
      "On autodetecting platforms (Cloud TPU pods, SLURM): let "
      "jax.distributed.initialize() discover the topology.")
_knob("BST_PAIR_MULTIHOST", "str", "auto",
      "Split the pair-parallel stages (stitching PCM, descriptor and "
      "intensity matching) across the processes of a multi-host world "
      "before the local LPT device placement. auto enables the split "
      "exactly when the jax world has >1 process (every rank computes "
      "its cost-weighted slice, results allgather back so every rank "
      "returns the full list); 1 forces it; 0 keeps every rank "
      "computing every pair.",
      choices=("auto", "1", "0"))

# -- telemetry -------------------------------------------------------------
_knob("BST_TELEMETRY_DIR", "str", None,
      "Telemetry output directory for bench.py runs (CLI tools take "
      "--telemetry-dir instead).", consumer="bench")
_knob("BST_TRACE", "bool", False,
      "Enable the timeline flight recorder without the --trace CLI flag "
      "(bench.py and scripted runs); the trace archives next to the run "
      "manifest when telemetry is on.")
_knob("BST_TRACE_BUFFER_BYTES", "bytes", 64 << 20,
      "Byte budget of the --trace flight-recorder ring buffer "
      "(observe/trace.py); overflow keeps the NEWEST events and counts "
      "drops in bst_trace_events_dropped_total.")
_knob("BST_TRACE_PATH", "str", None,
      "Explicit output path for the --trace Perfetto JSON. Default: "
      "trace-{process}.json in the telemetry dir when one is set, else "
      "./bst-trace.json.")
_knob("BST_METRICS_PORT", "int", 0,
      "TCP port of the embedded live HTTP exporter (observe/httpexport.py: "
      "/metrics Prometheus text, /healthz liveness, /status + /jobs JSON) "
      "on BST_METRICS_HOST; 0 disables. The `bst serve` daemon and long "
      "one-shot runs both honor it; `bst serve --metrics-port 0` asks the "
      "OS for a free port instead.")
_knob("BST_METRICS_HOST", "str", "127.0.0.1",
      "Bind address of the live HTTP exporter. The default keeps the "
      "plane host-local; a pod's rank-0 exporter sets 0.0.0.0 (or a "
      "specific interface) so dashboards can scrape the aggregated view "
      "from outside the host. The exporter has NO auth — only widen the "
      "bind on a trusted network (see README 'Live monitoring').")
_knob("BST_TELEMETRY_RELAY", "str", None,
      "host:port of the pod telemetry collector (observe/relay.py). When "
      "set, rank 0 of a multi-process world (and any `bst serve` daemon) "
      "hosts the collector at that address and every other process pushes "
      "periodic metric snapshots, health heartbeats and warn/error events "
      "to it over TCP, so the rank-0 live plane (/metrics /healthz "
      "/cluster, `bst top --cluster`) covers the whole pod. Unset (the "
      "default) the relay is fully off: zero overhead, byte-identical "
      "telemetry.")
_knob("BST_RELAY_INTERVAL_S", "float", 2.0,
      "Seconds between a relay push client's metric-snapshot heartbeats. "
      "Must be comfortably below BST_STALL_TIMEOUT_S, past which a "
      "silent rank flips the pod /healthz to 503.")
_knob("BST_RELAY_QUEUE", "int", 256,
      "Bounded length of the relay client's outbound message queue. A "
      "slow or absent collector fills it and further messages drop (and "
      "count in bst_relay_dropped_total) — the producing rank's hot path "
      "never blocks on telemetry.",
      tunable=Tunable(lo=64, hi=8192))
_knob("BST_HISTORY_DIR", "str", None,
      "Directory of the cross-run manifest history store "
      "(observe/history.py): every finalized run/job manifest appends a "
      "compact record there for `bst history` / `bst perf-diff` (and, "
      "eventually, `bst tune` replay). Unset disables recording.")

# -- serve daemon ----------------------------------------------------------
_knob("BST_SERVE_SOCKET", "str", None,
      "Unix-domain socket path of the `bst serve` daemon (`bst submit` / "
      "`bst jobs` / `bst cancel` connect here). Default: "
      "bst-serve-<uid>.sock in the system temp dir.")
_knob("BST_SERVE_SLOTS", "int", 2,
      "Concurrent job slots of the `bst serve` daemon. Per-job byte-window "
      "budgets (BST_INFLIGHT_BYTES / BST_PAIR_INFLIGHT_BYTES) split by this "
      "count unless the job overrides them, so concurrent jobs share the "
      "derived HBM windows instead of each claiming the whole budget.")
_knob("BST_SERVE_IDLE_TIMEOUT", "int", 0,
      "Seconds of no connections AND no jobs after which a `bst serve` "
      "daemon exits on its own (0 = run until shutdown). CI smoke runs "
      "set it so a crashed client can never leak a resident daemon.")
_knob("BST_STALL_TIMEOUT_S", "int", 300,
      "Stall watchdog threshold of the `bst serve` daemon: a RUNNING job "
      "whose stage.progress has not advanced for this many seconds is "
      "flagged `stalled` (bst_serve_jobs_stalled gauge, a job.stall warn "
      "event on its sink, non-200 /healthz) until progress resumes or it "
      "is cancelled. 0 disables the watchdog.")
_knob("BST_PROFILE_AUTO", "bool", False,
      "Let the `bst serve` daemon resolve the best matching tuned profile "
      "(BST_HISTORY_DIR/profiles.json, written by `bst tune run`) for "
      "every submitted job that does not name one — the always-on "
      "equivalent of `bst submit --profile auto`. Profile knobs apply "
      "through per-job config.overrides(), under any explicit --set.")

# -- streaming stage-DAG executor (dag/) -----------------------------------
_knob("BST_DAG_EXCHANGE_BYTES", "bytes", 256 << 20,
      "Byte budget of the block-exchange ledger between a streaming "
      "pipeline's producer and consumer stages (dag/stream.py): a "
      "producer whose published-but-unconsumed blocks exceed this stalls "
      "until consumers catch up (unless a consumer is starved waiting "
      "for unpublished blocks — then the producer always proceeds). "
      "0 disables backpressure. Full in-memory elision additionally "
      "needs BST_CHUNK_CACHE_BYTES >= this budget, or evicted handoff "
      "chunks fall back to a container decode.",
      tunable=Tunable(lo=32 << 20, hi=8 << 30))
_knob("BST_DAG_EXCHANGE_ADDR", "str", None,
      "Comma-separated, rank-ordered host:port list of the cross-host "
      "block-exchange endpoints (dag/exchange.py) — entry i is where "
      "rank i serves the blocks its producer stages write. When set in "
      "a multi-process world, `bst pipeline` runs multi-host: a "
      "consumer stage on one rank can read an edge produced on another "
      "(the gated read fetches the covering chunks once over TCP into "
      "the local decoded-chunk LRU, accounted as "
      "bst_dag_xhost_bytes_total). Unset, pipelines stay single-process "
      "and remote edges are an error.")
_knob("BST_DAG_HANDOFF_BYTES", "bytes", 0,
      "Byte budget of the DEVICE-resident (HBM) handoff cache between a "
      "streaming pipeline's producer and consumer stages (dag/stream.py): "
      "a producer publishing device arrays keeps its covered chunks in "
      "HBM and the consumer's gated read is served as device arrays with "
      "zero D2H + zero container decode; over budget the oldest chunks "
      "spill to the host decoded-chunk LRU (backpressure semantics are "
      "unchanged — spilled chunks still count as published). 0 disables "
      "the device tier bit-identically (publishers drain to host as "
      "before).",
      tunable=Tunable(lo=64 << 20, hi=8 << 30))

# -- install wrappers ------------------------------------------------------
_knob("BST_DEVICES", "int", None,
      "Virtual CPU mesh size (xla_force_host_platform_device_count) "
      "exported by the ./install shell wrappers — the local[N] analogue.",
      consumer="wrapper")

# -- bench.py --------------------------------------------------------------
_knob("BST_BENCH_DIR", "str", "/tmp/bst_bench",
      "Fixture/working directory for bench.py.", consumer="bench")
_knob("BST_BENCH_TILE", "int", None,
      "Override the primary bench config's tile edge (e.g. 384 runs "
      "(384,384,192) tiles).", consumer="bench")
_knob("BST_BENCH_CHILD_TIMEOUT", "int", 1500,
      "Per-child-process timeout (s) for bench.py subprocess runs.",
      consumer="bench")
_knob("BST_BENCH_DEVICE_TIMEOUT", "int", 300,
      "Accelerator-probe timeout (s) for bench.py.", consumer="bench")
_knob("BST_BENCH_RUNS", "int", 5,
      "Fusion benchmark repetitions per config.", consumer="bench")
_knob("BST_BENCH_FRESH_BASELINE", "bool", True,
      "Re-measure numpy/tensorstore baselines inside every bench run; 0 "
      "reuses BASELINE_MEASURED.json.", consumer="bench")
_knob("BST_BENCH_PARTIAL", "str", None,
      "Path where a bench child process streams partial results "
      "(set by the bench parent).", consumer="bench")
_knob("BST_BENCH_CHILD", "bool", False,
      "Marks a bench subprocess (set by the bench parent).",
      consumer="bench")
_knob("BST_BENCH_TPU_ONLY", "bool", False,
      "Fail the bench run instead of falling back to CPU when the "
      "accelerator is unreachable.", consumer="bench")

# -- test suite ------------------------------------------------------------
_knob("BST_TEST_TPU", "bool", False,
      "Run the pytest suite against the real TPU instead of the forced "
      "8-device virtual CPU mesh (tests/conftest.py).", consumer="tests")
_knob("BST_BIG_TESTS", "bool", False,
      "Enable the slow large-N scaling tests (e.g. the 1e5-descriptor "
      "matcher case).", consumer="tests")


# -- per-context override layer --------------------------------------------
# Raw knob strings layered OVER the environment for the current
# contextvars context: the serve daemon's per-job configuration isolation
# (each job reads its own values, no process-env mutation, worker threads
# inherit via utils.threads). Values are stored as the same raw strings
# the environment would carry, so parsing/fallback semantics are
# identical; None masks an environment value back to the declared default.
_OVERRIDES: contextvars.ContextVar[dict[str, str | None] | None] = \
    contextvars.ContextVar("bst-config-overrides", default=None)


def validate_overrides(mapping: dict) -> dict[str, str | None]:
    """Normalize an override mapping: every key must be a declared knob
    (raises KeyError otherwise — an undeclared override is a typo that
    would otherwise silently do nothing), values become raw strings
    (bools as the canonical "1"/"0"), None stays None (mask-to-default)."""
    out: dict[str, str | None] = {}
    for name, v in mapping.items():
        if name not in KNOBS:
            raise KeyError(f"override for undeclared knob {name!r} — "
                           f"declare it in config.py first")
        if v is None:
            out[name] = None
        elif isinstance(v, bool):
            out[name] = "1" if v else "0"
        else:
            out[name] = str(v)
    return out


@contextmanager
def overrides(mapping: dict | None):
    """Layer ``mapping`` (knob name -> raw value) over the environment for
    the duration of the ``with`` block in THIS context. Nested scopes
    stack (inner wins); worker threads spawned through utils.threads see
    the caller's layered view. An empty/None mapping is a no-op scope."""
    cur = _OVERRIDES.get() or {}
    token = _OVERRIDES.set({**cur, **validate_overrides(mapping or {})})
    try:
        yield
    finally:
        _OVERRIDES.reset(token)


def current_overrides() -> dict[str, str | None]:
    """The active override layer (flattened), for diagnostics and for
    handing a job's configuration across process boundaries."""
    return dict(_OVERRIDES.get() or {})


def raw_value(name: str) -> str | None:
    """The override-or-environment string for a DECLARED knob (KeyError
    otherwise); unset and set-but-empty both read as None. The package's
    single ``BST_*`` environment touchpoint."""
    knob = KNOBS[name]
    ov = _OVERRIDES.get()
    if ov is not None and knob.name in ov:
        v = ov[knob.name]
        return None if v is None or v == "" else v
    v = os.environ.get(knob.name)
    return None if v is None or v == "" else v


def _parse(knob: Knob, raw: str):
    if knob.kind == "str":
        if knob.choices and raw not in knob.choices:
            # raise like any unparseable value so get() falls back AND
            # source() reports "default" — returning the default here
            # would make `bst config` label the operator's typo as (env)
            raise ValueError(f"{raw!r} not in {knob.choices}")
        return raw
    if knob.kind == "bool":
        return raw.strip().lower() not in _FALSY
    if knob.kind == "int":
        return int(raw)
    if knob.kind == "bytes":
        return max(0, int(float(raw)))
    if knob.kind == "float":
        return float(raw)
    raise AssertionError(f"unknown knob kind {knob.kind}")


def get(name: str):
    """Resolved value of a declared knob, read from the environment at
    call time; unparseable values fall back to the declared default."""
    knob = KNOBS[name]
    raw = raw_value(name)
    if raw is None:
        return knob.default
    try:
        return _parse(knob, raw)
    except (ValueError, TypeError):
        return knob.default


def source(name: str) -> str:
    """Where :func:`get` resolves ``name`` from right now: ``"override"``
    (a config.overrides scope is active for it), ``"env"`` or
    ``"default"`` (unset, empty, masked, or unparseable)."""
    knob = KNOBS[name]
    raw = raw_value(name)
    if raw is None:
        return "default"
    try:
        _parse(knob, raw)
    except (ValueError, TypeError):
        return "default"
    ov = _OVERRIDES.get()
    if ov is not None and knob.name in ov:
        return "override"
    return "env"


# typed wrappers: call sites read as what they mean, and the linter can
# pair each knob with the declared kind
def get_bool(name: str) -> bool:
    v = get(name)
    return bool(v)


def get_int(name: str) -> int | None:
    return get(name)


def get_bytes(name: str) -> int | None:
    return get(name)


def get_str(name: str) -> str | None:
    return get(name)


def get_float(name: str) -> float | None:
    return get(name)


def resolve() -> list[dict]:
    """Every knob with its resolved value — the ``bst config`` payload."""
    out = []
    for name in sorted(KNOBS):
        k = KNOBS[name]
        out.append({
            "name": name,
            "value": get(name),
            "source": source(name),
            "default": k.default,
            "kind": k.kind,
            "consumer": k.consumer,
            "doc": k.doc,
            "tunable": k.tunable.as_dict() if k.tunable else None,
        })
    return out


def describe(verbose: bool = False) -> str:
    """Human-readable resolved-config dump (``bst config`` / ``bst env``).

    One line per knob: name, resolved value, and ``(env)`` /
    ``(override)`` when something overrides the default; ``verbose`` adds
    the docs."""
    lines = []
    for row in resolve():
        mark = ("  (env)" if row["source"] == "env"
                else "  (override)" if row["source"] == "override" else "")
        lines.append(f"{row['name']}={row['value']}{mark}")
        if verbose:
            lines.append(f"    [{row['kind']}, default {row['default']!r}, "
                         f"{row['consumer']}] {row['doc']}")
    return "\n".join(lines)

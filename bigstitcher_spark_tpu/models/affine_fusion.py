"""Affine fusion driver: plan blocks, prefetch patches, run the XLA kernel.

The TPU redesign of SparkAffineFusion's per-block map (reference call stack
SURVEY.md §3.1): the work list is the output block grid (strategy P1); per
block the host finds overlapping views (OverlappingViews.java:28-47),
prefetches the exact source boxes the inverse affine needs
(ViewUtil.findOverlappingBlocks role), buckets shapes, and launches one fused
XLA computation. Writers own disjoint storage chunks; halos are over-read —
both reference invariants preserved.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import jax
import numpy as np

from ..io.chunkstore import Dataset
from ..io.dataset_io import ViewLoader, best_mipmap_level
from ..io.spimdata import SpimData, ViewId
from ..ops import fusion as F
from ..utils.geometry import (
    Interval,
    concatenate,
    invert_affine,
    scale_affine,
    translation_affine,
    transformed_interval,
)
from ..utils.grid import GridBlock, create_grid
from .. import config, observe, profiling
from ..observe import metrics as _metrics

_H2D_BYTES = _metrics.counter("bst_xfer_h2d_bytes_total")
_D2H_BYTES = _metrics.counter("bst_xfer_d2h_bytes_total")
_H2D_SAVED = _metrics.counter("bst_xfer_h2d_bytes_saved_total")
_D2H_SAVED = _metrics.counter("bst_xfer_d2h_bytes_saved_total")
_TILE_HITS = _metrics.counter("bst_tile_cache_hits_total")
_TILE_MISSES = _metrics.counter("bst_tile_cache_misses_total")
_TILE_HIT_BYTES = _metrics.counter("bst_tile_cache_hit_bytes_total")
_TILE_EVICT_BYTES = _metrics.counter("bst_tile_cache_evict_bytes_total")
_EPI_D2H_BYTES = _metrics.counter("bst_epilogue_d2h_bytes_total")
_EPI_WRITE_BYTES = _metrics.counter("bst_epilogue_write_bytes_total")


@dataclass
class BlendParams:
    """Cosine blending configuration (mvrecon FusionTools defaults)."""

    border: tuple[float, float, float] = (0.0, 0.0, 0.0)
    range: tuple[float, float, float] = (40.0, 40.0, 40.0)


@dataclass
class FusionStats:
    voxels: int = 0
    blocks: int = 0
    skipped_empty: int = 0
    seconds: float = 0.0
    compile_keys: set = field(default_factory=set)
    # multiscale-epilogue output, kept SEPARATE from ``voxels`` so
    # full-res-only and pyramid-inclusive rates stay distinguishable
    # (the epilogue must not masquerade as a kernel slowdown — or win)
    pyramid_voxels: int = 0
    pyramid_levels: int = 0


@dataclass(frozen=True)
class PyramidLevel:
    """One downsample pyramid level the fusion drivers may materialize as a
    kernel epilogue while the fused data is still device-resident
    (ROADMAP item 3a), instead of the downsample stage re-reading the
    full-res container. ``rel`` is the factor from the PREVIOUS level,
    ``abs_factor`` from full resolution, ``dims`` the 3-D level extent —
    all straight off the container's ``MultiResolutionLevelInfo``."""

    ds: Dataset
    rel: tuple[int, int, int]
    abs_factor: tuple[int, int, int]
    dims: tuple[int, int, int]


def pyramid_from_mr(store, mr_levels) -> list["PyramidLevel"]:
    """Epilogue spec for a container slot's ``MultiResolutionLevelInfo``
    list (levels 1..n; level 0 is the fusion target itself) — the one
    place the rel/abs/dims unpacking rules live, shared by the CLI
    ``--pyramid`` path and the bench measure that validates it."""
    return [PyramidLevel(
        ds=store.open_dataset(m.dataset.strip("/")),
        rel=tuple(int(v) for v in m.relativeDownsampling[:3]),
        abs_factor=tuple(int(v) for v in m.absoluteDownsampling[:3]),
        dims=tuple(int(v) for v in m.dimensions[:3]),
    ) for m in mr_levels[1:]]


def anisotropy_transform(factor: float) -> np.ndarray:
    """Concatenate (1,1,1/f) scaling into all view models
    (TransformVirtual.adjustAllTransforms, SparkAffineFusion.java:487-491)."""
    if not np.isfinite(factor) or factor == 1.0:
        return None
    return scale_affine((1.0, 1.0, 1.0 / factor))


@dataclass
class _ViewPlan:
    patch_offset: np.ndarray  # (3,) int, level coords
    patch_interval: Interval
    affine: np.ndarray        # (3,4) block idx -> patch coords
    inv_total: np.ndarray     # (3,4) world -> level coords
    img_dim: np.ndarray       # (3,) level image dims
    level: int
    view: ViewId

    @property
    def is_translation(self) -> bool:
        """True when sampling is a pure (sub-pixel) shift — the no-gather
        fast path applies (ops.fusion.fuse_block_shift)."""
        return bool(np.allclose(self.inv_total[:, :3], np.eye(3), atol=1e-7))

    @property
    def is_diagonal(self) -> bool:
        """True when the linear part is axis-aligned (diagonal) — e.g.
        translation-registered tiles under --preserveAnisotropy z-scaling:
        sampling factorizes into three 1-D interpolation GEMMs, no gathers
        (ops.fusion.fuse_block_sep)."""
        lin = self.inv_total[:, :3]
        return bool(np.allclose(lin, np.diag(np.diagonal(lin)), atol=1e-7))


def plan_block(
    sd: SpimData,
    loader: ViewLoader,
    views: list[ViewId],
    block_global: Interval,
    anisotropy: np.ndarray | None,
) -> list[_ViewPlan]:
    """Find views overlapping this output block and their needed source boxes."""
    plans: list[_ViewPlan] = []
    for v in views:
        model = sd.model(v)
        if anisotropy is not None:
            model = concatenate(anisotropy, model)
        factors = loader.downsampling_factors(v.setup)
        level = best_mipmap_level(factors, (1.0, 1.0, 1.0))
        mip = loader.mipmap_transform(v.setup, level)
        total = concatenate(model, mip)  # level coords -> world
        inv_total = invert_affine(total)
        src = transformed_interval(inv_total, block_global).expand(1)
        img_shape = loader.open(v, level).shape
        img_iv = Interval.from_shape(img_shape)
        # +2 px tolerance like OverlappingViews (fusion/OverlappingViews.java:28-47)
        if not src.overlaps(img_iv.expand(2)):
            continue
        clipped = src.intersect(img_iv)
        if clipped.is_empty():
            continue
        patch_offset = np.array(clipped.min, dtype=np.float64)
        aff = concatenate(
            translation_affine(-patch_offset),
            concatenate(inv_total, translation_affine(block_global.min)),
        )
        plans.append(
            _ViewPlan(
                patch_offset=np.array(clipped.min, dtype=np.int64),
                patch_interval=clipped,
                affine=aff,
                inv_total=inv_total,
                img_dim=np.array(img_shape, dtype=np.float64),
                level=level,
                view=v,
            )
        )
    return plans


def fuse_grid_block(
    sd: SpimData,
    loader: ViewLoader,
    views: list[ViewId],
    block: GridBlock,
    bbox: Interval,
    fusion_type: str = "AVG_BLEND",
    blend: BlendParams | None = None,
    anisotropy: np.ndarray | None = None,
    patch_quantum: int = 32,
    compute_block_shape: tuple[int, ...] | None = None,
    stats: FusionStats | None = None,
    inside_offset: tuple[float, float, float] = (0.0, 0.0, 0.0),
    coefficients: dict[ViewId, np.ndarray] | None = None,
) -> tuple[np.ndarray, np.ndarray] | None:
    """Fuse one grid block. Returns (fused f32, weight f32) arrays of
    ``block.size``, or None when no view overlaps (block left empty —
    reference skips saving empty blocks).

    ``coefficients``: optional per-view (cx,cy,cz,2) intensity-correction
    grids (BlkAffineFusion.initWithIntensityCoefficients role); forces the
    general gather kernel."""
    blend = blend or BlendParams()
    bshape = tuple(compute_block_shape or block.size)
    block_global = Interval.from_shape(bshape, block.offset).translate(bbox.min)
    plans = plan_block(sd, loader, views, block_global, anisotropy)
    if not plans:
        return None

    if coefficients is None and all(p.is_translation for p in plans):
        return _fuse_shift_path(
            loader, plans, block, block_global, bshape, fusion_type, blend,
            stats, inside_offset,
        )

    if coefficients is None and all(p.is_diagonal for p in plans):
        return _fuse_sep_path(
            sd, loader, plans, block, bshape, fusion_type, blend, stats,
            inside_offset, patch_quantum,
        )

    vb = F.bucket_views(len(plans))
    pshape = F.bucket_shape(
        np.max([p.patch_interval.shape for p in plans], axis=0), patch_quantum
    )
    (patches, affines, offsets, img_dims, borders, ranges, valid, ioffs,
     coeffs, coeff_affs) = _gather_inputs(
        sd, loader, plans, pshape, vb, blend, inside_offset, coefficients)

    if stats is not None:
        stats.compile_keys.add((bshape, pshape, vb, fusion_type,
                                coefficients is not None))
    with profiling.span("fusion.kernel", item=tuple(map(int, block.offset))):
        fused, wsum = F.fuse_block(
            patches, affines, offsets, img_dims, borders, ranges, valid,
            block_shape=bshape, fusion_type=fusion_type, inside_offs=ioffs,
            coeffs=coeffs, coeff_affines=coeff_affs,
        )
        fused, wsum = jax.device_get((fused, wsum))
    # crop the static compute shape back to the (possibly clipped) block
    sl = tuple(slice(0, s) for s in block.size)
    return fused[sl], wsum[sl]


def _coeff_grid_affine(sd, loader, p, cdims):
    """(3, 4) lpos->grid affine for one view plan: level coords -> grid
    coords with full-res px = f*l + (f-1)/2 and cell centers at
    (k+0.5)*cs - 0.5, cs = view_size/dims (BlkAffineFusion coefficients
    semantics). The one place the convention lives, shared by the
    composite and per-block gather paths so it cannot diverge."""
    f = np.asarray(loader.downsampling_factors(p.view.setup)[p.level],
                   np.float64)
    cs = np.array(sd.view_size(p.view), np.float64) / np.array(cdims)
    aff = np.zeros((3, 4), np.float32)
    aff[:, :3] = np.diag(f / cs)
    aff[:, 3] = ((f - 1) / 2.0 + 0.5) / cs - 0.5
    return aff


def _coeff_digest(coefficients) -> bytes:
    """Content signature of a coefficient set: view identity + grid bytes.
    Any regenerated/reloaded grid (a new solve, a store round-trip after a
    rewrite) hashes differently, so a stale device table can never serve a
    changed solve — the in-memory equivalent of the tile cache's
    (signature, write-generation) key."""
    import hashlib

    h = hashlib.blake2b(digest_size=16)
    for v in sorted(coefficients, key=lambda v: (v.timepoint, v.setup)):
        g = np.ascontiguousarray(coefficients[v], np.float32)
        h.update(np.asarray([v.timepoint, v.setup, *g.shape],
                            np.int64).tobytes())
        h.update(g.tobytes())
    return h.digest()


def _coeff_rows(coefficients) -> dict:
    """Canonical {view: table row} assignment (row 0 is the identity)."""
    views = sorted(coefficients, key=lambda v: (v.timepoint, v.setup))
    return {v: i + 1 for i, v in enumerate(views)}


# One-time device residency for intensity-correction grids: the old
# per-block path re-staged the FULL (vb, Cx,Cy,Cz, 2) grid stack into
# every block's kernel inputs, so identical coefficient bytes re-crossed
# H2D with every fused block. The table uploads once per coefficient-set
# content digest; per-block inputs become a device-side jnp.take.
_COEFF_TABLE_KEEP = 4


def coefficient_table(coefficients):
    """(table, rows): ``table`` a DEVICE (n_views+1, Cx,Cy,Cz, 2) stack
    whose row 0 is the identity map (gain 1, offset 0) for padded/missing
    slots, ``rows`` the {view: row} map. Uploaded at most once per content
    digest (LRU of ``_COEFF_TABLE_KEEP`` sets)."""
    import jax

    dig = _coeff_digest(coefficients)
    with _TILE_CACHE_LOCK:
        ent = _COEFF_TABLE_CACHE.get(dig)
        if ent is not None:
            _COEFF_TABLE_CACHE.move_to_end(dig)
            return ent
    rows = _coeff_rows(coefficients)
    cdims = next(iter(coefficients.values())).shape[:3]
    host = np.zeros((len(rows) + 1, *cdims, 2), np.float32)
    host[..., 0] = 1.0
    for v, r in rows.items():
        host[r] = coefficients[v]
    table = jax.device_put(host)
    _H2D_BYTES.inc(int(table.nbytes))
    with _TILE_CACHE_LOCK:
        _COEFF_TABLE_CACHE[dig] = (table, rows)
        while len(_COEFF_TABLE_CACHE) > _COEFF_TABLE_KEEP:
            _COEFF_TABLE_CACHE.popitem(last=False)
    return table, rows


def register_coefficient_table(coefficients, per_view_dev) -> None:
    """Adopt ALREADY-DEVICE-RESIDENT per-view grids for ``coefficients``
    (the solve→fusion handoff: models.intensity registers the CG solver's
    device output here, reshaped on device, so fusion's first
    :func:`coefficient_table` lookup hits without the grids ever making a
    host->device round trip). ``per_view_dev``: {view: device
    (Cx,Cy,Cz,2)} matching ``coefficients`` bit-for-bit."""
    import jax.numpy as jnp

    rows = _coeff_rows(coefficients)
    if set(rows) != set(per_view_dev):
        return
    cdims = next(iter(coefficients.values())).shape[:3]
    ident = jnp.concatenate(
        [jnp.ones((1, *cdims, 1), jnp.float32),
         jnp.zeros((1, *cdims, 1), jnp.float32)], axis=-1)
    order = sorted(rows, key=rows.get)
    table = jnp.concatenate(
        [ident] + [jnp.asarray(per_view_dev[v],
                               jnp.float32)[None] for v in order], axis=0)
    _H2D_SAVED.inc(int(table.nbytes))  # the upload that never happens
    dig = _coeff_digest(coefficients)
    with _TILE_CACHE_LOCK:
        _COEFF_TABLE_CACHE[dig] = (table, rows)
        while len(_COEFF_TABLE_CACHE) > _COEFF_TABLE_KEEP:
            _COEFF_TABLE_CACHE.popitem(last=False)


def gather_coefficient_inputs(sd, loader, plans, coefficients, nb):
    """Per-block coefficient kernel inputs off the device-resident table:
    a DEVICE (nb, Cx,Cy,Cz, 2) row gather — it rides the work loop's
    device-side batch stacking, so zero grid bytes cross H2D per block —
    plus the tiny host (nb, 3, 4) lpos->grid affines (48 B/view)."""
    import jax.numpy as jnp

    table, rows = coefficient_table(coefficients)
    cdims = tuple(int(s) for s in table.shape[1:4])
    idx = np.zeros((nb,), np.int32)
    coeff_affs = np.zeros((nb, 3, 4), np.float32)
    coeff_affs[:, :, :3] = np.eye(3)
    for i, p in enumerate(plans):
        r = rows.get(p.view)
        if r is None or coefficients.get(p.view) is None:
            continue
        idx[i] = r
        coeff_affs[i] = _coeff_grid_affine(sd, loader, p, cdims)
    coeffs = jnp.take(table, jnp.asarray(idx), axis=0)
    # grid bytes the per-block re-staging path would have re-shipped
    _H2D_SAVED.inc(int(coeffs.nbytes))
    return coeffs, coeff_affs


def patch_dtype(loader, view_levels) -> np.dtype:
    """The staged patch stack's dtype for ``(view, level)`` pairs: the
    stored dtype when every view shares a <=16-bit integer type — patches
    then ship to the device at native width and the kernels cast to
    float32 on device (lossless, halves h2d bytes on wire-limited links)
    — float32 otherwise. Probes are memoized per (view, level) on the
    loader for the whole run."""
    memo = loader.__dict__.setdefault("_patch_dtype_memo", {})
    dts = set()
    for key in view_levels:
        d = memo.get(key)
        if d is None:  # probe once per (view, level) for the whole run
            d = np.dtype(loader.open(*key).dtype).newbyteorder("=")
            memo[key] = d
        dts.add(d)
    if len(dts) == 1:
        d = dts.pop()
        if d.kind in "ui" and d.itemsize <= 2:
            return d
    return np.dtype(np.float32)


def _gather_inputs(sd, loader, plans, pshape, vb, blend, inside_offset,
                   coefficients):
    """Host-side input staging for the general gather kernel: prefetch the
    clipped source boxes and assemble the per-view parameter arrays."""
    patches = np.zeros((vb, *pshape), dtype=patch_dtype(
        loader, [(p.view, p.level) for p in plans]))
    affines = np.zeros((vb, 3, 4), dtype=np.float32)
    offsets = np.zeros((vb, 3), dtype=np.float32)
    img_dims = np.ones((vb, 3), dtype=np.float32)
    borders = np.zeros((vb, 3), dtype=np.float32)
    ranges = np.ones((vb, 3), dtype=np.float32)
    valid = np.zeros((vb,), dtype=np.float32)
    for i, p in enumerate(plans):
        with profiling.span("fusion.prefetch"):
            patches[i] = loader.read_block(
                p.view, p.level, tuple(p.patch_offset), pshape)
        affines[i] = p.affine
        offsets[i] = p.patch_offset
        img_dims[i] = p.img_dim
        factors = loader.downsampling_factors(p.view.setup)[p.level]
        borders[i] = np.asarray(blend.border) / np.asarray(factors, dtype=np.float64)
        ranges[i] = np.asarray(blend.range) / np.asarray(factors, dtype=np.float64)
        valid[i] = 1.0

    coeffs = coeff_affs = None
    if coefficients is not None:
        coeffs, coeff_affs = gather_coefficient_inputs(
            sd, loader, plans, coefficients, vb)
    ioffs = np.tile(np.asarray(inside_offset, np.float32), (vb, 1))
    return (patches, affines, offsets, img_dims, borders, ranges, valid,
            ioffs, coeffs, coeff_affs)


def _shift_inputs(loader, plans, block_global, bshape, vb, blend,
                  inside_offset):
    """Host-side input staging for the translation shifted-slice kernel."""
    pshape = tuple(s + 1 for s in bshape)
    patches = np.zeros((vb, *pshape), dtype=patch_dtype(
        loader, [(p.view, p.level) for p in plans]))
    fracs = np.zeros((vb, 3), dtype=np.float32)
    lpos0 = np.zeros((vb, 3), dtype=np.float32)
    img_dims = np.ones((vb, 3), dtype=np.float32)
    borders = np.zeros((vb, 3), dtype=np.float32)
    ranges = np.ones((vb, 3), dtype=np.float32)
    valid = np.zeros((vb,), dtype=np.float32)
    bg_min = np.asarray(block_global.min, dtype=np.float64)
    for i, p in enumerate(plans):
        tlevel = p.inv_total[:, :3] @ bg_min + p.inv_total[:, 3]
        floor_off = np.floor(tlevel).astype(np.int64)
        with profiling.span("fusion.prefetch"):
            patches[i] = loader.read_block(
                p.view, p.level, tuple(floor_off), pshape)
        fracs[i] = tlevel - floor_off
        lpos0[i] = tlevel
        img_dims[i] = p.img_dim
        factors = loader.downsampling_factors(p.view.setup)[p.level]
        borders[i] = np.asarray(blend.border) / np.asarray(factors, dtype=np.float64)
        ranges[i] = np.asarray(blend.range) / np.asarray(factors, dtype=np.float64)
        valid[i] = 1.0
    ioffs = np.tile(np.asarray(inside_offset, np.float32), (vb, 1))
    return patches, fracs, lpos0, img_dims, borders, ranges, valid, ioffs


def _sep_inputs(sd, loader, plans, pshape, vb, blend, inside_offset):
    """Host-side staging for the diagonal separable kernel: same clipped
    patch prefetch as the gather path, plus the per-view (diag, t) of the
    block-index -> patch-coordinate affine."""
    (patches, affines, offsets, img_dims, borders, ranges, valid, ioffs,
     _c, _ca) = _gather_inputs(sd, loader, plans, pshape, vb, blend,
                               inside_offset, None)
    diags = np.ascontiguousarray(
        np.stack([np.diagonal(affines[i, :, :3]) for i in range(vb)]))
    ts = np.ascontiguousarray(affines[:, :, 3])
    return patches, diags, ts, offsets, img_dims, borders, ranges, valid, ioffs


def _fuse_sep_path(sd, loader, plans, block, bshape, fusion_type, blend,
                   stats, inside_offset=(0.0, 0.0, 0.0), patch_quantum=32):
    """Diagonal-affine blocks (e.g. --preserveAnisotropy over
    translation-registered views): separable interpolation GEMMs, no
    gathers."""
    vb = F.bucket_views(len(plans))
    pshape = F.bucket_shape(
        np.max([p.patch_interval.shape for p in plans], axis=0), patch_quantum)
    (patches, diags, ts, offsets, img_dims, borders, ranges, valid, ioffs
     ) = _sep_inputs(sd, loader, plans, pshape, vb, blend, inside_offset)
    if stats is not None:
        stats.compile_keys.add((bshape, pshape, "sep", vb, fusion_type))
    with profiling.span("fusion.kernel", item=tuple(map(int, block.offset))):
        fused, wsum = F.fuse_block_sep(
            patches, diags, ts, offsets, img_dims, borders, ranges, valid,
            block_shape=bshape, fusion_type=fusion_type, inside_offs=ioffs,
        )
        fused, wsum = jax.device_get((fused, wsum))
    sl = tuple(slice(0, s) for s in block.size)
    return fused[sl], wsum[sl]


def _fuse_shift_path(loader, plans, block, block_global, bshape, fusion_type,
                     blend, stats, inside_offset=(0.0, 0.0, 0.0)):
    """Translation-only blocks: 8-shifted-slice kernel, no gather, one compile
    per (block shape, view bucket)."""
    vb = F.bucket_views(len(plans))
    (patches, fracs, lpos0, img_dims, borders, ranges, valid, ioffs
     ) = _shift_inputs(loader, plans, block_global, bshape, vb, blend,
                       inside_offset)
    if stats is not None:
        stats.compile_keys.add((bshape, "shift", vb, fusion_type))
    with profiling.span("fusion.kernel", item=tuple(map(int, block.offset))):
        fused, wsum = F.fuse_block_shift(
            patches, fracs, lpos0, img_dims, borders, ranges, valid,
            block_shape=bshape, fusion_type=fusion_type, inside_offs=ioffs,
        )
        fused, wsum = jax.device_get((fused, wsum))
    sl = tuple(slice(0, s) for s in block.size)
    return fused[sl], wsum[sl]


def device_tile_budget_bytes() -> int:
    """Composite-path device residency budget, read at call time (the old
    import-time snapshot ignored BST_DEVICE_TILE_BUDGET set after import)."""
    return config.get_bytes("BST_DEVICE_TILE_BUDGET")


@dataclass
class CompositePlan:
    """Host-side plan for the whole-volume composite fusion path: static
    per-view windows/offsets (baked into the compiled program) plus the
    traced per-view parameter arrays."""

    plans: list
    out_shape: tuple
    windows: tuple
    n_offs: tuple
    pad: tuple
    fracs: np.ndarray
    img_dims: np.ndarray
    borders: np.ndarray
    ranges: np.ndarray
    inside_offs: np.ndarray
    coeffs: np.ndarray | None = None       # (V, Cx,Cy,Cz, 2) intensity maps
    coeff_affs: np.ndarray | None = None   # (V, 3, 4) diagonal lpos->grid
    kinds: tuple = ()                      # per-view "shift" | "sep"
    diags: np.ndarray | None = None        # (V, 3) sampling step per axis
    offs: np.ndarray | None = None         # (V, 3) tile coord of output idx 0


def plan_composite_volume(
    sd, loader, views, bbox, anisotropy, blend, masks=False,
    mask_offset=(0.0, 0.0, 0.0), coefficients=None,
) -> CompositePlan | None:
    """Plan the composite device path. None when a view is not a pure
    translation at stored level 0 or the tile stack exceeds the budget."""
    vol_iv = Interval.from_shape(bbox.shape).translate(bbox.min)
    plans = plan_block(sd, loader, views, vol_iv, anisotropy)
    if not plans:
        return None
    if any(not (p.is_translation or p.is_diagonal) or p.level != 0
           for p in plans):
        return None
    if coefficients is not None and any(not p.is_translation for p in plans):
        return None  # coeffs + diagonal views -> per-block path
    if any(not p.is_translation
           and np.any(np.diagonal(p.inv_total[:, :3]) <= 0) for p in plans):
        return None  # mirrored axes: keep the general gather path
    shapes = [tuple(int(s) for s in p.img_dim) for p in plans]
    itemsizes = [np.dtype(loader.open(p.view, 0).dtype).itemsize
                 for p in plans]
    nbytes = sum(int(np.prod(s)) * isz for s, isz in zip(shapes, itemsizes))
    # device residency: tiles + the kernel's full-volume f32 accumulators
    # (acc + wsum + converted output ~= 3x) must fit the budget, or the
    # caller falls back to the per-block path (fuse_grid_block loop).
    # Cached tiles of OTHER datasets/generations also occupy HBM — but a
    # cache must never lock a fitting plan out of the fast path, so
    # foreign residents are EVICTED (LRU-first) to make room rather than
    # counted against the plan (this plan's own cached tiles are the very
    # buffers `nbytes` already prices).
    nbytes += 3 * int(np.prod(bbox.shape)) * 4
    budget = device_tile_budget_bytes()
    if nbytes > budget:
        return None
    own_keys = {k for k in (_tile_cache_key(loader.open(p.view, 0))
                            for p in plans) if k is not None}
    with _TILE_CACHE_LOCK:
        for k in [k for k in _TILE_CACHE if k not in own_keys]:
            if nbytes + _TILE_CACHE_BYTES[0] <= budget:
                break
            _tile_cache_drop_locked(k)

    out_shape = tuple(bbox.shape)
    io_ceil = tuple(int(np.ceil(max(0.0, o))) for o in
                    (mask_offset if masks else (0.0, 0.0, 0.0)))
    # tile pad must cover the window widening from --maskOffset inside-test
    # expansion, or the static corner slices run out of bounds
    pad = tuple(1 + io_ceil[d] for d in range(3))
    windows, n_offs, kinds = [], [], []
    fracs = np.zeros((len(plans), 3), np.float32)
    diags = np.ones((len(plans), 3), np.float32)
    offs = np.zeros((len(plans), 3), np.float32)
    img_dims = np.ones((len(plans), 3), np.float32)
    borders = np.zeros((len(plans), 3), np.float32)
    ranges = np.ones((len(plans), 3), np.float32)
    inside_offs = np.zeros((len(plans), 3), np.float32)
    if masks:
        inside_offs[:] = np.asarray(mask_offset, np.float32)
    bb_min = np.asarray(bbox.min, np.float64)
    for i, p in enumerate(plans):
        # tile coord of output voxel (0,0,0): g = inv_total @ bbox.min
        g = p.inv_total[:, :3] @ bb_min + p.inv_total[:, 3]
        S = shapes[i]
        if p.is_translation:
            kinds.append("shift")
            n = np.floor(g).astype(np.int64)
            f = g - n
            a = tuple(int(max(0, -n[d] - 1 - io_ceil[d])) for d in range(3))
            b = tuple(int(min(out_shape[d], S[d] - n[d] + io_ceil[d]))
                      for d in range(3))
            n_offs.append(tuple(int(v) for v in n))
            fracs[i] = f
        else:
            # diagonal: tile coord at output idx = diag*idx + g; window from
            # the inverse map of the tile extent [-1, S] (+maskOffset slack)
            kinds.append("sep")
            dg = np.diagonal(p.inv_total[:, :3]).astype(np.float64)
            a = tuple(int(max(0, np.floor((-1.0 - io_ceil[d] - g[d]) / dg[d])))
                      for d in range(3))
            b = tuple(int(min(out_shape[d],
                              np.ceil((S[d] + io_ceil[d] - g[d]) / dg[d]) + 1))
                      for d in range(3))
            n_offs.append((0, 0, 0))
            diags[i] = dg
            offs[i] = g
        windows.append((a, b))
        img_dims[i] = p.img_dim
        factors = loader.downsampling_factors(p.view.setup)[p.level]
        borders[i] = np.asarray(blend.border) / np.asarray(factors)
        ranges[i] = np.asarray(blend.range) / np.asarray(factors)
    coeffs = coeff_affs = None
    if coefficients is not None:
        coeffs, coeff_affs = gather_coefficient_inputs(
            sd, loader, plans, coefficients, len(plans))
    return CompositePlan(plans, out_shape, tuple(windows), tuple(n_offs),
                         pad, fracs, img_dims, borders, ranges, inside_offs,
                         coeffs, coeff_affs, tuple(kinds), diags, offs)


# Cross-call device residency for composite-path tiles: repeated fusions
# over the same stored views (best-of bench reps, the --masks double pass,
# parameter sweeps) re-shipped identical tiles up a 70 MB/s wire every
# call. Keys fold the dataset's chunk-cache identity, metadata signature
# AND write-generation (io.chunkcache bumps it on every Dataset.write /
# remove / recreate), so any host-visible mutation orphans the HBM copy;
# orphaned generations of a dataset are purged eagerly when its current
# generation uploads, not just under LRU pressure.
import threading as _threading
from collections import OrderedDict as _OrderedDict

_TILE_CACHE: "_OrderedDict[tuple, object]" = _OrderedDict()
_TILE_CACHE_LOCK = _threading.Lock()
_TILE_CACHE_BYTES = [0]
# device-resident coefficient tables (coefficient_table above); shares the
# tile-cache lock — both are tiny critical sections on the same call paths
_COEFF_TABLE_CACHE: "_OrderedDict[bytes, tuple]" = _OrderedDict()


def _tile_cache_budget() -> int:
    return config.get_bytes("BST_TILE_CACHE_BYTES")


def _tile_cache_key(ds) -> tuple | None:
    """Stable content identity of a stored tile, or None when the dataset
    has no cacheable identity (wrapper datasets, remote stores)."""
    from ..io import chunkcache

    if not (hasattr(ds, "_cache_key") and hasattr(ds, "_cacheable")):
        return None
    if not ds._cacheable():
        return None
    dkey = ds._cache_key()
    return (*dkey, ds._cache_sig(), chunkcache.get_cache().generation(dkey))


def _tile_cache_drop_locked(key) -> None:
    v = _TILE_CACHE.pop(key, None)
    if v is not None:
        _TILE_CACHE_BYTES[0] -= int(v.nbytes)
        _TILE_EVICT_BYTES.inc(int(v.nbytes))


def upload_composite_tiles(loader, cp: CompositePlan) -> list:
    """Stage the plan's tiles in HBM (async device_put per tile), serving
    unchanged tiles from the device-resident cache
    (``BST_TILE_CACHE_BYTES`` budget, 0 disables)."""
    import jax

    budget = _tile_cache_budget()
    from ..io import prefetch as _prefetch

    if _prefetch.enabled():
        # announce every tile read below to the async prefetcher: the
        # upload loop is serial per view, so later views' chunks fetch
        # (and decode into the chunk LRU) while earlier tiles upload
        boxes = []
        for p in cp.plans:
            ds = loader.open(p.view, 0)
            if hasattr(ds, "prefetch_box"):
                boxes.append((ds, (0,) * len(ds.shape),
                              tuple(int(s) for s in ds.shape)))
        _prefetch.submit_boxes(boxes)
    tiles = []
    with profiling.span("fusion.h2d_tiles"):
        h2d = saved = 0
        for p in cp.plans:
            ds = loader.open(p.view, 0)
            key = _tile_cache_key(ds) if budget > 0 else None
            if key is not None:
                with _TILE_CACHE_LOCK:
                    ent = _TILE_CACHE.get(key)
                    if ent is not None:
                        _TILE_CACHE.move_to_end(key)
                if ent is not None:
                    _TILE_HITS.inc()
                    _TILE_HIT_BYTES.inc(int(ent.nbytes))
                    tiles.append(ent)
                    continue
            arr = ds.read_full()
            t = jax.device_put(arr)
            h2d += int(t.nbytes)
            if arr.dtype.kind in "iu" and arr.dtype.itemsize < 4:
                saved += arr.size * 4 - arr.nbytes  # vs a float32 upload
            if key is not None:
                _TILE_MISSES.inc()
                with _TILE_CACHE_LOCK:
                    # purge write-orphaned generations of this dataset NOW
                    # (they could otherwise pin dead HBM until LRU pressure)
                    for stale in [k for k in _TILE_CACHE
                                  if k[:2] == key[:2] and k != key]:
                        _tile_cache_drop_locked(stale)
                    if int(t.nbytes) <= budget:  # oversize: never resident
                        _TILE_CACHE[key] = t
                        _TILE_CACHE_BYTES[0] += int(t.nbytes)
                        while _TILE_CACHE_BYTES[0] > budget and len(_TILE_CACHE) > 1:
                            _tile_cache_drop_locked(next(iter(_TILE_CACHE)))
            tiles.append(t)
        _H2D_BYTES.inc(h2d)
        _H2D_SAVED.inc(saved)
        return tiles


def dispatch_composite(cp: CompositePlan, tiles, fusion_type, out_dtype,
                       masks, min_intensity, max_intensity):
    """Run the compiled composite program; returns the device-resident
    converted output (does not block)."""
    with_coeffs = cp.coeffs is not None
    from ..parallel.mesh import record_compile_bucket

    record_compile_bucket(("composite", cp.out_shape, cp.windows, cp.n_offs,
                           cp.pad, fusion_type, out_dtype, masks,
                           with_coeffs, cp.kinds))
    fuser = F.make_translation_composite(
        cp.out_shape, cp.windows, cp.n_offs, pad=cp.pad,
        fusion_type=fusion_type, out_dtype=out_dtype, masks=masks,
        with_coeffs=with_coeffs, kinds=cp.kinds)
    extra = (cp.coeffs, cp.coeff_affs) if with_coeffs else ()
    return fuser(tiles, cp.fracs, cp.img_dims, cp.borders, cp.ranges,
                 cp.inside_offs, np.float32(min_intensity),
                 np.float32(max_intensity), cp.diags, cp.offs, *extra)


def _try_fuse_volume_device(
    sd, loader, views, bbox, fusion_type, blend,
    anisotropy, out_dtype, min_intensity, max_intensity, masks, stats,
    mask_offset=(0.0, 0.0, 0.0), coefficients=None,
):
    """Whole-volume device-resident fusion via the static composite kernel
    (ops.fusion.make_translation_composite): per-view static output windows,
    8 statically-shifted slices, separable blend — no dynamic slices, so the
    XLA program is pure fused elementwise work at HBM speed.

    Applies when every view is translation-registered at stored level 0 and
    the tile stack fits the device budget; returns the fused volume as a
    DEVICE array (converted to out_dtype) ready for pipelined D2H via
    _drain_device_volume, or None to fall back to the per-block path."""
    cp = plan_composite_volume(sd, loader, views, bbox, anisotropy, blend,
                               masks, mask_offset, coefficients)
    if cp is None:
        return None
    tiles = upload_composite_tiles(loader, cp)
    if stats is not None:
        stats.compile_keys.add((cp.out_shape, cp.windows, fusion_type,
                                out_dtype, masks, "composite"))
    with profiling.span("fusion.kernel"):
        out = dispatch_composite(cp, tiles, fusion_type, out_dtype, masks,
                                 min_intensity, max_intensity)
        if profiling.get().enabled:
            # span attribution only: costs one round-trip, so skip it when
            # nobody reads the spans (the drain's D2H is the real sync)
            profiling.device_sync(out)
    return out


def _epilogue_pyramid_device(vol, pyramid, out_dtype):
    """Chain the downsample pyramid ON DEVICE from the converted full-res
    volume (the fused multiscale epilogue, ROADMAP item 3a): each level is
    a strided float32 mean of the previous one, quantized back to the
    storage dtype between steps — exactly what the container-reread path
    sees when it reads the stored previous level, so levels are
    bit-identical to ``downsample_pyramid_level`` output. Dispatch only
    (the drain's D2H is the real sync). Returns [(PyramidLevel, device
    array), ...]."""
    from ..ops.downsample import downsample_level

    levels = []
    prev = vol
    with profiling.span("fusion.epilogue.kernel"):
        for lv in pyramid:
            prev = downsample_level(prev, tuple(int(v) for v in lv.rel),
                                    tuple(int(v) for v in lv.dims),
                                    str(out_dtype))
            levels.append((lv, prev))
    return levels


def _drain_device_volume(out, out_ds, zarr_ct, pyramid=(),
                         out_dtype="float32"):
    """Pipelined D2H + write of a device-resident fused volume and its
    epilogue ``pyramid`` levels: slab every level along x in storage-chunk
    multiples (each slab write touches its chunks exactly once), start all
    transfers asynchronously, and let a thread pool overlap the remaining
    transfers with compression + disk writes. Every fused voxel crosses
    the wire exactly once; the pyramid rides the same drain instead of a
    second read-modify-write pass over the container.

    Dispatch order matters: the full-res slab transfers are primed FIRST,
    then the epilogue levels are computed (they queue behind the slab
    slices on the device stream) — s0 lands earliest and the pyramid
    reductions overlap the full-res compression + writes instead of
    stalling them. Returns the [(PyramidLevel, device array), ...] it
    materialized."""
    from ..io.chunkstore import StorageFormat
    from ..utils.threads import CtxThreadPool

    # ~8 MB slabs over ~8 streams measured best on the wire-limited link
    # (the knob's default); --prefetch/io_threads does not reach this
    # drain — BST_WRITE_THREADS is its one width control
    io_threads = config.get_int("BST_WRITE_THREADS") or 1
    if getattr(out_ds.store, "format", None) == StorageFormat.HDF5:
        io_threads = 1  # h5py writers must not run concurrently

    def slab_plan(vol, ds):
        bs = ds.block_size
        step = max(int(bs[0]), 1)
        target = 8 << 20
        row_bytes = int(np.prod(vol.shape[1:])) * vol.dtype.itemsize
        if row_bytes * step < target:
            step = int(np.ceil(target / max(row_bytes * step, 1))) * step
        return [(x0, vol[x0:min(x0 + step, vol.shape[0])])
                for x0 in range(0, vol.shape[0], step)]

    from ..dag.stream import handoff_active

    handoff = handoff_active() and zarr_ct is None

    def prime(jobs):
        if handoff:
            return  # slabs are offered to the HBM handoff tier first —
            # pre-starting their D2H would burn wire for claimed slabs
        for _, _, slab, _ in jobs:
            try:
                slab.copy_to_host_async()
            except AttributeError:
                pass

    jobs = [(out_ds, x0, slab, False) for x0, slab in slab_plan(out, out_ds)]
    prime(jobs)
    levels = _epilogue_pyramid_device(out, pyramid, out_dtype)
    for lv, lvol in levels:
        lvl_jobs = [(lv.ds, x0, slab, True)
                    for x0, slab in slab_plan(lvol, lv.ds)]
        prime(lvl_jobs)
        jobs += lvl_jobs

    def drain(job):
        from ..utils import cancel as _cancel

        # per-slab safe point: a cancelled composite-path job stops
        # fetching/writing between slabs (writes are chunk-atomic)
        _cancel.check("fusion drain")
        ds, x0, slab, epi = job
        # device-resident handoff: a streamed same-mesh consumer takes the
        # slab as device chunks straight out of HBM — no D2H, no write, no
        # container decode on its side (dag.stream publishes + accounts)
        if handoff and ds.write_device(slab, (x0, 0, 0)):
            return
        nb = int(slab.nbytes)   # known pre-fetch: device arrays size freely
        d2h_span = (profiling.span("fusion.epilogue.d2h", item=int(x0),
                                   nbytes=nb) if epi else
                    profiling.span("fusion.d2h", item=int(x0), nbytes=nb))
        with d2h_span:
            data = np.asarray(slab)
            _D2H_BYTES.inc(data.nbytes)
            if epi:
                _EPI_D2H_BYTES.inc(data.nbytes)
            if data.dtype.kind in "iu" and data.dtype.itemsize < 4:
                # output converted to storage dtype ON DEVICE: the wire
                # carries uint16/uint8, not the kernel's float32
                _D2H_SAVED.inc(data.size * 4 - data.nbytes)
        write_span = (profiling.span("fusion.epilogue.write", item=int(x0),
                                     nbytes=nb) if epi else
                      profiling.span("fusion.write", item=int(x0), nbytes=nb))
        with write_span:
            if zarr_ct is not None:
                c, t = zarr_ct
                ds.write(data[..., None, None], (x0, 0, 0, c, t))
            else:
                ds.write(data, (x0, 0, 0))
            if epi:
                _EPI_WRITE_BYTES.inc(data.nbytes)

    with CtxThreadPool(max_workers=max(1, io_threads)) as pool:
        list(pool.map(drain, jobs))
    return levels

def _write_block(out_ds, data, block, zarr_ct):
    from ..parallel.mesh import drain_device

    with profiling.span("fusion.write", item=tuple(map(int, block.offset)),
                        nbytes=int(data.nbytes), device=drain_device()):
        if zarr_ct is not None:
            c, t = zarr_ct
            out_ds.write(data[..., None, None], (*block.offset, c, t))
        else:
            out_ds.write(data, block.offset)


def _write_epilogue_block(ds, data, offset, zarr_ct):
    """One pyramid sub-block produced by the sharded per-block epilogue,
    written by the device worker that drained it (its bytes crossed the
    wire inside the batch shard fetch — counted as epilogue traffic
    here)."""
    from ..parallel.mesh import drain_device

    with profiling.span("fusion.epilogue.write",
                        item=tuple(map(int, offset)),
                        nbytes=int(data.nbytes), device=drain_device()):
        if zarr_ct is not None:
            c, t = zarr_ct
            ds.write(data[..., None, None], (*offset, c, t))
        else:
            ds.write(data, offset)
    _EPI_D2H_BYTES.inc(int(data.nbytes))
    _EPI_WRITE_BYTES.inc(int(data.nbytes))


def eligible_epilogue_levels(pyramid, compute_block, full_dims):
    """The PREFIX of pyramid levels the per-block sharded epilogue can
    materialize. Per axis, a level's absolute factor must (1) divide the
    compute block exactly, so block boundaries align with reduction
    windows; (2) be no wider than the axis, so no window needs the
    edge-replication only the whole-volume composite path can do; and
    (3) leave the per-block level piece a whole multiple of the level
    dataset's storage chunk, so concurrent per-device writers never
    read-modify-write a shared chunk. Later levels chain off earlier
    ones, so the first ineligible level stops the prefix; the remaining
    levels fall back to the container-reread downsample stage (which then
    reads the much smaller last materialized level, not full res)."""
    out = []
    for lv in (pyramid or ()):
        ok = all(int(cb) % int(a) == 0 and int(dim) >= int(a)
                 for cb, a, dim in zip(compute_block, lv.abs_factor,
                                       full_dims))
        if ok:
            chunk = lv.ds.block_size[:3]
            ok = all((int(cb) // int(a)) % max(int(c), 1) == 0
                     for cb, a, c in zip(compute_block, lv.abs_factor,
                                         chunk))
        if not ok:
            break
        out.append(lv)
    return out


def _fuse_volume_sharded(
    sd, loader, views, out_ds, bbox, compute_block, fusion_type, blend,
    aniso, out_dtype, min_intensity, max_intensity, masks, mask_offset,
    zarr_ct, stats, coefficients, n_dev, io_threads, progress,
    patch_quantum=32, pyramid=None,
):
    """Multi-device per-block fusion: the block work list is bucketed by
    kernel signature, batched ``n_dev`` at a time, sharded over the local
    device mesh — the TPU replacement of the reference's Spark map over
    grid blocks (SparkAffineFusion.java:480-482).

    Host prefetch for batch k+1 overlaps device compute for batch k
    (double buffering); writers own disjoint chunks so no write needs a
    lock (the reference's no-shuffle invariant). Each device's worker
    drains and WRITES its own shard directly (``device_drain`` in
    parallel.mesh) — the driver thread performs no D2H and no writes —
    except into h5py containers, whose single-writer rule keeps the
    driver-drained path. ``pyramid`` levels whose factors divide
    ``compute_block`` are produced per block as a kernel epilogue and
    written by the same per-device workers."""
    from ..io.chunkstore import StorageFormat
    from ..parallel.mesh import make_mesh, make_sharded_fuser, run_sharded_batches
    from ..utils.threads import CtxThreadPool

    grid = create_grid(bbox.shape, compute_block, compute_block)
    inside_offset = mask_offset if masks else (0.0, 0.0, 0.0)
    epi = eligible_epilogue_levels(pyramid, compute_block, bbox.shape)
    epi_rels = tuple(tuple(int(v) for v in lv.rel) for lv in epi)
    direct = getattr(out_ds.store, "format", None) != StorageFormat.HDF5

    # multi-host: slice the grid BEFORE bucketing so batching heuristics
    # (per_dev) see this process's actual work list
    from ..parallel.distributed import partition_items

    grid = partition_items(grid)
    planned = []
    for block in grid:
        bg = Interval.from_shape(compute_block, block.offset).translate(bbox.min)
        plans = plan_block(sd, loader, views, bg, aniso)
        stats.blocks += 1
        if not plans:
            stats.skipped_empty += 1
            continue
        planned.append((block, bg, plans))

    # bucket by compiled-kernel signature
    buckets: dict[tuple, list] = {}
    for item in planned:
        _, _, plans = item
        vb = F.bucket_views(len(plans))
        if coefficients is None and all(p.is_translation for p in plans):
            key = ("shift", vb)
        else:
            pshape = F.bucket_shape(
                np.max([p.patch_interval.shape for p in plans], axis=0),
                patch_quantum)
            if coefficients is None and all(p.is_diagonal for p in plans):
                key = ("sep", pshape, vb)
            else:
                key = ("gather", pshape, vb)
        buckets.setdefault(key, []).append(item)

    mesh = make_mesh(n_dev)
    mi = np.float32(min_intensity)
    ma = np.float32(max_intensity)
    pwritten: dict[tuple, int] = {}
    pool = CtxThreadPool(max_workers=max(1, io_threads))
    try:
        for key, items in sorted(buckets.items(), key=lambda kv: str(kv[0])):
            kernel, vb = key[0], key[-1]
            fuser = make_sharded_fuser(
                mesh, compute_block, fusion_type, kernel=kernel,
                with_coeffs=coefficients is not None and kernel == "gather",
                out_dtype=out_dtype, masks=masks, pyramid=epi_rels,
            )
            stats.compile_keys.add((compute_block, key, fusion_type,
                                    out_dtype, masks, "sharded"))

            def build(item, _key=key, _kernel=kernel, _vb=vb):
                block, bg, plans = item
                if _kernel == "shift":
                    arrs = _shift_inputs(loader, plans, bg, compute_block,
                                         _vb, blend, inside_offset)
                elif _kernel == "sep":
                    arrs = _sep_inputs(sd, loader, plans, _key[1], _vb,
                                       blend, inside_offset)
                else:
                    arrs = _gather_inputs(sd, loader, plans, _key[1], _vb,
                                          blend, inside_offset, coefficients)
                    if coefficients is None:
                        arrs = arrs[:8]
                return arrs

            def prefetch_boxes(item, _key=key, _kernel=kernel):
                # the same source boxes build() will read (io/prefetch.py
                # feed: batch k+2's crops fetch while batch k computes)
                block, bg, plans = item
                boxes = []
                for p in plans:
                    if _kernel == "shift":
                        tlevel = (p.inv_total[:, :3]
                                  @ np.asarray(bg.min, np.float64)
                                  + p.inv_total[:, 3])
                        off = np.floor(tlevel).astype(np.int64)
                        shp = tuple(int(s) + 1 for s in compute_block)
                    else:
                        off, shp = p.patch_offset, _key[1]
                    b = loader.prefetch_box(
                        p.view, p.level, tuple(int(o) for o in off), shp)
                    if b is not None:
                        boxes.append(b)
                return boxes

            def kernel_call(*stacked):
                # dispatch only — return the DEVICE arrays and let the work
                # loop's per-device drains fetch them, so the early-dispatch
                # window actually overlaps compute with this batch's D2H
                # (a blocking np.asarray here serialized the pipeline,
                # ADVICE r5); wsum is dropped on device, never fetched.
                # Epilogue pyramid levels ride the same dispatch.
                with profiling.span("fusion.kernel"):
                    out, _wsum, *lvls = fuser(mi, ma, *stacked)
                    return (out, *lvls)

            written: dict[tuple, int] = {}

            def epi_pieces(block, lvls):
                for lv, ldata in zip(epi, lvls):
                    a = lv.abs_factor
                    off = tuple(int(o) // int(f)
                                for o, f in zip(block.offset, a))
                    end = tuple(min(int(d), (int(o) + int(s)) // int(f))
                                for d, o, s, f in zip(lv.dims, block.offset,
                                                      block.size, a))
                    size = tuple(e - o for e, o in zip(end, off))
                    if any(s <= 0 for s in size):
                        continue
                    yield lv, ldata, off, size

            def consume(item, data, *lvls):
                block, bg, plans = item
                sl = tuple(slice(0, s) for s in block.size)
                _write_block(out_ds, data[sl], block, zarr_ct)
                written[tuple(block.offset)] = int(np.prod(block.size))
                for lv, ldata, off, size in epi_pieces(block, lvls):
                    _write_epilogue_block(
                        lv.ds, ldata[tuple(slice(0, s) for s in size)],
                        off, zarr_ct)
                    pwritten[(lv.abs_factor, off)] = int(np.prod(size))

            def device_consume(item, data, *lvls):
                # offer the block to the HBM handoff tier BEFORE any D2H:
                # a claimed block stays device-resident for the streamed
                # consumer stage and its rows never cross the wire. All or
                # nothing per item — a partial claim host-writes everything
                # (on_write supersedes the device copies, so no stale read)
                block, bg, plans = item
                sl = tuple(slice(0, s) for s in block.size)
                if not out_ds.write_device(data[sl], block.offset):
                    return False
                for lv, ldata, off, size in epi_pieces(block, lvls):
                    piece = ldata[tuple(slice(0, s) for s in size)]
                    if not lv.ds.write_device(piece, off):
                        return False
                    pwritten[(lv.abs_factor, off)] = int(np.prod(size))
                written[tuple(block.offset)] = int(np.prod(block.size))
                return True

            # pack several blocks per device per batch: fusion dispatches
            # are compute-light, so fewer+bigger launches amortize dispatch
            # and keep the host IO pipeline ahead (VERDICT r3 item 1b) — but
            # bounded by a per-device staging budget so configurations that
            # fit at per_dev=1 cannot OOM
            if kernel == "shift":
                item_bytes = vb * int(np.prod(
                    [c + 1 for c in compute_block])) * 4
            else:
                item_bytes = vb * int(np.prod(key[1])) * 4
            budget = config.get_bytes("BST_PER_DEV_BUDGET")
            per_dev = max(1, min(4, len(items) // max(n_dev, 1),
                                 budget // max(item_bytes, 1)))
            # device-resident per item: converted block + f32 wsum + the
            # epilogue levels
            item_out = int(np.prod(compute_block)) \
                * (np.dtype(out_dtype or "float32").itemsize + 4)
            for lv in epi:
                item_out += int(np.prod(
                    [int(c) // int(a) for c, a in zip(compute_block,
                                                      lv.abs_factor)])) \
                    * np.dtype(out_dtype or "float32").itemsize
            from ..dag.stream import handoff_active

            run_sharded_batches(
                items, build, kernel_call, consume, n_dev, pool,
                label=f"fusion batch {key}", progress=progress,
                per_dev=per_dev,
                out_bytes_per_item=item_out,
                workspace_mult=3.0,
                device_drain=direct,
                device_consume=(device_consume
                                if handoff_active() and zarr_ct is None
                                else None),
                prefetch_boxes=prefetch_boxes,
            )
            stats.voxels += sum(written.values())
    finally:
        pool.shutdown(wait=True)
    stats.pyramid_levels = len(epi)
    stats.pyramid_voxels += sum(pwritten.values())


def _record_fusion_stage(stage: str, stats: "FusionStats",
                         path_kind: str) -> None:
    """File the driver's end-of-stage summary with the telemetry layer
    (block/voxel totals the reference reads off the Spark UI)."""
    observe.progress.record_stage(
        stage,
        done=stats.blocks - stats.skipped_empty,
        total=stats.blocks,
        blocks=stats.blocks,
        skipped_empty=stats.skipped_empty,
        voxels=stats.voxels,
        seconds=round(stats.seconds, 3),
        rate_per_s=round((stats.blocks - stats.skipped_empty)
                         / max(stats.seconds, 1e-9), 3),
        voxels_per_s=round(stats.voxels / max(stats.seconds, 1e-9), 1),
        compile_keys=len(stats.compile_keys),
        path=path_kind,
        # epilogue output reported SEPARATELY from the full-res rate so
        # pyramid voxels can never masquerade as (or hide) a kernel change
        pyramid_levels=stats.pyramid_levels,
        pyramid_voxels=stats.pyramid_voxels,
        voxels_per_s_incl_pyramid=round(
            (stats.voxels + stats.pyramid_voxels)
            / max(stats.seconds, 1e-9), 1),
    )


def fuse_volume(
    sd: SpimData,
    loader: ViewLoader,
    views: list[ViewId],
    out_ds: Dataset,
    bbox: Interval,
    block_size: tuple[int, ...],
    block_scale: tuple[int, ...] = (2, 2, 1),
    fusion_type: str = "AVG_BLEND",
    blend: BlendParams | None = None,
    anisotropy_factor: float = float("nan"),
    out_dtype: str = "float32",
    min_intensity: float | None = None,
    max_intensity: float | None = None,
    masks: bool = False,
    mask_offset: tuple[float, float, float] = (0.0, 0.0, 0.0),
    zarr_ct: tuple[int, int] | None = None,
    progress: bool = False,
    coefficients: dict[ViewId, np.ndarray] | None = None,
    devices: int | None = None,
    io_threads: int = 4,
    device_resident: bool | None = None,
    pyramid: list[PyramidLevel] | None = None,
) -> FusionStats:
    """Fuse ``views`` into ``out_ds`` over ``bbox``.

    ``zarr_ct``: (channel, timepoint) indices when out_ds is a 5-D OME-ZARR
    dataset (3-D block embedded at [...,c,t], SparkAffineFusion.java:630-651).
    ``coefficients``: per-view intensity-correction grids (models.intensity).
    ``devices``: number of local devices to shard the block grid over
    (default: all); with one device the whole-volume device-resident scan
    path is tried first (``device_resident=False`` disables it).
    ``pyramid``: downsample levels to materialize as a fused multiscale
    epilogue while the data is device-resident — shipped in the same
    drain, bit-identical to the container-reread downsample. The composite
    path produces every level; the sharded path the
    :func:`eligible_epilogue_levels` prefix; the per-block fallback none
    (``stats.pyramid_levels`` says how many were done — the rest is the
    downsample stage's job).
    """
    stats = FusionStats()
    t0 = time.time()
    aniso = anisotropy_transform(anisotropy_factor)
    compute_block = tuple(b * s for b, s in zip(block_size, block_scale))
    grid = create_grid(bbox.shape, compute_block, block_size)
    if min_intensity is None or max_intensity is None:
        if out_dtype == "uint8":
            min_intensity, max_intensity = 0.0, 255.0
        elif out_dtype == "uint16":
            min_intensity, max_intensity = 0.0, 65535.0
        else:
            min_intensity, max_intensity = 0.0, 1.0

    import jax

    n_dev = devices if devices is not None else len(jax.local_devices())
    if n_dev > 1:
        _fuse_volume_sharded(
            sd, loader, views, out_ds, bbox, compute_block, fusion_type,
            blend or BlendParams(), aniso, out_dtype, min_intensity,
            max_intensity, masks, mask_offset, zarr_ct, stats, coefficients,
            n_dev, io_threads, progress, pyramid=pyramid,
        )
        stats.seconds = time.time() - t0
        _record_fusion_stage("affine-fusion", stats, "sharded")
        return stats

    # multi-host with one local device: each process takes its slice of the
    # block grid (strided partition); the whole-volume composite path is
    # skipped — it would compute and write the full volume on every host
    from ..parallel.distributed import partition_items, world

    multi_process = world()[1] > 1
    if multi_process:
        grid = partition_items(grid)

    use_composite = device_resident is not False and not multi_process
    vol = None if not use_composite else (
        _try_fuse_volume_device(
            sd, loader, views, bbox, fusion_type,
            blend or BlendParams(), aniso, out_dtype, min_intensity,
            max_intensity, masks, stats, mask_offset=mask_offset,
            coefficients=coefficients,
        ))
    if vol is not None:
        levels = _drain_device_volume(vol, out_ds, zarr_ct,
                                      pyramid=pyramid or (),
                                      out_dtype=out_dtype)
        stats.blocks = len(grid)
        stats.voxels = bbox.num_elements
        stats.pyramid_levels = len(levels)
        stats.pyramid_voxels = sum(int(np.prod(lv.dims))
                                   for lv, _ in levels)
        stats.seconds = time.time() - t0
        _record_fusion_stage("affine-fusion", stats, "composite")
        return stats

    def process(block: GridBlock) -> None:
        res = fuse_grid_block(
            sd, loader, views, block, bbox, fusion_type, blend, aniso,
            compute_block_shape=compute_block, stats=stats,
            inside_offset=mask_offset if masks else (0.0, 0.0, 0.0),
            coefficients=coefficients,
        )
        stats.blocks += 1
        if res is None:
            stats.skipped_empty += 1
            return
        fused, wsum = res
        bkey = tuple(map(int, block.offset))
        if masks:
            out = (wsum > 0).astype(np.float32)
            if out_dtype != "float32":
                out *= float(np.iinfo(np.dtype(out_dtype)).max)
            data = out.astype(out_dtype)
        else:
            out_nbytes = int(np.prod(block.size)
                             * np.dtype(out_dtype).itemsize)
            with profiling.span("fusion.d2h", item=bkey, nbytes=out_nbytes):
                data = jax.device_get(
                    F.convert_intensity(
                        fused, np.float32(min_intensity),
                        np.float32(max_intensity), out_dtype=out_dtype,
                    )
                )
        with profiling.span("fusion.write", item=bkey,
                            nbytes=int(data.nbytes)):
            if zarr_ct is not None:
                c, t = zarr_ct
                out5 = data[..., None, None]
                out_ds.write(out5, (*block.offset, c, t))
            else:
                out_ds.write(data, block.offset)
        stats.voxels += int(np.prod(block.size))
        if progress:
            observe.log(f"  block {block.offset} done ({len(grid)} total)",
                        stage="affine-fusion")

    from ..parallel.retry import run_with_retry

    run_with_retry(grid, process, label="fusion block")
    stats.seconds = time.time() - t0
    _record_fusion_stage("affine-fusion", stats, "per-block")
    return stats

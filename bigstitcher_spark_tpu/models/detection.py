"""Interest-point detection driver: per-view block grid with halo, batched
DoG kernel, subpixel localization, brightest-N filtering, interestpoints.n5.

TPU redesign of SparkInterestPointDetection (reference call stack SURVEY.md
§3.3): the work list is (view, block) tuples at detection resolution
(strategy P3 — halo by over-read, never neighbor communication); equally
shaped blocks from ALL views batch into one compiled DoG kernel; the sparse
tail (argwhere, quadratic fit, filters) runs on host. Detections restricted
to overlap regions replace the reference's per-(view,pair) duplicate pass +
KDTree dedup (SparkInterestPointDetection.java:809-892) with a single pass
over the union of overlap boxes — same output set, no dedup needed.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import numpy as np

from ..utils.threads import CtxThreadPool

from .. import observe
from ..io.dataset_io import ViewLoader, best_mipmap_level, mipmap_transform
from ..io.interestpoints import InterestPointStore, register_points_in_xml
from ..io.spimdata import SpimData, ViewId
from ..ops.dog import (
    dog_block_topk_batch,
    dog_block_topk_batch_impl,
    dog_detect_extract_batch,
    dog_detect_extract_batch_impl,
    dog_halo,
    sample_trilinear,
)
from ..ops.descriptors import (
    block_descriptors_batch,
    block_descriptors_batch_impl,
)
from ..parallel.mesh import make_mesh, run_sharded_batches, shard_jit
from ..ops.downsample import downsample_block
from ..utils.geometry import (
    Interval,
    apply_affine,
    concatenate,
    invert_affine,
    transformed_interval,
)
from ..utils.grid import create_grid
from .. import profiling


@dataclass
class DetectionParams:
    """Defaults match the reference CLI (SparkInterestPointDetection.java:116-170)."""

    label: str = "beads"
    sigma: float = 1.8
    threshold: float = 0.008
    downsample_xy: int = 2
    downsample_z: int = 1
    min_intensity: float | None = None
    max_intensity: float | None = None
    find_max: bool = True
    find_min: bool = False
    overlapping_only: bool = False
    max_spots: int = 0
    max_spots_per_overlap: bool = False
    store_intensities: bool = False
    median_radius: int = 0          # 0 = off (LazyBackgroundSubtract role)
    median_exact: bool = False      # exact per-slice radius-r median
    localization: str = "QUADRATIC"  # NONE | QUADRATIC subpixel
    only_compare_overlap_tiles: bool = False  # --onlyCompareOverlapTiles
    block_size: tuple[int, int, int] = (512, 512, 128)
    batch_size: int = 8
    # device-side compaction budget: K strongest candidates per block leave
    # the device (count is returned, so truncation is detected and warned)
    max_candidates_per_block: int = 4096
    # geometric descriptor extraction riding the detection pass: when on,
    # each block's peaks get kNN-frame descriptors computed WITHOUT leaving
    # HBM (one fused program per block, gated by BST_FUSED_DETECT)
    extract_descriptors: bool = False
    descriptor_neighbors: int = 3
    descriptor_redundancy: int = 1

    @property
    def downsampling(self) -> tuple[int, int, int]:
        return (self.downsample_xy, self.downsample_xy, self.downsample_z)

    def params_string(self) -> str:
        return (f"DOG (TPU) s={self.sigma} t={self.threshold} "
                f"overlappingOnly={self.overlapping_only} min={self.min_intensity} "
                f"max={self.max_intensity} ds={','.join(map(str, self.downsampling))}")


@dataclass
class ViewDetections:
    view: ViewId
    points: np.ndarray            # (N,3) float64, full-res view-local px
    values: np.ndarray            # (N,) DoG response at the detection
    intensities: np.ndarray | None = None
    # extract_descriptors riders: per-point kNN-frame descriptors (N, S, d)
    # and their validity (points near block borders may lack a full pool)
    descriptors: np.ndarray | None = None
    descriptor_valid: np.ndarray | None = None


@dataclass
class _BlockJob:
    view_idx: int
    core: Interval                # detection-res block (core, no halo)
    result: tuple | None = None   # (subpixel pts, values) after extraction


class _ViewPlan:
    """Per-view read geometry: stored level + residual in-memory downsampling."""

    def __init__(self, loader: ViewLoader, view: ViewId, ds: tuple[int, int, int]):
        factors = loader.downsampling_factors(view.setup)
        lvl = best_mipmap_level(factors, ds)
        f = tuple(int(x) for x in factors[lvl])
        if any(int(ds[d]) % f[d] != 0 for d in range(3)):
            lvl, f = 0, (1, 1, 1)
        self.view = view
        self.level = lvl
        self.rel = tuple(int(ds[d]) // f[d] for d in range(3))
        lvl_dims = loader.open(view, lvl).shape
        self.det_dims = tuple(int(s) // r for s, r in zip(lvl_dims, self.rel))

    def read_raw_block(self, loader: ViewLoader, offset, shape) -> np.ndarray:
        """Read the LEVEL-resolution voxels backing a detection-res box
        (mirror-padded outside the image), native dtype: level voxels
        [o*rel, (o+s)*rel) — the shared geometry for both the host-pooled
        and device-pooled paths."""
        lvl_off = [int(o) * r for o, r in zip(offset, self.rel)]
        lvl_shape = [int(s) * r for s, r in zip(shape, self.rel)]
        return _read_mirror(loader, self.view, self.level, lvl_off, lvl_shape)

    def read_det_block(self, loader: ViewLoader, offset, shape) -> np.ndarray:
        """Read a detection-res box (mirror-padded outside the image): level
        voxels [o*rel, (o+s)*rel) average-pooled by ``rel``
        (openAndDownsample, SparkInterestPointDetection.java:998-1118)."""
        raw = self.read_raw_block(loader, offset, shape)
        if all(r == 1 for r in self.rel):
            return raw.astype(np.float32)
        import jax

        return jax.device_get(
            downsample_block(raw.astype(np.float32), self.rel))


def _read_mirror(loader: ViewLoader, view, level, offset, shape) -> np.ndarray:
    """read_block with mirror (reflect) padding outside the image — matches
    the reference's extended images so borders don't produce edge extrema.
    When a streamed producer's device-resident blocks cover the box the
    read serves straight from HBM (``Dataset.read_device``) and the
    padding runs on device — values identical to the host path."""
    ds = loader.open(view, level)
    full = ds.shape
    lo = [max(0, int(o)) for o in offset]
    hi = [min(int(f), int(o) + int(s)) for f, o, s in zip(full, offset, shape)]
    if all(h > l for l, h in zip(lo, hi)):
        size = [h - l for l, h in zip(lo, hi)]
        rd = getattr(ds, "read_device", None)
        data = rd(lo, size) if rd is not None else None
        if data is None:
            data = ds.read(lo, size)
    else:
        return np.zeros(tuple(int(s) for s in shape),
                        dtype=np.dtype(ds.dtype))
    pad = [(l - int(o), int(o) + int(s) - h)
           for l, h, o, s in zip(lo, hi, offset, shape)]
    if any(p != (0, 0) for p in pad):
        capped = [(min(p0, data.shape[d] - 1), min(p1, data.shape[d] - 1))
                  for d, (p0, p1) in enumerate(pad)]
        extra = [(p[0] - c[0], p[1] - c[1]) for p, c in zip(pad, capped)]
        if isinstance(data, np.ndarray):
            data = np.pad(data, capped, mode="reflect")
            if any(e != (0, 0) for e in extra):
                data = np.pad(data, extra, mode="edge")
        else:
            import jax.numpy as jnp

            data = jnp.pad(data, capped, mode="reflect")
            if any(e != (0, 0) for e in extra):
                data = jnp.pad(data, extra, mode="edge")
    return data


def _median_background_divide(block: np.ndarray, radius: int,
                              exact: bool = False) -> np.ndarray:
    """Per-z-slice 2-D median background divide (LazyBackgroundSubtract role,
    SparkInterestPointDetection.java:536-543; median at
    LazyBackgroundSubtract.java:74-140).

    ``exact=True`` computes the true radius-r median over a circular
    footprint per slice (ImageJ RankFilters semantics). The default is a
    4x-decimated estimate bilinearly upsampled — a cheap stand-in at equal
    purpose (flat-field normalization); tests/test_detection.py quantifies
    the detection difference between the two on structured data."""
    if exact:
        from scipy.ndimage import median_filter

        r = int(radius)
        yy, xx = np.mgrid[-r:r + 1, -r:r + 1]
        # ImageJ RankFilters circular kernel: include when d^2 <= r^2 + 1
        footprint = (yy * yy + xx * xx) <= (r * r + 1)
        out = np.empty_like(block, dtype=np.float32)
        for z in range(block.shape[2]):
            sl = block[:, :, z].astype(np.float32)
            bg = median_filter(sl, footprint=footprint, mode="nearest")
            out[:, :, z] = sl / np.maximum(bg, 1e-6)
        return out
    from numpy.lib.stride_tricks import sliding_window_view

    dec = 4
    r = max(1, radius // dec)
    out = np.empty_like(block, dtype=np.float32)
    for z in range(block.shape[2]):
        sl = block[:, :, z].astype(np.float32)
        small = sl[::dec, ::dec]
        padded = np.pad(small, r, mode="edge")
        win = sliding_window_view(padded, (2 * r + 1, 2 * r + 1))
        med = np.median(win, axis=(-2, -1))
        # bilinear upsample back to the slice grid
        yi = np.minimum(np.arange(sl.shape[0]) / dec, med.shape[0] - 1)
        xi = np.minimum(np.arange(sl.shape[1]) / dec, med.shape[1] - 1)
        y0 = np.floor(yi).astype(int)
        x0 = np.floor(xi).astype(int)
        y1 = np.minimum(y0 + 1, med.shape[0] - 1)
        x1 = np.minimum(x0 + 1, med.shape[1] - 1)
        fy = (yi - y0)[:, None]
        fx = (xi - x0)[None, :]
        bg = (med[np.ix_(y0, x0)] * (1 - fy) * (1 - fx)
              + med[np.ix_(y1, x0)] * fy * (1 - fx)
              + med[np.ix_(y0, x1)] * (1 - fy) * fx
              + med[np.ix_(y1, x1)] * fy * fx)
        out[:, :, z] = sl / np.maximum(bg, 1e-6)
    return out


def _overlap_boxes_det(
    sd: SpimData, view: ViewId, others: list[ViewId],
    det_dims, ds, expand_px: int = 2, only_tiles: bool = False,
) -> list[Interval]:
    """Overlap regions of ``view`` with each other view, in detection-res
    view-local px (the --overlappingOnly pre-pass,
    SparkInterestPointDetection.java:291-367). ``only_tiles``: compare only
    same-timepoint same-channel views, i.e. overlap across TILES only
    (--onlyCompareOverlapTiles, :263-270)."""
    model = sd.model(view)
    inv = invert_affine(model)
    my_box = transformed_interval(model, Interval.from_shape(sd.view_size(view)))
    my_channel = sd.setups[view.setup].attributes.get("channel", 0)
    out = []
    for o in others:
        if o == view:
            continue
        if only_tiles and (
                o.timepoint != view.timepoint
                or sd.setups[o.setup].attributes.get("channel", 0) != my_channel):
            continue
        ob = transformed_interval(
            sd.model(o), Interval.from_shape(sd.view_size(o)))
        if not my_box.overlaps(ob):
            continue
        world = my_box.intersect(ob)
        local = transformed_interval(inv, world).expand(expand_px)
        det = Interval(
            tuple(int(np.floor(local.min[d] / ds[d])) for d in range(3)),
            tuple(int(np.ceil(local.max[d] / ds[d])) for d in range(3)),
        ).intersect(Interval.from_shape(det_dims))
        if not det.is_empty():
            out.append(det)
    return out


def _estimate_min_max(loader: ViewLoader, view: ViewId) -> tuple[float, float]:
    """Image min/max from the coarsest stored level (the reference scans the
    downsampled image when min/maxIntensity are absent)."""
    lvl = loader.num_levels(view.setup) - 1
    img = loader.open(view, lvl).read_full()
    return float(img.min()), float(img.max())


def _make_dog_kernel(n_dev: int, params: DetectionParams,
                     rel: tuple[int, int, int] = (1, 1, 1)):
    """DoG kernel over a batch of blocks (compacted top-K output: candidate
    coords + device-refined subpixel positions, ~KB/block across the host
    link instead of two dense volumes); with ``n_dev > 1`` the batch axis is
    sharded over the device mesh (one/few blocks per device). ``rel``:
    residual downsampling the kernel applies on device (blocks arrive at
    level resolution, native dtype)."""
    desc = None
    if params.extract_descriptors:
        from .. import config

        desc = (int(params.descriptor_neighbors),
                int(params.descriptor_redundancy),
                bool(config.get_bool("BST_FUSED_DETECT")))
    return _make_dog_kernel_cached(
        n_dev, float(params.sigma), bool(params.find_max),
        bool(params.find_min), int(params.max_candidates_per_block),
        dog_halo(params.sigma), tuple(int(r) for r in rel), desc)


@functools.lru_cache(maxsize=32)
def _make_dog_kernel_cached(n_dev, sigma, find_max, find_min, k, halo, rel,
                            desc=None):
    """lru_cache'd so repeated detections in one process (multi-run benches,
    detection+nonrigid pipelines) reuse the sharded jit instead of
    recompiling (same defect class as the nonrigid kernel, fixed r4).

    ``desc``: None for plain detection; (n_neighbors, redundancy, fused)
    for detect+extract. fused=True compiles ONE program per block batch —
    the peaks never leave HBM between top-K and the descriptor frame math,
    and the whole dispatch sits under the "detection.kernel" span. The
    staged fallback (fused=False, BST_FUSED_DETECT=0) runs the identical
    impl functions as two dispatches with a "detection.extract" span on the
    second, so fused-vs-staged outputs are bitwise comparable."""
    from types import SimpleNamespace

    params = SimpleNamespace(sigma=sigma, find_max=find_max,
                             find_min=find_min)
    if desc is not None:
        nn, red, fused = desc
        if fused:
            if n_dev <= 1:
                def kernel(blocks, lo, hi, thr, origins):
                    with profiling.span("detection.kernel"):
                        return dog_detect_extract_batch(
                            blocks, lo, hi, thr, origins, params.sigma,
                            params.find_max, params.find_min, k, halo, rel,
                            nn, red, True)
                return kernel
            mesh = make_mesh(n_dev)
            fn = shard_jit(
                lambda b, l, h, t, o: dog_detect_extract_batch_impl(
                    b, l, h, t, o, params.sigma, params.find_max,
                    params.find_min, k, halo, rel, nn, red, True),
                mesh, n_in=5, n_out=7,
            )

            def kernel(blocks, lo, hi, thr, origins):
                with profiling.span("detection.kernel"):
                    return fn(blocks, lo, hi, thr, origins)
            return kernel
        # staged two-pass: same impls, two compiled dispatches; the
        # (sub, valid) intermediates still stay on device between them
        detect = _make_dog_kernel_cached(n_dev, sigma, find_max, find_min,
                                         k, halo, rel, None)
        if n_dev <= 1:
            def extract(sub, valid):
                with profiling.span("detection.extract"):
                    return block_descriptors_batch(sub, valid, nn, red, True)
        else:
            efn = shard_jit(
                lambda s, v: block_descriptors_batch_impl(s, v, nn, red,
                                                          True),
                make_mesh(n_dev), n_in=2, n_out=2,
            )

            def extract(sub, valid):
                with profiling.span("detection.extract"):
                    return efn(sub, valid)

        def kernel(blocks, lo, hi, thr, origins):
            idx, sub, val, valid, count = detect(blocks, lo, hi, thr,
                                                 origins)
            dsc, dvalid = extract(sub, valid)
            return idx, sub, val, valid, count, dsc, dvalid
        return kernel
    if n_dev <= 1:
        def kernel(blocks, lo, hi, thr, origins):
            with profiling.span("detection.kernel"):
                return dog_block_topk_batch(
                    blocks, lo, hi, thr, origins, params.sigma,
                    params.find_max, params.find_min, k, halo, rel)
        return kernel

    mesh = make_mesh(n_dev)
    fn = shard_jit(
        lambda b, l, h, t, o: dog_block_topk_batch_impl(
            b, l, h, t, o, params.sigma, params.find_max, params.find_min,
            k, halo, rel),
        mesh, n_in=5, n_out=5,
    )

    def kernel(blocks, lo, hi, thr, origins):
        with profiling.span("detection.kernel"):
            return fn(blocks, lo, hi, thr, origins)
    return kernel


def detect_interest_points(
    sd: SpimData,
    loader: ViewLoader,
    views: list[ViewId],
    params: DetectionParams | None = None,
    progress: bool = True,
    devices: int | None = None,
) -> list[ViewDetections]:
    """Run DoG detection over all ``views``; returns per-view detections in
    FULL-RES view-local pixel coordinates (correctForDownsampling applied,
    SparkInterestPointDetection.java:611)."""
    params = params or DetectionParams()
    ds = params.downsampling
    halo = dog_halo(params.sigma)
    bs = tuple(int(b) for b in params.block_size)

    plans = {v: _ViewPlan(loader, v, ds) for v in views}
    minmax = {}
    need = [v for v in views
            if params.min_intensity is None or params.max_intensity is None]
    ests: dict[ViewId, tuple[float, float]] = {}
    if need:  # estimation reads are independent -> overlap them
        with CtxThreadPool(max_workers=min(8, len(need))) as mpool:
            ests = dict(zip(need, mpool.map(
                lambda v: _estimate_min_max(loader, v), need)))
    for v in views:
        lo, hi = ests.get(v, (0.0, 0.0))
        minmax[v] = (
            params.min_intensity if params.min_intensity is not None else lo,
            params.max_intensity if params.max_intensity is not None else hi)

    overlap_boxes: dict[ViewId, list[Interval]] = {}
    jobs: list[_BlockJob] = []
    view_list = list(views)
    for vi, v in enumerate(view_list):
        plan = plans[v]
        region = Interval.from_shape(plan.det_dims)
        boxes = None
        if params.overlapping_only:
            boxes = _overlap_boxes_det(
                sd, v, view_list, plan.det_dims, ds,
                only_tiles=params.only_compare_overlap_tiles)
            overlap_boxes[v] = boxes
            if not boxes:
                continue
            region = boxes[0]
            for b in boxes[1:]:
                region = region.union(b)
        for blk in create_grid(region.shape, bs):
            core = Interval.from_shape(blk.size, blk.offset).translate(region.min)
            if boxes is not None and not any(core.overlaps(b) for b in boxes):
                continue
            jobs.append(_BlockJob(vi, core))

    observe.log(f"detection: {len(view_list)} views, {len(jobs)} blocks "
                f"(block {bs}, halo {halo}, ds {ds})",
                stage="detection", echo=progress,
                views=len(view_list), blocks=len(jobs))

    # bucket by block shape (edge blocks are smaller) -> one compiled kernel
    # per shape bucket; the bucket's block list is batched over the device
    # mesh (the reference's detection Spark map,
    # SparkInterestPointDetection.java:448-660, strategy P3)
    import jax

    n_dev = devices if devices is not None else len(jax.local_devices())
    per_dev = max(1, params.batch_size // max(n_dev, 1))

    def build(job: _BlockJob):
        v = view_list[job.view_idx]
        plan = plans[v]
        off = [m - halo for m in job.core.min]
        shape = [s + 2 * halo for s in job.core.shape]
        if params.median_radius > 0:
            raw = plan.read_det_block(loader, off, shape)
            raw = _median_background_divide(raw, params.median_radius,
                                            exact=params.median_exact)
            raw = raw.astype(np.float32)
        else:
            # ship the LEVEL-resolution block in its native dtype; the
            # kernel pools by ``rel`` + normalizes on device (half the
            # wire bytes, no separate downsample dispatch)
            raw = plan.read_raw_block(loader, off, shape)
            if raw.dtype.byteorder == ">":  # JAX rejects big-endian (HDF5)
                raw = raw.astype(raw.dtype.newbyteorder("="))
        lo, hi = minmax[v]
        return (raw, np.float32(lo), np.float32(hi),
                np.float32(params.threshold),
                np.array([m - halo for m in job.core.min], np.int32))

    def consume(job: _BlockJob, idx, sub, vals, valid, count, *extra):
        shape = job.core.shape
        k = len(idx)
        if int(count) > k:
            import warnings

            warnings.warn(
                f"detection block {job.core.min} found {int(count)} extrema, "
                f"keeping the {k} strongest (raise max_candidates_per_block "
                "or lower the threshold noise)", stacklevel=2)
        # the kernel pre-masks to the core slab; re-check as a safety net
        # (halo detections belong to the neighboring block)
        keep = valid.astype(bool)
        for d in range(3):
            keep &= (idx[:, d] >= halo) & (idx[:, d] < halo + shape[d])
        if not keep.any():
            return
        # block-local (with halo) -> view detection-res coords; lexsorted by
        # position so output order is deterministic (top-K rank order would
        # reshuffle under f32 accumulation noise between compilations)
        src = (sub if params.localization.upper() == "QUADRATIC"
               else idx)  # --localization NONE keeps integer extrema
        pts = (src[keep].astype(np.float64) - halo
               + np.array(job.core.min, np.float64))
        vv = vals[keep].astype(np.float64)
        order = np.lexsort(pts.T[::-1])
        if extra:  # (desc, dvalid) riders from detect+extract kernels
            dsc, dvalid = extra
            job.result = (pts[order], vv[order], dsc[keep][order],
                          dvalid[keep][order].astype(bool))
        else:
            job.result = (pts[order], vv[order])

    pool = CtxThreadPool(max_workers=8)
    try:
        # bucket by (det-res block shape, residual factors, input dtype):
        # one compiled kernel per bucket (median path pre-pools on host,
        # so its kernel sees rel=(1,1,1) float32 det-res blocks)
        buckets: dict[tuple, list[_BlockJob]] = {}
        for job in jobs:
            plan = plans[view_list[job.view_idx]]
            if params.median_radius > 0:
                rel, dt = (1, 1, 1), "<f4"
            else:
                rel = plan.rel
                dt = np.dtype(loader.open(plan.view, plan.level).dtype
                              ).newbyteorder("=").str
            shp = tuple(s + 2 * halo for s in job.core.shape)
            buckets.setdefault((shp, rel, dt), []).append(job)
        for (shp, rel, dt), bjobs in sorted(buckets.items()):
            kernel_fn = _make_dog_kernel(n_dev, params, rel)
            # level-res inputs are prod(rel) x larger per det-voxel than the
            # pooled float32 blocks batch_size was tuned for — scale the
            # per-device packing down so batch device memory stays bounded
            rel_vol = int(np.prod(rel))
            wmult = 8.0
            if params.extract_descriptors:
                # the (K, K) masked-distance matrix of the extract half
                # dominates its workspace; express it relative to the input
                kk = int(params.max_candidates_per_block) ** 2 * 4
                wmult += kk / max(1, int(np.prod(shp))
                                  * np.dtype(dt).itemsize)
            run_sharded_batches(bjobs, build, kernel_fn, consume, n_dev, pool,
                                label="detection batch",
                                per_dev=max(1, per_dev // rel_vol),
                                # DoG expands the native-dtype input to
                                # several pooled f32 volumes on device
                                workspace_mult=wmult)
    finally:
        pool.shutdown(wait=True)

    per_view: dict[int, list[tuple[np.ndarray, np.ndarray]]] = {
        i: [] for i in range(len(view_list))}
    for job in jobs:  # original job order => deterministic concatenation
        if job.result is not None:
            per_view[job.view_idx].append(job.result)

    want_desc = bool(params.extract_descriptors)
    out = []
    for vi, v in enumerate(view_list):
        plan = plans[v]
        res = per_view[vi]
        if res:
            pts = np.concatenate([r[0] for r in res])
            vals = np.concatenate([r[1] for r in res])
            riders = ((np.concatenate([np.asarray(r[2]) for r in res]),
                       np.concatenate([np.asarray(r[3]) for r in res]))
                      if want_desc else ())
        else:
            pts, vals = np.zeros((0, 3)), np.zeros(0)
            riders = ()
            if want_desc:
                from ..ops.descriptors import subset_combinations

                nn = int(params.descriptor_neighbors)
                s = len(subset_combinations(
                    nn + int(params.descriptor_redundancy), nn))
                riders = (np.zeros((0, s, nn * 3), np.float32),
                          np.zeros(0, bool))
        pts, vals, riders = _filter_spots(pts, vals, overlap_boxes.get(v),
                                          params, riders)
        # detection-res -> full-res: average downsampling by f maps level
        # voxel p to full-res f*p + (f-1)/2 (DownsampleTools.correctForDownsampling)
        T = mipmap_transform(ds)
        full = apply_affine(T, pts) if len(pts) else pts
        det = ViewDetections(v, full, vals)
        if want_desc:
            det.descriptors, det.descriptor_valid = riders
        if params.store_intensities and len(pts):
            det.intensities = _sample_intensities(loader, plan, pts)
        out.append(det)
        observe.log(f"  {v}: {len(full)} interest points",
                    stage="detection", echo=progress, points=len(full))
    return out


def _filter_spots(pts, vals, boxes, params: DetectionParams, riders=()):
    """overlappingOnly final crop + brightest-N filters
    (filterPoints / maxSpotsPerOverlap, SparkInterestPointDetection.java:745-806,973-995).
    ``riders``: extra per-point arrays (descriptors, validity) that must
    follow every mask/reorder applied to ``pts``/``vals``."""
    riders = tuple(riders)
    if boxes is not None and len(pts):
        keep = np.zeros(len(pts), bool)
        for b in boxes:
            inside = np.all(
                (pts >= np.array(b.min)) & (pts <= np.array(b.max)), axis=1
            )
            keep |= inside
        pts, vals = pts[keep], vals[keep]
        riders = tuple(r[keep] for r in riders)
    if params.max_spots > 0 and len(pts):
        if params.max_spots_per_overlap and boxes:
            total_vol = sum(b.num_elements for b in boxes)
            keep = np.zeros(len(pts), bool)
            assigned = np.zeros(len(pts), bool)
            for b in boxes:
                budget = max(1, int(round(params.max_spots * b.num_elements / total_vol)))
                inside = np.all(
                    (pts >= np.array(b.min)) & (pts <= np.array(b.max)), axis=1
                ) & ~assigned
                idx = np.where(inside)[0]
                assigned[idx] = True
                if len(idx) > budget:
                    order = np.argsort(-np.abs(vals[idx]))[:budget]
                    idx = idx[order]
                keep[idx] = True
            pts, vals = pts[keep], vals[keep]
            riders = tuple(r[keep] for r in riders)
        elif len(pts) > params.max_spots:
            order = np.argsort(-np.abs(vals))[: params.max_spots]
            pts, vals = pts[order], vals[order]
            riders = tuple(r[order] for r in riders)
    return pts, vals, riders


def _sample_intensities(loader, plan: _ViewPlan, det_pts: np.ndarray,
                        cell: int = 64) -> np.ndarray:
    """Sample image intensity at each detection (detection-res coords) via
    trilinear interpolation. Points are binned into ``cell``-sized spatial
    cells and each occupied cell is read once (+1 px margin), so memory is
    bounded by the cell size instead of the detections' bounding box —
    the lazy-per-point analogue of the reference's interpolation sampling
    (SparkInterestPointDetection.java:581-606)."""
    if len(det_pts) == 0:
        return np.zeros(0)
    out = np.zeros(len(det_pts))
    cells = np.floor(det_pts / cell).astype(np.int64)
    order = np.lexsort(cells.T[::-1])
    uniq, starts = np.unique(cells[order], axis=0, return_index=True)
    bounds = np.append(starts, len(order))
    for k, c in enumerate(uniq):
        idx = order[bounds[k]:bounds[k + 1]]
        lo = np.maximum(c * cell - 1, 0)
        hi = np.minimum((c + 1) * cell + 2, np.asarray(plan.det_dims))
        vol = plan.read_det_block(loader, lo, hi - lo)
        out[idx] = sample_trilinear(vol, det_pts[idx] - lo)
    return out


def save_detections(
    sd: SpimData,
    store: InterestPointStore,
    detections: list[ViewDetections],
    params: DetectionParams,
) -> None:
    """Persist to interestpoints.n5 + register in the XML
    (InterestPointTools.addInterestPoints role)."""
    for det in detections:
        grp = store.save_points(
            det.view, params.label, det.points,
            intensities=det.intensities,
        )
        register_points_in_xml(sd, det.view, params.label,
                               params.params_string(), grp)

"""Pairwise interest-point matching driver: pair planning per timepoint
policy, descriptor matching + RANSAC (or ICP), correspondence storage.

TPU redesign of SparkGeometricDescriptorMatching (reference call stack
SURVEY.md §3.4): the work list is overlapping view pairs (strategy P2); per
pair, interest points are world-transformed under current registrations,
candidate correspondences come from the batched descriptor kernels and are
verified by hypothesis-parallel RANSAC (ops.descriptors). Inliers are stored
symmetrically into interestpoints.n5 ``correspondences`` datasets — the
exact format ``models.solver.matches_from_interest_points`` consumes.

Grouped matching (--groupChannels/--groupTiles/--groupIllums/
--splitTimepoints): member views' interest points are pooled in world space,
near-duplicates across views are merged within ``merge_distance`` px
(InterestPointGroupingMinDistance role), the pooled clouds are matched as one
pair, and the inliers are split back per original view pair — per-view lists
smaller than the model's minimum match count are dropped
(SparkGeometricDescriptorMatching.java:343-503).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..io.interestpoints import CorrespondingPoint, InterestPointStore
from ..io.spimdata import SpimData, ViewId
from ..ops import descriptors as D
from ..ops import models as M
from ..utils.geometry import Interval, apply_affine, transformed_interval
from .. import observe, profiling

INDIVIDUAL_TIMEPOINTS = "TIMEPOINTS_INDIVIDUALLY"
ALL_TO_ALL = "ALL_TO_ALL"
ALL_TO_ALL_RANGE = "ALL_TO_ALL_WITH_RANGE"
REFERENCE_TIMEPOINT = "REFERENCE_TIMEPOINT"


@dataclass
class MatchingParams:
    """Defaults follow the reference CLI
    (SparkGeometricDescriptorMatching.java:82,180-189; AbstractRegistration.java:59-108)."""

    label: str = "beads"
    labels: tuple = ()                   # extra labels (-l repeatable)
    match_across_labels: bool = False    # --matchAcrossLabels
    method: str = D.GEOMETRIC_HASHING   # FAST_ROTATION|FAST_TRANSLATION|PRECISE_TRANSLATION|ICP
    model: str = M.AFFINE
    regularization: str = M.RIGID
    lam: float = 0.1
    n_neighbors: int = 3
    redundancy: int = 1
    ratio_of_distance: float = 3.0
    ransac_iterations: int = 10000
    ransac_max_epsilon: float = 5.0
    ransac_min_inlier_ratio: float = 0.1
    ransac_min_inliers: int = 12
    ransac_multi_consensus: bool = False  # --ransacMultiConsensus (-rmc)
    search_radius: float | None = None   # -sr: world-space candidate limit
    icp_max_distance: float = 2.5
    icp_max_iterations: int = 200
    icp_use_ransac: bool = False         # --icpUseRANSAC
    registration_tp: str = INDIVIDUAL_TIMEPOINTS
    reference_tp: int = 0
    range_tp: int = 5
    overlap_filter: bool = True          # SimpleBoundingBoxOverlap vs all-against-all
    interest_points_for_overlap_only: bool = False
    clear_correspondences: bool = False
    # grouping (SparkGeometricDescriptorMatching.java:115-129)
    group_tiles: bool = False
    group_channels: bool = False
    group_illums: bool = False
    split_timepoints: bool = False
    merge_distance: float = 5.0          # --interestPointMergeDistance

    @property
    def all_labels(self) -> tuple:
        out = [self.label]
        for l in self.labels:
            if l not in out:
                out.append(l)
        return tuple(out)

    def label_pairs(self):
        """(label_a, label_b) matching tasks: same-label always; with
        --matchAcrossLabels BOTH directions of every cross-label combo,
        because each view pair is planned once unordered — (beads of A vs
        nuclei of B) and (nuclei of A vs beads of B) are distinct pairings
        (MatcherPairwiseTools.getTasksList role)."""
        ls = self.all_labels
        out = [(l, l) for l in ls]
        if self.match_across_labels:
            for i in range(len(ls)):
                for j in range(len(ls)):
                    if i != j:
                        out.append((ls[i], ls[j]))
        return out

    @property
    def grouped(self) -> bool:
        return (self.group_tiles or self.group_channels or self.group_illums
                or self.split_timepoints)


@dataclass
class PairMatchResult:
    view_a: ViewId
    view_b: ViewId
    ids_a: np.ndarray        # (K,) interest-point ids on A
    ids_b: np.ndarray
    model: np.ndarray | None
    n_candidates: int
    label_a: str = "beads"
    label_b: str = "beads"


def plan_match_pairs(
    sd: SpimData, views: list[ViewId], params: MatchingParams
) -> list[tuple[ViewId, ViewId]]:
    """Enumerate view pairs per timepoint policy + overlap filter
    (PairwiseSetup constellation, AbstractRegistration.java:143-179)."""
    views = sorted(views)
    boxes = {
        v: transformed_interval(sd.model(v), Interval.from_shape(sd.view_size(v)))
        for v in views
    }
    policy = params.registration_tp.upper()
    out = []
    for i in range(len(views)):
        for j in range(i + 1, len(views)):
            a, b = views[i], views[j]
            ta, tb = a.timepoint, b.timepoint
            if policy == INDIVIDUAL_TIMEPOINTS:
                if ta != tb:
                    continue
            elif policy == ALL_TO_ALL_RANGE:
                if abs(ta - tb) > params.range_tp:
                    continue
            elif policy == REFERENCE_TIMEPOINT:
                # each timepoint registers against the reference timepoint
                if not (ta == tb or params.reference_tp in (ta, tb)):
                    continue
            # ALL_TO_ALL: no timepoint restriction
            if params.overlap_filter and not boxes[a].overlaps(boxes[b]):
                continue
            out.append((a, b))
    return out


def _filter_to_overlap(
    sd: SpimData, ids, world, view: ViewId, other: ViewId
) -> tuple[np.ndarray, np.ndarray]:
    """Keep only points inside the pair's world overlap bbox (+epsilon)
    (filterForOverlappingInterestPoints, SparkGeometricDescriptorMatching.java:294-305)."""
    box_a = transformed_interval(sd.model(view), Interval.from_shape(sd.view_size(view)))
    box_b = transformed_interval(sd.model(other), Interval.from_shape(sd.view_size(other)))
    ov = box_a.intersect(box_b).expand(2)
    if ov.is_empty() or not len(world):
        return ids[:0], world[:0]
    keep = np.all(
        (world >= np.array(ov.min)) & (world <= np.array(ov.max)), axis=1
    )
    return ids[keep], world[keep]


def match_pair(
    wa: np.ndarray, wb: np.ndarray, params: MatchingParams, seed: int = 17
) -> tuple[np.ndarray, np.ndarray | None, int]:
    """Match two world-space point clouds.

    Returns (inlier index pairs (K,2) into wa/wb, model 3x4 a->b or None,
    n_candidates)."""
    if params.method == D.ICP:
        res = D.icp(
            wa, wb, params.model, params.regularization, params.lam,
            params.icp_max_distance, params.icp_max_iterations,
            use_ransac=params.icp_use_ransac,
            ransac_epsilon=params.ransac_max_epsilon,
            ransac_iterations=params.ransac_iterations, seed=seed,
        )
        if res is None:
            return np.zeros((0, 2), np.int32), None, 0
        model, pairs = res
        return pairs, model, len(pairs)

    cand = D.match_candidates(
        wa, wb, params.method, params.n_neighbors, params.redundancy,
        params.ratio_of_distance,
    )
    if len(cand) == 0:
        return np.zeros((0, 2), np.int32), None, 0
    if params.search_radius is not None:
        # -sr limits corresponding points in global coordinate space
        # (SparkGeometricDescriptorMatching.java:93-94)
        d = np.linalg.norm(wa[cand[:, 0]] - wb[cand[:, 1]], axis=1)
        cand = cand[d <= float(params.search_radius)]
        if len(cand) == 0:
            return np.zeros((0, 2), np.int32), None, 0
    if params.ransac_multi_consensus:
        sets = D.ransac_multi(
            wa[cand[:, 0]], wb[cand[:, 1]],
            params.model, params.regularization, params.lam,
            params.ransac_max_epsilon, params.ransac_min_inlier_ratio,
            params.ransac_min_inliers, params.ransac_iterations, seed=seed,
        )
        # bst-lint: off=host-sync (ransac_multi returns a host list)
        if not sets:
            return np.zeros((0, 2), np.int32), None, len(cand)
        union = np.zeros(len(cand), bool)
        for _, mask in sets:
            union |= mask
        # the dominant model represents the pair; correspondences keep
        # every consensus set (reference multiconsensus semantics)
        return cand[union], sets[0][0], len(cand)
    res = D.ransac(
        wa[cand[:, 0]], wb[cand[:, 1]],
        params.model, params.regularization, params.lam,
        params.ransac_max_epsilon, params.ransac_min_inlier_ratio,
        params.ransac_min_inliers, params.ransac_iterations, seed=seed,
    )
    if res is None:
        return np.zeros((0, 2), np.int32), None, len(cand)
    model, inliers = res
    return cand[inliers], model, len(cand)


def build_match_groups(
    sd: SpimData, views: list[ViewId], params: MatchingParams
) -> list[tuple[ViewId, ...]]:
    """Partition views into match groups: a view's group key keeps every
    attribute EXCEPT the grouped ones (groups always stay within one
    timepoint; --splitTimepoints merges everything per timepoint)."""
    by_key: dict[tuple, list[ViewId]] = {}
    for v in sorted(views):
        s = sd.setups[v.setup]
        if params.split_timepoints:
            key = (v.timepoint,)
        else:
            key = (
                v.timepoint,
                s.attributes.get("angle", 0),
                None if params.group_channels else s.attributes.get("channel", 0),
                None if params.group_illums else s.attributes.get("illumination", 0),
                None if params.group_tiles else s.attributes.get("tile", 0),
            )
        by_key.setdefault(key, []).append(v)
    return [tuple(vs) for _, vs in sorted(by_key.items())]


def _group_bbox(sd: SpimData, group: tuple[ViewId, ...]) -> Interval:
    box = None
    for v in group:
        iv = transformed_interval(sd.model(v), Interval.from_shape(sd.view_size(v)))
        box = iv if box is None else box.union(iv)
    return box


def plan_group_pairs(
    sd: SpimData, groups: list[tuple[ViewId, ...]], params: MatchingParams
) -> list[tuple[tuple[ViewId, ...], tuple[ViewId, ...]]]:
    """Group-pair enumeration under the same timepoint policy + overlap
    filter as the ungrouped path."""
    boxes = [_group_bbox(sd, g) for g in groups]
    policy = params.registration_tp.upper()
    out = []
    for i in range(len(groups)):
        for j in range(i + 1, len(groups)):
            ta, tb = groups[i][0].timepoint, groups[j][0].timepoint
            if policy == INDIVIDUAL_TIMEPOINTS:
                if ta != tb:
                    continue
            elif policy == ALL_TO_ALL_RANGE:
                if abs(ta - tb) > params.range_tp:
                    continue
            elif policy == REFERENCE_TIMEPOINT:
                if not (ta == tb or params.reference_tp in (ta, tb)):
                    continue
            if params.overlap_filter and not boxes[i].overlaps(boxes[j]):
                continue
            out.append((groups[i], groups[j]))
    if (not out and len(groups) > 1 and params.split_timepoints
            and policy == INDIVIDUAL_TIMEPOINTS):
        import warnings

        warnings.warn(
            "--splitTimepoints merges each timepoint into one group, and the "
            "default TIMEPOINTS_INDIVIDUALLY policy only pairs groups within "
            "a timepoint — no pairs to match. Use -rtp ALL_TO_ALL(_RANGE) or "
            "REFERENCE_TIMEPOINT with --splitTimepoints.",
            stacklevel=2)
    return out


def merge_min_distance(
    view_of: np.ndarray, world: np.ndarray, radius: float
) -> np.ndarray:
    """Keep-mask for pooled group points: a point is dropped when a point of
    an EARLIER member view lies within ``radius`` (the near-duplicate beads
    that views of one group see in their mutual overlap —
    InterestPointGroupingMinDistance semantics, merge radius default 5 px)."""
    from scipy.spatial import cKDTree

    keep = np.ones(len(world), bool)
    if len(world) == 0 or radius <= 0:
        return keep
    kept_pts: list[np.ndarray] = []
    for uv in sorted(set(view_of.tolist())):
        sel = view_of == uv
        if kept_pts:
            tree = cKDTree(np.concatenate(kept_pts))
            d, _ = tree.query(world[sel], k=1)
            keep[sel] = d > radius
        if np.any(keep & sel):
            kept_pts.append(world[keep & sel])
    return keep


def _match_grouped(
    sd: SpimData,
    views: list[ViewId],
    params: MatchingParams,
    store: InterestPointStore,
    progress: bool,
    devices: int | None = None,
) -> list[PairMatchResult]:
    """Grouped matching: pool member views' points, merge near-duplicates,
    match once per group pair, split inliers back per view pair
    (SparkGeometricDescriptorMatching.java:343-503)."""
    groups = build_match_groups(sd, views, params)
    pairs = plan_group_pairs(sd, groups, params)
    observe.log(f"matching (grouped): {len(groups)} groups, {len(pairs)} "
                f"group pairs, merge distance {params.merge_distance}",
                stage="matching", echo=progress,
                groups=len(groups), pairs=len(pairs))

    cache: dict[ViewId, tuple[np.ndarray, np.ndarray]] = {}

    def world(view: ViewId):
        if view not in cache:
            ids, locs = store.load_points(view, params.label)
            w = apply_affine(sd.model(view), locs) if len(locs) else locs
            cache[view] = (ids, w)
        return cache[view]

    def pooled(group: tuple[ViewId, ...]):
        view_of, ids, pts = [], [], []
        for k, v in enumerate(group):
            i, w = world(v)
            view_of.append(np.full(len(i), k, np.int32))
            ids.append(i)
            pts.append(w)
        view_of = np.concatenate(view_of) if view_of else np.zeros(0, np.int32)
        ids = np.concatenate(ids) if ids else np.zeros(0, np.uint64)
        pts = (np.concatenate(pts) if pts else np.zeros((0, 3), np.float64))
        keep = merge_min_distance(view_of, pts, params.merge_distance)
        return view_of[keep], ids[keep], pts[keep]

    from ..parallel.pairsched import PairTask, run_pair_tasks

    min_matches = M.MIN_POINTS[params.model]
    results: list[PairMatchResult] = []
    # prefetch member clouds once (IO, caller's thread; cache read-only
    # afterwards). Pooling/merging runs inside each worker so one pair's
    # merged clouds are resident per worker, not all pairs at once.
    ptasks = []
    for k, (ga, gb) in enumerate(pairs):
        na = sum(len(world(v)[1]) for v in ga)
        nb = sum(len(world(v)[1]) for v in gb)
        ptasks.append(PairTask(index=len(ptasks), cost=_pair_cost(na, nb),
                               tag=(k, ga, gb)))

    def run_one(task):
        k, ga, gb = task.tag
        va_of, ids_a, wa = pooled(ga)
        vb_of, ids_b, wb = pooled(gb)
        if params.interest_points_for_overlap_only:
            # group = one unit: filter to the GROUP overlap bbox, never
            # within a group (SparkGeometricDescriptorMatching.java:404-411)
            ov = _group_bbox(sd, ga).intersect(_group_bbox(sd, gb)).expand(2)
            if ov.is_empty():
                return None
            ka = np.all((wa >= np.array(ov.min)) & (wa <= np.array(ov.max)),
                        axis=1) if len(wa) else np.zeros(0, bool)
            kb = np.all((wb >= np.array(ov.min)) & (wb <= np.array(ov.max)),
                        axis=1) if len(wb) else np.zeros(0, bool)
            va_of, ids_a, wa = va_of[ka], ids_a[ka], wa[ka]
            vb_of, ids_b, wb = vb_of[kb], ids_b[kb], wb[kb]
        with profiling.span("matching.group_pair"):
            inl, model, n_cand = match_pair(wa, wb, params, seed=17 + k)
        return inl, model, n_cand, va_of, ids_a, vb_of, ids_b

    outs = run_pair_tasks(ptasks, run_one, n_devices=devices,
                          stage="matching")

    for (ga, gb), out in zip(pairs, outs):
        if out is None:  # empty group-overlap bbox: nothing to match
            continue
        inl, model, n_cand, va_of, ids_a, vb_of, ids_b = out
        observe.log(f"  group {ga[0]}x{len(ga)} <-> {gb[0]}x{len(gb)}: "
                    f"{len(inl)} inliers / {n_cand} candidates",
                    stage="matching", echo=progress,
                    inliers=len(inl), candidates=n_cand)
        # split grouped inliers per original (viewA, viewB) pair
        per_pair: dict[tuple[ViewId, ViewId], list[tuple[int, int]]] = {}
        for ia, ib in inl:
            pair = (ga[va_of[ia]], gb[vb_of[ib]])
            per_pair.setdefault(pair, []).append((int(ids_a[ia]), int(ids_b[ib])))
        for (va, vb), id_pairs in sorted(per_pair.items()):
            if len(id_pairs) < min_matches:
                observe.log(f"    {va} <-> {vb}: {len(id_pairs)} "
                            "correspondences (omitted: fewer than the model "
                            "minimum)", stage="matching", echo=progress,
                            correspondences=len(id_pairs), omitted=True)
                continue
            arr = np.array(id_pairs, np.uint64)
            results.append(PairMatchResult(
                va, vb, arr[:, 0], arr[:, 1], model, n_cand,
                label_a=params.label, label_b=params.label))
            observe.log(f"    {va} <-> {vb}: {len(id_pairs)} correspondences",
                        stage="matching", echo=progress,
                        correspondences=len(id_pairs))
    return results


def _pair_cost(na: int, nb: int) -> float:
    """Placement weight for one pair's device work given the two cloud
    sizes: the descriptor ratio test is ~|A|x|B| and the per-cloud kNN
    ~|A|²+|B|² distance entries."""
    return float(na * nb + na * na + nb * nb + 1)


def match_interest_points(
    sd: SpimData,
    views: list[ViewId],
    params: MatchingParams | None = None,
    store: InterestPointStore | None = None,
    progress: bool = True,
    devices: int | None = None,
) -> list[PairMatchResult]:
    """Run pairwise matching over all planned pairs; results are NOT yet
    persisted (use ``save_matches``).

    Point clouds load once on the caller's thread (IO); the per-pair
    device cascades (descriptor kNN + ratio test + RANSAC) then spread
    over every local device via the pair scheduler, weighted by descriptor
    count. Seeds are attached per task index, so placement never changes
    results and multi-device output equals single-device exactly."""
    params = params or MatchingParams()
    store = store or InterestPointStore.for_project(sd)
    if params.grouped:
        if len(params.all_labels) > 1 or params.match_across_labels:
            raise ValueError(
                "grouped matching (--groupTiles/--groupChannels/"
                "--groupIllums/--splitTimepoints) supports a single label; "
                "run ungrouped for multi-label / --matchAcrossLabels")
        return _match_grouped(sd, views, params, store, progress,
                              devices=devices)
    pairs = plan_match_pairs(sd, views, params)
    observe.log(f"matching: {len(pairs)} view pairs, method {params.method}, "
                f"model {params.model} reg {params.regularization} "
                f"λ={params.lam}", stage="matching", echo=progress,
                pairs=len(pairs), method=str(params.method))

    cache: dict[tuple, tuple[np.ndarray, np.ndarray]] = {}

    def world(view: ViewId, label: str):
        key = (view, label)
        if key not in cache:
            ids, locs = store.load_points(view, label)
            w = apply_affine(sd.model(view), locs) if len(locs) else locs
            cache[key] = (ids, w)
        return cache[key]

    from ..parallel.pairsched import PairTask, run_pair_tasks

    label_tasks = params.label_pairs()
    tasks = [(va, vb, la, lb) for va, vb in pairs for la, lb in label_tasks]
    # prefetch every needed cloud ONCE on the caller's thread (IO); the
    # cache is read-only from here on, so worker threads share it safely.
    # Tags carry only keys — per-pair filtered copies are built (and
    # dropped) inside each worker, not pinned for the whole stage.
    ptasks = []
    for k, (va, vb, la, lb) in enumerate(tasks):
        _, wa = world(va, la)
        _, wb = world(vb, lb)
        ptasks.append(PairTask(index=k, cost=_pair_cost(len(wa), len(wb)),
                               tag=(k, va, vb, la, lb)))

    def run_one(task):
        k, va, vb, la, lb = task.tag
        ids_a, wa = world(va, la)
        ids_b, wb = world(vb, lb)
        if params.interest_points_for_overlap_only:
            ids_a, wa = _filter_to_overlap(sd, ids_a, wa, va, vb)
            ids_b, wb = _filter_to_overlap(sd, ids_b, wb, vb, va)
        with profiling.span("matching.pair"):
            inl, model, n_cand = match_pair(wa, wb, params, seed=17 + k)
        return (
            inl, model, n_cand,
            ids_a[inl[:, 0]] if len(inl) else np.zeros(0, np.uint64),
            ids_b[inl[:, 1]] if len(inl) else np.zeros(0, np.uint64),
        )

    outs = run_pair_tasks(ptasks, run_one, n_devices=devices,
                          stage="matching")

    results = []
    for (va, vb, la, lb), (inl, model, n_cand, sel_a, sel_b) in zip(
            tasks, outs):
        res = PairMatchResult(va, vb, sel_a, sel_b, model, n_cand,
                              label_a=la, label_b=lb)
        results.append(res)
        observe.log(f"  {va} <-> {vb}: {len(inl)} inliers / {n_cand} "
                    "candidates", stage="matching", echo=progress,
                    inliers=len(inl), candidates=n_cand)
    return results


def save_matches(
    sd: SpimData,
    store: InterestPointStore,
    results: list[PairMatchResult],
    params: MatchingParams,
    views: list[ViewId],
) -> None:
    """Persist correspondences symmetrically per view
    (MatcherPairwiseTools.addCorrespondences + save,
    SparkGeometricDescriptorMatching.java:509-545). Existing correspondences
    of re-matched views are kept and merged unless clear_correspondences."""
    new: dict[tuple, list[CorrespondingPoint]] = {
        (v, l): [] for v in views for l in params.all_labels}
    for r in results:
        for ia, ib in zip(r.ids_a.astype(int), r.ids_b.astype(int)):
            new.setdefault((r.view_a, r.label_a), []).append(
                CorrespondingPoint(ia, r.view_b, r.label_b, ib))
            new.setdefault((r.view_b, r.label_b), []).append(
                CorrespondingPoint(ib, r.view_a, r.label_a, ia))
    for (v, label), corrs in new.items():
        if not params.clear_correspondences:
            existing = store.load_correspondences(v, label)
            seen = {(c.id, c.other_view, c.other_label, c.other_id)
                    for c in corrs}
            corrs = corrs + [
                c for c in existing
                if (c.id, c.other_view, c.other_label, c.other_id) not in seen
            ]
        store.save_correspondences(v, label, corrs)

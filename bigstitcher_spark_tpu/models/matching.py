"""Pairwise interest-point matching driver: pair planning per timepoint
policy, descriptor matching + RANSAC (or ICP), correspondence storage.

TPU redesign of SparkGeometricDescriptorMatching (reference call stack
SURVEY.md §3.4): the work list is overlapping view pairs (strategy P2); per
pair, interest points are world-transformed under current registrations,
candidate correspondences come from the batched descriptor kernels and are
verified by hypothesis-parallel RANSAC (ops.descriptors). Inliers are stored
symmetrically into interestpoints.n5 ``correspondences`` datasets — the
exact format ``models.solver.matches_from_interest_points`` consumes.

Reference parity notes: grouped matching (tile/channel/illum merging via
InterestPointGroupingMinDistance, SparkGeometricDescriptorMatching.java:343-503)
is not implemented yet — each view matches individually.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..io.interestpoints import CorrespondingPoint, InterestPointStore
from ..io.spimdata import SpimData, ViewId
from ..ops import descriptors as D
from ..ops import models as M
from ..utils.geometry import Interval, apply_affine, transformed_interval
from .. import profiling

INDIVIDUAL_TIMEPOINTS = "TIMEPOINTS_INDIVIDUALLY"
ALL_TO_ALL = "ALL_TO_ALL"
ALL_TO_ALL_RANGE = "ALL_TO_ALL_WITH_RANGE"
REFERENCE_TIMEPOINT = "REFERENCE_TIMEPOINT"


@dataclass
class MatchingParams:
    """Defaults follow the reference CLI
    (SparkGeometricDescriptorMatching.java:82,180-189; AbstractRegistration.java:59-108)."""

    label: str = "beads"
    method: str = D.GEOMETRIC_HASHING   # FAST_ROTATION|FAST_TRANSLATION|PRECISE_TRANSLATION|ICP
    model: str = M.AFFINE
    regularization: str = M.RIGID
    lam: float = 0.1
    n_neighbors: int = 3
    redundancy: int = 1
    ratio_of_distance: float = 3.0
    ransac_iterations: int = 10000
    ransac_max_epsilon: float = 5.0
    ransac_min_inlier_ratio: float = 0.1
    ransac_min_inliers: int = 12
    icp_max_distance: float = 2.5
    icp_max_iterations: int = 200
    registration_tp: str = INDIVIDUAL_TIMEPOINTS
    reference_tp: int = 0
    range_tp: int = 5
    overlap_filter: bool = True          # SimpleBoundingBoxOverlap vs all-against-all
    interest_points_for_overlap_only: bool = False
    clear_correspondences: bool = False


@dataclass
class PairMatchResult:
    view_a: ViewId
    view_b: ViewId
    ids_a: np.ndarray        # (K,) interest-point ids on A
    ids_b: np.ndarray
    model: np.ndarray | None
    n_candidates: int


def plan_match_pairs(
    sd: SpimData, views: list[ViewId], params: MatchingParams
) -> list[tuple[ViewId, ViewId]]:
    """Enumerate view pairs per timepoint policy + overlap filter
    (PairwiseSetup constellation, AbstractRegistration.java:143-179)."""
    views = sorted(views)
    boxes = {
        v: transformed_interval(sd.model(v), Interval.from_shape(sd.view_size(v)))
        for v in views
    }
    policy = params.registration_tp.upper()
    out = []
    for i in range(len(views)):
        for j in range(i + 1, len(views)):
            a, b = views[i], views[j]
            ta, tb = a.timepoint, b.timepoint
            if policy == INDIVIDUAL_TIMEPOINTS:
                if ta != tb:
                    continue
            elif policy == ALL_TO_ALL_RANGE:
                if abs(ta - tb) > params.range_tp:
                    continue
            elif policy == REFERENCE_TIMEPOINT:
                # each timepoint registers against the reference timepoint
                if not (ta == tb or params.reference_tp in (ta, tb)):
                    continue
            # ALL_TO_ALL: no timepoint restriction
            if params.overlap_filter and not boxes[a].overlaps(boxes[b]):
                continue
            out.append((a, b))
    return out


def _filter_to_overlap(
    sd: SpimData, ids, world, view: ViewId, other: ViewId
) -> tuple[np.ndarray, np.ndarray]:
    """Keep only points inside the pair's world overlap bbox (+epsilon)
    (filterForOverlappingInterestPoints, SparkGeometricDescriptorMatching.java:294-305)."""
    box_a = transformed_interval(sd.model(view), Interval.from_shape(sd.view_size(view)))
    box_b = transformed_interval(sd.model(other), Interval.from_shape(sd.view_size(other)))
    ov = box_a.intersect(box_b).expand(2)
    if ov.is_empty() or not len(world):
        return ids[:0], world[:0]
    keep = np.all(
        (world >= np.array(ov.min)) & (world <= np.array(ov.max)), axis=1
    )
    return ids[keep], world[keep]


def match_pair(
    wa: np.ndarray, wb: np.ndarray, params: MatchingParams, seed: int = 17
) -> tuple[np.ndarray, np.ndarray | None, int]:
    """Match two world-space point clouds.

    Returns (inlier index pairs (K,2) into wa/wb, model 3x4 a->b or None,
    n_candidates)."""
    if params.method == D.ICP:
        res = D.icp(
            wa, wb, params.model, params.regularization, params.lam,
            params.icp_max_distance, params.icp_max_iterations,
        )
        if res is None:
            return np.zeros((0, 2), np.int32), None, 0
        model, pairs = res
        return pairs, model, len(pairs)

    cand = D.match_candidates(
        wa, wb, params.method, params.n_neighbors, params.redundancy,
        params.ratio_of_distance,
    )
    if len(cand) == 0:
        return np.zeros((0, 2), np.int32), None, 0
    res = D.ransac(
        wa[cand[:, 0]], wb[cand[:, 1]],
        params.model, params.regularization, params.lam,
        params.ransac_max_epsilon, params.ransac_min_inlier_ratio,
        params.ransac_min_inliers, params.ransac_iterations, seed=seed,
    )
    if res is None:
        return np.zeros((0, 2), np.int32), None, len(cand)
    model, inliers = res
    return cand[inliers], model, len(cand)


def match_interest_points(
    sd: SpimData,
    views: list[ViewId],
    params: MatchingParams | None = None,
    store: InterestPointStore | None = None,
    progress: bool = True,
) -> list[PairMatchResult]:
    """Run pairwise matching over all planned pairs; results are NOT yet
    persisted (use ``save_matches``)."""
    params = params or MatchingParams()
    store = store or InterestPointStore.for_project(sd)
    pairs = plan_match_pairs(sd, views, params)
    if progress:
        print(f"matching: {len(pairs)} view pairs, method {params.method}, "
              f"model {params.model} reg {params.regularization} λ={params.lam}")

    cache: dict[ViewId, tuple[np.ndarray, np.ndarray]] = {}

    def world(view: ViewId):
        if view not in cache:
            ids, locs = store.load_points(view, params.label)
            w = apply_affine(sd.model(view), locs) if len(locs) else locs
            cache[view] = (ids, w)
        return cache[view]

    results = []
    for k, (va, vb) in enumerate(pairs):
        ids_a, wa = world(va)
        ids_b, wb = world(vb)
        if params.interest_points_for_overlap_only:
            ids_a, wa = _filter_to_overlap(sd, ids_a, wa, va, vb)
            ids_b, wb = _filter_to_overlap(sd, ids_b, wb, vb, va)
        with profiling.span("matching.pair"):
            inl, model, n_cand = match_pair(wa, wb, params, seed=17 + k)
        res = PairMatchResult(
            va, vb,
            ids_a[inl[:, 0]] if len(inl) else np.zeros(0, np.uint64),
            ids_b[inl[:, 1]] if len(inl) else np.zeros(0, np.uint64),
            model, n_cand,
        )
        results.append(res)
        if progress:
            print(f"  {va} <-> {vb}: {len(inl)} inliers / {n_cand} candidates")
    return results


def save_matches(
    sd: SpimData,
    store: InterestPointStore,
    results: list[PairMatchResult],
    params: MatchingParams,
    views: list[ViewId],
) -> None:
    """Persist correspondences symmetrically per view
    (MatcherPairwiseTools.addCorrespondences + save,
    SparkGeometricDescriptorMatching.java:509-545). Existing correspondences
    of re-matched views are kept and merged unless clear_correspondences."""
    label = params.label
    new: dict[ViewId, list[CorrespondingPoint]] = {v: [] for v in views}
    for r in results:
        for ia, ib in zip(r.ids_a.astype(int), r.ids_b.astype(int)):
            new.setdefault(r.view_a, []).append(
                CorrespondingPoint(ia, r.view_b, label, ib))
            new.setdefault(r.view_b, []).append(
                CorrespondingPoint(ib, r.view_a, label, ia))
    for v, corrs in new.items():
        if not params.clear_correspondences:
            existing = store.load_correspondences(v, label)
            seen = {(c.id, c.other_view, c.other_label, c.other_id)
                    for c in corrs}
            corrs = corrs + [
                c for c in existing
                if (c.id, c.other_view, c.other_label, c.other_id) not in seen
            ]
        store.save_correspondences(v, label, corrs)

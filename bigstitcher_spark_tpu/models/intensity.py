"""Intensity matching + solving driver: per-pair cell sampling, RANSAC line
fits, global solve, coefficients store.

TPU redesign of SparkIntensityMatching (SparkIntensityMatching.java:137-183)
and IntensitySolver (IntensitySolver.java:100-118): every view gets a coarse
coefficient grid (default 8x8x8, --renderScale 0.25); overlapping view pairs
contribute co-located intensity samples per cell pair; pairwise linear fits
run in one batched RANSAC kernel (ops.intensity); the global solve assembles
sufficient statistics into one quadratic form. Coefficients persist to an N5
(``setup{s}/timepoint{t}/coefficients`` shape (2, cx, cy, cz)) that
affine-fusion applies per view via trilinear interpolation over cell centers
(role of mvrecon ``Coefficients`` + SparkAffineFusion.java:545-559).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import observe
from ..io.chunkstore import ChunkStore, StorageFormat
from ..io.dataset_io import ViewLoader, best_mipmap_level
from ..io.spimdata import SpimData, ViewId
from ..ops.dog import sample_trilinear
from ..ops.intensity import (
    match_cells_histogram,
    match_cells_ransac,
    match_stats,
    solve_intensity_coefficients,
)
from ..utils.geometry import (
    Interval,
    invert_affine,
    transformed_interval,
)


@dataclass
class IntensityParams:
    """Defaults follow the reference CLI (SparkIntensityMatching.java)."""

    coefficients: tuple[int, int, int] = (8, 8, 8)
    render_scale: float = 0.25
    method: str = "RANSAC"            # RANSAC | HISTOGRAM
    ransac_epsilon: float = 0.02      # relative to [0,1]-normalized intensity
    ransac_iterations: int = 1000
    min_samples_per_cell: int = 10
    lam: float = 0.1                  # solve regularization toward identity
    max_samples_per_cell: int = 2000
    # reference candidate/inlier filters (SparkIntensityMatching.java:51-77)
    min_threshold: float = 1.0        # --minThreshold: discard samples below
    max_threshold: float = float("nan")  # --maxThreshold: discard above
    # --minNumCandidates per cell pair (SparkIntensityMatching.java:58
    # default; programmatic callers get the same filtering as the CLI)
    min_num_candidates: int = 1000
    min_inlier_ratio: float = 0.1     # --minInlierRatio (RANSAC)
    min_num_inliers: int = 10         # --minNumInliers (RANSAC)
    max_trust: float = 3.0            # --maxTrust: drop inliers with residual
    #                                   > maxTrust * median residual


@dataclass
class CellMatch:
    view_a: ViewId
    view_b: ViewId
    cell_a: int                # flat cell index within view A's grid
    cell_b: int
    stats: tuple[float, ...]   # (n, Sx, Sy, Sxx, Syy, Sxy) of inlier samples
    fit: tuple[float, float]   # (a, b): i_b ~= a*i_a + b


def _cell_index(px: np.ndarray, view_size: np.ndarray, dims) -> np.ndarray:
    """Flat coefficient-cell index for full-res pixel coords (N,3)."""
    cell = np.floor(px / (view_size / np.asarray(dims, np.float64))).astype(int)
    cell = np.clip(cell, 0, np.asarray(dims) - 1)
    return (cell[:, 0] * dims[1] + cell[:, 1]) * dims[2] + cell[:, 2]


def _sample_view(sd, loader, view, world_pts):
    """Intensities + full-res px coords of world points inside the view
    (None-padded with NaN outside)."""
    inv = invert_affine(sd.model(view))
    px = world_pts @ inv[:, :3].T + inv[:, 3]
    size = np.array(sd.view_size(view), np.float64)
    inside = np.all((px >= 0) & (px <= size - 1), axis=1)
    vals = np.full(len(px), np.nan)
    if inside.any():
        ds_factors = loader.downsampling_factors(view.setup)
        lvl = best_mipmap_level(ds_factors, (2, 2, 2))
        f = np.asarray(ds_factors[lvl], np.float64)
        lpx = (px[inside] - (f - 1) / 2.0) / f
        lo = np.maximum(np.floor(lpx.min(axis=0)).astype(int) - 1, 0)
        hi = np.ceil(lpx.max(axis=0)).astype(int) + 2
        patch = loader.read_block(view, lvl, lo, hi - lo).astype(np.float32)
        vals[inside] = sample_trilinear(patch, lpx - lo)
    return vals, px, inside


def match_pair_intensities(
    sd: SpimData, loader: ViewLoader, va: ViewId, vb: ViewId,
    params: IntensityParams, seed: int = 5,
) -> list[CellMatch]:
    """Collect co-located samples in the pair overlap on a renderScale grid
    and fit per-cell-pair linear maps."""
    box_a = transformed_interval(sd.model(va), Interval.from_shape(sd.view_size(va)))
    box_b = transformed_interval(sd.model(vb), Interval.from_shape(sd.view_size(vb)))
    ov = box_a.intersect(box_b)
    if ov.is_empty():
        return []
    step = max(1.0 / params.render_scale, 1.0)
    axes = [np.arange(ov.min[d], ov.max[d] + 1, step) for d in range(3)]
    gx, gy, gz = np.meshgrid(*axes, indexing="ij")
    world = np.stack([gx.ravel(), gy.ravel(), gz.ravel()], axis=-1)

    ia, pa, in_a = _sample_view(sd, loader, va, world)
    ib, pb, in_b = _sample_view(sd, loader, vb, world)
    both = in_a & in_b & np.isfinite(ia) & np.isfinite(ib)
    # intensity thresholds: discard candidates outside [min, max]
    both &= (ia >= params.min_threshold) & (ib >= params.min_threshold)
    if np.isfinite(params.max_threshold):
        both &= (ia <= params.max_threshold) & (ib <= params.max_threshold)
    if not both.any():
        return []
    dims = params.coefficients
    ca = _cell_index(pa[both], np.array(sd.view_size(va), np.float64), dims)
    cb = _cell_index(pb[both], np.array(sd.view_size(vb), np.float64), dims)
    xa, xb = ia[both], ib[both]

    # normalize to [0,1] for a scale-free RANSAC epsilon
    scale = max(float(np.max(xa)), float(np.max(xb)), 1e-9)
    xa_n, xb_n = xa / scale, xb / scale

    groups: dict[tuple[int, int], np.ndarray] = {}
    order = np.lexsort((cb, ca))
    keys = np.stack([ca[order], cb[order]], axis=1)
    uniq, starts = np.unique(keys, axis=0, return_index=True)
    bounds = list(starts) + [len(order)]
    sa_list, sb_list, pairs = [], [], []
    min_cand = max(params.min_samples_per_cell, params.min_num_candidates)
    for i, (cell_a, cell_b) in enumerate(uniq):
        sel = order[bounds[i]:bounds[i + 1]]
        if len(sel) < min_cand:
            continue
        if len(sel) > params.max_samples_per_cell:
            sel = sel[:: len(sel) // params.max_samples_per_cell + 1]
        sa_list.append(xa_n[sel])
        sb_list.append(xb_n[sel])
        pairs.append((int(cell_a), int(cell_b), sel))

    if not pairs:
        return []
    if params.method.upper() == "HISTOGRAM":
        fits = match_cells_histogram(sa_list, sb_list,
                                     params.min_samples_per_cell)
    else:
        fits = match_cells_ransac(
            sa_list, sb_list, epsilon=params.ransac_epsilon,
            min_inliers=params.min_samples_per_cell,
            iterations=params.ransac_iterations, seed=seed,
        )
    out = []
    for (cell_a, cell_b, sel), fit in zip(pairs, fits):
        if fit is None:
            continue
        a, b, _ = fit
        # inlier stats in ORIGINAL intensity units for the global solve
        x, y = xa[sel], xb[sel]
        xn, yn = x / scale, y / scale
        resid = np.abs(yn - (a * xn + b))
        inl = resid < 2.0 * params.ransac_epsilon
        # --maxTrust: iterative trim + REFIT (mpicbg filterRansac: drop
        # candidates with residual > maxTrust * median, refit, repeat)
        for _ in range(10):
            if inl.sum() < 2:
                break
            A = np.stack([xn[inl], np.ones(int(inl.sum()))], axis=1)
            (a, b), *_ = np.linalg.lstsq(A, yn[inl], rcond=None)
            resid = np.abs(yn - (a * xn + b))
            med = float(np.median(resid[inl]))
            new_inl = inl & (resid <= max(params.max_trust * med,
                                          1e-12))
            if (new_inl == inl).all():
                break
            inl = new_inl
        if inl.sum() < max(params.min_samples_per_cell,
                           params.min_num_inliers):
            continue
        if inl.sum() < params.min_inlier_ratio * len(sel):
            continue
        out.append(CellMatch(
            va, vb, int(cell_a), int(cell_b),
            match_stats(x[inl], y[inl]),
            (float(a), float(b * scale)),
        ))
    return out


def _pair_sample_boxes(sd, loader, va, vb, ov):
    """``(ds, offset, shape)`` source boxes of the two level-patch reads a
    pair's ``_sample_view`` calls make — the async prefetcher feed
    (parallel.pairsched ``prefetch_boxes``). The sample grid's pixel extremes
    sit at overlap corners under an affine model, so the corner-derived box
    covers the pair's ``read_block`` (over-covering by at most one grid step,
    clipped by ``prefetch_box``)."""
    corners = np.array([[ov.min[d] if (i >> d) & 1 == 0 else ov.max[d]
                         for d in range(3)] for i in range(8)], np.float64)
    boxes = []
    for v in (va, vb):
        inv = invert_affine(sd.model(v))
        px = corners @ inv[:, :3].T + inv[:, 3]
        size = np.array(sd.view_size(v), np.float64)
        px = np.clip(px, 0, size - 1)
        ds_factors = loader.downsampling_factors(v.setup)
        lvl = best_mipmap_level(ds_factors, (2, 2, 2))
        f = np.asarray(ds_factors[lvl], np.float64)
        lpx = (px - (f - 1) / 2.0) / f
        lo = np.maximum(np.floor(lpx.min(axis=0)).astype(int) - 1, 0)
        hi = np.ceil(lpx.max(axis=0)).astype(int) + 2
        b = loader.prefetch_box(v, lvl, tuple(int(x) for x in lo),
                                tuple(int(x) for x in hi - lo))
        if b is not None:
            boxes.append(b)
    return boxes


def match_intensities(
    sd: SpimData, loader: ViewLoader, views: list[ViewId],
    params: IntensityParams | None = None, progress: bool = True,
    devices: int | None = None,
) -> list[CellMatch]:
    """All overlapping pairs (SparkIntensityMatching.java:146-166).

    Pairs spread over every local device via the pair scheduler, weighted
    by each overlap's renderScale-grid sample count; seeds are attached
    per pair so placement never changes the fits and multi-device output
    equals single-device exactly."""
    from ..parallel.pairsched import PairTask, run_pair_tasks

    params = params or IntensityParams()
    views = sorted(views)
    boxes = {
        v: transformed_interval(sd.model(v), Interval.from_shape(sd.view_size(v)))
        for v in views
    }
    step = max(1.0 / params.render_scale, 1.0)
    pairs: list[tuple[ViewId, ViewId]] = []
    tasks: list[PairTask] = []
    for i in range(len(views)):
        for j in range(i + 1, len(views)):
            va, vb = views[i], views[j]
            if va.timepoint != vb.timepoint:
                continue
            if not boxes[va].overlaps(boxes[vb]):
                continue
            ov = boxes[va].intersect(boxes[vb])
            # placement ∝ the pair's sample-grid point count
            n_samples = float(np.prod(
                [max(1.0, (ov.shape[d] - 1) / step + 1) for d in range(3)]))
            tasks.append(PairTask(index=len(tasks), cost=n_samples,
                                  tag=(len(pairs), va, vb)))
            pairs.append((va, vb))

    def run_one(task):
        k, va, vb = task.tag
        return match_pair_intensities(sd, loader, va, vb, params, seed=5 + k)

    def prefetch_boxes(task):
        k, va, vb = task.tag
        return _pair_sample_boxes(sd, loader, va, vb,
                                  boxes[va].intersect(boxes[vb]))

    outs = run_pair_tasks(tasks, run_one, n_devices=devices,
                          stage="intensity", prefetch_boxes=prefetch_boxes)
    matches: list[CellMatch] = []
    for (va, vb), m in zip(pairs, outs):
        matches.extend(m)
        observe.log(f"  {va} <-> {vb}: {len(m)} cell matches",
                    stage="match-intensities", echo=progress,
                    matches=len(m))
    return matches


# --------------------------------------------------------------------------
# persistence (matches + coefficients N5)
# --------------------------------------------------------------------------

MATCH_GROUP = "matches"
COEFF_GROUP = "coefficients"


class IntensityStore:
    """N5 store for pairwise cell matches and solved coefficients
    (ViewPairCoefficientMatchesIO + Coefficients persistence role)."""

    def __init__(self, root: str):
        import os

        self.root = str(root)
        if os.path.isdir(self.root):
            self.store = ChunkStore.open(self.root)
        else:
            self.store = ChunkStore.create(self.root, StorageFormat.N5)

    @staticmethod
    def for_project(sd: SpimData, name: str = "intensity.n5") -> "IntensityStore":
        import os

        base = os.path.dirname(sd.xml_path or ".")
        return IntensityStore(os.path.join(base, name))

    @staticmethod
    def _pair_path(va: ViewId, vb: ViewId) -> str:
        return (f"{MATCH_GROUP}/tpId_{va.timepoint}_viewSetupId_{va.setup}"
                f"__tpId_{vb.timepoint}_viewSetupId_{vb.setup}")

    def save_matches(self, matches: list[CellMatch],
                     dims: tuple[int, int, int]) -> None:
        by_pair: dict[tuple[ViewId, ViewId], list[CellMatch]] = {}
        for m in matches:
            by_pair.setdefault((m.view_a, m.view_b), []).append(m)
        if self.store.exists(MATCH_GROUP):
            self.store.remove(MATCH_GROUP)
        for (va, vb), ms in by_pair.items():
            rows = np.array(
                [[m.cell_a, m.cell_b, *m.stats, *m.fit] for m in ms],
                np.float64,
            )  # (M, 10)
            path = self._pair_path(va, vb)
            ds = self.store.create_dataset(
                f"{path}/data", rows.shape, (max(len(ms), 1), 10), "float64"
            )
            ds.write(rows, (0, 0))
        self.store.set_attribute(MATCH_GROUP, "coefficientDims", list(dims))

    def load_all_matches(self) -> list[CellMatch]:
        out = []
        if not self.store.exists(MATCH_GROUP):
            return out
        for name in self.store.list_children(MATCH_GROUP):
            a, b = name.split("__")
            va = ViewId(int(a.split("_")[1]), int(a.split("_")[3]))
            vb = ViewId(int(b.split("_")[1]), int(b.split("_")[3]))
            rows = self.store.open_dataset(
                f"{MATCH_GROUP}/{name}/data").read_full()
            for r in rows:
                out.append(CellMatch(va, vb, int(r[0]), int(r[1]),
                                     tuple(r[2:8]), (r[8], r[9])))
        return out

    def coefficient_dims(self) -> tuple[int, int, int] | None:
        d = self.store.get_attribute(MATCH_GROUP, "coefficientDims", None)
        return tuple(int(v) for v in d) if d else None

    def save_coefficients(self, view: ViewId, coeffs: np.ndarray,
                          group: str | None = None,
                          dataset: str | None = None) -> None:
        """coeffs (cx,cy,cz,2) -> dataset (2,cx,cy,cz). ``group``/``dataset``
        override the default layout (--intensityN5Group/--intensityN5Dataset,
        IntensitySolver.java)."""
        path = (f"{group or COEFF_GROUP}/setup{view.setup}"
                f"/timepoint{view.timepoint}/{dataset or 'coefficients'}")
        arr = np.moveaxis(coeffs, -1, 0).astype(np.float64)
        if self.store.exists(path):
            self.store.remove(path)
        ds = self.store.create_dataset(path, arr.shape, arr.shape, "float64")
        ds.write(arr, (0,) * arr.ndim)

    def load_coefficients(self, view: ViewId,
                          group: str | None = None,
                          dataset: str | None = None) -> np.ndarray | None:
        path = (f"{group or COEFF_GROUP}/setup{view.setup}"
                f"/timepoint{view.timepoint}/{dataset or 'coefficients'}")
        if not self.store.is_dataset(path):
            return None
        arr = self.store.open_dataset(path).read_full()
        return np.moveaxis(arr, 0, -1)


def smoothness_pairs(dims: tuple[int, int, int], n_views: int) -> np.ndarray:
    """Intra-view adjacent-cell pairs for every view's coefficient grid,
    as a (P, 2) array of GLOBAL flat cell indices.

    Pure index arithmetic (one sliced ``arange`` cube per axis broadcast
    over views) — the former per-view cx/cy/cz/axis quadruple Python loop
    walked every cell of every view and dominated ``solve_intensities``
    setup at large grids. Same pair set, axis-major order."""
    ncell = int(np.prod(dims))
    idx = np.arange(ncell).reshape(dims)
    per_axis = []
    for d in range(3):
        lo = [slice(None)] * 3
        hi = [slice(None)] * 3
        lo[d] = slice(0, dims[d] - 1)
        hi[d] = slice(1, dims[d])
        per_axis.append(np.stack(
            [idx[tuple(lo)].ravel(), idx[tuple(hi)].ravel()], axis=1))
    base = np.concatenate(per_axis, axis=0)
    offs = (np.arange(n_views) * ncell)[:, None, None]
    return (base[None, :, :] + offs).reshape(-1, 2)


def solve_intensities(
    matches: list[CellMatch],
    views: list[ViewId],
    dims: tuple[int, int, int],
    lam: float = 0.1,
    progress: bool = True,
) -> dict[ViewId, np.ndarray]:
    """Global solve -> per-view (cx,cy,cz,2) [scale, offset] grids."""
    views = sorted(views)
    ncell = int(np.prod(dims))
    base = {v: i * ncell for i, v in enumerate(views)}
    stats_rows = []
    for m in matches:
        if m.view_a not in base or m.view_b not in base:
            continue
        stats_rows.append((base[m.view_a] + m.cell_a,
                           base[m.view_b] + m.cell_b, *m.stats))
    observe.log(f"solve-intensities: {len(views)} views x {ncell} cells, "
                f"{len(stats_rows)} matches, λ={lam}",
                stage="solve-intensities", echo=progress,
                views=len(views), cells=ncell, matches=len(stats_rows))
    # intensities can be large (uint16): normalize the quadratic form by the
    # global mean intensity so lam is scale-free
    mean_i = (np.mean([r[3] / max(r[2], 1) for r in stats_rows])
              if stats_rows else 1.0)
    s = 1.0 / max(mean_i, 1e-9)
    norm = []
    for ca, cb, n, sx, sy, sxx, syy, sxy in stats_rows:
        norm.append((int(ca), int(cb), n, sx * s, sy * s,
                     sxx * s * s, syy * s * s, sxy * s * s))
    # intra-view smoothness: 6-neighborhood of each cell grid, propagating
    # corrections into cells without overlap matches
    smooth = smoothness_pairs(dims, len(views))
    dev_sol: list = []
    sol = solve_intensity_coefficients(ncell * len(views), norm, lam,
                                       smooth_pairs=smooth,
                                       on_device_solution=dev_sol.append)
    # un-normalize: f(i) = a*(i*s)/s + b/s... scale invariant: offsets scale
    out = {}
    for v in views:
        c = sol[base[v]: base[v] + ncell].copy()
        c[:, 1] /= s
        out[v] = c.reshape(*dims, 2)
    if dev_sol:
        _register_device_coefficients(dev_sol[0], out, views, base, ncell,
                                      dims, s)
    return out


def _register_device_coefficients(dev, out, views, base, ncell, dims, s):
    """Mirror the host un-normalization ON DEVICE from the CG solver's
    device output and register the per-view grids with the fusion
    coefficient-table cache (models.affine_fusion.register_coefficient_table):
    the solve→fusion coefficient path stays device-resident, so a
    following fusion's first table lookup hits without the grids ever
    making the host->device round trip. The float64 math is the same IEEE
    sequence as the host branch above, so the registered table is
    bit-identical to one rebuilt from ``out``."""
    try:
        import jax.numpy as jnp
        from jax.experimental import enable_x64

        from .affine_fusion import register_coefficient_table

        with enable_x64():
            d = jnp.reshape(dev[: 2 * ncell * len(views)], (-1, 2))
            per = {}
            for v in views:
                c = d[base[v]: base[v] + ncell]
                c = jnp.concatenate([c[:, :1], c[:, 1:] / s], axis=1)
                per[v] = jnp.reshape(c, (*dims, 2)).astype(jnp.float32)
        register_coefficient_table(out, per)
    except Exception as e:  # pragma: no cover - residency is best-effort
        observe.log(f"device coefficient registration skipped: {e!r}",
                    stage="solve-intensities")

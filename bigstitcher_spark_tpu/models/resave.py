"""Resave driver: copy a project's views into a chunked multi-resolution
container and rewire the XML (SparkResaveN5 equivalent).

Reference call stack (SparkResaveN5.java:107-457): plan per-view dims/grids,
create all datasets + BDV metadata, copy s0 block-parallel with retry, build
pyramid levels by chained 2x half-pixel downsampling, then swap the XML's
imgloader to the new container. Here blocks are copied by a host thread pool
(IO-bound; tensorstore releases the GIL) and downsampling runs as an XLA
kernel per block — the reference's race-freedom invariant (writers own
disjoint chunks) is preserved by the grid construction.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from .. import observe
from ..io.chunkstore import ChunkStore, StorageFormat
from ..io.container import estimate_multires_pyramid, _relative_steps
from ..io.dataset_io import ViewLoader, create_bdv_view_datasets
from ..io.spimdata import ImageLoader, SpimData, ViewId
from ..parallel.retry import run_with_retry
from ..utils.grid import create_grid
from .downsample_driver import (
    _convert_to_dtype,
    prefetch_src_box,
    read_padded,
    run_sharded_downsample,
    validate_pyramid,
)


@dataclass
class ResaveStats:
    views: int = 0
    s0_blocks: int = 0
    pyramid_blocks: int = 0
    seconds: float = 0.0


def propose_pyramid(sd: SpimData, views: list[ViewId]) -> list[list[int]]:
    """Automatic pyramid from the largest view's dims
    (ExportN5Api.estimateMultiResPyramid role, SparkResaveN5.java:204-209)."""
    dims = np.max([sd.view_size(v) for v in views], axis=0)
    return estimate_multires_pyramid(dims)


def resave(
    sd: SpimData,
    loader: ViewLoader,
    views: list[ViewId],
    out_path: str,
    storage_format: StorageFormat = StorageFormat.N5,
    block_size: tuple[int, int, int] = (128, 128, 64),
    block_scale: tuple[int, int, int] = (16, 16, 1),
    downsamplings: list[list[int]] | None = None,
    compression: str = "zstd",
    threads: int = 8,
    dry_run: bool = False,
    devices: int | None = None,
) -> ResaveStats:
    """Copy ``views`` into a BDV-layout container at ``out_path``.

    Output layout is ``setup{S}/timepoint{T}/s{L}`` for both N5 and ZARR
    (the bdv.n5 contract our ViewLoader reads back; dataset_io.py)."""
    stats = ResaveStats()
    t0 = time.time()
    if downsamplings is None:
        downsamplings = propose_pyramid(sd, views)
    validate_pyramid(downsamplings)
    rel = _relative_steps(downsamplings)
    if dry_run:
        return stats

    store = ChunkStore.create(out_path, storage_format)

    # dataset + metadata creation for every view (driver-side parallel stream
    # in the reference, SparkResaveN5.java:226-260)
    per_view_datasets: dict[ViewId, list] = {}
    for v in views:
        shape = sd.view_size(v)
        dtype = loader.open(v, 0).dtype
        per_view_datasets[v] = create_bdv_view_datasets(
            store, v.setup, v.timepoint, shape, block_size, dtype.name,
            downsampling_factors=downsamplings, compression=compression,
        )
    stats.views = len(views)

    # s0 copy, block-parallel with retry (SparkResaveN5.java:278-329)
    compute_block = tuple(b * s for b, s in zip(block_size, block_scale))
    s0_jobs: list[tuple[ViewId, object]] = []
    for v in views:
        for blk in create_grid(sd.view_size(v), compute_block, block_size):
            s0_jobs.append((v, blk))

    def copy_s0(job):
        v, blk = job
        src = loader.open(v, 0)
        data = src.read(blk.offset, blk.size)
        per_view_datasets[v][0].write(data, blk.offset)

    from ..parallel.distributed import barrier, partition_items

    s0_jobs = partition_items(s0_jobs)  # multi-host: each process its slice
    run_with_retry(s0_jobs, copy_s0, label="resave s0 block", threads=threads)
    stats.s0_blocks = len(s0_jobs)
    barrier("resave-s0")  # level 1 reads s0 chunks other processes wrote

    # pyramid levels from the previous level, block-sharded over the device
    # mesh across ALL views at once (SparkResaveN5.java:336-415)
    for lvl in range(1, len(downsamplings)):
        level_jobs: list[tuple[ViewId, object]] = []
        for v in views:
            dst = per_view_datasets[v][lvl]
            for blk in create_grid(dst.shape, compute_block, block_size):
                level_jobs.append((v, blk))
        f = tuple(int(x) for x in rel[lvl])

        def read_job(job, level=lvl, f=f):
            v, blk = job
            src = per_view_datasets[v][level - 1]
            src_off = [o * x for o, x in zip(blk.offset, f)]
            src_size = [s * x for s, x in zip(blk.size, f)]
            return read_padded(src.read, src.shape, src_off, src_size)

        def write_job(job, out, level=lvl):
            v, blk = job
            dst = per_view_datasets[v][level]
            dst.write(_convert_to_dtype(out, dst.dtype), blk.offset)

        def prefetch_job(job, level=lvl, f=f):
            v, blk = job
            src = per_view_datasets[v][level - 1]
            b = prefetch_src_box(src,
                                 [o * x for o, x in zip(blk.offset, f)],
                                 [s * x for s, x in zip(blk.size, f)])
            return [b] if b is not None else []

        level_jobs = partition_items(level_jobs)
        run_sharded_downsample(level_jobs, read_job, write_job, f,
                               devices=devices, io_threads=threads,
                               label=f"resave s{lvl} block", multihost=False,
                               prefetch_job=prefetch_job)
        stats.pyramid_blocks += len(level_jobs)
        barrier(f"resave-s{lvl}")  # next level reads this level's chunks

    stats.seconds = time.time() - t0
    observe.progress.record_stage(
        "resave",
        done=stats.s0_blocks + stats.pyramid_blocks,
        views=stats.views,
        s0_blocks=stats.s0_blocks,
        pyramid_blocks=stats.pyramid_blocks,
        seconds=round(stats.seconds, 3),
        rate_per_s=round((stats.s0_blocks + stats.pyramid_blocks)
                         / max(stats.seconds, 1e-9), 3),
    )
    return stats


def swap_imgloader(sd: SpimData, container_path: str,
                   storage_format: StorageFormat) -> None:
    """Point the project at the new container
    (SparkResaveN5.java:424-446 imgloader swap)."""
    fmt = "bdv.n5" if storage_format == StorageFormat.N5 else "bdv.zarr"
    sd.image_loader = ImageLoader(format=fmt, path=str(container_path),
                                  path_type="absolute", raw=None)

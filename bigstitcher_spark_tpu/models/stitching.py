"""Pairwise stitching driver: plan overlap pairs, extract + aggregate crops,
run the batched phase-correlation kernel, filter, store results.

TPU redesign of SparkPairwiseStitching (reference call stack SURVEY.md §3.2):
the work list is the set of overlapping grouped-view pairs (strategy P2);
pairs are bucketed by padded crop shape so one compiled kernel serves every
pair in a bucket, then results are filtered (minR/maxShift) and written into
the XML with a registration hash for solver staleness checks
(SparkPairwiseStitching.java:287-299,347-382).

Shift semantics (used by the solver): a stored result with shift S means the
per-view correction translations must satisfy ``c_A - c_B = S`` — S is the
world-space displacement by which group B's current render is offset against
group A's (derivation in ``_refine_bucket``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import jax
import numpy as np

from ..io.dataset_io import ViewLoader, best_mipmap_level
from ..io.spimdata import (
    PairwiseStitchingResult,
    SpimData,
    ViewId,
    registration_hash,
)
from ..ops.downsample import downsample_block
from ..ops.phasecorr import pad_to, pcm_peaks_batch, refine_peaks
from ..utils.geometry import (
    Interval,
    concatenate,
    invert_affine,
    transformed_interval,
    translation_affine,
)
from .. import observe, profiling
from ..observe import metrics as _metrics

_H2D_BYTES = _metrics.counter("bst_xfer_h2d_bytes_total")
_H2D_SAVED = _metrics.counter("bst_xfer_h2d_bytes_saved_total")


@dataclass
class StitchingParams:
    """Defaults match the reference CLI (SparkPairwiseStitching.java:76-106)."""

    downsampling: tuple[int, int, int] = (2, 2, 1)
    peaks_to_check: int = 5
    subpixel: bool = True
    min_r: float = 0.3
    max_r: float = 1.0
    max_shift: tuple[float, float, float] = (np.inf, np.inf, np.inf)
    max_shift_total: float = np.inf
    channel_combine: str = "AVERAGE"        # AVERAGE | PICK_BRIGHTEST
    illum_combine: str = "PICK_BRIGHTEST"   # AVERAGE | PICK_BRIGHTEST
    min_overlap_px: int = 32
    # candidate shifts must keep at least this fraction of the overlap crop
    # in play: a near-total shift can score a HIGHER Pearson r than the true
    # one by chance over a few thousand background voxels (observed on the
    # 2x2 fixture's corner pairs at full resolution)
    min_overlap_frac: float = 0.25
    batch_size: int = 16
    # PER-DEVICE ceiling on dispatched-but-undrained PCM bytes (padded f32
    # crop stacks x the FFT workspace multiplier below). None derives the
    # budget from the backend's memory_stats (utils.devicemem;
    # BST_PAIR_INFLIGHT_BYTES overrides per device) instead of a flat
    # constant that either starves big HBMs or overcommits small ones.
    inflight_bytes: int | None = None


@dataclass
class ViewGroup:
    """Views of one tile grouped over channel+illumination
    (reference grouping: group {Channel, Illumination}, compare {Tile},
    SparkPairwiseStitching.java:146-160)."""

    timepoint: int
    angle: int
    tile: int
    views: tuple[ViewId, ...]

    @property
    def key(self):
        return (self.timepoint, self.angle, self.tile)


def build_groups(sd: SpimData, views: list[ViewId]) -> list[ViewGroup]:
    by_key: dict[tuple, list[ViewId]] = {}
    for v in views:
        s = sd.setups[v.setup]
        key = (v.timepoint, s.attributes.get("angle", 0), s.attributes.get("tile", 0))
        by_key.setdefault(key, []).append(v)
    return [
        ViewGroup(k[0], k[1], k[2], tuple(sorted(vs)))
        for k, vs in sorted(by_key.items())
    ]


def group_bbox(sd: SpimData, g: ViewGroup) -> Interval:
    """World-space bbox of a group (union over member views)."""
    box = None
    for v in g.views:
        iv = transformed_interval(sd.model(v), Interval.from_shape(sd.view_size(v)))
        box = iv if box is None else box.union(iv)
    return box


def plan_pairs(sd: SpimData, groups: list[ViewGroup]) -> list[tuple[ViewGroup, ViewGroup, Interval]]:
    """All overlapping group pairs within one (timepoint, angle) slice
    (compare {Tile}, apply over {TimePoint, Angle};
    TransformationTools.filterNonOverlappingPairs role)."""
    out = []
    boxes = {g.key: group_bbox(sd, g) for g in groups}
    for i in range(len(groups)):
        for j in range(i + 1, len(groups)):
            a, b = groups[i], groups[j]
            if (a.timepoint, a.angle) != (b.timepoint, b.angle):
                continue
            if not boxes[a.key].overlaps(boxes[b.key]):
                continue
            ov = boxes[a.key].intersect(boxes[b.key])
            if ov.is_empty():
                continue
            out.append((a, b, ov))
    return out


def _aggregate(sd: SpimData, crops: dict[ViewId, np.ndarray], group: ViewGroup,
               params: StitchingParams) -> np.ndarray:
    """GroupedViewAggregator: combine channels (AVERAGE default) then
    illuminations (PICK_BRIGHTEST default) of one tile
    (SparkPairwiseStitching.java:204-208)."""
    def combine(imgs: list[np.ndarray], how: str) -> np.ndarray:
        if len(imgs) == 1:
            return imgs[0]
        if how == "AVERAGE":
            return np.mean(imgs, axis=0)
        if how == "PICK_BRIGHTEST":
            return imgs[int(np.argmax([np.sum(i, dtype=np.float64) for i in imgs]))]
        raise ValueError(f"unknown aggregation {how}")

    by_illum: dict[int, list[np.ndarray]] = {}
    for v in group.views:
        illum = sd.setups[v.setup].attributes.get("illumination", 0)
        by_illum.setdefault(illum, []).append(crops[v])
    per_illum = [combine(imgs, params.channel_combine)
                 for _, imgs in sorted(by_illum.items())]
    return combine(per_illum, params.illum_combine)


def _downsample_crop(crop: np.ndarray, ds: Sequence[int]) -> np.ndarray:
    if all(int(f) == 1 for f in ds):
        return crop.astype(np.float32)
    pad = [(0, (-crop.shape[d]) % int(ds[d])) for d in range(3)]
    if any(p[1] for p in pad):
        crop = np.pad(crop, pad, mode="edge")
    return jax.device_get(downsample_block(crop, tuple(int(f) for f in ds)))


@dataclass
class _PairJob:
    group_a: ViewGroup
    group_b: ViewGroup
    overlap: Interval
    crop_a: np.ndarray       # downsampled, float32
    crop_b: np.ndarray
    # shift post-processing: S = linear @ (p0b - p0a + residual_ds*s)
    # - (t_a - t_b) with linear/t from the LEVEL model (model o mipmap), or
    # S = ds*s for the rendered (non-equal-transform) path
    linear: np.ndarray | None
    p0_delta: np.ndarray | None
    t_delta: np.ndarray | None
    models_a: list[np.ndarray] = field(default_factory=list)
    models_b: list[np.ndarray] = field(default_factory=list)
    residual_ds: tuple[int, int, int] = (1, 1, 1)


def _equal_linear(models: list[np.ndarray]) -> bool:
    return all(np.allclose(m[:, :3], models[0][:, :3], atol=1e-9) for m in models)


def _pick_common_level(loader, views, ds) -> tuple[dict, tuple[int, int, int]] | None:
    """Coarsest stored mipmap level usable by every view of the pair whose
    factors exactly divide the requested downsampling (reference
    openAndDownsample picks stored levels before computing the rest,
    SparkInterestPointDetection.java:998-1118). Views may store the same
    factors at different level indexes, so the per-view LEVEL is returned
    alongside the common factors. None -> read s0."""
    levels: dict = {}
    common_f = None
    for v in views:
        factors = loader.downsampling_factors(v.setup)
        lvl = best_mipmap_level(factors, ds)
        f = tuple(int(x) for x in factors[lvl])
        if any(int(ds[d]) % f[d] != 0 for d in range(3)):
            return None
        if common_f is None:
            common_f = f
        elif f != common_f:
            return None
        levels[v] = lvl
    return levels, common_f


def _extract_pair_job(sd, loader, ga, gb, overlap, params) -> _PairJob | None:
    models_a = [sd.model(v) for v in ga.views]
    models_b = [sd.model(v) for v in gb.views]
    ds = params.downsampling

    if _equal_linear(models_a + models_b):
        # read at the coarsest stored level that divides the requested
        # downsampling; the rest is averaged in memory
        all_views = list(ga.views) + list(gb.views)
        common = _pick_common_level(loader, all_views, ds)
        if common is None:
            levels, f = {v: 0 for v in all_views}, (1, 1, 1)
        else:
            levels, f = common
        rel = tuple(int(ds[d]) // f[d] for d in range(3))
        mip = loader.mipmap_transform(ga.views[0].setup, levels[ga.views[0]])

        # raster the overlap into each view's LEVEL pixel space; exact
        # integer offsets enter the shift formula so rounding costs no
        # accuracy (model' = model o mipmap: level px -> world)
        lvl_shape = tuple(
            int(np.ceil(overlap.shape[d] / f[d])) for d in range(3)
        )

        def crops_for(group, models):
            crops = {}
            p0 = None
            for v, m in zip(group.views, models):
                inv = invert_affine(concatenate(m, mip))
                p0v = np.round(inv[:, :3] @ np.array(overlap.min, np.float64)
                               + inv[:, 3]).astype(np.int64)
                if p0 is None:
                    p0 = p0v
                crops[v] = loader.read_block(v, levels[v], tuple(p0v), lvl_shape
                                             ).astype(np.float32)
            return crops, p0

        crops_a, p0a = crops_for(ga, models_a)
        crops_b, p0b = crops_for(gb, models_b)
        agg_a = _aggregate(sd, crops_a, ga, params)
        agg_b = _aggregate(sd, crops_b, gb, params)
        total_a = concatenate(models_a[0], mip)
        total_b = concatenate(models_b[0], mip)
        return _PairJob(
            ga, gb, overlap,
            _downsample_crop(agg_a, rel), _downsample_crop(agg_b, rel),
            linear=total_a[:, :3].copy(),
            p0_delta=(p0b - p0a).astype(np.float64),
            t_delta=(total_a[:, 3] - total_b[:, 3]).copy(),
            models_a=models_a, models_b=models_b,
            residual_ds=rel,
        )

    # non-equal transforms: render each group virtually over the overlap
    # (computeStitchingNonEqualTransformations, SparkPairwiseStitching.java:259-267)
    from .affine_fusion import fuse_grid_block
    from ..utils.grid import GridBlock

    o_ds = Interval(
        tuple(int(np.floor(overlap.min[d] / ds[d])) for d in range(3)),
        tuple(int(np.ceil((overlap.max[d] + 1) / ds[d])) - 1 for d in range(3)),
    )
    scale = np.diag([1.0 / f for f in ds])
    pre = np.hstack([scale, np.zeros((3, 1))])

    def render(group):
        block = GridBlock((0, 0, 0), o_ds.shape, (0, 0, 0))
        res = fuse_grid_block(
            sd, loader, list(group.views), block, o_ds,
            fusion_type="AVG", anisotropy=pre,
        )
        if res is None:
            return None
        return res[0]

    ra, rb = render(ga), render(gb)
    if ra is None or rb is None:
        return None
    return _PairJob(ga, gb, overlap, ra, rb,
                    linear=None, p0_delta=None, t_delta=None,
                    models_a=models_a, models_b=models_b)


def _pair_crop_boxes(sd, loader, ga, gb, overlap, params):
    """``(ds, offset, shape)`` source boxes of the equal-linear crop reads in
    ``_extract_pair_job`` — the async prefetcher feed (io/prefetch.py).
    Mirrors the level/mipmap/p0 arithmetic exactly so the prefetched chunks
    are the ones the extract loop decodes; empty for the non-equal
    (virtually rendered) path."""
    models_a = [sd.model(v) for v in ga.views]
    models_b = [sd.model(v) for v in gb.views]
    if not _equal_linear(models_a + models_b):
        return []
    all_views = list(ga.views) + list(gb.views)
    common = _pick_common_level(loader, all_views, params.downsampling)
    if common is None:
        levels, f = {v: 0 for v in all_views}, (1, 1, 1)
    else:
        levels, f = common
    mip = loader.mipmap_transform(ga.views[0].setup, levels[ga.views[0]])
    lvl_shape = tuple(
        int(np.ceil(overlap.shape[d] / f[d])) for d in range(3)
    )
    boxes = []
    for group, models in ((ga, models_a), (gb, models_b)):
        for v, m in zip(group.views, models):
            inv = invert_affine(concatenate(m, mip))
            p0v = np.round(inv[:, :3] @ np.array(overlap.min, np.float64)
                           + inv[:, 3]).astype(np.int64)
            b = loader.prefetch_box(v, levels[v],
                                    tuple(int(o) for o in p0v), lvl_shape)
            if b is not None:
                boxes.append(b)
    return boxes


def _fft_shape(shape: Sequence[int]) -> tuple[int, ...]:
    """Next power of two per axis (TPU FFTs are fastest/most accurate at
    powers of two; wrap ambiguity is resolved by the host correlation
    check, ops/phasecorr.refine_peaks)."""
    return tuple(1 << max(0, int(np.ceil(np.log2(max(int(s), 1))))) for s in shape)


def stitch_all_pairs(
    sd: SpimData,
    loader: ViewLoader,
    views: list[ViewId],
    params: StitchingParams | None = None,
    progress: bool = True,
    devices: int | None = None,
) -> list[PairwiseStitchingResult]:
    """Compute pairwise shifts for every overlapping tile pair.

    Returns unfiltered results; apply ``filter_results`` + store into
    ``sd.stitching_results`` (the driver-side collect of the reference)."""
    params = params or StitchingParams()
    groups = build_groups(sd, views)
    pairs = plan_pairs(sd, groups)
    observe.log(f"stitching: {len(groups)} groups, {len(pairs)} overlapping "
                "pairs", stage="stitching", echo=progress,
                groups=len(groups), pairs=len(pairs))

    from ..io import prefetch as _prefetch

    if _prefetch.enabled():
        # warm the chunk LRU ahead of the serial extract loop below: each
        # pair's crop reads are known now, so the read-ahead pool overlaps
        # remote fetches with the per-pair decode + aggregate work
        for ga, gb, ov in pairs:
            _prefetch.submit(
                lambda a=ga, b=gb, o=ov:
                _pair_crop_boxes(sd, loader, a, b, o, params))

    jobs: list[_PairJob] = []
    for ga, gb, ov in pairs:
        with profiling.span("stitching.extract"):
            job = _extract_pair_job(sd, loader, ga, gb, ov, params)
        if job is not None:
            jobs.append(job)

    return stitch_jobs(sd, jobs, params, devices=devices)


# resident bytes one PCM dispatch pins beyond its a+b f32 input stacks:
# windowed copies, two rfftn complex spectra, the normalized cross-power
# and the irfftn PCM — ~4x the input stacks in practice (ADVICE r5: the
# old ledger charged only the inputs and undercounted the FFT workspace)
_FFT_WORKSPACE_MULT = 4.0


def stitch_jobs(sd, jobs: list[_PairJob], params: StitchingParams,
                devices: int | None = None, multihost: bool | None = None
                ) -> list[PairwiseStitchingResult]:
    """Run the device PCM + host refinement pipeline over prepared jobs.

    Chunks (shape-bucketed pair batches) become pair-scheduler tasks spread
    over every local device (parallel.pairsched): placement is weighted by
    FFT volume, each device bounds its dispatched-but-undrained bytes with
    its own window (inputs x FFT workspace multiplier against the
    device-derived budget — ``params.inflight_bytes`` overrides), and each
    device's drain is pipelined so host refinement of one bucket overlaps
    the device FFTs of the next. One local device degrades to exactly that
    pipelined loop on the caller's thread (the pre-sharding path).

    In a multi-process world chunks split across processes FIRST
    (cost-aware LPT over FFT volume), each process's slice over its
    local devices second, and the per-process results allgather back so
    every rank returns the full pair list — on by default when
    ``jax.process_count() > 1`` (``BST_PAIR_MULTIHOST``); pass
    ``multihost=False``/``True`` to pin it."""
    from ..parallel.pairsched import PairTask, run_pair_tasks

    buckets: dict[tuple, list[_PairJob]] = {}
    for j in jobs:
        shp = _fft_shape(np.maximum(j.crop_a.shape, j.crop_b.shape))
        buckets.setdefault(shp, []).append(j)

    chunks = []
    for shp, bjobs in sorted(buckets.items()):
        for i in range(0, len(bjobs), params.batch_size):
            chunks.append((shp, bjobs[i:i + params.batch_size]))

    tasks = []
    for i, (shp, chunk) in enumerate(chunks):
        vol = int(np.prod(shp))
        stack_bytes = 2 * len(chunk) * vol * 4  # a+b stacks, f32 on device
        tasks.append(PairTask(
            index=i,
            cost=float(len(chunk) * vol),       # placement ∝ FFT volume
            nbytes=int(stack_bytes * _FFT_WORKSPACE_MULT),
            tag=(shp, chunk),
        ))

    def dispatch(task):
        shp, chunk = task.tag
        with profiling.span("stitching.kernel"):
            return _dispatch_bucket(chunk, shp, params)

    def drain(seg_tasks, peaks_devs):
        # one pipelined fetch for the whole segment: round-trip latency —
        # which dominates small workloads on a tunneled device — is paid
        # per memory-bounded segment, not per shape bucket
        with profiling.span("stitching.kernel_sync"):
            peaks_list = jax.device_get(list(peaks_devs))
        out = []
        for task, peaks in zip(seg_tasks, peaks_list):
            shp, chunk = task.tag
            out.append(_refine_bucket(sd, chunk, shp, peaks, params))
        return out

    per_chunk = run_pair_tasks(tasks, dispatch, drain, n_devices=devices,
                               stage="stitching",
                               budget_bytes=params.inflight_bytes,
                               multihost=multihost)
    return [r for chunk_results in per_chunk
            if chunk_results is not None for r in chunk_results]


def _as_uint16_lossless(stack: np.ndarray) -> np.ndarray | None:
    """uint16 copy of the stack when every value survives the round-trip
    exactly (integral, in range — single-channel stored-level crops), else
    None. NaN/inf/out-of-range values are rejected by a min/max pre-check
    BEFORE the cast: casting them to uint16 is C-implementation-defined
    and raises numpy 'invalid value encountered in cast' RuntimeWarnings
    (ADVICE r5). Fractional in-range values cast quietly and fail the
    equality check."""
    if stack.dtype == np.uint16:
        return stack
    if stack.dtype.kind in "iu":
        if stack.size == 0:
            return stack.astype(np.uint16)
        mn, mx = stack.min(), stack.max()
        if mn < 0 or mx > np.iinfo(np.uint16).max:
            return None
        return stack.astype(np.uint16)
    if stack.dtype.kind != "f":
        return None
    if stack.size == 0:
        return stack.astype(np.uint16)
    mn, mx = stack.min(), stack.max()  # min/max propagate NaN
    if (not np.isfinite(mn) or not np.isfinite(mx)
            or mn < 0 or mx > np.iinfo(np.uint16).max):
        return None
    u = stack.astype(np.uint16)
    return u if np.array_equal(stack, u) else None


def _dispatch_bucket(jobs: list[_PairJob], shp, params):
    a = np.stack([pad_to(j.crop_a, shp) for j in jobs])
    b = np.stack([pad_to(j.crop_b, shp) for j in jobs])
    # lossless h2d downcast, decided ONCE for both stacks so the jitted
    # kernel sees only two dtype signatures (u16/u16 or f32/f32) per
    # shape bucket: halves wire bytes on tunneled/PCIe links, and the
    # device cast back to float32 is bit-identical
    ua = _as_uint16_lossless(a)
    ub = _as_uint16_lossless(b) if ua is not None else None
    if ua is not None and ub is not None:
        a, b = ua, ub
        _H2D_SAVED.inc(a.size * 4 - a.nbytes + b.size * 4 - b.nbytes)
    ext_a = np.stack([np.array(j.crop_a.shape, np.int32) for j in jobs])
    ext_b = np.stack([np.array(j.crop_b.shape, np.int32) for j in jobs])
    _H2D_BYTES.inc(a.nbytes + b.nbytes + ext_a.nbytes + ext_b.nbytes)
    return pcm_peaks_batch(a, b, ext_a, ext_b, params.peaks_to_check, 0.25)


def _refine_bucket(sd, jobs: list[_PairJob], shp, peaks,
                   params) -> list[PairwiseStitchingResult]:
    # per-peak true-correlation scoring + subpixel on the overlap slices
    # (host, float64 — see ops/phasecorr.refine_peaks); numpy reductions
    # release the GIL, so pairs refine in parallel
    shifts = np.zeros((len(jobs), 3))
    rs = np.zeros(len(jobs))

    def _refine(k):
        j = jobs[k]
        min_ov = max(
            params.min_overlap_px,
            params.min_overlap_frac
            * min(int(np.prod(j.crop_a.shape)),
                  int(np.prod(j.crop_b.shape))))
        shifts[k], rs[k] = refine_peaks(
            j.crop_a, j.crop_b, peaks[k], shp,
            min_overlap=min_ov, subpixel=params.subpixel)

    with profiling.span("stitching.refine"):
        # bound concurrent scorers by their SAT footprint: each refine
        # builds 4 float64 summed-area tables (~32 B/crop voxel), so an
        # unbounded 8-thread pool over huge crops would hold gigabytes of
        # transient tables at once. The 2e9 host budget is shared across
        # the drains actually refining concurrently (the pair scheduler's
        # active workers; 1 on the inline single-device path)
        from ..parallel.pairsched import concurrent_pair_workers

        sat_bytes = 32 * max(int(np.prod(j.crop_a.shape))
                             + int(np.prod(j.crop_b.shape)) for j in jobs)
        budget = max(1, int(2e9 // max(concurrent_pair_workers(), 1)
                            // max(sat_bytes, 1)))
        workers = min(8, len(jobs), budget)
        if workers > 1:
            from ..utils.threads import CtxThreadPool

            with CtxThreadPool(max_workers=workers) as pool:
                list(pool.map(_refine, range(len(jobs))))
        else:
            for k in range(len(jobs)):
                _refine(k)

    ds = np.array(params.downsampling, np.float64)
    out = []
    for j, s, r in zip(jobs, shifts, rs):
        if j.linear is not None:
            # S = L (p0b - p0a + rel*s) - (t_a - t_b): c_A - c_B = S
            rel = np.array(j.residual_ds, np.float64)
            S = j.linear @ (j.p0_delta + rel * s.astype(np.float64)) - j.t_delta
        else:
            S = ds * s.astype(np.float64)
        out.append(PairwiseStitchingResult(
            views_a=j.group_a.views,
            views_b=j.group_b.views,
            transform=translation_affine(S),
            correlation=float(r),
            hash=registration_hash(j.models_a, j.models_b),
            bbox=j.overlap,
        ))
    return out


def filter_results(
    results: list[PairwiseStitchingResult], params: StitchingParams,
    verbose: bool = True,
) -> list[PairwiseStitchingResult]:
    """Link filters (FilteredStitchingResults: Correlation, AbsoluteShift,
    ShiftMagnitude — SparkPairwiseStitching.java:347-382)."""
    out = []
    for res in results:
        shift = res.transform[:, 3]
        ok = (params.min_r <= res.correlation <= params.max_r
              and all(abs(shift[d]) <= params.max_shift[d] for d in range(3))
              and float(np.linalg.norm(shift)) <= params.max_shift_total)
        if ok:
            out.append(res)
        else:
            observe.log(f"  dropped pair {res.views_a[0]}<->{res.views_b[0]}: "
                        f"r={res.correlation:.3f} shift={np.round(shift, 2)}",
                        stage="stitching", echo=verbose,
                        correlation=round(float(res.correlation), 4))
    return out


def store_results(
    sd: SpimData,
    results: list[PairwiseStitchingResult],
    computed: list[PairwiseStitchingResult] | None = None,
) -> None:
    """Store kept results; entries for every RECOMPUTED pair (``computed``,
    default = ``results``) are cleared first so links the user just filtered
    out don't survive from a previous run."""
    for res in computed if computed is not None else results:
        sd.stitching_results.pop(res.pair_key, None)
    for res in results:
        sd.stitching_results[res.pair_key] = res

"""Non-rigid fusion driver: unique interest points, per-block control grids,
deformation kernel, block writes.

TPU redesign of SparkNonRigidFusion (reference call stack SURVEY.md §3.3/§2.1:
SparkNonRigidFusion.java:313-435): per output block, the views to fuse are
those overlapping the block (+50 px margin) and the deformation of each view
comes from corresponding interest points near the block (+25 px margin),
merged into "unique points" (the average world position of each
correspondence group) — each view's control grid maps the averaged position
back to the view's own world frame, so all views agree at the control points.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from ..io.chunkstore import Dataset
from ..io.dataset_io import ViewLoader
from ..io.interestpoints import InterestPointStore
from ..io.spimdata import SpimData, ViewId
from ..ops import fusion as F
from ..ops.nonrigid import fit_control_grid
from ..utils.geometry import (
    Interval,
    apply_affine,
    concatenate,
    invert_affine,
    translation_affine,
)
from ..utils.grid import GridBlock, create_grid
from .. import profiling
from .affine_fusion import (
    BlendParams, FusionStats, _record_fusion_stage, anisotropy_transform,
    patch_dtype,
)

FUSE_MARGIN = 50.0   # px margin for view selection (SparkNonRigidFusion.java:326-371)
IP_MARGIN = 25.0     # px margin for deformation-defining points


@dataclass
class UniquePoints:
    """Per-view correspondence-averaged control points."""

    targets: dict[ViewId, np.ndarray]      # (M,3) averaged world positions
    view_world: dict[ViewId, np.ndarray]   # (M,3) the view's own world position


def build_unique_points(
    sd: SpimData,
    store: InterestPointStore,
    views: list[ViewId],
    labels: list[str],
) -> UniquePoints:
    """Union-find over correspondences -> groups; target = mean world position
    of the group (NonRigidTools 'unique interest points')."""
    keys: list[tuple[ViewId, str, int]] = []
    index: dict[tuple[ViewId, str, int], int] = {}
    world: dict[tuple[ViewId, str], dict[int, np.ndarray]] = {}
    vset = set(views)

    def load(view: ViewId, label: str):
        k = (view, label)
        if k not in world:
            ids, locs = store.load_points(view, label)
            w = apply_affine(sd.model(view), locs) if len(locs) else locs
            world[k] = dict(zip(ids.astype(int).tolist(), w))
        return world[k]

    def key_id(k):
        if k not in index:
            index[k] = len(keys)
            keys.append(k)
        return index[k]

    parent: list[int] = []

    def find(i):
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    edges = []
    for v in views:
        for label in labels:
            if label not in sd.interest_points.get(v, {}):
                continue
            mine = load(v, label)
            for c in store.load_correspondences(v, label):
                if c.other_view not in vset:
                    continue
                theirs = load(c.other_view, c.other_label)
                if c.id not in mine or c.other_id not in theirs:
                    continue
                edges.append(((v, label, c.id),
                              (c.other_view, c.other_label, c.other_id)))
    for a, b in edges:
        ia, ib = key_id(a), key_id(b)
        while len(parent) < len(keys):
            parent.append(len(parent))
        ra, rb = find(ia), find(ib)
        if ra != rb:
            parent[ra] = rb
    while len(parent) < len(keys):
        parent.append(len(parent))

    groups: dict[int, list[tuple[ViewId, str, int]]] = {}
    for i, k in enumerate(keys):
        groups.setdefault(find(i), []).append(k)

    targets: dict[ViewId, list[np.ndarray]] = {v: [] for v in views}
    vw: dict[ViewId, list[np.ndarray]] = {v: [] for v in views}
    for members in groups.values():
        pos = np.array([world[(v, lab)][i] for v, lab, i in members])
        tgt = pos.mean(axis=0)
        for (v, lab, i), p in zip(members, pos):
            if v in targets:
                targets[v].append(tgt)
                vw[v].append(p)
    return UniquePoints(
        {v: (np.array(t) if t else np.zeros((0, 3))) for v, t in targets.items()},
        {v: (np.array(t) if t else np.zeros((0, 3))) for v, t in vw.items()},
    )


def fuse_nonrigid_volume(
    sd: SpimData,
    loader: ViewLoader,
    views: list[ViewId],
    unique: UniquePoints,
    out_ds: Dataset,
    bbox: Interval,
    block_size: tuple[int, ...],
    block_scale: tuple[int, ...] = (2, 2, 1),
    cpd: float = 10.0,
    alpha: float = 1.0,
    fusion_type: str = "AVG_BLEND",
    blend: BlendParams | None = None,
    anisotropy_factor: float = float("nan"),
    out_dtype: str = "float32",
    min_intensity: float | None = None,
    max_intensity: float | None = None,
    zarr_ct: tuple[int, int] | None = None,
    progress: bool = False,
    devices: int | None = None,
    io_threads: int = 4,
) -> FusionStats:
    """Fuse ``views`` non-rigidly into ``out_ds`` over ``bbox``, block-sharded
    over the local device mesh (``devices`` defaults to all)."""
    stats = FusionStats()
    t0 = time.time()
    blend = blend or BlendParams()
    aniso = anisotropy_transform(anisotropy_factor)
    compute_block = tuple(b * s for b, s in zip(block_size, block_scale))
    grid_blocks = create_grid(bbox.shape, compute_block, block_size)
    if min_intensity is None or max_intensity is None:
        if out_dtype == "uint8":
            min_intensity, max_intensity = 0.0, 255.0
        elif out_dtype == "uint16":
            min_intensity, max_intensity = 0.0, 65535.0
        else:
            min_intensity, max_intensity = 0.0, 1.0

    # control-grid geometry is per COMPUTE block and static: origin one
    # spacing before the block, dims covering block + margins
    gdims = tuple(int(np.ceil(compute_block[d] / cpd)) + 3 for d in range(3))

    import jax

    from ..parallel.mesh import run_sharded_batches

    n_dev = devices if devices is not None else len(jax.local_devices())

    # plan every block up front (host geometry + control-grid fits), then
    # bucket by compiled-kernel signature and batch over the device mesh —
    # the reference's per-block Spark foreach (SparkNonRigidFusion.java:313-435)
    planned = []
    for block in grid_blocks:
        stats.blocks += 1
        res = _plan_nonrigid_block(
            sd, views, unique, block, bbox, compute_block, gdims, cpd, alpha,
            aniso)
        if res is None:
            stats.skipped_empty += 1
            continue
        planned.append((block, *res))

    buckets: dict[tuple, list] = {}
    for item in planned:
        plans = item[3]
        vb = F.bucket_views(len(plans))
        pshape = F.bucket_shape(np.max([p[3].shape for p in plans], axis=0), 32)
        buckets.setdefault((pshape, vb), []).append(item)

    mi, ma = np.float32(min_intensity), np.float32(max_intensity)
    from ..utils.threads import CtxThreadPool

    pool = CtxThreadPool(max_workers=max(1, io_threads))
    try:
        for (pshape, vb), items in sorted(buckets.items(),
                                          key=lambda kv: str(kv[0])):
            kernel = _make_nonrigid_kernel(
                n_dev, compute_block, fusion_type, out_dtype)
            stats.compile_keys.add((tuple(compute_block), pshape, vb,
                                    fusion_type, "nonrigid", n_dev > 1))

            def build(item, _pshape=pshape, _vb=vb):
                block, block_global, grid_origin, plans = item
                arrs = _stage_nonrigid(loader, plans, _pshape, _vb, blend,
                                       gdims)
                return (*arrs,
                        np.asarray(block_global.min, np.float32),
                        np.asarray(grid_origin, np.float32),
                        np.full(3, cpd, np.float32))

            def kernel_call(*stacked):
                with profiling.span("nonrigid.kernel"):
                    return kernel(mi, ma, *stacked)

            written: dict[tuple, int] = {}

            def consume(item, data):
                block = item[0]
                sl = tuple(slice(0, s) for s in block.size)
                with profiling.span("nonrigid.write"):
                    if zarr_ct is not None:
                        c, t = zarr_ct
                        out_ds.write(data[sl][..., None, None],
                                     (*block.offset, c, t))
                    else:
                        out_ds.write(data[sl], block.offset)
                written[tuple(block.offset)] = int(np.prod(block.size))

            run_sharded_batches(items, build, kernel_call, consume, n_dev,
                                pool, label="nonrigid batch",
                                progress=progress, multihost=True,
                                out_bytes_per_item=int(np.prod(compute_block))
                                * np.dtype(out_dtype or "float32").itemsize,
                                workspace_mult=4.0)
            stats.voxels += sum(written.values())
    finally:
        pool.shutdown(wait=True)
    stats.seconds = time.time() - t0
    _record_fusion_stage("nonrigid-fusion", stats, "sharded")
    return stats


import functools


@functools.lru_cache(maxsize=32)
def _make_nonrigid_kernel(n_dev, compute_block, fusion_type, out_dtype):
    """Batch-of-blocks nonrigid fusion kernel with on-device intensity
    conversion; batch axis sharded over the mesh when n_dev > 1.
    lru_cache'd: a fresh jax.jit per call would recompile every run."""
    import jax

    from ..ops.nonrigid import nonrigid_fuse_block_impl
    from ..parallel.mesh import make_mesh, shard_jit

    def one(mi, ma, *args):
        fused, _ = nonrigid_fuse_block_impl(
            *args, block_shape=tuple(compute_block), fusion_type=fusion_type)
        return F._convert_intensity_expr(fused, mi, ma, out_dtype)

    def batched(mi, ma, *arrays):
        return jax.vmap(lambda *a: one(mi, ma, *a))(*arrays)

    if n_dev <= 1:
        return jax.jit(batched)
    return shard_jit(batched, make_mesh(n_dev), n_in=11, n_repl=2)


def _plan_nonrigid_block(
    sd, views, unique: UniquePoints, block: GridBlock, bbox: Interval,
    compute_block, gdims, cpd, alpha, aniso,
):
    """Select + fit the views contributing to one block; returns
    (block_global, grid_origin, plans) or None when nothing overlaps."""
    block_global = Interval.from_shape(compute_block, block.offset
                                       ).translate(bbox.min)
    grid_origin = np.asarray(block_global.min, np.float64) - cpd
    sel_box = block_global.expand(int(FUSE_MARGIN))
    ip_box = block_global.expand(int(IP_MARGIN + 2 * cpd))

    plans = []
    for v in views:
        model = sd.model(v)
        if aniso is not None:
            model = concatenate(aniso, model)
        from ..utils.geometry import transformed_interval

        vbox = transformed_interval(
            model, Interval.from_shape(sd.view_size(v)))
        if not vbox.overlaps(sel_box):
            continue

        # deformation grid from unique points near the block
        tgt = unique.targets.get(v, np.zeros((0, 3)))
        vw = unique.view_world.get(v, np.zeros((0, 3)))
        if len(tgt):
            keep = np.all(
                (tgt >= np.array(ip_box.min)) & (tgt <= np.array(ip_box.max)),
                axis=1,
            )
            tgt, vw = tgt[keep], vw[keep]
        grid = fit_control_grid(tgt, vw, grid_origin, gdims, cpd, alpha)

        # source patch must cover the DEFORMED block under every vertex model
        corners = np.array(
            [[(block_global.min[d], block_global.max[d] + 1)[(i >> d) & 1]
              for d in range(3)] for i in range(8)], np.float64,
        )
        A = grid.reshape(-1, 3, 4).astype(np.float64)
        warped = np.einsum("gij,cj->gci", A[:, :, :3], corners) + A[:, None, :, 3]
        inv_total = invert_affine(model)  # world -> full-res view px (level 0)
        lo = warped.reshape(-1, 3) @ inv_total[:, :3].T + inv_total[:, 3]
        src = Interval(
            tuple(np.floor(lo.min(axis=0)).astype(np.int64) - 1),
            tuple(np.ceil(lo.max(axis=0)).astype(np.int64) + 1),
        )
        img_iv = Interval.from_shape(sd.view_size(v))
        clipped = src.intersect(img_iv)
        if clipped.is_empty():
            continue
        plans.append((v, grid, inv_total, clipped,
                      np.array(sd.view_size(v), np.float64)))

    if not plans:
        return None
    return block_global, grid_origin, plans


def _stage_nonrigid(loader, plans, pshape, vb, blend: BlendParams, gdims):
    """Host-side input staging for one block's nonrigid kernel inputs."""
    # stored integer dtype when every view shares one (<=16-bit): ships at
    # native width, kernel casts to float32 on device (lossless — same
    # memoized transport decision as the affine paths)
    patches = np.zeros(
        (vb, *pshape), patch_dtype(loader, [(v, 0) for v, *_ in plans]))
    grids = np.zeros((vb, *gdims, 12), np.float32)
    grids[..., 0] = 1.0
    grids[..., 5] = 1.0
    grids[..., 10] = 1.0
    vaffines = np.zeros((vb, 3, 4), np.float32)
    offsets = np.zeros((vb, 3), np.float32)
    img_dims = np.ones((vb, 3), np.float32)
    borders = np.zeros((vb, 3), np.float32)
    ranges = np.ones((vb, 3), np.float32)
    valid = np.zeros((vb,), np.float32)
    for i, (v, grid, inv_total, clipped, dim) in enumerate(plans):
        with profiling.span("nonrigid.prefetch"):
            patches[i] = loader.read_block(v, 0, tuple(clipped.min), pshape)
        grids[i] = grid
        vaffines[i] = concatenate(
            translation_affine(-np.asarray(clipped.min, np.float64)), inv_total
        )
        offsets[i] = clipped.min
        img_dims[i] = dim
        borders[i] = blend.border
        ranges[i] = blend.range
        valid[i] = 1.0
    return (patches, grids, vaffines, offsets, img_dims, borders, ranges,
            valid)

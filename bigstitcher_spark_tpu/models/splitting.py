"""Virtual tile splitting: divide huge views into overlapping sub-views.

Role of ``SplittingTools.splitImages`` + ``Split_Views`` used by the
reference split-images tool (SplitDatasets.java:94-124): each view setup is
replaced by a grid of sub-views of ~target size with ~target overlap (both
rounded up to the mipmap step size so pyramid levels stay addressable), the
registrations gain an innermost translation per sub-view, and optionally
"fake" interest points with exact correspondences are planted in the
sub-view overlaps so the solver keeps split pieces rigidly together.

The split is VIRTUAL: no image data is rewritten. Sub-view reads resolve
through ``SpimData.split_info`` (new setup -> source setup + pixel offset),
which ``ViewLoader`` applies at every mipmap level. This framework
serializes that mapping as a ``<SplitInfo>`` element in the XML — our own
extension; the reference instead serializes its SplitViewerImgLoader.
"""

from __future__ import annotations

import numpy as np

from ..io.interestpoints import CorrespondingPoint, InterestPointStore, register_points_in_xml
from ..io.spimdata import SpimData, ViewId, ViewSetup, ViewTransform
from ..utils.geometry import Interval, translation_affine


def closest_larger_divisible(value: int, step: int) -> int:
    """Round up to a multiple of ``step`` (Split_Views.closestLargerLongDivisableBy)."""
    step = max(int(step), 1)
    value = int(value)
    return value if value % step == 0 else (value // step + 1) * step


def min_step_size(sd: SpimData, loader) -> np.ndarray:
    """Per-axis step every split offset/size must be divisible by: the
    coarsest mipmap factor over all setups (Split_Views.findMinStepSize)."""
    step = np.ones(3, np.int64)
    for sid in sd.setups:
        for f in loader.downsampling_factors(sid):
            step = np.maximum(step, np.asarray(f, np.int64))
    return step


def _axis_starts(dim: int, size: int, overlap: int) -> list[int]:
    """Sub-interval start offsets covering [0,dim): stride size-overlap, the
    last interval clamped so coverage is exact."""
    if size >= dim:
        return [0]
    stride = max(size - overlap, 1)
    starts = list(range(0, dim - size + 1, stride))
    if starts[-1] + size < dim:
        starts.append(dim - size)
    return starts


def split_images(
    sd: SpimData,
    loader,
    target_size: tuple[int, int, int],
    target_overlap: tuple[int, int, int],
    assign_illuminations: bool = False,
    fake_interest_points: bool = False,
    fip_density: float = 100.0,       # points per 100^3 px of overlap volume
    fip_min: int = 20,
    fip_max: int = 500,
    fip_error: float = 0.5,
    fip_store: InterestPointStore | None = None,
    rng_seed: int = 23,
    fip_exclusion_radius: float = 0.0,
    optimize: bool = True,
) -> SpimData:
    """Build a new virtually-split project (the input is not modified).

    ``optimize`` rounds size/overlap up to the closest larger value divisible
    by every stored downsampling step (Split_Views.closestLargerLongDivisableBy);
    --disableOptimization uses the targets exactly."""
    if optimize:
        step = min_step_size(sd, loader)
        size = np.array([closest_larger_divisible(target_size[d], step[d])
                         for d in range(3)], np.int64)
        overlap = np.array([closest_larger_divisible(target_overlap[d], step[d])
                            for d in range(3)], np.int64)
    else:
        size = np.array(target_size, np.int64)
        overlap = np.array(target_overlap, np.int64)
    if np.any(overlap > size):
        raise ValueError(f"overlap {overlap} cannot exceed size {size}")

    out = SpimData()
    # absolute loader path: the split XML may be saved anywhere
    from ..io.spimdata import ImageLoader

    out.image_loader = ImageLoader(
        format=sd.image_loader.format,
        path=sd.resolve_loader_path(),
        path_type="absolute",
    )
    out.timepoints = list(sd.timepoints)
    out.attributes = {k: dict(v) for k, v in sd.attributes.items()}
    out.bounding_boxes = dict(sd.bounding_boxes)
    from ..io.spimdata import AttributeEntity

    out.attributes["tile"] = {}
    if assign_illuminations:
        out.attributes["illumination"] = {}

    new_id = 0
    tile_id = 0
    sub_of_source: dict[int, list[tuple[int, np.ndarray, np.ndarray]]] = {}
    for sid in sorted(sd.setups):
        src = sd.setups[sid]
        dims = np.asarray(src.size, np.int64)
        sub_size = np.minimum(size, dims)
        starts = [
            _axis_starts(int(dims[d]), int(sub_size[d]), int(overlap[d]))
            for d in range(3)
        ]
        subs = []
        for sx in starts[0]:
            for sy in starts[1]:
                for sz in starts[2]:
                    off = np.array([sx, sy, sz], np.int64)
                    attrs = dict(src.attributes)
                    attrs["tile"] = tile_id
                    out.attributes["tile"][tile_id] = AttributeEntity(
                        tile_id, str(tile_id))
                    if assign_illuminations:
                        illum = src.attributes.get("tile", 0)
                        attrs["illumination"] = illum
                        out.attributes["illumination"].setdefault(
                            illum, AttributeEntity(illum, str(illum)))
                    out.setups[new_id] = ViewSetup(
                        id=new_id,
                        name=f"{src.name or sid} split {tile_id}",
                        size=tuple(int(v) for v in sub_size),
                        attributes=attrs,
                        voxel_size=src.voxel_size,
                    )
                    out.split_info[new_id] = (sid, tuple(int(v) for v in off))
                    for t in sd.timepoints:
                        vid = ViewId(t, sid)
                        if vid not in sd.registrations:
                            continue
                        chain = [tr.copy() for tr in sd.registrations[vid]]
                        # innermost (applied first): sub-view px -> source px
                        chain.append(ViewTransform(
                            "split offset",
                            translation_affine(off.astype(np.float64)),
                        ))
                        out.registrations[ViewId(t, new_id)] = chain
                    subs.append((new_id, off, sub_size.copy()))
                    tile_id += 1
                    new_id += 1
        sub_of_source[sid] = subs

    if fake_interest_points:
        if fip_store is None:
            raise ValueError("fake_interest_points requires fip_store")
        _plant_fake_points(
            sd, out, sub_of_source, fip_store,
            fip_density, fip_min, fip_max, fip_error, rng_seed,
            exclusion_radius=fip_exclusion_radius,
        )
    return out


def _plant_fake_points(
    sd, out, sub_of_source, store, density, fip_min, fip_max, error, seed,
    exclusion_radius: float = 0.0,
) -> None:
    """Uniform random points in each overlap between sub-views of one source
    view, identical up to ``error`` jitter, with exact correspondences —
    solver glue holding split pieces together (SplittingTools fake IPs)."""
    rng = np.random.default_rng(seed)
    label = "splitPoints"
    pts: dict[int, list[np.ndarray]] = {}
    corrs: dict[int, list[tuple[int, int, int]]] = {}  # setup -> (id, other_setup, other_id)
    for sid, subs in sub_of_source.items():
        for i in range(len(subs)):
            id_a, off_a, size_a = subs[i]
            box_a = Interval.from_shape(size_a, off_a)
            for j in range(i + 1, len(subs)):
                id_b, off_b, size_b = subs[j]
                box_b = Interval.from_shape(size_b, off_b)
                if not box_a.overlaps(box_b):
                    continue
                ov = box_a.intersect(box_b)
                vol = ov.num_elements
                n = int(np.clip(density * vol / 1e6, fip_min, fip_max))
                p_src = rng.uniform(np.array(ov.min, float),
                                    np.array(ov.max, float) + 1.0, (n, 3))
                if exclusion_radius > 0 and len(p_src) > 1:
                    # greedy thinning: keep points at least the exclusion
                    # radius apart (--fipExclusionRadius)
                    kept: list[np.ndarray] = []
                    for q in p_src:
                        if all(np.linalg.norm(q - r) >= exclusion_radius
                               for r in kept):
                            kept.append(q)
                    p_src = np.array(kept)
                    n = len(p_src)
                jit = rng.normal(0.0, error, (n, 3)) if error > 0 else 0.0
                la = pts.setdefault(id_a, [])
                lb = pts.setdefault(id_b, [])
                ca = corrs.setdefault(id_a, [])
                cb = corrs.setdefault(id_b, [])
                base_a, base_b = len(la), len(lb)
                for k in range(n):
                    la.append(p_src[k] - off_a)
                    lb.append(p_src[k] + (jit[k] if error > 0 else 0.0) - off_b)
                    ca.append((base_a + k, id_b, base_b + k))
                    cb.append((base_b + k, id_a, base_a + k))
    for t in out.timepoints:
        for setup_id, plist in pts.items():
            vid = ViewId(t, setup_id)
            if vid not in out.registrations:
                continue
            grp = store.save_points(vid, label, np.array(plist))
            register_points_in_xml(out, vid, label, "fake split points", grp)
            store.save_correspondences(vid, label, [
                CorrespondingPoint(pid, ViewId(t, other), label, oid)
                for pid, other, oid in corrs.get(setup_id, [])
            ])

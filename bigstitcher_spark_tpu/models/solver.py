"""Global registration solver: tile-graph relaxation over point matches.

TPU-era redesign of the reference ``solver`` tool (Solver.java:161-396) and
the mvrecon/mpicbg global-optimization stack it calls (GlobalOpt,
GlobalOptIterative, GlobalOptTwoRound, mpicbg TileConfiguration —
Solver.java:297-338). Instead of mpicbg's sequential per-tile fits, each
relaxation sweep is fully vectorized: segment-sum the weighted point moments
per tile, then batch-fit every tile's model at once (batched 4x4 solves /
3x3 SVDs) — the same Jacobi-style fixed point, but one numpy pass per
iteration regardless of tile count.

Sources of matches (Solver.java:96):
  * STITCHING — pairwise translation links from phase correlation, expanded
    into 8 corner point matches of the overlap bbox weighted by correlation
    (role of ImageCorrelationPointMatchCreator); stale links whose stored
    registration hash no longer matches are skipped (Solver.java:398-432).
  * IP — corresponding interest points of selected labels, transformed to
    world coordinates under current registrations (Solver.java:434-673).

The solved per-tile correction is preconcatenated to every member view's
transform chain (TransformationTools.storeTransformation role,
Solver.java:351-369).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .. import observe, profiling
from ..io.interestpoints import InterestPointStore
from ..io.spimdata import SpimData, ViewId, ViewTransform, registration_hash
from ..observe import metrics as _metrics
from ..ops import models as M
from ..utils.geometry import (
    Interval,
    apply_affine,
    transformed_interval,
)

Key = tuple  # canonical tile key: sorted tuple of member ViewIds

_SOLVE_ITERS = _metrics.counter("bst_solve_iterations_total")
_SOLVE_DROPPED = _metrics.counter("bst_solve_links_dropped_total")


@dataclass
class SolverParams:
    """Defaults match Solver.java:104-149 + AbstractRegistration.java:62-77."""

    source: str = "STITCHING"              # STITCHING | IP
    method: str = "ONE_ROUND_SIMPLE"       # ONE_ROUND_{SIMPLE,ITERATIVE} | TWO_ROUND_{SIMPLE,ITERATIVE}
    model: str = M.TRANSLATION             # TRANSLATION | RIGID | AFFINE
    regularization: str = M.NONE           # NONE | IDENTITY | TRANSLATION | RIGID | AFFINE
    lam: float = 0.1
    max_error: float = 5.0
    max_iterations: int = 10000
    max_plateau_width: int = 200
    relative_threshold: float = 3.5
    absolute_threshold: float = 7.0
    damping: float = 1.0                   # Jacobi under-relaxation factor
    backend: str | None = None             # device | numpy | None (knob)
    fixed_views: list[ViewId] = field(default_factory=list)
    disable_fixed_views: bool = False
    labels: list[str] = field(default_factory=list)
    label_weights: list[float] = field(default_factory=list)
    group_illums: bool | None = None       # default: True for STITCHING
    group_channels: bool | None = None
    group_tiles: bool = False
    split_timepoints: bool = False

    def resolved_grouping(self) -> tuple[bool, bool]:
        stitch = self.source.upper() == "STITCHING"
        gi = self.group_illums if self.group_illums is not None else stitch
        gc = self.group_channels if self.group_channels is not None else stitch
        return gi, gc


@dataclass
class MatchLink:
    """All point matches of one tile pair (one graph edge)."""

    key_a: Key
    key_b: Key
    p: np.ndarray  # (N,3) world coords on A's side
    q: np.ndarray  # (N,3) world coords on B's side
    w: np.ndarray  # (N,)


@dataclass
class SolveResult:
    corrections: dict[Key, np.ndarray]  # tile key -> 3x4 world correction
    error: float
    iterations: int
    removed_links: list[tuple[Key, Key]]
    link_errors: dict[tuple[Key, Key], float]
    history: np.ndarray | None = None   # per-iteration mean error


# ---------------------------------------------------------------------------
# tile grouping
# ---------------------------------------------------------------------------

def build_tiles(sd: SpimData, views: list[ViewId], params: SolverParams) -> list[Key]:
    """Group views into solver tiles (Solver.java:108-119 grouping flags)."""
    gi, gc = params.resolved_grouping()
    by_key: dict[tuple, list[ViewId]] = {}
    for v in views:
        s = sd.setups[v.setup]
        if params.split_timepoints:
            key: tuple = (v.timepoint,)
        else:
            key = (
                v.timepoint,
                s.attributes.get("angle", 0),
                None if params.group_tiles else s.attributes.get("tile", 0),
                None if gc else s.attributes.get("channel", 0),
                None if gi else s.attributes.get("illumination", 0),
            )
        by_key.setdefault(key, []).append(v)
    return [tuple(sorted(vs)) for _, vs in sorted(by_key.items())]


def _tile_of_view(tiles: list[Key]) -> dict[ViewId, Key]:
    out = {}
    for t in tiles:
        for v in t:
            out[v] = t
    return out


# ---------------------------------------------------------------------------
# match assembly
# ---------------------------------------------------------------------------

def matches_from_stitching(
    sd: SpimData, tiles: list[Key], verbose: bool = True
) -> list[MatchLink]:
    """Expand each non-stale pairwise shift into 8 corner matches of its
    overlap bbox: with corrections c, the stored shift S demands
    c_A - c_B = S, i.e. match (x, x + S) (models.stitching shift semantics)."""
    lookup = _tile_of_view(tiles)
    links: dict[tuple[Key, Key], list[tuple[np.ndarray, np.ndarray, float]]] = {}
    n_stale = 0
    for res in sd.stitching_results.values():
        ka = lookup.get(res.views_a[0])
        kb = lookup.get(res.views_b[0])
        if ka is None or kb is None or ka == kb:
            continue
        cur = registration_hash(
            [sd.model(v) for v in res.views_a], [sd.model(v) for v in res.views_b]
        )
        if not np.isclose(cur, res.hash, rtol=1e-9, atol=1e-6):
            n_stale += 1
            continue
        if res.bbox is not None:
            box = res.bbox
        else:
            box = None
            for v in res.views_a:
                iv = transformed_interval(
                    sd.model(v), Interval.from_shape(sd.view_size(v))
                )
                box = iv if box is None else box.union(iv)
        corners = _corners(box)
        S = res.transform[:, 3]
        links.setdefault((ka, kb), []).append(
            (corners, corners + S, float(res.correlation))
        )
    if n_stale:
        observe.log(f"solver: skipped {n_stale} stale stitching links "
                    "(registration hash changed)", stage="solver",
                    echo=verbose, stale_links=n_stale)
    out = []
    for (ka, kb), items in sorted(links.items()):
        p = np.concatenate([i[0] for i in items])
        q = np.concatenate([i[1] for i in items])
        w = np.concatenate([np.full(len(i[0]), i[2]) for i in items])
        out.append(MatchLink(ka, kb, p, q, w))
    return out


def _corners(box: Interval) -> np.ndarray:
    mn = np.asarray(box.min, np.float64)
    mx = np.asarray(box.max, np.float64) + 1.0
    return np.array(
        [[(mn[0], mx[0])[(i >> 0) & 1], (mn[1], mx[1])[(i >> 1) & 1],
          (mn[2], mx[2])[(i >> 2) & 1]] for i in range(8)]
    )


def matches_from_interest_points(
    sd: SpimData,
    tiles: list[Key],
    store: InterestPointStore,
    labels: list[str],
    label_weights: list[float] | None = None,
    verbose: bool = True,
) -> list[MatchLink]:
    """World-transformed corresponding interest points per tile pair
    (Solver.java:434-673: points under current registrations; the solve
    computes a correction on top)."""
    weights = {
        lab: (label_weights[i] if label_weights and i < len(label_weights) else 1.0)
        for i, lab in enumerate(labels)
    }
    lookup = _tile_of_view(tiles)
    cache: dict[tuple[ViewId, str], dict[int, np.ndarray]] = {}

    def world_points(view: ViewId, label: str) -> dict[int, np.ndarray]:
        k = (view, label)
        if k not in cache:
            ids, locs = store.load_points(view, label)
            w = apply_affine(sd.model(view), locs) if len(locs) else locs
            cache[k] = dict(zip(ids.astype(int).tolist(), w))
        return cache[k]

    links: dict[tuple[Key, Key], list[tuple[np.ndarray, np.ndarray, float]]] = {}
    n_pts = 0
    for view in sorted(lookup):
        for label in labels:
            if label not in sd.interest_points.get(view, {}):
                continue
            mine = world_points(view, label)
            for c in store.load_correspondences(view, label):
                ka = lookup.get(view)
                kb = lookup.get(c.other_view)
                if kb is None or ka == kb:
                    continue
                if (view, label) > (c.other_view, c.other_label):
                    continue  # each correspondence is stored on both sides
                theirs = world_points(c.other_view, c.other_label)
                if c.id not in mine or c.other_id not in theirs:
                    continue
                links.setdefault((ka, kb), []).append(
                    (mine[c.id], theirs[c.other_id], weights.get(label, 1.0))
                )
                n_pts += 1
    observe.log(f"solver: {n_pts} corresponding interest points over "
                f"{len(links)} pairs", stage="solver", echo=verbose,
                points=n_pts, pairs=len(links))
    out = []
    for (ka, kb), items in sorted(links.items()):
        p = np.stack([i[0] for i in items])
        q = np.stack([i[1] for i in items])
        w = np.array([i[2] for i in items])
        out.append(MatchLink(ka, kb, p, q, w))
    return out


# ---------------------------------------------------------------------------
# the relaxation core
# ---------------------------------------------------------------------------

def _flatten(links: list[MatchLink], index: dict[Key, int]):
    """Incidence arrays: every point match appears once per side."""
    loc, tgt_pts, own, other, w = [], [], [], [], []
    for lk in links:
        ia, ib = index[lk.key_a], index[lk.key_b]
        n = len(lk.p)
        loc.append(lk.p); tgt_pts.append(lk.q)
        own.append(np.full(n, ia)); other.append(np.full(n, ib)); w.append(lk.w)
        loc.append(lk.q); tgt_pts.append(lk.p)
        own.append(np.full(n, ib)); other.append(np.full(n, ia)); w.append(lk.w)
    return (
        np.concatenate(loc), np.concatenate(tgt_pts),
        np.concatenate(own), np.concatenate(other), np.concatenate(w),
    )


def _apply_batch(models: np.ndarray, pts: np.ndarray, idx: np.ndarray) -> np.ndarray:
    m = models[idx]
    return np.einsum("nij,nj->ni", m[:, :, :3], pts) + m[:, :, 3]


def _segment_moments(local, target, own, w, T):
    """Per-tile weighted moments for all three model fits in one pass."""
    ph = np.concatenate([local, np.ones((len(local), 1))], axis=1)  # (N,4)
    sw = np.zeros(T)
    np.add.at(sw, own, w)
    swp = np.zeros((T, 4))
    np.add.at(swp, own, w[:, None] * ph)
    swq = np.zeros((T, 3))
    np.add.at(swq, own, w[:, None] * target)
    spp = np.zeros((T, 4, 4))
    np.add.at(spp, own, w[:, None, None] * ph[:, :, None] * ph[:, None, :])
    spq = np.zeros((T, 4, 3))
    np.add.at(spq, own, w[:, None, None] * ph[:, :, None] * target[:, None, :])
    return sw, swp, swq, spp, spq


def _fit_from_moments(kind: str, sw, swp, swq, spp, spq, eps=1e-9):
    """Batched per-tile model fit from accumulated moments."""
    T = len(sw)
    sw_safe = np.maximum(sw, eps)
    if kind == M.IDENTITY:
        out = np.zeros((T, 3, 4))
        out[:, :, :3] = np.eye(3)
        return out
    if kind == M.TRANSLATION:
        t = (swq - swp[:, :3]) / sw_safe[:, None]
        out = np.zeros((T, 3, 4))
        out[:, :, :3] = np.eye(3)
        out[:, :, 3] = t
        return out
    if kind == M.AFFINE:
        a = spp + eps * np.eye(4)
        sol = np.linalg.solve(a, spq)  # (T,4,3)
        return np.swapaxes(sol, 1, 2)
    if kind == M.RIGID:
        pc = swp[:, :3] / sw_safe[:, None]
        qc = swq / sw_safe[:, None]
        # H = Σw p qᵀ - Σw pc qᵀ - Σw p qcᵀ + Σw pc qcᵀ = spq[:3] - pc (swq)ᵀ ...
        h = (spq[:, :3, :]
             - pc[:, :, None] * swq[:, None, :]
             - swp[:, :3, None] * qc[:, None, :]
             + sw_safe[:, None, None] * pc[:, :, None] * qc[:, None, :])
        u, _, vt = np.linalg.svd(h)
        d = np.linalg.det(np.swapaxes(vt, 1, 2) @ np.swapaxes(u, 1, 2))
        sign = np.stack([np.ones_like(d), np.ones_like(d), d], axis=1)
        r = np.swapaxes(vt, 1, 2) @ (sign[:, :, None] * np.swapaxes(u, 1, 2))
        t = qc - np.einsum("nij,nj->ni", r, pc)
        return np.concatenate([r, t[:, :, None]], axis=2)
    raise ValueError(kind)


def _resolve_backend(params: SolverParams) -> str:
    """``device`` (jit lax.while_loop relaxation, the default) or
    ``numpy`` (the host reference path): explicit params.backend wins,
    else the BST_SOLVE_DEVICE knob (policy owned by ops.solve)."""
    from ..ops import solve as _dsolve

    return _dsolve.resolve_backend(params.backend)


def relax(
    links: list[MatchLink],
    tiles: list[Key],
    fixed: set[Key],
    params: SolverParams,
) -> SolveResult:
    """One global relaxation: device backend (default) compiles the whole
    Jacobi iteration into one ``lax.while_loop`` (ops/solve.py), the numpy
    backend is the host reference both share their convergence semantics
    with."""
    if _resolve_backend(params) == "device" and links:
        return _DeviceRelax(links, tiles, fixed, params).solve()
    return _relax_numpy(links, tiles, fixed, params)


def _relax_numpy(
    links: list[MatchLink],
    tiles: list[Key],
    fixed: set[Key],
    params: SolverParams,
) -> SolveResult:
    """Vectorized Jacobi tile relaxation with mpicbg-style convergence
    (maxError / maxIterations / maxPlateauwidth, ConvergenceStrategy role)."""
    index = {k: i for i, k in enumerate(tiles)}
    T = len(tiles)
    identity = np.zeros((T, 3, 4))
    identity[:, :, :3] = np.eye(3)
    if not links:
        return SolveResult({k: identity[0].copy() for k in tiles}, 0.0, 0, [], {})
    local, target_pts, own, other, w = _flatten(links, index)
    fixed_idx = np.array(sorted(index[k] for k in fixed if k in index), int)
    cur = identity.copy()
    # warm start: exact weighted-Laplacian solve of the translation part
    # (exact optimum for TRANSLATION/NONE; a good basin for the rest)
    cur[:, :, 3] = _direct_translations(links, index, fixed_idx, T)
    damping = params.damping
    history: list[float] = []
    it = 0
    stall = 0
    for it in range(1, params.max_iterations + 1):
        tgt_world = _apply_batch(cur, target_pts, other)
        sw, swp, swq, spp, spq = _segment_moments(local, tgt_world, own, w, T)
        new = _fit_from_moments(params.model, sw, swp, swq, spp, spq)
        if params.regularization != M.NONE and params.lam > 0:
            reg = _fit_from_moments(params.regularization, sw, swp, swq, spp, spq)
            new = (1 - params.lam) * new + params.lam * reg
        # tiles with no matches keep identity
        new[sw <= 0] = identity[sw <= 0]
        if len(fixed_idx):
            new[fixed_idx] = identity[fixed_idx]
        cur = (1 - damping) * cur + damping * new
        # weighted mean point-match displacement (mpicbg mean error)
        err = _mean_error(cur, local, target_pts, own, other, w)
        history.append(err)
        if len(history) > 1:
            stall = stall + 1 if history[-2] - err < 1e-9 * max(err, 1.0) else 0
            if stall >= 5:
                break  # exact fixed point — no further progress possible
        pw = params.max_plateau_width
        if it > pw and history[-1] < params.max_error:
            # plateau ends the solve only once below the target error
            # (mpicbg ConvergenceStrategy: maxAllowedError + maxPlateauwidth)
            window = history[-pw:]
            improvement = history[-pw - 1] - min(window)
            if improvement < 1e-4 * max(history[-1], 1e-12) or history[-1] < 1e-9:
                break
    err = history[-1] if history else 0.0
    link_errors = _per_link_errors(cur, links, index)
    _SOLVE_ITERS.inc(it)
    return SolveResult(
        {k: cur[i].copy() for k, i in index.items()}, err, it, [],
        link_errors, history=np.asarray(history),
    )


def _direct_translations(links, index, fixed_idx, T) -> np.ndarray:
    """Closed-form weighted least squares over link mean shifts (graph
    Laplacian); fixed tiles pinned at zero.

    Assembled SPARSELY from the link incidence (4 entries per link + the
    anchor/isolated diagonal) and solved with a sparse LU: a tile graph
    has O(T) links, so the former dense (T, T) build allocated O(T²)
    purely for structure — at million-tile grids that is the warm start
    OOMing before the solve even starts."""
    import scipy.sparse as sp
    from scipy.sparse.linalg import splu

    if not links:
        return np.zeros((T, 3))
    ia = np.fromiter((index[lk.key_a] for lk in links), int, len(links))
    ib = np.fromiter((index[lk.key_b] for lk in links), int, len(links))
    wsum = np.array([float(lk.w.sum()) for lk in links])
    s = np.stack([((lk.q - lk.p) * lk.w[:, None]).sum(0)
                  / max(float(lk.w.sum()), 1e-12) for lk in links])
    anchor = fixed_idx if len(fixed_idx) else np.arange(1)
    anchored = np.zeros(T, bool)
    anchored[anchor] = True
    B = np.zeros((T, 3))
    np.add.at(B, ia, wsum[:, None] * s)
    np.add.at(B, ib, -wsum[:, None] * s)
    B[anchored] = 0.0
    # Laplacian entries, with anchored ROWS replaced by identity rows
    # (the same pinning the dense build applied destructively)
    rows = np.concatenate([ia, ib, ia, ib])
    cols = np.concatenate([ia, ib, ib, ia])
    vals = np.concatenate([wsum, wsum, -wsum, -wsum])
    keep = ~anchored[rows]
    rows, cols, vals = rows[keep], cols[keep], vals[keep]
    diag = np.zeros(T)
    np.add.at(diag, ia[~anchored[ia]], wsum[~anchored[ia]])
    np.add.at(diag, ib[~anchored[ib]], wsum[~anchored[ib]])
    # anchors and isolated tiles (zero diagonal) get a bare 1.0 diagonal
    unit = anchored | (diag == 0)
    off = vals != 0
    A = sp.coo_matrix(
        (np.concatenate([vals[off], np.ones(int(unit.sum()))]),
         (np.concatenate([rows[off], np.flatnonzero(unit)]),
          np.concatenate([cols[off], np.flatnonzero(unit)]))),
        shape=(T, T)).tocsc()
    try:
        return splu(A).solve(B)
    except RuntimeError:
        return np.zeros((T, 3))


def _mean_error(models, local, target_pts, own, other, w) -> float:
    a = _apply_batch(models, local, own)
    b = _apply_batch(models, target_pts, other)
    d = np.linalg.norm(a - b, axis=1)
    return float((d * w).sum() / max(w.sum(), 1e-12))


def _per_link_errors(models, links, index) -> dict[tuple[Key, Key], float]:
    out = {}
    for lk in links:
        ma, mb = models[index[lk.key_a]], models[index[lk.key_b]]
        a = lk.p @ ma[:, :3].T + ma[:, 3]
        b = lk.q @ mb[:, :3].T + mb[:, 3]
        d = np.linalg.norm(a - b, axis=1)
        out[(lk.key_a, lk.key_b)] = float((d * lk.w).sum() / max(lk.w.sum(), 1e-12))
    return out


class _DeviceRelax:
    """Driver for the compiled relaxation (ops/solve.py): flattens the
    link graph ONCE into padded device arrays, then every solve — the
    first and every masked re-solve of the iterative drop-worst-link loop
    — re-enters the same compiled ``lax.while_loop`` with a per-link
    weight mask. Above ``BST_SOLVE_SHARD`` point rows the arrays are laid
    out per mesh device — every process's devices when the global solve
    mesh is on (``BST_SOLVE_GLOBAL``), the local ones otherwise — with
    tiles placed cost-weighted via ``pairsched.assign_tasks`` and each
    sweep's segment moments reduced with ``lax.psum`` over the 1-D solve
    mesh axis."""

    def __init__(self, links: list[MatchLink], tiles: list[Key],
                 fixed: set[Key], params: SolverParams):
        from ..ops import solve as _dsolve

        self.links = list(links)
        self.tiles = tiles
        self.params = params
        self.index = {k: i for i, k in enumerate(tiles)}
        self.fixed_idx = np.array(
            sorted(self.index[k] for k in fixed if k in self.index), int)
        T = len(tiles)
        rows = [(self.index[lk.key_a], self.index[lk.key_b],
                 np.asarray(lk.p, np.float64), np.asarray(lk.q, np.float64),
                 np.asarray(lk.w, np.float64)) for lk in self.links]
        n_rows = 2 * sum(len(lk.p) for lk in self.links)
        n_shards, global_mesh = _dsolve.solve_layout(n_rows)
        # bst-lint: off=host-sync (solve_layout returns host ints)
        if n_shards > 1:
            from ..parallel.pairsched import PairTask, assign_tasks

            # rows per tile drive placement: the per-device row counts are
            # the actual load of the sharded segment-moment pass
            per_tile = np.zeros(T)
            for ia, ib, p, _, _ in rows:
                per_tile[ia] += len(p)
                per_tile[ib] += len(p)
            bins = assign_tasks(
                [PairTask(index=t, cost=float(per_tile[t]))
                 for t in range(T)], n_shards)
            tile_shard = np.zeros(T, np.int32)
            for d, bin_tasks in enumerate(bins):
                for t in bin_tasks:
                    tile_shard[t.index] = d
            self.problem = _dsolve.prepare_relax(rows, T, n_shards,
                                                 tile_shard,
                                                 global_mesh=global_mesh)
        else:
            self.problem = _dsolve.prepare_relax(rows, T, 1)
        self.fixed_mask = np.zeros(T, bool)
        if len(self.fixed_idx):
            self.fixed_mask[self.fixed_idx] = True

    def solve(self, link_mask: np.ndarray | None = None) -> SolveResult:
        import time

        import jax

        from ..ops import solve as _dsolve

        p = self.params
        T = len(self.tiles)
        identity = np.zeros((3, 4))
        identity[:, :3] = np.eye(3)
        if link_mask is None:
            link_mask = np.ones(len(self.links))
        active = [lk for lk, m in zip(self.links, link_mask) if m]
        if not active:
            return SolveResult({k: identity.copy() for k in self.tiles},
                               0.0, 0, [], {}, history=np.zeros(0))
        # warm start on the ACTIVE links only, so a masked re-solve equals
        # a rebuilt-link-list solve exactly
        warm_t = _direct_translations(active, self.index, self.fixed_idx, T)
        reg = p.regularization if (p.regularization != M.NONE
                                   and p.lam > 0) else M.NONE
        # build + XLA-compile OUTSIDE the timed span: the device-ms
        # counter measures the compiled loop, never a cold bucket's build
        _dsolve.ensure_relax_compiled(self.problem, p.model, reg,
                                      p.max_iterations,
                                      p.max_plateau_width)
        t0 = time.perf_counter()
        with profiling.span("solve.relax", stage="solver",
                            item=self.problem.n_rows):
            out = _dsolve.relax_on_device(
                self.problem, link_mask, self.fixed_mask, warm_t,
                p.lam, p.damping, p.max_error, p.max_iterations,
                p.model, reg, p.max_plateau_width)
        _metrics.counter("bst_solve_device_ms_total", stage="relax").inc(
            (time.perf_counter() - t0) * 1000.0)
        with profiling.span("solve.reduce", stage="solver"):
            models, hist, iters, link_err = jax.device_get(out)
        iters = int(iters)
        history = hist[:iters]
        err = float(history[-1]) if iters else 0.0
        link_errors = {
            (lk.key_a, lk.key_b): float(link_err[l])
            for l, lk in enumerate(self.links) if link_mask[l]
        }
        _SOLVE_ITERS.inc(iters)
        return SolveResult(
            {k: models[i].copy() for k, i in self.index.items()},
            err, iters, [], link_errors, history=history,
        )


def solve_iterative(
    links: list[MatchLink], tiles: list[Key], fixed: set[Key], params: SolverParams,
    verbose: bool = True,
) -> SolveResult:
    """GlobalOptIterative: re-solve dropping the worst link while it exceeds
    max(relThresh × avg, absThresh) (Solver.java:310-318; defaults
    relative 3.5 / absolute 7.0, Solver.java:131-134).

    On the device backend the link list is flattened/compiled ONCE and
    every re-solve re-enters the warm compiled fn with a zeroed entry in
    the link-weight mask — no per-drop re-trace, no array rebuild."""
    links = list(links)
    if _resolve_backend(params) == "device" and links:
        state = _DeviceRelax(links, tiles, fixed, params)
        key_to_l = {(lk.key_a, lk.key_b): l for l, lk in enumerate(links)}
        mask = np.ones(len(links))
        removed: list[tuple[Key, Key]] = []
        while True:
            res = state.solve(mask)
            if not res.link_errors or int(mask.sum()) <= 1:
                break
            avg = float(np.mean(list(res.link_errors.values())))
            worst_key = max(res.link_errors, key=res.link_errors.get)
            worst = res.link_errors[worst_key]
            if not (worst > params.relative_threshold * avg
                    and worst > params.absolute_threshold):
                break
            observe.log(f"solver: dropping link {worst_key[0][0]}<->"
                        f"{worst_key[1][0]} error {worst:.2f} "
                        f"(avg {avg:.2f})", stage="solver", echo=verbose,
                        error=round(float(worst), 3))
            mask[key_to_l[worst_key]] = 0.0
            removed.append(worst_key)
        res.removed_links.extend(removed)
        _SOLVE_DROPPED.inc(len(removed))
        return res
    removed = []
    while True:
        res = _relax_numpy(links, tiles, fixed, params)
        if not res.link_errors or len(links) <= 1:
            break
        avg = float(np.mean(list(res.link_errors.values())))
        worst_key = max(res.link_errors, key=res.link_errors.get)
        worst = res.link_errors[worst_key]
        # a link is "wrong" when it is BOTH many times worse than the average
        # AND above the absolute floor (SimpleIterativeConvergenceStrategy)
        if not (worst > params.relative_threshold * avg
                and worst > params.absolute_threshold):
            break
        observe.log(f"solver: dropping link {worst_key[0][0]}<->"
                    f"{worst_key[1][0]} error {worst:.2f} (avg {avg:.2f})",
                    stage="solver", echo=verbose,
                    error=round(float(worst), 3))
        links = [lk for lk in links if (lk.key_a, lk.key_b) != worst_key]
        removed.append(worst_key)
    res.removed_links.extend(removed)
    _SOLVE_DROPPED.inc(len(removed))
    return res


# ---------------------------------------------------------------------------
# subsets, fixed views, two-round
# ---------------------------------------------------------------------------

def connected_components(tiles: list[Key], links: list[MatchLink]) -> list[list[Key]]:
    parent = {k: k for k in tiles}

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for lk in links:
        if lk.key_a in parent and lk.key_b in parent:
            parent[find(lk.key_a)] = find(lk.key_b)
    comps: dict[Key, list[Key]] = {}
    for k in tiles:
        comps.setdefault(find(k), []).append(k)
    return sorted(comps.values(), key=lambda c: c[0])


def pick_fixed(tiles: list[Key], params: SolverParams) -> set[Key]:
    """User-specified fixed views, else the first tile per timepoint subset
    (Solver.java:675-718)."""
    if params.disable_fixed_views:
        return set()
    if params.fixed_views:
        fixed = set()
        for t in tiles:
            if any(v in params.fixed_views for v in t):
                fixed.add(t)
        return fixed
    first_per_tp: dict[int, Key] = {}
    for t in tiles:
        tp = t[0].timepoint
        first_per_tp.setdefault(tp, t)
    return set(first_per_tp.values())


def solve(
    sd: SpimData,
    views: list[ViewId],
    params: SolverParams,
    store: InterestPointStore | None = None,
    verbose: bool = True,
) -> SolveResult:
    """Full solve: assemble matches, pick fixed tiles, run the requested
    method, return per-tile corrections (not yet stored into the XML)."""
    tiles = build_tiles(sd, views, params)
    if params.source.upper() == "STITCHING":
        links = matches_from_stitching(sd, tiles, verbose)
    else:
        if store is None:
            store = InterestPointStore.for_project(sd)
        labels = params.labels or _all_labels(sd, views)
        links = matches_from_interest_points(
            sd, tiles, store, labels, params.label_weights, verbose
        )
    observe.log(f"solver: {len(tiles)} tiles, {len(links)} links, "
                f"method {params.method}, model {params.model}"
                + (f" reg {params.regularization} λ={params.lam}"
                   if params.regularization != M.NONE else ""),
                stage="solver", echo=verbose,
                tiles=len(tiles), links=len(links))

    fixed = pick_fixed(tiles, params)
    iterative = params.method.endswith("ITERATIVE")
    two_round = params.method.startswith("TWO_ROUND")

    comps = connected_components(tiles, links)
    corrections: dict[Key, np.ndarray] = {}
    total_err, total_it = 0.0, 0
    removed: list[tuple[Key, Key]] = []
    link_errors: dict[tuple[Key, Key], float] = {}
    for comp in comps:
        comp_set = set(comp)
        comp_links = [lk for lk in links
                      if lk.key_a in comp_set and lk.key_b in comp_set]
        comp_fixed = fixed & comp_set
        if not comp_fixed:
            comp_fixed = {comp[0]}  # per-subset anchor (round-1 of two-round)
        solver_fn = solve_iterative if iterative else relax
        res = solver_fn(comp_links, comp, comp_fixed, params)
        corrections.update(res.corrections)
        total_err = max(total_err, res.error)
        total_it += res.iterations
        removed.extend(res.removed_links)
        link_errors.update(res.link_errors)

    if two_round and len(comps) > 1:
        _align_components_to_metadata(comps, corrections, fixed, verbose)
    elif not two_round and len(comps) > 1:
        observe.log(f"solver: WARNING {len(comps)} unconnected subsets solved "
                    "independently (use TWO_ROUND_* to place them via "
                    "metadata)", stage="solver", echo=verbose,
                    subsets=len(comps))

    observe.log(f"solver: done, max subset error {total_err:.3f} px "
                f"({total_it} iterations total"
                + (f", {len(removed)} links removed" if removed else "") + ")",
                stage="solver", echo=verbose,
                max_error_px=round(float(total_err), 4),
                iterations=total_it, removed_links=len(removed))
    if total_err > params.max_error:
        observe.log(f"solver: WARNING did not reach --maxError "
                    f"{params.max_error} px (best {total_err:.3f} px)",
                    stage="solver", echo=verbose)
    return SolveResult(corrections, total_err, total_it, removed, link_errors)


def _align_components_to_metadata(comps, corrections, fixed, verbose):
    """Round 2 of GlobalOptTwoRound (Solver.java:324-338), simplified: each
    component without a globally fixed tile gets a rigid-free translation
    removing its mean correction, so unconnected groups stay where the
    metadata (current registrations) places them — the role of
    MetaDataWeakLinkFactory weak links."""
    for comp in comps:
        if any(k in fixed for k in comp):
            continue
        mean_t = np.mean([corrections[k][:, 3] for k in comp], axis=0)
        for k in comp:
            corrections[k] = corrections[k].copy()
            corrections[k][:, 3] -= mean_t
        observe.log(f"solver: re-anchored unconnected subset of {len(comp)} "
                    f"tile(s) to metadata (Δ={np.round(mean_t, 2)})",
                    stage="solver", echo=verbose, tiles=len(comp))


def _all_labels(sd: SpimData, views: list[ViewId]) -> list[str]:
    labels = []
    for v in views:
        for lab in sd.interest_points.get(v, {}):
            if lab not in labels:
                labels.append(lab)
    return labels


def store_corrections(
    sd: SpimData, result: SolveResult, params: SolverParams
) -> None:
    """Preconcatenate each tile's correction to all member views
    (TransformationTools.storeTransformation, Solver.java:351-369)."""
    name = f"{params.model.capitalize()}Model3D"
    if params.regularization != M.NONE:
        name += f" regularized by {params.regularization.capitalize()} (λ={params.lam})"
    name += f" on [{params.source.lower()}]"
    for key, corr in result.corrections.items():
        for v in key:
            sd.preconcatenate_transform(v, ViewTransform(name, corr.copy()))

"""Pyramid-level writer: block-parallel 2x downsampling of an existing level
(SparkAffineFusion.java:703-782 and SparkDownsample.java:141-177 equivalent).

The block grid is the work list (strategy P1); blocks batch over the device
mesh via run_sharded_batches — the TPU replacement of the reference's
per-level Spark map (SparkDownsample.java:141-177), with double-buffered
host IO on either side of the kernel.
"""

from __future__ import annotations

import numpy as np

from ..utils.threads import CtxThreadPool

from ..io.chunkstore import ChunkStore, Dataset, StorageFormat
from ..io.container import MultiResolutionLevelInfo
from ..ops.downsample import downsample_block
from ..parallel.mesh import make_mesh, run_sharded_batches, shard_jit
from ..utils.grid import GridBlock, create_grid


def read_padded(src_read, src_shape, src_off, src_size) -> "np.ndarray":
    """Read ``src_size`` voxels at ``src_off``, edge-replicating past the
    source extent (thin axes whose level dim was clamped to 1).
    ``src_read(off, size)`` is the raw reader."""
    clamped = [min(int(s), int(e) - int(o)) for s, e, o in
               zip(src_size, src_shape, src_off)]
    data = src_read([int(o) for o in src_off], clamped)
    if clamped != [int(s) for s in src_size]:
        pad = [(0, int(s) - c) for s, c in zip(src_size, clamped)]
        if isinstance(data, np.ndarray):
            data = np.pad(data, pad, mode="edge")
        else:
            # device array (a streaming handoff read): pad on device, the
            # bytes must not round-trip through the host here
            import jax.numpy as jnp

            data = jnp.pad(data, pad, mode="edge")
    return data


def downsample_read(src_read, src_shape, src_off, src_size, factors) -> "np.ndarray":
    """read_padded + average-downsample by ``factors``."""
    import jax

    data = read_padded(src_read, src_shape, src_off, src_size)
    return jax.device_get(
        downsample_block(data, tuple(int(f) for f in factors)))


def _convert_to_dtype(out: np.ndarray, dtype) -> np.ndarray:
    if np.issubdtype(np.dtype(dtype), np.integer):
        info = np.iinfo(np.dtype(dtype))
        out = np.clip(np.round(out), info.min, info.max)
    return out.astype(dtype)


def downsample_write_block(src: Dataset, dst: Dataset, block: GridBlock,
                           factors, src_read=None, src_shape=None,
                           dst_write=None) -> None:
    """The shared per-block downsample step: read factor-scaled source box,
    average, clip/round for integer outputs, write (used by the fusion
    pyramid, resave pyramid, and the standalone downsample tool).
    ``src_read``/``src_shape``/``dst_write`` override the raw 3-D accessors
    (the 5-D OME-ZARR path supplies channel/timepoint-sliced wrappers)."""
    src_off = [o * f for o, f in zip(block.offset, factors)]
    src_size = [s * f for s, f in zip(block.size, factors)]
    out = downsample_read(src_read or src.read,
                          src_shape or src.shape, src_off, src_size, factors)
    (dst_write or dst.write)(_convert_to_dtype(out, dst.dtype), block.offset)


def make_downsample_kernel(n_dev: int, rel):
    """Batched average-downsample kernel; batch axis sharded when n_dev > 1."""
    return _make_downsample_kernel_cached(n_dev, tuple(int(f) for f in rel))


import functools


@functools.lru_cache(maxsize=32)
def _make_downsample_kernel_cached(n_dev: int, rel_t):
    """lru_cache'd: pyramid writers call this once per level — without the
    cache each level recompiled the same program."""
    import jax

    def batched(raws):
        return jax.vmap(lambda x: downsample_block(x, rel_t))(raws)

    if n_dev <= 1:
        return jax.jit(batched)
    return shard_jit(batched, make_mesh(n_dev), n_in=1)


def prefetch_src_box(ds, src_off, src_size):
    """``(ds, clipped offset, clipped shape)`` of a padded source-box read
    — what the async prefetcher feeds (io/prefetch.py) hand to
    ``Dataset.prefetch_box``. None when the clip is empty or ``ds`` is
    not a chunkstore dataset."""
    if not hasattr(ds, "prefetch_box"):
        return None
    dims = ds.shape
    lo = [max(0, int(o)) for o in src_off]
    hi = [min(int(d), int(o) + int(s))
          for d, o, s in zip(dims, src_off, src_size)]
    if any(h <= l for l, h in zip(lo, hi)):
        return None
    return ds, tuple(lo), tuple(h - l for l, h in zip(lo, hi))


def run_sharded_downsample(jobs, read_job, write_job, rel, devices=None,
                           io_threads: int = 8, per_dev: int = 4,
                           label: str = "downsample block",
                           multihost: bool = True,
                           device_drain: bool = False,
                           prefetch_job=None) -> None:
    """Downsample every (job, src-box) through the mesh. ``read_job(job)``
    returns the raw source box (size = out_block * rel, edge-padded);
    ``write_job(job, data)`` converts + writes. Jobs are bucketed by source
    shape so one compile serves each shape. ``device_drain`` routes each
    device's output shard through its own drain+write worker
    (parallel.mesh) — only safe for parallel-writer stores, never h5py.
    ``prefetch_job(job) -> [(ds, off, shape), ...]`` names the source
    boxes for the async prefetcher feed (parallel.mesh ``prefetch_boxes``;
    advisory, inert while the prefetcher is off)."""
    import jax

    n_dev = devices if devices is not None else len(jax.local_devices())
    kernel = make_downsample_kernel(n_dev, rel)
    buckets: dict[tuple, list] = {}
    for job in jobs:
        buckets.setdefault(tuple(read_shape(job, rel)), []).append(job)
    def build(job):
        # ship the source box in its stored dtype — downsample_block casts
        # to float32 ON DEVICE, so the host astype only doubled wire bytes
        # (big-endian HDF5 blocks byteswap on host: JAX rejects them)
        raw = read_job(job)
        if raw.dtype.kind in "iu" and raw.dtype.itemsize < 4:
            if raw.dtype.byteorder == ">":
                raw = raw.astype(raw.dtype.newbyteorder("="))
            return (raw,)
        return (raw.astype(np.float32),)

    pool = CtxThreadPool(max_workers=max(1, io_threads))
    try:
        for shp, items in sorted(buckets.items()):
            out_vox = int(np.prod([s // int(f) for s, f in zip(shp, rel)]))
            run_sharded_batches(
                items, build, kernel, write_job,
                n_dev, pool, label=label, per_dev=per_dev,
                multihost=multihost,
                out_bytes_per_item=out_vox * 4,  # f32 device output
                workspace_mult=3.0,              # f32 cast of the input
                device_drain=device_drain,
                prefetch_boxes=prefetch_job,
            )
    finally:
        pool.shutdown(wait=True)


def read_shape(job, rel):
    """Source-box shape of a (block,) job: out block size * relative factor."""
    block = job if isinstance(job, GridBlock) else job[1]
    return [int(s) * int(f) for s, f in zip(block.size, rel)]


def validate_pyramid(absolute: list[list[int]]) -> None:
    """Each absolute factor must be an exact multiple of the previous one,
    starting at 1,1,1 — otherwise relative steps floor-divide and levels
    would be silently corrupt."""
    if list(absolute[0]) != [1, 1, 1]:
        raise ValueError(f"pyramid must start with 1,1,1, got {absolute[0]}")
    for prev, cur in zip(absolute, absolute[1:]):
        if any(int(c) % int(p) != 0 for p, c in zip(prev, cur)):
            raise ValueError(
                f"pyramid step {cur} is not an exact multiple of {prev}"
            )


def downsample_pyramid_level(
    store: ChunkStore,
    src_info: MultiResolutionLevelInfo,
    dst_info: MultiResolutionLevelInfo,
    is_zarr5d: bool = False,
    ct: tuple[int, int] = (0, 0),
    devices: int | None = None,
    io_threads: int = 8,
    skip_existing: bool = False,
) -> None:
    """Fill ``dst_info`` from ``src_info`` by relative-factor averaging,
    block-sharded over the device mesh (SparkDownsample.java:141-177).

    ``skip_existing``: return immediately when the fusion drivers already
    materialized this level for this (channel, timepoint) slot as a fused
    multiscale epilogue (the container records that per level; epilogue
    output is bit-identical to this path, so there is nothing to redo —
    and crucially no full-res container re-read)."""
    import time

    from .. import observe
    from ..io.container import epilogue_written

    if skip_existing and epilogue_written(store, dst_info.dataset, ct):
        observe.progress.record_stage(
            f"downsample {dst_info.dataset.strip('/')}",
            done=0, total=0, blocks=0, seconds=0.0,
            skipped="fusion epilogue already materialized this level",
        )
        return

    t0 = time.time()
    src = store.open_dataset(src_info.dataset.strip("/"))
    dst = store.open_dataset(dst_info.dataset.strip("/"))
    rel = [int(v) for v in dst_info.relativeDownsampling[:3]]
    dims3 = [int(v) for v in dst_info.dimensions[:3]]
    block3 = [int(v) for v in dst_info.blockSize[:3]]
    grid = create_grid(dims3, block3)

    if is_zarr5d:
        c, t = ct

        def read3d(off, size):
            return src.read((*off, c, t), (*size, 1, 1))[..., 0, 0]

        def write3d(data, off):
            dst.write(data[..., None, None], (*off, c, t))

        src_shape = src.shape[:3]
    else:
        def read3d(off, size):
            # a streamed producer's device-resident blocks serve straight
            # from HBM (zero D2H + zero container decode); None falls back
            # to the gated host read
            dev = src.read_device(off, size)
            return dev if dev is not None else src.read(off, size)

        write3d, src_shape = dst.write, src.shape

    def read_job(block: GridBlock):
        src_off = [o * f for o, f in zip(block.offset, rel)]
        src_size = [s * f for s, f in zip(block.size, rel)]
        return read_padded(read3d, src_shape, src_off, src_size)

    def write_job(block: GridBlock, out):
        write3d(_convert_to_dtype(out, dst.dtype), block.offset)

    def prefetch_job(block: GridBlock):
        src_off = [o * f for o, f in zip(block.offset, rel)]
        src_size = [s * f for s, f in zip(block.size, rel)]
        if is_zarr5d:
            c, t = ct
            b = prefetch_src_box(src, (*src_off, c, t), (*src_size, 1, 1))
        else:
            b = prefetch_src_box(src, src_off, src_size)
        return [b] if b is not None else []

    run_sharded_downsample(grid, read_job, write_job, rel, devices=devices,
                           io_threads=io_threads,
                           # per-device direct chunk writes wherever the
                           # store allows concurrent writers
                           device_drain=getattr(store, "format", None)
                           != StorageFormat.HDF5,
                           prefetch_job=prefetch_job)
    dt = time.time() - t0
    observe.progress.record_stage(
        f"downsample {dst_info.dataset.strip('/')}",
        done=len(grid), blocks=len(grid), seconds=round(dt, 3),
        rate_per_s=round(len(grid) / max(dt, 1e-9), 3),
    )

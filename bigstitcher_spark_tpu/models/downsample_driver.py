"""Pyramid-level writer: block-parallel 2x downsampling of an existing level
(SparkAffineFusion.java:703-782 and SparkDownsample.java:141-177 equivalent).
"""

from __future__ import annotations

import numpy as np

from ..io.chunkstore import ChunkStore
from ..io.container import MultiResolutionLevelInfo
from ..ops.downsample import downsample_block
from ..parallel.retry import run_with_retry
from ..utils.grid import create_grid


def downsample_pyramid_level(
    store: ChunkStore,
    src_info: MultiResolutionLevelInfo,
    dst_info: MultiResolutionLevelInfo,
    is_zarr5d: bool = False,
    ct: tuple[int, int] = (0, 0),
) -> None:
    """Fill ``dst_info`` from ``src_info`` by relative-factor averaging."""
    src = store.open_dataset(src_info.dataset.strip("/"))
    dst = store.open_dataset(dst_info.dataset.strip("/"))
    rel = [int(v) for v in dst_info.relativeDownsampling[:3]]
    dims3 = [int(v) for v in dst_info.dimensions[:3]]
    block3 = [int(v) for v in dst_info.blockSize[:3]]
    grid = create_grid(dims3, block3)

    def process(block):
        src_off = [o * f for o, f in zip(block.offset, rel)]
        src_size = [s * f for s, f in zip(block.size, rel)]
        if is_zarr5d:
            c, t = ct
            data = src.read((*src_off, c, t), (*src_size, 1, 1))[..., 0, 0]
        else:
            data = src.read(src_off, src_size)
        out = np.asarray(downsample_block(data, tuple(rel)))
        if np.issubdtype(dst.dtype, np.integer):
            out = np.clip(np.round(out), np.iinfo(dst.dtype).min,
                          np.iinfo(dst.dtype).max)
        out = out.astype(dst.dtype)
        if is_zarr5d:
            dst.write(out[..., None, None], (*block.offset, *ct))
        else:
            dst.write(out, block.offset)

    run_with_retry(grid, process, label="downsample block")

"""Pyramid-level writer: block-parallel 2x downsampling of an existing level
(SparkAffineFusion.java:703-782 and SparkDownsample.java:141-177 equivalent).
"""

from __future__ import annotations

import numpy as np

from ..io.chunkstore import ChunkStore, Dataset
from ..io.container import MultiResolutionLevelInfo
from ..ops.downsample import downsample_block
from ..parallel.retry import run_with_retry
from ..utils.grid import GridBlock, create_grid


def downsample_read(src_read, src_shape, src_off, src_size, factors) -> "np.ndarray":
    """Read ``src_size`` voxels at ``src_off``, edge-replicating past the
    source extent (thin axes whose level dim was clamped to 1), then
    average-downsample by ``factors``. ``src_read(off, size)`` is the raw
    reader."""
    clamped = [min(int(s), int(e) - int(o)) for s, e, o in
               zip(src_size, src_shape, src_off)]
    data = src_read([int(o) for o in src_off], clamped)
    if clamped != [int(s) for s in src_size]:
        pad = [(0, int(s) - c) for s, c in zip(src_size, clamped)]
        data = np.pad(data, pad, mode="edge")
    return np.asarray(downsample_block(data, tuple(int(f) for f in factors)))


def downsample_write_block(src: Dataset, dst: Dataset, block: GridBlock,
                           factors, src_read=None, src_shape=None,
                           dst_write=None) -> None:
    """The shared per-block downsample step: read factor-scaled source box,
    average, clip/round for integer outputs, write (used by the fusion
    pyramid, resave pyramid, and the standalone downsample tool).
    ``src_read``/``src_shape``/``dst_write`` override the raw 3-D accessors
    (the 5-D OME-ZARR path supplies channel/timepoint-sliced wrappers)."""
    src_off = [o * f for o, f in zip(block.offset, factors)]
    src_size = [s * f for s, f in zip(block.size, factors)]
    out = downsample_read(src_read or src.read,
                          src_shape or src.shape, src_off, src_size, factors)
    if np.issubdtype(dst.dtype, np.integer):
        info = np.iinfo(dst.dtype)
        out = np.clip(np.round(out), info.min, info.max)
    (dst_write or dst.write)(out.astype(dst.dtype), block.offset)


def validate_pyramid(absolute: list[list[int]]) -> None:
    """Each absolute factor must be an exact multiple of the previous one,
    starting at 1,1,1 — otherwise relative steps floor-divide and levels
    would be silently corrupt."""
    if list(absolute[0]) != [1, 1, 1]:
        raise ValueError(f"pyramid must start with 1,1,1, got {absolute[0]}")
    for prev, cur in zip(absolute, absolute[1:]):
        if any(int(c) % int(p) != 0 for p, c in zip(prev, cur)):
            raise ValueError(
                f"pyramid step {cur} is not an exact multiple of {prev}"
            )


def downsample_pyramid_level(
    store: ChunkStore,
    src_info: MultiResolutionLevelInfo,
    dst_info: MultiResolutionLevelInfo,
    is_zarr5d: bool = False,
    ct: tuple[int, int] = (0, 0),
) -> None:
    """Fill ``dst_info`` from ``src_info`` by relative-factor averaging."""
    src = store.open_dataset(src_info.dataset.strip("/"))
    dst = store.open_dataset(dst_info.dataset.strip("/"))
    rel = [int(v) for v in dst_info.relativeDownsampling[:3]]
    dims3 = [int(v) for v in dst_info.dimensions[:3]]
    block3 = [int(v) for v in dst_info.blockSize[:3]]
    grid = create_grid(dims3, block3)

    if is_zarr5d:
        c, t = ct

        def read3d(off, size):
            return src.read((*off, c, t), (*size, 1, 1))[..., 0, 0]

        def write3d(data, off):
            dst.write(data[..., None, None], (*off, c, t))

        def process(block):
            downsample_write_block(src, dst, block, rel, src_read=read3d,
                                   src_shape=src.shape[:3], dst_write=write3d)
    else:
        def process(block):
            downsample_write_block(src, dst, block, rel)

    run_with_retry(grid, process, label="downsample block")

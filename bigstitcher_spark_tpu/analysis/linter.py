"""``bst lint`` driver: file discovery, suppression comments, baseline.

Runs the invariant checks in :mod:`.checks` over a package tree and
reconciles the findings against a committed baseline, so NEW violations
fail tier-1 (tests/test_lint.py, scripts/lint.sh) while any legacy ones
are tracked instead of silenced.

Suppressions
------------
``# bst-lint: off`` or ``# bst-lint: off=check-a,check-b`` on the
offending line (or the line directly above it, for statements that do
not fit a trailing comment) suppresses the named checks — the reasoning
belongs in the same comment. Suppressions are per-line, never per-file:
a module cannot opt out wholesale.

Baseline
--------
``analysis/baseline.json`` maps finding keys (``check|path|source-line``
— line NUMBERS are deliberately absent, so unrelated edits above a
legacy finding do not churn the file) to occurrence counts. A finding is
NEW when its key is absent or its count exceeds the baselined count.
The shipped baseline is EMPTY: the codebase lints clean, and the
machinery exists so a future genuinely-unfixable finding can be tracked
without weakening the gate for everything else.
"""

from __future__ import annotations

import ast
import io
import json
import re
import tokenize
from pathlib import Path

from .checks import ALL_CHECKS, FileCtx, Finding

_SUPPRESS_RE = re.compile(r"#\s*bst-lint:\s*off(?:=([\w,-]+))?")

# keep full-line suppression state out of these; compiled artifacts etc.
_SKIP_DIRS = {"__pycache__"}


def parse_suppressions(source: str) -> dict[int, frozenset[str] | None]:
    """line -> suppressed check names (None = all checks)."""
    out: dict[int, frozenset[str] | None] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            checks = (frozenset(c.strip() for c in m.group(1).split(","))
                      if m.group(1) else None)
            out[tok.start[0]] = checks
    except tokenize.TokenError:
        pass
    return out


def _suppressed(finding: Finding,
                table: dict[int, frozenset[str] | None]) -> bool:
    for line in (finding.line, finding.line - 1):
        checks = table.get(line, False)
        if checks is False:
            continue
        if checks is None or finding.check in checks:
            return True
    return False


def collect_files(root: Path) -> list[tuple[Path, str]]:
    files = []
    for p in sorted(root.rglob("*.py")):
        if any(part in _SKIP_DIRS for part in p.parts):
            continue
        files.append((p, p.relative_to(root).as_posix()))
    return files


# parse each file ONCE and share the AST across invocations: a full `bst
# lint --all` plus the per-check smokes (and test_lint.py, which calls
# run_lint dozens of times against the live package) would otherwise
# re-read and re-parse the whole tree every call. Keyed by absolute path
# and invalidated on (mtime_ns, size) change, so fixture trees rewritten
# in place between runs are re-parsed. Checks must treat trees as
# read-only — they all do (pure visitors).
_AST_CACHE: dict[str, tuple[int, int, str, FileCtx, dict]] = {}


def _parse_one(path: Path, rel: str) -> tuple[FileCtx | None, dict,
                                              Finding | None]:
    """(ctx, suppression table, parse-error finding) for one file, via
    the shared cache."""
    key = str(path)
    st = path.stat()
    hit = _AST_CACHE.get(key)
    if hit is not None and hit[0] == st.st_mtime_ns and hit[1] == st.st_size \
            and hit[2] == rel:
        return hit[3], hit[4], None
    source = path.read_text(encoding="utf-8")
    try:
        tree = ast.parse(source, filename=key)
    except SyntaxError as e:
        _AST_CACHE.pop(key, None)
        return None, {}, Finding("parse", rel, e.lineno or 1,
                                 f"syntax error: {e.msg}", "")
    ctx = FileCtx(rel, tree, source.splitlines())
    table = parse_suppressions(source)
    _AST_CACHE[key] = (st.st_mtime_ns, st.st_size, rel, ctx, table)
    return ctx, table, None


def parse_package(root: Path | str) -> tuple[list[FileCtx],
                                             dict[str, dict],
                                             list[Finding]]:
    """Parsed FileCtx list + per-file suppression tables + parse-error
    findings for the tree at ``root`` (shared-AST cached)."""
    root = Path(root)
    ctxs: list[FileCtx] = []
    suppressions: dict[str, dict] = {}
    errors: list[Finding] = []
    for path, rel in collect_files(root):
        ctx, table, err = _parse_one(path, rel)
        if err is not None:
            errors.append(err)
            continue
        ctxs.append(ctx)
        suppressions[rel] = table
    return ctxs, suppressions, errors


def run_lint(root: Path | str,
             checks: dict | None = None) -> list[Finding]:
    """All unsuppressed findings for the package tree at ``root``."""
    ctxs, suppressions, findings = parse_package(root)
    for name, fn in (checks or ALL_CHECKS).items():
        findings.extend(fn(ctxs))
    findings = [f for f in findings
                if not _suppressed(f, suppressions.get(f.path, {}))]
    findings.sort(key=lambda f: (f.path, f.line, f.check, f.message))
    return findings


# -- baseline --------------------------------------------------------------

def baseline_counts(findings: list[Finding]) -> dict[str, int]:
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    return counts


def load_baseline(path: Path | str) -> dict[str, int]:
    path = Path(path)
    if not path.exists():
        return {}
    data = json.loads(path.read_text(encoding="utf-8"))
    return {str(k): int(v) for k, v in data.get("findings", {}).items()}


def save_baseline(path: Path | str, findings: list[Finding]) -> None:
    payload = {
        "comment": "bst lint baseline: legacy findings tracked, not "
                   "silenced; new findings fail. Regenerate with "
                   "`bst lint --update-baseline`.",
        "findings": dict(sorted(baseline_counts(findings).items())),
    }
    Path(path).write_text(json.dumps(payload, indent=1) + "\n",
                          encoding="utf-8")


def new_findings(findings: list[Finding],
                 baseline: dict[str, int]) -> list[Finding]:
    """Findings beyond the baselined count for their key (a key seen N
    times in the baseline admits N occurrences, any more are new)."""
    remaining = dict(baseline)
    out = []
    for f in findings:
        if remaining.get(f.key, 0) > 0:
            remaining[f.key] -= 1
        else:
            out.append(f)
    return out


def default_root() -> Path:
    """The installed package tree (what ``bst lint`` scans by default)."""
    return Path(__file__).resolve().parent.parent


def default_baseline_path(root: Path | str | None = None) -> Path:
    root = Path(root) if root is not None else default_root()
    return root / "analysis" / "baseline.json"

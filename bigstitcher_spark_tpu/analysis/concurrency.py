"""Concurrency-discipline checks for ``bst lint`` (pure stdlib ``ast``).

The threaded surface (``serve/daemon.py``, ``observe/relay.py``,
``dag/exchange.py``, ``dag/stream.py``, ``io/prefetch.py``,
``io/disktier.py``) grew past what review passes can police: the PR 15
round showed the dominant bug classes are *mechanical* concurrency
violations — a blocking read torn down by its own send timeout, a
``close()`` without ``shutdown()``, an unlocked check-then-close. The
reference's Spark runtime gets this discipline for free from the JVM
scheduler; our hand-rolled threads, locks and sockets get it from these
five checks instead. Like every ``bst lint`` check they are
approximations of the convention — a deliberate exception earns a
``# bst-lint: off=<check>`` suppression with the reasoning alongside.

Checks
------
``lock-order``
    Whole-package, interprocedural lock-acquisition graph. Locks are
    identified by their binding — ``self.<attr>`` assigned from
    ``threading.Lock/RLock/Condition/Semaphore`` (a ``Condition(self.x)``
    ALIASES to the lock it wraps: the condition and its lock are one
    node), module globals likewise, plus a name fallback for lock-ish
    ``with`` targets. An edge A->B is added when a ``with B:`` is
    reachable inside a ``with A:`` body — directly nested, or one call
    level deep through a resolvable callee that acquires B. Any cycle in
    the graph is a potential deadlock (two threads entering the cycle at
    different nodes can each hold what the other wants). Replaces the
    old single-file A->B/B->A pair heuristic; debug the computed graph
    with ``bst lint --graph lock-order``.

``blocking-under-lock``
    Calls that can block indefinitely (or for seconds) while a lock is
    held stall every other thread that needs the lock — the relay
    send-timeout-tears-down-the-reader bug class. Flags, inside a
    ``with <lock>:`` body: socket ``send*/recv*/accept/connect``,
    ``readline``, ``queue.Queue.get/put`` without ``block=False`` /
    ``timeout=`` / ``*_nowait``, ``subprocess.*``, ``time.sleep`` above
    a small literal threshold, ``jax.device_get`` /
    ``.block_until_ready()``, and chunk-container reads/writes — plus,
    one call level deep, same-file helpers that do any of the above.
    ``Condition.wait`` is exempt: it RELEASES the lock while blocked.

``thread-spawn``
    Raw ``threading.Thread`` / ``concurrent.futures.ThreadPoolExecutor``
    outside ``utils/threads.py`` drop the ``config.overrides()``
    contextvars and the ambient cancel token that ``CtxThreadPool`` /
    ``ctx_thread`` carry into workers — a worker spawned raw silently
    runs with the wrong knobs and ignores job cancellation.
    Process-lived daemon infrastructure that deliberately must NOT pin
    one job's context (relay, prefetch pool, exchange) carries explicit
    suppressions with the justification in the comment.

``cancel-coverage``
    An unbounded ``while True:`` loop in a worker callable under
    ``models/``, ``parallel/``, ``dag/`` or ``serve/`` must poll
    cooperative cancellation somewhere in the loop body —
    ``utils.cancel.check()``, ``.cancelled()``, a stop-event
    ``.is_set()``/``.wait()``, a stop-flag test, or a bounded
    ``*_nowait`` drain. A poll-free loop keeps running after its job is
    cancelled and wedges daemon drain.

``socket-hygiene``
    ``socket.close()`` without a preceding ``shutdown()`` on the same
    binding: io-refs held by ``makefile()`` wrappers keep the fd alive
    past the bare ``close()``, leaving phantom half-open connections the
    peer never notices (the PR 15 reconnect-flap class). Server sockets
    (``bind``/``listen``) are exempt — shutdown on a listener is
    meaningless — as are the blessed teardown helpers
    (``_shutdown_close`` / ``_close_sock``) and ``utils/`` files.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from .checks import ALL_CHECKS, FileCtx, Finding, dotted

# --------------------------------------------------------------------------
# shared: lock identification
# --------------------------------------------------------------------------

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_LOCKNAME_RE = re.compile(r"lock|cond|cv|mutex", re.IGNORECASE)


@dataclass
class _LockDecls:
    """Per-file lock declarations: attr -> canonical attr per class (a
    ``Condition(self.x)`` aliases to ``x``), plus module-global locks."""
    class_locks: dict[str, dict[str, str]] = field(default_factory=dict)
    module_locks: dict[str, str] = field(default_factory=dict)

    def canonical_attr(self, class_name: str, attr: str) -> str:
        amap = self.class_locks.get(class_name, {})
        seen = set()
        while attr in amap and amap[attr] != attr and attr not in seen:
            seen.add(attr)
            attr = amap[attr]
        return attr


def _lock_ctor_call(value: ast.AST) -> tuple[str, ast.AST | None] | None:
    """(ctor name, aliased-lock expr or None) when ``value`` constructs a
    threading lock/condition; ``Condition(x)`` carries ``x`` through."""
    if not isinstance(value, ast.Call):
        return None
    d = dotted(value.func)
    if not d:
        return None
    last = d.rsplit(".", 1)[-1]
    if last not in _LOCK_CTORS:
        return None
    alias = value.args[0] if (last == "Condition" and value.args) else None
    return last, alias


def _collect_lock_decls(ctx: FileCtx) -> _LockDecls:
    decls = _LockDecls()

    def record(store: dict[str, str], name: str, alias: ast.AST | None,
               attr_of_self: bool) -> None:
        if alias is not None:
            ad = dotted(alias)
            if ad and attr_of_self and ad.startswith("self."):
                store[name] = ad[5:]
                return
            if ad and not attr_of_self and "." not in ad:
                store[name] = ad
                return
        store[name] = name

    for node in ctx.tree.body:
        if isinstance(node, ast.ClassDef):
            cmap = decls.class_locks.setdefault(node.name, {})
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Assign):
                    continue
                ctor = _lock_ctor_call(sub.value)
                if ctor is None:
                    continue
                for t in sub.targets:
                    d = dotted(t)
                    if d and d.startswith("self.") and "." not in d[5:]:
                        record(cmap, d[5:], ctor[1], attr_of_self=True)
        elif isinstance(node, ast.Assign):
            ctor = _lock_ctor_call(node.value)
            if ctor is None:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    record(decls.module_locks, t.id, ctor[1],
                           attr_of_self=False)
    return decls


def _lock_node_id(expr: ast.AST, ctx: FileCtx, class_name: str | None,
                  fn_name: str, decls: _LockDecls) -> str | None:
    """Graph node id for a ``with <expr>:`` target when it names a lock.

    Declared locks resolve through the alias map (condition == its
    lock); undeclared lock-ish names still count, scoped to their
    class / function so distinct objects stay distinct nodes."""
    d = dotted(expr)
    if d is None:
        return None
    if d.startswith("self.") and "." not in d[5:]:
        attr = d[5:]
        cname = class_name or "?"
        amap = decls.class_locks.get(cname, {})
        if attr in amap:
            return f"{ctx.relpath}:{cname}.{decls.canonical_attr(cname, attr)}"
        if _LOCKNAME_RE.search(attr):
            return f"{ctx.relpath}:{cname}.{attr}"
        return None
    if "." not in d:
        if d in decls.module_locks:
            return f"{ctx.relpath}:{decls.module_locks[d]}"
        if _LOCKNAME_RE.search(d):
            # local binding / parameter: a per-function lock object
            scope = f"{class_name}." if class_name else ""
            return f"{ctx.relpath}:{scope}{fn_name}:{d}"
        return None
    last = d.rsplit(".", 1)[-1]
    if _LOCKNAME_RE.search(last):
        return f"{ctx.relpath}:{d}"
    return None


def _iter_functions(tree: ast.Module):
    """Yields ``(class_name or None, fn_node)`` for every def in the
    module, including defs nested in functions (class context kept)."""
    def walk(body, class_name):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield class_name, node
                yield from walk(node.body, class_name)
            elif isinstance(node, ast.ClassDef):
                yield from walk(node.body, node.name)
            elif hasattr(node, "body") and isinstance(
                    getattr(node, "body", None), list):
                yield from walk(node.body, class_name)
                for extra in ("orelse", "finalbody"):
                    yield from walk(getattr(node, extra, []) or [],
                                    class_name)
                for h in getattr(node, "handlers", []) or []:
                    yield from walk(h.body, class_name)
    yield from walk(tree.body, None)


# --------------------------------------------------------------------------
# lock-order: interprocedural acquisition graph
# --------------------------------------------------------------------------

# names too generic to resolve across files (get/put/read/... exist on
# dicts, queues and files as well as on lock-holding classes — resolving
# them by name alone would fabricate edges)
_GENERIC_NAMES = {"get", "put", "pop", "load", "save", "read", "write",
                  "close", "open", "stop", "start", "run", "wait", "set",
                  "clear", "stats", "submit", "send", "append", "update",
                  "add", "remove", "join", "next", "items", "keys",
                  "values", "copy", "acquire", "release"}


@dataclass
class _FnRecord:
    ctx: FileCtx
    class_name: str | None
    name: str
    acquires: list = field(default_factory=list)   # (lock_id, lineno)
    # (outer_lock_id, callee_form, callee_name, lineno); callee_form is
    # "self" (self.m()), "bare" (m()) or "any" (x.m() / chained)
    calls_under: list = field(default_factory=list)
    nested: list = field(default_factory=list)     # (outer, inner, lineno)


@dataclass
class LockEdge:
    src: str
    dst: str
    ctx: FileCtx
    line: int
    via: str       # "nested with" | "call to f() -> file:line"


def _scan_fn_locks(ctx: FileCtx, class_name: str | None, fn: ast.AST,
                   decls: _LockDecls) -> _FnRecord:
    rec = _FnRecord(ctx, class_name, fn.name)
    lock_stack: list[str] = []

    def callee_of(call: ast.Call) -> tuple[str, str] | None:
        f = call.func
        if isinstance(f, ast.Name):
            return "bare", f.id
        if isinstance(f, ast.Attribute):
            base = dotted(f.value)
            if base == "self":
                return "self", f.attr
            return "any", f.attr
        return None

    def walk(stmts) -> None:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue   # nested defs get their own record
            if isinstance(s, ast.With):
                acquired = []
                for item in s.items:
                    lock = _lock_node_id(item.context_expr, ctx,
                                         class_name, fn.name, decls)
                    if lock is None:
                        continue
                    rec.acquires.append((lock, s.lineno))
                    if lock_stack and lock_stack[-1] != lock:
                        rec.nested.append((lock_stack[-1], lock, s.lineno))
                    lock_stack.append(lock)
                    acquired.append(lock)
                walk(s.body)
                for _ in acquired:
                    lock_stack.pop()
                continue
            if lock_stack:
                for sub in ast.walk(s):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        break
                    if isinstance(sub, ast.Call):
                        callee = callee_of(sub)
                        if callee is not None:
                            rec.calls_under.append(
                                (lock_stack[-1], callee[0], callee[1],
                                 sub.lineno))
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.stmt):
                    walk([child])
                elif hasattr(child, "body") and isinstance(
                        getattr(child, "body", None), list):
                    walk(child.body)

    walk(fn.body)
    return rec


def build_lock_graph(files: list[FileCtx]) -> list[LockEdge]:
    """Every lock-order edge in the package, with provenance. Direct
    ``with A: with B:`` nesting plus one call level deep (a call under A
    into a resolvable function that acquires B)."""
    records: list[_FnRecord] = []
    by_method: dict[tuple[str, str, str], _FnRecord] = {}
    by_file_fn: dict[tuple[str, str], _FnRecord] = {}
    by_name: dict[str, list[_FnRecord]] = {}
    for ctx in files:
        decls = _collect_lock_decls(ctx)
        for class_name, fn in _iter_functions(ctx.tree):
            rec = _scan_fn_locks(ctx, class_name, fn, decls)
            records.append(rec)
            if class_name:
                by_method[(ctx.relpath, class_name, fn.name)] = rec
            else:
                by_file_fn.setdefault((ctx.relpath, fn.name), rec)
            by_name.setdefault(fn.name, []).append(rec)

    def resolve(rec: _FnRecord, form: str, name: str) -> _FnRecord | None:
        if form == "self" and rec.class_name:
            hit = by_method.get((rec.ctx.relpath, rec.class_name, name))
            if hit is not None:
                return hit
        if form in ("self", "bare"):
            hit = by_file_fn.get((rec.ctx.relpath, name))
            if hit is not None:
                return hit
        if name in _GENERIC_NAMES:
            return None
        # cross-file: only a UNIQUE lock-acquiring definition resolves
        cands = [r for r in by_name.get(name, ()) if r.acquires]
        return cands[0] if len(cands) == 1 else None

    edges: list[LockEdge] = []
    for rec in records:
        for outer, inner, line in rec.nested:
            edges.append(LockEdge(outer, inner, rec.ctx, line,
                                  "nested with"))
        for outer, form, name, line in rec.calls_under:
            callee = resolve(rec, form, name)
            if callee is None or callee is rec:
                continue
            for lock, lline in callee.acquires:
                if lock == outer:
                    continue
                edges.append(LockEdge(
                    outer, lock, rec.ctx, line,
                    f"call to {name}() acquiring it at "
                    f"{callee.ctx.relpath}:{lline}"))
    return edges


def _short(node_id: str) -> str:
    return node_id.split(":", 1)[-1]


def lock_graph_dot(files: list[FileCtx]) -> str:
    """The lock-order graph as DOT (``bst lint --graph lock-order``)."""
    edges = build_lock_graph(files)
    nodes: set[str] = set()
    seen: set[tuple[str, str]] = set()
    lines = ["digraph lock_order {", '  rankdir=LR;',
             '  node [shape=box, fontsize=10];']
    for e in edges:
        nodes.update((e.src, e.dst))
    for n in sorted(nodes):
        lines.append(f'  "{n}" [label="{_short(n)}\\n'
                     f'{n.split(":", 1)[0]}"];')
    for e in edges:
        if (e.src, e.dst) in seen:
            continue
        seen.add((e.src, e.dst))
        lines.append(f'  "{e.src}" -> "{e.dst}" '
                     f'[label="{e.ctx.relpath}:{e.line}"];')
    lines.append("}")
    return "\n".join(lines) + "\n"


def _find_cycles(edges: list[LockEdge]) -> list[list[LockEdge]]:
    """One representative cycle (as its edge path) per strongly
    connected component of size > 1."""
    adj: dict[str, list[LockEdge]] = {}
    for e in edges:
        adj.setdefault(e.src, []).append(e)

    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[set[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        # iterative Tarjan: (node, edge iterator) frames
        work = [(v, iter(adj.get(v, ())))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for e in it:
                w = e.dst
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(adj.get(w, ()))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.add(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(comp)

    for e in edges:
        if e.src not in index:
            strongconnect(e.src)

    cycles: list[list[LockEdge]] = []
    for comp in sccs:
        start = sorted(comp)[0]
        # BFS within the component for the shortest path back to start
        best: list[LockEdge] | None = None
        frontier: list[tuple[str, list[LockEdge]]] = [(start, [])]
        visited = {start}
        while frontier and best is None:
            nxt: list[tuple[str, list[LockEdge]]] = []
            for node, path in frontier:
                for e in adj.get(node, ()):
                    if e.dst not in comp:
                        continue
                    if e.dst == start:
                        best = path + [e]
                        break
                    if e.dst not in visited:
                        visited.add(e.dst)
                        nxt.append((e.dst, path + [e]))
                if best is not None:
                    break
            frontier = nxt
        if best:
            cycles.append(best)
    return cycles


def check_lock_order(files: list[FileCtx]) -> list[Finding]:
    out: list[Finding] = []
    edges = build_lock_graph(files)
    for cycle in _find_cycles(edges):
        path = " -> ".join([_short(e.src) for e in cycle]
                           + [_short(cycle[0].src)])
        prov = "; ".join(f"{_short(e.src)}->{_short(e.dst)} at "
                         f"{e.ctx.relpath}:{e.line} ({e.via})"
                         for e in cycle)
        anchor = cycle[0]
        out.append(anchor.ctx.finding(
            "lock-order", _Loc(anchor.line),
            f"lock-order cycle (potential deadlock): {path} — two "
            f"threads entering at different nodes deadlock. Edges: "
            f"{prov}. Inspect with `bst lint --graph lock-order`"))
    return out


class _Loc:
    """Minimal node stand-in carrying a line number for ctx.finding."""

    def __init__(self, lineno: int):
        self.lineno = lineno


# --------------------------------------------------------------------------
# blocking-under-lock
# --------------------------------------------------------------------------

_SOCK_BLOCKING_ATTRS = {"send", "sendall", "sendto", "sendmsg", "recv",
                        "recv_into", "recvfrom", "recvfrom_into",
                        "recvmsg", "accept", "connect", "connect_ex",
                        "readline"}
_QUEUEISH_RE = re.compile(r"(^|[._])(q|queue|waiter|inbox|outbox)s?$",
                          re.IGNORECASE)
_CONTAINER_RECV_RE = re.compile(r"(^|[._])(ds|dataset|store|container)s?$",
                                re.IGNORECASE)
_CONTAINER_IO_ATTRS = {"read_block", "write_block", "prefetch_box"}
_SLEEP_THRESHOLD_S = 0.1


def _blocking_call_reason(call: ast.Call) -> str | None:
    """Why this call can block indefinitely, or None when it cannot (as
    far as the heuristic can tell)."""
    d = dotted(call.func) or ""
    last = d.rsplit(".", 1)[-1] if d else ""
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        recv = dotted(call.func.value) or ""
        if attr in _SOCK_BLOCKING_ATTRS:
            return f"socket/stream {attr}() can block on the peer"
        if attr in ("get", "put") and _QUEUEISH_RE.search(recv):
            kwnames = {k.arg for k in call.keywords}
            if attr == "get" and call.args:
                return None    # dict.get(key) style — not a queue get
            if not ({"timeout", "block"} & kwnames):
                return (f"queue {attr}() without block=False/timeout "
                        f"blocks until a peer acts")
            return None
        if attr == "block_until_ready":
            return "block_until_ready() waits on the device"
        if attr in _CONTAINER_IO_ATTRS or (
                attr in ("read", "write")
                and _CONTAINER_RECV_RE.search(recv)):
            return (f"container {attr}() is a (possibly remote) IO "
                    f"round trip")
    if d.startswith("subprocess."):
        return f"{d}() blocks on a child process"
    if d in ("jax.device_get", "device_get"):
        return "jax.device_get blocks on the device"
    if d in ("time.sleep", "sleep") and call.args:
        a = call.args[0]
        if (isinstance(a, ast.Constant)
                and isinstance(a.value, (int, float))
                and a.value > _SLEEP_THRESHOLD_S):
            return f"time.sleep({a.value}) parks the lock holder"
    if d in ("socket.create_connection", "create_connection"):
        return "create_connection() blocks on the TCP handshake"
    _ = last
    return None


def check_blocking_under_lock(files: list[FileCtx]) -> list[Finding]:
    out: list[Finding] = []
    for ctx in files:
        decls = _collect_lock_decls(ctx)
        # same-file helpers that contain a direct blocking call, for the
        # one-call-deep expansion (catches send/recv wrapped in module
        # helpers like _send_line / _recv_exact)
        helper_blocks: dict[str, str] = {}
        for class_name, fn in _iter_functions(ctx.tree):
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    reason = _blocking_call_reason(node)
                    if reason is not None:
                        helper_blocks.setdefault(fn.name, reason)
                        break

        def scan_fn(class_name: str | None, fn: ast.AST) -> None:
            lock_stack: list[str] = []

            def flag_calls(s: ast.stmt) -> None:
                for sub in ast.walk(s):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        return
                    if not isinstance(sub, ast.Call):
                        continue
                    reason = _blocking_call_reason(sub)
                    name = None
                    if isinstance(sub.func, ast.Name):
                        name = sub.func.id
                    elif (isinstance(sub.func, ast.Attribute)
                          and dotted(sub.func.value) == "self"):
                        name = sub.func.attr
                    if reason is None and name is not None \
                            and name != fn.name:
                        helper = helper_blocks.get(name)
                        if helper is not None:
                            reason = (f"{name}() does blocking IO "
                                      f"({helper})")
                    if reason is not None:
                        out.append(ctx.finding(
                            "blocking-under-lock", sub,
                            f"{reason} while {_short(lock_stack[-1])} is "
                            f"held — every thread needing the lock "
                            f"stalls behind it; move the call outside "
                            f"the lock"))

            def walk(stmts) -> None:
                for s in stmts:
                    if isinstance(s, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                        continue
                    if isinstance(s, ast.With):
                        acquired = []
                        for item in s.items:
                            lock = _lock_node_id(item.context_expr, ctx,
                                                 class_name, fn.name,
                                                 decls)
                            if lock is not None:
                                lock_stack.append(lock)
                                acquired.append(lock)
                        walk(s.body)
                        for _ in acquired:
                            lock_stack.pop()
                        continue
                    if lock_stack:
                        kids = [c for c in ast.iter_child_nodes(s)
                                if isinstance(c, (ast.stmt, ast.expr))]
                        # flag expressions at THIS level, then recurse
                        # into statement bodies so nested withs are seen
                        for c in kids:
                            if isinstance(c, ast.expr):
                                flag_calls(c)
                        sub_stmts = [c for c in kids
                                     if isinstance(c, ast.stmt)]
                        if sub_stmts:
                            walk(sub_stmts)
                        for child in ast.iter_child_nodes(s):
                            if hasattr(child, "body") and isinstance(
                                    getattr(child, "body", None), list) \
                                    and not isinstance(child, ast.stmt):
                                walk(child.body)
                    else:
                        for child in ast.iter_child_nodes(s):
                            if isinstance(child, ast.stmt):
                                walk([child])
                            elif hasattr(child, "body") and isinstance(
                                    getattr(child, "body", None), list):
                                walk(child.body)

            walk(fn.body)

        for class_name, fn in _iter_functions(ctx.tree):
            scan_fn(class_name, fn)
    return out


# --------------------------------------------------------------------------
# thread-spawn
# --------------------------------------------------------------------------

_SPAWN_EXEMPT_FILE = "utils/threads.py"


def check_thread_spawn(files: list[FileCtx]) -> list[Finding]:
    out: list[Finding] = []
    for ctx in files:
        if ctx.relpath == _SPAWN_EXEMPT_FILE:
            continue
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func) or ""
            last = d.rsplit(".", 1)[-1]
            if last == "Thread" and (d == "Thread"
                                     or d.endswith("threading.Thread")
                                     or d == "threading.Thread"):
                out.append(ctx.finding(
                    "thread-spawn", node,
                    "raw threading.Thread drops config.overrides() "
                    "contextvars and the ambient cancel token — spawn "
                    "via utils.threads.ctx_thread (or justify with a "
                    "suppression: process-lived daemon infrastructure "
                    "must NOT pin one job's context)"))
            elif last == "ThreadPoolExecutor":
                out.append(ctx.finding(
                    "thread-spawn", node,
                    "raw ThreadPoolExecutor workers drop "
                    "config.overrides() contextvars and the cancel "
                    "token — use utils.threads.CtxThreadPool (or "
                    "justify with a suppression)"))
    return out


# --------------------------------------------------------------------------
# cancel-coverage
# --------------------------------------------------------------------------

_CANCEL_SCOPES = ("models/", "parallel/", "dag/", "serve/")
_STOPFLAG_RE = re.compile(r"stop|cancel|closed|done|drain|shutdown",
                          re.IGNORECASE)


def _worker_callables(ctx: FileCtx) -> set[tuple[str | None, str]]:
    """(class or None, fn name) for every callable handed to a thread
    spawn / pool submit in this file: ``Thread(target=X)``,
    ``ctx_thread(X, ...)``, ``pool.submit(X, ...)``."""
    out: set[tuple[str | None, str]] = set()

    def record(expr: ast.AST) -> None:
        d = dotted(expr)
        if not d:
            return
        if d.startswith("self.") and "." not in d[5:]:
            out.add((None, d[5:]))      # method: class resolved later
        elif "." not in d:
            out.add((None, d))

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func) or ""
        last = d.rsplit(".", 1)[-1]
        if last in ("Thread", "Timer"):
            for kw in node.keywords:
                if kw.arg == "target":
                    record(kw.value)
        elif last == "ctx_thread" and node.args:
            record(node.args[0])
        elif last in ("submit", "map") and isinstance(
                node.func, ast.Attribute) and node.args:
            record(node.args[0])
    return out


def _loop_polls_cancellation(loop: ast.While) -> bool:
    for node in ast.walk(loop):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, ast.Call):
            d = dotted(node.func) or ""
            parts = d.split(".")
            attr = parts[-1]
            recv = ".".join(parts[:-1])
            if attr == "check" and ("cancel" in recv or recv.endswith(
                    "_cancel")):
                return True
            if attr in ("cancelled", "is_cancelled"):
                return True
            if attr in ("is_set", "wait") and _STOPFLAG_RE.search(recv):
                return True
            if attr in ("get_nowait", "put_nowait"):
                return True    # bounded drain: ends when the queue does
        if isinstance(node, ast.Attribute) and node.attr and \
                _STOPFLAG_RE.search(node.attr):
            return True        # `if self._stopping: return` style flag
        if isinstance(node, ast.Name) and _STOPFLAG_RE.search(node.id):
            return True
    return False


def check_cancel_coverage(files: list[FileCtx]) -> list[Finding]:
    out: list[Finding] = []
    for ctx in files:
        if not ctx.relpath.startswith(_CANCEL_SCOPES):
            continue
        workers = _worker_callables(ctx)
        if not workers:
            continue
        worker_names = {name for _cls, name in workers}
        for _class_name, fn in _iter_functions(ctx.tree):
            if fn.name not in worker_names:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.While) and isinstance(
                        node.test, ast.Constant) and node.test.value \
                        is True:
                    if not _loop_polls_cancellation(node):
                        out.append(ctx.finding(
                            "cancel-coverage", node,
                            f"unbounded `while True:` in worker "
                            f"callable {fn.name}() never polls "
                            f"cancellation — call utils.cancel.check() "
                            f"(or test a stop flag) in the loop body so "
                            f"job cancel / daemon drain can reach it"))
    return out


# --------------------------------------------------------------------------
# socket-hygiene
# --------------------------------------------------------------------------

_SOCK_HELPER_FNS = {"_shutdown_close", "_close_sock"}
_SOCK_PARAM_RE = re.compile(r"(^|_)(sock|conn)$", re.IGNORECASE)
_SOCK_EXEMPT_PREFIX = "utils/"


def _is_socket_ctor(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    d = dotted(value.func) or ""
    if d in ("socket.socket", "socket.create_connection",
             "create_connection", "socket.socketpair"):
        return True
    return isinstance(value.func, ast.Attribute) and \
        value.func.attr == "accept"


def _param_is_socket(arg: ast.arg) -> bool:
    ann = getattr(arg, "annotation", None)
    if ann is not None:
        ad = dotted(ann)
        if ad and ad.rsplit(".", 1)[-1] == "socket":
            return True
    return bool(_SOCK_PARAM_RE.search(arg.arg))


def check_socket_hygiene(files: list[FileCtx]) -> list[Finding]:
    out: list[Finding] = []
    for ctx in files:
        if ctx.relpath.startswith(_SOCK_EXEMPT_PREFIX):
            continue
        for _class_name, fn in _iter_functions(ctx.tree):
            if fn.name in _SOCK_HELPER_FNS:
                continue
            socks: set[str] = set()
            args = getattr(fn, "args", None)
            if args is not None:
                for a in (*args.posonlyargs, *args.args,
                          *args.kwonlyargs):
                    if a.arg != "self" and _param_is_socket(a):
                        socks.add(a.arg)
            server: set[str] = set()
            shut: set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and node is not fn:
                    continue
                if isinstance(node, ast.Assign) and _is_socket_ctor(
                        node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            socks.add(t.id)
                        elif isinstance(t, ast.Tuple) and t.elts and \
                                isinstance(t.elts[0], ast.Name):
                            socks.add(t.elts[0].id)   # conn, addr = accept()
                if isinstance(node, ast.Call):
                    f = node.func
                    if isinstance(f, ast.Attribute) and isinstance(
                            f.value, ast.Name):
                        if f.attr in ("bind", "listen"):
                            server.add(f.value.id)
                        elif f.attr == "shutdown":
                            shut.add(f.value.id)
                    if isinstance(f, ast.Name) and \
                            f.id in _SOCK_HELPER_FNS and node.args and \
                            isinstance(node.args[0], ast.Name):
                        shut.add(node.args[0].id)
                    if isinstance(f, ast.Attribute) and \
                            f.attr in _SOCK_HELPER_FNS and node.args and \
                            isinstance(node.args[0], ast.Name):
                        shut.add(node.args[0].id)
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "close"
                        and isinstance(node.func.value, ast.Name)):
                    continue
                name = node.func.value.id
                if name in socks and name not in server \
                        and name not in shut:
                    out.append(ctx.finding(
                        "socket-hygiene", node,
                        f"{name}.close() without a preceding "
                        f"{name}.shutdown() — io-refs (makefile "
                        f"wrappers) keep the fd alive past a bare "
                        f"close, leaving a phantom half-open "
                        f"connection the peer never notices; use "
                        f"observe.relay._shutdown_close (shutdown "
                        f"SHUT_RDWR, then close)"))
    return out


CONCURRENCY_CHECKS = {
    "lock-order": check_lock_order,
    "blocking-under-lock": check_blocking_under_lock,
    "thread-spawn": check_thread_spawn,
    "cancel-coverage": check_cancel_coverage,
    "socket-hygiene": check_socket_hygiene,
}

ALL_CHECKS.update(CONCURRENCY_CHECKS)

"""Self-hosted AST-based invariant analyzer (``bst lint``).

Machine-checks the conventions the package's correctness rests on —
no hidden host syncs in device hot paths, lock discipline around shared
mutable state, a cycle-free lock-order graph, no blocking calls under a
lock, context-carrying thread spawns, cancellable worker loops, clean
socket teardown, all ``BST_*`` knobs read through the config registry,
every metric name declared once — as a tier-1 test and a CLI tool.
Stdlib ``ast`` only; see :mod:`.checks` and :mod:`.concurrency` for the
check catalogue and :mod:`.linter` for suppressions and the baseline
protocol.
"""

from .checks import ALL_CHECKS, Finding
from .concurrency import build_lock_graph, lock_graph_dot
from .linter import (
    baseline_counts,
    default_baseline_path,
    default_root,
    load_baseline,
    new_findings,
    parse_package,
    run_lint,
    save_baseline,
)

__all__ = [
    "ALL_CHECKS",
    "Finding",
    "baseline_counts",
    "build_lock_graph",
    "default_baseline_path",
    "default_root",
    "load_baseline",
    "lock_graph_dot",
    "new_findings",
    "parse_package",
    "run_lint",
    "save_baseline",
]

"""Self-hosted AST-based invariant analyzer (``bst lint``).

Machine-checks the conventions the package's correctness rests on —
no hidden host syncs in device hot paths, lock discipline around shared
mutable state, all ``BST_*`` knobs read through the config registry,
every metric name declared once — as a tier-1 test and a CLI tool.
Stdlib ``ast`` only; see :mod:`.checks` for the check catalogue and
:mod:`.linter` for suppressions and the baseline protocol.
"""

from .checks import ALL_CHECKS, Finding
from .linter import (
    baseline_counts,
    default_baseline_path,
    default_root,
    load_baseline,
    new_findings,
    run_lint,
    save_baseline,
)

__all__ = [
    "ALL_CHECKS",
    "Finding",
    "baseline_counts",
    "default_baseline_path",
    "default_root",
    "load_baseline",
    "new_findings",
    "run_lint",
    "save_baseline",
]

"""``bst trace-report``: the questions span AGGREGATES cannot answer.

``profiling`` can say `fusion.d2h` totalled 13.8 s; only the timeline can
say whether those seconds hid under `fusion.write`, how long each device
sat idle between dispatches, and which per-block causal chain
(dispatch → kernel → d2h → write) ended the run. This module turns a
flight-recorder trace (``observe/trace.py`` Perfetto JSON, single file or
the ``telemetry-merge`` fold of a pod run) into exactly those numbers:

- per-stage wall-clock decomposed into **compute / d2h / write / idle**
  (union time per category, so N overlapping writes count once);
- **pairwise overlap** seconds + percentages between the categories —
  the direct measurement of "does D2H overlap the writes", the 0.64×
  frontier question (ROADMAP "Known gap");
- per-track (device / writer thread) busy/idle and the largest idle
  gaps — the scheduler-shaped holes items 2–3 must fill;
- the **critical path**: per-item causal chains reassembled from the
  events' work-item identity, the chain that finishes last, and its
  top-k blocking segments by duration.

Everything here is pure computation over the parsed JSON — the CLI shim
lives in ``cli/telemetry_tools.py``.
"""

from __future__ import annotations

import glob
import json
import os


def _category(name: str) -> str:
    if name.endswith(".d2h"):
        return "d2h"
    if name.endswith(".write"):
        return "write"
    if name.endswith(".kernel") or name.endswith(".kernel_sync") \
            or name.endswith(".dispatch"):
        return "compute"
    if name.endswith(".prefetch") or name.endswith(".extract"):
        return "read"
    if name.endswith(".h2d_tiles"):
        return "h2d"
    return "other"


def _group(name: str, args: dict) -> str:
    """Report group for one interval: the span-name prefix, except the
    generic layers (mesh loop, retry wrapper, pair scheduler) which
    borrow their stage label's first token — ``mesh.d2h`` inside a
    ``"fusion batch …"`` stage belongs to the fusion story."""
    head = name.split(".")[0]
    if head in ("mesh", "retry", "pair", "barrier"):
        stage = str(args.get("stage") or "")
        tok = stage.split(" ")[0].split(".")[0].split("-")[0]
        return tok or head
    return head


def load_events(path: str) -> tuple[list[dict], dict]:
    """Flat event list + metadata from a trace file, a telemetry dir
    (preferring ``merged-trace.json``, else every ``trace-*.json``), or a
    merged trace."""
    paths: list[str]
    if os.path.isdir(path):
        merged = os.path.join(path, "merged-trace.json")
        per_proc = sorted(glob.glob(os.path.join(path, "trace-*-of-*.json")))
        # a merged fold is preferred — unless a per-process trace is NEWER
        # (the dir was reused for another run after the last telemetry-merge),
        # in which case the stale merge would silently report the old run
        if os.path.exists(merged) and not any(
                os.path.getmtime(p) > os.path.getmtime(merged)
                for p in per_proc):
            paths = [merged]
        else:
            paths = per_proc
        if not paths:
            raise FileNotFoundError(
                f"no merged-trace.json or trace-*.json under {path}")
    else:
        paths = [path]
    events: list[dict] = []
    meta: dict = {"files": [os.path.basename(p) for p in paths],
                  "recorded": 0, "dropped": 0,
                  "unaligned_processes": []}
    for p in paths:
        with open(p, encoding="utf-8") as f:
            doc = json.load(f)
        b = doc.get("bst", {})
        meta["recorded"] += int(b.get("recorded") or 0)
        meta["dropped"] += int(b.get("dropped") or 0)
        meta["unaligned_processes"] += b.get("unaligned_processes") or []
        events.extend(doc.get("traceEvents", ()))
    # concatenating several PER-PROCESS traces puts unaligned host clocks
    # on one timeline — every cross-process number (overlap, idle,
    # critical path) is then skewed; the CLI warns and points at
    # telemetry-merge, which barrier-aligns the clocks first
    meta["unmerged"] = len(paths) > 1
    return events, meta


def build_intervals(events: list[dict]) -> tuple[list[dict], dict]:
    """Pair B/E events into intervals (seconds); returns (intervals,
    track_names). Pairing is a per-(pid, tid, name) LIFO stack — Chrome
    ``B``/``E`` semantics; unmatched begins (ring overflow tore their
    end off) are dropped rather than invented."""
    stacks: dict[tuple, list] = {}
    track_names: dict[tuple, str] = {}
    out: list[dict] = []
    for ev in events:
        ph = ev.get("ph")
        if ph == "M":
            if ev.get("name") == "thread_name":
                track_names[(ev.get("pid", 0), ev.get("tid", 0))] = \
                    (ev.get("args") or {}).get("name", "")
            continue
        if ph not in ("B", "E", "X"):
            continue
        key = (ev.get("pid", 0), ev.get("tid", 0), ev.get("name"))
        ts = float(ev.get("ts", 0.0)) / 1e6
        if ph == "X":
            out.append({"name": ev.get("name"), "start": ts,
                        "end": ts + float(ev.get("dur", 0.0)) / 1e6,
                        "pid": key[0], "tid": key[1],
                        "args": ev.get("args") or {}})
        elif ph == "B":
            stacks.setdefault(key, []).append((ts, ev.get("args") or {}))
        else:
            stack = stacks.get(key)
            if stack:
                t0, args = stack.pop()
                if ts < t0:
                    continue   # wall clock stepped backwards (NTP/suspend)
                               # mid-span: drop rather than go negative
                merged = {**args, **(ev.get("args") or {})}
                out.append({"name": key[2], "start": t0, "end": ts,
                            "pid": key[0], "tid": key[1], "args": merged})
    out.sort(key=lambda iv: (iv["start"], iv["end"]))
    return out, track_names


def _union(ivs: list[dict]) -> list[tuple[float, float]]:
    if not ivs:
        return []
    spans = sorted((iv["start"], iv["end"]) for iv in ivs)
    merged = [list(spans[0])]
    for s, e in spans[1:]:
        if s <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], e)
        else:
            merged.append([s, e])
    return [(s, e) for s, e in merged]


def _total(union: list[tuple[float, float]]) -> float:
    return sum(e - s for s, e in union)


def _intersect(a: list[tuple[float, float]],
               b: list[tuple[float, float]]) -> float:
    i = j = 0
    out = 0.0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            out += e - s
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return out


def _pct(x: float, denom: float) -> float:
    return round(100.0 * x / denom, 1) if denom > 0 else 0.0


def analyze(path: str, top: int = 5) -> dict:
    """The decomposition as DATA: load a trace (file or telemetry dir)
    and return the :func:`build_report` dict — the machine face of
    ``bst trace-report`` that `bst tune advise` (and any script) consumes
    without parsing the rendered table. The report additionally carries
    the resolved source ``files``."""
    events, meta = load_events(path)
    rep = build_report(events, meta, top=top)
    rep["files"] = meta.get("files", [])
    return rep


def build_report(events: list[dict], meta: dict | None = None,
                 top: int = 5) -> dict:
    intervals, track_names = build_intervals(events)
    rep: dict = {"events": len([e for e in events
                                if e.get("ph") in ("B", "E", "X", "i")]),
                 "intervals": len(intervals),
                 "recorded": (meta or {}).get("recorded", 0),
                 "dropped": (meta or {}).get("dropped", 0),
                 "stages": {}, "tracks": [],
                 "critical_path": None, "top_blocking": []}
    if not intervals:
        return rep
    t0 = min(iv["start"] for iv in intervals)
    t1 = max(iv["end"] for iv in intervals)
    rep["wall_s"] = round(t1 - t0, 6)

    # -- per-stage category decomposition + pairwise overlap ---------------
    by_group: dict[str, list[dict]] = {}
    for iv in intervals:
        by_group.setdefault(_group(iv["name"], iv["args"]), []).append(iv)
    for group, ivs in sorted(by_group.items()):
        g0 = min(iv["start"] for iv in ivs)
        g1 = max(iv["end"] for iv in ivs)
        wall = g1 - g0
        unions = {}
        for cat in ("compute", "d2h", "write", "read", "h2d", "other"):
            unions[cat] = _union([iv for iv in ivs
                                  if _category(iv["name"]) == cat])
        busy = _union(ivs)
        entry = {
            "wall_s": round(wall, 6),
            "idle_s": round(max(0.0, wall - _total(busy)), 6),
            "idle_pct": _pct(max(0.0, wall - _total(busy)), wall),
            "overlap": {},
        }
        for cat in ("compute", "d2h", "write", "read", "h2d"):
            tot = _total(unions[cat])
            if tot:
                entry[f"{cat}_s"] = round(tot, 6)
                entry[f"{cat}_pct"] = _pct(tot, wall)
        for a, b in (("d2h", "write"), ("compute", "d2h"),
                     ("compute", "write")):
            ta, tb = _total(unions[a]), _total(unions[b])
            if ta and tb:
                ov = _intersect(unions[a], unions[b])
                entry["overlap"][f"{a}_{b}"] = {
                    "seconds": round(ov, 6),
                    f"pct_of_{a}": _pct(ov, ta),
                    f"pct_of_{b}": _pct(ov, tb),
                }
        rep["stages"][group] = entry

    # -- per-track (device / thread) busy, idle, largest gaps --------------
    by_track: dict[tuple, list[dict]] = {}
    for iv in intervals:
        by_track.setdefault((iv["pid"], iv["tid"]), []).append(iv)
    for (pid, tid), ivs in sorted(by_track.items()):
        busy = _union(ivs)
        first, last = busy[0][0], busy[-1][1]
        span = last - first
        gaps = [(busy[i + 1][0] - busy[i][1], busy[i][1])
                for i in range(len(busy) - 1)]
        gaps.sort(reverse=True)
        rep["tracks"].append({
            "pid": pid, "tid": tid,
            "name": track_names.get((pid, tid)) or f"tid {tid}",
            "busy_s": round(_total(busy), 6),
            "span_s": round(span, 6),
            "util_pct": _pct(_total(busy), span),
            "largest_gaps": [{"seconds": round(g, 6),
                              "at_s": round(at - t0, 6)}
                             for g, at in gaps[:3] if g > 0],
        })

    # -- critical path over per-item causal chains -------------------------
    chains: dict[tuple, list[dict]] = {}
    for iv in intervals:
        item = iv["args"].get("item")
        if item is None or iv["name"] == "retry.attempt":
            continue   # the attempt wrapper CONTAINS the chain segments
        key = (_group(iv["name"], iv["args"]), json.dumps(item))
        chains.setdefault(key, []).append(iv)
    if chains:
        crit_key = max(chains, key=lambda k: max(iv["end"]
                                                 for iv in chains[k]))
        segs = sorted(chains[crit_key], key=lambda iv: iv["start"])
        path = []
        prev_end = None
        for iv in segs:
            if prev_end is not None and iv["start"] - prev_end > 1e-6:
                path.append({"name": "(wait)", "start_s":
                             round(prev_end - t0, 6),
                             "seconds": round(iv["start"] - prev_end, 6)})
            path.append({"name": iv["name"],
                         "start_s": round(iv["start"] - t0, 6),
                         "seconds": round(iv["end"] - iv["start"], 6)})
            prev_end = iv["end"] if prev_end is None \
                else max(prev_end, iv["end"])
        rep["critical_path"] = {
            "stage": crit_key[0],
            "item": json.loads(crit_key[1]),
            "total_s": round(max(iv["end"] for iv in segs)
                             - segs[0]["start"], 6),
            "ends_at_s": round(max(iv["end"] for iv in segs) - t0, 6),
            "segments": path,
        }
        rep["top_blocking"] = sorted(
            path, key=lambda s: -s["seconds"])[:max(1, top)]
    return rep


def render_report(rep: dict) -> str:
    lines = []
    lines.append(
        f"trace: {rep.get('wall_s', 0.0):.3f}s wall, "
        f"{rep['intervals']} interval(s) from {rep['events']} event(s)"
        + (f", {rep['dropped']} DROPPED by ring overflow"
           if rep.get("dropped") else ""))
    for group, e in rep["stages"].items():
        parts = []
        for cat, label in (("compute", "compute"), ("d2h", "d2h"),
                           ("write", "write"), ("read", "read"),
                           ("h2d", "h2d")):
            if f"{cat}_s" in e:
                parts.append(f"{label} {e[f'{cat}_s']:.3f}s "
                             f"({e[f'{cat}_pct']:.0f}%)")
        parts.append(f"idle {e['idle_s']:.3f}s ({e['idle_pct']:.0f}%)")
        lines.append(f"[{group}] wall {e['wall_s']:.3f}s: "
                     + " | ".join(parts))
        for pair, ov in e["overlap"].items():
            a, b = pair.split("_", 1)
            pa = ov.get(f"pct_of_{a}", 0.0)
            pb = ov.get(f"pct_of_{b}", 0.0)
            lines.append(f"  overlap {a}<->{b}: {ov['seconds']:.3f}s "
                         f"({pa:.0f}% of {a}, {pb:.0f}% of {b})")
    if rep["tracks"]:
        lines.append("tracks:")
        for t in rep["tracks"]:
            gaps = ", ".join(f"{g['seconds']:.3f}s @{g['at_s']:.3f}s"
                             for g in t["largest_gaps"]) or "none"
            lines.append(f"  p{t['pid']} {t['name']}: busy {t['busy_s']:.3f}s"
                         f" ({t['util_pct']:.0f}% of its {t['span_s']:.3f}s"
                         f" span), largest gaps: {gaps}")
    cp = rep.get("critical_path")
    if cp:
        lines.append(f"critical path [{cp['stage']} item {cp['item']}]: "
                     f"{cp['total_s']:.3f}s, ends at "
                     f"+{cp['ends_at_s']:.3f}s")
        lines.append("  " + " -> ".join(
            f"{s['name']} {s['seconds']:.3f}s" for s in cp["segments"]))
        lines.append("top blocking segments:")
        for i, s in enumerate(rep["top_blocking"], 1):
            lines.append(f"  {i}. {s['name']} {s['seconds']:.3f}s "
                         f"(at +{s['start_s']:.3f}s)")
    return "\n".join(lines)

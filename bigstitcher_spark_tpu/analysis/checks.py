"""The core ``bst lint`` invariant checks (pure stdlib ``ast``).

Each check is a function ``(files: list[FileCtx]) -> list[Finding]`` over
the whole parsed package, so cross-file invariants (the lock-order
graph, the metric-name registry, the config-knob declarations) see every
module at once. All checks are approximations by design — they encode
the conventions the codebase actually follows, and anything cleverer
than the convention earns a ``# bst-lint: off=<check>`` suppression with
the reasoning next to it.

The concurrency-discipline suite (lock-order, blocking-under-lock,
thread-spawn, cancel-coverage, socket-hygiene) lives in
``analysis/concurrency.py`` and registers into ``ALL_CHECKS`` below.

Checks
------
``host-sync``
    In ``ops/`` and ``models/``: flags blocking host conversions
    (``np.asarray``/``np.array``, ``float``/``int``/``bool``, ``.item()``/
    ``.tolist()``, ``if``/``while`` truthiness) applied to values that
    dataflow from ``jnp.``/``lax.``/``jax.device_put`` calls — the hidden
    device round-trips of ADVICE r5 #1. ``jax.device_get`` and
    ``.block_until_ready()`` are the allowlisted drain points: fetches
    must be explicit, so the reader (and the next reviewer) can see every
    sync on the hot path.

``lock-discipline``
    State mutated at least once inside a ``with <lock>:`` block is
    lock-guarded; mutating the same attribute/global outside any lock
    block (outside ``__init__`` and ``*_locked`` helpers, which assume
    the caller holds it) is a finding. Acquisition ORDER is the
    ``lock-order`` check's job (concurrency.py): it builds the whole
    interprocedural graph rather than matching single-file pairs.

``config-registry``
    Bans raw ``os.environ``/``os.getenv`` access to ``BST_*`` names
    anywhere outside ``config.py``, and checks every name passed to
    ``config.get*()`` is declared in the registry.

``env-mutation``
    Bans MUTATING the process environment for ``BST_*`` names anywhere in
    the package, ``config.py`` included (assignment, ``del``,
    ``setdefault``/``pop``/``update``, ``os.putenv``). One process now
    hosts many jobs (``bst serve``): an env write from one job's code path
    leaks into every concurrent job and the daemon itself. Per-job
    configuration goes through ``config.overrides()`` — a contextvars
    layer the worker threads inherit — never the shared environment.

``metric-name``
    Every ``bst_*`` string literal in the package must be declared in
    ``observe/metric_names.py`` (a typo'd counter otherwise reports zero
    forever), metric constructors must be called with literal names, and
    the registry itself must declare each name exactly once.

``span-name``
    The trace/span twin of ``metric-name``: every name passed to
    ``profiling.span`` / ``trace.span`` / ``trace.instant`` must be a
    literal declared once in ``observe/metric_names.py``'s ``SPANS``
    table. Dynamic span-name construction is banned outright — a
    constructed name fractures both the span aggregates and the
    flight-recorder timeline into unmergeable series; dynamic identity
    (device, block offset, pair id, bytes) belongs in the attribution
    kwargs.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Finding:
    check: str
    path: str          # posix relpath from the scanned root
    line: int
    message: str
    snippet: str       # stripped source line — the stable baseline key

    @property
    def key(self) -> str:
        return f"{self.check}|{self.path}|{self.snippet}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.check}] {self.message}"


@dataclass
class FileCtx:
    relpath: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)

    def snippet(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, check: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(check, self.relpath, line, message, self.snippet(line))


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains; ``__import__("os").x`` resolves
    the base to ``os`` (the inline-import idiom the analyzer must see
    through, or the ban it enforces has a one-call escape hatch)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id == "__import__" and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)):
        return node.args[0].value
    return None


# --------------------------------------------------------------------------
# host-sync
# --------------------------------------------------------------------------

_TAINT_PREFIXES = ("jnp.", "lax.", "jax.numpy.", "jax.lax.")
_TAINT_EXACT = {"jax.device_put"}
_DRAIN_EXACT = {"jax.device_get", "device_get", "profiling.device_sync"}
# reading these never leaves the host / never forces a device sync
_NEUTRAL_ATTRS = {"shape", "dtype", "ndim", "size", "nbytes", "itemsize",
                  "sharding", "device", "devices", "weak_type", "aval"}
_NP_SINKS = {"np.asarray", "np.array", "np.ascontiguousarray",
             "numpy.asarray", "numpy.array", "numpy.ascontiguousarray"}
_BUILTIN_SINKS = {"float", "int", "bool"}
_METHOD_SINKS = {"item", "tolist"}
_HOST_SYNC_SCOPES = ("ops/", "models/")


class _TaintEnv:
    def __init__(self, ops_aliases: frozenset[str] = frozenset(),
                 ops_fns: frozenset[str] = frozenset()):
        self.tainted: set[str] = set()
        # names bound to ops kernel modules (``from ..ops import fusion as
        # F``) and functions imported straight from them: the kernel layer
        # returns DEVICE arrays, so its results are taint sources — the
        # exact provenance of the ADVICE r5 blocking-fetch bug
        self.ops_aliases = ops_aliases
        self.ops_fns = ops_fns

    def mark(self, name: str, on: bool) -> None:
        (self.tainted.add if on else self.tainted.discard)(name)


def _ops_imports(ctx: FileCtx) -> tuple[frozenset[str], frozenset[str]]:
    """(module aliases, directly-imported function names) that resolve into
    the ops kernel package, from this file's import statements."""
    aliases: set[str] = set()
    fns: set[str] = set()
    in_ops = ctx.relpath.startswith("ops/")
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ImportFrom):
            continue
        mod = node.module or ""
        if mod == "ops" or mod.endswith(".ops"):
            for a in node.names:           # from ..ops import fusion as F
                aliases.add(a.asname or a.name)
        elif ("ops." in mod or mod.startswith("ops")
              or (in_ops and node.level == 1 and mod)):
            # from ..ops.fusion import fuse_block / ops-internal siblings
            for a in node.names:
                fns.add(a.asname or a.name)
    return frozenset(aliases), frozenset(fns)


def _expr_tainted(e: ast.AST, env: _TaintEnv) -> bool:
    if isinstance(e, ast.Name):
        return e.id in env.tainted
    if isinstance(e, ast.Call):
        d = dotted(e.func)
        if d in _DRAIN_EXACT:
            return False
        if isinstance(e.func, ast.Attribute):
            if e.func.attr == "block_until_ready":
                return False
            # method on a device value returns a device value (.astype,
            # .reshape, .sum, ...) — neutral attrs are handled below
            if _expr_tainted(e.func.value, env):
                return True
        if d and (d.startswith(_TAINT_PREFIXES) or d in _TAINT_EXACT):
            return True
        if d and d.split(".", 1)[0] in env.ops_aliases:
            return True        # F.fuse_block_shift(...) and friends
        if isinstance(e.func, ast.Name) and (e.func.id in env.ops_fns
                                             or e.func.id in env.tainted):
            # directly-imported kernel fn, or calling a callable a kernel
            # factory returned (fuser = F.make_...(); fuser(...))
            return True
        return False
    if isinstance(e, ast.Attribute):
        if e.attr in _NEUTRAL_ATTRS:
            return False
        return _expr_tainted(e.value, env)
    if isinstance(e, ast.Subscript):
        return _expr_tainted(e.value, env)
    if isinstance(e, ast.BinOp):
        return _expr_tainted(e.left, env) or _expr_tainted(e.right, env)
    if isinstance(e, ast.UnaryOp):
        return _expr_tainted(e.operand, env)
    if isinstance(e, ast.Compare):
        # identity tests (`x is None`) never touch device values — they
        # compare references on the host
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
            return False
        return (_expr_tainted(e.left, env)
                or any(_expr_tainted(c, env) for c in e.comparators))
    if isinstance(e, ast.BoolOp):
        return any(_expr_tainted(v, env) for v in e.values)
    if isinstance(e, ast.IfExp):
        return _expr_tainted(e.body, env) or _expr_tainted(e.orelse, env)
    if isinstance(e, (ast.Tuple, ast.List)):
        return any(_expr_tainted(v, env) for v in e.elts)
    if isinstance(e, ast.Starred):
        return _expr_tainted(e.value, env)
    if isinstance(e, ast.NamedExpr):
        return _expr_tainted(e.value, env)
    return False


def _sink_findings(e: ast.AST, env: _TaintEnv, ctx: FileCtx,
                   out: list[Finding]) -> None:
    """Detect conversion sinks in one expression tree (current env)."""
    for node in ast.walk(e):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        d = dotted(node.func)
        arg0 = node.args[0]
        if d in _NP_SINKS and _expr_tainted(arg0, env):
            out.append(ctx.finding(
                "host-sync", node,
                f"blocking host fetch: {d}() on a value that dataflows "
                f"from a jax call — fetch via jax.device_get at an "
                f"explicit drain point"))
        elif (isinstance(node.func, ast.Name)
              and node.func.id in _BUILTIN_SINKS
              and _expr_tainted(arg0, env)):
            out.append(ctx.finding(
                "host-sync", node,
                f"blocking host fetch: {node.func.id}() on a device "
                f"value — jax.device_get first (or keep it on device)"))
    for node in ast.walk(e):
        if (isinstance(node, ast.Call) and not node.args
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METHOD_SINKS
                and _expr_tainted(node.func.value, env)):
            out.append(ctx.finding(
                "host-sync", node,
                f".{node.func.attr}() on a device value blocks on the "
                f"device — jax.device_get at an explicit drain point"))


def _assign_taint(target: ast.AST, value_tainted: bool,
                  env: _TaintEnv) -> None:
    if isinstance(target, ast.Name):
        env.mark(target.id, value_tainted)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for t in target.elts:
            _assign_taint(t, value_tainted, env)
    elif isinstance(target, ast.Starred):
        _assign_taint(target.value, value_tainted, env)
    # attribute/subscript targets: no name-level tracking


def _walk_function(fn: ast.AST, ctx: FileCtx, out: list[Finding],
                   imports: tuple[frozenset, frozenset]) -> None:
    env = _TaintEnv(*imports)

    def stmt(s: ast.stmt) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # fresh env for nested defs
            _walk_function(s, ctx, out, (env.ops_aliases, env.ops_fns))
            return
        if isinstance(s, ast.Assign):
            _sink_findings(s.value, env, ctx, out)
            tainted = _expr_tainted(s.value, env)
            if (len(s.targets) == 1 and isinstance(s.targets[0], ast.Tuple)
                    and isinstance(s.value, ast.Tuple)
                    and len(s.targets[0].elts) == len(s.value.elts)):
                for t, v in zip(s.targets[0].elts, s.value.elts):
                    _assign_taint(t, _expr_tainted(v, env), env)
            else:
                for t in s.targets:
                    _assign_taint(t, tainted, env)
            return
        if isinstance(s, ast.AnnAssign) and s.value is not None:
            _sink_findings(s.value, env, ctx, out)
            _assign_taint(s.target, _expr_tainted(s.value, env), env)
            return
        if isinstance(s, ast.AugAssign):
            _sink_findings(s.value, env, ctx, out)
            if isinstance(s.target, ast.Name):
                env.mark(s.target.id,
                         s.target.id in env.tainted
                         or _expr_tainted(s.value, env))
            return
        if isinstance(s, ast.Return) and s.value is not None:
            _sink_findings(s.value, env, ctx, out)
            return
        if isinstance(s, ast.Expr):
            _sink_findings(s.value, env, ctx, out)
            return
        if isinstance(s, (ast.If, ast.While)):
            _sink_findings(s.test, env, ctx, out)
            if _expr_tainted(s.test, env):
                out.append(ctx.finding(
                    "host-sync", s.test,
                    "implicit host sync: truthiness of a device value — "
                    "jax.device_get (or bool(jax.device_get(...))) at an "
                    "explicit drain point"))
            for b in (*s.body, *s.orelse):
                stmt(b)
            return
        if isinstance(s, ast.Assert):
            _sink_findings(s.test, env, ctx, out)
            if _expr_tainted(s.test, env):
                out.append(ctx.finding(
                    "host-sync", s.test,
                    "implicit host sync: assert on a device value"))
            return
        if isinstance(s, ast.For):
            _sink_findings(s.iter, env, ctx, out)
            _assign_taint(s.target, _expr_tainted(s.iter, env), env)
            for b in (*s.body, *s.orelse):
                stmt(b)
            return
        if isinstance(s, ast.With):
            for item in s.items:
                _sink_findings(item.context_expr, env, ctx, out)
                if item.optional_vars is not None:
                    _assign_taint(item.optional_vars,
                                  _expr_tainted(item.context_expr, env), env)
            for b in s.body:
                stmt(b)
            return
        if isinstance(s, ast.Try):
            for b in (*s.body, *[h for hh in s.handlers for h in hh.body],
                      *s.orelse, *s.finalbody):
                stmt(b)
            return
        # other statements: still scan contained expressions for sinks
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.expr):
                _sink_findings(child, env, ctx, out)

    for s in fn.body:
        stmt(s)


def check_host_sync(files: list[FileCtx]) -> list[Finding]:
    out: list[Finding] = []
    for ctx in files:
        if not ctx.relpath.startswith(_HOST_SYNC_SCOPES):
            continue
        imports = _ops_imports(ctx)
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _walk_function(node, ctx, out, imports)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        _walk_function(sub, ctx, out, imports)
    return out


# --------------------------------------------------------------------------
# lock-discipline
# --------------------------------------------------------------------------

_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
             "pop", "popitem", "remove", "discard", "clear", "move_to_end",
             "appendleft", "popleft"}
_LOCK_RE = re.compile(r"lock", re.IGNORECASE)
_EXEMPT_FNS = {"__init__", "__new__", "__post_init__"}


def _is_lock_expr(e: ast.AST) -> str | None:
    """The lock's dotted text when ``e`` names a lock (last path component
    contains 'lock'), else None."""
    d = dotted(e)
    if d and _LOCK_RE.search(d.rsplit(".", 1)[-1]):
        return d
    return None


def _mutation_base(node: ast.AST) -> ast.AST | None:
    """The object being mutated: ``self.x[...] = v`` -> self.x,
    ``x.append(v)`` -> x. Returns the base expression node."""
    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            base = t
            while isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, (ast.Attribute, ast.Name)):
                # plain rebinding of a local name is not shared-state
                # mutation; subscript/attribute writes are
                if isinstance(t, ast.Subscript) or isinstance(
                        base, ast.Attribute):
                    return base
                if isinstance(base, ast.Name):
                    return base    # caller filters to module globals
    if isinstance(node, ast.Delete):
        for t in node.targets:
            base = t
            while isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, (ast.Attribute, ast.Name)):
                return base
    if (isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)
            and isinstance(node.value.func, ast.Attribute)
            and node.value.func.attr in _MUTATORS):
        return node.value.func.value
    return None


def _target_key(base: ast.AST, class_name: str | None,
                module_globals: set[str]) -> str | None:
    d = dotted(base)
    if d is None:
        return None
    if d.startswith("self."):
        return f"{class_name or ''}:{d}" if class_name else None
    root = d.split(".", 1)[0]
    if root in module_globals:
        return f"<module>:{d}"
    return None


@dataclass
class _MutSite:
    key: str
    node: ast.AST
    in_lock: bool
    fn_name: str


def _module_globals(tree: ast.Module) -> set[str]:
    names: set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name):
            names.add(node.target.id)
    return names


def check_lock_discipline(files: list[FileCtx]) -> list[Finding]:
    out: list[Finding] = []
    for ctx in files:
        mglobals = _module_globals(ctx.tree)
        sites: list[_MutSite] = []

        def scan_fn(fn, class_name: str | None) -> None:
            exempt = (fn.name in _EXEMPT_FNS
                      or fn.name.endswith("_locked"))
            lock_stack: list[str] = []

            def walk(stmts) -> None:
                for s in stmts:
                    if isinstance(s, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                        scan_fn(s, class_name)
                        continue
                    if isinstance(s, ast.With):
                        lock_texts = [t for t in
                                      (_is_lock_expr(i.context_expr)
                                       for i in s.items) if t]
                        lock_stack.extend(lock_texts)
                        walk(s.body)
                        for _ in lock_texts:
                            lock_stack.pop()
                        continue
                    base = _mutation_base(s)
                    if base is not None and not exempt:
                        key = _target_key(base, class_name, mglobals)
                        if key is not None:
                            sites.append(_MutSite(
                                key, s, bool(lock_stack), fn.name))
                    for child in ast.iter_child_nodes(s):
                        if isinstance(child, ast.stmt):
                            walk([child])
                        elif hasattr(child, "body") and isinstance(
                                getattr(child, "body", None), list):
                            walk(child.body)
                    # bodies of If/For/While/Try reached via iter_child
                    # statements above

            walk(fn.body)

        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan_fn(node, None)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        scan_fn(sub, node.name)

        guarded: dict[str, _MutSite] = {}
        for site in sites:
            if site.in_lock and site.key not in guarded:
                guarded[site.key] = site
        for site in sites:
            if not site.in_lock and site.key in guarded:
                g = guarded[site.key]
                name = site.key.split(":", 1)[1]
                out.append(ctx.finding(
                    "lock-discipline", site.node,
                    f"{name} is mutated here without the lock that guards "
                    f"it in {g.fn_name}() (line {g.node.lineno}); hold the "
                    f"lock or rename the helper *_locked"))
    return out


# --------------------------------------------------------------------------
# config-registry
# --------------------------------------------------------------------------

_ENV_GETTERS = {"os.environ.get", "environ.get", "os.getenv", "getenv",
                "os.environ.setdefault", "os.environ.pop",
                "environ.setdefault", "environ.pop"}
_ENV_SUBSCRIPTS = {"os.environ", "environ"}
_CONFIG_GETTERS = {"config.get", "config.get_bool", "config.get_int",
                   "config.get_bytes", "config.get_str", "config.get_float",
                   "config.raw_value", "config.source"}
_CONFIG_FILE = "config.py"


def _declared_knobs(files: list[FileCtx]) -> set[str] | None:
    """Knob names declared via ``_knob("NAME", ...)`` in config.py, or
    None when the scanned tree has no config module (fixture runs)."""
    for ctx in files:
        if ctx.relpath == _CONFIG_FILE:
            names: set[str] = set()
            for node in ast.walk(ctx.tree):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "_knob" and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    names.add(node.args[0].value)
            return names
    return None


def check_config_registry(files: list[FileCtx]) -> list[Finding]:
    out: list[Finding] = []
    declared = _declared_knobs(files)
    if declared is None:
        try:
            from .. import config as _config

            declared = set(_config.KNOBS)
        except Exception:
            declared = set()
    for ctx in files:
        if ctx.relpath == _CONFIG_FILE:
            continue
        for node in ast.walk(ctx.tree):
            key = None
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d in _ENV_GETTERS and node.args and isinstance(
                        node.args[0], ast.Constant):
                    key = node.args[0].value
                elif (d in _CONFIG_GETTERS and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    if node.args[0].value not in declared:
                        out.append(ctx.finding(
                            "config-registry", node,
                            f"config knob {node.args[0].value!r} is not "
                            f"declared in config.py"))
                    continue
            elif isinstance(node, ast.Subscript):
                d = dotted(node.value)
                if d in _ENV_SUBSCRIPTS and isinstance(
                        node.slice, ast.Constant):
                    key = node.slice.value
            if isinstance(key, str) and key.startswith("BST_"):
                out.append(ctx.finding(
                    "config-registry", node,
                    f"raw environment access to {key} — read it through "
                    f"bigstitcher_spark_tpu.config (call-time, typed, "
                    f"documented)"))
    return out


# --------------------------------------------------------------------------
# env-mutation
# --------------------------------------------------------------------------

_ENV_MUTATORS = {"os.environ.setdefault", "environ.setdefault",
                 "os.environ.pop", "environ.pop",
                 "os.environ.update", "environ.update",
                 "os.putenv", "putenv"}


def _bst_const(node: ast.AST) -> str | None:
    if (isinstance(node, ast.Constant) and isinstance(node.value, str)
            and node.value.startswith("BST_")):
        return node.value
    return None


def check_env_mutation(files: list[FileCtx]) -> list[Finding]:
    """Flag every write to a ``BST_*`` process-environment name. Unlike
    config-registry (read hygiene, config.py exempt) this check has no
    exempt file: nothing in the package may mutate the shared env — the
    override layer (config.overrides) is the per-job mechanism."""
    out: list[Finding] = []
    msg = ("mutating the {name} process environment leaks across daemon "
           "jobs — use config.overrides() for per-job values")
    for ctx in files:
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                                 ast.Delete)):
                targets = (node.targets if isinstance(node, (ast.Assign,
                                                             ast.Delete))
                           else [node.target])
                for t in targets:
                    if (isinstance(t, ast.Subscript)
                            and dotted(t.value) in _ENV_SUBSCRIPTS):
                        name = _bst_const(t.slice)
                        if name:
                            out.append(ctx.finding(
                                "env-mutation", node,
                                msg.format(name=name)))
            elif isinstance(node, ast.Call):
                d = dotted(node.func)
                if d not in _ENV_MUTATORS or not node.args:
                    continue
                # environ.update takes a dict of names; the others take
                # the name first. setdefault/update/putenv WRITE;
                # environ.pop only reads-and-removes, but removal is
                # mutation too
                if (isinstance(node.args[0], ast.Dict)
                        and any(_bst_const(k) for k in node.args[0].keys)):
                    out.append(ctx.finding(
                        "env-mutation", node, msg.format(name="BST_*")))
                else:
                    name = _bst_const(node.args[0])
                    if name:
                        out.append(ctx.finding(
                            "env-mutation", node, msg.format(name=name)))
    return out


# --------------------------------------------------------------------------
# metric-name
# --------------------------------------------------------------------------

_METRIC_RE = re.compile(r"^bst_[a-z0-9]+(?:_[a-z0-9]+)*$")
_METRIC_REGISTRY_FILE = "observe/metric_names.py"
_METRIC_IMPL_FILE = "observe/metrics.py"
_METRIC_CTORS = {"counter", "gauge", "histogram"}


def _registry_names(files: list[FileCtx]) -> tuple[set[str], list[Finding]]:
    for ctx in files:
        if ctx.relpath == _METRIC_REGISTRY_FILE:
            names: set[str] = set()
            dupes: list[Finding] = []
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Dict):
                    for k in node.keys:
                        if isinstance(k, ast.Constant) and isinstance(
                                k.value, str):
                            if k.value in names:
                                dupes.append(ctx.finding(
                                    "metric-name", k,
                                    f"metric {k.value!r} declared more "
                                    f"than once in the registry"))
                            names.add(k.value)
            return names, dupes
    try:
        from ..observe import metric_names as _mn

        return set(_mn.METRICS), []
    except Exception:
        return set(), []


def check_metric_names(files: list[FileCtx]) -> list[Finding]:
    declared, out = _registry_names(files)
    for ctx in files:
        if ctx.relpath in (_METRIC_REGISTRY_FILE, _METRIC_IMPL_FILE):
            continue
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and _METRIC_RE.match(node.value)
                    and node.value not in declared):
                out.append(ctx.finding(
                    "metric-name", node,
                    f"metric name {node.value!r} is not declared in "
                    f"observe/metric_names.py — typo'd series silently "
                    f"report zero"))
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_CTORS
                    and (dotted(node.func.value) or "").split(".")[-1]
                    in ("metrics", "_metrics")
                    and node.args
                    and not (isinstance(node.args[0], ast.Constant)
                             and isinstance(node.args[0].value, str))):
                out.append(ctx.finding(
                    "metric-name", node,
                    "dynamic metric name — construct series from literal "
                    "names declared in observe/metric_names.py"))
    return out


# --------------------------------------------------------------------------
# span-name
# --------------------------------------------------------------------------

# call sites that name a span/trace series: <module>.<fn> where the fn is
# a recorder entry point — matched by the LAST TWO dotted components so
# both `profiling.span(...)` and an aliased `_trace.instant(...)` resolve
_SPAN_FNS = {"span": ("profiling", "trace", "_trace"),
             "instant": ("trace", "_trace"),
             "record": ("trace", "_trace")}
# the declaring/implementing modules are exempt (they manipulate names)
_SPAN_EXEMPT_FILES = {_METRIC_REGISTRY_FILE, "profiling.py",
                      "observe/trace.py"}


def _span_registry(files: list[FileCtx]) -> tuple[set[str], list[Finding]]:
    """Names declared in metric_names.SPANS (+ duplicate findings); falls
    back to the live registry when the scanned tree has no copy (fixture
    runs)."""
    for ctx in files:
        if ctx.relpath == _METRIC_REGISTRY_FILE:
            names: set[str] = set()
            dupes: list[Finding] = []
            for node in ctx.tree.body:
                # SPANS = {...} plain or annotated (SPANS: dict[...] = {...})
                target = (node.targets[0] if isinstance(node, ast.Assign)
                          and len(node.targets) == 1
                          else node.target if isinstance(node, ast.AnnAssign)
                          else None)
                if not (isinstance(target, ast.Name)
                        and target.id == "SPANS"
                        and isinstance(getattr(node, "value", None),
                                       ast.Dict)):
                    continue
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) and isinstance(
                            k.value, str):
                        if k.value in names:
                            dupes.append(ctx.finding(
                                "span-name", k,
                                f"span {k.value!r} declared more than "
                                f"once in the SPANS registry"))
                        names.add(k.value)
            return names, dupes
    try:
        from ..observe import metric_names as _mn

        return set(_mn.declared_spans()), []
    except Exception:
        return set(), []


def check_span_names(files: list[FileCtx]) -> list[Finding]:
    declared, out = _span_registry(files)
    for ctx in files:
        if ctx.relpath in _SPAN_EXEMPT_FILES:
            continue
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            d = dotted(node.func)
            if not d:
                continue
            parts = d.split(".")
            if len(parts) < 2 or parts[-1] not in _SPAN_FNS \
                    or parts[-2] not in _SPAN_FNS[parts[-1]]:
                continue
            # trace.record's name is the SECOND positional (after ph)
            arg = node.args[1 if parts[-1] == "record"
                            and len(node.args) > 1 else 0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                out.append(ctx.finding(
                    "span-name", node,
                    "dynamic span name — span/trace names must be "
                    "literals declared in observe/metric_names.py SPANS; "
                    "put dynamic identity (device, item, bytes) in the "
                    "attribution kwargs"))
            elif arg.value not in declared:
                out.append(ctx.finding(
                    "span-name", node,
                    f"span name {arg.value!r} is not declared in "
                    f"observe/metric_names.py SPANS — a typo'd span "
                    f"silently forks the timeline and the aggregates"))
    return out


ALL_CHECKS = {
    "host-sync": check_host_sync,
    "lock-discipline": check_lock_discipline,
    "config-registry": check_config_registry,
    "env-mutation": check_env_mutation,
    "metric-name": check_metric_names,
    "span-name": check_span_names,
}
# the concurrency-discipline suite (analysis/concurrency.py) registers
# its five checks into ALL_CHECKS when imported; the package __init__
# imports it, so any `analysis.*` import sees the full table

"""Line-JSON framing over the serve daemon's Unix-domain socket.

One request object per connection, then a stream of response objects —
newline-delimited JSON, UTF-8, one object per line (the same framing as
the JSONL event log, so a response stream is greppable/replayable with
the same tooling). The final object of every stream has ``"event":
"done"`` (or ``"error"``); ``bst submit --follow`` renders everything in
between as live heartbeats.

Requests::

    {"op": "submit", "tool": "...", "args": [...], "priority": 0,
     "share": "...", "overrides": {"BST_X": "..."}, "cost": 1.0,
     "follow": true, "after": ["j0001"], "profile": "auto"}
    {"op": "jobs"}            {"op": "cancel", "job": "..."}
    {"op": "shutdown", "drain": true}        {"op": "ping"}
    {"op": "status"}          {"op": "trace-dump", "out": "path.json"}
"""

from __future__ import annotations

import contextlib
import json
import os
import socket
import tempfile

# requests and response lines are small control messages; a line larger
# than this is a protocol violation, not data
MAX_LINE = 1 << 20


def default_socket_path() -> str:
    """BST_SERVE_SOCKET, else a per-user path in the system temp dir (the
    uid keeps multi-user hosts from colliding on one socket)."""
    from .. import config

    p = config.get_str("BST_SERVE_SOCKET")
    if p:
        return p
    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(tempfile.gettempdir(), f"bst-serve-{uid}.sock")


def send_line(sock_or_file, obj: dict) -> None:
    data = (json.dumps(obj) + "\n").encode("utf-8")
    if hasattr(sock_or_file, "sendall"):
        sock_or_file.sendall(data)
    else:
        sock_or_file.write(data)
        sock_or_file.flush()


def read_line(f) -> dict | None:
    """One framed object from a socket makefile; None on EOF."""
    line = f.readline(MAX_LINE)
    if not line:
        return None
    line = line.strip()
    if not line:
        return {}
    return json.loads(line)


def connect(socket_path: str | None = None,
            timeout: float | None = None) -> socket.socket:
    path = socket_path or default_socket_path()
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    if timeout is not None:
        s.settimeout(timeout)
    s.connect(path)
    return s


def close(sock: socket.socket) -> None:
    """shutdown(SHUT_RDWR) then close: the makefile() io-ref clients
    wrap around the connection keeps the fd alive past a bare close(),
    so shutdown is what actually tells the daemon we hung up."""
    with contextlib.suppress(OSError):
        sock.shutdown(socket.SHUT_RDWR)
    with contextlib.suppress(OSError):
        sock.close()

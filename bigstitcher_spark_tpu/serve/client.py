"""Thin client side of the serve protocol (what ``bst submit`` / ``bst
jobs`` / ``bst cancel`` call, and what tests drive in-process).

Every function takes the socket path explicitly (None = the
BST_SERVE_SOCKET / per-user default) and raises ``OSError`` when no
daemon is listening — the CLI turns that into a friendly message."""

from __future__ import annotations

from . import protocol


def _one_shot(socket_path: str | None, req: dict,
              timeout: float | None = 30.0) -> dict:
    s = protocol.connect(socket_path, timeout=timeout)
    try:
        f = s.makefile("rwb")
        protocol.send_line(f, req)
        resp = protocol.read_line(f)
        if resp is None:
            raise OSError("daemon closed the connection without replying")
        if resp.get("event") == "error":
            raise RuntimeError(resp.get("error", "daemon error"))
        return resp
    finally:
        protocol.close(s)


def ping(socket_path: str | None = None, timeout: float = 5.0) -> dict:
    return _one_shot(socket_path, {"op": "ping"}, timeout=timeout)


def list_jobs(socket_path: str | None = None) -> dict:
    """{"daemon": {...status...}, "jobs": [...]}."""
    resp = _one_shot(socket_path, {"op": "jobs"})
    return {"daemon": resp.get("daemon", {}), "jobs": resp.get("jobs", [])}


def status(socket_path: str | None = None) -> dict:
    """The daemon's full live status object (what /status also serves)."""
    return _one_shot(socket_path, {"op": "status"}).get("status", {})


def trace_dump(socket_path: str | None = None,
               out: str | None = None, cluster: bool = False) -> dict:
    """Snapshot the daemon's live flight-recorder ring to Perfetto JSON
    (jobs keep running); returns ``{"path": ..., recorder stats...}``.
    ``cluster=True`` additionally pulls every relay-connected rank's
    live ring and folds them into the one barrier-aligned file."""
    req: dict = {"op": "trace-dump"}
    if out:
        req["out"] = out
    if cluster:
        req["cluster"] = True
    # a cluster pull waits up to the collector's per-rank timeout
    return _one_shot(socket_path, req, timeout=60.0 if cluster else 30.0)


def cluster_status(socket_path: str | None = None) -> dict:
    """The relay collector's per-rank view (what /cluster also serves);
    raises RuntimeError when the daemon hosts no collector."""
    return _one_shot(socket_path, {"op": "cluster"})


def cancel(socket_path: str | None, job_id: str) -> dict:
    return _one_shot(socket_path, {"op": "cancel", "job": job_id})


def shutdown(socket_path: str | None = None, drain: bool = True) -> dict:
    return _one_shot(socket_path, {"op": "shutdown", "drain": drain})


def submit(socket_path: str | None, tool: str, args: list[str],
           *, priority: int = 0, share: str | None = None,
           overrides: dict | None = None, cost: float = 1.0,
           after: list[str] | None = None, profile: str | None = None,
           follow: bool = True, on_event=None,
           timeout: float | None = None) -> dict:
    """Submit one job. ``follow=True`` (default) blocks until the job
    finishes, calling ``on_event(record)`` for every streamed heartbeat,
    and returns the final ``done`` record (``exit_code``, ``state``,
    ``warm_compile_hits``, ``telemetry_dir``). ``follow=False`` returns
    the ``accepted`` record immediately. ``after`` lists parent job ids:
    the job stays queued until they all succeed and cancels if any of
    them fails or is cancelled. ``profile`` names a tuned profile from
    the daemon's BST_HISTORY_DIR store (or ``"auto"`` for the best
    backend/shape match) applied under the job's own overrides."""
    s = protocol.connect(socket_path, timeout=timeout)
    try:
        f = s.makefile("rwb")
        req = {
            "op": "submit", "tool": tool, "args": list(args),
            "priority": priority, "share": share, "cost": cost,
            "overrides": overrides or {}, "follow": follow,
            "after": list(after or []),
        }
        if profile:
            req["profile"] = profile
        protocol.send_line(f, req)
        first = protocol.read_line(f)
        if first is None:
            raise OSError("daemon closed the connection without replying")
        if first.get("event") == "error":
            raise RuntimeError(first.get("error", "daemon error"))
        if not follow:
            return first
        job_id = first.get("job")
        while True:
            msg = protocol.read_line(f)
            if msg is None:
                raise OSError(f"daemon connection lost while following "
                              f"job {job_id}")
            if msg.get("event") == "done":
                msg.setdefault("job", job_id)
                return msg
            if on_event is not None:
                on_event(msg)
    finally:
        protocol.close(s)

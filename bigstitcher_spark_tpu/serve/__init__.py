"""``bst serve`` — the persistent multi-job stitching daemon.

Every stage used to be a one-shot CLI process paying jax init, compile
warmup, chunk/tile-cache fill and device placement from zero — the
opposite of a system that "serves heavy traffic" (ROADMAP Open item 1).
This package is the Spark driver / history-server role (PAPER.md §L3/L5)
rebuilt for resident accelerators: one long-lived process owns the device
mesh and every process-wide cache (decoded-chunk LRU, HBM tile cache, the
compiled-fn bucket tables), and the existing CLI tools become thin
submitters over a local Unix-domain socket.

- :mod:`.protocol` — the line-JSON request/stream framing both sides use;
- :mod:`.jobs` — the job model and the priority + fair-share queue
  (slot placement reuses ``pairsched``'s cost-weighted LPT);
- :mod:`.daemon` — the resident server: socket accept loop, executor
  slots, per-job config/telemetry/cancellation scoping, drain-on-SIGTERM;
- :mod:`.client` — what ``bst submit`` / ``bst jobs`` / ``bst cancel``
  call; streams job heartbeats back and returns the job's exit code.

Per-job isolation is scoping, not process isolation: configuration rides
:func:`config.overrides` (a contextvars layer — never ``os.environ``
mutation, which the ``env-mutation`` lint check bans), telemetry rides
:class:`observe.JobRun` (per-job event log + manifest + metric deltas),
cancellation rides :mod:`utils.cancel`, and :mod:`utils.threads` carries
all three into every worker thread a job spawns.
"""

from .jobs import Job, JobQueue  # noqa: F401
from .protocol import default_socket_path  # noqa: F401

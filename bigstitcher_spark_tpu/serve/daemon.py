"""The resident ``bst serve`` daemon.

One process owns jax, the device mesh, and every process-wide cache
(decoded-chunk LRU, HBM tile cache, compiled-fn bucket tables); submitted
jobs execute the SAME click commands the one-shot CLI runs, in-process on
executor-slot threads, so a warm second job skips jax init, compile and
cache fill entirely. Isolation is scoping:

- **config** — each job runs under :func:`config.overrides` with its own
  knob dict; unless the job sets them itself, the daemon splits the
  derived in-flight byte budgets (``BST_INFLIGHT_BYTES``,
  ``BST_PAIR_INFLIGHT_BYTES``) across the executor slots so concurrent
  jobs SHARE the per-device windows instead of each claiming all of HBM;
- **telemetry** — each job gets an :class:`observe.JobRun` (its own
  ``events-job-*.jsonl`` + manifest + metric deltas in its own
  directory) and its stdout routed to its own ``output.log``;
- **cancellation** — each job carries a :class:`utils.cancel.CancelToken`
  that the shared work loops poll at their safe points;
- **crash isolation** — a job is one big try/except on its slot thread:
  a failing job records FAILED and the mesh, caches and every other job
  keep running.

Lifecycle: SIGTERM/SIGINT (or the ``shutdown`` op) drains — the queue
closes (queued jobs cancel), running jobs finish (or are cancelled when
``drain=false``), then the accept loop exits and the socket unlinks.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import queue as _queuemod
import signal
import socket
import sys
import threading
import time

from .. import config, observe, profiling
from ..observe.relay import _shutdown_close
from ..observe import events, httpexport, metrics as _metrics, \
    trace as _trace
from ..utils import cancel as _cancel
from ..utils.threads import ctx_thread
from . import protocol
from .jobs import CANCELLED, DONE, FAILED, QUEUED, RUNNING, Job, JobQueue

# tools a job may NOT be: the serve surface itself (a job submitting jobs
# recurses; `top` would follow its own daemon forever), plus flags that
# would re-enter the process-global telemetry lifecycle under the
# daemon's feet
_BLOCKED_TOOLS = {"serve", "submit", "jobs", "cancel", "top", "trace-dump"}
_BLOCKED_FLAGS = {"--telemetry-dir", "--profile", "--trace"}

_WARM_HITS = _metrics.counter("bst_serve_compile_warm_hits_total")
_PROFILES_APPLIED = _metrics.counter("bst_tune_profiles_applied_total")

# events forwarded to following submit clients (everything else stays in
# the job's JSONL only — a chatty fusion log must not flood the socket)
_STREAMED_EVENTS = {"job.start", "job.end", "stage.start", "stage.progress",
                    "stage.end", "log", "retry.round", "pair.redispatch",
                    "job.stall", "job.resume"}

_STALLED = _metrics.gauge("bst_serve_jobs_stalled")

# a slot loop that is IDLE (no job) must touch its heartbeat at least
# every take() timeout; past this age the loop thread is presumed dead
_SLOT_DEAD_AFTER_S = 15.0


class _StdoutRouter(io.TextIOBase):
    """Routes ``sys.stdout`` writes to the emitting context's job log.

    click.echo and the drivers' progress prints all write to the process
    stdout; in a multi-job daemon that interleaves jobs. The router keys
    on the ambient event scope (the same contextvar the event log routes
    by, carried into worker threads by utils.threads) and appends to the
    job's ``output.log``, falling back to the real stdout outside any job
    scope."""

    def __init__(self):
        self._real = sys.__stdout__
        self._lock = threading.Lock()
        self._files: dict[str, object] = {}

    def register(self, label: str, path: str) -> None:
        """Open the job's log and make sure the router IS sys.stdout.

        Installation happens here, per job, not at daemon start: anything
        else that swaps sys.stdout while the daemon idles (pytest's
        capture does, between test phases) would silently orphan an
        install-once router. Re-checking at every job start self-heals —
        whatever stream is current becomes the fallthrough target."""
        with self._lock:
            self._files[label] = open(path, "a", encoding="utf-8",
                                      buffering=1)
            if sys.stdout is not self:
                self._real = sys.stdout
                sys.stdout = self

    def unregister(self, label: str) -> None:
        with self._lock:
            f = self._files.pop(label, None)
            if not self._files and sys.stdout is self:
                sys.stdout = self._real
        if f is not None:
            f.close()

    def _target(self):
        label = events.current_job()
        if label is not None:
            with self._lock:
                f = self._files.get(label)
            if f is not None:
                return f
        return self._real

    def write(self, s) -> int:
        return self._target().write(s)

    def flush(self) -> None:
        try:
            self._target().flush()
        except ValueError:
            pass

    @property
    def encoding(self):
        return getattr(self._real, "encoding", "utf-8")

    def isatty(self):
        return False


class Daemon:
    """The resident server. ``start()`` binds and spawns the accept loop
    and executor slots; ``wait()`` blocks until shutdown completes (the
    foreground ``bst serve`` mode); tests drive it in-process."""

    def __init__(self, socket_path: str | None = None,
                 slots: int | None = None,
                 jobs_root: str | None = None,
                 idle_timeout: float | None = None,
                 metrics_port: int | None = None,
                 relay: str | None = None):
        self.socket_path = socket_path or protocol.default_socket_path()
        self.slots = slots if slots is not None else \
            max(1, config.get_int("BST_SERVE_SLOTS") or 1)
        self.jobs_root = os.path.abspath(
            jobs_root or (self.socket_path + "-jobs"))
        self.idle_timeout = (idle_timeout if idle_timeout is not None
                             else config.get_int("BST_SERVE_IDLE_TIMEOUT")
                             or 0)
        # live HTTP exporter: None reads BST_METRICS_PORT (whose 0 means
        # OFF); an EXPLICIT 0 (CLI --metrics-port 0, tests) asks the OS
        # for a free ephemeral port instead — the resolved port lands in
        # self.metrics_port / the ping response
        self._metrics_port_arg = metrics_port
        self.metrics_port = 0
        # telemetry relay collector: an explicit --relay host:port beats
        # the BST_TELEMETRY_RELAY knob; a daemon always HOSTS (it is the
        # pod's natural fan-in point — multi-host daemons inherit the
        # aggregated live plane for free)
        self._relay_arg = relay
        self._own_relay = False
        self._own_exchange = False
        self.queue = JobQueue(self.slots)
        self.started_at = time.time()
        self._sock: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._drained = threading.Event()
        self._job_seq = 0
        self._dump_seq = 0
        self._last_activity = time.monotonic()
        self._router: _StdoutRouter | None = None
        self._inflight_base: int | None = None
        self._pair_base: int | None = None
        self.device_info: dict = {}
        self._slot_seen = [time.monotonic()] * self.slots
        self._slot_busy = [False] * self.slots
        self._own_exporter = False
        self._own_trace = False

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Daemon":
        os.makedirs(self.jobs_root, exist_ok=True)
        self._warm_mesh()
        # a resident process records its flight recorder ALWAYS (bounded
        # ring, newest-wins): `bst trace-dump` can then snapshot the last
        # BST_TRACE_BUFFER_BYTES of timeline at any point without anyone
        # having thought to pass --trace before the incident
        if not _trace.enabled():
            _trace.configure()
            self._own_trace = True
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        with contextlib.suppress(OSError):
            os.unlink(self.socket_path)
        s.bind(self.socket_path)
        s.listen(16)
        s.settimeout(1.0)
        self._sock = s
        with self._lock:
            self._router = _StdoutRouter()   # installs itself per job
        self._start_exporter()
        self._start_relay()
        self._start_exchange()
        for slot in range(self.slots):
            th = ctx_thread(self._slot_loop, (slot,),
                            name=f"bst-serve-slot-{slot}")
            th.start()
            self._threads.append(th)
        th = ctx_thread(self._accept_loop, (), name="bst-serve-accept")
        th.start()
        self._threads.append(th)
        th = ctx_thread(self._watchdog_loop, (), name="bst-serve-watchdog")
        th.start()
        self._threads.append(th)
        observe.log(f"bst serve: listening on {self.socket_path} "
                    f"({self.slots} slot(s), "
                    f"{self.device_info.get('local_device_count', '?')} "
                    f"device(s))", stage="serve")
        if self.metrics_port:
            exp = httpexport.active()
            url = exp.url if exp is not None \
                else f"http://127.0.0.1:{self.metrics_port}"
            observe.log(f"bst serve: live exporter on {url} "
                        f"(/metrics /healthz /status /jobs /cluster)",
                        stage="serve")
        return self

    def _start_exporter(self) -> None:
        """Bring the live HTTP exporter up (explicit port arg beats the
        BST_METRICS_PORT knob) and point its providers at this daemon;
        bind failure downgrades to socket-only serving, never a crash."""
        exp = httpexport.active()
        if exp is None:
            try:
                if self._metrics_port_arg is not None:
                    exp = httpexport.start(self._metrics_port_arg)
                else:
                    exp = httpexport.ensure_started()
                self._own_exporter = exp is not None
            except OSError as e:
                observe.log(f"bst serve: live exporter disabled "
                            f"({e})", stage="serve")
                return
        if exp is not None:
            httpexport.set_providers(status=self._status,
                                     health=self._health,
                                     jobs=self._jobs_payload)
            self.metrics_port = exp.port

    def _start_relay(self) -> None:
        """Host the pod telemetry collector (--relay / the knob) so the
        daemon's /metrics, /healthz and /cluster aggregate every relayed
        rank; bind failure downgrades, never a crash."""
        from ..observe import relay as _relay

        addr = (self._relay_arg if self._relay_arg is not None
                else config.get_str("BST_TELEMETRY_RELAY"))
        if not addr or _relay.collector() is not None:
            return
        try:
            col = _relay.serve(addr)
        except (OSError, ValueError) as e:
            observe.log(f"bst serve: relay collector disabled ({e})",
                        stage="serve")
            return
        self._own_relay = True
        observe.log(f"bst serve: telemetry relay collecting on "
                    f"{col.host}:{col.port}", stage="serve")

    def _start_exchange(self) -> None:
        """Host this rank's cross-host block-exchange endpoint
        (BST_DAG_EXCHANGE_ADDR) so multi-process pipeline jobs submitted
        to the daemon stream blocks between ranks; inert without the
        knob or in a single-process world, and a bind failure downgrades
        (the pipeline job will then reject multi-process specs loudly)."""
        from ..dag import exchange as _exchange

        try:
            x = _exchange.ensure_started()
        except Exception as e:   # noqa: BLE001 — never block the daemon
            observe.log(f"bst serve: block exchange disabled ({e})",
                        stage="serve")
            return
        if x is not None:
            self._own_exchange = True
            host, port = x.addresses[x.rank]
            observe.log(f"bst serve: block exchange rank {x.rank}/"
                        f"{x.world} serving on {host}:{port}",
                        stage="serve")

    def _warm_mesh(self) -> None:
        """Pay jax init + device placement ONCE, before accepting work;
        derive the budget bases concurrent jobs split."""
        from ..utils.devicemem import dispatch_budget_bytes, pair_budget_bytes

        try:
            import jax

            devs = jax.local_devices()
            self.device_info = {
                "platform": devs[0].platform,
                "local_device_count": len(devs),
            }
            self._inflight_base = dispatch_budget_bytes(devs[0])
            self._pair_base = pair_budget_bytes(devs[0], 1)
        except Exception as e:  # CPU-only hosts must still serve
            self.device_info = {"error": repr(e)[:200]}
            self._inflight_base = None
            self._pair_base = None

    def _on_signal(self, signum, frame) -> None:
        self.shutdown(drain=True, wait=False)

    def shutdown(self, drain: bool = True, wait: bool = True) -> None:
        """Close the queue (queued jobs cancel); ``drain`` lets running
        jobs finish, otherwise their tokens are set too. Idempotent."""
        _trace.instant("serve.shutdown")
        doomed = self.queue.close()
        for job in doomed:
            self._notify(job, {"event": "done", "job": job.id,
                               "state": job.state, "exit_code": None})
            job.waiters.clear()
        if not drain:
            for job in self.queue.jobs():
                if job.state == RUNNING:
                    job.token.cancel()
        self._stop.set()
        if wait:
            self.wait()

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the daemon fully stopped (socket closed, slots
        joined)."""
        return self._drained.wait(timeout)

    def _finish_stop(self) -> None:
        if self._sock is not None:
            with contextlib.suppress(OSError):
                self._sock.close()
        with contextlib.suppress(OSError):
            os.unlink(self.socket_path)
        for th in self._threads:
            if th is not threading.current_thread():
                # no timeout: drain means running jobs FINISH (a cancel
                # already poisons them when drain=False)
                th.join()
        with self._lock:
            router = self._router
            self._router = None
        if router is not None and sys.stdout is router:
            sys.stdout = router._real   # no job left it installed
        httpexport.clear_providers()
        if self._own_relay:
            from ..observe import relay as _relay

            _relay.stop_collector()   # frees the address, clears the
            #                           cluster providers it attached
        if self._own_exchange:
            from ..dag import exchange as _exchange

            _exchange.shutdown()   # frees the rank's exchange port
        if self._own_exporter:
            httpexport.stop()   # frees the port for the next daemon
        if self._own_trace and _trace.enabled():
            _trace.reset()      # leave the recorder as we found it
        self._drained.set()

    # -- accept / connection handling ----------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                if (self.idle_timeout and self.queue.idle()
                        and time.monotonic() - self._last_activity
                        > self.idle_timeout):
                    observe.log("bst serve: idle timeout, exiting",
                                stage="serve")
                    self.shutdown(drain=True, wait=False)
                    break
                continue
            except OSError:
                break
            self._last_activity = time.monotonic()
            th = ctx_thread(self._handle_conn, (conn,),
                            name="bst-serve-conn")
            th.start()
        # the accept thread owns teardown so shutdown(wait=False) callers
        # (signal handlers) never block inside the handler
        self._finish_stop()

    def _handle_conn(self, conn: socket.socket) -> None:
        f = conn.makefile("rwb")
        try:
            try:
                req = protocol.read_line(f)
            except (ValueError, OSError) as e:
                protocol.send_line(f, {"event": "error",
                                       "error": f"bad request: {e!r}"})
                return
            if not req:
                return
            op = req.get("op")
            if op == "ping":
                rly = self._relay_summary()
                protocol.send_line(f, {
                    "event": "pong", "pid": os.getpid(),
                    "uptime_s": self.uptime_s(),
                    "metrics_port": self.metrics_port,
                    "relay": rly["address"] if rly else None,
                    "device": self.device_info})
            elif op == "jobs":
                protocol.send_line(f, {"event": "jobs",
                                       "daemon": self._status(),
                                       "jobs": self._jobs_payload()})
            elif op == "cancel":
                self._op_cancel(f, req)
            elif op == "shutdown":
                protocol.send_line(f, {"event": "shutdown",
                                       "drain": bool(req.get("drain",
                                                             True))})
                self.shutdown(drain=bool(req.get("drain", True)),
                              wait=False)
            elif op == "submit":
                self._op_submit(f, req)
            elif op == "status":
                protocol.send_line(f, {"event": "status",
                                       "status": self._status()})
            elif op == "trace-dump":
                self._op_trace_dump(f, req)
            elif op == "cluster":
                self._op_cluster(f)
            else:
                protocol.send_line(f, {"event": "error",
                                       "error": f"unknown op {op!r}"})
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass   # client went away; jobs keep running
        finally:
            with contextlib.suppress(OSError):
                f.close()
            # shutdown before close: f is an io-ref on the same fd, so a
            # bare close() would leave the connection half-open and the
            # client hanging on a reply that cannot come
            _shutdown_close(conn)

    def uptime_s(self) -> float:
        """Daemon uptime — the ONE place it is computed (ping, /status
        and `bst jobs --json` must all agree)."""
        return round(time.time() - self.started_at, 1)

    def _stalled_jobs(self) -> list[str]:
        return [j.id for j in self.queue.jobs()
                if j.stalled and j.state == RUNNING]

    def _relay_summary(self) -> dict | None:
        from ..observe import relay as _relay

        col = _relay.collector()
        if col is None:
            return None
        doc = col.cluster_status()["collector"]
        return {"address": doc["address"], "ranks": doc["ranks"],
                "connected": doc["connected"]}

    def _status(self) -> dict:
        from ..io.chunkcache import get_cache

        return {
            "pid": os.getpid(),
            "socket": self.socket_path,
            "slots": self.slots,
            "uptime_s": self.uptime_s(),
            "metrics_port": self.metrics_port,
            "queue_depth": self.queue.depth(),
            "active": self.queue.active(),
            "stalled": self._stalled_jobs(),
            "device": self.device_info,
            # the same process self-view the /metrics scrape refreshes,
            # so `bst jobs --json` and /status literally agree
            "process": httpexport.process_stats(),
            "share_runtime_s": {k: round(v, 3) for k, v in
                                self.queue.share_runtime().items()},
            # warm-cache state: why the second submit is cheaper
            "chunk_cache": get_cache().stats(),
            "compiled_fn": {
                "warm_hits": _metrics.counter(
                    "bst_compiled_fn_warm_hits_total").value,
                "cold_builds": _metrics.counter(
                    "bst_compiled_fn_cold_builds_total").value,
            },
            # live frontier gauges: the in-flight HBM high-water and the
            # streamed-pipeline exchange/stall state (a starved dag
            # consumer shows up here while it is starving, not post-run)
            "inflight": {
                "bytes": _metrics.gauge("bst_inflight_bytes").value,
                "highwater_bytes": _metrics.gauge(
                    "bst_inflight_bytes_highwater").value,
            },
            "dag": {
                "exchange_bytes": _metrics.gauge(
                    "bst_dag_exchange_bytes").value,
                "exchange_blocks": _metrics.gauge(
                    "bst_dag_exchange_blocks").value,
                "producer_stall_s": _metrics.counter(
                    "bst_dag_producer_stall_seconds_total").value,
                "consumer_wait_s": _metrics.counter(
                    "bst_dag_consumer_wait_seconds_total").value,
            },
            "trace": _trace.stats(),
            # the relay collector's pod summary (None when not hosting)
            "relay": self._relay_summary(),
        }

    def _health(self) -> tuple[bool, dict]:
        """The /healthz verdict: 200 only while the mesh came up, every
        slot loop's heartbeat is fresh (idle slots tick each take()
        timeout; a busy slot is alive by definition), no running job is
        stalled, and the daemon is not draining."""
        now = time.monotonic()
        stalled = self._stalled_jobs()
        ages = [round(now - seen, 1) for seen in self._slot_seen]
        dead_slots = [i for i in range(self.slots)
                      if not self._slot_busy[i]
                      and ages[i] > _SLOT_DEAD_AFTER_S]
        mesh_ok = "error" not in self.device_info
        draining = self._stop.is_set()
        ok = mesh_ok and not stalled and not dead_slots and not draining
        return ok, {
            "ok": ok,
            "uptime_s": self.uptime_s(),
            "mesh_ok": mesh_ok,
            "device": self.device_info,
            "slot_heartbeat_age_s": ages,
            "dead_slots": dead_slots,
            "stalled_jobs": stalled,
            "active": self.queue.active(),
            "queue_depth": self.queue.depth(),
            "draining": draining,
        }

    def _jobs_payload(self) -> list[dict]:
        rows = []
        for j in self.queue.jobs():
            d = j.describe()
            open_ids = self.queue.waiting_on(j.id)
            if open_ids:
                d["waiting_on"] = sorted(open_ids)
            rows.append(d)
        return rows

    def _op_cancel(self, f, req: dict) -> None:
        job = self.queue.get(str(req.get("job", "")))
        if job is None:
            protocol.send_line(f, {"event": "error",
                                   "error": f"no such job "
                                            f"{req.get('job')!r}"})
            return
        self.queue.cancel(job.id)
        _trace.instant("serve.cancel", item=job.id)
        # cancelled straight off the queue (the job itself, and any
        # dependents its cancellation cascaded into): no slot will ever
        # notify their followers, so close those streams here
        for j in self.queue.jobs():
            if j.state == CANCELLED and j.started_at is None and j.waiters:
                self._notify(j, {"event": "done", "job": j.id,
                                 "state": j.state, "exit_code": None,
                                 "error": j.error})
                j.waiters.clear()
        protocol.send_line(f, {"event": "cancelled", "job": job.id,
                               "state": job.state})

    def _op_trace_dump(self, f, req: dict) -> None:
        """Snapshot the live flight-recorder ring to Perfetto JSON
        without pausing jobs (the ring copy happens under the trace
        lock; the recorder keeps recording). With ``cluster`` set, the
        relay collector additionally pulls every connected rank's live
        ring and folds them — barrier-aligned — into the one file."""
        out = req.get("out")
        if not out:
            with self._lock:
                self._dump_seq += 1
                n = self._dump_seq
            out = os.path.join(self.jobs_root, f"trace-dump-{n:04d}.json")
        if req.get("cluster"):
            from ..observe import relay as _relay

            col = _relay.collector()
            if col is None:
                protocol.send_line(f, {
                    "event": "error",
                    "error": "no relay collector in this daemon — start "
                             "it with --relay HOST:PORT (or "
                             "BST_TELEMETRY_RELAY)"})
                return
            try:
                res = col.cluster_trace_dump(os.path.abspath(str(out)))
            except (RuntimeError, OSError) as e:
                protocol.send_line(f, {"event": "error", "error": str(e)})
                return
            _trace.instant("serve.trace_dump",
                           item=os.path.basename(res["path"]))
            protocol.send_line(f, {"event": "trace-dump", **res})
            return
        try:
            path = _trace.dump_live(os.path.abspath(str(out)))
        except (RuntimeError, OSError) as e:
            protocol.send_line(f, {"event": "error", "error": str(e)})
            return
        _trace.instant("serve.trace_dump", item=os.path.basename(path))
        protocol.send_line(f, {"event": "trace-dump", "path": path,
                               **_trace.stats()})

    def _op_cluster(self, f) -> None:
        """The /cluster JSON over the daemon socket (`bst top --cluster`
        without an HTTP exporter)."""
        from ..observe import relay as _relay

        col = _relay.collector()
        if col is None:
            protocol.send_line(f, {
                "event": "error",
                "error": "no relay collector in this daemon — start it "
                         "with --relay HOST:PORT (or "
                         "BST_TELEMETRY_RELAY)"})
            return
        protocol.send_line(f, {"event": "cluster", **col.cluster_status()})

    # -- stall watchdog ------------------------------------------------------

    def _watchdog_loop(self) -> None:
        """Flags RUNNING jobs whose stage.progress stopped advancing for
        BST_STALL_TIMEOUT_S: raises the bst_serve_jobs_stalled gauge,
        warns on the job's scoped event sink (and the follower stream),
        and clears the flag the moment progress resumes. The knob is read
        per sweep, so a long-lived daemon can be retuned live."""
        while not self._stop.is_set():
            timeout_s = config.get_int("BST_STALL_TIMEOUT_S") or 0
            now = time.time()
            stalled_n = 0
            for job in self.queue.jobs():
                # clearing runs even with the watchdog disabled: setting
                # the knob to 0 must RELEASE stale stall state (flags,
                # gauge, /healthz), not freeze it
                if job.state != RUNNING or timeout_s <= 0:
                    job.stalled = False
                    continue
                last = job.last_progress or job.started_at or now
                is_stalled = now - last > timeout_s
                if is_stalled:
                    stalled_n += 1
                if is_stalled and not job.stalled:
                    job.stalled = True
                    _trace.instant("serve.stall", item=job.id)
                    self._emit_job_event(
                        job, "job.stall",
                        message=f"no stage.progress for "
                                f"{round(now - last, 1)}s "
                                f"(BST_STALL_TIMEOUT_S={timeout_s})",
                        stalled_for_s=round(now - last, 1),
                        # the streamed-exchange state diagnoses a
                        # starved dag consumer live
                        dag_exchange_bytes=_metrics.gauge(
                            "bst_dag_exchange_bytes").value,
                        dag_producer_stall_s=_metrics.counter(
                            "bst_dag_producer_stall_seconds_total"
                        ).value,
                        dag_consumer_wait_s=_metrics.counter(
                            "bst_dag_consumer_wait_seconds_total"
                        ).value)
                elif not is_stalled and job.stalled:
                    job.stalled = False
                    self._emit_job_event(job, "job.resume",
                                         message="progress resumed")
            _STALLED.set(stalled_n)
            self._stop.wait(max(0.2, min(timeout_s / 4, 5.0))
                            if timeout_s > 0 else 1.0)
        _STALLED.set(0)

    def _emit_job_event(self, job: Job, etype: str, **fields) -> None:
        """Emit a daemon-side event INTO the job's scoped sink (and so
        its follower stream) from the watchdog thread."""
        token = events.activate_job(job.id)
        try:
            events.emit(etype, job=job.id, **fields)
        finally:
            events.deactivate_job(token)

    def _op_submit(self, f, req: dict) -> None:
        from ..cli.main import cli as _cli

        tool = str(req.get("tool", ""))
        args = [str(a) for a in (req.get("args") or [])]
        if tool not in _cli.commands or tool in _BLOCKED_TOOLS:
            protocol.send_line(f, {"event": "error",
                                   "error": f"unknown or unservable tool "
                                            f"{tool!r}"})
            return
        # match both the split ("--flag", "v") and the fused ("--flag=v")
        # spellings click accepts
        bad = sorted({a for a in args
                      if a.split("=", 1)[0] in _BLOCKED_FLAGS})
        if bad:
            protocol.send_line(f, {
                "event": "error",
                "error": f"{bad} are daemon-owned: per-job telemetry is "
                         f"automatic (see the job directory)"})
            return
        try:
            ov = config.validate_overrides(req.get("overrides") or {})
        except KeyError as e:
            protocol.send_line(f, {"event": "error", "error": str(e)})
            return
        # tuned-profile application: an explicit `submit --profile` ref,
        # or BST_PROFILE_AUTO resolving every job against the store. The
        # profile's knobs merge UNDER the job's own --set overrides (the
        # operator's explicit word always wins) and the applied key rides
        # in the job description + manifest params for auditability.
        prof = None
        prof_ref = req.get("profile")
        if prof_ref or config.get_bool("BST_PROFILE_AUTO"):
            try:
                prof = self._resolve_profile(str(prof_ref or "auto"))
            except (KeyError, FileNotFoundError, ValueError) as e:
                if prof_ref and prof_ref != "auto":
                    # the client named a specific profile: failing to
                    # resolve it must not silently run untuned
                    protocol.send_line(f, {"event": "error",
                                           "error": str(e)})
                    return
                prof = None   # auto is best-effort by design
        if prof is not None:
            try:
                prof_ov = config.validate_overrides(
                    prof.get("overrides") or {})
            except KeyError as e:   # store written by a newer/older build
                protocol.send_line(f, {"event": "error", "error": str(e)})
                return
            ov = {**prof_ov, **ov}
            _PROFILES_APPLIED.inc()
        with self._lock:
            self._job_seq += 1
            jid = f"j{self._job_seq:04d}"
        job = Job(
            id=jid, tool=tool, args=args,
            priority=int(req.get("priority") or 0),
            share=str(req.get("share") or "default"),
            overrides=ov,
            cost=float(req.get("cost") or 1.0),
            after=[str(a) for a in (req.get("after") or [])],
        )
        if prof is not None:
            job.profile = prof.get("key")
        job.telemetry_dir = os.path.join(self.jobs_root, jid)
        follow = bool(req.get("follow", True))
        waiter = None
        if follow:
            waiter = _queuemod.Queue()
            job.waiters.append(waiter)
        try:
            self.queue.submit(job)
        except RuntimeError as e:   # draining
            protocol.send_line(f, {"event": "error", "error": str(e)})
            return
        except KeyError as e:       # unknown --after parent
            protocol.send_line(f, {"event": "error", "error": str(e)})
            return
        _trace.instant("serve.submit", item=jid)
        events.emit("serve.submit", job=jid, tool=tool, share=job.share,
                    priority=job.priority, after=job.after)
        accepted = {"event": "accepted", "job": jid,
                    "telemetry_dir": job.telemetry_dir}
        if job.profile:
            accepted["profile"] = job.profile
        protocol.send_line(f, accepted)
        if job.state == CANCELLED:
            # a parent had already failed/cancelled: terminal on arrival
            self._notify(job, {"event": "done", "job": jid,
                               "state": job.state, "exit_code": None,
                               "error": job.error})
            job.waiters.clear()
        if not follow:
            return
        while True:
            msg = waiter.get()
            protocol.send_line(f, msg)
            if msg.get("event") == "done":
                return

    def _resolve_profile(self, ref: str) -> dict | None:
        """Resolve a submit-time profile reference against the tuned-
        profile store (BST_HISTORY_DIR/profiles.json) along THIS
        daemon's backend axes. ``auto`` returns None when nothing
        matches; an explicit ref raises KeyError (handled by the
        caller into a submit error)."""
        from ..tune import profiles as _profiles

        store = _profiles.load_store(None)
        backend = self.device_info.get("platform") or "cpu"
        ndev = int(self.device_info.get("local_device_count") or 1)
        return _profiles.match_profile(store, backend=backend,
                                       device_count=ndev, ref=ref)

    # -- job execution -------------------------------------------------------

    def _notify(self, job: Job, msg: dict) -> None:
        for w in list(job.waiters):
            w.put(msg)

    def _job_budget_overrides(self, job: Job) -> dict[str, str]:
        """The job's effective override layer: its own knobs win; below
        them, the derived per-device byte windows split across the
        executor slots so concurrent jobs share HBM instead of each
        claiming the full budget (the window ledger's high-water gauge
        stays <= the single-job budget)."""
        ov = dict(job.overrides)
        if self.slots > 1:
            if self._inflight_base and "BST_INFLIGHT_BYTES" not in ov:
                ov["BST_INFLIGHT_BYTES"] = str(
                    max(1, self._inflight_base // self.slots))
            if self._pair_base and "BST_PAIR_INFLIGHT_BYTES" not in ov:
                ov["BST_PAIR_INFLIGHT_BYTES"] = str(
                    max(1, self._pair_base // self.slots))
        return ov

    def _slot_loop(self, slot: int) -> None:
        while True:
            self._slot_seen[slot] = time.monotonic()
            job = self.queue.take(slot, timeout=0.5)
            if job is None:
                if self._stop.is_set():
                    return
                continue
            self._last_activity = time.monotonic()
            self._slot_busy[slot] = True
            try:
                self._run_job(slot, job)
            finally:
                self._slot_busy[slot] = False
                self._slot_seen[slot] = time.monotonic()
            self._last_activity = time.monotonic()

    def _run_job(self, slot: int, job: Job) -> None:
        """The crash-isolated job wrapper: whatever this raises is THIS
        job's failure — the slot, the mesh and the caches live on. The
        per-job SETUP (job dir, telemetry sink, output router) sits
        inside the isolation too: a full disk must fail the job, not
        kill the slot thread and wedge the queue."""
        import click

        from ..cli.main import cli as _cli

        jobrun = None
        router = None
        warm0 = _metrics.counter("bst_compiled_fn_warm_hits_total").value
        state, rc, error = DONE, 0, None
        try:
            os.makedirs(job.telemetry_dir, exist_ok=True)
            jobrun = observe.JobRun(job.id, job.telemetry_dir,
                                    tool=job.tool)
            # live heartbeats: the job's event sink exists now, bridge its
            # progress subset to every following client (the sink — and
            # with it this subscription — is dropped by jobrun.finalize)
            events.subscribe(job.id, _streaming_forwarder(job))
            with self._lock:
                router = self._router
            if router is not None:
                router.register(job.id, os.path.join(job.telemetry_dir,
                                                     "output.log"))
            # new remote-cache coherence window: chunks cached from remote
            # object stores (BST_REMOTE_CACHE=run) are pinned to one run —
            # another writer may have touched the bucket between jobs, so
            # each job re-validates via fresh metadata signatures. Local
            # stores keep their mtime-keyed warmth across jobs.
            from ..io.chunkstore import bump_remote_pin

            bump_remote_pin()
            with config.overrides(self._job_budget_overrides(job)), \
                    _cancel.scope(job.token), jobrun:
                # the stall clock starts NOW: a job that never emits a
                # heartbeat stalls timeout_s after start, not after epoch
                job.last_progress = time.time()
                self._notify(job, {"event": "start", "job": job.id,
                                   "slot": slot})
                with profiling.span("serve.job", stage=job.tool,
                                    item=job.id):
                    _cli(args=[job.tool, *job.args], prog_name="bst",
                         standalone_mode=False)
        except _cancel.Cancelled:
            state, rc, error = CANCELLED, 130, "cancelled"
        except click.exceptions.Exit as e:
            rc = int(e.exit_code or 0)
            state = DONE if rc == 0 else FAILED
        except SystemExit as e:   # a tool calling sys.exit stays one job
            rc = int(e.code) if isinstance(e.code, int) else 1
            state = DONE if rc == 0 else FAILED
        except click.ClickException as e:
            state, rc, error = FAILED, e.exit_code or 1, e.format_message()
        except BaseException as e:  # noqa: BLE001 — crash isolation
            state, rc, error = FAILED, 1, repr(e)[:500]
        if job.token.cancelled and state != CANCELLED:
            # token set but the job finished first: report what happened
            state = state if state == DONE else CANCELLED
        job.warm_compile_hits = int(
            _metrics.counter("bst_compiled_fn_warm_hits_total").value
            - warm0)
        _WARM_HITS.inc(job.warm_compile_hits)
        try:
            if jobrun is None:
                raise RuntimeError("job setup failed before telemetry")
            jobrun.finalize(
                status={DONE: "ok", CANCELLED: "cancelled"}.get(state,
                                                                "error"),
                error=error,
                params={"tool": job.tool, "args": job.args,
                        "overrides": job.overrides,
                        "profile": job.profile,
                        "priority": job.priority, "share": job.share,
                        "slot": slot,
                        "warm_compile_hits": job.warm_compile_hits})
        except Exception:   # manifest IO must not flip the job's outcome
            pass
        if router is not None:
            router.unregister(job.id)
        cascaded = self.queue.finish(job, state, exit_code=rc, error=error)
        self._notify(job, {"event": "done", "job": job.id, "state": state,
                           "exit_code": rc, "error": error,
                           "seconds": job.describe().get("seconds"),
                           "warm_compile_hits": job.warm_compile_hits,
                           "telemetry_dir": job.telemetry_dir})
        job.waiters.clear()   # done delivered; drop follower queues
        for child in cascaded:
            # dependents cancelled because THIS job failed: their
            # followers' streams close here — no slot will ever run them
            self._notify(child, {"event": "done", "job": child.id,
                                 "state": child.state, "exit_code": None,
                                 "error": child.error})
            child.waiters.clear()


def _streaming_forwarder(job: Job):
    """events->waiters bridge: forwards the heartbeat subset of a job's
    event stream to every following client, and feeds the stall
    watchdog's progress clock + `bst top`'s live progress row."""
    def cb(rec: dict) -> None:
        t = rec.get("type")
        if t in ("stage.start", "stage.progress", "stage.end"):
            job.last_progress = time.time()
            if t == "stage.progress":
                job.progress = {k: rec[k] for k in
                                ("stage", "done", "total", "rate_per_s",
                                 "eta_s") if k in rec}
            elif t == "stage.end":
                job.progress = None   # stage finished; row is stale
        if t in _STREAMED_EVENTS:
            for w in list(job.waiters):
                w.put({"event": "job-event", "job": job.id, **rec})

    return cb


def run_foreground(socket_path: str | None = None, slots: int | None = None,
                   jobs_root: str | None = None,
                   idle_timeout: float | None = None,
                   metrics_port: int | None = None,
                   relay: str | None = None) -> int:
    """``bst serve`` without --detach: start, block until shutdown.

    Signal handling lives HERE, not in Daemon.start(): only the
    foreground CLI owns the process (and the main thread signal.signal
    requires) — an in-process daemon (tests, bench) must never hijack
    its host's SIGINT/SIGTERM. Previous handlers are restored on exit."""
    d = Daemon(socket_path, slots=slots, jobs_root=jobs_root,
               idle_timeout=idle_timeout, metrics_port=metrics_port,
               relay=relay)
    d.start()
    prev = {}
    if threading.current_thread() is threading.main_thread():
        for sig in (signal.SIGTERM, signal.SIGINT):
            prev[sig] = signal.signal(sig, d._on_signal)
    try:
        while not d.wait(timeout=0.5):
            pass
    except KeyboardInterrupt:
        d.shutdown(drain=True, wait=True)
    finally:
        for sig, h in prev.items():
            signal.signal(sig, h)
    return 0


def spawn_detached(socket_path: str | None = None, slots: int | None = None,
                   jobs_root: str | None = None,
                   idle_timeout: float | None = None,
                   metrics_port: int | None = None,
                   relay: str | None = None,
                   ready_timeout: float = 180.0) -> int:
    """``bst serve --detach``: fork a daemon subprocess, wait until its
    socket answers ping, return its pid."""
    import subprocess

    from . import client

    path = socket_path or protocol.default_socket_path()
    # the daemon inherits the caller's cwd (so the job's relative paths
    # resolve the same way), which need not be the package checkout —
    # put wherever THIS package imports from on the child's path
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                         if env.get("PYTHONPATH") else pkg_root)
    args = [sys.executable, "-m", "bigstitcher_spark_tpu.cli.main",
            "serve", "--socket", path]
    if slots is not None:
        args += ["--slots", str(slots)]
    if jobs_root is not None:
        args += ["--jobs-root", jobs_root]
    if idle_timeout is not None:
        args += ["--idle-timeout", str(int(idle_timeout))]
    if metrics_port is not None:
        args += ["--metrics-port", str(int(metrics_port))]
    if relay is not None:
        args += ["--relay", relay]
    log_path = path + ".log"
    with open(log_path, "ab") as logf:
        proc = subprocess.Popen(args, stdout=logf, stderr=logf, env=env,
                                start_new_session=True)
    deadline = time.monotonic() + ready_timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"serve daemon exited rc={proc.returncode} before ready "
                f"(log: {log_path})")
        try:
            client.ping(path, timeout=2.0)
            return proc.pid
        except (OSError, ValueError):
            time.sleep(0.2)
    raise TimeoutError(f"serve daemon not ready after {ready_timeout}s "
                       f"(log: {log_path})")

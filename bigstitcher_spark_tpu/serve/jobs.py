"""Job model and the daemon's priority + fair-share queue.

Scheduling is three-layered, cheapest concern last:

1. **Priority** — higher ``priority`` strictly first (an operator's
   interactive fusion beats a batch re-registration sweep).
2. **Fair share** — within a priority band, the submitter (``share``)
   with the least accumulated runtime goes first, so one chatty client
   cannot starve the others (the Spark fair-scheduler pool role).
3. **LPT slot placement** — the ordered backlog is spread over the
   executor slots with :func:`pairsched.assign_tasks`, the same
   cost-weighted greedy placement the pair stages use on devices: the
   heaviest queued job lands on the least-loaded slot, bounding slot
   imbalance by one job's cost. A slot whose plan is empty steals the
   head of the ordered backlog rather than idling.

Jobs carry their config override dict (resolved per job by the daemon
through :func:`config.overrides`, never the process environment) and
their :class:`utils.cancel.CancelToken`; cancelling a QUEUED job is a
pure state flip, cancelling a RUNNING one sets the token and lets the
work loops' poison points unwind it.
"""

from __future__ import annotations

import threading
import time

from dataclasses import dataclass, field
from typing import Any

from ..observe import metrics as _metrics
from ..parallel.pairsched import PairTask, assign_tasks
from ..utils.cancel import CancelToken

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
CANCELLED = "cancelled"

_SUBMITTED = _metrics.counter("bst_serve_jobs_submitted_total")
_DEPTH = _metrics.gauge("bst_serve_queue_depth")
_ACTIVE = _metrics.gauge("bst_serve_active_jobs")
_WAIT = _metrics.histogram("bst_serve_wait_seconds")

# terminal-job history kept for `bst jobs`: a resident daemon serving a
# steady stream must not grow its registry (or its one-line `jobs`
# response) without bound — oldest finished jobs age out past this
MAX_FINISHED_JOBS = 200


@dataclass
class Job:
    """One submitted tool invocation and its lifecycle record."""

    id: str
    tool: str
    args: list[str]
    priority: int = 0
    share: str = "default"
    overrides: dict[str, str] = field(default_factory=dict)
    cost: float = 1.0            # relative placement weight (LPT)
    state: str = QUEUED
    submitted_at: float = field(default_factory=time.time)
    started_at: float | None = None
    finished_at: float | None = None
    exit_code: int | None = None
    error: str | None = None
    seq: int = 0                 # FIFO tiebreak within a share
    slot: int | None = None
    telemetry_dir: str | None = None
    warm_compile_hits: int = 0
    token: CancelToken = field(default_factory=CancelToken)
    waiters: list = field(default_factory=list)   # queue.Queue per client

    def describe(self) -> dict[str, Any]:
        now = time.time()
        d = {
            "id": self.id,
            "tool": self.tool,
            "args": list(self.args),
            "priority": self.priority,
            "share": self.share,
            "state": self.state,
            "submitted_at": round(self.submitted_at, 3),
            "wait_s": round((self.started_at or now) - self.submitted_at, 3),
        }
        if self.overrides:
            d["overrides"] = dict(self.overrides)
        if self.started_at is not None:
            d["seconds"] = round((self.finished_at or now)
                                 - self.started_at, 3)
        if self.slot is not None:
            d["slot"] = self.slot
        if self.exit_code is not None:
            d["exit_code"] = self.exit_code
        if self.error:
            d["error"] = self.error
        if self.telemetry_dir:
            d["telemetry_dir"] = self.telemetry_dir
        if self.warm_compile_hits:
            d["warm_compile_hits"] = self.warm_compile_hits
        return d


class JobQueue:
    """Thread-safe job registry + scheduler for N executor slots."""

    def __init__(self, slots: int = 1):
        self.slots = max(1, int(slots))
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._jobs: dict[str, Job] = {}
        self._order: list[str] = []        # ids still QUEUED, FIFO
        self._share_runtime: dict[str, float] = {}
        self._seq = 0
        self._closed = False

    # -- submission / lookup ------------------------------------------------

    def submit(self, job: Job) -> Job:
        with self._nonempty:
            if self._closed:
                raise RuntimeError("daemon is draining: not accepting jobs")
            self._seq += 1
            job.seq = self._seq
            self._jobs[job.id] = job
            self._order.append(job.id)
            _SUBMITTED.inc()
            _DEPTH.set(len(self._order))
            self._nonempty.notify_all()
        return job

    def get(self, job_id: str) -> Job | None:
        with self._lock:
            return self._jobs.get(job_id)

    def jobs(self) -> list[Job]:
        with self._lock:
            return list(self._jobs.values())

    def depth(self) -> int:
        with self._lock:
            return len(self._order)

    def active(self) -> int:
        with self._lock:
            return sum(1 for j in self._jobs.values() if j.state == RUNNING)

    def idle(self) -> bool:
        with self._lock:
            return not self._order and not any(
                j.state == RUNNING for j in self._jobs.values())

    # -- scheduling ---------------------------------------------------------

    def _ordered_backlog_locked(self) -> list[Job]:
        backlog = [self._jobs[i] for i in self._order]
        return sorted(backlog, key=lambda j: (
            -j.priority,
            self._share_runtime.get(j.share, 0.0),
            j.seq))

    def plan(self) -> list[list[str]]:
        """Current backlog spread over the slots: the priority/fair-share
        order feeds pairsched's cost-weighted LPT placement (heaviest job
        -> least-loaded slot, deterministic). Advisory — ``take`` replans
        on every pull, so the plan tracks a changing backlog."""
        with self._lock:
            backlog = self._ordered_backlog_locked()
            bins = assign_tasks(
                [PairTask(index=i, cost=max(j.cost, 0.0), tag=j.id)
                 for i, j in enumerate(backlog)], self.slots)
            return [[t.tag for t in b] for b in bins]

    def take(self, slot_id: int, timeout: float | None = None) -> Job | None:
        """Block until a job is available for ``slot_id`` (its LPT plan
        entry first, else the backlog head), mark it RUNNING and return
        it; None on timeout or when the queue closed empty."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._nonempty:
            while True:
                if self._order:
                    backlog = self._ordered_backlog_locked()
                    bins = assign_tasks(
                        [PairTask(index=i, cost=max(j.cost, 0.0), tag=j.id)
                         for i, j in enumerate(backlog)], self.slots)
                    mine = bins[slot_id % self.slots]
                    job_id = mine[0].tag if mine else backlog[0].id
                    job = self._jobs[job_id]
                    self._order.remove(job_id)
                    job.state = RUNNING
                    job.slot = slot_id
                    job.started_at = time.time()
                    _DEPTH.set(len(self._order))
                    _ACTIVE.inc(1)
                    _WAIT.observe(job.started_at - job.submitted_at)
                    return job
                if self._closed:
                    return None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    self._nonempty.wait(remaining)
                else:
                    self._nonempty.wait()

    def finish(self, job: Job, state: str, exit_code: int | None = None,
               error: str | None = None) -> None:
        with self._nonempty:
            job.state = state
            job.exit_code = exit_code
            job.error = error
            job.finished_at = time.time()
            if job.started_at is not None:
                self._share_runtime[job.share] = (
                    self._share_runtime.get(job.share, 0.0)
                    + (job.finished_at - job.started_at))
                _ACTIVE.inc(-1)
            _metrics.counter("bst_serve_jobs_completed_total",
                             status=state).inc()
            self._prune_locked()
            self._nonempty.notify_all()

    def _prune_locked(self) -> None:
        terminal = [i for i, j in self._jobs.items()
                    if j.state in (DONE, FAILED, CANCELLED)]
        for jid in terminal[:max(0, len(terminal) - MAX_FINISHED_JOBS)]:
            del self._jobs[jid]   # dict order == submission order

    def cancel(self, job_id: str) -> Job | None:
        """Cancel a job: queued -> terminal CANCELLED immediately; running
        -> set its token (the work loops unwind at their poison points).
        Returns the job, or None when unknown."""
        with self._nonempty:
            job = self._jobs.get(job_id)
            if job is None:
                return None
            job.token.cancel()
            if job.state == QUEUED:
                self._order.remove(job_id)
                job.state = CANCELLED
                job.finished_at = time.time()
                _DEPTH.set(len(self._order))
                _metrics.counter("bst_serve_jobs_completed_total",
                                 status=CANCELLED).inc()
                self._nonempty.notify_all()
            return job

    def close(self) -> list[Job]:
        """Stop accepting; cancel everything still QUEUED (drain keeps the
        RUNNING jobs). Returns the jobs cancelled off the queue."""
        with self._nonempty:
            self._closed = True
            doomed = [self._jobs[i] for i in self._order]
            self._order.clear()
            for job in doomed:
                job.token.cancel()
                job.state = CANCELLED
                job.finished_at = time.time()
                _metrics.counter("bst_serve_jobs_completed_total",
                                 status=CANCELLED).inc()
            _DEPTH.set(0)
            self._nonempty.notify_all()
            return doomed

    def share_runtime(self) -> dict[str, float]:
        with self._lock:
            return dict(self._share_runtime)

"""Job model and the daemon's priority + fair-share queue.

Scheduling is three-layered, cheapest concern last:

1. **Priority** — higher ``priority`` strictly first (an operator's
   interactive fusion beats a batch re-registration sweep).
2. **Fair share** — within a priority band, the submitter (``share``)
   with the least accumulated runtime goes first, so one chatty client
   cannot starve the others (the Spark fair-scheduler pool role).
3. **LPT slot placement** — the ordered backlog is spread over the
   executor slots with :func:`pairsched.assign_tasks`, the same
   cost-weighted greedy placement the pair stages use on devices: the
   heaviest queued job lands on the least-loaded slot, bounding slot
   imbalance by one job's cost. A slot whose plan is empty steals the
   head of the ordered backlog rather than idling.

Jobs carry their config override dict (resolved per job by the daemon
through :func:`config.overrides`, never the process environment) and
their :class:`utils.cancel.CancelToken`; cancelling a QUEUED job is a
pure state flip, cancelling a RUNNING one sets the token and lets the
work loops' poison points unwind it.

Jobs may also declare **dependency edges** (``bst submit --after
<job-id>[,...]``): a job with unmet parents waits OUTSIDE the runnable
backlog (state QUEUED, ``waiting_on`` listing the open parents) until
every parent finishes DONE; a parent that fails or is cancelled cancels
the child — and, transitively, the child's own dependents. This is the
daemon-side primitive `bst submit --pipeline` chains stages on, and it
is useful standalone (submit a fusion now, a downsample after it).
"""

from __future__ import annotations

import threading
import time

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any

from ..observe import metrics as _metrics
from ..parallel.pairsched import PairTask, assign_tasks
from ..utils.cancel import CancelToken

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
CANCELLED = "cancelled"

_SUBMITTED = _metrics.counter("bst_serve_jobs_submitted_total")
_DEPTH = _metrics.gauge("bst_serve_queue_depth")
_ACTIVE = _metrics.gauge("bst_serve_active_jobs")
_WAIT = _metrics.histogram("bst_serve_wait_seconds")

# terminal-job history kept for `bst jobs`: a resident daemon serving a
# steady stream must not grow its registry (or its one-line `jobs`
# response) without bound — oldest finished jobs age out past this
MAX_FINISHED_JOBS = 200

# terminal STATES remembered past pruning, so `--after <old-job>` keeps
# its documented semantics (DONE parent -> runnable, FAILED/CANCELLED ->
# cancel) even after the job itself aged out of the registry
MAX_PRUNED_STATES = 2000


@dataclass
class Job:
    """One submitted tool invocation and its lifecycle record."""

    id: str
    tool: str
    args: list[str]
    priority: int = 0
    share: str = "default"
    overrides: dict[str, str] = field(default_factory=dict)
    cost: float = 1.0            # relative placement weight (LPT)
    after: list[str] = field(default_factory=list)  # parent job ids
    state: str = QUEUED
    submitted_at: float = field(default_factory=time.time)
    started_at: float | None = None
    finished_at: float | None = None
    exit_code: int | None = None
    error: str | None = None
    seq: int = 0                 # FIFO tiebreak within a share
    slot: int | None = None
    telemetry_dir: str | None = None
    profile: str | None = None   # tuned-profile key applied to this job
    warm_compile_hits: int = 0
    token: CancelToken = field(default_factory=CancelToken)
    waiters: list = field(default_factory=list)   # queue.Queue per client
    # live-progress state the stall watchdog and `bst top` read: last
    # wall-clock a stage.progress/start/end advanced, the latest progress
    # payload, and whether the watchdog currently flags the job stalled
    last_progress: float | None = None
    progress: dict[str, Any] | None = None
    stalled: bool = False

    def describe(self) -> dict[str, Any]:
        now = time.time()
        d = {
            "id": self.id,
            "tool": self.tool,
            "args": list(self.args),
            "priority": self.priority,
            "share": self.share,
            "state": self.state,
            "submitted_at": round(self.submitted_at, 3),
            "wait_s": round((self.started_at or now) - self.submitted_at, 3),
        }
        if self.overrides:
            d["overrides"] = dict(self.overrides)
        if self.after:
            d["after"] = list(self.after)
        if self.started_at is not None:
            d["seconds"] = round((self.finished_at or now)
                                 - self.started_at, 3)
        if self.slot is not None:
            d["slot"] = self.slot
        if self.exit_code is not None:
            d["exit_code"] = self.exit_code
        if self.error:
            d["error"] = self.error
        if self.telemetry_dir:
            d["telemetry_dir"] = self.telemetry_dir
        if self.profile:
            d["profile"] = self.profile
        if self.warm_compile_hits:
            d["warm_compile_hits"] = self.warm_compile_hits
        # snapshot first: the streaming forwarder thread may null this
        # out (stage.end) between a truthiness check and the copy
        progress = self.progress
        if progress:
            d["progress"] = dict(progress)
        if self.stalled and self.state == RUNNING:
            d["stalled"] = True
            if self.last_progress is not None:
                d["stalled_for_s"] = round(now - self.last_progress, 1)
        return d


class JobQueue:
    """Thread-safe job registry + scheduler for N executor slots."""

    def __init__(self, slots: int = 1):
        self.slots = max(1, int(slots))
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._jobs: dict[str, Job] = {}
        self._order: list[str] = []        # ids runnable now, FIFO
        self._waiting: dict[str, set[str]] = {}  # id -> open parent ids
        self._pruned: OrderedDict[str, str] = OrderedDict()  # id -> state
        self._share_runtime: dict[str, float] = {}
        self._seq = 0
        self._closed = False

    # -- submission / lookup ------------------------------------------------

    def submit(self, job: Job) -> Job:
        """Register + enqueue a job. Jobs with ``after`` parents that are
        still open wait off the runnable backlog; a parent that already
        failed/cancelled cancels the job on the spot (state CANCELLED on
        the returned job). Raises KeyError for an unknown parent id."""
        with self._nonempty:
            if self._closed:
                raise RuntimeError("daemon is draining: not accepting jobs")
            unmet: set[str] = set()
            doomed_by = None
            for p in job.after:
                parent = self._jobs.get(p)
                state = parent.state if parent is not None \
                    else self._pruned.get(p)
                if state is None:
                    raise KeyError(f"unknown job {p!r} in --after")
                if state in (FAILED, CANCELLED):
                    doomed_by = (p, state)
                elif state != DONE:
                    unmet.add(p)
            self._seq += 1
            job.seq = self._seq
            self._jobs[job.id] = job
            _SUBMITTED.inc()
            if doomed_by is not None:
                self._cancel_locked(job, f"parent {doomed_by[0]} "
                                         f"{doomed_by[1]}")
            elif unmet:
                self._waiting[job.id] = unmet
            else:
                self._order.append(job.id)
            self._update_depth_locked()
            self._nonempty.notify_all()
        return job

    def _update_depth_locked(self) -> None:
        _DEPTH.set(len(self._order) + len(self._waiting))

    def _cancel_locked(self, job: Job, error: str | None = None) -> None:
        """Flip a not-yet-started job to terminal CANCELLED and cascade
        to its waiting dependents."""
        if job.state in (DONE, FAILED, CANCELLED):
            return  # diamond dependency: already cancelled via a sibling
        job.token.cancel()
        job.state = CANCELLED
        job.error = error
        job.finished_at = time.time()
        self._waiting.pop(job.id, None)
        if job.id in self._order:
            self._order.remove(job.id)
        _metrics.counter("bst_serve_jobs_completed_total",
                         status=CANCELLED).inc()
        self._resolve_children_locked(job)

    def _resolve_children_locked(self, job: Job) -> None:
        """A job reached a terminal state: release children waiting on it
        (DONE) or cancel them — and their cones — (FAILED/CANCELLED)."""
        children = [self._jobs[c] for c, open_ids in list(self._waiting.items())
                    if job.id in open_ids]
        for child in children:
            if job.state == DONE:
                open_ids = self._waiting[child.id]
                open_ids.discard(job.id)
                if not open_ids:
                    del self._waiting[child.id]
                    self._order.append(child.id)
            else:
                self._cancel_locked(child, f"parent {job.id} {job.state}")

    def get(self, job_id: str) -> Job | None:
        with self._lock:
            return self._jobs.get(job_id)

    def jobs(self) -> list[Job]:
        with self._lock:
            return list(self._jobs.values())

    def depth(self) -> int:
        with self._lock:
            return len(self._order) + len(self._waiting)

    def waiting_on(self, job_id: str) -> set[str] | None:
        """Open parent ids a queued job still waits for (None when it is
        runnable / unknown)."""
        with self._lock:
            open_ids = self._waiting.get(job_id)
            return set(open_ids) if open_ids is not None else None

    def active(self) -> int:
        with self._lock:
            return sum(1 for j in self._jobs.values() if j.state == RUNNING)

    def idle(self) -> bool:
        with self._lock:
            return (not self._order and not self._waiting and not any(
                j.state == RUNNING for j in self._jobs.values()))

    # -- scheduling ---------------------------------------------------------

    def _ordered_backlog_locked(self) -> list[Job]:
        backlog = [self._jobs[i] for i in self._order]
        return sorted(backlog, key=lambda j: (
            -j.priority,
            self._share_runtime.get(j.share, 0.0),
            j.seq))

    def plan(self) -> list[list[str]]:
        """Current backlog spread over the slots: the priority/fair-share
        order feeds pairsched's cost-weighted LPT placement (heaviest job
        -> least-loaded slot, deterministic). Advisory — ``take`` replans
        on every pull, so the plan tracks a changing backlog."""
        with self._lock:
            backlog = self._ordered_backlog_locked()
            bins = assign_tasks(
                [PairTask(index=i, cost=max(j.cost, 0.0), tag=j.id)
                 for i, j in enumerate(backlog)], self.slots)
            return [[t.tag for t in b] for b in bins]

    def take(self, slot_id: int, timeout: float | None = None) -> Job | None:
        """Block until a job is available for ``slot_id`` (its LPT plan
        entry first, else the backlog head), mark it RUNNING and return
        it; None on timeout or when the queue closed empty."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._nonempty:
            while True:
                if self._order:
                    backlog = self._ordered_backlog_locked()
                    bins = assign_tasks(
                        [PairTask(index=i, cost=max(j.cost, 0.0), tag=j.id)
                         for i, j in enumerate(backlog)], self.slots)
                    mine = bins[slot_id % self.slots]
                    job_id = mine[0].tag if mine else backlog[0].id
                    job = self._jobs[job_id]
                    self._order.remove(job_id)
                    job.state = RUNNING
                    job.slot = slot_id
                    job.started_at = time.time()
                    self._update_depth_locked()
                    _ACTIVE.inc(1)
                    _WAIT.observe(job.started_at - job.submitted_at)
                    return job
                if self._closed:
                    return None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    self._nonempty.wait(remaining)
                else:
                    self._nonempty.wait()

    def finish(self, job: Job, state: str, exit_code: int | None = None,
               error: str | None = None) -> list[Job]:
        """Record a job's terminal state; resolves dependency edges (DONE
        releases waiting children, FAILED/CANCELLED cancels their cones).
        Returns the children cancelled by cascade so the daemon can close
        their followers' streams."""
        with self._nonempty:
            job.state = state
            job.exit_code = exit_code
            job.error = error
            job.finished_at = time.time()
            if job.started_at is not None:
                self._share_runtime[job.share] = (
                    self._share_runtime.get(job.share, 0.0)
                    + (job.finished_at - job.started_at))
                _ACTIVE.inc(-1)
            _metrics.counter("bst_serve_jobs_completed_total",
                             status=state).inc()
            before = {j.id for j in self._jobs.values()
                      if j.state == CANCELLED}
            self._resolve_children_locked(job)
            cascaded = [j for j in self._jobs.values()
                        if j.state == CANCELLED and j.id not in before]
            self._update_depth_locked()
            self._prune_locked()
            self._nonempty.notify_all()
        return cascaded

    def _prune_locked(self) -> None:
        terminal = [i for i, j in self._jobs.items()
                    if j.state in (DONE, FAILED, CANCELLED)]
        for jid in terminal[:max(0, len(terminal) - MAX_FINISHED_JOBS)]:
            # remember the terminal state (bounded) so --after edges to
            # pruned jobs keep their semantics instead of erroring
            self._pruned[jid] = self._jobs[jid].state
            del self._jobs[jid]   # dict order == submission order
        while len(self._pruned) > MAX_PRUNED_STATES:
            self._pruned.popitem(last=False)

    def cancel(self, job_id: str) -> Job | None:
        """Cancel a job: queued/waiting -> terminal CANCELLED immediately
        (dependents cancel by cascade); running -> set its token (the
        work loops unwind at their poison points). Returns the job, or
        None when unknown."""
        with self._nonempty:
            job = self._jobs.get(job_id)
            if job is None:
                return None
            job.token.cancel()
            if job.state == QUEUED:
                self._cancel_locked(job)
                self._update_depth_locked()
                self._nonempty.notify_all()
            return job

    def close(self) -> list[Job]:
        """Stop accepting; cancel everything still QUEUED — runnable and
        dependency-waiting alike (drain keeps the RUNNING jobs). Returns
        the jobs cancelled off the queue."""
        with self._nonempty:
            self._closed = True
            doomed = [self._jobs[i] for i in
                      [*self._order, *self._waiting]]
            self._order.clear()
            self._waiting.clear()
            for job in doomed:
                job.token.cancel()
                job.state = CANCELLED
                job.finished_at = time.time()
                _metrics.counter("bst_serve_jobs_completed_total",
                                 status=CANCELLED).inc()
            _DEPTH.set(0)
            self._nonempty.notify_all()
            return doomed

    def share_runtime(self) -> dict[str, float]:
        with self._lock:
            return dict(self._share_runtime)

"""Non-rigid fusion kernel: per-view control-point deformation grids applied
during resample + blend (XLA).

Role of ``NonRigidTools.fuseVirtualInterpolatedNonRigid`` called at
SparkNonRigidFusion.java:388-402: each view carries a regular grid of control
points (spacing ``cpd``, default 10 px) whose per-vertex affine models are
fitted host-side from corresponding interest points (moving-least-squares
with inverse-distance weights, alpha=1.0); the kernel trilinearly interpolates
the 12 model coefficients across the grid per output voxel, deforms the world
coordinate into the view's world frame, then applies the view's static
world->patch affine and samples exactly like the affine-fusion kernel.

TPU design: the deformation is a dense vector-valued trilinear interpolation
(8 gathers of 12-float vertex records) fused by XLA into the sampling kernel;
all shapes static (grid dims bucketed per block), views vmapped, padding
masked — one compile serves every block with the same bucket.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .fusion import _blend_weight, _combine_views, _trilinear_sample


def _trilinear_vec(grid: jnp.ndarray, pts: jnp.ndarray) -> jnp.ndarray:
    """Sample a vector-valued grid (Gx,Gy,Gz,C) at (N,3) float coords
    (grid units); clamped at the edges. Returns (N,C)."""
    gx, gy, gz, C = grid.shape
    p0 = jnp.floor(pts)
    f = pts - p0
    p0 = p0.astype(jnp.int32)
    x0 = jnp.clip(p0[:, 0], 0, gx - 1)
    y0 = jnp.clip(p0[:, 1], 0, gy - 1)
    z0 = jnp.clip(p0[:, 2], 0, gz - 1)
    x1 = jnp.clip(p0[:, 0] + 1, 0, gx - 1)
    y1 = jnp.clip(p0[:, 1] + 1, 0, gy - 1)
    z1 = jnp.clip(p0[:, 2] + 1, 0, gz - 1)
    flat = grid.reshape(-1, C)
    syz = gy * gz

    def g(xi, yi, zi):
        return jnp.take(flat, xi * syz + yi * gz + zi, axis=0)

    fx = f[:, 0:1]
    fy = f[:, 1:2]
    fz = f[:, 2:3]
    return (
        g(x0, y0, z0) * (1 - fx) * (1 - fy) * (1 - fz)
        + g(x1, y0, z0) * fx * (1 - fy) * (1 - fz)
        + g(x0, y1, z0) * (1 - fx) * fy * (1 - fz)
        + g(x1, y1, z0) * fx * fy * (1 - fz)
        + g(x0, y0, z1) * (1 - fx) * (1 - fy) * fz
        + g(x1, y0, z1) * fx * (1 - fy) * fz
        + g(x0, y1, z1) * (1 - fx) * fy * fz
        + g(x1, y1, z1) * fx * fy * fz
    )


def _sample_one_view_nonrigid(
    patch, grid, view_affine, patch_offset, img_dim, border, blend_range,
    block_origin, grid_origin, grid_spacing, block_shape,
):
    """Per view: deform world coords by the interpolated control-point model,
    map into patch coords, sample + blend. Returns (val, inside, w_blend).

    The control-grid interpolation is SEPARABLE: output voxels form a regular
    lattice, so their grid coordinates are affine per axis and the trilinear
    interpolation of the (Gx,Gy,Gz,12) vertex models is the tensor product of
    three 1-D interpolation matrices — three small GEMMs (MXU work) instead
    of 8×12 gathers per voxel. Only the final patch sampling gathers (its
    coordinates are data-dependent through the deformation)."""
    from .fusion import _separable_interp_matrix

    L = block_shape
    so = grid  # (Gx,Gy,Gz,12)
    for d in range(3):
        pos = (block_origin[d] + jnp.arange(L[d], dtype=jnp.float32)
               - grid_origin[d]) / grid_spacing[d]
        m = _separable_interp_matrix(pos, grid.shape[d])
        so = jnp.tensordot(so, m, axes=[[0], [1]])
    A = so.reshape(3, 4, *L)  # per-voxel affine coefficients
    wx = block_origin[0] + jnp.arange(L[0], dtype=jnp.float32)[:, None, None]
    wy = block_origin[1] + jnp.arange(L[1], dtype=jnp.float32)[None, :, None]
    wz = block_origin[2] + jnp.arange(L[2], dtype=jnp.float32)[None, None, :]
    deformed = [A[i, 0] * wx + A[i, 1] * wy + A[i, 2] * wz + A[i, 3]
                for i in range(3)]
    p = jnp.stack([
        (view_affine[i, 0] * deformed[0] + view_affine[i, 1] * deformed[1]
         + view_affine[i, 2] * deformed[2] + view_affine[i, 3]).ravel()
        for i in range(3)
    ], axis=-1)  # (N,3) patch coords
    val = _trilinear_sample(patch, p)
    lpos = p + patch_offset
    inside = jnp.all(
        (lpos >= 0.0) & (lpos <= img_dim - 1.0), axis=-1
    ).astype(jnp.float32)
    w_blend = _blend_weight(lpos, img_dim, border, blend_range)
    return val, inside, w_blend


def nonrigid_fuse_block_impl(
    patches: jnp.ndarray,        # (V, Px,Py,Pz) float32
    grids: jnp.ndarray,          # (V, Gx,Gy,Gz, 12) float32 vertex models
    view_affines: jnp.ndarray,   # (V, 3, 4) view-world -> patch coords
    patch_offsets: jnp.ndarray,  # (V, 3) patch origin in level coords
    img_dims: jnp.ndarray,       # (V, 3)
    borders: jnp.ndarray,        # (V, 3)
    blend_ranges: jnp.ndarray,   # (V, 3)
    valid: jnp.ndarray,          # (V,)
    block_origin: jnp.ndarray,   # (3,) world coords of output voxel (0,0,0)
    grid_origin: jnp.ndarray,    # (3,) world coords of grid vertex (0,0,0)
    grid_spacing: jnp.ndarray,   # (3,) cpd
    block_shape: tuple[int, int, int],
    fusion_type: str = "AVG_BLEND",
):
    """Fuse one output block under per-view non-rigid deformation.
    Returns (fused, weight-sum) blocks."""
    patches = patches.astype(jnp.float32)  # lossless transport downcast
    def one(*args):
        return _sample_one_view_nonrigid(*args, block_shape=block_shape)

    vals, insides, wblends = jax.vmap(
        one, in_axes=(0, 0, 0, 0, 0, 0, 0, None, None, None),
    )(patches, grids, view_affines, patch_offsets, img_dims, borders,
      blend_ranges, block_origin, grid_origin, grid_spacing)
    fused, wsum = _combine_views(vals, insides, wblends, valid, fusion_type)
    return fused.reshape(block_shape), wsum.reshape(block_shape)


nonrigid_fuse_block = jax.jit(
    nonrigid_fuse_block_impl, static_argnames=("block_shape", "fusion_type")
)


# ---------------------------------------------------------------------------
# host-side control-grid fitting (moving least squares, IDW weights)
# ---------------------------------------------------------------------------

def fit_control_grid(
    targets: np.ndarray,         # (M,3) averaged world positions of unique IPs
    view_world: np.ndarray,      # (M,3) same IPs in this view's world frame
    grid_origin: np.ndarray,     # (3,)
    grid_dims: tuple[int, int, int],
    spacing: float,
    alpha: float = 1.0,
    reg_eps: float = 1e-6,
) -> np.ndarray:
    """Per-vertex affine models mapping target-world -> view-world.

    Weighted least squares per vertex with inverse-distance weights
    w_i = 1/(d^alpha + eps) (the MLS/IDW scheme of NonRigidTools, alpha=1.0,
    SparkNonRigidFusion.java:373-402). Falls back to the global affine (or
    translation) fit when points are scarce. Returns (Gx,Gy,Gz,12) float32.
    """
    gx, gy, gz = grid_dims
    G = gx * gy * gz
    m = len(targets)
    idx = np.indices((gx, gy, gz)).reshape(3, -1).T  # (G,3)
    verts = grid_origin + idx * spacing

    out = np.zeros((G, 3, 4))
    out[:, :, :3] = np.eye(3)
    if m == 0:
        return out.reshape(gx, gy, gz, 12).astype(np.float32)
    if m < 4:
        # translation-only fallback: mean displacement
        t = (view_world - targets).mean(axis=0)
        out[:, :, 3] = t
        return out.reshape(gx, gy, gz, 12).astype(np.float32)

    d = np.linalg.norm(verts[:, None, :] - targets[None, :, :], axis=2)  # (G,M)
    w = 1.0 / (d**alpha + 0.5)

    # solve in vertex-centered coordinates (both sides), which keeps the
    # normal equations well-conditioned and makes the tiny identity
    # regularizer scale-free: fit maps (p - vert) -> (q - vert)
    pc = targets[None, :, :] - verts[:, None, :]          # (G,M,3)
    qc = view_world[None, :, :] - verts[:, None, :]
    ph = np.concatenate([pc, np.ones((G, m, 1))], axis=2)  # (G,M,4)
    A = np.einsum("gm,gmi,gmj->gij", w, ph, ph)
    B = np.einsum("gm,gmi,gmk->gik", w, ph, qc)
    lam = reg_eps * w.sum(axis=1)[:, None, None]
    x_id = np.zeros((4, 3))
    x_id[:3, :3] = np.eye(3)
    sol = np.linalg.solve(A + lam * np.eye(4), B + lam * x_id)  # (G,4,3)
    lin = np.swapaxes(sol[:, :3, :], 1, 2)                # (G,3,3)
    t = sol[:, 3, :] + verts - np.einsum("gij,gj->gi", lin, verts)
    out[:, :, :3] = lin
    out[:, :, 3] = t
    return out.reshape(gx, gy, gz, 12).astype(np.float32)

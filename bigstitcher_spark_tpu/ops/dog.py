"""Difference-of-Gaussians blob detection kernel (XLA).

Reference equivalent: ``DoGImgLib2.computeDoG`` called from
SparkInterestPointDetection.java:552-568 — two Gaussian blurs (sigma,
sigma*k), subtraction, 3x3x3 extrema, threshold, quadratic subpixel fit,
with the image normalized to [0,1] via min/maxIntensity.

TPU design: the blurs are separable 1-D passes (banded-Toeplitz GEMMs on
the MXU, or one FFT transfer-function product on CPU), the normalization
is folded into the response scale (the min offset cancels in the kernel
difference), extrema detection is a separable shifted-slice 3^3
max/min compared against the response — all dense, static
shapes, fused by XLA and vmapped over a batch of equally-shaped blocks.
Detections leave the device as a boolean mask + response volume; the sparse
tail (argwhere + 3-D quadratic refinement) runs on host where dynamic point
counts are natural.

Constants follow mpicbg's classic scale-space setup: k = 2^(1/4), response
scaled by 1/(k-1) so thresholds are comparable to the reference's defaults
(sigma=1.8, threshold=0.008).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

DOG_K = float(2.0 ** (1.0 / 4.0))


def gaussian_kernel_1d(sigma: float) -> np.ndarray:
    """Normalized 1-D Gaussian, radius 3*sigma (imglib2 Gauss3-style support)."""
    r = max(1, int(np.ceil(3.0 * float(sigma))))
    x = np.arange(-r, r + 1, dtype=np.float64)
    k = np.exp(-(x**2) / (2.0 * float(sigma) ** 2))
    return (k / k.sum()).astype(np.float32)


def dog_halo(sigma: float) -> int:
    """Halo needed so core+1-ring response values are padding-free: the larger
    blur radius plus one voxel for the extremum neighborhood."""
    r2 = max(1, int(np.ceil(3.0 * float(sigma) * DOG_K)))
    return r2 + 1


@functools.lru_cache(maxsize=64)
def _toeplitz_band(n: int, kernel_bytes: bytes) -> np.ndarray:
    """(n, n + 2r) banded Toeplitz matrix applying a 1-D kernel along an
    axis of length n (rows select the VALID window of a padded axis)."""
    k = np.frombuffer(kernel_bytes, np.float32)
    m = np.zeros((n, n + k.size - 1), np.float32)
    for i in range(n):
        m[i, i:i + k.size] = k
    return m


def _blur_separable(x: jnp.ndarray, kernels) -> jnp.ndarray:
    """Separable 3-D Gaussian blur of an (X,Y,Z) volume with mirror extension
    (imglib2's extended-image semantics — no zero-padding edge responses).

    Each 1-D pass is a banded-Toeplitz MATMUL rather than a conv: the MXU is
    where TPU FLOPs live, and XLA:CPU's conv lowering is ~60x slower than its
    GEMM for these shapes (measured) — same math to float rounding."""
    for ax, k in enumerate(kernels):
        n = x.shape[ax]
        r = k.size // 2
        m = jnp.asarray(_toeplitz_band(int(n), np.asarray(k, np.float32)
                                       .tobytes()))
        xp = jnp.pad(x, [(r, r) if d == ax else (0, 0) for d in range(3)],
                     mode="reflect")
        x = jnp.moveaxis(
            jnp.tensordot(m, jnp.moveaxis(xp, ax, 0), axes=[[1], [0]]), 0, ax)
    return x


@functools.lru_cache(maxsize=64)
def _dog_transfer(shape: tuple, s1_bytes: bytes, s2_bytes: bytes):
    """Fourier transfer function of (G_s1 - G_s2) for an (X,Y,Z) grid:
    per-axis DFTs of the SAME truncated, normalized discrete kernels the
    Toeplitz path applies (so core responses agree to float rounding), as
    a separable outer product on the rfftn grid. Real-valued (kernels are
    even)."""
    k1 = np.frombuffer(s1_bytes, np.float32).astype(np.float64)
    k2 = np.frombuffer(s2_bytes, np.float32).astype(np.float64)

    def axis_hat(k, n, half):
        r = k.size // 2
        pad = np.zeros(n)
        pad[: r + 1] = k[r:]
        pad[n - r:] = k[:r]
        h = np.fft.rfft(pad) if half else np.fft.fft(pad)
        return np.real(h)

    hx1 = axis_hat(k1, shape[0], False)
    hy1 = axis_hat(k1, shape[1], False)
    hz1 = axis_hat(k1, shape[2], True)
    hx2 = axis_hat(k2, shape[0], False)
    hy2 = axis_hat(k2, shape[1], False)
    hz2 = axis_hat(k2, shape[2], True)
    H = (hx1[:, None, None] * hy1[None, :, None] * hz1[None, None, :]
         - hx2[:, None, None] * hy2[None, :, None] * hz2[None, None, :])
    return H.astype(np.float32)


def _dog_response_fft(x: jnp.ndarray, k1, k2) -> jnp.ndarray:
    """(G_s1 - G_s2) * x via one rfftn + one transfer multiply + one irfftn
    (circular edges; blocks carry halo >= the blur radius, so core values
    are edge-mode-independent). ~an order of magnitude fewer FLOPs than the
    two banded-matmul blur chains — the better trade on XLA:CPU, where GEMM
    throughput is the bottleneck rather than the MXU being free."""
    H = jnp.asarray(_dog_transfer(
        tuple(int(s) for s in x.shape),
        np.asarray(k1, np.float32).tobytes(),
        np.asarray(k2, np.float32).tobytes()))
    f = jnp.fft.rfftn(x)
    return jnp.fft.irfftn(f * H, s=x.shape).astype(jnp.float32)


def _blur_strategy() -> str:
    """'fft' on CPU, 'gemm' (Toeplitz matmuls on the MXU) elsewhere;
    BST_DOG_BLUR=fft|gemm overrides. Read at trace time — fixed per process."""
    from .. import config

    mode = config.get_str("BST_DOG_BLUR")
    if mode == "auto":
        return "fft" if jax.default_backend() == "cpu" else "gemm"
    return mode


def _window_extremum3(x: jnp.ndarray, op, fill) -> jnp.ndarray:
    """3x3x3 windowed max/min as three separable shifted-slice passes
    (2 elementwise ops per axis) — identical to ``reduce_window`` with SAME
    padding, but pure elementwise work instead of the generic window
    reduction, which lowers poorly on XLA:CPU and adds nothing on TPU."""
    for ax in range(3):
        xp = jnp.pad(x, [(1, 1) if d == ax else (0, 0) for d in range(3)],
                     constant_values=fill)
        n = x.shape[ax]
        x = op(op(lax.slice_in_dim(xp, 0, n, axis=ax),
                  lax.slice_in_dim(xp, 1, n + 1, axis=ax)),
               lax.slice_in_dim(xp, 2, n + 2, axis=ax))
    return x


def _tiebreak(shape, origin) -> jnp.ndarray:
    """Tiny deterministic per-voxel offset hashed from ABSOLUTE coordinates
    (block origin + local index), so plateau ties — e.g. a bead centered
    exactly between two voxels — resolve to exactly one detection, and
    identically so across block boundaries (halo consistency)."""
    ix = lax.broadcasted_iota(jnp.int32, shape, 0) + origin[0]
    iy = lax.broadcasted_iota(jnp.int32, shape, 1) + origin[1]
    iz = lax.broadcasted_iota(jnp.int32, shape, 2) + origin[2]
    h = (ix * 73856093 + iy * 19349663 + iz * 83492791) & 1023
    return h.astype(jnp.float32) * jnp.float32(2.0**-30)


@functools.partial(
    jax.jit, static_argnames=("sigma", "find_max", "find_min")
)
def dog_block(
    block: jnp.ndarray,
    min_intensity: jnp.ndarray,
    max_intensity: jnp.ndarray,
    threshold: jnp.ndarray,
    sigma: float,
    find_max: bool = True,
    find_min: bool = False,
    origin: jnp.ndarray | None = None,
):
    """DoG response + extrema mask for one (X,Y,Z) block.

    Returns (dog float32, mask bool). ``mask`` marks voxels that are a strict
    3x3x3 max of the response above ``threshold`` (or min below -threshold).
    The response equals DoG of the [0,1]-normalized input (DoGImgLib2
    normalization, SparkInterestPointDetection.java:552-568), with the
    1/(max-min) scale folded into the response instead of a separate
    normalization pass (the offset cancels; see inline comment).
    ``origin`` is the block's absolute voxel offset (for tie-breaking only).
    """
    x = block.astype(jnp.float32)
    s1 = float(sigma)
    s2 = float(sigma) * DOG_K
    k1 = gaussian_kernel_1d(s1)
    k2 = gaussian_kernel_1d(s2)
    if _blur_strategy() == "fft":
        diff = _dog_response_fft(x, k1, k2)
    else:
        diff = _blur_separable(x, [k1] * 3) - _blur_separable(x, [k2] * 3)
    # the [min,max]->[0,1] normalization (DoGImgLib2,
    # SparkInterestPointDetection.java:552-568) commutes with the DoG:
    # both blur kernels are normalized, so the constant offset cancels in
    # the difference and only the 1/(max-min) scale survives — folding it
    # into the response scale saves two full-volume passes over the input.
    # Degenerate max<=min (flat view, data-derived bounds): the old
    # normalization produced all-zero input => zero response; gate the
    # scale to 0 so blur roundoff is not amplified into fake detections
    inv_range = jnp.where(max_intensity > min_intensity,
                          1.0 / jnp.maximum(max_intensity - min_intensity,
                                            1e-20), 0.0)
    dog = diff * ((1.0 / (DOG_K - 1.0)) * inv_range)

    if origin is None:
        origin = jnp.zeros(3, jnp.int32)
    tb = _tiebreak(dog.shape, origin)
    mask = jnp.zeros(dog.shape, bool)
    if find_max:
        d = dog + tb
        mp = _window_extremum3(d, jnp.maximum, -jnp.inf)
        mask = mask | ((d >= mp) & (dog > threshold))
    if find_min:
        d = dog - tb
        mp = _window_extremum3(d, jnp.minimum, jnp.inf)
        mask = mask | ((d <= mp) & (dog < -threshold))
    return dog, mask


def dog_block_batch_impl(blocks, min_i, max_i, threshold, sigma,
                         find_max=True, find_min=False, origins=None):
    """vmapped ``dog_block`` over a leading batch axis (one compile serves
    every equally-shaped block of every view — strategy P3 of SURVEY §2.4).
    Un-jitted so the mesh layer can wrap it with batch-axis shardings."""
    if origins is None:
        origins = jnp.zeros((blocks.shape[0], 3), jnp.int32)
    return jax.vmap(
        lambda b, lo, hi, t, o: dog_block(b, lo, hi, t, sigma,
                                          find_max, find_min, o)
    )(blocks, min_i, max_i, threshold, origins)


dog_block_batch = functools.partial(
    jax.jit, static_argnames=("sigma", "find_max", "find_min")
)(dog_block_batch_impl)


# ---------------------------------------------------------------------------
# Compacted output: top-K candidates + on-device subpixel refinement.
#
# The dense (dog, mask) output costs two full volumes of D2H per block — on
# a wire-limited host link that dwarfs the compute. Detections are sparse
# (beads), so the TPU-idiomatic move is to compact on device: top-K extrema
# by |response|, the iterative 3-D quadratic refinement vectorized over the
# K candidates (fixed move count — no data-dependent control flow), and only
# (K,3)+(K,) scalars cross the boundary (~KB instead of ~MB).
# ---------------------------------------------------------------------------


def _gather3(dog_flat, p, shape):
    """dog values at clipped integer coords p (K,3) from the flat volume."""
    x = jnp.clip(p[:, 0], 0, shape[0] - 1)
    y = jnp.clip(p[:, 1], 0, shape[1] - 1)
    z = jnp.clip(p[:, 2], 0, shape[2] - 1)
    return jnp.take(dog_flat, (x * shape[1] + y) * shape[2] + z)


def _localize_quadratic_device(dog, p0, valid, max_moves: int = 4):
    """Vectorized device port of ``localize_quadratic``: central-difference
    gradient/Hessian, offset = -H^-1 g clipped to [-1,1]; bases that land
    past half-sample move one voxel and refit (fixed ``max_moves`` rounds)."""
    shape = dog.shape
    flat = dog.ravel()
    dims = jnp.array(shape, jnp.int32)
    p = p0.astype(jnp.int32)
    result = p.astype(jnp.float32)
    value = _gather3(flat, p, shape)
    active = valid

    eye = jnp.eye(3, dtype=jnp.int32)
    for _ in range(max_moves):
        ok = jnp.all((p >= 1) & (p <= dims - 2), axis=1)
        elig = active & ok
        c = _gather3(flat, p, shape)
        plus = [_gather3(flat, p + eye[d], shape) for d in range(3)]
        minus = [_gather3(flat, p - eye[d], shape) for d in range(3)]
        g = jnp.stack([0.5 * (plus[d] - minus[d]) for d in range(3)], axis=-1)
        diag = [plus[d] - 2.0 * c + minus[d] for d in range(3)]

        def cross(d, e):
            return 0.25 * (
                _gather3(flat, p + eye[d] + eye[e], shape)
                - _gather3(flat, p + eye[d] - eye[e], shape)
                - _gather3(flat, p - eye[d] + eye[e], shape)
                + _gather3(flat, p - eye[d] - eye[e], shape))

        # assemble by stacking (scatter-free; .at[:, d, e].set emits
        # per-row HLO scatters)
        hxy, hxz, hyz = cross(0, 1), cross(0, 2), cross(1, 2)
        H = jnp.stack([
            jnp.stack([diag[0], hxy, hxz], axis=-1),
            jnp.stack([hxy, diag[1], hyz], axis=-1),
            jnp.stack([hxz, hyz, diag[2]], axis=-1),
        ], axis=-2)
        det = jnp.linalg.det(H)
        det_ok = jnp.abs(det) > 1e-12
        Hsafe = jnp.where(det_ok[:, None, None], H,
                          jnp.eye(3, dtype=jnp.float32)[None])
        off = -jnp.linalg.solve(Hsafe, g[..., None])[..., 0]
        off = jnp.where(det_ok[:, None], jnp.clip(off, -1.0, 1.0), 0.0)
        upd = elig
        result = jnp.where(upd[:, None], p.astype(jnp.float32) + off, result)
        value = jnp.where(upd, c + 0.5 * jnp.sum(g * off, axis=-1), value)
        moved = jnp.abs(off) > 0.5
        needs = jnp.any(moved, axis=1) & det_ok & elig
        step = jnp.where(moved, jnp.sign(off).astype(jnp.int32), 0)
        p = jnp.where(needs[:, None], p + step, p)
        active = needs
    return result, value


def _pool_mean(x: jnp.ndarray, rel: tuple[int, int, int]) -> jnp.ndarray:
    """Average-pool by integer factors: the SHARED downsample kernel, traced
    inside the DoG program (a jitted fn called during tracing inlines into
    the same XLA computation), so the device pooling stays bit-identical to
    the host path's ``read_det_block`` pooling."""
    from .downsample import downsample_block

    return downsample_block(x, tuple(int(r) for r in rel))


def dog_block_topk_impl(block, min_i, max_i, threshold, origin, sigma,
                        find_max=True, find_min=False, k=2048, halo=0,
                        rel=(1, 1, 1)):
    """DoG + extrema + device-side subpixel, compacted to the K strongest
    candidates. Returns (idx (K,3) int32 base voxels, sub (K,3) float32
    subpixel coords, val (K,) refined response, valid (K,) bool,
    count () int32 total CORE extrema found — count > K means truncation).

    ``halo``: static halo width; extrema in the halo belong to neighboring
    blocks, so they are masked out BEFORE top-K — they must neither consume
    the K budget nor inflate the truncation count.

    ``rel``: residual downsampling factors applied ON DEVICE before
    everything else (openAndDownsample's in-memory averaging,
    SparkInterestPointDetection.java:1094-1114) — the block arrives at
    level resolution in its native dtype, so the wire carries uint16 and
    the pool/normalize/DoG chain is one fused program."""
    if any(int(r) != 1 for r in rel):
        block = _pool_mean(block, rel)
    dog, mask = dog_block(block, min_i, max_i, threshold, sigma,
                          find_max, find_min, origin)
    if halo > 0:
        # broadcasted-iota comparisons, NOT a full-volume .at[].set — the
        # latter lowers to an HLO scatter (a TPU serialization cliff)
        core = None
        for ax in range(3):
            i = lax.broadcasted_iota(jnp.int32, dog.shape, ax)
            m = (i >= halo) & (i < dog.shape[ax] - halo)
            core = m if core is None else (core & m)
        mask = mask & core
    k = int(min(k, int(np.prod(dog.shape))))
    score = jnp.where(mask, jnp.abs(dog), -jnp.inf).ravel()
    _, flat_idx = jax.lax.top_k(score, k)
    valid = jnp.take(score, flat_idx) > -jnp.inf
    sy, sz = dog.shape[1], dog.shape[2]
    idx = jnp.stack([flat_idx // (sy * sz), (flat_idx // sz) % sy,
                     flat_idx % sz], axis=-1).astype(jnp.int32)
    sub, val = _localize_quadratic_device(dog, idx, valid)
    count = mask.sum().astype(jnp.int32)
    return idx, sub, jnp.where(valid, val, 0.0), valid, count


def dog_block_topk_batch_impl(blocks, min_i, max_i, threshold, origins,
                              sigma, find_max=True, find_min=False, k=2048,
                              halo=0, rel=(1, 1, 1)):
    return jax.vmap(
        lambda b, lo, hi, t, o: dog_block_topk_impl(
            b, lo, hi, t, o, sigma, find_max, find_min, k, halo, rel)
    )(blocks, min_i, max_i, threshold, origins)


dog_block_topk_batch = functools.partial(
    jax.jit,
    static_argnames=("sigma", "find_max", "find_min", "k", "halo", "rel"),
)(dog_block_topk_batch_impl)


def dog_detect_extract_impl(block, min_i, max_i, threshold, origin, sigma,
                            find_max=True, find_min=False, k=2048, halo=0,
                            rel=(1, 1, 1), n_neighbors=3, redundancy=1,
                            rotation_invariant=True):
    """DoG detection + geometric descriptor extraction as ONE program:
    the K candidate peaks never leave HBM between top-K/subpixel and the
    kNN/frame math. Composes :func:`dog_block_topk_impl` with
    ops.descriptors.block_descriptors_impl on the block-LOCAL subpixel
    coords (descriptors are pure neighbor offsets, hence translation
    invariant — adding the block origin later cannot change them).
    Returns the topk 5-tuple plus (desc, dvalid)."""
    from .descriptors import block_descriptors_impl

    idx, sub, val, valid, count = dog_block_topk_impl(
        block, min_i, max_i, threshold, origin, sigma, find_max, find_min,
        k, halo, rel)
    desc, dvalid = block_descriptors_impl(
        sub, valid, n_neighbors, redundancy, rotation_invariant)
    return idx, sub, val, valid, count, desc, dvalid


def dog_detect_extract_batch_impl(blocks, min_i, max_i, threshold, origins,
                                  sigma, find_max=True, find_min=False,
                                  k=2048, halo=0, rel=(1, 1, 1),
                                  n_neighbors=3, redundancy=1,
                                  rotation_invariant=True):
    return jax.vmap(
        lambda b, lo, hi, t, o: dog_detect_extract_impl(
            b, lo, hi, t, o, sigma, find_max, find_min, k, halo, rel,
            n_neighbors, redundancy, rotation_invariant)
    )(blocks, min_i, max_i, threshold, origins)


dog_detect_extract_batch = functools.partial(
    jax.jit,
    static_argnames=("sigma", "find_max", "find_min", "k", "halo", "rel",
                     "n_neighbors", "redundancy", "rotation_invariant"),
)(dog_detect_extract_batch_impl)


def localize_quadratic(
    dog: np.ndarray, coords: np.ndarray, max_moves: int = 4
) -> tuple[np.ndarray, np.ndarray]:
    """3-D quadratic subpixel refinement of integer extrema (host-side).

    Fits the local paraboloid via central differences: offset = -H^{-1} g;
    if any |offset_d| > 0.5 the base voxel moves one step and the fit repeats
    (imglib2 SubpixelLocalization behavior, up to ``max_moves``).
    Returns (subpixel coords (N,3) float64, refined values (N,)).
    """
    if len(coords) == 0:
        return np.zeros((0, 3)), np.zeros(0)
    p = np.asarray(coords, np.int64).copy()
    shape = np.array(dog.shape)
    result = p.astype(np.float64)
    value = dog[tuple(p.T)].astype(np.float64)
    active = np.ones(len(p), bool)
    for _ in range(max_moves):
        idx = np.where(active)[0]
        if idx.size == 0:
            break
        q = p[idx]
        ok = np.all((q >= 1) & (q <= shape - 2), axis=1)
        idx = idx[ok]
        if idx.size == 0:
            break
        q = p[idx]
        g = np.empty((len(q), 3))
        H = np.empty((len(q), 3, 3))
        c = dog[tuple(q.T)].astype(np.float64)
        plus, minus = [], []
        for d in range(3):
            e = np.zeros(3, np.int64)
            e[d] = 1
            plus.append(dog[tuple((q + e).T)].astype(np.float64))
            minus.append(dog[tuple((q - e).T)].astype(np.float64))
            g[:, d] = 0.5 * (plus[d] - minus[d])
            H[:, d, d] = plus[d] - 2.0 * c + minus[d]
        for d in range(3):
            for e_ in range(d + 1, 3):
                ed = np.zeros(3, np.int64)
                ee = np.zeros(3, np.int64)
                ed[d] = 1
                ee[e_] = 1
                v = 0.25 * (
                    dog[tuple((q + ed + ee).T)] - dog[tuple((q + ed - ee).T)]
                    - dog[tuple((q - ed + ee).T)] + dog[tuple((q - ed - ee).T)]
                ).astype(np.float64)
                H[:, d, e_] = v
                H[:, e_, d] = v
        det_ok = np.abs(np.linalg.det(H)) > 1e-12
        off = np.zeros((len(q), 3))
        if det_ok.any():
            off[det_ok] = -np.linalg.solve(H[det_ok], g[det_ok][..., None])[..., 0]
        off = np.clip(off, -1.0, 1.0)
        # keep this fit as the current best answer; a base move only refits
        # (never discards), so an oscillating half-sample tie still converges
        result[idx] = q + off
        value[idx] = c + 0.5 * np.einsum("ij,ij->i", g, off)
        moved = np.abs(off) > 0.5
        needs_move = moved.any(axis=1) & det_ok
        active[:] = False
        active[idx[needs_move]] = True
        step = np.where(moved, np.sign(off).astype(np.int64), 0)
        p[idx[needs_move]] += step[needs_move]
    return result, value


def sample_trilinear(vol: np.ndarray, points: np.ndarray) -> np.ndarray:
    """n-linear interpolation of ``vol`` at float ``points`` (N,3) (host-side;
    the reference samples detection intensities the same way,
    SparkInterestPointDetection.java:581-606)."""
    if len(points) == 0:
        return np.zeros(0)
    p = np.asarray(points, np.float64)
    lo = np.clip(np.floor(p).astype(np.int64), 0,
                 np.array(vol.shape) - 2)
    f = np.clip(p - lo, 0.0, 1.0)
    out = np.zeros(len(p))
    for corner in range(8):
        d = np.array([(corner >> i) & 1 for i in range(3)])
        w = np.prod(np.where(d, f, 1.0 - f), axis=1)
        out += w * vol[tuple((lo + d).T)].astype(np.float64)
    return out

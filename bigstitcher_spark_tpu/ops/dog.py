"""Difference-of-Gaussians blob detection kernel (XLA).

Reference equivalent: ``DoGImgLib2.computeDoG`` called from
SparkInterestPointDetection.java:552-568 — two Gaussian blurs (sigma,
sigma*k), subtraction, 3x3x3 extrema, threshold, quadratic subpixel fit,
with the image normalized to [0,1] via min/maxIntensity.

TPU design: the blurs are separable 1-D convolutions (three
``conv_general_dilated`` passes), extrema detection is a 3^3
``reduce_window`` max/min compared against the response — all dense, static
shapes, fused by XLA and vmapped over a batch of equally-shaped blocks.
Detections leave the device as a boolean mask + response volume; the sparse
tail (argwhere + 3-D quadratic refinement) runs on host where dynamic point
counts are natural.

Constants follow mpicbg's classic scale-space setup: k = 2^(1/4), response
scaled by 1/(k-1) so thresholds are comparable to the reference's defaults
(sigma=1.8, threshold=0.008).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

DOG_K = float(2.0 ** (1.0 / 4.0))


def gaussian_kernel_1d(sigma: float) -> np.ndarray:
    """Normalized 1-D Gaussian, radius 3*sigma (imglib2 Gauss3-style support)."""
    r = max(1, int(np.ceil(3.0 * float(sigma))))
    x = np.arange(-r, r + 1, dtype=np.float64)
    k = np.exp(-(x**2) / (2.0 * float(sigma) ** 2))
    return (k / k.sum()).astype(np.float32)


def dog_halo(sigma: float) -> int:
    """Halo needed so core+1-ring response values are padding-free: the larger
    blur radius plus one voxel for the extremum neighborhood."""
    r2 = max(1, int(np.ceil(3.0 * float(sigma) * DOG_K)))
    return r2 + 1


def _blur_separable(x: jnp.ndarray, kernels) -> jnp.ndarray:
    """Separable 3-D Gaussian blur of an (X,Y,Z) volume with mirror extension
    (imglib2's extended-image semantics — no zero-padding edge responses)."""
    pads = [(k.size // 2, k.size // 2) for k in kernels]
    x = jnp.pad(x, pads, mode="reflect")
    v = x[None, None]  # NC XYZ
    dn = lax.conv_dimension_numbers(v.shape, (1, 1, 1, 1, 1),
                                    ("NCDHW", "OIDHW", "NCDHW"))
    for axis, k in enumerate(kernels):
        kshape = [1, 1, 1, 1, 1]
        kshape[2 + axis] = k.size
        v = lax.conv_general_dilated(
            v, jnp.asarray(k).reshape(kshape), (1, 1, 1), "VALID",
            dimension_numbers=dn,
        )
    return v[0, 0]


def _tiebreak(shape, origin) -> jnp.ndarray:
    """Tiny deterministic per-voxel offset hashed from ABSOLUTE coordinates
    (block origin + local index), so plateau ties — e.g. a bead centered
    exactly between two voxels — resolve to exactly one detection, and
    identically so across block boundaries (halo consistency)."""
    ix = lax.broadcasted_iota(jnp.int32, shape, 0) + origin[0]
    iy = lax.broadcasted_iota(jnp.int32, shape, 1) + origin[1]
    iz = lax.broadcasted_iota(jnp.int32, shape, 2) + origin[2]
    h = (ix * 73856093 + iy * 19349663 + iz * 83492791) & 1023
    return h.astype(jnp.float32) * jnp.float32(2.0**-30)


@functools.partial(
    jax.jit, static_argnames=("sigma", "find_max", "find_min")
)
def dog_block(
    block: jnp.ndarray,
    min_intensity: jnp.ndarray,
    max_intensity: jnp.ndarray,
    threshold: jnp.ndarray,
    sigma: float,
    find_max: bool = True,
    find_min: bool = False,
    origin: jnp.ndarray | None = None,
):
    """DoG response + extrema mask for one (X,Y,Z) block.

    Returns (dog float32, mask bool). ``mask`` marks voxels that are a strict
    3x3x3 max of the response above ``threshold`` (or min below -threshold).
    Input is normalized to [0,1] by min/max intensity first
    (DoGImgLib2 normalization, SparkInterestPointDetection.java:552-568).
    ``origin`` is the block's absolute voxel offset (for tie-breaking only).
    """
    x = block.astype(jnp.float32)
    x = (x - min_intensity) / jnp.maximum(max_intensity - min_intensity, 1e-20)
    s1 = float(sigma)
    s2 = float(sigma) * DOG_K
    k1 = [gaussian_kernel_1d(s1)] * 3
    k2 = [gaussian_kernel_1d(s2)] * 3
    g1 = _blur_separable(x, k1)
    g2 = _blur_separable(x, k2)
    dog = (g1 - g2) * (1.0 / (DOG_K - 1.0))

    if origin is None:
        origin = jnp.zeros(3, jnp.int32)
    tb = _tiebreak(dog.shape, origin)
    mask = jnp.zeros(dog.shape, bool)
    window = (3, 3, 3)
    if find_max:
        d = dog + tb
        mp = lax.reduce_window(d, -jnp.inf, lax.max, window, (1, 1, 1), "SAME")
        mask = mask | ((d >= mp) & (dog > threshold))
    if find_min:
        d = dog - tb
        mp = lax.reduce_window(d, jnp.inf, lax.min, window, (1, 1, 1), "SAME")
        mask = mask | ((d <= mp) & (dog < -threshold))
    return dog, mask


def dog_block_batch_impl(blocks, min_i, max_i, threshold, sigma,
                         find_max=True, find_min=False, origins=None):
    """vmapped ``dog_block`` over a leading batch axis (one compile serves
    every equally-shaped block of every view — strategy P3 of SURVEY §2.4).
    Un-jitted so the mesh layer can wrap it with batch-axis shardings."""
    if origins is None:
        origins = jnp.zeros((blocks.shape[0], 3), jnp.int32)
    return jax.vmap(
        lambda b, lo, hi, t, o: dog_block(b, lo, hi, t, sigma,
                                          find_max, find_min, o)
    )(blocks, min_i, max_i, threshold, origins)


dog_block_batch = functools.partial(
    jax.jit, static_argnames=("sigma", "find_max", "find_min")
)(dog_block_batch_impl)


def localize_quadratic(
    dog: np.ndarray, coords: np.ndarray, max_moves: int = 4
) -> tuple[np.ndarray, np.ndarray]:
    """3-D quadratic subpixel refinement of integer extrema (host-side).

    Fits the local paraboloid via central differences: offset = -H^{-1} g;
    if any |offset_d| > 0.5 the base voxel moves one step and the fit repeats
    (imglib2 SubpixelLocalization behavior, up to ``max_moves``).
    Returns (subpixel coords (N,3) float64, refined values (N,)).
    """
    if len(coords) == 0:
        return np.zeros((0, 3)), np.zeros(0)
    p = np.asarray(coords, np.int64).copy()
    shape = np.array(dog.shape)
    result = p.astype(np.float64)
    value = dog[tuple(p.T)].astype(np.float64)
    active = np.ones(len(p), bool)
    for _ in range(max_moves):
        idx = np.where(active)[0]
        if idx.size == 0:
            break
        q = p[idx]
        ok = np.all((q >= 1) & (q <= shape - 2), axis=1)
        idx = idx[ok]
        if idx.size == 0:
            break
        q = p[idx]
        g = np.empty((len(q), 3))
        H = np.empty((len(q), 3, 3))
        c = dog[tuple(q.T)].astype(np.float64)
        plus, minus = [], []
        for d in range(3):
            e = np.zeros(3, np.int64)
            e[d] = 1
            plus.append(dog[tuple((q + e).T)].astype(np.float64))
            minus.append(dog[tuple((q - e).T)].astype(np.float64))
            g[:, d] = 0.5 * (plus[d] - minus[d])
            H[:, d, d] = plus[d] - 2.0 * c + minus[d]
        for d in range(3):
            for e_ in range(d + 1, 3):
                ed = np.zeros(3, np.int64)
                ee = np.zeros(3, np.int64)
                ed[d] = 1
                ee[e_] = 1
                v = 0.25 * (
                    dog[tuple((q + ed + ee).T)] - dog[tuple((q + ed - ee).T)]
                    - dog[tuple((q - ed + ee).T)] + dog[tuple((q - ed - ee).T)]
                ).astype(np.float64)
                H[:, d, e_] = v
                H[:, e_, d] = v
        det_ok = np.abs(np.linalg.det(H)) > 1e-12
        off = np.zeros((len(q), 3))
        if det_ok.any():
            off[det_ok] = -np.linalg.solve(H[det_ok], g[det_ok][..., None])[..., 0]
        off = np.clip(off, -1.0, 1.0)
        # keep this fit as the current best answer; a base move only refits
        # (never discards), so an oscillating half-sample tie still converges
        result[idx] = q + off
        value[idx] = c + 0.5 * np.einsum("ij,ij->i", g, off)
        moved = np.abs(off) > 0.5
        needs_move = moved.any(axis=1) & det_ok
        active[:] = False
        active[idx[needs_move]] = True
        step = np.where(moved, np.sign(off).astype(np.int64), 0)
        p[idx[needs_move]] += step[needs_move]
    return result, value


def sample_trilinear(vol: np.ndarray, points: np.ndarray) -> np.ndarray:
    """n-linear interpolation of ``vol`` at float ``points`` (N,3) (host-side;
    the reference samples detection intensities the same way,
    SparkInterestPointDetection.java:581-606)."""
    if len(points) == 0:
        return np.zeros(0)
    p = np.asarray(points, np.float64)
    lo = np.clip(np.floor(p).astype(np.int64), 0,
                 np.array(vol.shape) - 2)
    f = np.clip(p - lo, 0.0, 1.0)
    out = np.zeros(len(p))
    for corner in range(8):
        d = np.array([(corner >> i) & 1 for i in range(3)])
        w = np.prod(np.where(d, f, 1.0 - f), axis=1)
        out += w * vol[tuple((lo + d).T)].astype(np.float64)
    return out

"""Affine-fusion XLA kernel: resample + blend all views into an output block.

TPU-native re-design of the reference's core fusion pipeline
(``BlkAffineFusion.initWithIntensityCoefficients``, SparkAffineFusion.java:602-615):
for each output block, every overlapping view is inverse-affine resampled
(tri-linear) out of a host-prefetched source patch, weighted with a cosine
ramp at the image borders (FusionType AVG_BLEND), accumulated, and normalized.
One fused XLA computation per (block shape, patch bucket, view bucket) — all
shapes static, no data-dependent control flow; views are a vmapped leading
axis and invalid/padded views are masked, so a single compile serves every
block with the same bucket.

Fusion types (reference enum use at SparkAffineFusion.java:124-125):
AVG, AVG_BLEND, MAX_INTENSITY, FIRST_WINS (lowest view wins),
LAST_WINS (highest view wins).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

FUSION_TYPES = ("AVG", "AVG_BLEND", "MAX_INTENSITY", "FIRST_WINS", "LAST_WINS")


def block_coords(block_shape: Sequence[int]) -> jnp.ndarray:
    """(N,3) float32 local voxel indices of a block, N = prod(shape)."""
    bx, by, bz = block_shape
    gx, gy, gz = jnp.meshgrid(
        jnp.arange(bx, dtype=jnp.float32),
        jnp.arange(by, dtype=jnp.float32),
        jnp.arange(bz, dtype=jnp.float32),
        indexing="ij",
    )
    return jnp.stack([gx.ravel(), gy.ravel(), gz.ravel()], axis=-1)


def _trilinear_sample(patch: jnp.ndarray, pts: jnp.ndarray) -> jnp.ndarray:
    """Sample one (Px,Py,Pz) patch at (N,3) float coords; clamped at edges."""
    px, py, pz = patch.shape
    p0 = jnp.floor(pts)
    f = pts - p0
    p0 = p0.astype(jnp.int32)
    x0 = jnp.clip(p0[:, 0], 0, px - 1)
    y0 = jnp.clip(p0[:, 1], 0, py - 1)
    z0 = jnp.clip(p0[:, 2], 0, pz - 1)
    x1 = jnp.clip(p0[:, 0] + 1, 0, px - 1)
    y1 = jnp.clip(p0[:, 1] + 1, 0, py - 1)
    z1 = jnp.clip(p0[:, 2] + 1, 0, pz - 1)
    flat = patch.ravel()
    syz = py * pz

    def g(xi, yi, zi):
        return jnp.take(flat, xi * syz + yi * pz + zi)

    fx, fy, fz = f[:, 0], f[:, 1], f[:, 2]
    c000 = g(x0, y0, z0) * (1 - fx) * (1 - fy) * (1 - fz)
    c100 = g(x1, y0, z0) * fx * (1 - fy) * (1 - fz)
    c010 = g(x0, y1, z0) * (1 - fx) * fy * (1 - fz)
    c110 = g(x1, y1, z0) * fx * fy * (1 - fz)
    c001 = g(x0, y0, z1) * (1 - fx) * (1 - fy) * fz
    c101 = g(x1, y0, z1) * fx * (1 - fy) * fz
    c011 = g(x0, y1, z1) * (1 - fx) * fy * fz
    c111 = g(x1, y1, z1) * fx * fy * fz
    return c000 + c100 + c010 + c110 + c001 + c101 + c011 + c111


def _blend_weight(
    lpos: jnp.ndarray, img_dim: jnp.ndarray, border: jnp.ndarray,
    blend_range: jnp.ndarray,
) -> jnp.ndarray:
    """Cosine border-ramp blending weight at level-image coords lpos (N,3).

    Per dim: distance to the (border-offset) image edge; 0 outside, cosine
    ramp over ``blend_range`` px, 1 in the interior; total = product
    (mvrecon BlendingRealRandomAccess semantics)."""
    lo = border  # (3,)
    hi = img_dim - 1.0 - border
    d = jnp.minimum(lpos - lo, hi - lpos)  # (N,3) distance to nearest edge
    r = jnp.maximum(blend_range, 1e-6)
    ramp = 0.5 * (jnp.cos((1.0 - d / r) * jnp.pi) + 1.0)
    w = jnp.where(d < 0, 0.0, jnp.where(d < r, ramp, 1.0))
    return jnp.prod(w, axis=-1)


def _sample_one_view(patch, affine, patch_offset, img_dim, border, blend_range,
                     inside_off, coords, coeff=None, coeff_affine=None):
    """Per-view: transform block coords, sample, weight. Returns (val, w).

    ``inside_off`` expands (+) or shrinks (-) the image box used for the
    inside test — the reference's ``--maskOffset`` for masks mode
    (GenerateComputeBlockMasks, fusion/GenerateComputeBlockMasks.java:84-177).
    ``coeff`` (Cx,Cy,Cz,2): per-view intensity-correction grid [scale,offset]
    sampled at ``coeff_affine @ lpos`` — mvrecon Coefficients applied inside
    the fusion kernel (SparkAffineFusion.java:545-559)."""
    p = coords @ affine[:, :3].T + affine[:, 3]  # patch coords (N,3)
    val = _trilinear_sample(patch, p)
    lpos = p + patch_offset  # level-image coords
    if coeff is not None:
        from .nonrigid import _trilinear_vec

        g = lpos @ coeff_affine[:, :3].T + coeff_affine[:, 3]
        so = _trilinear_vec(coeff, g)
        val = so[:, 0] * val + so[:, 1]
    inside = jnp.all(
        (lpos >= -inside_off) & (lpos <= img_dim - 1.0 + inside_off), axis=-1
    ).astype(jnp.float32)
    w_blend = _blend_weight(lpos, img_dim, border, blend_range)
    return val, inside, w_blend


def fuse_block_impl(
    patches: jnp.ndarray,        # (V, Px, Py, Pz) float32
    affines: jnp.ndarray,        # (V, 3, 4) float32: block idx -> patch coords
    patch_offsets: jnp.ndarray,  # (V, 3) float32: patch origin in level coords
    img_dims: jnp.ndarray,       # (V, 3) float32
    borders: jnp.ndarray,        # (V, 3) float32
    blend_ranges: jnp.ndarray,   # (V, 3) float32
    valid: jnp.ndarray,          # (V,) float32 1/0 (padding mask)
    block_shape: tuple[int, int, int],
    fusion_type: str = "AVG_BLEND",
    inside_offs: jnp.ndarray | None = None,  # (V, 3) mask-offset expansion
    coeffs: jnp.ndarray | None = None,       # (V, Cx,Cy,Cz, 2) intensity maps
    coeff_affines: jnp.ndarray | None = None,  # (V, 3, 4) lpos -> grid coords
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fuse one output block. Returns (fused float32 block, weight-sum block).

    Weight-sum doubles as the coverage mask for ``--masks`` mode
    (GenerateComputeBlockMasks equivalent)."""
    # patches may arrive in their stored integer dtype (lossless transport
    # downcast — halves h2d bytes on wire-limited links); math is float32
    patches = patches.astype(jnp.float32)
    if inside_offs is None:
        inside_offs = jnp.zeros_like(borders)
    coords = block_coords(block_shape)
    if coeffs is None:
        vals, insides, wblends = jax.vmap(
            _sample_one_view, in_axes=(0, 0, 0, 0, 0, 0, 0, None)
        )(patches, affines, patch_offsets, img_dims, borders, blend_ranges,
          inside_offs, coords)
    else:
        vals, insides, wblends = jax.vmap(
            _sample_one_view, in_axes=(0, 0, 0, 0, 0, 0, 0, None, 0, 0)
        )(patches, affines, patch_offsets, img_dims, borders, blend_ranges,
          inside_offs, coords, coeffs, coeff_affines)
    fused, wsum = _combine_views(vals, insides, wblends, valid, fusion_type)
    return (fused.reshape(block_shape), wsum.reshape(block_shape))


fuse_block = jax.jit(
    fuse_block_impl, static_argnames=("block_shape", "fusion_type")
)


# ---------------------------------------------------------------------------
# Translation fast path: no gather at all.
#
# When a view's inverse affine has an identity linear part (the common case:
# translation-registered tiles, which is everything before/after a
# translation-model solve), sampling degenerates to EIGHT STATICALLY-SHIFTED
# SLICES of the patch with constant trilinear corner weights, and the blend
# weight is separable per axis. That is pure elementwise arithmetic — the
# shape XLA/TPU wants — instead of 8 random gathers per voxel. The host
# planner picks this kernel per block (models/affine_fusion.py).
# ---------------------------------------------------------------------------


def _axis_blend(lp0, n: int, dim, border, blend_range, inside_off=0.0):
    """1-D blend weight + inside mask along one axis, positions lp0+[0..n)."""
    pos = lp0 + jnp.arange(n, dtype=jnp.float32)
    lo = border
    hi = dim - 1.0 - border
    d = jnp.minimum(pos - lo, hi - pos)
    r = jnp.maximum(blend_range, 1e-6)
    ramp = 0.5 * (jnp.cos((1.0 - d / r) * jnp.pi) + 1.0)
    w = jnp.where(d < 0, 0.0, jnp.where(d < r, ramp, 1.0))
    inside = ((pos >= -inside_off) & (pos <= dim - 1.0 + inside_off)).astype(
        jnp.float32)
    return w, inside


def _one_view_shift(patch, frac, lpos0, img_dim, border, blend_range,
                    inside_off, block_shape):
    bx, by, bz = block_shape
    fx, fy, fz = frac[0], frac[1], frac[2]
    val = jnp.zeros(block_shape, jnp.float32)
    for cx in (0, 1):
        wxc = fx if cx else 1.0 - fx
        for cy in (0, 1):
            wyc = fy if cy else 1.0 - fy
            for cz in (0, 1):
                wzc = fz if cz else 1.0 - fz
                sl = jax.lax.slice(
                    patch, (cx, cy, cz), (cx + bx, cy + by, cz + bz)
                )
                val = val + (wxc * wyc * wzc) * sl
    wx, ix = _axis_blend(lpos0[0], bx, img_dim[0], border[0], blend_range[0],
                         inside_off[0])
    wy, iy = _axis_blend(lpos0[1], by, img_dim[1], border[1], blend_range[1],
                         inside_off[1])
    wz, iz = _axis_blend(lpos0[2], bz, img_dim[2], border[2], blend_range[2],
                         inside_off[2])
    blend = wx[:, None, None] * wy[None, :, None] * wz[None, None, :]
    inside = ix[:, None, None] * iy[None, :, None] * iz[None, None, :]
    return val, inside, blend


def _axis_blend_at(pos, dim, border, blend_range, inside_off=0.0):
    """1-D blend weight + inside mask at arbitrary float positions (the
    non-unit-step generalization of ``_axis_blend``)."""
    lo = border
    hi = dim - 1.0 - border
    d = jnp.minimum(pos - lo, hi - pos)
    r = jnp.maximum(blend_range, 1e-6)
    ramp = 0.5 * (jnp.cos((1.0 - d / r) * jnp.pi) + 1.0)
    w = jnp.where(d < 0, 0.0, jnp.where(d < r, ramp, 1.0))
    inside = ((pos >= -inside_off) & (pos <= dim - 1.0 + inside_off)).astype(
        jnp.float32)
    return w, inside


def _one_view_sep(patch, diag, t, patch_offset, img_dim, border, blend_range,
                  inside_off, block_shape):
    """One view with a DIAGONAL block->patch affine (axis-aligned scale +
    translation — e.g. translation-registered tiles under --preserveAnisotropy
    z-scaling): trilinear sampling factorizes into three 1-D interpolation
    matrix contractions (GEMMs), no gathers; blending stays separable."""
    L = block_shape
    so = patch
    ws, ins = [], []
    for d in range(3):
        pos = diag[d] * jnp.arange(L[d], dtype=jnp.float32) + t[d]
        m = _separable_interp_matrix(pos, patch.shape[d])
        so = jnp.tensordot(so, m, axes=[[0], [1]])
        lpos = pos + patch_offset[d]
        w, i = _axis_blend_at(lpos, img_dim[d], border[d], blend_range[d],
                              inside_off[d])
        ws.append(w)
        ins.append(i)
    blend = ws[0][:, None, None] * ws[1][None, :, None] * ws[2][None, None, :]
    inside = ins[0][:, None, None] * ins[1][None, :, None] * ins[2][None, None, :]
    return so, inside, blend


def fuse_block_sep_impl(
    patches: jnp.ndarray,       # (V, Px, Py, Pz) float32
    diags: jnp.ndarray,         # (V, 3) diagonal of the block->patch affine
    ts: jnp.ndarray,            # (V, 3) its translation
    patch_offsets: jnp.ndarray,  # (V, 3) patch origin in level coords
    img_dims: jnp.ndarray,      # (V, 3)
    borders: jnp.ndarray,       # (V, 3)
    blend_ranges: jnp.ndarray,  # (V, 3)
    valid: jnp.ndarray,         # (V,)
    block_shape: tuple[int, int, int],
    fusion_type: str = "AVG_BLEND",
    inside_offs: jnp.ndarray | None = None,
):
    if inside_offs is None:
        inside_offs = jnp.zeros_like(borders)
    patches = patches.astype(jnp.float32)  # lossless transport downcast

    def one(*args):
        return _one_view_sep(*args, block_shape=block_shape)

    vals, insides, wblends = jax.vmap(
        one, in_axes=(0, 0, 0, 0, 0, 0, 0, 0),
    )(patches, diags, ts, patch_offsets, img_dims, borders, blend_ranges,
      inside_offs)
    return _combine_views(vals, insides, wblends, valid, fusion_type)


fuse_block_sep = jax.jit(
    fuse_block_sep_impl, static_argnames=("block_shape", "fusion_type")
)


def _combine_views(vals, insides, wblends, valid, fusion_type: str):
    """Combine per-view samples (V, ...) by fusion type -> (fused, wsum)."""
    extra = (1,) * (vals.ndim - 1)
    vmask = valid.reshape(valid.shape + extra)
    if fusion_type == "AVG":
        w = insides * vmask
    elif fusion_type == "AVG_BLEND":
        w = insides * wblends * vmask
    elif fusion_type == "MAX_INTENSITY":
        w = insides * vmask
        fused = jnp.max(jnp.where(w > 0, vals, -jnp.inf), axis=0)
        wsum = jnp.sum(w, axis=0)
        return jnp.where(wsum > 0, fused, 0.0), wsum
    elif fusion_type in ("FIRST_WINS", "LAST_WINS"):
        inside = insides * vmask
        V = vals.shape[0]
        order = jnp.arange(V, dtype=jnp.float32).reshape((V,) + extra)
        if fusion_type == "FIRST_WINS":
            pick = jnp.where(inside > 0, order, jnp.inf)
            sel = jnp.argmin(pick, axis=0)
        else:
            pick = jnp.where(inside > 0, order, -jnp.inf)
            sel = jnp.argmax(pick, axis=0)
        fused = jnp.take_along_axis(vals, sel[None], axis=0)[0]
        wsum = jnp.sum(inside, axis=0)
        return jnp.where(wsum > 0, fused, 0.0), wsum
    else:
        raise ValueError(f"unknown fusion type {fusion_type}")
    wsum = jnp.sum(w, axis=0)
    acc = jnp.sum(vals * w, axis=0)
    fused = jnp.where(wsum > 0, acc / jnp.maximum(wsum, 1e-20), 0.0)
    return fused, wsum


def fuse_block_shift_impl(
    patches: jnp.ndarray,       # (V, bx+1, by+1, bz+1) float32
    fracs: jnp.ndarray,         # (V, 3) in [0,1)
    lpos0: jnp.ndarray,         # (V, 3) level coords of output voxel (0,0,0)
    img_dims: jnp.ndarray,      # (V, 3)
    borders: jnp.ndarray,       # (V, 3)
    blend_ranges: jnp.ndarray,  # (V, 3)
    valid: jnp.ndarray,         # (V,)
    block_shape: tuple[int, int, int],
    fusion_type: str = "AVG_BLEND",
    inside_offs: jnp.ndarray | None = None,  # (V, 3)
):
    if inside_offs is None:
        inside_offs = jnp.zeros_like(borders)
    patches = patches.astype(jnp.float32)  # lossless transport downcast
    vals, insides, wblends = jax.vmap(
        _one_view_shift, in_axes=(0, 0, 0, 0, 0, 0, 0, None)
    )(patches, fracs, lpos0, img_dims, borders, blend_ranges, inside_offs,
      block_shape)
    return _combine_views(vals, insides, wblends, valid, fusion_type)


fuse_block_shift = jax.jit(
    fuse_block_shift_impl, static_argnames=("block_shape", "fusion_type")
)


# ---------------------------------------------------------------------------
# Device-resident volume fusion: one dispatch per (channel, timepoint) volume.
#
# Host<->device transfers are the scarce resource (PCIe, or worse a tunnel);
# the per-block path moves every patch across it. Here the source tiles are
# uploaded ONCE as a uint16 stack living in HBM, a lax.scan walks the output
# block grid — per block: gather the K relevant tiles, dynamic-slice the
# needed window out of each, realign (roll) for out-of-range clamping, fuse
# with the shifted-slice kernel — and dynamic-update-slices into the output
# volume, which leaves the device exactly once, already converted to the
# output dtype. The scan carry is donated, so XLA updates in place.
# ---------------------------------------------------------------------------


def _realign(S: jnp.ndarray, delta: jnp.ndarray) -> jnp.ndarray:
    """patch[i] = S[(i + delta) mod n] per axis (wrapped entries are later
    masked by the inside test, so wrap garbage never contributes)."""
    for ax in range(3):
        n = S.shape[ax]
        shift = jnp.mod(delta[ax], n)
        S2 = jnp.concatenate([S, S], axis=ax)
        S = jax.lax.dynamic_slice_in_dim(S2, shift, n, axis=ax)
    return S


def _one_view_device(tile, floor_off, frac, lp0, img_dim, border, blend_range,
                     inside_off, block_shape):
    ps = tuple(s + 1 for s in block_shape)
    tshape = jnp.array(tile.shape, jnp.int32)
    lim = tshape - jnp.array(ps, jnp.int32)
    clamp = jnp.clip(floor_off, 0, lim)
    S = jax.lax.dynamic_slice(tile, tuple(clamp[d] for d in range(3)), ps)
    S = _realign(S, floor_off - clamp).astype(jnp.float32)
    return _one_view_shift(S, frac, lp0, img_dim, border, blend_range,
                           inside_off, block_shape)


def fuse_volume_scan_impl(
    tiles: jnp.ndarray,          # (V, tx, ty, tz) uint16/float32, HBM-resident
    view_idx: jnp.ndarray,       # (B, K) int32 into tiles
    floor_offs: jnp.ndarray,     # (B, K, 3) int32
    fracs: jnp.ndarray,          # (B, K, 3) float32
    lpos0: jnp.ndarray,          # (B, K, 3) float32
    img_dims: jnp.ndarray,       # (B, K, 3) float32 (true dims, pre-padding)
    borders: jnp.ndarray,        # (B, K, 3) float32
    blend_ranges: jnp.ndarray,   # (B, K, 3) float32
    valid: jnp.ndarray,          # (B, K) float32
    block_offsets: jnp.ndarray,  # (B, 3) int32 into the padded output volume
    min_i: jnp.ndarray,
    max_i: jnp.ndarray,
    out_shape: tuple[int, int, int],   # padded to block multiples
    block_shape: tuple[int, int, int],
    fusion_type: str = "AVG_BLEND",
    out_dtype: str = "float32",
    masks: bool = False,
    inside_offs: jnp.ndarray | None = None,  # (B, K, 3)
):
    if inside_offs is None:
        inside_offs = jnp.zeros_like(borders)

    def body(out, p):
        vidx, fo, fr, lp, dim, bo, rg, va, io, boff = p
        tiles_sel = jnp.take(tiles, vidx, axis=0)
        vals, insides, wblends = jax.vmap(
            _one_view_device, in_axes=(0, 0, 0, 0, 0, 0, 0, 0, None)
        )(tiles_sel, fo, fr, lp, dim, bo, rg, io, block_shape)
        fused, wsum = _combine_views(vals, insides, wblends, va, fusion_type)
        res = (wsum > 0).astype(jnp.float32) if masks else fused
        out = jax.lax.dynamic_update_slice(out, res, tuple(boff[d] for d in range(3)))
        return out, None

    out0 = jnp.zeros(out_shape, jnp.float32)
    out, _ = jax.lax.scan(
        body, out0,
        (view_idx, floor_offs, fracs, lpos0, img_dims, borders, blend_ranges,
         valid, inside_offs, block_offsets),
    )
    if masks:
        info_max = (1.0 if out_dtype == "float32"
                    else float(np.iinfo(np.dtype(out_dtype)).max))
        return (out * info_max).astype(np.dtype(out_dtype))
    return _convert_intensity_expr(out, min_i, max_i, out_dtype)


fuse_volume_scan = jax.jit(
    fuse_volume_scan_impl,
    static_argnames=("out_shape", "block_shape", "fusion_type", "out_dtype",
                     "masks"),
)


# ---------------------------------------------------------------------------
# Static composite translation fusion: the whole-volume device path, redesigned.
#
# The lax.scan device path (above) walks the block grid with dynamic slices —
# on TPU those force relayouts of unaligned windows and run two orders of
# magnitude below HBM speed. For translation-registered views the right XLA
# program has NO dynamic control flow at all: each view's tile occupies a
# statically-known output window (floor of its world offset), its sub-pixel
# fraction is a constant trilinear mix of EIGHT STATICALLY-SHIFTED tile
# slices, and its blend weight is a separable outer product of 1-D vectors.
# So the volume fuse compiles to a handful of pads, slices, and fused
# elementwise ops — pure bandwidth. One compile per (volume layout) key,
# cached; offsets are baked in as constants.
# ---------------------------------------------------------------------------


def _composite_one_view(P, frac, img_dim, border, blend_range, inside_off,
                        a, L, n, pad):
    """One view's contribution over its static output window.

    ``P``: tile padded by ``pad`` voxels on every side (so the 8 corner
    slices are always in-bounds, including windows widened by --maskOffset).
    ``a``/``L``/``n``: static window start, window length, and integer tile
    offset. Returns (val, inside, blend) of shape L."""
    fx, fy, fz = frac[0], frac[1], frac[2]
    val = jnp.zeros(L, jnp.float32)
    for cx in (0, 1):
        wxc = fx if cx else 1.0 - fx
        for cy in (0, 1):
            wyc = fy if cy else 1.0 - fy
            for cz in (0, 1):
                wzc = fz if cz else 1.0 - fz
                start = (a[0] + n[0] + pad[0] + cx, a[1] + n[1] + pad[1] + cy,
                         a[2] + n[2] + pad[2] + cz)
                sl = jax.lax.slice(
                    P, start, tuple(start[d] + L[d] for d in range(3)))
                val = val + (wxc * wyc * wzc) * sl
    ws, ins = [], []
    for d in range(3):
        pos = (a[d] + n[d]) + jnp.arange(L[d], dtype=jnp.float32) + frac[d]
        lo = border[d]
        hi = img_dim[d] - 1.0 - border[d]
        dd = jnp.minimum(pos - lo, hi - pos)
        r = jnp.maximum(blend_range[d], 1e-6)
        ramp = 0.5 * (jnp.cos((1.0 - dd / r) * jnp.pi) + 1.0)
        ws.append(jnp.where(dd < 0, 0.0, jnp.where(dd < r, ramp, 1.0)))
        ins.append(((pos >= -inside_off[d])
                    & (pos <= img_dim[d] - 1.0 + inside_off[d])
                    ).astype(jnp.float32))
    blend = ws[0][:, None, None] * ws[1][None, :, None] * ws[2][None, None, :]
    inside = ins[0][:, None, None] * ins[1][None, :, None] * ins[2][None, None, :]
    return val, inside, blend


def _composite_one_view_sep(P, diag, off, img_dim, border, blend_range,
                            inside_off, a, L, pad):
    """Diagonal-affine sibling of ``_composite_one_view``: sampling positions
    step by ``diag`` per output voxel, so the tile contribution is three 1-D
    interpolation matrix contractions (GEMMs) over the padded tile — no
    gathers, still a static window."""
    so = P
    ws, ins = [], []
    for d in range(3):
        pos = (diag[d] * (a[d] + jnp.arange(L[d], dtype=jnp.float32))
               + off[d])
        m = _separable_interp_matrix(pos + pad[d], P.shape[d])
        so = jnp.tensordot(so, m, axes=[[0], [1]])
        w, i = _axis_blend_at(pos, img_dim[d], border[d], blend_range[d],
                              inside_off[d])
        ws.append(w)
        ins.append(i)
    blend = ws[0][:, None, None] * ws[1][None, :, None] * ws[2][None, None, :]
    inside = ins[0][:, None, None] * ins[1][None, :, None] * ins[2][None, None, :]
    return so, inside, blend


def _separable_interp_matrix(pos, c: int):
    """(L, c) linear-interpolation matrix for 1-D grid coords ``pos`` (L,),
    edge-clamped: row i holds weights (1-f) at floor(pos_i), f at floor+1.
    Trilinear interpolation of a regular grid at separable coordinates is
    the tensor product of three of these (exact, no gathers)."""
    p = jnp.clip(pos, 0.0, float(c - 1))
    lo = jnp.clip(jnp.floor(p), 0, max(c - 2, 0)).astype(jnp.int32)
    f = p - lo
    cols = jnp.arange(c, dtype=jnp.int32)[None, :]
    return (jnp.where(cols == lo[:, None], 1.0 - f[:, None], 0.0)
            + jnp.where(cols == jnp.minimum(lo + 1, c - 1)[:, None],
                        f[:, None], 0.0))


@functools.lru_cache(maxsize=32)
def make_translation_composite(
    out_shape: tuple[int, int, int],
    windows: tuple,      # per-view ((a0,a1,a2), (b0,b1,b2)) static ints
    n_offs: tuple,       # per-view (3,) static int tile offsets (floor)
    pad: tuple = (1, 1, 1),  # per-axis tile pad (1 + ceil(maskOffset))
    fusion_type: str = "AVG_BLEND",
    out_dtype: str = "float32",
    masks: bool = False,
    with_coeffs: bool = False,
    kinds: tuple = (),   # per-view "shift" | "sep" ("" -> all shift)
):
    """Build + jit the composite fusion program for one volume layout.

    Returned fn(tiles, fracs, img_dims, borders, ranges, inside_offs,
    min_i, max_i[, diags, offs][, coeffs, coeff_affs]) -> converted output
    of ``out_shape``. ``tiles`` is a list of raw (unpadded) per-view tiles
    (any integer/float dtype). Views may mix two sampling kinds: "shift"
    (translation: 8 statically-shifted slices) and "sep" (diagonal affine:
    separable interpolation GEMMs) — ``diags``/``offs`` are consumed by the
    "sep" views. With ``with_coeffs``, per-view (Cx,Cy,Cz,2) intensity grids
    [scale, offset] are applied inside the kernel — trilinear over the
    window via separable interpolation matrices
    (BlkAffineFusion.initWithIntensityCoefficients role)."""
    V = len(windows)
    if not kinds:
        kinds = ("shift",) * V
    any_sep = any(k == "sep" for k in kinds)
    if with_coeffs and any_sep:
        # the in-kernel coefficient interpolation assumes unit-step lpos;
        # the planner routes coeffs+diagonal volumes to the per-block path
        raise ValueError("intensity coefficients with diagonal views are "
                         "handled by the per-block kernels")

    def impl(tiles, fracs, img_dims, borders, ranges, inside_offs, min_i,
             max_i, diags=None, offs=None, coeffs=None, coeff_affs=None):
        if fusion_type == "MAX_INTENSITY":
            acc = jnp.full(out_shape, -jnp.inf, jnp.float32)
        else:
            acc = jnp.zeros(out_shape, jnp.float32)
        wsum = jnp.zeros(out_shape, jnp.float32)
        order = range(V - 1, -1, -1) if fusion_type == "FIRST_WINS" else range(V)
        for v in order:
            (a, b), n = windows[v], n_offs[v]
            L = tuple(b[d] - a[d] for d in range(3))
            if any(s <= 0 for s in L):
                continue
            P = jnp.pad(tiles[v].astype(jnp.float32),
                        tuple((p, p) for p in pad))
            if kinds[v] == "sep":
                val, inside, blend = _composite_one_view_sep(
                    P, diags[v], offs[v], img_dims[v], borders[v], ranges[v],
                    inside_offs[v], a, L, pad)
            else:
                val, inside, blend = _composite_one_view(
                    P, fracs[v], img_dims[v], borders[v], ranges[v],
                    inside_offs[v], a, L, n, pad)
            if with_coeffs:
                # lpos over the window is separable; grid coords through the
                # diagonal coeff affine stay separable -> trilinear of the
                # (Cx,Cy,Cz,2) grid = 3 small tensordots, no gathers.
                # Each step contracts the leading C axis and appends L_d;
                # after 3 steps the layout is (2, L0, L1, L2).
                so = coeffs[v]
                for d in range(3):
                    lpos_d = ((a[d] + n[d])
                              + jnp.arange(L[d], dtype=jnp.float32)
                              + fracs[v][d])
                    gc = lpos_d * coeff_affs[v][d, d] + coeff_affs[v][d, 3]
                    m = _separable_interp_matrix(gc, so.shape[0])
                    so = jnp.tensordot(so, m, axes=[[0], [1]])
                val = so[0] * val + so[1]
            win = tuple(slice(a[d], b[d]) for d in range(3))

            # window updates as slice + combine + dynamic_update_slice with
            # STATIC starts: jnp's .at[win].add lowers to HLO scatter even
            # for static windows, and scatter is the classic TPU lowering
            # cliff (serialized, no vectorization); DUS stays a dense fused
            # update on every backend
            starts = tuple(int(a[d]) for d in range(3))

            def win_update(x, new_region):
                return jax.lax.dynamic_update_slice(x, new_region, starts)

            if fusion_type == "AVG":
                w = inside
            elif fusion_type == "AVG_BLEND":
                w = inside * blend
            elif fusion_type == "MAX_INTENSITY":
                acc = win_update(acc, jnp.maximum(
                    acc[win], jnp.where(inside > 0, val, -jnp.inf)))
                wsum = win_update(wsum, wsum[win] + inside)
                continue
            elif fusion_type in ("FIRST_WINS", "LAST_WINS"):
                acc = win_update(acc, jnp.where(inside > 0, val, acc[win]))
                wsum = win_update(wsum, wsum[win] + inside)
                continue
            else:
                raise ValueError(f"unknown fusion type {fusion_type}")
            acc = win_update(acc, acc[win] + val * w)
            wsum = win_update(wsum, wsum[win] + w)
        if fusion_type in ("MAX_INTENSITY", "FIRST_WINS", "LAST_WINS"):
            fused = jnp.where(wsum > 0, acc, 0.0)
        else:
            fused = jnp.where(wsum > 0, acc / jnp.maximum(wsum, 1e-20), 0.0)
        if masks:
            info_max = (1.0 if out_dtype == "float32"
                        else float(np.iinfo(np.dtype(out_dtype)).max))
            return ((wsum > 0).astype(jnp.float32) * info_max).astype(
                np.dtype(out_dtype))
        return _convert_intensity_expr(fused, min_i, max_i, out_dtype)

    return jax.jit(impl)


def _convert_intensity_expr(block, min_i, max_i, out_dtype: str):
    """Map [min,max] -> full integer range (uint8/uint16) or pass float through
    (reference type converters, SparkAffineFusion.java:497-517)."""
    if out_dtype == "float32":
        return block.astype(jnp.float32)
    info = np.iinfo(np.dtype(out_dtype))
    scaled = (block - min_i) / (max_i - min_i) * float(info.max)
    return jnp.clip(jnp.round(scaled), 0, info.max).astype(np.dtype(out_dtype))


convert_intensity = jax.jit(
    _convert_intensity_expr, static_argnames=("out_dtype",)
)


def bucket_shape(shape: Sequence[int], quantum: int = 32) -> tuple[int, ...]:
    """Round patch shapes up so recompiles are bounded (shape bucketing —
    the central TPU dynamic-shape mitigation, SURVEY.md §7)."""
    return tuple(int(np.ceil(max(int(s), 1) / quantum)) * quantum for s in shape)


def bucket_views(n: int) -> int:
    """Pad view count to the next power of two (>=1)."""
    return 1 << max(0, int(np.ceil(np.log2(max(n, 1)))))

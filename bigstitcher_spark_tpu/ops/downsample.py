"""2x downsampling kernels (XLA).

Reference equivalents: ``LazyHalfPixelDownsample2x`` (pyramid levels,
SparkDownsample.java:159-177, SparkResaveN5.java:370-386) and
``Downsample.simple2x`` / ``LazyDownsample2x`` (detection pre-downsampling,
SparkInterestPointDetection.java:1094-1114). Both average pairs along one
axis; the half-pixel variant pairs (2i, 2i+1) which together with the
(f-1)/2 mipmap offset keeps coordinates consistent across levels.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("factors",))
def downsample_block(block: jnp.ndarray, factors: tuple[int, ...]) -> jnp.ndarray:
    """Average-downsample a block by integer ``factors`` per axis.

    Input extent must be an exact multiple of ``factors`` (drivers read
    out_size*factor source voxels, which level dims guarantee in-bounds)."""
    x = block.astype(jnp.float32)
    for d, f in enumerate(factors):
        f = int(f)
        if f == 1:
            continue
        shape = list(x.shape)
        shape[d] = shape[d] // f
        shape.insert(d + 1, f)
        x = x.reshape(shape).mean(axis=d + 1)
    return x


@functools.partial(jax.jit, static_argnames=("axis",))
def halfpixel_downsample2x_axis(x: jnp.ndarray, axis: int) -> jnp.ndarray:
    """One chained 2x half-pixel step along ``axis`` (out[i]=(in[2i]+in[2i+1])/2)."""
    n = x.shape[axis] // 2
    sl0 = [slice(None)] * x.ndim
    sl1 = [slice(None)] * x.ndim
    sl0[axis] = slice(0, 2 * n, 2)
    sl1[axis] = slice(1, 2 * n, 2)
    return 0.5 * (x[tuple(sl0)].astype(jnp.float32) + x[tuple(sl1)].astype(jnp.float32))

"""2x downsampling kernels (XLA).

Reference equivalents: ``LazyHalfPixelDownsample2x`` (pyramid levels,
SparkDownsample.java:159-177, SparkResaveN5.java:370-386) and
``Downsample.simple2x`` / ``LazyDownsample2x`` (detection pre-downsampling,
SparkInterestPointDetection.java:1094-1114). Both average pairs along one
axis; the half-pixel variant pairs (2i, 2i+1) which together with the
(f-1)/2 mipmap offset keeps coordinates consistent across levels.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("factors",))
def downsample_block(block: jnp.ndarray, factors: tuple[int, ...]) -> jnp.ndarray:
    """Average-downsample a block by integer ``factors`` per axis.

    Input extent must be an exact multiple of ``factors`` (drivers read
    out_size*factor source voxels, which level dims guarantee in-bounds)."""
    x = block.astype(jnp.float32)
    for d, f in enumerate(factors):
        f = int(f)
        if f == 1:
            continue
        shape = list(x.shape)
        shape[d] = shape[d] // f
        shape.insert(d + 1, f)
        x = x.reshape(shape).mean(axis=d + 1)
    return x


def convert_storage(x: jnp.ndarray, out_dtype: str) -> jnp.ndarray:
    """Round/clip a float result to the storage dtype — the traced twin of
    the downsample drivers' host-side conversion (``np.clip(np.round(x),
    lo, hi).astype``) so epilogue-produced pyramid levels match the
    container-reread path bit for bit."""
    dt = np.dtype(out_dtype)
    if np.issubdtype(dt, np.integer):
        info = np.iinfo(dt)
        x = jnp.clip(jnp.round(x), info.min, info.max)
    return x.astype(dt)


@functools.partial(jax.jit, static_argnames=("factors", "dims", "out_dtype"))
def downsample_level(prev: jnp.ndarray, factors: tuple[int, ...],
                     dims: tuple[int, ...], out_dtype: str) -> jnp.ndarray:
    """One pyramid level from the previous level's STORED-dtype array,
    while it is still device-resident (the fusion multiscale epilogue).

    Reproduces the container-reread path (``read_padded`` +
    :func:`downsample_block` + host round/clip) exactly: the reduction
    extent is ``dims * factors`` — trailing source voxels beyond it are
    dropped (level dims floor-divide), and axes thinner than one window
    are edge-replicated, the ``read_padded`` rule — then a float32 mean
    per window and a round/clip back to the storage dtype. Chaining
    levels through the storage dtype between steps keeps them
    bit-identical to levels computed by re-reading the stored previous
    level from the container."""
    needed = tuple(int(d) * int(f) for d, f in zip(dims, factors))
    x = prev[tuple(slice(0, min(n, int(s)))
                   for n, s in zip(needed, prev.shape))]
    pad = tuple((0, n - min(n, int(s)))
                for n, s in zip(needed, prev.shape))
    if any(p for _, p in pad):
        x = jnp.pad(x, pad, mode="edge")
    return convert_storage(
        downsample_block(x, tuple(int(f) for f in factors)), out_dtype)


@functools.partial(jax.jit, static_argnames=("axis",))
def halfpixel_downsample2x_axis(x: jnp.ndarray, axis: int) -> jnp.ndarray:
    """One chained 2x half-pixel step along ``axis`` (out[i]=(in[2i]+in[2i+1])/2)."""
    n = x.shape[axis] // 2
    sl0 = [slice(None)] * x.ndim
    sl1 = [slice(None)] * x.ndim
    sl0[axis] = slice(0, 2 * n, 2)
    sl1[axis] = slice(1, 2 * n, 2)
    return 0.5 * (x[tuple(sl0)].astype(jnp.float32) + x[tuple(sl1)].astype(jnp.float32))

"""Intensity correction kernels: per-cell linear-map RANSAC matching and the
global coefficient solve.

Role of mvrecon ``IntensityCorrection.{matchRansac, matchHistograms, solve}``
used at SparkIntensityMatching.java:171-183 and IntensitySolver.java:116-118:
every view carries a coarse coefficient grid (default 8x8x8 cells); between
two overlapping views, co-located intensity samples are collected per cell
pair and a 1-D linear model i_B ~= a*i_A + b is RANSAC-fitted per cell pair;
the global solve then finds per-cell (scale, offset) maps minimizing
disagreement over all matched pairs, regularized toward identity.

TPU design: cell-pair matches are a vmapped hypothesis-parallel RANSAC over
a padded (pairs, samples) batch — one compile per bucket; the global solve
is a Jacobi/conjugate-gradient pass over the quadratic form assembled from
per-match sufficient statistics, all dense vectorized numpy (the system is
tiny: 2 unknowns per cell).
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import profiling
from ..observe import metrics as _metrics


# --------------------------------------------------------------------------
# pairwise matching
# --------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("iterations",))
def _linefit_ransac_kernel(x, y, valid, key, epsilon, iterations):
    """Batched over leading axis P: RANSAC a 1-D linear model y ~= a*x + b.

    x,y: (P,N); valid: (P,N); returns (a (P,), b (P,), n_inliers (P,)).
    """
    P, N = x.shape

    def per_pair(xp_, yp, vp, k):
        idx = jax.random.randint(k, (iterations, 2), 0, N)
        x0 = xp_[idx[:, 0]]
        x1 = xp_[idx[:, 1]]
        y0 = yp[idx[:, 0]]
        y1 = yp[idx[:, 1]]
        dx = x1 - x0
        a = jnp.where(jnp.abs(dx) > 1e-6, (y1 - y0) / jnp.where(
            jnp.abs(dx) > 1e-6, dx, 1.0), 1.0)
        b = y0 - a * x0
        err = jnp.abs(yp[None, :] - (a[:, None] * xp_[None, :] + b[:, None]))
        inl = (err < epsilon) & (vp[None, :] > 0)
        counts = inl.sum(-1)
        best = jnp.argmax(counts)
        w = inl[best].astype(jnp.float32)
        # weighted least-squares refit on the best consensus set
        sw = jnp.maximum(w.sum(), 1e-6)
        mx = (w * xp_).sum() / sw
        my = (w * yp).sum() / sw
        cov = (w * (xp_ - mx) * (yp - my)).sum()
        var = jnp.maximum((w * (xp_ - mx) ** 2).sum(), 1e-12)
        a_f = cov / var
        b_f = my - a_f * mx
        return a_f, b_f, counts[best]

    keys = jax.random.split(key, P)
    return jax.vmap(per_pair)(x, y, valid, keys)


def match_cells_ransac(
    samples_a: list[np.ndarray],
    samples_b: list[np.ndarray],
    epsilon: float = 0.1,
    min_inliers: int = 10,
    iterations: int = 1000,
    seed: int = 5,
) -> list[tuple[float, float, int] | None]:
    """RANSAC linear fits for a list of cell-pair sample sets.

    samples_a[i], samples_b[i]: (N_i,) co-located intensities. Sample counts
    are padded to the max bucket so ONE kernel serves the whole list. Entries
    with < min_inliers consensus return None (IntensityCorrection.matchRansac
    role; epsilon is relative to the intensity range).
    """
    P = len(samples_a)
    if P == 0:
        return []
    n = max(8, 1 << int(np.ceil(np.log2(max(max(len(s) for s in samples_a), 2)))))
    x = np.zeros((P, n), np.float32)
    y = np.zeros((P, n), np.float32)
    v = np.zeros((P, n), np.float32)
    for i, (sa, sb) in enumerate(zip(samples_a, samples_b)):
        m = len(sa)
        x[i, :m] = sa
        y[i, :m] = sb
        v[i, :m] = 1.0
    a, b, cnt = _linefit_ransac_kernel(
        jnp.asarray(x), jnp.asarray(y), jnp.asarray(v),
        jax.random.PRNGKey(seed), jnp.float32(epsilon), int(iterations),
    )
    a, b, cnt = np.asarray(a), np.asarray(b), np.asarray(cnt)
    out = []
    for i in range(P):
        if cnt[i] >= min_inliers and len(samples_a[i]) >= 2:
            out.append((float(a[i]), float(b[i]), int(cnt[i])))
        else:
            out.append(None)
    return out


def match_cells_histogram(
    samples_a: list[np.ndarray], samples_b: list[np.ndarray],
    min_samples: int = 10,
) -> list[tuple[float, float, int] | None]:
    """Histogram-alignment alternative (IntensityCorrection.matchHistograms
    role): fit the linear map aligning the two sample distributions by their
    robust quantiles."""
    out = []
    for sa, sb in zip(samples_a, samples_b):
        if len(sa) < min_samples:
            out.append(None)
            continue
        qa = np.quantile(sa, [0.1, 0.9])
        qb = np.quantile(sb, [0.1, 0.9])
        if qa[1] - qa[0] < 1e-9:
            out.append(None)
            continue
        a = (qb[1] - qb[0]) / (qa[1] - qa[0])
        b = qb[0] - a * qa[0]
        out.append((float(a), float(b), len(sa)))
    return out


# --------------------------------------------------------------------------
# global solve
# --------------------------------------------------------------------------

def solve_intensity_coefficients(
    n_cells: int,
    matches: list[tuple[int, int, float, float, float, float, float]],
    lam: float = 0.1,
    smooth_pairs: list[tuple[int, int]] | None = None,
    smooth_weight: float = 0.5,
    backend: str | None = None,
    on_device_solution=None,
) -> np.ndarray:
    """Global least squares over the coefficient graph.

    ``on_device_solution``: optional callback handed the solver's
    DEVICE-resident solution vector before the host fetch (device backend
    only) — the solve→fusion residency hook: models.intensity reshapes it
    on device and registers the result with the fusion coefficient-table
    cache so the grids never re-cross H2D.

    Unknowns: per cell c a map f_c(i) = s_c*i + o_c (2*n_cells unknowns,
    cells indexed globally over all views). Each match contributes, for its
    sample set {(x_k, y_k)} between cells (ca, cb), the residuals
    f_ca(x_k) - f_cb(y_k) — passed in as sufficient statistics
    (ca, cb, n, Sx, Sy, Sxx, Syy_plus_Sxy...) — see ``match_stats``.
    Regularized toward identity with weight ``lam`` per cell
    (IntensityCorrection.solve role). ``smooth_pairs`` adds an intra-view
    smoothness term tying ADJACENT cells of one view together, which
    propagates corrections into cells that have no overlap matches (weighted
    by the mean data moments so it is scale-free).
    Returns (n_cells, 2) [scale, offset].

    ``backend`` picks the solve: ``"device"`` (default via
    ``BST_SOLVE_DEVICE``) runs a matrix-free conjugate-gradient iteration
    over the match rows in one compiled device loop (ops/solve.py) —
    above ``BST_SOLVE_SHARD`` rows the rows shard across local devices
    and each CG matvec reduces with psum; ``"numpy"`` assembles the dense
    (2C, 2C) normal equations and solves directly (the reference path the
    CG agrees with to ≤1e-6, documented in tests/test_solve_device.py).
    """
    # quadratic form: min Σ_m Σ_k (s_a x_k + o_a - s_b y_k - o_b)^2
    #               + Σ_c lam_c ((s_c-1)^2) + mu_c o_c^2
    # The data term is HOMOGENEOUS (scaling all maps jointly shrinks it), so
    # the identity regularizer must be weighted by each cell's own data
    # moments (lam_c = lam * Σ x², mu_c = lam * Σ n) — scale-free, and the
    # gauge collapse toward s=0 is resisted in proportion to the data.
    smooth_arr = (np.asarray(smooth_pairs, int).reshape(-1, 2)
                  if smooth_pairs is not None and len(smooth_pairs)
                  else np.zeros((0, 2), int))
    cell_xx = np.full(n_cells, 1e-12)
    cell_n = np.full(n_cells, 1e-12)
    rows = (np.asarray(matches, np.float64).reshape(-1, 8) if matches
            else np.zeros((0, 8)))
    ca_all = rows[:, 0].astype(int)
    cb_all = rows[:, 1].astype(int)
    np.add.at(cell_xx, ca_all, rows[:, 5])
    np.add.at(cell_n, ca_all, rows[:, 2])
    np.add.at(cell_xx, cb_all, rows[:, 6])
    np.add.at(cell_n, cb_all, rows[:, 2])
    idx = np.arange(n_cells)
    lam_eff = max(lam, 1e-6)  # unmatched cells must still solve to identity
    wxx = wn = 0.0
    if len(smooth_arr):
        wxx = smooth_weight * max(float(np.mean(cell_xx[cell_xx > 1e-6]))
                                  if (cell_xx > 1e-6).any() else 1.0, 1.0)
        wn = smooth_weight * max(float(np.mean(cell_n[cell_n > 1e-6]))
                                 if (cell_n > 1e-6).any() else 1.0, 1.0)
    from . import solve as _dsolve

    backend = _dsolve.resolve_backend(backend)
    if backend == "device" and len(rows):
        return _solve_coefficients_device(
            n_cells, rows, lam_eff, cell_xx, cell_n, smooth_arr, wxx, wn,
            on_device_solution)

    A = np.zeros((2 * n_cells, 2 * n_cells))
    rhs = np.zeros(2 * n_cells)
    A[2 * idx, 2 * idx] += lam_eff * np.maximum(cell_xx, 1.0)
    A[2 * idx + 1, 2 * idx + 1] += lam_eff * np.maximum(cell_n, 1.0)
    rhs[2 * idx] += lam_eff * np.maximum(cell_xx, 1.0)
    if len(smooth_arr):
        for ci, cj in smooth_arr:
            for off, w in ((0, wxx), (1, wn)):
                i, j = 2 * ci + off, 2 * cj + off
                A[i, i] += w
                A[j, j] += w
                A[i, j] -= w
                A[j, i] -= w
    for ca, cb, n, sx, sy, sxx, syy, sxy in matches:
        ia, ib = 2 * ca, 2 * cb
        # d/ds_a: Σ x_k (s_a x_k + o_a - s_b y_k - o_b)
        A[ia, ia] += sxx
        A[ia, ia + 1] += sx
        A[ia, ib] -= sxy
        A[ia, ib + 1] -= sx
        # d/do_a
        A[ia + 1, ia] += sx
        A[ia + 1, ia + 1] += n
        A[ia + 1, ib] -= sy
        A[ia + 1, ib + 1] -= n
        # d/ds_b: -Σ y_k (...)
        A[ib, ia] -= sxy
        A[ib, ia + 1] -= sy
        A[ib, ib] += syy
        A[ib, ib + 1] += sy
        # d/do_b
        A[ib + 1, ia] -= sx
        A[ib + 1, ia + 1] -= n
        A[ib + 1, ib] += sy
        A[ib + 1, ib + 1] += n
    sol = np.linalg.solve(A, rhs)
    return sol.reshape(n_cells, 2)


def _solve_coefficients_device(n_cells, rows, lam_eff, cell_xx, cell_n,
                               smooth_arr, wxx, wn,
                               on_device_solution=None) -> np.ndarray:
    """Device CG path of :func:`solve_intensity_coefficients`: same
    regularizer/smoothness assembly, matrix-free matvec over the match
    rows inside one compiled while_loop (sharded + psum-reduced above
    BST_SOLVE_SHARD rows)."""
    from . import solve as _dsolve

    diag = np.zeros(2 * n_cells)
    diag[0::2] = lam_eff * np.maximum(cell_xx, 1.0)
    diag[1::2] = lam_eff * np.maximum(cell_n, 1.0)
    rhs = np.zeros(2 * n_cells)
    rhs[0::2] = diag[0::2]
    # per-component flattened smoothness pairs: scale rows tie 2c indices
    # with weight wxx, offset rows 2c+1 with wn
    if len(smooth_arr):
        sidx = np.concatenate([2 * smooth_arr, 2 * smooth_arr + 1])
        sw = np.concatenate([np.full(len(smooth_arr), wxx),
                             np.full(len(smooth_arr), wn)])
    else:
        sidx = np.zeros((0, 2), int)
        sw = np.zeros(0)
    n_shards, global_mesh = _dsolve.solve_layout(len(rows))
    # build + XLA-compile outside the timed span (cold-bucket builds must
    # not pollute the device-ms counter); the bucket record derives from
    # the SAME shape math the factory key uses
    _dsolve.ensure_cg_compiled(n_cells, len(rows), len(sidx), n_shards,
                               global_mesh)
    t0 = time.perf_counter()
    with profiling.span("solve.relax", stage="intensity", item=len(rows)):
        out = _dsolve.solve_intensity_device(
            n_cells, rows, diag, rhs, sidx, sw, n_shards, global_mesh)
    _metrics.counter("bst_solve_device_ms_total", stage="intensity").inc(
        (time.perf_counter() - t0) * 1000.0)
    if on_device_solution is not None:
        on_device_solution(out[0])  # device vector, pre-fetch
    with profiling.span("solve.reduce", stage="intensity"):
        sol, iters = jax.device_get(out)
    _metrics.counter("bst_solve_iterations_total", stage="intensity").inc(
        int(iters))
    return np.asarray(sol)[: 2 * n_cells].reshape(n_cells, 2)


def match_stats(x: np.ndarray, y: np.ndarray) -> tuple[float, ...]:
    """Sufficient statistics (n, Sx, Sy, Sxx, Syy, Sxy) of a sample pair set."""
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    return (float(len(x)), float(x.sum()), float(y.sum()),
            float((x * x).sum()), float((y * y).sum()), float((x * y).sum()))

"""Device-side global solvers: jit-compiled tile relaxation and the
intensity coefficient solve, with collective reduction over the mesh.

The affine solver and intensity solve were the last stages that kept the
reference's Spark shape — driver-side collect/reduce with host numpy
iterating every link and tile per sweep (ROADMAP Open item 4). This module
ports the iterative global optimization onto the device:

* :func:`relax_on_device` runs the whole mpicbg-style Jacobi relaxation —
  ``_apply_batch`` → segment moments → batched model fits → damped update →
  mean error → convergence test — as ONE ``lax.while_loop`` inside one
  compiled function. The host uploads the flattened link arrays once and
  sees only the final models, the error history and the per-link errors;
  zero per-iteration host transfers.
* Above ``BST_SOLVE_SHARD`` point rows, the same loop runs under
  ``shard_map`` over a 1-D mesh of the local devices: per-shard segment
  moments are computed where the rows live and reduced with ``lax.psum``
  each sweep — the JAMPI barrier-mode collective pattern (arXiv
  2007.01811). Rows are grouped by OWNER TILE (tiles placed cost-weighted
  by the caller), so every tile's moments are accumulated entirely on one
  device in the single-device row order and the psum only adds exact
  zeros from the other shards — single-device and sharded solves are
  bitwise identical, not merely close.
* :func:`solve_intensity_device` replaces the dense ``(2C, 2C)`` normal
  equations of the intensity solve with a matrix-free conjugate-gradient
  iteration over (optionally sharded) match rows: the quadratic form is
  applied via gather/segment-sum per CG step, psum-reduced across shards,
  so the memory footprint is O(matches + cells) instead of O(cells²).

All solver math runs in float64 under a scoped ``enable_x64`` so the
device path tracks the numpy reference to its convergence thresholds
(documented tolerance ≤ 1e-6; in practice ~1e-12 relative): the graph is
tiny next to the voxel stages, and the iteration-count/convergence parity
matters more than f32 throughput here.

Numerical parity with :mod:`models.solver`'s numpy path is the contract —
the per-iteration math mirrors ``_segment_moments`` / ``_fit_from_moments``
/ ``_mean_error`` exactly, including the mpicbg convergence state
(maxError / plateau / stall / maxIterations). Padding rows carry weight
0.0 and padded tiles solve to identity, so bucketed shapes (pow2 rows /
tiles / links — the fusion compile-bucket discipline) never perturb the
result and repeated solves of similar graphs hit warm compiled fns. A
dropped link is a zeroed entry in the ``link_mask`` argument: re-solving
after ``solve_iterative`` drops a link re-enters the SAME compiled fn.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import enable_x64
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import config
from . import models as M

# the 1-D mesh axis the sharded reduction psums over
SOLVE_AXIS = "links"

_EPS_FIT = 1e-9


def bucket(n: int, minimum: int = 8) -> int:
    """Next power-of-two shape bucket (≥ ``minimum``) — the same padding
    discipline as the fusion/RANSAC compile buckets, so repeated solves of
    similar-sized graphs reuse the jitted fn instead of re-tracing."""
    n = max(int(n), minimum)
    return 1 << int(np.ceil(np.log2(n)))


def resolve_backend(explicit: str | None = None) -> str:
    """``device`` (the compiled solvers here, the default) or ``numpy``
    (the host reference paths): an explicit request wins, else the
    ``BST_SOLVE_DEVICE`` knob. The single owner of that policy — the
    affine solver and the intensity solve must never drift apart on it."""
    if explicit:
        return explicit.lower()
    return "device" if config.get_bool("BST_SOLVE_DEVICE") else "numpy"


def global_enabled() -> bool:
    """Whether the sharded solve mesh spans ALL processes' devices
    (``BST_SOLVE_GLOBAL``): ``auto`` follows the jax world (>1 process),
    ``1`` forces the global layout (single-process worlds then span just
    the local devices — the 'virtual' global mesh the parity tests use),
    ``0`` pins the mesh to local devices."""
    mode = config.get_str("BST_SOLVE_GLOBAL") or "auto"
    if mode == "1":
        return True
    if mode == "0":
        return False
    return jax.process_count() > 1


def solve_layout(n_rows: int) -> tuple[int, bool]:
    """``(n_shards, global_mesh)`` for a solve of ``n_rows`` point rows:
    above the ``BST_SOLVE_SHARD`` threshold (0 = never) the links axis
    spans every device of the execution world — ALL processes' devices
    when :func:`global_enabled`, the local ones otherwise. Shared by the
    relax and CG layouts so the threshold semantics cannot diverge
    between them."""
    thr = config.get_int("BST_SOLVE_SHARD") or 0
    g = global_enabled()
    n_dev = len(jax.devices()) if g else len(jax.local_devices())
    if thr > 0 and n_rows >= thr and n_dev > 1:
        return n_dev, g
    return 1, False


def shard_count(n_rows: int) -> int:
    """Shard count of :func:`solve_layout` (compat wrapper)."""
    return solve_layout(n_rows)[0]


def _solve_mesh(n_shards: int, global_mesh: bool) -> Mesh:
    """The 1-D solve mesh: the first ``n_shards`` devices of the world
    (global) or the host (local) along the ``links`` axis."""
    devs = (jax.devices() if global_mesh else jax.local_devices())[:n_shards]
    return Mesh(np.array(devs), (SOLVE_AXIS,))


def global_axis_span(n_shards: int, global_mesh: bool) -> tuple[int, int]:
    """``(n_devices, n_processes)`` the solve mesh axis spans — the
    introspection hook the MULTICHIP dryrun and the multihost tests use
    to assert the global links axis really crosses process boundaries."""
    devs = (jax.devices() if global_mesh else jax.local_devices())[:n_shards]
    return len(devs), len({d.process_index for d in devs})


def _to_global(mesh: Mesh, arr, spec) -> jax.Array:
    """Lift a host array every process holds in full onto the (possibly
    multi-process) solve mesh with the given PartitionSpec. The callback
    slices the SAME replicated host array on every rank — the solver is
    driver-side collect, so each process already has identical inputs —
    which makes cross-host construction exact and allocation-local."""
    a = np.asarray(arr)
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(a.shape, sharding,
                                        lambda idx: a[idx])


def _record_bucket(namespace: str, key: tuple) -> bool:
    """Warm/cold-count one compiled-solver bucket request (lazy import:
    parallel.mesh pulls ops.fusion at module load)."""
    from ..parallel.mesh import record_compile_bucket

    return record_compile_bucket((namespace,) + key)


# ---------------------------------------------------------------------------
# problem layout
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class RelaxProblem:
    """Flattened, padded, (optionally) sharded link arrays for the device
    relaxation. Built once per link list; every ``relax_on_device`` call —
    including masked re-solves — reuses the same arrays and compiled fn.

    Row arrays carry every point match twice (once per side, like the
    numpy ``_flatten``); sharded layouts add a leading shard axis with
    rows grouped by owner tile (see module docstring for why that makes
    the collective reduction exact)."""

    n_tiles: int              # real tile count T (≤ T_pad)
    n_links: int              # real link count L (≤ L_pad)
    n_rows: int               # real point-match rows (both sides)
    n_shards: int             # 1 = plain jit, >1 = shard_map over devices
    local: np.ndarray         # (N,3) or (D,Nd,3) f64
    target: np.ndarray        # same shape as local
    own: np.ndarray           # (N,) or (D,Nd) int32 owner tile per row
    other: np.ndarray         # counterpart tile per row
    w: np.ndarray             # row weights (0.0 on padding)
    link_id: np.ndarray       # link index per row
    side_a: np.ndarray        # 1.0 on the A-side copy of each match row
    global_mesh: bool = False  # links axis spans all processes' devices

    @property
    def T_pad(self) -> int:
        return bucket(self.n_tiles, 2)

    @property
    def L_pad(self) -> int:
        return bucket(self.n_links, 2)

    def bucket_key(self, model: str, reg: str, hist_cap: int,
                   pw: int) -> tuple:
        """The compile-bucket identity of this problem's kernel (keyed by
        the GLOBAL axis size — n_shards counts every mesh device)."""
        return (model, reg, self.T_pad, self.local.shape[-2], self.L_pad,
                hist_cap, pw, self.n_shards, self.global_mesh)


def prepare_relax(
    link_rows: list[tuple[int, int, np.ndarray, np.ndarray, np.ndarray]],
    n_tiles: int,
    n_shards: int = 1,
    tile_shard: np.ndarray | None = None,
    global_mesh: bool = False,
) -> RelaxProblem:
    """Flatten ``(ia, ib, p, q, w)`` links into padded device-ready arrays.

    With ``n_shards > 1``, ``tile_shard`` (T,) assigns each tile's rows to
    a shard (callers place tiles cost-weighted via
    ``pairsched.assign_tasks``); rows keep their single-device relative
    order within each shard so per-tile segment sums are bit-identical
    across layouts. ``global_mesh`` marks a layout whose shards span
    every process's devices (the shard arrays are identical on every
    rank; each rank materializes only its addressable slices)."""
    loc, tgt, own, other, w, lid, side = [], [], [], [], [], [], []
    for l, (ia, ib, p, q, wl) in enumerate(link_rows):
        n = len(p)
        loc.append(p); tgt.append(q)
        own.append(np.full(n, ia)); other.append(np.full(n, ib))
        w.append(wl); lid.append(np.full(n, l)); side.append(np.ones(n))
        loc.append(q); tgt.append(p)
        own.append(np.full(n, ib)); other.append(np.full(n, ia))
        w.append(wl); lid.append(np.full(n, l)); side.append(np.zeros(n))
    local = np.concatenate(loc).astype(np.float64)
    target = np.concatenate(tgt).astype(np.float64)
    own_a = np.concatenate(own).astype(np.int32)
    other_a = np.concatenate(other).astype(np.int32)
    w_a = np.concatenate(w).astype(np.float64)
    lid_a = np.concatenate(lid).astype(np.int32)
    side_a = np.concatenate(side).astype(np.float64)
    n_rows = len(local)

    def pad_rows(arrs, n_pad):
        out = []
        for a in arrs:
            shape = (n_pad,) + a.shape[1:]
            p = np.zeros(shape, a.dtype)
            p[: len(a)] = a
            out.append(p)
        return out

    if n_shards <= 1:
        n_pad = bucket(n_rows)
        local, target, own_a, other_a, w_a, lid_a, side_a = pad_rows(
            (local, target, own_a, other_a, w_a, lid_a, side_a), n_pad)
        return RelaxProblem(n_tiles, len(link_rows), n_rows, 1, local,
                            target, own_a, other_a, w_a, lid_a, side_a)

    if tile_shard is None:
        tile_shard = np.arange(n_tiles) % n_shards
    row_shard = np.asarray(tile_shard)[own_a]
    counts = [int((row_shard == d).sum()) for d in range(n_shards)]
    n_pad = bucket(max(counts + [1]))
    stacks: list[list[np.ndarray]] = [[] for _ in range(7)]
    for d in range(n_shards):
        sel = row_shard == d  # stable: preserves single-device row order
        for i, a in enumerate((local, target, own_a, other_a, w_a, lid_a,
                               side_a)):
            stacks[i].append(pad_rows((a[sel],), n_pad)[0])
    local, target, own_a, other_a, w_a, lid_a, side_a = (
        np.stack(s) for s in stacks)
    return RelaxProblem(n_tiles, len(link_rows), n_rows, n_shards, local,
                        target, own_a, other_a, w_a, lid_a, side_a,
                        global_mesh=global_mesh)


# ---------------------------------------------------------------------------
# batched fits from moments (jnp mirror of models.solver._fit_from_moments)
# ---------------------------------------------------------------------------


def _fit_from_moments_jnp(kind, sw, swp, swq, spp, spq, eps=_EPS_FIT):
    T = sw.shape[0]
    sw_safe = jnp.maximum(sw, eps)
    identity = jnp.zeros((T, 3, 4), sw.dtype).at[:, :, :3].set(jnp.eye(3))
    if kind == M.IDENTITY:
        return identity
    if kind == M.TRANSLATION:
        t = (swq - swp[:, :3]) / sw_safe[:, None]
        return identity.at[:, :, 3].set(t)
    if kind == M.AFFINE:
        a = spp + eps * jnp.eye(4, dtype=sw.dtype)
        sol = jnp.linalg.solve(a, spq)  # (T,4,3)
        return jnp.swapaxes(sol, 1, 2)
    if kind == M.RIGID:
        pc = swp[:, :3] / sw_safe[:, None]
        qc = swq / sw_safe[:, None]
        h = (spq[:, :3, :]
             - pc[:, :, None] * swq[:, None, :]
             - swp[:, :3, None] * qc[:, None, :]
             + sw_safe[:, None, None] * pc[:, :, None] * qc[:, None, :])
        u, _, vt = jnp.linalg.svd(h)
        d = jnp.linalg.det(jnp.swapaxes(vt, 1, 2) @ jnp.swapaxes(u, 1, 2))
        sign = jnp.stack([jnp.ones_like(d), jnp.ones_like(d), d], axis=1)
        r = jnp.swapaxes(vt, 1, 2) @ (sign[:, :, None]
                                      * jnp.swapaxes(u, 1, 2))
        t = qc - jnp.einsum("nij,nj->ni", r, pc)
        return jnp.concatenate([r, t[:, :, None]], axis=2)
    raise ValueError(kind)


# ---------------------------------------------------------------------------
# the relax kernel
# ---------------------------------------------------------------------------


def _relax_core(model: str, reg: str, T_pad: int, L_pad: int, hist_cap: int,
                pw: int, reduce_fn):
    """The per-shard relaxation program. ``reduce_fn`` is identity for the
    single-device kernel and a tree'd ``lax.psum`` under shard_map; all
    post-reduction math is replicated so every device carries the same
    convergence state and the while_loop stays in lock-step."""

    def seg_t(data, own):
        return jax.ops.segment_sum(data, own, num_segments=T_pad)

    def kernel(local, target, own, other, w, link_id, side_a, link_w,
               fixed_mask, warm_t, lam, damping, max_error, max_iter):
        w_eff = w * link_w[link_id]
        identity = jnp.zeros((T_pad, 3, 4),
                             local.dtype).at[:, :, :3].set(jnp.eye(3))
        cur0 = identity.at[:, :, 3].set(warm_t)
        ph = jnp.concatenate(
            [local, jnp.ones((local.shape[0], 1), local.dtype)], axis=1)

        def apply_batch(models, pts, idx):
            m = models[idx]
            return jnp.einsum("nij,nj->ni", m[:, :, :3], pts) + m[:, :, 3]

        def mean_error(models):
            # per-tile partial sums (exact across shard layouts), reduced
            # collectively, then summed over tiles in a fixed order
            a = apply_batch(models, local, own)
            b = apply_batch(models, target, other)
            d = jnp.linalg.norm(a - b, axis=1)
            num, den = reduce_fn((seg_t(d * w_eff, own), seg_t(w_eff, own)))
            return jnp.sum(num) / jnp.maximum(jnp.sum(den), 1e-12)

        def body(carry):
            cur, hist, i, stall, done, prev = carry
            tgt_world = apply_batch(cur, target, other)
            sw, swp, swq, spp, spq = reduce_fn((
                seg_t(w_eff, own),
                seg_t(w_eff[:, None] * ph, own),
                seg_t(w_eff[:, None] * tgt_world, own),
                seg_t(w_eff[:, None, None] * ph[:, :, None]
                      * ph[:, None, :], own),
                seg_t(w_eff[:, None, None] * ph[:, :, None]
                      * tgt_world[:, None, :], own),
            ))
            new = _fit_from_moments_jnp(model, sw, swp, swq, spp, spq)
            if reg != M.NONE:
                rm = _fit_from_moments_jnp(reg, sw, swp, swq, spp, spq)
                new = (1 - lam) * new + lam * rm
            keep = (sw <= 0) | fixed_mask
            new = jnp.where(keep[:, None, None], identity, new)
            cur = (1 - damping) * cur + damping * new
            err = mean_error(cur)
            it = i + 1
            hist = hist.at[i].set(err)
            stall = jnp.where(
                i > 0,
                jnp.where(prev - err < 1e-9 * jnp.maximum(err, 1.0),
                          stall + 1, jnp.int32(0)),
                stall)
            window = jax.lax.dynamic_slice(
                hist, (jnp.maximum(it - pw, 0),), (pw,))
            improvement = hist[jnp.maximum(it - pw - 1, 0)] - jnp.min(window)
            plateau = ((it > pw) & (err < max_error)
                       & ((improvement < 1e-4 * jnp.maximum(err, 1e-12))
                          | (err < 1e-9)))
            return cur, hist, it, stall, (stall >= 5) | plateau, err

        def cond(carry):
            return (~carry[4]) & (carry[2] < max_iter)

        hist0 = jnp.zeros((hist_cap,), local.dtype)
        cur, hist, iters, _, _, _ = jax.lax.while_loop(
            cond, body,
            (cur0, hist0, jnp.int32(0), jnp.int32(0), jnp.bool_(False),
             jnp.float64(0.0)))

        # per-link mean errors under the FINAL models, A-side rows only
        # (both sides carry the same displacement, so one side's weighted
        # mean equals the numpy _per_link_errors value exactly)
        a = apply_batch(cur, local, own)
        b = apply_batch(cur, target, other)
        d = jnp.linalg.norm(a - b, axis=1)
        wa = w_eff * side_a
        ln, ld = reduce_fn((
            jax.ops.segment_sum(d * wa, link_id, num_segments=L_pad),
            jax.ops.segment_sum(wa, link_id, num_segments=L_pad),
        ))
        link_err = ln / jnp.maximum(ld, 1e-12)
        return cur, hist, iters, link_err

    return kernel


@functools.lru_cache(maxsize=32)
def _build_relax_fn(model: str, reg: str, T_pad: int, N_pad: int,
                    L_pad: int, hist_cap: int, pw: int, n_shards: int,
                    global_mesh: bool = False):
    """Compile (or fetch) the relax kernel for one shape bucket. Callers
    count warm/cold via ``record_compile_bucket`` at the call site."""
    if n_shards <= 1:
        kernel = _relax_core(model, reg, T_pad, L_pad, hist_cap, pw,
                             lambda t: t)
        return jax.jit(kernel)

    mesh = _solve_mesh(n_shards, global_mesh)
    psum = functools.partial(jax.lax.psum, axis_name=SOLVE_AXIS)
    kernel = _relax_core(model, reg, T_pad, L_pad, hist_cap, pw,
                         lambda t: jax.tree_util.tree_map(psum, t))

    def shard_kernel(local, target, own, other, w, link_id, side_a,
                     link_w, fixed_mask, warm_t, lam, damping, max_error,
                     max_iter):
        # shard_map hands each device a (1, Nd, ...) block of the
        # leading-axis-sharded row arrays; drop the unit axis
        return kernel(local[0], target[0], own[0], other[0], w[0],
                      link_id[0], side_a[0], link_w, fixed_mask, warm_t,
                      lam, damping, max_error, max_iter)

    sharded = P(SOLVE_AXIS)
    rep = P()
    return jax.jit(shard_map(
        shard_kernel, mesh,
        in_specs=(sharded,) * 7 + (rep,) * 7,
        out_specs=rep,
        # outputs are replicated by construction (all post-psum math is
        # identical on every device); the while_loop has no rep rule, so
        # tell shard_map not to try proving it
        check_rep=False,
    ))


def ensure_relax_compiled(problem: RelaxProblem, model: str, reg: str,
                          max_iterations: int, plateau_width: int) -> bool:
    """Resolve — building AND XLA-compiling if needed — the relax kernel
    for this problem's shape bucket, and warm/cold-count the request.
    Call this OUTSIDE any timed span: a cold bucket executes one
    zero-iteration call here so the timed solve measures only the
    compiled loop, never seconds of XLA build. Returns the warm flag."""
    hist_cap = bucket(max_iterations, 16)
    warm = _record_bucket(
        "solve", problem.bucket_key(model, reg, hist_cap, plateau_width))
    if not warm:
        relax_on_device(
            problem, np.zeros(problem.n_links), np.zeros(problem.n_tiles,
                                                         bool),
            np.zeros((problem.n_tiles, 3)), 0.0, 1.0, 1.0, max_iterations,
            model, reg, plateau_width, limit_iterations=0)
    return warm


def relax_on_device(
    problem: RelaxProblem,
    link_mask: np.ndarray,
    fixed_mask: np.ndarray,
    warm_t: np.ndarray,
    lam: float,
    damping: float,
    max_error: float,
    max_iterations: int,
    model: str,
    reg: str,
    plateau_width: int,
    limit_iterations: int | None = None,
):
    """Run the compiled relaxation; returns DEVICE values
    ``(models (T_pad,3,4), history (hist_cap,), iterations, link_errors
    (L_pad,))`` — the caller fetches once via ``jax.device_get`` at its
    drain point. One call == one ``lax.while_loop`` == zero per-iteration
    host transfers.

    ``limit_iterations`` overrides the DYNAMIC loop bound without
    changing the compile bucket (which follows ``max_iterations``) —
    the 0-sweep compile-warmup path of :func:`ensure_relax_compiled`."""
    hist_cap = bucket(max_iterations, 16)
    run_iter = (max_iterations if limit_iterations is None
                else limit_iterations)
    T_pad, L_pad = problem.T_pad, problem.L_pad
    lw = np.zeros(L_pad)
    lw[: problem.n_links] = np.asarray(link_mask, np.float64)
    fm = np.zeros(T_pad, bool)
    fm[: problem.n_tiles] = np.asarray(fixed_mask, bool)
    wt = np.zeros((T_pad, 3))
    wt[: problem.n_tiles] = np.asarray(warm_t, np.float64)
    with enable_x64():
        fn = _build_relax_fn(model, reg, T_pad, problem.local.shape[-2],
                             L_pad, hist_cap, plateau_width,
                             problem.n_shards, problem.global_mesh)
        args = (problem.local, problem.target, problem.own, problem.other,
                problem.w, problem.link_id, problem.side_a, lw, fm, wt,
                np.float64(lam), np.float64(damping),
                np.float64(max_error), np.int32(run_iter))
        if problem.global_mesh:
            # multi-process mesh: every input must be a global jax.Array
            # with the kernel's exact sharding (each rank materializes
            # only its addressable slices of the replicated host arrays)
            mesh = _solve_mesh(problem.n_shards, True)
            specs = (P(SOLVE_AXIS),) * 7 + (P(),) * 7
            args = tuple(_to_global(mesh, a, s)
                         for a, s in zip(args, specs))
            from .. import profiling

            n_dev, n_proc = global_axis_span(problem.n_shards, True)
            with profiling.span("solve.global", stage="relax",
                                item=f"{n_dev}dev/{n_proc}proc"):
                out = fn(*args)
                jax.block_until_ready(out)
        else:
            out = fn(*args[:10], jnp.float64(lam), jnp.float64(damping),
                     jnp.float64(max_error), jnp.int32(run_iter))
            jax.block_until_ready(out)
    return out


# ---------------------------------------------------------------------------
# intensity coefficient solve: matrix-free CG over (sharded) match rows
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=16)
def _build_cg_fn(n_unknowns: int, M_pad: int, S_pad: int, max_iter: int,
                 n_shards: int, global_mesh: bool = False):
    """CG over the intensity quadratic form. The data term is applied
    per match row (gather the four unknowns, apply the 4x4 block, scatter
    the residual forces) and psum-reduced when sharded; the smoothness +
    identity-regularizer terms are replicated."""

    def data_term(v, ca, cb, n, sx, sy, sxx, syy, sxy):
        sa, oa = v[2 * ca], v[2 * ca + 1]
        sb, ob = v[2 * cb], v[2 * cb + 1]
        r_sa = sxx * sa + sx * oa - sxy * sb - sx * ob
        r_oa = sx * sa + n * oa - sy * sb - n * ob
        r_sb = -sxy * sa - sy * oa + syy * sb + sy * ob
        r_ob = -sx * sa - n * oa + sy * sb + n * ob
        vals = jnp.concatenate([r_sa, r_oa, r_sb, r_ob])
        idx = jnp.concatenate([2 * ca, 2 * ca + 1, 2 * cb, 2 * cb + 1])
        return jax.ops.segment_sum(vals, idx, num_segments=n_unknowns)

    def kernel(ca, cb, mn, sx, sy, sxx, syy, sxy, si, sj, sweights, diag,
               rhs, x0, tol2, max_iter_run, reduce_fn):
        def matvec(v):
            dv = reduce_fn(data_term(v, ca, cb, mn, sx, sy, sxx, syy, sxy))
            # smoothness Laplacian over adjacent-cell pairs, per component
            ds = sweights * (v[si] - v[sj])
            dv = dv + jax.ops.segment_sum(ds, si, num_segments=n_unknowns)
            dv = dv - jax.ops.segment_sum(ds, sj, num_segments=n_unknowns)
            return dv + diag * v

        r0 = rhs - matvec(x0)
        p0 = r0
        rs0 = jnp.dot(r0, r0)

        def body(carry):
            x, r, p, rs, k = carry
            ap = matvec(p)
            alpha = rs / jnp.maximum(jnp.dot(p, ap), 1e-300)
            x = x + alpha * p
            r = r - alpha * ap
            rs_new = jnp.dot(r, r)
            p = r + (rs_new / jnp.maximum(rs, 1e-300)) * p
            return x, r, p, rs_new, k + 1

        def cond(carry):
            # max_iter (static) bounds the compile bucket; max_iter_run
            # (dynamic) is the actual cap — 0 on the compile-warmup path
            return (carry[3] > tol2) & (carry[4]
                                        < jnp.minimum(max_iter_run,
                                                      max_iter))

        x, _, _, _, iters = jax.lax.while_loop(
            cond, body, (x0, r0, p0, rs0, jnp.int32(0)))
        return x, iters

    if n_shards <= 1:
        def single(ca, cb, mn, sx, sy, sxx, syy, sxy, si, sj, sweights,
                   diag, rhs, x0, tol2, max_iter_run):
            return kernel(ca, cb, mn, sx, sy, sxx, syy, sxy, si, sj,
                          sweights, diag, rhs, x0, tol2, max_iter_run,
                          lambda t: t)

        return jax.jit(single)

    mesh = _solve_mesh(n_shards, global_mesh)

    def shard_fn(ca, cb, mn, sx, sy, sxx, syy, sxy, si, sj, sweights,
                 diag, rhs, x0, tol2, max_iter_run):
        return kernel(ca[0], cb[0], mn[0], sx[0], sy[0], sxx[0], syy[0],
                      sxy[0], si, sj, sweights, diag, rhs, x0, tol2,
                      max_iter_run,
                      functools.partial(jax.lax.psum, axis_name=SOLVE_AXIS))

    sharded = P(SOLVE_AXIS)
    rep = P()
    return jax.jit(shard_map(
        shard_fn, mesh,
        in_specs=(sharded,) * 8 + (rep,) * 8,
        out_specs=rep, check_rep=False))


def _cg_shapes(n_cells: int, n_rows: int, n_smooth: int,
               n_shards: int) -> tuple[int, int, int, int]:
    """The CG kernel's compile-bucket shapes: (unknowns, per-shard row
    pad, smooth pad, iteration cap). The single source of truth — the
    warm/cold bucket record and the actual ``_build_cg_fn`` key both
    derive from here, so the telemetry can never disagree with the
    factory cache about what compiles."""
    n_unknowns = 2 * bucket(n_cells, 16)
    if n_shards > 1:
        M_pad = bucket(max(-(-n_rows // n_shards), 1))  # strided max part
    else:
        M_pad = bucket(n_rows, 8)
    S_pad = bucket(max(n_smooth, 1), 8)
    max_iter = min(4 * n_unknowns + 64, 20000)
    return n_unknowns, M_pad, S_pad, max_iter


def ensure_cg_compiled(n_cells: int, n_rows: int, n_smooth: int,
                       n_shards: int, global_mesh: bool = False) -> bool:
    """Build + XLA-compile the CG kernel for this shape bucket outside
    any timed span (cold buckets run one zero-iteration solve), and
    warm/cold-count the request. Returns the warm flag."""
    shapes = _cg_shapes(n_cells, n_rows, n_smooth, n_shards)
    warm = _record_bucket("solve_cg", shapes + (n_shards, global_mesh))
    if not warm:
        solve_intensity_device(
            n_cells, np.zeros((n_rows, 8)), np.ones(2 * n_cells),
            np.zeros(2 * n_cells), np.zeros((n_smooth, 2), int),
            np.zeros(n_smooth), n_shards, global_mesh=global_mesh,
            limit_iterations=0)
    return warm


def solve_intensity_device(
    n_cells: int,
    rows: np.ndarray,
    diag: np.ndarray,
    rhs: np.ndarray,
    smooth_idx: np.ndarray,
    smooth_weights: np.ndarray,
    n_shards: int = 1,
    global_mesh: bool = False,
    rtol: float = 1e-11,
    limit_iterations: int | None = None,
) -> tuple[np.ndarray, int]:
    """CG-solve the intensity normal equations assembled by
    ``ops.intensity.solve_intensity_coefficients``.

    ``rows`` is the (M, 8) match-statistics table ``(ca, cb, n, Sx, Sy,
    Sxx, Syy, Sxy)``; ``diag``/``rhs`` (2C,) carry the identity
    regularizer (+ any padding diagonal); ``smooth_idx`` (S, 2) /
    ``smooth_weights`` (S,) the flattened intra-view smoothness pairs.
    Returns the DEVICE solution vector (2C,) and the CG iteration count —
    the caller fetches at its drain point. ``limit_iterations`` caps the
    dynamic loop without changing the compile bucket (the 0-step
    compile-warmup path of :func:`ensure_cg_compiled`)."""
    n_unknowns, M_pad, S_pad, max_iter = _cg_shapes(
        n_cells, len(rows), len(smooth_idx), n_shards)
    # padded match rows point at cell 0 with all-zero stats: exact no-ops;
    # padded CELLS get diag 1 / rhs 0 so they solve to 0 without touching
    # the real system (the matrix stays SPD)
    spad = np.zeros((S_pad, 2), np.int32)
    wpad = np.zeros(S_pad)
    if len(smooth_idx):
        spad[: len(smooth_idx)] = smooth_idx
        wpad[: len(smooth_weights)] = smooth_weights
    dpad = np.ones(n_unknowns)
    dpad[: 2 * n_cells] = diag
    rhspad = np.zeros(n_unknowns)
    rhspad[: 2 * n_cells] = rhs
    if n_shards > 1:
        # even strided row split (rows are uniform cost); psum reassembles
        def split(a):
            out = np.zeros((n_shards, M_pad) + a.shape[1:], a.dtype)
            for d in range(n_shards):
                p = a[d::n_shards]
                out[d, : len(p)] = p
            return out
    else:
        def split(a):
            out = np.zeros((M_pad,) + a.shape[1:], a.dtype)
            out[: len(a)] = a
            return out

    ca = split(rows[:, 0].astype(np.int32))
    cb = split(rows[:, 1].astype(np.int32))
    stats = [split(rows[:, i].astype(np.float64)) for i in range(2, 8)]
    # rhs/diag is the exact solution for matchless cells (identity) and a
    # tight start everywhere else
    x0 = rhspad / np.maximum(dpad, 1e-300)
    tol2 = (rtol * float(np.linalg.norm(rhspad))) ** 2
    if limit_iterations is not None:
        max_iter_run = limit_iterations
    else:
        max_iter_run = max_iter
    with enable_x64():
        fn = _build_cg_fn(n_unknowns, M_pad, S_pad, max_iter, n_shards,
                          global_mesh)
        args = (ca, cb, *stats, spad[:, 0], spad[:, 1], wpad, dpad,
                rhspad, x0, np.float64(tol2), np.int32(max_iter_run))
        if global_mesh and n_shards > 1:
            mesh = _solve_mesh(n_shards, True)
            specs = (P(SOLVE_AXIS),) * 8 + (P(),) * 8
            args = tuple(_to_global(mesh, a, s)
                         for a, s in zip(args, specs))
            from .. import profiling

            n_dev, n_proc = global_axis_span(n_shards, True)
            with profiling.span("solve.global", stage="intensity",
                                item=f"{n_dev}dev/{n_proc}proc"):
                out = fn(*args)
                jax.block_until_ready(out)
        else:
            out = fn(ca, cb, *stats, spad[:, 0], spad[:, 1], wpad, dpad,
                     rhspad, x0, jnp.float64(tol2),
                     jnp.int32(max_iter_run))
            jax.block_until_ready(out)
    return out

"""FFT phase-correlation pairwise shift estimation (XLA + host refinement).

TPU-native re-design of the reference's stitching math (BigStitcher core
``PairwiseStitching``/``PhaseCorrelation2``, called at
SparkPairwiseStitching.java:247-267), split by what each side is good at:

- DEVICE (one fused, statically-shaped XLA computation per crop-shape
  bucket, vmapped over the batch): windowing, 3-D FFT phase correlation,
  3x3x3 local-maxima suppression, top-N peak extraction — the heavy regular
  compute.
- HOST (numpy, float64): scoring each peak's 2^3 periodic-wrap
  interpretations by true Pearson correlation over the overlap SLICES, a
  hill-climb to the best integer shift, quadratic subpixel refinement. These
  touch only the (dynamic-shaped) overlap boxes — a few dozen tiny
  reductions per pair that would each cost a full-volume masked pass under
  static shapes (the r3 kernel did exactly that and spent 2 orders of
  magnitude more HBM traffic there than on the FFTs).

Shift convention: the returned ``shift`` s satisfies a[x] ~= b[x + s]; the
correction to apply to view B's translation is ``-s`` (see
models/stitching.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _local_maxima(pcm: jnp.ndarray) -> jnp.ndarray:
    """Mask of voxels that are >= all neighbors in their 3x3x3 window,
    with periodic wrap (the PCM is circular). Separable roll-max: 2
    elementwise max ops per axis — ``reduce_window`` computes the same
    thing but lowers ~20x slower on XLA:CPU and no better on TPU."""
    pooled = pcm
    for ax in range(3):
        pooled = jnp.maximum(
            pooled,
            jnp.maximum(jnp.roll(pooled, 1, axis=ax),
                        jnp.roll(pooled, -1, axis=ax)))
    return pcm >= pooled


def _windowed(img: jnp.ndarray, ext: jnp.ndarray, fade_frac: float):
    """Mean-subtract over the actual extent and apply a cosine (Hann-edge)
    fade so the crop-edge discontinuity does not dominate the PCM — without
    this, smooth microscopy data (spectral energy at low k only) buries the
    true peak under zero-padding edge correlation."""
    n = jnp.prod(ext.astype(jnp.float32))
    mean = jnp.sum(img) / jnp.maximum(n, 1.0)
    w = img
    masks = []
    for ax in range(3):
        x = jnp.arange(img.shape[ax], dtype=jnp.float32)
        e = ext[ax].astype(jnp.float32)
        m = jnp.maximum(jnp.round(e * fade_frac), 1.0)
        d = jnp.minimum(x + 0.5, e - (x + 0.5))  # distance into the crop
        ramp = 0.5 * (1.0 - jnp.cos(jnp.pi * jnp.clip(d / m, 0.0, 1.0)))
        masks.append(jnp.where(x < e, ramp, 0.0))
    win = (masks[0][:, None, None] * masks[1][None, :, None]
           * masks[2][None, None, :])
    return (w - mean) * win


@functools.partial(jax.jit, static_argnames=("n_peaks",))
def pcm_peaks(
    a: jnp.ndarray,           # (X,Y,Z) float32 or uint16 (lossless
    b: jnp.ndarray,           # transport downcast), zero-padded crops
    ext_a: jnp.ndarray,       # (3,) int32 actual extent of a before padding
    ext_b: jnp.ndarray,       # (3,) int32
    n_peaks: int = 5,
    fade_frac: float = 0.25,
) -> jnp.ndarray:
    """Top-N local maxima of the phase-correlation matrix -> (n_peaks, 3)
    int32 wrapped indices. The PCM is computed on windowed copies; the
    correlation check happens on the raw crops host-side."""
    # crops may arrive as uint16 (lossless transport downcast when every
    # value is integral — halves h2d bytes on wire-limited links); the
    # kernel math is float32 either way
    a = a.astype(jnp.float32)
    b = b.astype(jnp.float32)
    fa = jnp.fft.rfftn(_windowed(a, ext_a, fade_frac))
    fb = jnp.fft.rfftn(_windowed(b, ext_b, fade_frac))
    cross = fa * jnp.conj(fb)
    mag = jnp.abs(cross)
    # zero out negligible bins instead of normalizing their garbage phase
    norm = jnp.where(mag > 1e-5 * jnp.max(mag),
                     cross / jnp.maximum(mag, 1e-30), 0.0)
    pcm = jnp.fft.irfftn(norm, s=a.shape).astype(jnp.float32)

    masked = jnp.where(_local_maxima(pcm), pcm, -jnp.inf)
    _, flat_idx = jax.lax.top_k(masked.ravel(), n_peaks)
    sy = a.shape[1] * a.shape[2]
    sz = a.shape[2]
    return jnp.stack(
        [flat_idx // sy, (flat_idx // sz) % a.shape[1], flat_idx % a.shape[2]],
        axis=-1,
    ).astype(jnp.int32)


pcm_peaks_batch = jax.jit(
    jax.vmap(pcm_peaks, in_axes=(0, 0, 0, 0, None, None)),
    static_argnames=("n_peaks",),
)


# ---------------------------------------------------------------------------
# host-side refinement (float64, overlap slices only)
# ---------------------------------------------------------------------------


def _sat(x: np.ndarray) -> np.ndarray:
    """3-D summed-area table with a zero border: S[i,j,k] = sum of
    x[:i,:j,:k]; box sums become 8 corner lookups. Cumsums run on
    contiguous arrays (cumsum into a strided border view is ~5x slower)."""
    c = np.cumsum(np.cumsum(np.cumsum(x, 0, dtype=np.float64), 1), 2)
    S = np.zeros(tuple(s + 1 for s in x.shape), np.float64)
    S[1:, 1:, 1:] = c
    return S


def _box_sum(S: np.ndarray, lo, hi) -> float:
    x0, y0, z0 = int(lo[0]), int(lo[1]), int(lo[2])
    x1, y1, z1 = int(hi[0]), int(hi[1]), int(hi[2])
    return (S[x1, y1, z1] - S[x0, y1, z1] - S[x1, y0, z1] - S[x1, y1, z0]
            + S[x0, y0, z1] + S[x0, y1, z0] + S[x1, y0, z0] - S[x0, y0, z0])


class _PearsonScorer:
    """Pearson r of a[x] vs b[x+s] over the rectangular overlap (the
    reference's per-peak true cross-correlation check), with the window
    sums S_a, S_aa, S_b, S_bb served by summed-area tables — only the
    cross term S_ab costs a pass over the overlap, ~6x less memory
    traffic per candidate than the naive centered-copy evaluation."""

    def __init__(self, a: np.ndarray, b: np.ndarray):
        self.a = a
        self.b = b
        self.ext_a = np.array(a.shape, np.int64)
        self.ext_b = np.array(b.shape, np.int64)
        self.Sa = _sat(a)
        self.Saa = _sat(a * a)
        self.Sb = _sat(b)
        self.Sbb = _sat(b * b)

    def r(self, s, min_overlap) -> float:
        lo = np.maximum(0, -s)
        hi = np.minimum(self.ext_a, self.ext_b - s)
        if np.any(hi - lo < 1):
            return -np.inf
        n = float(np.prod(hi - lo))
        if n < min_overlap:
            return -np.inf
        av = self.a[tuple(slice(int(lo[d]), int(hi[d])) for d in range(3))]
        bv = self.b[tuple(slice(int(lo[d] + s[d]), int(hi[d] + s[d]))
                          for d in range(3))]
        s_ab = float(np.einsum("ijk,ijk->", av, bv, dtype=np.float64,
                               casting="unsafe"))
        s_a = _box_sum(self.Sa, lo, hi)
        s_aa = _box_sum(self.Saa, lo, hi)
        s_b = _box_sum(self.Sb, lo + s, hi + s)
        s_bb = _box_sum(self.Sbb, lo + s, hi + s)
        va = s_aa - s_a * s_a / n
        vb = s_bb - s_b * s_b / n
        den = np.sqrt(max(va, 0.0) * max(vb, 0.0))
        if den <= 1e-12:
            return -1.0
        return float((s_ab - s_a * s_b / n) / den)


def _r_candidate(a, b, ext_a, ext_b, s, min_overlap) -> float:
    """One-shot Pearson r (kept for API compatibility; batch callers use
    ``_PearsonScorer`` to amortize the summed-area tables)."""
    return _PearsonScorer(np.asarray(a, np.float64),
                          np.asarray(b, np.float64)).r(
        np.asarray(s, np.int64), min_overlap)


def refine_peaks(
    crop_a: np.ndarray,       # unpadded crop of group A (any float dtype)
    crop_b: np.ndarray,
    peaks: np.ndarray,        # (n_peaks, 3) wrapped PCM indices
    fft_shape: tuple[int, int, int],
    min_overlap: float = 32.0,
    subpixel: bool = True,
) -> tuple[np.ndarray, float]:
    """Score peak wraps by true correlation, hill-climb (argmax over the 6
    unit neighbors + self per round, 3 rounds — the round-1..3 device-kernel
    search), then quadratic subpixel. Returns (shift (3,) f64, best r).
    Candidate r values are memoized: the subpixel fit reuses the final
    round's neighbor evaluations instead of recomputing them."""
    a = np.asarray(crop_a, np.float64)
    b = np.asarray(crop_b, np.float64)
    N = np.array(fft_shape, np.int64)
    scorer = _PearsonScorer(a, b)
    memo: dict[tuple, float] = {}

    def r_at(s):
        key = tuple(int(v) for v in s)
        if key not in memo:
            memo[key] = scorer.r(np.asarray(s, np.int64), min_overlap)
        return memo[key]

    best_s, best_r = np.zeros(3, np.int64), -np.inf
    for p in np.asarray(peaks, np.int64):
        for wrap in range(8):
            c = np.array([p[d] - (N[d] if (wrap >> d) & 1 else 0)
                          for d in range(3)])
            s = -c  # PCM index c names shift -c (see _windowed convention)
            r = r_at(s)
            if r > best_r:
                best_r, best_s = r, s
    if not np.isfinite(best_r):
        return best_s.astype(np.float64), -1.0

    # hill-climb on the true correlation: the PCM peak can be split across
    # voxels (windowing) so the best integer shift may be a neighbor
    unit = np.concatenate([np.zeros((1, 3), np.int64),
                           np.eye(3, dtype=np.int64),
                           -np.eye(3, dtype=np.int64)], axis=0)
    for _ in range(3):
        cand = best_s[None, :] + unit
        rc = [r_at(s) for s in cand]
        i = int(np.argmax(rc))
        if i == 0:
            break
        best_s, best_r = cand[i], rc[i]

    shift = best_s.astype(np.float64)
    if subpixel:
        for ax in range(3):
            e = np.zeros(3, np.int64)
            e[ax] = 1
            fp, fm = r_at(best_s + e), r_at(best_s - e)
            denom = fm - 2.0 * best_r + fp
            if abs(denom) > 1e-12 and np.isfinite(fp) and np.isfinite(fm):
                shift[ax] += float(np.clip(0.5 * (fm - fp) / denom, -0.5, 0.5))
    return shift, float(best_r)


def stitch_crops(
    a, b, ext_a, ext_b, n_peaks: int = 5, min_overlap: float = 32.0,
    subpixel: bool = True, fade_frac: float = 0.25,
) -> tuple[np.ndarray, float]:
    """Single-pair convenience: device PCM peaks + host refinement.
    ``a``/``b`` are padded crops; ``ext_*`` their true extents."""
    peaks = np.asarray(pcm_peaks(jnp.asarray(a), jnp.asarray(b),
                                 jnp.asarray(ext_a), jnp.asarray(ext_b),
                                 n_peaks, fade_frac))
    ea = tuple(int(v) for v in np.asarray(ext_a))
    eb = tuple(int(v) for v in np.asarray(ext_b))
    crop_a = np.asarray(a)[tuple(slice(0, s) for s in ea)]
    crop_b = np.asarray(b)[tuple(slice(0, s) for s in eb)]
    return refine_peaks(crop_a, crop_b, peaks, tuple(np.asarray(a).shape),
                        min_overlap=min_overlap, subpixel=subpixel)


def pad_to(crop: np.ndarray, shape: tuple[int, ...]) -> np.ndarray:
    out = np.zeros(shape, dtype=np.float32)
    sl = tuple(slice(0, s) for s in crop.shape)
    out[sl] = crop
    return out

"""FFT phase-correlation pairwise shift estimation (XLA).

TPU-native re-design of the reference's stitching math (BigStitcher core
``PairwiseStitching``/``PhaseCorrelation2``, called at
SparkPairwiseStitching.java:247-267): the two zero-padded overlap crops are
phase-correlated with a 3-D FFT, the top-N local maxima of the correlation
matrix are extracted, every peak's 2^3 periodic-wrap interpretations are
scored by true (masked) Pearson cross-correlation, and the winner gets
quadratic subpixel refinement. Everything is one fused, statically-shaped
XLA computation per crop-shape bucket, vmappable over a batch of pairs —
the reference runs one single-threaded Java FFT per Spark task instead.

Shift convention: the returned ``shift`` s satisfies a[x] ~= b[x + s]; the
correction to apply to view B's translation is ``-s`` (see
models/stitching.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _local_maxima(pcm: jnp.ndarray) -> jnp.ndarray:
    """Mask of voxels that are >= all neighbors in their 3x3x3 window."""
    pooled = jax.lax.reduce_window(
        pcm, -jnp.inf, jax.lax.max, (3, 3, 3), (1, 1, 1), "SAME"
    )
    return pcm >= pooled


def _masked_pearson(a, b_shifted, mask, min_overlap):
    n = jnp.sum(mask)
    am = jnp.sum(a * mask) / jnp.maximum(n, 1.0)
    bm = jnp.sum(b_shifted * mask) / jnp.maximum(n, 1.0)
    da = (a - am) * mask
    db = (b_shifted - bm) * mask
    cov = jnp.sum(da * db)
    var = jnp.sqrt(jnp.sum(da * da) * jnp.sum(db * db))
    r = jnp.where(var > 1e-12, cov / var, -1.0)
    return jnp.where(n >= min_overlap, r, -jnp.inf), n


def _corr_candidate(a, b, ext_a, ext_b, s, min_overlap):
    """Pearson r of a[x] vs b[x+s] over the valid region (true
    cross-correlation check of one candidate shift)."""
    b_sh = b
    for ax in range(3):
        b_sh = jnp.roll(b_sh, -s[ax], axis=ax)
    dims = a.shape
    masks_1d = []
    for ax in range(3):
        x = jnp.arange(dims[ax])
        lo = jnp.maximum(0, -s[ax])
        hi = jnp.minimum(ext_a[ax], ext_b[ax] - s[ax])
        masks_1d.append((x >= lo) & (x < hi))
    mask = (masks_1d[0][:, None, None] & masks_1d[1][None, :, None]
            & masks_1d[2][None, None, :]).astype(jnp.float32)
    return _masked_pearson(a, b_sh, mask, min_overlap)


def _windowed(img: jnp.ndarray, ext: jnp.ndarray, fade_frac: float):
    """Mean-subtract over the actual extent and apply a cosine (Hann-edge)
    fade so the crop-edge discontinuity does not dominate the PCM — without
    this, smooth microscopy data (spectral energy at low k only) buries the
    true peak under zero-padding edge correlation."""
    n = jnp.prod(ext.astype(jnp.float32))
    mean = jnp.sum(img) / jnp.maximum(n, 1.0)
    w = img
    masks = []
    for ax in range(3):
        x = jnp.arange(img.shape[ax], dtype=jnp.float32)
        e = ext[ax].astype(jnp.float32)
        m = jnp.maximum(jnp.round(e * fade_frac), 1.0)
        d = jnp.minimum(x + 0.5, e - (x + 0.5))  # distance into the crop
        ramp = 0.5 * (1.0 - jnp.cos(jnp.pi * jnp.clip(d / m, 0.0, 1.0)))
        masks.append(jnp.where(x < e, ramp, 0.0))
    win = (masks[0][:, None, None] * masks[1][None, :, None]
           * masks[2][None, None, :])
    return (w - mean) * win


@functools.partial(jax.jit, static_argnames=("n_peaks", "subpixel"))
def stitch_crops(
    a: jnp.ndarray,           # (X,Y,Z) float32, zero-padded crop of group A
    b: jnp.ndarray,           # (X,Y,Z) float32, zero-padded crop of group B
    ext_a: jnp.ndarray,       # (3,) int32 actual extent of a before padding
    ext_b: jnp.ndarray,       # (3,) int32
    n_peaks: int = 5,
    min_overlap: float = 32.0,
    subpixel: bool = True,
    fade_frac: float = 0.25,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Estimate the shift between two crops. Returns (shift (3,) f32, r).

    ``shift`` satisfies a[x] ~= b[x + shift]; r is the true cross-correlation
    of the winning candidate (NOT the PCM value — reference checks peaks by
    real correlation, SURVEY.md §2.2 'top-5 peak extraction, per-peak true
    cross-correlation r'). The PCM is computed on windowed copies; the
    correlation check uses the raw crops."""
    shape = jnp.array(a.shape, jnp.int32)
    fa = jnp.fft.rfftn(_windowed(a, ext_a, fade_frac))
    fb = jnp.fft.rfftn(_windowed(b, ext_b, fade_frac))
    cross = fa * jnp.conj(fb)
    mag = jnp.abs(cross)
    # zero out negligible bins instead of normalizing their garbage phase
    norm = jnp.where(mag > 1e-5 * jnp.max(mag),
                     cross / jnp.maximum(mag, 1e-30), 0.0)
    pcm = jnp.fft.irfftn(norm, s=a.shape).astype(jnp.float32)

    masked = jnp.where(_local_maxima(pcm), pcm, -jnp.inf)
    _, flat_idx = jax.lax.top_k(masked.ravel(), n_peaks)
    sy = a.shape[1] * a.shape[2]
    sz = a.shape[2]
    peaks = jnp.stack(
        [flat_idx // sy, (flat_idx // sz) % a.shape[1], flat_idx % a.shape[2]],
        axis=-1,
    ).astype(jnp.int32)  # (n_peaks, 3)

    # all 2^3 periodic interpretations c in {p, p - N}; shift s = -c
    combos = jnp.array(
        [[(i >> d) & 1 for d in range(3)] for i in range(8)], jnp.int32
    )  # (8, 3)
    cands = peaks[:, None, :] - combos[None, :, :] * shape[None, None, :]
    cands = cands.reshape(-1, 3)  # (n_peaks*8, 3)
    shifts = -cands

    def eval_cand(s):
        r, n = _corr_candidate(a, b, ext_a, ext_b, s, min_overlap)
        return r

    rs = jax.vmap(eval_cand)(shifts)
    best = jnp.argmax(rs)
    s0 = shifts[best]
    r0 = rs[best]

    # hill-climb on the true correlation: the PCM peak can be split across
    # voxels (windowing) so the best integer shift may be a neighbor of the
    # best PCM candidate
    unit = jnp.concatenate(
        [jnp.zeros((1, 3), jnp.int32),
         jnp.eye(3, dtype=jnp.int32), -jnp.eye(3, dtype=jnp.int32)], axis=0
    )  # (7, 3)

    def climb(_, carry):
        s, r = carry
        cand = s[None, :] + unit
        rc = jax.vmap(eval_cand)(cand)
        i = jnp.argmax(rc)
        return cand[i], rc[i]

    s_int, best_r = jax.lax.fori_loop(0, 3, climb, (s0, r0))
    best_shift = s_int.astype(jnp.float32)

    if subpixel:
        # quadratic fit per axis on the correlation values at s +- 1
        neigh = jnp.concatenate(
            [jnp.eye(3, dtype=jnp.int32), -jnp.eye(3, dtype=jnp.int32)], axis=0
        )
        rn = jax.vmap(eval_cand)(s_int[None, :] + neigh)  # (6,) [+x,+y,+z,-x,-y,-z]
        offs = []
        for ax in range(3):
            fp, fm = rn[ax], rn[ax + 3]
            denom = fm - 2.0 * best_r + fp
            off = jnp.where((jnp.abs(denom) > 1e-12) & jnp.isfinite(fp)
                            & jnp.isfinite(fm),
                            0.5 * (fm - fp) / denom, 0.0)
            offs.append(jnp.clip(off, -0.5, 0.5))
        best_shift = best_shift + jnp.stack(offs)
    return best_shift, best_r


# min_overlap is batched (axis 5): each pair keeps its own 10%-of-crop
# threshold regardless of which pairs share its batch
stitch_crops_batch = jax.jit(
    jax.vmap(stitch_crops, in_axes=(0, 0, 0, 0, None, 0, None, None)),
    static_argnames=("n_peaks", "subpixel"),
)


def pad_to(crop: np.ndarray, shape: tuple[int, ...]) -> np.ndarray:
    out = np.zeros(shape, dtype=np.float32)
    sl = tuple(slice(0, s) for s in crop.shape)
    out[sl] = crop
    return out

"""Geometric descriptor matching + RANSAC + ICP kernels (XLA).

Role of the mvrecon matchers the reference instantiates at
SparkGeometricDescriptorMatching.java:564-621 — ``GeometricHashingPairwise``
(rotation-invariant local frames), ``(F)RGLDMPairwise`` (translation-invariant
redundant local geometric descriptors), ``IterativeClosestPointPairwise`` —
and the RANSAC consensus fit (``RANSACParameters``: 10k iterations, eps 5 px,
minInlierRatio 0.1, minInliers 12).

TPU design: descriptors for a whole point cloud build as dense (N,k) kNN +
gather ops; candidate matching is one squared-distance matmul + top-2 + ratio
test; RANSAC is hypothesis-parallel — a fixed batch of minimal samples is
fitted with the batched model fits of ``ops.models`` and scored against all
candidates at once (argmax selection, no data-dependent control flow).
"""

from __future__ import annotations

import functools
from itertools import combinations

import jax
import jax.numpy as jnp
import numpy as np

from .models import MIN_POINTS, fit_model, fit_interpolated

GEOMETRIC_HASHING = "FAST_ROTATION"        # reference method enum names
RGLDM = "PRECISE_TRANSLATION"
FRGLDM = "FAST_TRANSLATION"
ICP = "ICP"


# --------------------------------------------------------------------------
# descriptors
# --------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("k",))
def knn_indices(points: jnp.ndarray, k: int) -> jnp.ndarray:
    """Indices of the k nearest neighbors (self excluded) for each of N
    points — dense (N,N) distance matrix + top-k; fine for the 1e3–1e5
    points per view this pipeline sees."""
    p = points.astype(jnp.float32)
    d2 = ((p[:, None, :] - p[None, :, :]) ** 2).sum(-1)
    d2 = d2 + jnp.eye(p.shape[0], dtype=jnp.float32) * jnp.inf
    _, idx = jax.lax.top_k(-d2, k)
    return idx


def subset_combinations(n_pool: int, n_use: int) -> np.ndarray:
    """All ordered subsets (preserving distance order) of size ``n_use`` from
    the ``n_pool`` nearest neighbors — the 'redundancy' of RGLDM."""
    return np.array(list(combinations(range(n_pool), n_use)), np.int32)


@functools.partial(
    jax.jit, static_argnames=("n_neighbors", "redundancy", "rotation_invariant")
)
def build_descriptors(
    points: jnp.ndarray,
    n_neighbors: int = 3,
    redundancy: int = 1,
    rotation_invariant: bool = True,
):
    """Per-point local geometric descriptors.

    Returns (descriptors (N*S, n_neighbors*3) float32, owner (N*S,) int32)
    where S = C(n_neighbors+redundancy, n_neighbors) subsets per point.

    rotation_invariant=True expresses the neighbor offsets in a local frame
    built from the two nearest neighbors (GeometricHashing role); False keeps
    raw offsets ordered by distance (RGLDM/FRGLDM role, translation-invariant
    only).
    """
    n = points.shape[0]
    pool = n_neighbors + redundancy
    idx = knn_indices(points, pool)                       # (N, pool)
    offs = points[idx] - points[:, None, :]               # (N, pool, 3)
    subs = jnp.asarray(subset_combinations(pool, n_neighbors))  # (S, n_use)
    sel = offs[:, subs, :]                                # (N, S, n_use, 3)

    if rotation_invariant:
        # local frame from the subset's two nearest offsets:
        # x along o0; y in span(o0,o1) orthogonal to x; z = x×y (handedness
        # fixed -> reflections are NOT matched, same as the reference)
        o0 = sel[..., 0, :]
        o1 = sel[..., 1 % n_neighbors, :]
        ex = o0 / (jnp.linalg.norm(o0, axis=-1, keepdims=True) + 1e-12)
        ey = o1 - (o1 * ex).sum(-1, keepdims=True) * ex
        ey = ey / (jnp.linalg.norm(ey, axis=-1, keepdims=True) + 1e-12)
        ez = jnp.cross(ex, ey)
        frame = jnp.stack([ex, ey, ez], axis=-1)          # (N, S, 3, 3) cols=basis
        sel = jnp.einsum("nsji,nskj->nski", frame, sel)   # coords in local frame

    desc = sel.reshape(n, -1, n_neighbors * 3)            # (N, S, d)
    s = desc.shape[1]
    owner = jnp.repeat(jnp.arange(n, dtype=jnp.int32), s)
    return desc.reshape(n * s, -1).astype(jnp.float32), owner


@jax.jit
def _pairwise_sqdist(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(Na,Nb) squared euclidean distances via the matmul identity.

    The clouds are shifted to a common centroid (distance-invariant) and the
    matmul forced to full f32 — TPU matmuls default to bf16 passes, whose
    ~0.4% error would drown small distances under the a²+b²-2ab cancellation.
    """
    c = b.mean(0)
    a = a - c
    b = b - c
    a2 = (a**2).sum(-1)[:, None]
    b2 = (b**2).sum(-1)[None, :]
    ab = jnp.matmul(a, b.T, precision=jax.lax.Precision.HIGHEST)
    return jnp.maximum(a2 + b2 - 2.0 * ab, 0.0)


@jax.jit
def match_ratio_test(desc_a, owner_a, desc_b, owner_b, ratio: jnp.ndarray):
    """Best-vs-second-best candidate matching.

    For each descriptor of A: nearest and second-nearest descriptor of B
    (second-nearest restricted to a DIFFERENT owner point, so redundant
    descriptors of one point don't veto themselves); accept if
    second/best >= ratio (mpicbg nearest-neighbor-distance-ratio test).
    Returns (match_b (Da,) int32 owner index in B, accept (Da,) bool).
    """
    d2 = _pairwise_sqdist(desc_a, desc_b)                 # (Da, Db)
    best = jnp.argmin(d2, axis=1)
    bestd = jnp.take_along_axis(d2, best[:, None], axis=1)[:, 0]
    same_owner = owner_b[None, :] == owner_b[best][:, None]
    d2_masked = jnp.where(same_owner, jnp.inf, d2)
    second = jnp.min(d2_masked, axis=1)
    accept = jnp.sqrt(second) >= ratio * jnp.sqrt(bestd)
    return owner_b[best], accept


def match_candidates(
    points_a: np.ndarray,
    points_b: np.ndarray,
    method: str = GEOMETRIC_HASHING,
    n_neighbors: int = 3,
    redundancy: int = 1,
    ratio_of_distance: float = 3.0,
) -> np.ndarray:
    """Descriptor-based correspondence candidates between two clouds.

    Returns (M,2) int32 [index_a, index_b] with duplicates removed. Needs
    at least n_neighbors+redundancy+1 points per cloud.
    """
    pool = n_neighbors + redundancy
    if len(points_a) <= pool or len(points_b) <= pool:
        return np.zeros((0, 2), np.int32)
    rot = method == GEOMETRIC_HASHING
    da, oa = build_descriptors(jnp.asarray(points_a, jnp.float32),
                               n_neighbors, redundancy, rot)
    db, ob = build_descriptors(jnp.asarray(points_b, jnp.float32),
                               n_neighbors, redundancy, rot)
    mb, acc = match_ratio_test(da, oa, db, ob,
                               jnp.float32(ratio_of_distance))
    oa, mb, acc = np.asarray(oa), np.asarray(mb), np.asarray(acc)
    pairs = np.stack([oa[acc], mb[acc]], axis=1)
    return np.unique(pairs, axis=0).astype(np.int32)


# --------------------------------------------------------------------------
# RANSAC
# --------------------------------------------------------------------------

@functools.partial(
    jax.jit,
    static_argnames=("model_kind", "reg_kind", "iterations", "sample", "lam"),
)
def _ransac_kernel(pa, pb, valid, key, epsilon, lam,
                   model_kind, reg_kind, iterations, sample):
    m = pa.shape[0]
    keys = jax.random.split(key, iterations)
    idx = jax.vmap(
        lambda k: jax.random.choice(k, m, (sample,), replace=False,
                                    p=valid / valid.sum())
    )(keys)                                               # (I, sample)
    sp = pa[idx]                                          # (I, sample, 3)
    sq = pb[idx]
    models = fit_model(model_kind, sp, sq, xp=jnp)        # (I, 3, 4)
    pred = jnp.einsum("iab,mb->ima", models[:, :, :3], pa) + models[:, None, :, 3]
    err = jnp.linalg.norm(pred - pb[None], axis=-1)       # (I, M)
    inl = (err < epsilon) & (valid[None, :] > 0)
    counts = inl.sum(-1)
    best = jnp.argmax(counts)
    w = inl[best].astype(pa.dtype)
    final = fit_interpolated(model_kind, reg_kind, lam, pa, pb, w, xp=jnp)
    # one consensus re-fit round on the final model's inliers
    pred = pa @ final[:, :3].T + final[:, 3]
    err2 = jnp.linalg.norm(pred - pb, axis=-1)
    w2 = ((err2 < epsilon) & (valid > 0)).astype(pa.dtype)
    final = fit_interpolated(model_kind, reg_kind, lam, pa, pb, w2, xp=jnp)
    pred = pa @ final[:, :3].T + final[:, 3]
    err3 = jnp.linalg.norm(pred - pb, axis=-1)
    inliers = (err3 < epsilon) & (valid > 0)
    return final, inliers, counts[best]


def ransac(
    cand_a: np.ndarray,
    cand_b: np.ndarray,
    model_kind: str = "AFFINE",
    reg_kind: str = "RIGID",
    lam: float = 0.1,
    epsilon: float = 5.0,
    min_inlier_ratio: float = 0.1,
    min_inliers: int = 12,
    iterations: int = 10000,
    seed: int = 17,
) -> tuple[np.ndarray, np.ndarray] | None:
    """Hypothesis-parallel RANSAC over candidate correspondences.

    cand_a/cand_b: (M,3) matched candidate coordinates. Returns
    (model 3x4, inlier_mask (M,)) or None if consensus is too small
    (RANSAC defaults: SparkGeometricDescriptorMatching.java:180-189).
    Candidates are padded to the next power of two so compilation is shared
    across pairs of similar size.
    """
    m = len(cand_a)
    sample = max(MIN_POINTS[model_kind], MIN_POINTS.get(reg_kind, 0), 1)
    if m < max(min_inliers, sample):
        return None
    padded = 1 << int(np.ceil(np.log2(max(m, 8))))
    pa = np.zeros((padded, 3), np.float32)
    pb = np.zeros((padded, 3), np.float32)
    val = np.zeros(padded, np.float32)
    pa[:m], pb[:m], val[:m] = cand_a, cand_b, 1.0
    model, inliers, _ = _ransac_kernel(
        jnp.asarray(pa), jnp.asarray(pb), jnp.asarray(val),
        jax.random.PRNGKey(seed), jnp.float32(epsilon), float(lam),
        model_kind, reg_kind, int(iterations), int(sample),
    )
    inliers = np.asarray(inliers)[:m]
    n_in = int(inliers.sum())
    if n_in < min_inliers or n_in < min_inlier_ratio * m:
        return None
    # final f64 refit on the inlier set (the device kernel runs f32)
    model = fit_interpolated(model_kind, reg_kind, lam,
                             np.asarray(cand_a, np.float64)[inliers],
                             np.asarray(cand_b, np.float64)[inliers])
    return np.asarray(model, np.float64), inliers


# --------------------------------------------------------------------------
# ICP
# --------------------------------------------------------------------------

def icp(
    points_a: np.ndarray,
    points_b: np.ndarray,
    model_kind: str = "AFFINE",
    reg_kind: str = "RIGID",
    lam: float = 0.1,
    max_distance: float = 2.5,
    max_iterations: int = 200,
    min_converged: float = 1e-4,
) -> tuple[np.ndarray, np.ndarray] | None:
    """Iterative closest point: A is progressively transformed onto B.

    Returns (model 3x4 mapping a->b, correspondences (K,2) [ia, ib]) or None.
    Defaults follow the reference (200 iterations, 2.5 px max distance).
    The NN assignment each round is one device distance matrix; the model
    refit reuses the batched fits.
    """
    a = np.asarray(points_a, np.float64)
    b = np.asarray(points_b, np.float64)
    if len(a) < MIN_POINTS[model_kind] or len(b) < MIN_POINTS[model_kind]:
        return None
    model = np.hstack([np.eye(3), np.zeros((3, 1))])
    prev_err = np.inf
    pairs = None
    for _ in range(max_iterations):
        moved = a @ model[:, :3].T + model[:, 3]
        d2 = np.asarray(_pairwise_sqdist(jnp.asarray(moved, jnp.float32),
                                         jnp.asarray(b, jnp.float32)))
        nn = d2.argmin(1)
        nd = np.sqrt(d2[np.arange(len(a)), nn])
        keep = nd < max_distance
        if keep.sum() < max(MIN_POINTS[model_kind], 3):
            return None
        pairs = np.stack([np.where(keep)[0], nn[keep]], 1)
        model = fit_interpolated(model_kind, reg_kind, lam,
                                 a[pairs[:, 0]], b[pairs[:, 1]])
        err = float(nd[keep].mean())
        if abs(prev_err - err) < min_converged:
            break
        prev_err = err
    return model, pairs.astype(np.int32)

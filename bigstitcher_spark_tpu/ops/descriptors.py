"""Geometric descriptor matching + RANSAC + ICP kernels (XLA).

Role of the mvrecon matchers the reference instantiates at
SparkGeometricDescriptorMatching.java:564-621 — ``GeometricHashingPairwise``
(rotation-invariant local frames), ``(F)RGLDMPairwise`` (translation-invariant
redundant local geometric descriptors), ``IterativeClosestPointPairwise`` —
and the RANSAC consensus fit (``RANSACParameters``: 10k iterations, eps 5 px,
minInlierRatio 0.1, minInliers 12).

TPU design: descriptors for a whole point cloud build as dense (N,k) kNN +
gather ops; candidate matching is one squared-distance matmul + top-2 + ratio
test; RANSAC is hypothesis-parallel — a fixed batch of minimal samples is
fitted with the batched model fits of ``ops.models`` and scored against all
candidates at once (argmax selection, no data-dependent control flow).
"""

from __future__ import annotations

import functools
from itertools import combinations

import jax
import jax.numpy as jnp
import numpy as np

from .models import MIN_POINTS, fit_model, fit_interpolated

GEOMETRIC_HASHING = "FAST_ROTATION"        # reference method enum names
RGLDM = "PRECISE_TRANSLATION"
FRGLDM = "FAST_TRANSLATION"
ICP = "ICP"


# --------------------------------------------------------------------------
# descriptors
# --------------------------------------------------------------------------

# row-tile budget: a distance tile holds at most this many f32 entries
# (2^26 = 256 MB), so big clouds never materialize an (N,N) matrix
_TILE_ENTRIES = 1 << 26


def _row_block(n: int) -> int:
    r = max(128, _TILE_ENTRIES // max(n, 1))
    return int(min(1 << int(np.ceil(np.log2(r))), max(n, 1)))


@functools.partial(jax.jit, static_argnames=("k", "rb"))
def _knn_kernel(points: jnp.ndarray, k: int, rb: int) -> jnp.ndarray:
    """(N,k) nearest-neighbor indices, row-tiled: each lax.map step builds
    one (rb, N) distance tile — memory stays O(rb*N) instead of O(N^2), so
    1e5-point clouds (the reference handles these via KD-trees) fit HBM."""
    p = points.astype(jnp.float32)
    n = p.shape[0]
    pad_rows = (-n) % rb
    rows = jnp.pad(p, ((0, pad_rows), (0, 0)))
    row_ids = jnp.arange(n + pad_rows, dtype=jnp.int32)

    def block(args):
        rp, rid = args
        d2 = ((rp[:, None, :] - p[None, :, :]) ** 2).sum(-1)  # (rb, N)
        d2 = jnp.where(rid[:, None] == jnp.arange(n)[None, :], jnp.inf, d2)
        _, idx = jax.lax.top_k(-d2, k)
        return idx

    idx = jax.lax.map(block, (rows.reshape(-1, rb, 3),
                              row_ids.reshape(-1, rb)))
    return idx.reshape(-1, k)[:n]


def knn_indices(points, k: int):
    """Indices of the k nearest neighbors (self excluded) for each point."""
    n = int(points.shape[0])
    return _knn_kernel(jnp.asarray(points), k, _row_block(n))


def subset_combinations(n_pool: int, n_use: int) -> np.ndarray:
    """All ordered subsets (preserving distance order) of size ``n_use`` from
    the ``n_pool`` nearest neighbors — the 'redundancy' of RGLDM."""
    return np.array(list(combinations(range(n_pool), n_use)), np.int32)


@functools.partial(
    jax.jit, static_argnames=("n_neighbors", "redundancy", "rotation_invariant")
)
def build_descriptors(
    points: jnp.ndarray,
    n_neighbors: int = 3,
    redundancy: int = 1,
    rotation_invariant: bool = True,
):
    """Per-point local geometric descriptors.

    Returns (descriptors (N*S, n_neighbors*3) float32, owner (N*S,) int32)
    where S = C(n_neighbors+redundancy, n_neighbors) subsets per point.

    rotation_invariant=True expresses the neighbor offsets in a local frame
    built from the two nearest neighbors (GeometricHashing role); False keeps
    raw offsets ordered by distance (RGLDM/FRGLDM role, translation-invariant
    only).
    """
    n = points.shape[0]
    pool = n_neighbors + redundancy
    idx = knn_indices(points, pool)                       # (N, pool)
    offs = points[idx] - points[:, None, :]               # (N, pool, 3)
    subs = jnp.asarray(subset_combinations(pool, n_neighbors))  # (S, n_use)
    sel = offs[:, subs, :]                                # (N, S, n_use, 3)

    if rotation_invariant:
        # local frame from the subset's two nearest offsets:
        # x along o0; y in span(o0,o1) orthogonal to x; z = x×y (handedness
        # fixed -> reflections are NOT matched, same as the reference)
        o0 = sel[..., 0, :]
        o1 = sel[..., 1 % n_neighbors, :]
        ex = o0 / (jnp.linalg.norm(o0, axis=-1, keepdims=True) + 1e-12)
        ey = o1 - (o1 * ex).sum(-1, keepdims=True) * ex
        ey = ey / (jnp.linalg.norm(ey, axis=-1, keepdims=True) + 1e-12)
        ez = jnp.cross(ex, ey)
        frame = jnp.stack([ex, ey, ez], axis=-1)          # (N, S, 3, 3) cols=basis
        sel = jnp.einsum("nsji,nskj->nski", frame, sel)   # coords in local frame

    desc = sel.reshape(n, -1, n_neighbors * 3)            # (N, S, d)
    s = desc.shape[1]
    owner = jnp.repeat(jnp.arange(n, dtype=jnp.int32), s)
    return desc.reshape(n * s, -1).astype(jnp.float32), owner


def block_descriptors_impl(points, valid, n_neighbors: int = 3,
                           redundancy: int = 1,
                           rotation_invariant: bool = True):
    """Per-point descriptors of one detection block's FIXED-K candidate
    list (padded slots flagged by ``valid``) — the extract half of the
    fused detect+extract program (ops.dog.dog_detect_extract_impl), where
    the peaks never leave HBM between the DoG top-K and this.

    Same subset/frame math as :func:`build_descriptors`; the kNN is
    masked by VALIDITY instead of run on a dense cloud: invalid rows and
    columns (and the diagonal) get +inf DISTANCE — the coordinates are
    never poisoned, because an inf-inf arithmetic path would NaN the
    distances and break top_k ordering. Invalid offsets are zeroed before
    the frame math so padded slots produce deterministic all-zero
    descriptors. Returns (desc (K, S, n_neighbors*3) float32,
    dvalid (K,) bool); dvalid marks points with a full pool of valid
    neighbors."""
    k = int(points.shape[0])
    pool = n_neighbors + redundancy
    n_subs = len(subset_combinations(pool, n_neighbors))
    if k <= pool:  # static: fewer candidate slots than a neighbor pool
        return (jnp.zeros((k, n_subs, n_neighbors * 3), jnp.float32),
                jnp.zeros((k,), bool))
    p = points.astype(jnp.float32)
    d2 = ((p[:, None, :] - p[None, :, :]) ** 2).sum(-1)       # (K, K)
    pair_ok = valid[:, None] & valid[None, :]
    d2 = jnp.where(pair_ok & ~jnp.eye(k, dtype=bool), d2, jnp.inf)
    neg, idx = jax.lax.top_k(-d2, pool)                       # (K, pool)
    dvalid = valid & (neg[:, -1] > -jnp.inf)  # pool-th neighbor is real
    offs = p[idx] - p[:, None, :]                             # (K, pool, 3)
    offs = jnp.where(dvalid[:, None, None], offs, 0.0)
    subs = jnp.asarray(subset_combinations(pool, n_neighbors))
    sel = offs[:, subs, :]                                    # (K, S, u, 3)
    if rotation_invariant:
        o0 = sel[..., 0, :]
        o1 = sel[..., 1 % n_neighbors, :]
        ex = o0 / (jnp.linalg.norm(o0, axis=-1, keepdims=True) + 1e-12)
        ey = o1 - (o1 * ex).sum(-1, keepdims=True) * ex
        ey = ey / (jnp.linalg.norm(ey, axis=-1, keepdims=True) + 1e-12)
        ez = jnp.cross(ex, ey)
        frame = jnp.stack([ex, ey, ez], axis=-1)
        sel = jnp.einsum("nsji,nskj->nski", frame, sel)
    desc = sel.reshape(k, -1, n_neighbors * 3).astype(jnp.float32)
    return desc, dvalid


def block_descriptors_batch_impl(points, valid, n_neighbors: int = 3,
                                 redundancy: int = 1,
                                 rotation_invariant: bool = True):
    """vmapped :func:`block_descriptors_impl` over a leading batch axis.
    Un-jitted so the mesh layer can wrap it with batch-axis shardings."""
    return jax.vmap(
        lambda pp, vv: block_descriptors_impl(
            pp, vv, n_neighbors, redundancy, rotation_invariant)
    )(points, valid)


block_descriptors_batch = functools.partial(
    jax.jit,
    static_argnames=("n_neighbors", "redundancy", "rotation_invariant"),
)(block_descriptors_batch_impl)


@jax.jit
def _pairwise_sqdist(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(Na,Nb) squared euclidean distances via the matmul identity.

    The clouds are shifted to a common centroid (distance-invariant) and the
    matmul forced to full f32 — TPU matmuls default to bf16 passes, whose
    ~0.4% error would drown small distances under the a²+b²-2ab cancellation.
    """
    c = b.mean(0)
    a = a - c
    b = b - c
    a2 = (a**2).sum(-1)[:, None]
    b2 = (b**2).sum(-1)[None, :]
    ab = jnp.matmul(a, b.T, precision=jax.lax.Precision.HIGHEST)
    return jnp.maximum(a2 + b2 - 2.0 * ab, 0.0)


@jax.jit
def _match_ratio_dense(desc_a, owner_a, desc_b, owner_b, ratio: jnp.ndarray):
    d2 = _pairwise_sqdist(desc_a, desc_b)                 # (Da, Db)
    best = jnp.argmin(d2, axis=1)
    bestd = jnp.take_along_axis(d2, best[:, None], axis=1)[:, 0]
    same_owner = owner_b[None, :] == owner_b[best][:, None]
    d2_masked = jnp.where(same_owner, jnp.inf, d2)
    second = jnp.min(d2_masked, axis=1)
    accept = jnp.sqrt(second) >= ratio * jnp.sqrt(bestd)
    return owner_b[best], accept


@functools.partial(jax.jit, static_argnames=("cb", "topk"))
def _match_ratio_row_chunk(desc_r, desc_b, owner_b, ratio, cb: int,
                           topk: int):
    """One row chunk of the tiled ratio test: scan B in ``cb``-column tiles
    keeping a running per-row top-``topk`` (distance, owner) — memory is
    O(rows*cb). topk must exceed the per-owner descriptor multiplicity so
    the best different-owner distance survives the truncation."""
    db = desc_b.shape[0]
    pad = (-db) % cb
    # pad with zeros (scale-neutral for the centered matmul — huge pad
    # values would wreck the a^2+b^2-2ab cancellation) and mask by owner
    descs = jnp.pad(desc_b, ((0, pad), (0, 0)))
    owners = jnp.pad(owner_b, (0, pad), constant_values=-1)
    r = desc_r.shape[0]
    init = (jnp.full((r, topk), jnp.inf, jnp.float32),
            jnp.full((r, topk), -1, jnp.int32))

    def step(carry, tile):
        vals, owns = carry
        dt, ot = tile
        d2 = _pairwise_sqdist(desc_r, dt)                 # (r, cb)
        d2 = jnp.where(ot[None, :] == -1, jnp.inf, d2)
        allv = jnp.concatenate([vals, d2], axis=1)
        allo = jnp.concatenate([owns, jnp.broadcast_to(ot, (r, cb))], axis=1)
        nv, ni = jax.lax.top_k(-allv, topk)
        return (-nv, jnp.take_along_axis(allo, ni, axis=1)), None

    (vals, owns), _ = jax.lax.scan(
        step, init, (descs.reshape(-1, cb, descs.shape[1]),
                     owners.reshape(-1, cb)))
    best_owner = owns[:, 0]
    bestd = vals[:, 0]
    diff = owns != best_owner[:, None]
    second = jnp.min(jnp.where(diff, vals, jnp.inf), axis=1)
    accept = jnp.sqrt(second) >= ratio * jnp.sqrt(bestd)
    return best_owner, accept


def match_ratio_test(desc_a, owner_a, desc_b, owner_b, ratio,
                     max_owner_multiplicity: int = 6):
    """Best-vs-second-best candidate matching.

    For each descriptor of A: nearest and second-nearest descriptor of B
    (second-nearest restricted to a DIFFERENT owner point, so redundant
    descriptors of one point don't veto themselves); accept if
    second/best >= ratio (mpicbg nearest-neighbor-distance-ratio test).
    Returns (match_b (Da,) int32 owner index in B, accept (Da,) bool).

    Small problems take the dense (Da,Db) kernel; large ones are tiled in
    row chunks x column tiles with a running top-k, so 1e5-point views
    (dense would need tens of GB) run in bounded memory.
    """
    da, db = int(desc_a.shape[0]), int(desc_b.shape[0])
    if da * db <= _TILE_ENTRIES:
        return _match_ratio_dense(desc_a, owner_a, desc_b, owner_b,
                                  jnp.float32(ratio))
    # one upload each, shared by every row chunk (numpy inputs used to ride
    # up the wire once per chunk before the asarray hoist; device_put makes
    # the single staging explicit and async)
    desc_a = jax.device_put(desc_a)
    desc_b = jax.device_put(desc_b)
    owner_b = jax.device_put(owner_b)
    rb = _row_block(min(db, 1 << 16))
    cb = 1 << 14
    topk = max(8, max_owner_multiplicity + 2)
    # row chunks dispatch in BYTE-BUDGETED segments instead of all at once
    # (unbounded dispatch pinned every chunk's row slice + scan workspace
    # simultaneously, so device memory scaled with da/rb): each in-flight
    # chunk pins its row slice, the (rb, cb) distance tile + top-k scan
    # carry, and its output tables; segment k+1 dispatches before segment
    # k drains — one pipelined device_get per segment, up to two segments
    # resident — so the device never idles between segments
    from ..utils.devicemem import InflightWindow, dispatch_budget_bytes

    dim = int(desc_a.shape[1])
    chunk_cost = (rb * dim * 4          # row slice copy
                  + 2 * rb * cb * 4     # distance tile + masked variant
                  + rb * (topk + cb) * 8)  # scan carry + top_k workspace
    # under the pair scheduler this runs pinned to a worker's device
    # (thread-local jax.default_device); size the segment window from THAT
    # device's PER-WORKER budget — N concurrent workers each claiming the
    # whole process fallback would pin N x the intended bytes, while
    # dividing by more workers than actually run shrinks the window and
    # pays avoidable sync round-trips
    own_dev = getattr(jax.config, "jax_default_device", None)
    if own_dev is not None:
        from ..parallel.pairsched import concurrent_pair_workers
        from ..utils.devicemem import pair_budget_bytes

        budget = pair_budget_bytes(own_dev, concurrent_pair_workers())
    else:
        budget = dispatch_budget_bytes()
    per_seg = max(1, int(budget // (2 * chunk_cost)))
    window = InflightWindow()
    starts = list(range(0, da, rb))
    ratio32 = jnp.float32(ratio)
    owners: list[np.ndarray] = []
    accepts: list[np.ndarray] = []

    def drain(seg):
        try:
            got = jax.device_get(seg)
        finally:
            # drained or dead, the buffers leave the ledger either way
            window.release(chunk_cost * len(seg))
        for o, a in got:
            owners.append(o)
            accepts.append(a)

    prev = None
    for s0 in range(0, len(starts), per_seg):
        seg = []
        for s in starts[s0:s0 + per_seg]:
            seg.append(_match_ratio_row_chunk(desc_a[s:s + rb], desc_b,
                                              owner_b, ratio32, cb, topk))
            window.charge(chunk_cost)
        if prev is not None:
            drain(prev)
        prev = seg
    if prev is not None:
        drain(prev)
    return np.concatenate(owners), np.concatenate(accepts)


def match_candidates(
    points_a: np.ndarray,
    points_b: np.ndarray,
    method: str = GEOMETRIC_HASHING,
    n_neighbors: int = 3,
    redundancy: int = 1,
    ratio_of_distance: float = 3.0,
) -> np.ndarray:
    """Descriptor-based correspondence candidates between two clouds.

    Returns (M,2) int32 [index_a, index_b] with duplicates removed. Needs
    at least n_neighbors+redundancy+1 points per cloud.
    """
    pool = n_neighbors + redundancy
    if len(points_a) <= pool or len(points_b) <= pool:
        return np.zeros((0, 2), np.int32)
    rot = method == GEOMETRIC_HASHING
    da, oa = build_descriptors(jnp.asarray(points_a, jnp.float32),
                               n_neighbors, redundancy, rot)
    db, ob = build_descriptors(jnp.asarray(points_b, jnp.float32),
                               n_neighbors, redundancy, rot)
    # per-owner descriptor multiplicity bounds the tiled top-k truncation
    n_subsets = len(subset_combinations(pool, n_neighbors))
    mb, acc = match_ratio_test(da, oa, db, ob,
                               jnp.float32(ratio_of_distance),
                               max_owner_multiplicity=n_subsets)
    oa, mb, acc = np.asarray(oa), np.asarray(mb), np.asarray(acc)
    pairs = np.stack([oa[acc], mb[acc]], axis=1)
    return np.unique(pairs, axis=0).astype(np.int32)


# --------------------------------------------------------------------------
# RANSAC
# --------------------------------------------------------------------------

@functools.partial(
    jax.jit,
    static_argnames=("model_kind", "reg_kind", "iterations", "sample", "lam"),
)
def _ransac_kernel(pa, pb, valid, key, epsilon, lam,
                   model_kind, reg_kind, iterations, sample):
    m = pa.shape[0]
    keys = jax.random.split(key, iterations)
    idx = jax.vmap(
        lambda k: jax.random.choice(k, m, (sample,), replace=False,
                                    p=valid / valid.sum())
    )(keys)                                               # (I, sample)
    sp = pa[idx]                                          # (I, sample, 3)
    sq = pb[idx]
    models = fit_model(model_kind, sp, sq, xp=jnp)        # (I, 3, 4)
    pred = jnp.einsum("iab,mb->ima", models[:, :, :3], pa) + models[:, None, :, 3]
    err = jnp.linalg.norm(pred - pb[None], axis=-1)       # (I, M)
    inl = (err < epsilon) & (valid[None, :] > 0)
    counts = inl.sum(-1)
    best = jnp.argmax(counts)
    w = inl[best].astype(pa.dtype)
    final = fit_interpolated(model_kind, reg_kind, lam, pa, pb, w, xp=jnp)
    # one consensus re-fit round on the final model's inliers
    pred = pa @ final[:, :3].T + final[:, 3]
    err2 = jnp.linalg.norm(pred - pb, axis=-1)
    w2 = ((err2 < epsilon) & (valid > 0)).astype(pa.dtype)
    final = fit_interpolated(model_kind, reg_kind, lam, pa, pb, w2, xp=jnp)
    pred = pa @ final[:, :3].T + final[:, 3]
    err3 = jnp.linalg.norm(pred - pb, axis=-1)
    inliers = (err3 < epsilon) & (valid > 0)
    return final, inliers, counts[best]


@functools.partial(
    jax.jit, static_argnames=("model_kind", "iterations", "sample"),
)
def _ransac_score_chunk(pa, pb, valid, key, epsilon,
                        model_kind, iterations, sample):
    """Score one chunk of hypotheses; returns (best_count, best_model).
    Used for big candidate sets where (10k, M) error matrices would not fit;
    the (iterations, M) tile is bounded by the caller's chunking."""
    m = pa.shape[0]
    keys = jax.random.split(key, iterations)
    idx = jax.vmap(
        lambda k: jax.random.choice(k, m, (sample,), replace=False,
                                    p=valid / valid.sum())
    )(keys)
    models = fit_model(model_kind, pa[idx], pb[idx], xp=jnp)
    pred = jnp.einsum("iab,mb->ima", models[:, :, :3], pa) + models[:, None, :, 3]
    err = jnp.linalg.norm(pred - pb[None], axis=-1)
    counts = ((err < epsilon) & (valid[None, :] > 0)).sum(-1)
    best = jnp.argmax(counts)
    return counts[best], models[best]


def ransac(
    cand_a: np.ndarray,
    cand_b: np.ndarray,
    model_kind: str = "AFFINE",
    reg_kind: str = "RIGID",
    lam: float = 0.1,
    epsilon: float = 5.0,
    min_inlier_ratio: float = 0.1,
    min_inliers: int = 12,
    iterations: int = 10000,
    seed: int = 17,
) -> tuple[np.ndarray, np.ndarray] | None:
    """Hypothesis-parallel RANSAC over candidate correspondences.

    cand_a/cand_b: (M,3) matched candidate coordinates. Returns
    (model 3x4, inlier_mask (M,)) or None if consensus is too small
    (RANSAC defaults: SparkGeometricDescriptorMatching.java:180-189).
    Candidates are padded to the next power of two so compilation is shared
    across pairs of similar size. Sets too large for one (10k, M) error
    matrix are scored in iteration chunks with the consensus refits on host.
    """
    m = len(cand_a)
    sample = max(MIN_POINTS[model_kind], MIN_POINTS.get(reg_kind, 0), 1)
    if m < max(min_inliers, sample):
        return None
    padded = 1 << int(np.ceil(np.log2(max(m, 8))))
    pa = np.zeros((padded, 3), np.float32)
    pb = np.zeros((padded, 3), np.float32)
    val = np.zeros(padded, np.float32)
    pa[:m], pb[:m], val[:m] = cand_a, cand_b, 1.0

    if int(iterations) * padded <= _TILE_ENTRIES * 2:
        model, inliers, _ = _ransac_kernel(
            jnp.asarray(pa), jnp.asarray(pb), jnp.asarray(val),
            jax.random.PRNGKey(seed), jnp.float32(epsilon), float(lam),
            model_kind, reg_kind, int(iterations), int(sample),
        )
        inliers = np.asarray(inliers)[:m]
    else:
        chunk = max(64, (_TILE_ENTRIES * 2) // padded)
        ja, jb, jv = jnp.asarray(pa), jnp.asarray(pb), jnp.asarray(val)
        best_count, best_model = -1, None
        done = 0
        while done < int(iterations):
            it = int(min(chunk, int(iterations) - done))
            c, mdl = _ransac_score_chunk(
                ja, jb, jv, jax.random.PRNGKey(seed + done),
                jnp.float32(epsilon), model_kind, it, int(sample))
            if int(c) > best_count:
                best_count, best_model = int(c), np.asarray(mdl, np.float64)
            done += it
        # consensus refits on host (mirror of _ransac_kernel's tail)
        a64 = np.asarray(cand_a, np.float64)
        b64 = np.asarray(cand_b, np.float64)
        w = (np.linalg.norm(
            a64 @ best_model[:, :3].T + best_model[:, 3] - b64, axis=-1)
            < epsilon).astype(np.float64)
        mdl = fit_interpolated(model_kind, reg_kind, lam, a64, b64, w)
        w2 = (np.linalg.norm(a64 @ mdl[:, :3].T + mdl[:, 3] - b64, axis=-1)
              < epsilon).astype(np.float64)
        mdl = fit_interpolated(model_kind, reg_kind, lam, a64, b64, w2)
        inliers = np.linalg.norm(
            a64 @ mdl[:, :3].T + mdl[:, 3] - b64, axis=-1) < epsilon

    n_in = int(inliers.sum())
    if n_in < min_inliers or n_in < min_inlier_ratio * m:
        return None
    # final f64 refit on the inlier set (the device kernel runs f32)
    model = fit_interpolated(model_kind, reg_kind, lam,
                             np.asarray(cand_a, np.float64)[inliers],
                             np.asarray(cand_b, np.float64)[inliers])
    # bst-lint: off=host-sync (fit_interpolated xp=np: host f64 refit)
    return np.asarray(model, np.float64), inliers


def ransac_multi(
    cand_a: np.ndarray,
    cand_b: np.ndarray,
    model_kind: str = "AFFINE",
    reg_kind: str = "RIGID",
    lam: float = 0.1,
    epsilon: float = 5.0,
    min_inlier_ratio: float = 0.1,
    min_inliers: int = 12,
    iterations: int = 10000,
    seed: int = 17,
) -> list[tuple[np.ndarray, np.ndarray]]:
    """Multi-consensus RANSAC (RANSACParameters multiconsensus option,
    SparkGeometricDescriptorMatching.java:145-146,307): repeatedly find the
    largest consensus among the REMAINING candidates, remove its inliers,
    and continue until no consensus is left (the reference's loop
    semantics) — so a pair whose correspondences follow several distinct
    transforms (e.g. grouped tiles moving independently) yields every set.

    Returns [(model 3x4, inlier_mask over the ORIGINAL candidates), ...]
    ordered by discovery (largest consensus first in practice). Terminates:
    every accepted set removes >= min_inliers >= 1 candidates."""
    remaining = np.arange(len(cand_a))
    out: list[tuple[np.ndarray, np.ndarray]] = []
    round_i = 0
    while len(remaining) >= max(min_inliers, 1):
        res = ransac(cand_a[remaining], cand_b[remaining], model_kind,
                     reg_kind, lam, epsilon, min_inlier_ratio, min_inliers,
                     iterations, seed=seed + round_i)
        if res is None:
            break
        model, inl = res
        mask = np.zeros(len(cand_a), bool)
        mask[remaining[inl]] = True
        out.append((model, mask))
        remaining = remaining[~inl]
        round_i += 1
    return out


# --------------------------------------------------------------------------
# ICP
# --------------------------------------------------------------------------

def icp(
    points_a: np.ndarray,
    points_b: np.ndarray,
    model_kind: str = "AFFINE",
    reg_kind: str = "RIGID",
    lam: float = 0.1,
    max_distance: float = 2.5,
    max_iterations: int = 200,
    min_converged: float = 1e-4,
    use_ransac: bool = False,
    ransac_epsilon: float = 5.0,
    ransac_iterations: int = 200,
    seed: int = 17,
) -> tuple[np.ndarray, np.ndarray] | None:
    """Iterative closest point: A is progressively transformed onto B.

    Returns (model 3x4 mapping a->b, correspondences (K,2) [ia, ib]) or None.
    Defaults follow the reference (200 iterations, 2.5 px max distance).
    The NN assignment each round is one device distance matrix; the model
    refit reuses the batched fits. ``use_ransac`` filters each round's NN
    correspondences through a RANSAC consensus before the refit
    (--icpUseRANSAC, SparkGeometricDescriptorMatching.java:155-156).
    """
    a = np.asarray(points_a, np.float64)
    b = np.asarray(points_b, np.float64)
    if len(a) < MIN_POINTS[model_kind] or len(b) < MIN_POINTS[model_kind]:
        return None
    model = np.hstack([np.eye(3), np.zeros((3, 1))])
    prev_err = np.inf
    pairs = None
    for it in range(max_iterations):
        moved = a @ model[:, :3].T + model[:, 3]
        d2 = np.asarray(_pairwise_sqdist(jnp.asarray(moved, jnp.float32),
                                         jnp.asarray(b, jnp.float32)))
        nn = d2.argmin(1)
        nd = np.sqrt(d2[np.arange(len(a)), nn])
        keep = nd < max_distance
        if keep.sum() < max(MIN_POINTS[model_kind], 3):
            return None
        pairs = np.stack([np.where(keep)[0], nn[keep]], 1)
        if use_ransac:
            res = ransac(a[pairs[:, 0]], b[pairs[:, 1]], model_kind,
                         reg_kind, lam, epsilon=ransac_epsilon,
                         min_inlier_ratio=0.0,
                         min_inliers=max(MIN_POINTS[model_kind], 3),
                         iterations=ransac_iterations, seed=seed + it)
            if res is not None:
                pairs = pairs[res[1]]
        model = fit_interpolated(model_kind, reg_kind, lam,
                                 a[pairs[:, 0]], b[pairs[:, 1]])
        err = float(nd[keep].mean())
        if abs(prev_err - err) < min_converged:
            break
        prev_err = err
    return model, pairs.astype(np.int32)

"""3-D transformation-model fitting: weighted least squares for translation /
rigid / affine point-correspondence fits, plus regularization by model
interpolation.

Role of ``mpicbg.models.{TranslationModel3D, RigidModel3D, AffineModel3D,
InterpolatedAffineModel3D}`` used by the reference at
AbstractRegistration.java:110-140 and Solver.java:294-369. All fits map point
sets p -> q (``q ~= M @ [p;1]``), weighted; models are 3x4 row-major affines
(utils.geometry convention).

Everything here is written against the numpy API surface shared by
``numpy``/``jax.numpy`` so the same math serves the host-side solver (numpy)
and the vmapped RANSAC hypothesis kernels (jax) — pass ``xp=jax.numpy`` to
fit under jit.
"""

from __future__ import annotations

import numpy as np

TRANSLATION = "TRANSLATION"
RIGID = "RIGID"
AFFINE = "AFFINE"
IDENTITY = "IDENTITY"
NONE = "NONE"

MIN_POINTS = {TRANSLATION: 1, RIGID: 3, AFFINE: 4, IDENTITY: 0}


def _wmean(x, w, xp):
    return (x * w[..., None]).sum(-2) / w.sum(-1)[..., None]


def fit_translation(p, q, w=None, xp=np):
    """t = weighted mean(q - p); batched over leading dims."""
    p = xp.asarray(p, dtype=xp.float64 if xp is np else p.dtype)
    q = xp.asarray(q, dtype=p.dtype)
    if w is None:
        w = xp.ones(p.shape[:-1], dtype=p.dtype)
    t = _wmean(q - p, w, xp)
    eye = xp.broadcast_to(xp.eye(3, dtype=p.dtype), p.shape[:-2] + (3, 3))
    return xp.concatenate([eye, t[..., :, None]], axis=-1)


def fit_rigid(p, q, w=None, xp=np):
    """Weighted Kabsch: R = V diag(1,1,det) U^T from the cross-covariance SVD;
    batched over leading dims."""
    p = xp.asarray(p, dtype=xp.float64 if xp is np else p.dtype)
    q = xp.asarray(q, dtype=p.dtype)
    if w is None:
        w = xp.ones(p.shape[:-1], dtype=p.dtype)
    pc = _wmean(p, w, xp)
    qc = _wmean(q, w, xp)
    pp = p - pc[..., None, :]
    qq = q - qc[..., None, :]
    # H = sum_i w_i p_i q_i^T
    h = xp.einsum("...n,...ni,...nj->...ij", w, pp, qq)
    u, _, vt = xp.linalg.svd(h)
    d = xp.linalg.det(xp.swapaxes(vt, -1, -2) @ xp.swapaxes(u, -1, -2))
    sign = xp.stack(
        [xp.ones_like(d), xp.ones_like(d), d], axis=-1
    )
    r = xp.swapaxes(vt, -1, -2) @ (sign[..., :, None] * xp.swapaxes(u, -1, -2))
    t = qc - xp.einsum("...ij,...j->...i", r, pc)
    return xp.concatenate([r, t[..., :, None]], axis=-1)


def fit_affine(p, q, w=None, xp=np, eps=1e-12):
    """Weighted linear least squares for the full 3x4 affine (normal
    equations over homogeneous p; batched over leading dims)."""
    p = xp.asarray(p, dtype=xp.float64 if xp is np else p.dtype)
    q = xp.asarray(q, dtype=p.dtype)
    if w is None:
        w = xp.ones(p.shape[:-1], dtype=p.dtype)
    ones = xp.ones(p.shape[:-1] + (1,), dtype=p.dtype)
    ph = xp.concatenate([p, ones], axis=-1)  # (..., N, 4)
    a = xp.einsum("...n,...ni,...nj->...ij", w, ph, ph)
    b = xp.einsum("...n,...ni,...nk->...ik", w, ph, q)  # (..., 4, 3)
    a = a + eps * xp.eye(4, dtype=p.dtype)
    sol = xp.linalg.solve(a, b)  # (..., 4, 3)
    return xp.swapaxes(sol, -1, -2)


def fit_model(kind: str, p, q, w=None, xp=np):
    if kind == TRANSLATION:
        return fit_translation(p, q, w, xp)
    if kind == RIGID:
        return fit_rigid(p, q, w, xp)
    if kind == AFFINE:
        return fit_affine(p, q, w, xp)
    if kind == IDENTITY:
        p = xp.asarray(p)
        eye = xp.concatenate([xp.eye(3), xp.zeros((3, 1))], axis=-1)
        return xp.broadcast_to(eye, p.shape[:-2] + (3, 4))
    raise ValueError(f"unknown model {kind!r}")


def fit_interpolated(kind: str, reg_kind: str, lam: float, p, q, w=None, xp=np):
    """InterpolatedAffineModel3D semantics: fit both models to the same
    matches, then linearly interpolate the affine entries
    (m = (1-λ)·tm + λ·rm; AbstractRegistration.java:134-140)."""
    m = fit_model(kind, p, q, w, xp)
    if reg_kind == NONE or lam == 0.0:
        return m
    r = fit_model(reg_kind, p, q, w, xp)
    return (1.0 - lam) * m + lam * r


def model_error(m, p, q, w=None, xp=np):
    """Weighted RMS distance ||M(p) - q|| (mpicbg Tile cost)."""
    p = xp.asarray(p)
    q = xp.asarray(q)
    pred = xp.einsum("...ij,...nj->...ni", m[..., :, :3], p) + m[..., None, :, 3]
    d = xp.sqrt(((pred - q) ** 2).sum(-1))
    if w is None:
        return d.mean(-1)
    return (d * w).sum(-1) / w.sum(-1)

"""BDV-style multiresolution image IO on top of the chunk store.

Covers what the reference gets from ``N5ImageLoader``/``N5ApiTools``
(SparkResaveN5.java:233-254, Spark.java:253): the on-disk layout
``setup{S}/timepoint{T}/s{L}`` with ``downsamplingFactors``/``dataType``
attributes on the setup group, plus default mipmap transforms.
"""

from __future__ import annotations

import os
import threading
from typing import Sequence

import numpy as np

from ..utils.geometry import identity_affine
from . import uris
from .chunkstore import ChunkStore, Dataset, Hdf5Store
from .spimdata import SpimData, ViewId


def bdv_dataset_path(setup: int, timepoint: int, level: int) -> str:
    return f"setup{setup}/timepoint{timepoint}/s{level}"


def bdv_hdf5_dataset_path(setup: int, timepoint: int, level: int) -> str:
    """Classic BigDataViewer HDF5 cell layout (read by the reference through
    n5-hdf5 / bdv imgloaders, SparkResaveN5.java:107-457)."""
    return f"t{timepoint:05d}/s{setup:02d}/{level}/cells"


def mipmap_transform(factors: Sequence[float]) -> np.ndarray:
    """Level->full-res affine for averaging downsampling by ``factors``:
    scale by f, shift by (f-1)/2 (MipmapTransforms.getMipmapTransformDefault)."""
    m = identity_affine()
    for d in range(3):
        f = float(factors[d])
        m[d, d] = f
        m[d, 3] = (f - 1.0) / 2.0
    return m


def create_bdv_view_datasets(
    store: ChunkStore,
    setup: int,
    timepoint: int,
    shape: Sequence[int],
    block_size: Sequence[int],
    dtype: str,
    downsampling_factors: Sequence[Sequence[int]] = ((1, 1, 1),),
    compression: str = "zstd",
) -> list[Dataset]:
    """Create s0..sN datasets + BDV metadata for one view. ``shape`` xyz."""
    store.set_attribute(f"setup{setup}", "downsamplingFactors",
                        [list(f) for f in downsampling_factors])
    store.set_attribute(f"setup{setup}", "dataType", np.dtype(dtype).name)
    store.set_attribute(f"setup{setup}/timepoint{timepoint}", "multiScale",
                        len(downsampling_factors) > 1)
    store.set_attribute(f"setup{setup}/timepoint{timepoint}", "resolution",
                        [1.0, 1.0, 1.0])
    out = []
    for level, f in enumerate(downsampling_factors):
        lshape = [max(1, int(s) // int(ff)) for s, ff in zip(shape, f)]
        ds = store.create_dataset(
            bdv_dataset_path(setup, timepoint, level),
            lshape, block_size, dtype, compression=compression,
            delete_existing=True,
        )
        store.set_attribute(ds.path, "downsamplingFactors", [int(v) for v in f])
        out.append(ds)
    return out


class _CropDataset:
    """Read-only window into a source dataset — virtual split views
    (models.splitting; role of the reference's SplitViewerImgLoader)."""

    def __init__(self, ds: Dataset, offset, shape):
        self._ds = ds
        self._off = tuple(int(v) for v in offset)
        self.shape = tuple(int(v) for v in shape)
        self.dtype = ds.dtype

    def read(self, offset, shape):
        src_off = [o + d for o, d in zip(self._off, offset)]
        return self._ds.read(src_off, shape)

    def read_full(self):
        return self._ds.read(self._off, self.shape)


class _ArrayDataset:
    """In-memory read-only stand-in for a chunked Dataset (TIFF stacks)."""

    def __init__(self, arr: np.ndarray):
        self._arr = arr
        self.shape = arr.shape
        self.dtype = arr.dtype

    def read(self, offset, shape):
        sel = tuple(slice(int(o), int(o) + int(s))
                    for o, s in zip(offset, shape))
        return self._arr[sel]

    def read_full(self):
        return self._arr


class _LazyTiffDataset:
    """Defers the full-stack decode until pixels are actually read, so
    metadata probes (.dtype for dataset creation) stay cheap."""

    def __init__(self, tiff: "TiffStackLoader", view, shape):
        self._tiff = tiff
        self._view = view
        self.shape = tuple(int(v) for v in shape)

    @property
    def dtype(self):
        return self._tiff.dtype(self._view)

    def read(self, offset, shape):
        sel = tuple(slice(int(o), int(o) + int(s))
                    for o, s in zip(offset, shape))
        return self._tiff.load(self._view)[sel]

    def read_full(self):
        return self._tiff.load(self._view)


class TiffStackLoader:
    """Legacy TIFF-stack image loader (mvrecon StackImgLoaderIJ family,
    format ``spimreconstruction*``): one multi-page TIFF per view resolved
    from a file pattern with ``{t}/{c}/{i}/{a}`` placeholders. This is the
    input side the reference's resave ingests via bdv imgloaders
    (SparkResaveN5.java:107-457)."""

    def __init__(self, sd: SpimData, base_dir: str):
        raw = sd.image_loader.raw
        if raw is None:
            raise ValueError("TIFF loader needs the raw ImageLoader XML")
        txt = lambda tag, d="": (raw.findtext(tag) or d).strip()
        img_dir = txt("imagedirectory", ".")
        self.directory = (img_dir if os.path.isabs(img_dir)
                          else os.path.join(base_dir, img_dir))
        self.pattern = txt("filePattern")
        if not self.pattern:
            raise ValueError("TIFF loader XML has no <filePattern>")
        self.sd = sd
        self._cache: dict[str, np.ndarray] = {}
        self._lock = threading.Lock()
        self._path_locks: dict[str, threading.Lock] = {}

    def _entity_name(self, attr: str, eid: int) -> str:
        """Pattern placeholders take the entity NAME (angle degrees, channel
        wavelengths — StackImgLoaderIJ semantics), not the numeric id."""
        ent = self.sd.attributes.get(attr, {}).get(eid)
        return ent.name if ent is not None else str(eid)

    def filename(self, view: ViewId) -> str:
        s = self.sd.setups[view.setup]
        name = self.pattern
        subs = {
            "{t}": str(view.timepoint),
            "{c}": self._entity_name("channel", s.attributes.get("channel", 0)),
            "{i}": self._entity_name("illumination",
                                     s.attributes.get("illumination", 0)),
            "{a}": self._entity_name("angle", s.attributes.get("angle", 0)),
        }
        for k, v in subs.items():
            name = name.replace(k, v)
        return os.path.join(self.directory, name)

    def dtype(self, view: ViewId) -> np.dtype:
        """Cheap dtype probe: decode only the first page."""
        path = self.filename(view)
        with self._lock:
            if path in self._cache:
                return self._cache[path].dtype
        from PIL import Image

        with Image.open(path) as im:
            return np.asarray(im).dtype

    def load(self, view: ViewId) -> np.ndarray:
        path = self.filename(view)
        # one decode per file even under the resave thread pool: a per-path
        # lock serializes the decode, the global lock guards the dicts
        with self._lock:
            if path in self._cache:
                return self._cache[path]
            plock = self._path_locks.setdefault(path, threading.Lock())
        with plock:
            with self._lock:
                if path in self._cache:
                    return self._cache[path]
            from PIL import Image

            with Image.open(path) as im:
                pages = []
                for f in range(getattr(im, "n_frames", 1)):
                    im.seek(f)
                    pages.append(np.asarray(im))
            xyz = np.stack(pages).transpose(2, 1, 0)  # pages: z (y,x) slices
            with self._lock:
                if len(self._cache) >= 4:    # bound resident stacks
                    self._cache.pop(next(iter(self._cache)))
                self._cache[path] = xyz
            return xyz


class _LazyCziDataset:
    """Defers the CZI volume assembly until pixels are read."""

    def __init__(self, loader: "CziFileMapLoader", view, shape):
        self._loader = loader
        self._view = view
        self.shape = tuple(int(v) for v in shape)

    @property
    def dtype(self):
        return self._loader.dtype(self._view)

    def read(self, offset, shape):
        sel = tuple(slice(int(o), int(o) + int(s))
                    for o, s in zip(offset, shape))
        return self._loader.load(self._view)[sel]

    def read_full(self):
        return self._loader.load(self._view)


class CziFileMapLoader:
    """CZI input via per-view file mappings (mvrecon FileMapImgLoaderLOCI2,
    format ``spimreconstruction.filemap2``): the dataset XML maps each
    (setup, timepoint) to (file, series, channel); series is the CZI scene.
    This is the input side the reference's resave ingests through bioformats
    (SparkResaveN5.java:107-457); the CZI container itself is parsed by the
    from-scratch reader in ``io.czi``."""

    def __init__(self, sd: SpimData, base_dir: str):
        raw = sd.image_loader.raw
        if raw is None:
            raise ValueError("filemap2 loader needs the raw ImageLoader XML")
        self.sd = sd
        self.base_dir = base_dir
        self.mappings: dict[tuple[int, int], tuple[str, int, int]] = {}
        for fm in raw.findall(".//FileMapping"):
            key = (int(fm.get("view_setup")), int(fm.get("timepoint")))
            path = fm.get("file") or fm.findtext("file") or ""
            if not os.path.isabs(path):
                path = os.path.join(base_dir, path)
            self.mappings[key] = (path, int(fm.get("series", 0)),
                                  int(fm.get("channel", 0)))
        if not self.mappings:
            raise ValueError("filemap2 loader XML has no <FileMapping> entries")
        self._files: dict[str, object] = {}
        self._max_open_files = 16  # bound fds on one-CZI-per-timepoint projects
        self._cache: dict[tuple[int, int], np.ndarray] = {}
        self._dtype_cache: dict[tuple[str, int, int], np.dtype] = {}
        self._lock = threading.Lock()
        self._key_locks: dict[tuple[int, int], threading.Lock] = {}

    def _mapping(self, view: ViewId) -> tuple[str, int, int]:
        try:
            return self.mappings[(view.setup, view.timepoint)]
        except KeyError:
            raise ValueError(
                f"no file mapping for setup {view.setup} "
                f"timepoint {view.timepoint}") from None

    def _czi(self, path: str):
        from .czi import CziFile

        with self._lock:
            cz = self._files.get(path)
            if cz is None:
                while len(self._files) >= self._max_open_files:
                    self._files.pop(next(iter(self._files))).close()
                cz = self._files[path] = CziFile(path)
            return cz

    def dtype(self, view: ViewId) -> np.dtype:
        """Cheap probe from the subblock directory (no pixel decode);
        memoized per (file, scene, channel) — the probe runs on every
        boxed read, the directory scan must not."""
        from .czi import PIXEL_DTYPES

        path, scene, channel = self._mapping(view)
        key = (path, scene, channel)
        with self._lock:
            dt = self._dtype_cache.get(key)
        if dt is not None:
            return dt
        cz = self._czi(path)
        for e in cz.entries:
            if (e.pyramid_type == 0 and e.start("S", 0) == scene
                    and e.start("C", 0) == channel):
                dt = PIXEL_DTYPES.get(e.pixel_type)
                if dt is not None:
                    with self._lock:
                        self._dtype_cache[key] = dt
                    return dt
        raise ValueError(f"{path}: no subblocks for scene={scene} "
                         f"channel={channel}")

    def _file_timepoint(self, cz, scene: int, channel: int,
                        timepoint: int) -> int:
        """Map the project timepoint to the in-file CZI T index: use it when
        the file contains it; otherwise, a file holding a single T (the
        one-CZI-per-timepoint export — the FileMapping already resolved the
        timepoint to this file) maps to that T."""
        ts = {e.start("T", 0) for e in cz.entries
              if (e.pyramid_type == 0 and e.start("S", 0) == scene
                  and e.start("C", 0) == channel)}
        if timepoint in ts:
            return timepoint
        if len(ts) == 1:
            return next(iter(ts))
        raise ValueError(
            f"{cz.path}: project timepoint {timepoint} not in file "
            f"(T indices {sorted(ts)}) and file is multi-timepoint")

    def load(self, view: ViewId) -> np.ndarray:
        path, scene, channel = self._mapping(view)
        key = (view.setup, view.timepoint)
        # per-key lock: one decode per view even under the resave/detection
        # thread pools (same discipline as TiffStackLoader)
        with self._lock:
            if key in self._cache:
                return self._cache[key]
            klock = self._key_locks.setdefault(key, threading.Lock())
        with klock:
            with self._lock:
                if key in self._cache:
                    return self._cache[key]
            cz = self._czi(path)
            t = self._file_timepoint(cz, scene, channel, view.timepoint)
            try:
                vol = cz.read_volume(scene=scene, channel=channel, timepoint=t)
            except NotImplementedError as e:
                if "'I'" not in str(e):
                    raise
                # dual-illumination file: the view setup's illumination
                # attribute selects the in-file I index
                illum = self.sd.setups[view.setup].attributes.get(
                    "illumination", 0)
                vol = cz.read_volume(scene=scene, channel=channel,
                                     timepoint=t, illumination=illum)
            with self._lock:
                if len(self._cache) >= 4:  # bound resident volumes
                    self._cache.pop(next(iter(self._cache)))
                self._cache[key] = vol
            return vol


class ViewLoader:
    """Opens view images of a SpimData project (bdv.n5 loader equivalent)."""

    def __init__(self, spimdata: SpimData):
        self.sd = spimdata
        fmt = spimdata.image_loader.format
        self.is_hdf5 = fmt == "bdv.hdf5"
        self.is_filemap = fmt == "spimreconstruction.filemap2"
        self.is_tiff = fmt.startswith("spimreconstruction") and not self.is_filemap
        if fmt not in ("bdv.n5", "bdv.zarr", "bdv.hdf5") and not self.is_tiff \
                and not self.is_filemap:
            raise NotImplementedError(f"image loader format {fmt!r} not supported yet")
        if self.is_filemap:
            base = os.path.dirname(spimdata.xml_path or ".")
            self.store = None
            self._filemap = CziFileMapLoader(spimdata, base)
        elif self.is_tiff:
            base = os.path.dirname(spimdata.xml_path or ".")
            self.store = None
            self._tiff = TiffStackLoader(spimdata, base)
        elif self.is_hdf5:
            root = spimdata.resolve_loader_path()
            if not os.path.exists(root):
                raise FileNotFoundError(f"image container not found: {root}")
            self.store = Hdf5Store(root, mode="r")
        else:
            root = spimdata.resolve_loader_path()
            if not uris.has_scheme(root) and not os.path.exists(root):
                raise FileNotFoundError(f"image container not found: {root}")
            self.store = ChunkStore.open(root)
        self._cache: dict[tuple, Dataset] = {}
        self._factors_cache: dict[int, list[list[int]]] = {}

    def downsampling_factors(self, setup: int) -> list[list[int]]:
        # split sub-views share the SOURCE setup's stored pyramid; source ids
        # live in the container's namespace (they may collide with sub-view
        # ids, so resolve against the store directly — no recursion)
        split = self.sd.split_info.get(setup)
        src = split[0] if split is not None else setup
        if self.is_tiff or self.is_filemap:
            return [[1, 1, 1]]
        if src not in self._factors_cache:
            if self.is_hdf5:
                # BDV-HDF5 keeps per-setup pyramid factors in the
                # s{XX}/resolutions table (xyz columns)
                res = self.store.get_array(f"s{src:02d}/resolutions")
                f = (res.tolist() if res is not None else None)
            else:
                f = self.store.get_attribute(f"setup{src}", "downsamplingFactors")
            self._factors_cache[src] = [
                [int(v) for v in row] for row in (f or [[1, 1, 1]])
            ]
        return self._factors_cache[src]

    def num_levels(self, setup: int) -> int:
        return len(self.downsampling_factors(setup))

    def _open_raw(self, setup: int, timepoint: int, level: int) -> Dataset:
        key = (setup, timepoint, level)
        if self.is_filemap:
            if level != 0:
                raise ValueError("CZI file maps have no pyramid levels")
            view = ViewId(timepoint, setup)
            return _LazyCziDataset(self._filemap, view,
                                   self.sd.view_size(view))
        if self.is_tiff:
            if level != 0:
                raise ValueError("TIFF stacks have no pyramid levels")
            # lazy: the stack cache lives in TiffStackLoader (bounded);
            # don't pin a second unbounded copy here
            view = ViewId(timepoint, setup)
            return _LazyTiffDataset(self._tiff, view,
                                    self.sd.view_size(view))
        if key not in self._cache:
            path = (bdv_hdf5_dataset_path(setup, timepoint, level)
                    if self.is_hdf5
                    else bdv_dataset_path(setup, timepoint, level))
            self._cache[key] = self.store.open_dataset(path)
        return self._cache[key]

    def open(self, view: ViewId, level: int = 0) -> Dataset:
        split = self.sd.split_info.get(view.setup)
        if split is not None:
            src_setup, off = split
            src = self._open_raw(src_setup, view.timepoint, level)
            f = self.downsampling_factors(view.setup)[level]
            size = self.sd.view_size(view)
            return _CropDataset(
                src,
                [int(o) // int(ff) for o, ff in zip(off, f)],
                [max(1, int(s) // int(ff)) for s, ff in zip(size, f)],
            )
        return self._open_raw(view.setup, view.timepoint, level)

    def mipmap_transform(self, setup: int, level: int) -> np.ndarray:
        return mipmap_transform(self.downsampling_factors(setup)[level])

    def read_block(self, view: ViewId, level: int,
                   offset: Sequence[int], shape: Sequence[int],
                   pad_value: float = 0.0) -> np.ndarray:
        """Read a box, zero-padding parts outside the image (halo over-read)."""
        ds = self.open(view, level)
        full = ds.shape
        lo = [max(0, int(o)) for o in offset]
        hi = [min(int(f), int(o) + int(s)) for f, o, s in zip(full, offset, shape)]
        out = np.full(tuple(int(s) for s in shape), pad_value, dtype=ds.dtype)
        if all(h > l for l, h in zip(lo, hi)):
            data = ds.read(lo, [h - l for l, h in zip(lo, hi)])
            sl = tuple(
                slice(l - int(o), h - int(o)) for l, h, o in zip(lo, hi, offset)
            )
            out[sl] = data
        return out

    def prefetch_box(self, view: ViewId, level: int,
                     offset: Sequence[int], shape: Sequence[int]):
        """``(dataset, clipped offset, clipped shape)`` naming the chunk
        read a later ``read_block(view, level, offset, shape)`` will
        perform — what the async prefetcher feeds (io/prefetch.py) hand
        to ``Dataset.prefetch_box``. None when the clip is empty or the
        view is not chunkstore-backed (TIFF/CZI stacks, in-memory
        stand-ins have no chunk grid to read ahead)."""
        try:
            ds = self.open(view, level)
        except Exception:
            return None
        # clip in the view's own coordinates first (read_block's clip) …
        full = ds.shape
        lo = [max(0, int(o)) for o in offset]
        hi = [min(int(f), int(o) + int(s))
              for f, o, s in zip(full, offset, shape)]
        if any(h <= l for l, h in zip(lo, hi)):
            return None
        # … then unwrap a split-view crop window onto its source dataset
        if isinstance(ds, _CropDataset):
            lo = [l + d for l, d in zip(lo, ds._off)]
            hi = [h + d for h, d in zip(hi, ds._off)]
            ds = ds._ds
        if not hasattr(ds, "prefetch_box"):
            return None
        return ds, tuple(lo), tuple(h - l for l, h in zip(lo, hi))


def best_mipmap_level(
    factors: list[list[int]], target_downsampling: Sequence[float],
    accepted_error: float = 0.02,
) -> int:
    """Pick the coarsest stored level not coarser than ``target_downsampling``
    (replicates the FusionTools.fuseVirtual level pick, ViewUtil.java:425-493:
    largest level whose factors are <= target*(1+acceptedError) per axis)."""
    best = 0
    for lvl, f in enumerate(factors):
        ok = all(
            float(f[d]) <= float(target_downsampling[d]) * (1.0 + accepted_error)
            for d in range(3)
        )
        if ok and np.prod(f) >= np.prod(factors[best]):
            best = lvl
    return best

"""URI handling for cloud-capable storage roots (URITools role).

The reference reads/writes projects and containers on file/S3/GCS via
mvrecon ``URITools`` + n5-aws-s3/n5-universe (util/N5Util.java:47-80,
AbstractInfrastructure.java:20-27 ``--s3Region``). Here every root is either
a plain local path or a ``scheme://`` URI; tensorstore supplies the s3/gcs
(and in-process ``memory``) kvstore drivers, so this module only parses
URIs, builds kvstore specs, and does posix-style joins for non-local roots.
"""

from __future__ import annotations

import os

from .. import config

# Setter overrides for the s3 region/endpoint (--s3Region equivalent and
# MinIO/on-prem/test-fake endpoints). The sentinel keeps override and
# environment separate: until a setter runs, every get reads
# BST_S3_REGION/BST_S3_ENDPOINT through the config registry at CALL time
# (the old import-time snapshot silently ignored env set after import —
# exactly what tests and `bst` subprocesses do); an explicit setter call,
# including set_*(None), wins from then on.
_UNSET = object()
_S3_REGION: list = [_UNSET]
_S3_ENDPOINT: list = [_UNSET]


def set_s3_region(region: str | None) -> None:
    _S3_REGION[0] = region or None


def get_s3_region() -> str | None:
    if _S3_REGION[0] is _UNSET:
        return config.get_str("BST_S3_REGION")
    return _S3_REGION[0]


def set_s3_endpoint(endpoint: str | None) -> None:
    _S3_ENDPOINT[0] = endpoint or None


def get_s3_endpoint() -> str | None:
    if _S3_ENDPOINT[0] is _UNSET:
        return config.get_str("BST_S3_ENDPOINT")
    return _S3_ENDPOINT[0]


def has_scheme(path: str | os.PathLike) -> bool:
    p = str(path)
    return "://" in p and not p.startswith("file://")


def strip_file_scheme(path: str | os.PathLike) -> str:
    """``file:///x`` -> ``/x``; other paths unchanged. Apply at every entry
    point that treats a path as local."""
    p = str(path)
    return p[len("file://"):] if p.startswith("file://") else p


def split_uri(path: str | os.PathLike) -> tuple[str, str, str]:
    """``s3://bucket/a/b`` -> ("s3", "bucket", "a/b"); local -> ("file", "", path)."""
    p = str(path)
    if p.startswith("file://"):
        return "file", "", p[len("file://"):]
    if "://" not in p:
        return "file", "", p
    scheme, rest = p.split("://", 1)
    if scheme == "memory":
        return "memory", "", rest
    bucket, _, key = rest.partition("/")
    return scheme, bucket, key


def join(base: str | os.PathLike, *parts: str) -> str:
    """Join path components; posix-style for URIs, os.path locally."""
    base = str(base)
    cleaned = [p.strip("/") for p in parts if p and p.strip("/")]
    if has_scheme(base):
        return "/".join([base.rstrip("/")] + cleaned)
    return os.path.join(base, *cleaned)


def dirname(path: str | os.PathLike) -> str:
    p = str(path)
    if has_scheme(p):
        scheme, rest = p.split("://", 1)
        head = rest.rsplit("/", 1)[0] if "/" in rest else rest
        return f"{scheme}://{head}"
    return os.path.dirname(p)


def normpath(path: str | os.PathLike) -> str:
    """Collapse ``.``/``..`` segments; URI-aware."""
    p = str(path)
    if not has_scheme(p):
        return os.path.normpath(p)
    scheme, rest = p.split("://", 1)
    segs: list[str] = []
    for s in rest.split("/"):
        if s in ("", "."):
            continue
        if s == ".." and segs and segs[-1] != "..":
            segs.pop()
        else:
            segs.append(s)
    return f"{scheme}://" + "/".join(segs)


def kvstore_spec(root: str | os.PathLike, subpath: str = "") -> dict:
    """Tensorstore kvstore spec for ``root/subpath``.

    Non-file schemes mirror the reference's writer-per-URI factory
    (N5Util.java:47-80); ``s3`` honours the --s3Region default."""
    scheme, bucket, key = split_uri(root)
    full = "/".join([s for s in (key.strip("/"), subpath.strip("/")) if s])
    if scheme == "file":
        return {"driver": "file", "path": os.path.join(str(root).replace(
            "file://", "", 1), subpath.strip("/")) if subpath else
            str(root).replace("file://", "", 1)}
    if scheme == "memory":
        return {"driver": "memory", "path": full + "/" if full else ""}
    if scheme == "s3":
        spec = {"driver": "s3", "bucket": bucket,
                "path": full + "/" if full else ""}
        if get_s3_region():
            spec["aws_region"] = get_s3_region()
        if get_s3_endpoint():
            spec["endpoint"] = get_s3_endpoint()
        return spec
    if scheme == "gs":
        return {"driver": "gcs", "bucket": bucket,
                "path": full + "/" if full else ""}
    raise ValueError(f"unsupported storage scheme {scheme!r} in {root!r}")


def read_bytes(uri: str | os.PathLike) -> bytes:
    """Read a single object (local file or cloud URI)."""
    if not has_scheme(uri):
        with open(strip_file_scheme(uri), "rb") as f:
            return f.read()
    import tensorstore as ts

    from .chunkstore import ts_context

    parent = dirname(uri)
    name = str(uri).rsplit("/", 1)[1]
    kv = ts.KvStore.open(kvstore_spec(parent), context=ts_context()).result()
    r = kv.read(name).result()
    if r.state != "value":
        raise FileNotFoundError(uri)
    return bytes(r.value)


def write_bytes(uri: str | os.PathLike, data: bytes) -> None:
    if not has_scheme(uri):
        local = strip_file_scheme(uri)
        os.makedirs(os.path.dirname(local) or ".", exist_ok=True)
        with open(local, "wb") as f:
            f.write(data)
        return
    import tensorstore as ts

    from .chunkstore import ts_context

    parent = dirname(uri)
    name = str(uri).rsplit("/", 1)[1]
    kv = ts.KvStore.open(kvstore_spec(parent), context=ts_context()).result()
    kv.write(name, data).result()

"""interestpoints.n5 store: detected points + correspondences per (view, label).

On-disk schema matches the reference (mvrecon ``InterestPointsN5``; layout
visible in SpimData2Util.java:49-162) so the BigStitcher GUI stays the oracle:

    interestpoints.n5/tpId_{t}_viewSetupId_{s}/{label}/
        interestpoints/id    uint64  [1, N]   (dim0 = component, dim1 = point)
        interestpoints/loc   float64 [3, N]
        correspondences/data uint64  [3, M]   rows = (idA, idB, pairCode)
          attrs: "correspondences": version str,
                 "idMap": {"tp,setup,label": pairCode}

The XML's ``<ViewInterestPointsFile>`` elements point at the per-view group
path (``InterestPointLookup.path`` in io.spimdata).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

from .chunkstore import ChunkStore, StorageFormat
from .spimdata import InterestPointLookup, SpimData, ViewId

BLOCK = 30000  # points per storage block (reference default block size ~300k/10)


def view_group(view: ViewId, label: str) -> str:
    return f"tpId_{view.timepoint}_viewSetupId_{view.setup}/{label}"


@dataclass
class CorrespondingPoint:
    """One correspondence of a detection in the owning (view, label) to a
    detection in another (view, label) (mvrecon CorrespondingInterestPoints)."""

    id: int
    other_view: ViewId
    other_label: str
    other_id: int


class InterestPointStore:
    def __init__(self, root: str):
        self.root = str(root)
        if os.path.isdir(self.root):
            self.store = ChunkStore.open(self.root)
        else:
            self.store = ChunkStore.create(self.root, StorageFormat.N5)

    @staticmethod
    def for_project(sd: SpimData) -> "InterestPointStore":
        base = os.path.dirname(sd.xml_path or ".")
        return InterestPointStore(os.path.join(base, "interestpoints.n5"))

    # ----------------------------------------------------------------- points

    def save_points(
        self,
        view: ViewId,
        label: str,
        locs: np.ndarray,
        ids: np.ndarray | None = None,
        intensities: np.ndarray | None = None,
    ) -> str:
        """Write N detections; returns the group path for the XML lookup."""
        locs = np.asarray(locs, dtype=np.float64).reshape(-1, 3)
        n = len(locs)
        if ids is None:
            ids = np.arange(n, dtype=np.uint64)
        grp = view_group(view, label)
        base = f"{grp}/interestpoints"
        for sub in (base, f"{grp}/intensities"):
            if self.store.exists(sub):
                self.store.remove(sub)
        # xyz-first logical order: dataset dims (component, point)
        did = self.store.create_dataset(
            f"{base}/id", (1, max(n, 1)), (1, BLOCK), "uint64"
        )
        dloc = self.store.create_dataset(
            f"{base}/loc", (3, max(n, 1)), (3, BLOCK), "float64"
        )
        if n:
            did.write(np.asarray(ids, np.uint64).reshape(1, n), (0, 0))
            dloc.write(locs.T.copy(), (0, 0))
        self.store.set_attribute(base, "pointcloud", "1.0.0")
        self.store.set_attribute(base, "type", "list")
        # datasets are padded to >=1 row; record the true count
        self.store.set_attribute(base, "numPoints", int(n))
        if intensities is not None and n:
            dint = self.store.create_dataset(
                f"{grp}/intensities/i", (1, n), (1, BLOCK), "float64"
            )
            dint.write(np.asarray(intensities, np.float64).reshape(1, n), (0, 0))
        return grp

    def load_points(self, view: ViewId, label: str) -> tuple[np.ndarray, np.ndarray]:
        """-> (ids (N,) uint64, locs (N,3) float64); empty arrays if absent."""
        base = f"{view_group(view, label)}/interestpoints"
        if not self.store.is_dataset(f"{base}/id"):
            return np.zeros(0, np.uint64), np.zeros((0, 3))
        ids = self.store.open_dataset(f"{base}/id").read_full()[0]
        locs = self.store.open_dataset(f"{base}/loc").read_full().T
        # our empty saves are padded to one zero row; "numPoints" records the
        # true count (absent on foreign stores, whose datasets are exact-size)
        n = self.store.get_attribute(base, "numPoints", None)
        if n is not None:
            ids, locs = ids[: int(n)], locs[: int(n)]
        return ids.astype(np.uint64), locs.astype(np.float64)

    # -------------------------------------------------------------- correspondences

    def save_correspondences(
        self, view: ViewId, label: str, corrs: list[CorrespondingPoint]
    ) -> None:
        grp = view_group(view, label)
        base = f"{grp}/correspondences"
        if self.store.exists(base):
            self.store.remove(base)
        id_map: dict[str, int] = {}
        rows = np.zeros((3, max(len(corrs), 1)), dtype=np.uint64)
        for i, c in enumerate(corrs):
            key = f"{c.other_view.timepoint},{c.other_view.setup},{c.other_label}"
            code = id_map.setdefault(key, len(id_map))
            rows[:, i] = (c.id, c.other_id, code)
        d = self.store.create_dataset(
            f"{base}/data", rows.shape, (3, BLOCK), "uint64"
        )
        if corrs:
            d.write(rows, (0, 0))
        self.store.set_attribute(base, "correspondences", "1.0.0")
        self.store.set_attribute(base, "idMap", id_map)

    def load_correspondences(self, view: ViewId, label: str) -> list[CorrespondingPoint]:
        base = f"{view_group(view, label)}/correspondences"
        if not self.store.is_dataset(f"{base}/data"):
            return []
        id_map = self.store.get_attribute(base, "idMap", {}) or {}
        if not id_map:
            return []
        decode = {}
        for key, code in id_map.items():
            tp, setup, lab = key.split(",", 2)
            decode[int(code)] = (ViewId(int(tp), int(setup)), lab)
        rows = self.store.open_dataset(f"{base}/data").read_full()
        out = []
        for ida, idb, code in rows.T:
            ov, ol = decode[int(code)]
            out.append(CorrespondingPoint(int(ida), ov, ol, int(idb)))
        return out

    def clear_correspondences(self, view: ViewId, label: str) -> None:
        base = f"{view_group(view, label)}/correspondences"
        if self.store.exists(base):
            self.store.remove(base)

    def remove_view(self, view: ViewId, label: str | None = None) -> None:
        """Delete one label (or the whole view group) — ClearInterestPoints."""
        grp = view_group(view, label) if label else f"tpId_{view.timepoint}_viewSetupId_{view.setup}"
        if self.store.exists(grp):
            self.store.remove(grp)


def register_points_in_xml(
    sd: SpimData, view: ViewId, label: str, params: str, group_path: str
) -> None:
    """Record the store pointer in the project XML (InterestPointTools role)."""
    sd.interest_points.setdefault(view, {})[label] = InterestPointLookup(
        label=label, params=params, path=group_path
    )

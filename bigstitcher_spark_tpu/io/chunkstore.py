"""Chunked-array storage layer: N5, ZARR (v2 / OME-ZARR), HDF5.

TPU-native replacement for the reference's L1 (n5/n5-zarr/n5-hdf5 writers,
util/N5Util.java:45-105): tensorstore does the chunk IO (async, C codecs),
h5py covers HDF5 (local-only, same restriction as the reference's
CreateFusionContainer.java:141-145).

All public APIs use **xyz-first logical axis order** (N5/imglib2 convention —
first axis fastest). For the zarr driver, whose on-disk shape is C-order
(e.g. OME-NGFF ``[t,c,z,y,x]``), the wrapper reverses axes at the boundary so
callers never see driver-specific order. Group attributes are plain JSON files
(``attributes.json`` / ``.zattrs``) manipulated directly, with N5-style nested
key paths (``setAttribute("/", "a/b", v)`` -> ``{"a": {"b": v}}``).
"""

from __future__ import annotations

import enum
import json
import os
import shutil
from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np
import tensorstore as ts

from . import chunkcache, uris
from .. import config, profiling
from ..observe import events as _events
from ..observe import metrics as _metrics
from ..observe import trace as _trace

# remote-object-store traffic, counted SEPARATELY from the per-impl io
# counters above: these are the bytes that actually crossed the network
# (or the fake-S3 loopback), the denominator every warm-cache / prefetch
# claim in bench measure_cloud and scripts/cloud_smoke.sh is checked
# against ("warm rerun reads 0 remote bytes" is asserted on these)
_REMOTE_READ_BYTES = _metrics.counter("bst_io_remote_read_bytes_total")
_REMOTE_WRITE_BYTES = _metrics.counter("bst_io_remote_write_bytes_total")
_PREFETCH_BYTES = _metrics.counter("bst_io_prefetch_bytes_total")
_UPLOAD_INFLIGHT = _metrics.gauge("bst_io_upload_inflight")

# per-run pin folded into remote cache signatures (BST_REMOTE_CACHE=run):
# bumping it orphans every remote-keyed cache entry at once, the coarse
# invalidation lever for "another writer may have touched the bucket"
_REMOTE_PIN = [0]


def remote_pin() -> int:
    return _REMOTE_PIN[0]


def bump_remote_pin() -> int:
    """Start a new remote-cache coherence window: every cached remote
    chunk keyed under the old pin becomes unreachable (and ages out of
    the LRU). The serve daemon calls this at each job start; a one-shot
    CLI process is a single window (pin 0) its whole life."""
    _REMOTE_PIN[0] += 1
    return _REMOTE_PIN[0]

# one (bytes, chunk-ops) counter pair per (op, path-taken) — cached so the
# hot path pays one dict lookup + two lock'd adds per box read/write, which
# also records WHICH implementation served it (native codec vs tensorstore
# vs h5py), the tuning signal for the native-IO fast paths
_IO_COUNTERS: dict[tuple[str, str], tuple] = {}


def _record_io(op: str, via: str, nbytes: int, dataset: str) -> None:
    pair = _IO_COUNTERS.get((op, via))
    if pair is None:
        # literal series names per op branch so every metric string is
        # declared in observe/metric_names.py (the metric-name lint check
        # bans constructed names — a typo'd op would otherwise mint a
        # silent zero-valued series)
        if op == "read":
            pair = (_metrics.counter("bst_io_read_bytes_total", path=via),
                    _metrics.counter("bst_io_read_ops_total", path=via))
        else:
            pair = (_metrics.counter("bst_io_write_bytes_total", path=via),
                    _metrics.counter("bst_io_write_ops_total", path=via))
        _IO_COUNTERS[(op, via)] = pair
    pair[0].inc(int(nbytes))
    pair[1].inc()
    if _trace.enabled():
        # timeline marks with byte payload (literal names per branch —
        # the span-name lint check bans constructed names)
        if op == "read":
            _trace.instant("io.read", stage=via, nbytes=int(nbytes))
        else:
            _trace.instant("io.write", stage=via, nbytes=int(nbytes))
    if _events.enabled():
        _events.emit(f"io.{op}", path=via, bytes=int(nbytes),
                     dataset=dataset)

# streaming stage-DAG hooks (dag/stream.StreamRegistry): installed only
# while a pipeline run has streamed edges registered, None otherwise, so
# one list-load guards every hot path. The registry gates consumer reads
# on producer block completion, accounts handoff-vs-container bytes, and
# publishes producer writes into the block exchange.
_DAG_HOOKS: list = [None]


def set_dag_hooks(hooks) -> None:
    """Install (or with None remove) the streaming-DAG read/write hooks —
    called by dag.stream when the first edge registers / the last one
    unregisters."""
    _DAG_HOOKS[0] = hooks


# one shared Context so every open in this process sees the same caches and
# the same in-process ``memory://`` store (tensorstore scopes the memory
# kvstore to a Context; without sharing, each open would get an empty store)
_TS_CONTEXT: list = [None]


def ts_context():
    if _TS_CONTEXT[0] is None:
        _TS_CONTEXT[0] = ts.Context()
    return _TS_CONTEXT[0]


class StorageFormat(str, enum.Enum):
    N5 = "N5"
    ZARR = "ZARR"
    HDF5 = "HDF5"


_N5_DTYPES = {
    "uint8", "uint16", "uint32", "uint64",
    "int8", "int16", "int32", "int64",
    "float32", "float64",
}

_ZARR_DTYPE = {
    "uint8": "|u1", "uint16": "<u2", "uint32": "<u4", "uint64": "<u8",
    "int8": "|i1", "int16": "<i2", "int32": "<i4", "int64": "<i8",
    "float32": "<f4", "float64": "<f8",
}


def _split_level(name: str, level: int | None):
    """Compression specs may carry the reference's --compressionLevel inline
    as ``name:level`` (e.g. ``zstd:7``) so the spelling passes unchanged
    through every layer that forwards a compression string."""
    if ":" in name:
        name, lv = name.split(":", 1)
        if level is None:
            level = int(lv)
    return name, level


def _n5_compression(name: str, level: int | None = None) -> dict:
    """N5 codec factory (reference surface: Lz4/Gzip/Zstd/Blosc/Bzip2/Xz/Raw,
    util/N5Util.java:82-105). ``level`` is the reference's
    --compressionLevel (codec-specific meaning). lz4 has no tensorstore n5
    codec — create_dataset/open_dataset route it through the native-only
    path (io.native_blockio LZ4Block codec)."""
    name, level = _split_level(name.lower(), level)
    if name == "lz4":
        return {"type": "lz4",
                "blockSize": 65536 if level is None else int(level)}
    if name == "zstd":
        return {"type": "zstd"} if level is None else {
            "type": "zstd", "level": int(level)}
    if name == "gzip":
        return {"type": "gzip"} if level is None else {
            "type": "gzip", "level": int(level)}
    if name == "raw":
        return {"type": "raw"}
    if name == "blosc":
        return {"type": "blosc", "cname": "zstd",
                "clevel": 3 if level is None else int(level), "shuffle": 1}
    if name == "bzip2":
        return {"type": "bzip2"} if level is None else {
            "type": "bzip2", "blockSize": int(level)}
    if name == "xz":
        return {"type": "xz"} if level is None else {
            "type": "xz", "preset": int(level)}
    raise ValueError(f"unsupported n5 compression: {name}")


def _zarr_compressor(name: str, level: int | None = None) -> dict | None:
    name, level = _split_level(name.lower(), level)
    if name == "zstd":
        return {"id": "zstd", "level": 3 if level is None else int(level)}
    if name == "gzip":
        return {"id": "zlib", "level": 5 if level is None else int(level)}
    if name == "blosc":
        return {"id": "blosc", "cname": "zstd",
                "clevel": 3 if level is None else int(level), "shuffle": 1}
    if name == "bzip2":
        return {"id": "bz2", "level": 5 if level is None else int(level)}
    if name == "raw":
        return None
    raise ValueError(f"unsupported zarr compression: {name}")


_DECODE_POOL = None


def _decode_pool():
    """Shared long-lived pool for native chunk decodes (the foreign calls
    release the GIL): callers' build/prefetch threads issue reads from
    their own pools, so a per-read executor would pay create/join overhead
    and fan out to ~64 transient threads."""
    global _DECODE_POOL
    if _DECODE_POOL is None:
        from concurrent.futures import ThreadPoolExecutor

        # raw executor on purpose: decode workers run GIL-releasing
        # foreign calls only — they never read config or poll cancel,
        # and the pool outlives any one job's context
        _DECODE_POOL = ThreadPoolExecutor(max_workers=8,  # bst-lint: off=thread-spawn
                                          thread_name_prefix="n5decode")
    return _DECODE_POOL


@dataclass
class Dataset:
    """A chunked array presented in xyz-first logical order.

    ``_ts is None`` marks a NATIVE-ONLY dataset (N5 codecs tensorstore has
    no driver for — lz4): geometry comes from attributes.json and all IO
    goes through the in-repo codec (io.native_blockio)."""

    store: "ChunkStore"
    path: str
    _ts: Any  # tensorstore.TensorStore, h5py.Dataset, or None (native-only)
    reversed_axes: bool  # True when on-disk order is C (zarr/hdf5)

    def _n5_attrs(self) -> dict:
        attrs = self._meta_file_cached("attributes.json")
        if not attrs or "dimensions" not in attrs:
            raise ValueError(f"{self.path}: no N5 dataset attributes")
        return attrs

    @property
    def shape(self) -> tuple[int, ...]:
        if self._ts is None:
            return tuple(int(v) for v in self._n5_attrs()["dimensions"])
        s = tuple(int(v) for v in self._ts.shape)
        return s[::-1] if self.reversed_axes else s

    @property
    def block_size(self) -> tuple[int, ...]:
        if self._ts is None:
            return tuple(int(v) for v in self._n5_attrs()["blockSize"])
        if hasattr(self._ts, "chunk_layout"):
            c = self._ts.chunk_layout.read_chunk.shape
        else:  # h5py
            c = self._ts.chunks
        c = tuple(int(v) for v in c)
        return c[::-1] if self.reversed_axes else c

    @property
    def dtype(self) -> np.dtype:
        if self._ts is None:
            return np.dtype(self._n5_attrs()["dataType"])
        return np.dtype(self._ts.dtype.numpy_dtype if hasattr(self._ts.dtype, "numpy_dtype") else self._ts.dtype)

    def _sel(self, offset: Sequence[int], shape: Sequence[int]):
        idx = tuple(slice(int(o), int(o) + int(s)) for o, s in zip(offset, shape))
        return idx[::-1] if self.reversed_axes else idx

    # -- decoded-chunk cache plumbing (io.chunkcache) ----------------------

    def _cache_key(self) -> tuple:
        root = getattr(self.store, "root", None)
        if root is None:
            root = getattr(self.store, "path", None)
        return (root, self.path.strip("/"))

    def _cacheable(self) -> bool:
        """Process-coherent stores always participate: local filesystems,
        in-process ``memory://`` roots, and single-process HDF5. Remote
        object stores (s3/gs) participate under ``BST_REMOTE_CACHE=run``
        (the default) with a run-pinned signature — see ``_cache_sig`` —
        while ``off`` restores the historical bypass bit-identically."""
        store = self.store
        if store is None:
            return False
        if getattr(store, "format", None) == StorageFormat.HDF5:
            return True
        if (getattr(store, "is_local", False)
                or str(getattr(store, "root", "")).startswith("memory://")):
            return True
        return (getattr(store, "is_remote_object", False)
                and config.get_str("BST_REMOTE_CACHE") == "run")

    def _cache_sig(self):
        """Metadata signature folded into cache keys. Local stores use the
        metadata file's (mtime_ns, size) — the same identity
        ``_meta_file_cached`` uses — so an out-of-band recreate at this
        path orphans the old entries. Remote object stores fold the
        per-run pin plus the metadata object's content hash/size instead
        (one conditional GET per open, memoized per pin): this process's
        own writes still invalidate precisely via the generation bumps,
        and ``bump_remote_pin`` bounds the external-writer coherence
        window."""
        store = self.store
        if getattr(store, "is_remote_object", False):
            return self._remote_cache_sig()
        if not getattr(store, "is_local", False) or not hasattr(store, "_kvpath"):
            return None
        name = ("attributes.json"
                if getattr(store, "format", None) == StorageFormat.N5
                else ".zarray")
        try:
            st = os.stat(os.path.join(store._kvpath(self.path), name))
            return (st.st_mtime_ns, st.st_size)
        except OSError:
            return None

    def _remote_cache_sig(self):
        """Remote signature ("remote", pin, md5-of-metadata, size), fetched
        once per (dataset instance, pin) — re-opened datasets re-read it,
        so a REPLACED remote dataset (new .zarray/attributes.json bytes)
        never collides with stale cached chunks."""
        pin = remote_pin()
        memo = getattr(self, "_remote_sig_memo", None)
        if memo is not None and memo[0] == pin:
            return memo[1]
        name = ("attributes.json"
                if getattr(self.store, "format", None) == StorageFormat.N5
                else ".zarray")
        rel = f"{self.path.strip('/')}/{name}" if self.path.strip("/") else name
        try:
            raw = self.store._read_obj(rel)
        except Exception:
            raw = None
        if raw is None:
            sig = None  # unreadable metadata: share nothing across readers
        else:
            import hashlib

            sig = ("remote", pin, hashlib.md5(raw).hexdigest(), len(raw))
        self._remote_sig_memo = (pin, sig)
        return sig

    def _cached_read(self, offset: Sequence[int],
                     shape: Sequence[int]) -> np.ndarray | None:
        """Assemble a box from cached decoded chunks, decoding only the
        misses. Returns None when ineligible (out-of-bounds box,
        unchunked dataset, no usable decode route) — the caller then runs
        the exact pre-cache read path."""
        try:
            block = self.block_size
        except Exception:
            return None
        if not block or any(int(b) <= 0 for b in block):
            return None
        dims = self.shape
        ndim = len(dims)
        off = [int(o) for o in offset]
        shp = [int(s) for s in shape]
        if len(off) != ndim or len(shp) != ndim:
            return None
        if any(o < 0 or s <= 0 or o + s > dims[d]
               for d, (o, s) in enumerate(zip(off, shp))):
            return None
        cc = chunkcache.get_cache()
        dkey = self._cache_key()
        sig = self._cache_sig()
        out = np.empty(tuple(shp), self.dtype)
        import itertools

        grids = [range(off[d] // block[d],
                       (off[d] + shp[d] - 1) // block[d] + 1)
                 for d in range(ndim)]
        copied = {"cache": 0}

        def fill(pos, chunk) -> int:
            lo = [pos[d] * block[d] for d in range(ndim)]
            src = tuple(
                slice(max(off[d] - lo[d], 0),
                      min(off[d] + shp[d] - lo[d], chunk.shape[d]))
                for d in range(ndim))
            dst = tuple(
                slice(max(lo[d] - off[d], 0),
                      max(lo[d] - off[d], 0) + (src[d].stop - src[d].start))
                for d in range(ndim))
            out[dst] = chunk[src]
            return int(out[dst].nbytes)

        misses = []
        for pos in itertools.product(*grids):
            chunk = cc.get((dkey, sig, pos))
            if chunk is None:
                misses.append(pos)
            else:
                copied["cache"] += fill(pos, chunk)
        if misses:
            got = self._read_chunks(misses)
            if got is None:
                return None  # no decode route: fall back (and re-read hits)
            via, chunks = got
            nb = 0
            for pos, chunk in zip(misses, chunks):
                cc.put((dkey, sig, pos), chunk)
                nb += fill(pos, chunk)
            copied[via] = copied.get(via, 0) + nb
        hooks = _DAG_HOOKS[0]
        for via, nb in copied.items():
            if nb:
                _record_io("read", via, nb, self.path)
                if hooks is not None:
                    hooks.account_read(self, via, nb)
        return out

    def _read_chunks(self, positions):
        """Decode whole chunks (clipped to the array bounds, logical
        xyz-first orientation, absent chunks zero-filled). Returns
        (via, [chunk, ...]) aligned with ``positions``, or None when no
        decode route applies."""
        block = self.block_size
        dims = self.shape
        ndim = len(dims)

        def extent(pos):
            return tuple(min(block[d], dims[d] - pos[d] * block[d])
                         for d in range(ndim))

        ctype = self._native_n5_eligible()
        if ctype is not None:
            from . import native_blockio

            root = self.store._kvpath(self.path)

            def read_one(pos):
                path = os.path.join(root, *[str(p) for p in pos])
                blk = native_blockio.read_block(path, self.dtype, block,
                                                compression=ctype)
                ext = extent(pos)
                if blk is None:
                    return np.zeros(ext, self.dtype)
                if tuple(blk.shape) != ext:
                    # stored chunk dims may be full-size at the array edge
                    clipped = np.zeros(ext, self.dtype)
                    sl = tuple(slice(0, min(blk.shape[d], ext[d]))
                               for d in range(ndim))
                    clipped[sl] = blk[sl]
                    return clipped
                return blk

            if len(positions) > 1:
                return "native", list(_decode_pool().map(read_one, positions))
            return "native", [read_one(positions[0])]
        if self._ts is None:
            return None
        sels = []
        for pos in positions:
            lo = [pos[d] * block[d] for d in range(ndim)]
            sels.append(self._sel(lo, extent(pos)))
        rev = tuple(range(ndim))[::-1]
        if hasattr(self._ts, "read"):
            # issue every chunk read before resolving any: tensorstore
            # overlaps the decodes, so a miss burst costs one round of IO
            futs = [self._ts[sel].read() for sel in sels]
            chunks = [np.asarray(f.result()) for f in futs]
            via = "tensorstore"
            if getattr(self.store, "is_remote_object", False):
                _REMOTE_READ_BYTES.inc(sum(int(c.nbytes) for c in chunks))
        else:
            chunks = [np.asarray(self._ts[sel]) for sel in sels]
            via = "h5py"
        if self.reversed_axes:
            chunks = [c.transpose(rev) for c in chunks]
        return via, chunks

    def _invalidate_box(self, offset: Sequence[int],
                        shape: Sequence[int]) -> None:
        """Drop the cached chunks a written box covers (and bump the
        dataset generation device-side caches key on)."""
        try:
            block = self.block_size
        except Exception:
            chunkcache.get_cache().invalidate(self._cache_key())
            return
        if not block or any(int(b) <= 0 for b in block):
            chunkcache.get_cache().invalidate(self._cache_key())
            return
        import itertools

        grids = [range(int(offset[d]) // block[d],
                       (int(offset[d]) + int(shape[d]) - 1) // block[d] + 1)
                 for d in range(len(block))]
        chunkcache.get_cache().invalidate(self._cache_key(),
                                          itertools.product(*grids))

    def prefetch_box(self, offset: Sequence[int],
                     shape: Sequence[int]) -> list:
        """Decode the chunks a FUTURE read of this box will need into the
        decoded LRU, off the consumer's critical path (io/prefetch.py
        workers call this). Bypasses the DAG read gate (a non-blocking
        ``box_ready`` probe skips unpublished streamed blocks instead of
        waiting on them) and records no read-path io counters — the
        prefetcher attributes its own traffic. Returns the
        ``[(cache_key, nbytes), ...]`` it inserted (empty when everything
        was already resident or the dataset is ineligible)."""
        if not (chunkcache.enabled() and self._cacheable()):
            return []
        hooks = _DAG_HOOKS[0]
        if hooks is not None:
            ready = getattr(hooks, "box_ready", None)
            if ready is not None and not ready(self, offset, shape):
                return []
        try:
            block = self.block_size
            dims = self.shape
        except Exception:
            return []
        if not block or any(int(b) <= 0 for b in block):
            return []
        ndim = len(dims)
        off = [int(o) for o in offset]
        shp = [int(s) for s in shape]
        if len(off) != ndim or len(shp) != ndim:
            return []
        if any(o < 0 or s <= 0 or o + s > dims[d]
               for d, (o, s) in enumerate(zip(off, shp))):
            return []
        if self._native_n5_eligible() is None and (
                self._ts is None or not hasattr(self._ts, "read")):
            return []  # h5py handles are not thread-safe: never prefetch
        cc = chunkcache.get_cache()
        dkey = self._cache_key()
        sig = self._cache_sig()
        import itertools

        grids = [range(off[d] // block[d],
                       (off[d] + shp[d] - 1) // block[d] + 1)
                 for d in range(ndim)]
        misses = [pos for pos in itertools.product(*grids)
                  if not cc.peek((dkey, sig, pos))]
        if not misses:
            return []
        itemsize = np.dtype(self.dtype).itemsize
        est = sum(int(np.prod([min(block[d], dims[d] - p[d] * block[d])
                               for d in range(ndim)])) * itemsize
                  for p in misses)
        nbytes = 0
        inserted = []
        with profiling.span("io.prefetch", item=self.path, nbytes=est):
            got = self._read_chunks(misses)
            if got is None:
                return []
            _via, chunks = got
            for pos, chunk in zip(misses, chunks):
                key = (dkey, sig, pos)
                cc.put(key, chunk, record_miss=False)
                inserted.append((key, int(chunk.nbytes)))
                nbytes += int(chunk.nbytes)
        _PREFETCH_BYTES.inc(nbytes)
        return inserted

    def read(self, offset: Sequence[int], shape: Sequence[int]) -> np.ndarray:
        """Read a box (xyz-first offset/shape) into a numpy array (xyz-first)."""
        hooks = _DAG_HOOKS[0]
        if hooks is not None:
            # streaming pipelines: a consumer stage's read of a streamed
            # edge blocks here until the producer has written the covering
            # blocks (or finished); everyone else passes straight through
            hooks.gate(self, offset, shape)
        if chunkcache.enabled() and self._cacheable():
            cached = self._cached_read(offset, shape)
            if cached is not None:
                return cached
        native = self._native_read(offset, shape)
        if native is not None:
            _record_io("read", "native", native.nbytes, self.path)
            if hooks is not None:
                hooks.account_read(self, "native", native.nbytes)
            return native
        if self._ts is None:
            raise ValueError(
                f"{self.path}: native-only dataset (lz4) — read box "
                f"{offset}+{shape} must lie inside the array bounds")
        sel = self._sel(offset, shape)
        if hasattr(self._ts, "read"):
            data = self._ts[sel].read().result()
            via = "tensorstore"
            if getattr(self.store, "is_remote_object", False):
                _REMOTE_READ_BYTES.inc(int(np.asarray(data).nbytes))
        else:
            data = self._ts[sel]
            via = "h5py"
        data = np.asarray(data)
        _record_io("read", via, data.nbytes, self.path)
        if hooks is not None:
            hooks.account_read(self, via, data.nbytes)
        return data.transpose(tuple(range(data.ndim))[::-1]) if self.reversed_axes else data

    def read_device(self, offset: Sequence[int], shape: Sequence[int]):
        """Serve a read as a DEVICE array straight from a streaming
        pipeline's HBM handoff cache (dag/stream.py): zero D2H, zero
        container decode. Returns None whenever that tier cannot serve
        the whole box — callers fall back to :meth:`read`."""
        hooks = _DAG_HOOKS[0]
        if hooks is None:
            return None
        fn = getattr(hooks, "device_read", None)
        if fn is None:
            return None
        return fn(self, offset, shape)

    def _native_read(self, offset: Sequence[int],
                     shape: Sequence[int]) -> np.ndarray | None:
        """N5 + zstd/raw local read via the native codec: chunk files decode
        through GIL-free foreign calls (threads genuinely overlap), and the
        per-chunk decode avoids tensorstore's extra assembly copies (~25%
        faster even single-threaded). Returns None when ineligible."""
        ctype = self._native_n5_eligible()
        if ctype is None:
            return None
        from . import native_blockio

        block = self.block_size
        dims = self.shape
        ndim = len(dims)
        off = [int(o) for o in offset]
        shp = [int(s) for s in shape]
        if any(o < 0 or o + s > dims[d] or s <= 0
               for d, (o, s) in enumerate(zip(off, shp))):
            return None
        out = np.zeros(tuple(shp), self.dtype)
        root = self.store._kvpath(self.path)
        grids = [range(off[d] // block[d], (off[d] + shp[d] - 1) // block[d] + 1)
                 for d in range(ndim)]
        import itertools

        fused = native_blockio.has_region_read()

        def read_one(pos):
            path = os.path.join(root, *[str(p) for p in pos])
            lo = [pos[d] * block[d] for d in range(ndim)]
            src_lo = [max(off[d] - lo[d], 0) for d in range(ndim)]
            dst_off = [max(lo[d] - off[d], 0) for d in range(ndim)]
            copy = [min(off[d] + shp[d], lo[d] + block[d])
                    - max(off[d], lo[d]) for d in range(ndim)]
            if any(c <= 0 for c in copy):
                return
            if fused:
                # decode straight into the output box: the big-endian swap
                # fuses with the strided write (absent chunk = fill zeros)
                native_blockio.read_block_region(
                    path, out, dst_off, src_lo, copy, compression=ctype)
                return
            # stale libblockio.so without the region symbol: decode the
            # whole chunk and assemble in numpy (keeps lz4 readable)
            blk = native_blockio.read_block(path, self.dtype, block,
                                            compression=ctype)
            if blk is None:
                return
            src = tuple(
                slice(src_lo[d], min(src_lo[d] + copy[d], blk.shape[d]))
                for d in range(ndim))
            if any(s.stop <= s.start for s in src):
                return
            dst = tuple(
                slice(dst_off[d], dst_off[d] + (src[d].stop - src[d].start))
                for d in range(ndim))
            out[dst] = blk[src]

        positions = list(itertools.product(*grids))
        if len(positions) > 1:
            list(_decode_pool().map(read_one, positions))
        else:
            read_one(positions[0])
        return out

    def write(self, data: np.ndarray, offset: Sequence[int]) -> None:
        """Write a numpy array (xyz-first) at an xyz-first offset.

        Block-aligned N5 and zarr writes take the native codec fast path
        (GIL-free strided copy + zstd encode + file write,
        io.native_blockio) when available."""
        shape = data.shape
        try:
            self._write_impl(data, offset)
        finally:
            # drop exactly the cached chunks this box covers (finally: a
            # partially-applied failed write must not leave stale entries)
            self._invalidate_box(offset, shape)
        hooks = _DAG_HOOKS[0]
        if hooks is not None:
            # streaming pipelines: publish the completed block (coverage,
            # write-through handoff, backpressure) — AFTER the invalidation
            # above so the handoff's cache entries survive it
            hooks.on_write(self, data, offset)

    def write_device(self, dev, offset: Sequence[int]) -> bool:
        """Publish a DEVICE-resident block to a streaming pipeline's HBM
        handoff cache (dag/stream.py) instead of draining it to host.
        Returns True when the block was accepted device-resident — the
        caller skips the fetch and the host :meth:`write` entirely;
        False means the block must take the ordinary host write path."""
        hooks = _DAG_HOOKS[0]
        if hooks is None:
            return False
        fn = getattr(hooks, "on_write_device", None)
        if fn is None:
            return False
        return bool(fn(self, dev, offset))

    def _write_impl(self, data: np.ndarray, offset: Sequence[int]) -> None:
        if (self._native_write(data, offset)
                or self._native_write_zarr(data, offset)):
            _record_io("write", "native", data.nbytes, self.path)
            return
        if self._ts is None:
            raise ValueError(
                f"{self.path}: native-only dataset (lz4) — writes must "
                "be block-aligned and dtype-matched")
        if self._multipart_write(data, offset):
            return
        sel = self._sel(offset, data.shape)
        if self.reversed_axes:
            data = data.transpose(tuple(range(data.ndim))[::-1])
        if hasattr(self._ts, "read"):
            self._ts[sel].write(np.ascontiguousarray(data)).result()
            via = "tensorstore"
            if getattr(self.store, "is_remote_object", False):
                _REMOTE_WRITE_BYTES.inc(int(data.nbytes))
        else:
            self._ts[sel] = data
            via = "h5py"
        _record_io("write", via, data.nbytes, self.path)

    def _multipart_write(self, data: np.ndarray,
                         offset: Sequence[int]) -> bool:
        """Remote direct writes: split a multi-chunk box along storage-chunk
        boundaries and push the per-chunk puts through a bounded concurrent
        pool with retry/backoff (parallel/retry.py) instead of one
        serialized tensorstore write — each part touches exactly one chunk,
        so concurrent parts never contend and a retried part re-puts its
        whole object (no partial chunk is ever visible). Returns False
        (caller takes the ordinary single-write path) for non-remote
        stores, ``BST_UPLOAD_THREADS<=1``, or single-chunk boxes."""
        if not getattr(self.store, "is_remote_object", False):
            return False
        threads = config.get_int("BST_UPLOAD_THREADS")
        if threads <= 1 or not hasattr(self._ts, "read"):
            return False
        try:
            block = self.block_size
            dims = self.shape
        except Exception:
            return False
        ndim = data.ndim
        if len(block) != ndim or any(int(b) <= 0 for b in block):
            return False
        off = [int(o) for o in offset]
        import itertools

        grids = [range(off[d] // block[d],
                       (off[d] + data.shape[d] - 1) // block[d] + 1)
                 for d in range(ndim)]
        positions = list(itertools.product(*grids))
        if len(positions) <= 1:
            return False
        rev = tuple(range(ndim))[::-1]
        parts = []
        for pos in positions:
            lo = [max(off[d], pos[d] * block[d]) for d in range(ndim)]
            hi = [min(off[d] + data.shape[d], (pos[d] + 1) * block[d],
                      dims[d]) for d in range(ndim)]
            if any(hi[d] <= lo[d] for d in range(ndim)):
                continue
            src = tuple(slice(lo[d] - off[d], hi[d] - off[d])
                        for d in range(ndim))
            parts.append((lo, data[src]))

        def put_one(item):
            lo, part = item
            psel = self._sel(lo, part.shape)
            pdata = part.transpose(rev) if self.reversed_axes else part
            _UPLOAD_INFLIGHT.inc()
            try:
                with profiling.span("io.upload", item=self.path,
                                    nbytes=int(part.nbytes)):
                    _upload_one(self, psel, np.ascontiguousarray(pdata))
            finally:
                _UPLOAD_INFLIGHT.inc(-1)

        from ..parallel.retry import run_with_retry

        run_with_retry(parts, put_one, max_retries=4, delay_s=0.25,
                       label="upload", verbose=False,
                       threads=min(int(threads), len(parts)))
        _record_io("write", "tensorstore", data.nbytes, self.path)
        _REMOTE_WRITE_BYTES.inc(int(data.nbytes))
        return True

    def _native_n5_eligible(self) -> str | None:
        """Shared native-codec eligibility gate for N5 reads AND writes:
        local N5 store, zstd/raw codec, native library present. Returns the
        compression type, or None when the tensorstore path must be used."""
        if (self.reversed_axes or self.store is None
                or getattr(self.store, "format", None) != StorageFormat.N5
                or not getattr(self.store, "is_local", False)
                or not config.get_bool("BST_NATIVE_IO")):
            return None
        comp = (self._meta_file_cached("attributes.json")
                or {}).get("compression", {})
        ctype = comp.get("type", "zstd")
        from . import native_blockio

        if ctype == "lz4":
            return "lz4" if native_blockio.has_lz4() else None
        if ctype not in ("zstd", "raw"):
            return None
        if not native_blockio.available():
            return None
        return ctype

    def _native_write(self, data: np.ndarray, offset: Sequence[int]) -> bool:
        """N5 + zstd/raw + block-aligned box -> write chunk files natively.
        Returns False when ineligible (caller falls back to tensorstore)."""
        ctype = self._native_n5_eligible()
        if ctype is None:
            return False
        comp = (self._meta_file_cached("attributes.json")
                or {}).get("compression", {})
        from . import native_blockio

        block = self.block_size
        dims = self.shape
        if data.dtype != self.dtype:
            return False
        for d in range(data.ndim):
            o, s = int(offset[d]), int(data.shape[d])
            if o % block[d] != 0 or s <= 0 or o + s > dims[d]:
                return False
            # box must end on a storage-block boundary or the array edge
            if (o + s) % block[d] != 0 and (o + s) != dims[d]:
                return False
        # a compute block may span several storage blocks (blockScale > 1):
        # split per storage block, each an exact full/edge chunk file
        if any(int(data.shape[d]) > block[d] for d in range(data.ndim)):
            grid = [range(0, int(data.shape[d]), block[d])
                    for d in range(data.ndim)]
            import itertools

            for corner in itertools.product(*grid):
                sub = data[tuple(
                    slice(c, min(c + block[d], data.shape[d]))
                    for d, c in enumerate(corner))]
                off = [int(offset[d]) + c for d, c in enumerate(corner)]
                if not self._native_write(np.ascontiguousarray(sub), off):
                    return False
            return True
        pos = [int(offset[d]) // block[d] for d in range(data.ndim)]
        path = os.path.join(self.store._kvpath(self.path),
                            *[str(p) for p in pos])
        if ctype == "lz4":  # the level slot carries the LZ4Block blockSize
            level = int(comp.get("blockSize", 65536))
        else:
            level = int(comp.get("level", 3)) or 3
        native_blockio.write_block(path, data, compression=ctype, level=level)
        return True

    def _meta_file_cached(self, name: str):
        """Parse a per-dataset metadata file, cached against its
        (mtime_ns, size) signature — recreating the dataset at the same
        path invalidates the cache (ADVICE r4: a plain first-access cache
        could drive the native codec with stale codec/fill metadata)."""
        if not hasattr(self, "_meta_cache"):
            self._meta_cache: dict = {}
        p = os.path.join(self.store._kvpath(self.path), name)
        try:
            st = os.stat(p)
            sig = (st.st_mtime_ns, st.st_size)
        except OSError:
            sig = None
        ent = self._meta_cache.get(name)
        if ent is not None and ent[0] == sig:
            return ent[1]
        meta = None
        if sig is not None:
            try:
                with open(p) as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                meta = None
        self._meta_cache[name] = (sig, meta)
        return meta

    def _zarr_meta(self) -> dict | None:
        return self._meta_file_cached(".zarray")

    def _native_write_zarr(self, data: np.ndarray, offset: Sequence[int]) -> bool:
        """zarr v2 + zstd/raw + chunk-aligned box -> write chunk files
        natively: the C side walks the transposed (disk-order) strides, so no
        Python-side transpose copy happens. Returns False when ineligible."""
        if (not self.reversed_axes or self.store is None
                or getattr(self.store, "format", None) != StorageFormat.ZARR
                or not getattr(self.store, "is_local", False)
                or not config.get_bool("BST_NATIVE_IO")):
            return False
        from . import native_blockio

        if not native_blockio.has_zarr():
            return False
        meta = self._zarr_meta()
        if (meta is None or meta.get("order") != "C"
                or meta.get("dimension_separator", ".") != "."
                or meta.get("filters")):
            return False
        comp = meta.get("compressor")
        if comp is None:
            ctype, level = "raw", 0
        elif comp.get("id") == "zstd":
            ctype, level = "zstd", int(comp.get("level", 3))
        else:
            return False
        if data.dtype != self.dtype or np.dtype(meta["dtype"]).byteorder == ">":
            return False
        fill = meta.get("fill_value") or 0
        block = self.block_size
        dims = self.shape
        for d in range(data.ndim):
            o, s = int(offset[d]), int(data.shape[d])
            if o % block[d] != 0 or s <= 0:
                return False
            if (o + s) != dims[d] and (o + s) % block[d] != 0:
                return False  # box must end on a chunk (or array) boundary
        import itertools

        root = self.store._kvpath(self.path)
        grid = [range(0, int(data.shape[d]), block[d])
                for d in range(data.ndim)]
        for corner in itertools.product(*grid):
            sub = data[tuple(slice(c, min(c + block[d], data.shape[d]))
                             for d, c in enumerate(corner))]
            pos = [(int(offset[d]) + c) // block[d]
                   for d, c in enumerate(corner)]
            name = ".".join(str(p) for p in reversed(pos))
            rev = tuple(range(sub.ndim))[::-1]
            native_blockio.write_zarr_chunk(
                os.path.join(root, name), sub.transpose(rev),
                tuple(reversed(block)), compression=ctype, level=level,
                fill_value=fill,
            )
        return True

    def read_full(self) -> np.ndarray:
        return self.read((0,) * len(self.shape), self.shape)


def _upload_one(ds: "Dataset", sel, part: np.ndarray) -> None:
    """One multipart upload part — module-level so tests can inject
    transient put failures (tests/test_tiered_io.py monkeypatches this)."""
    ds._ts[sel].write(part).result()


class ChunkStore:
    """A root N5/ZARR container on a local path or cloud URI.

    Roots may be plain paths or ``s3://bucket/…``, ``gs://bucket/…``,
    ``memory://…`` URIs (the reference's URITools/N5Util URI routing,
    util/N5Util.java:47-80); tensorstore kvstore drivers do the transport."""

    def __init__(self, root: str | os.PathLike, fmt: StorageFormat):
        self.is_local = not uris.has_scheme(root)
        self.root = uris.strip_file_scheme(root) if self.is_local else str(root)
        # remote OBJECT stores (network round trip per chunk) as opposed to
        # merely non-local roots like memory:// — the tiered-IO engine keys
        # prefetch/remote-cache/multipart eligibility on this
        self.is_remote_object = str(self.root).startswith(("s3://", "gs://"))
        self.format = StorageFormat(fmt)
        if self.format == StorageFormat.HDF5:
            raise ValueError("use Hdf5Store for HDF5")
        self._kv = None

    def _kvstore(self):
        """Root-level tensorstore KvStore (non-local roots)."""
        if self._kv is None:
            self._kv = ts.KvStore.open(
                uris.kvstore_spec(self.root), context=ts_context()).result()
        return self._kv

    # -- raw object IO (attribute files, markers) --------------------------

    def _read_obj(self, rel: str) -> bytes | None:
        if self.is_local:
            p = os.path.join(self.root, rel)
            if not os.path.exists(p):
                return None
            with open(p, "rb") as f:
                return f.read()
        r = self._kvstore().read(rel).result()
        return bytes(r.value) if r.state == "value" else None

    def _write_obj(self, rel: str, data: bytes) -> None:
        if self.is_local:
            p = os.path.join(self.root, rel)
            os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
            with open(p, "wb") as f:
                f.write(data)
            return
        self._kvstore().write(rel, data).result()

    # -- creation ----------------------------------------------------------

    @staticmethod
    def create(root: str | os.PathLike, fmt: StorageFormat) -> "ChunkStore":
        fmt = StorageFormat(fmt)
        store = ChunkStore(root, fmt)
        if store.is_local:
            os.makedirs(store.root, exist_ok=True)
        if fmt == StorageFormat.N5:
            store._merge_json("attributes.json", {"n5": "2.5.1"})
        else:
            store._merge_json(".zgroup", {"zarr_format": 2})
        return store

    @staticmethod
    def open(root: str | os.PathLike) -> "ChunkStore":
        root = str(root)
        probe = ChunkStore(root, StorageFormat.N5)
        if probe._read_obj("attributes.json") is not None:
            return probe
        if (probe._read_obj(".zgroup") is not None
                or probe._read_obj(".zattrs") is not None):
            return ChunkStore(root, StorageFormat.ZARR)
        # guess by extension
        if root.rstrip("/").endswith((".zarr", ".ome.zarr")):
            return ChunkStore(root, StorageFormat.ZARR)
        return probe

    # -- attributes --------------------------------------------------------

    def _attr_rel(self, group: str) -> str:
        name = "attributes.json" if self.format == StorageFormat.N5 else ".zattrs"
        g = group.strip("/")
        return f"{g}/{name}" if g else name

    def _merge_json(self, rel: str, updates: dict) -> None:
        raw = self._read_obj(rel)
        current: dict = json.loads(raw) if raw else {}
        current.update(updates)
        self._write_obj(rel, json.dumps(
            current, indent=0, default=_json_default).encode())

    def get_attributes(self, group: str = "") -> dict:
        raw = self._read_obj(self._attr_rel(group))
        return json.loads(raw) if raw else {}

    def set_attribute(self, group: str, key_path: str, value: Any) -> None:
        """N5-style nested attribute: key path split on '/'."""
        attrs = self.get_attributes(group)
        keys = [k for k in key_path.split("/") if k]
        node = attrs
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        node[keys[-1]] = value
        self._write_obj(self._attr_rel(group), json.dumps(
            attrs, indent=0, default=_json_default).encode())

    def get_attribute(self, group: str, key_path: str, default: Any = None) -> Any:
        node: Any = self.get_attributes(group)
        for k in [k for k in key_path.split("/") if k]:
            if not isinstance(node, dict) or k not in node:
                return default
            node = node[k]
        return node

    # -- datasets ----------------------------------------------------------

    def _kvpath(self, path: str) -> str:
        """Local filesystem path of a sub-path (local roots only)."""
        return os.path.join(self.root, path.strip("/"))

    def _dataset_kvstore(self, path: str) -> dict:
        return uris.kvstore_spec(self.root, path.strip("/"))

    def create_dataset(
        self,
        path: str,
        shape: Sequence[int],
        block_size: Sequence[int],
        dtype: str | np.dtype,
        compression: str = "zstd",
        delete_existing: bool = False,
        compression_level: int | None = None,
    ) -> Dataset:
        """Create a chunked dataset. ``shape``/``block_size`` xyz-first."""
        chunkcache.get_cache().invalidate_prefix(self.root, path)
        dtype = np.dtype(dtype).name
        if dtype not in _N5_DTYPES:
            raise ValueError(f"unsupported dtype {dtype}")
        shape = tuple(int(v) for v in shape)
        block = tuple(min(int(b), int(s)) if int(s) > 0 else int(b)
                      for b, s in zip(block_size, shape))
        if self.format == StorageFormat.N5:
            comp = _n5_compression(compression, compression_level)
            if comp["type"] == "lz4":
                # tensorstore's n5 driver has no lz4 codec: create the
                # dataset metadata directly and serve IO through the
                # native LZ4Block codec (reference parity with
                # util/N5Util.java:87-88)
                from . import native_blockio

                if not (self.is_local and native_blockio.has_lz4()
                        and config.get_bool("BST_NATIVE_IO")):
                    raise ValueError(
                        "lz4 N5 datasets need a local store and the native "
                        "codec (liblz4, BST_NATIVE_IO enabled)")
                if delete_existing:
                    self.remove(path)
                elif self.is_dataset(path):
                    raise ValueError(f"{path} already exists")
                self._write_obj(
                    self._attr_rel(path.strip("/")),
                    json.dumps({
                        "dimensions": list(shape),
                        "blockSize": list(block),
                        "dataType": dtype,
                        "compression": comp,
                    }, indent=0).encode())
                return Dataset(self, path, None, reversed_axes=False)
            spec = {
                "driver": "n5",
                "kvstore": self._dataset_kvstore(path),
                "metadata": {
                    "dimensions": list(shape),
                    "blockSize": list(block),
                    "dataType": dtype,
                    "compression": comp,
                },
                "create": True,
                "delete_existing": delete_existing,
            }
            arr = ts.open(spec, context=ts_context()).result()
            return Dataset(self, path, arr, reversed_axes=False)
        else:
            meta: dict[str, Any] = {
                "shape": list(shape[::-1]),
                "chunks": list(block[::-1]),
                "dtype": _ZARR_DTYPE[dtype],
                "compressor": _zarr_compressor(compression, compression_level),
            }
            spec = {
                "driver": "zarr",
                "kvstore": self._dataset_kvstore(path),
                "metadata": meta,
                "create": True,
                "delete_existing": delete_existing,
            }
            arr = ts.open(spec, context=ts_context()).result()
            return Dataset(self, path, arr, reversed_axes=True)

    def open_dataset(self, path: str) -> Dataset:
        if self.format == StorageFormat.N5:
            spec = {
                "driver": "n5",
                "kvstore": self._dataset_kvstore(path),
                "open": True,
            }
            try:
                arr = ts.open(spec, context=ts_context()).result()
            except ValueError as e:
                # tensorstore has no n5 lz4 codec: sniff the metadata only
                # on failure (no extra read on the happy path, and remote
                # stores get the clear message too) and serve the dataset
                # natively when possible
                ctype = self.get_attribute(path.strip("/"),
                                           "compression/type")
                if ctype != "lz4":
                    raise
                from . import native_blockio

                native_ok = config.get_bool("BST_NATIVE_IO")
                if self.is_local and native_blockio.has_lz4() and native_ok:
                    return Dataset(self, path, None, reversed_axes=False)
                raise ValueError(
                    f"{path}: lz4-compressed N5 needs the native codec on "
                    f"a local store (liblz4 loaded: "
                    f"{native_blockio.has_lz4()}, local: {self.is_local}, "
                    f"BST_NATIVE_IO enabled: {config.get_bool('BST_NATIVE_IO')})"
                ) from e
            return Dataset(self, path, arr, reversed_axes=False)
        spec = {
            "driver": "zarr",
            "kvstore": self._dataset_kvstore(path),
            "open": True,
        }
        return Dataset(self, path, ts.open(spec, context=ts_context()).result(),
                       reversed_axes=True)

    def is_dataset(self, path: str) -> bool:
        p = path.strip("/")
        if self.format == StorageFormat.N5:
            raw = self._read_obj(f"{p}/attributes.json" if p else "attributes.json")
            return raw is not None and "dimensions" in json.loads(raw)
        return self._read_obj(f"{p}/.zarray" if p else ".zarray") is not None

    def exists(self, path: str) -> bool:
        if self.is_local:
            return os.path.exists(self._kvpath(path))
        p = path.strip("/")
        kv = self._kvstore()
        # metadata-only presence checks: exact key, then any key under p/
        if kv.list(ts.KvStore.KeyRange(p, p + "\x00")).result():
            return True
        keys = kv.list(ts.KvStore.KeyRange(p + "/", p + "0")).result()
        return len(keys) > 0

    def remove(self, path: str = "") -> None:
        chunkcache.get_cache().invalidate_prefix(self.root, path)
        if self.is_local:
            p = self._kvpath(path) if path else self.root
            if os.path.exists(p):
                shutil.rmtree(p)
            return
        kv = self._kvstore()
        p = path.strip("/")
        if p:
            kv.delete_range(ts.KvStore.KeyRange(p + "/", p + "0")).result()
            kv.write(p, None).result()  # delete exact key if present
        else:
            kv.delete_range(ts.KvStore.KeyRange()).result()

    def list_children(self, path: str = "") -> list[str]:
        if self.is_local:
            p = self._kvpath(path)
            if not os.path.isdir(p):
                return []
            return sorted(
                d for d in os.listdir(p) if os.path.isdir(os.path.join(p, d))
            )
        p = path.strip("/")
        prefix = p + "/" if p else ""
        keys = self._kvstore().list(
            ts.KvStore.KeyRange(prefix, prefix[:-1] + "0" if prefix else "")
        ).result()
        kids = set()
        for k in keys:
            rest = k.decode()[len(prefix):]
            if "/" in rest:
                kids.add(rest.split("/", 1)[0])
        return sorted(kids)

    def make_group(self, path: str) -> None:
        if self.is_local:
            p = self._kvpath(path)
            os.makedirs(p, exist_ok=True)
        if self.format == StorageFormat.ZARR:
            rel = f"{path.strip('/')}/.zgroup"
            if self._read_obj(rel) is None:
                self._write_obj(rel, json.dumps({"zarr_format": 2}).encode())


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON serializable: {type(o)}")


class Hdf5Store:
    """Minimal HDF5 store (local-only, single process — the reference keeps the
    same restriction via a process-wide shared writer, N5Util.java:45-64)."""

    def __init__(self, path: str | os.PathLike, mode: str = "a"):
        import h5py

        if uris.has_scheme(path):
            raise ValueError(
                "HDF5 containers are local-only (the reference has the same "
                f"restriction, CreateFusionContainer.java:141-145): {path}")
        self.path = uris.strip_file_scheme(path)
        self.format = StorageFormat.HDF5
        self.is_local = True
        self._f = h5py.File(self.path, mode)

    def create_dataset(
        self,
        path: str,
        shape: Sequence[int],
        block_size: Sequence[int],
        dtype: str | np.dtype,
        compression: str = "gzip",
        delete_existing: bool = False,
    ) -> Dataset:
        shape = tuple(int(v) for v in shape)
        block = tuple(min(int(b), int(s)) for b, s in zip(block_size, shape))
        chunkcache.get_cache().invalidate_prefix(self.path, path)
        if delete_existing and path in self._f:
            del self._f[path]
        kw = {}
        compression, level = _split_level(compression, None)
        if compression not in ("raw", "gzip"):
            raise ValueError(
                f"HDF5 store supports only gzip/raw compression, got {compression!r}"
            )
        if compression != "raw":
            kw["compression"] = "gzip"
            if level is not None:
                kw["compression_opts"] = int(level)
        d = self._f.create_dataset(
            path, shape=shape[::-1], chunks=block[::-1], dtype=np.dtype(dtype), **kw
        )
        return Dataset(self, path, d, reversed_axes=True)

    def open_dataset(self, path: str) -> Dataset:
        return Dataset(self, path, self._f[path], reversed_axes=True)

    def put_array(self, path: str, data: np.ndarray) -> None:
        """Store a small auxiliary array verbatim (no axis reversal) — BDV
        ``s{XX}/resolutions`` / ``subdivisions`` tables."""
        if path in self._f:
            del self._f[path]
        self._f.create_dataset(path, data=data)

    def get_array(self, path: str) -> np.ndarray | None:
        if path not in self._f:
            return None
        return np.asarray(self._f[path])

    def exists(self, path: str) -> bool:
        return path.strip("/") in self._f

    def is_dataset(self, path: str) -> bool:
        import h5py

        return isinstance(self._f.get(path.strip("/")), h5py.Dataset)

    def set_attribute(self, group: str, key_path: str, value: Any) -> None:
        g = self._f.require_group(group or "/")
        g.attrs[key_path] = json.dumps(value) if isinstance(value, (dict, list)) else value

    def get_attribute(self, group: str, key_path: str, default: Any = None) -> Any:
        g = self._f.get(group or "/")
        if g is None or key_path not in g.attrs:
            return default
        v = g.attrs[key_path]
        if isinstance(v, (bytes, str)):
            try:
                return json.loads(v)
            except (json.JSONDecodeError, TypeError):
                return v
        return v

    def close(self):
        self._f.close()
